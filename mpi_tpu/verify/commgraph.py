"""Whole-tree send/recv/collective match graph for mpilint v2.

:mod:`mpi_tpu.verify.dataflow` turns a module into analysis roots — flat
operation lists with guard chains and environment snapshots.  This
module instantiates each root against a small **model world**: for every
model rank r in ``range(N)`` it evaluates each operation's guard chain
with ``comm.rank := r`` and resolves the peer/tag/count expressions,
producing the per-rank operation schedule an SPMD execution of that code
would follow.  The match rules then read directly off the schedules:

* **MPL001** — the per-rank sequences of collective names diverge: some
  rank reaches a collective the others never post (hang) or posts a
  different collective at the same position (mismatch).
* **MPL002** — two ranks whose first operation toward each other is a
  blocking send, and both later receive from each other: head-to-head
  rendezvous deadlock.
* **MPL003** — a matched send/recv pair whose receive count is smaller
  than the send count (the receive truncates the message).
* **MPL007** — a send and an exact-tag receive on the same channel that
  can never match each other's tag.
* **MPL009** — an ``ANY_SOURCE`` receive with two or more eligible
  senders carrying a matching tag: the match order is a race (the
  runtime half of this PR observes the same race dynamically via vector
  clocks).

Undecidability is always silence: an operation whose guard chain does
not fully evaluate at every model rank is dropped from the model
uniformly (so a guard on a *different* communicator's rank — the
``if self.intra.rank == 0: self.leaders.allgather(...)`` leader pattern
— never produces a finding).  Operations inside rank-dependent loops are
likewise excluded here; they surface through MPL008 instead.
"""

from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Tuple

from .dataflow import (
    Guard,
    Op,
    RootOps,
    eval_expr,
    resolve_comm,
)

ANY = -1            # wildcard sentinel (matches ANY_SOURCE / ANY_TAG)
_MAX_WORLD = 12     # model-world clamp: literals past this stay unexercised
_MIN_WORLD = 3      # always at least 3 ranks (MPL009 needs 2 senders + 1)


class CGFinding(NamedTuple):
    line: int
    code: str
    msg: str


class _Inst(NamedTuple):
    """One operation as executed by one model rank."""
    op: Op
    rank: int
    peer: Optional[int]   # resolved dest/source; ANY for wildcard; None n/a
    tag: Optional[int]    # resolved tag; ANY for ANY_TAG
    count: Optional[int]
    order: int            # program-order index within the root


# -- model construction ------------------------------------------------------

def _guard_comm(guards: Tuple[Guard, ...]) -> Optional[str]:
    """The communicator whose ``.rank`` the innermost guard mentions —
    used to re-key ``MPI_Send``-style function ops that carry no comm."""
    for g in reversed(guards):
        for n in ast.walk(g.test):
            if isinstance(n, ast.Attribute) \
                    and n.attr in ("rank", "world_rank"):
                key = resolve_comm(n.value, g.env)
                if key is not None:
                    return key
    return None


def _rekey(op: Op) -> Op:
    if op.comm != "<world>":
        return op
    key = _guard_comm(op.guards)
    return op._replace(comm=key) if key is not None else op


def _literals(op: Op) -> List[int]:
    out: List[int] = []
    nodes: List[ast.AST] = [g.test for g in op.guards]
    if op.peer is not None:
        nodes.append(op.peer)
    if op.tag is not None:
        nodes.append(op.tag)
    for node in nodes:
        for n in ast.walk(node):
            if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                    and not isinstance(n.value, bool):
                out.append(n.value)
    return out


def _world_size(ops: List[Op]) -> int:
    lits = [v for op in ops for v in _literals(op) if 0 <= v < _MAX_WORLD]
    hi = max(lits) if lits else 0
    return min(_MAX_WORLD, max(_MIN_WORLD, hi + 2))


def _guards_decide(op: Op, comm: str, rank: int, size: int) -> Optional[bool]:
    """True/False: op does/does not execute at this rank; None: some
    guard is undecidable."""
    for g in op.guards:
        v = eval_expr(g.test, g.env, comm, rank, size)
        if v is None:
            return None
        if bool(v) != g.polarity:
            return False
    return True


def _direction(op: Op) -> Optional[str]:
    """'send' / 'recv' / 'coll' for matching purposes (nonblocking ops
    keep their direction)."""
    if op.kind == "coll":
        return "coll"
    if op.kind in ("send", "recv"):
        return op.kind
    low = op.name.lower()
    if "recv" in low and "send" not in low:
        return "recv"
    if "send" in low:
        return "send"
    return None


def _instantiate(ops: List[Op], comm: str,
                 size: int) -> Optional[Dict[int, List[_Inst]]]:
    """Per-rank schedules, or None when the comm has no usable model."""
    by_rank: Dict[int, List[_Inst]] = {r: [] for r in range(size)}
    any_usable = False
    for order, op in enumerate(ops):
        if op.in_rank_loop:
            continue  # surfaced via MPL008, not the match graph
        decisions = [_guards_decide(op, comm, r, size) for r in range(size)]
        if any(d is None for d in decisions):
            continue  # undecidable guard: drop the op uniformly
        direction = _direction(op)
        for r, execute in enumerate(decisions):
            if not execute:
                continue
            peer = tag = count = None
            if direction in ("send", "recv"):
                if op.peer is None:
                    peer = ANY if direction == "recv" else None
                else:
                    peer = eval_expr(op.peer, op.env, comm, r, size)
                if op.tag is None:
                    tag = 0 if direction == "send" else ANY
                else:
                    tag = eval_expr(op.tag, op.env, comm, r, size)
                if op.count is not None:
                    count = eval_expr(op.count, op.env, comm, r, size)
                    if not isinstance(count, int):
                        count = None
                # out-of-world peers (e.g. 1 - rank at rank 2) drop out
                if peer is None or tag is None:
                    continue
                if direction == "send" and not (0 <= peer < size):
                    continue
                if direction == "recv" and peer != ANY \
                        and not (0 <= peer < size):
                    continue
            by_rank[r].append(_Inst(op, r, peer, tag, count, order))
            any_usable = True
    return by_rank if any_usable else None


# -- rules -------------------------------------------------------------------

def _rule_collective_divergence(comm: str, size: int,
                                by_rank: Dict[int, List[_Inst]],
                                out: List[CGFinding]) -> None:
    seqs = {r: [i for i in by_rank[r] if i.op.kind == "coll"]
            for r in range(size)}
    if not any(seqs.values()):
        return
    depth = max(len(s) for s in seqs.values())
    for idx in range(depth):
        names = {r: (seqs[r][idx].op.name if idx < len(seqs[r]) else None)
                 for r in range(size)}
        if len(set(names.values())) <= 1:
            continue
        # first divergence: report each distinct collective posted here
        seen_lines = set()
        for r in range(size):
            if names[r] is None:
                continue
            inst = seqs[r][idx]
            if inst.op.line in seen_lines:
                continue
            seen_lines.add(inst.op.line)
            here = sorted(q for q in range(size) if names[q] == names[r])
            absent = sorted(q for q in range(size) if q not in here)
            out.append(CGFinding(
                inst.op.line, "MPL001",
                f"collective {comm}.{inst.op.name}() is reached by "
                f"rank(s) {here} but not rank(s) {absent} under the "
                f"resolved rank conditions; ranks diverge from the "
                f"collective schedule (hang or collective mismatch)"))
        return  # only the first divergence is actionable


def _involving(insts: List[_Inst], peer: int) -> List[_Inst]:
    out = []
    for i in insts:
        d = _direction(i.op)
        if d == "send" and i.peer == peer:
            out.append(i)
        elif d == "recv" and (i.peer == peer or i.peer == ANY):
            out.append(i)
    return out


def _rule_send_send_cycle(comm: str, size: int,
                          by_rank: Dict[int, List[_Inst]],
                          out: List[CGFinding]) -> None:
    for a in range(size):
        for b in range(a + 1, size):
            ia = _involving(by_rank[a], b)
            ib = _involving(by_rank[b], a)
            if not ia or not ib:
                continue
            fa, fb = ia[0], ib[0]
            if not (fa.op.kind == "send" and fb.op.kind == "send"):
                continue  # nonblocking sends don't rendezvous-deadlock
            if not any(_direction(i.op) == "recv" for i in ia[1:]) \
                    or not any(_direction(i.op) == "recv" for i in ib[1:]):
                continue
            line = min(fa.op.line, fb.op.line)
            out.append(CGFinding(
                line, "MPL002",
                f"ranks {a} and {b} both blocking-send to each other "
                f"before receiving (head-to-head rendezvous deadlock); "
                f"use {comm}.sendrecv()"))


def _rule_channel_rules(comm: str, size: int,
                        by_rank: Dict[int, List[_Inst]],
                        out: List[CGFinding]) -> None:
    """Per directed channel (src -> dst): order-respecting tag matching,
    then MPL003 on matched pairs and MPL007 on the unmatchable rest."""
    for s in range(size):
        sends_all = [i for i in by_rank[s] if _direction(i.op) == "send"]
        for d in range(size):
            if s == d:
                continue
            sends = [i for i in sends_all if i.peer == d]
            recvs = [i for i in by_rank[d]
                     if _direction(i.op) == "recv"
                     and (i.peer == s or i.peer == ANY)]
            if not sends:
                continue
            unmatched_recvs = list(recvs)
            unmatched_sends = []
            for snd in sends:
                hit = None
                for j, rcv in enumerate(unmatched_recvs):
                    if rcv.tag == ANY or rcv.tag == snd.tag:
                        hit = j
                        break
                if hit is None:
                    unmatched_sends.append(snd)
                    continue
                rcv = unmatched_recvs.pop(hit)
                if rcv.count is not None and snd.count is not None \
                        and rcv.count < snd.count:
                    out.append(CGFinding(
                        rcv.op.line, "MPL003",
                        f"recv count {rcv.count} truncates the "
                        f"message: the matching send (line "
                        f"{snd.op.line}) sends {snd.count} elements"))
            exact_left = [r for r in unmatched_recvs
                          if r.tag != ANY and r.peer == s]
            if unmatched_sends and exact_left:
                snd, rcv = unmatched_sends[0], exact_left[0]
                out.append(CGFinding(
                    rcv.op.line, "MPL007",
                    f"tag mismatch on {comm} channel {s}->{d}: send "
                    f"at line {snd.op.line} uses tag {snd.tag} but "
                    f"this recv expects tag {rcv.tag}; the pair can "
                    f"never match"))


def _rule_wildcard_race(comm: str, size: int,
                        by_rank: Dict[int, List[_Inst]],
                        out: List[CGFinding]) -> None:
    seen_lines = set()
    for d in range(size):
        for rcv in by_rank[d]:
            if _direction(rcv.op) != "recv" or rcv.peer != ANY:
                continue
            if rcv.op.line in seen_lines:
                continue
            senders = sorted({
                s for s in range(size) if s != d
                for i in by_rank[s]
                if _direction(i.op) == "send" and i.peer == d
                and (rcv.tag == ANY or i.tag == rcv.tag)})
            if len(senders) >= 2:
                seen_lines.add(rcv.op.line)
                tag_s = "ANY_TAG" if rcv.tag == ANY else str(rcv.tag)
                out.append(CGFinding(
                    rcv.op.line, "MPL009",
                    f"ANY_SOURCE recv (tag {tag_s}) has {len(senders)} "
                    f"eligible senders {senders} on {comm}: the match "
                    f"order is a nondeterministic race (run under "
                    f"verify mode to observe it via vector clocks)"))


# -- driver ------------------------------------------------------------------

def analyze_root(root: RootOps) -> List[CGFinding]:
    findings: List[CGFinding] = []
    by_comm: Dict[str, List[Op]] = {}
    for op in root.ops:
        op = _rekey(op)
        if op.comm in ("self", "<world>"):
            # `self`-keyed ops are a communicator implementing itself,
            # not an SPMD program over one; un-keyable MPI_* calls have
            # no model either way
            continue
        by_comm.setdefault(op.comm, []).append(op)
    for comm, ops in by_comm.items():
        size = _world_size(ops)
        by_rank = _instantiate(ops, comm, size)
        if by_rank is None:
            continue
        _rule_collective_divergence(comm, size, by_rank, findings)
        _rule_send_send_cycle(comm, size, by_rank, findings)
        _rule_channel_rules(comm, size, by_rank, findings)
        _rule_wildcard_race(comm, size, by_rank, findings)
    return findings


def analyze(roots: List[RootOps]) -> List[CGFinding]:
    """Match-graph findings for all roots of one module, deduplicated by
    (line, code)."""
    seen = set()
    out: List[CGFinding] = []
    for root in roots:
        for f in analyze_root(root):
            key = (f.line, f.code)
            if key not in seen:
                seen.add(key)
                out.append(f)
    return out
