"""Derived-datatypes demo: matrix-column exchange with MPI_Type_vector.

Rank 0 owns a matrix and sends its column 2 (a strided layout — no copy
loop in user code, the datatype describes it); rank 1 receives it into
column 0 of a zero matrix via the typed-recv unpack.  Run:

    python -m mpi_tpu.launcher -n 2 examples/datatypes_demo.py
"""

import os
import sys

import numpy as np

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mpi_tpu
from mpi_tpu import datatypes as dt
from mpi_tpu.api import MPI_Recv, MPI_Send

comm = mpi_tpu.COMM_WORLD
assert comm.size == 2, "run with -n 2"

nrows, ncols = 4, 5
col = dt.type_vector(nrows, 1, ncols, np.float64).commit()

if comm.rank == 0:
    a = np.arange(nrows * ncols, dtype=np.float64).reshape(nrows, ncols)
    col2 = dt.Datatype(col.base_dtype, col.indices + 2, col.extent)
    MPI_Send(a, dest=1, comm=comm, datatype=col2)
    print(f"rank 0 sent column 2: {a[:, 2]}")
else:
    out = np.zeros((nrows, ncols))
    MPI_Recv(source=0, comm=comm, datatype=col, buf=out)
    expect = np.arange(2, nrows * ncols, ncols, dtype=np.float64)
    assert np.array_equal(out[:, 0], expect), out
    assert np.all(out[:, 1:] == 0)
    print(f"rank 1 unpacked into column 0: {out[:, 0]} OK")
