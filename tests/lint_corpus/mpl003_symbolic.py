"""Seeded bug: the truncating count pair expressed through variables —
``n`` and ``n // 2`` only compare under constant propagation."""


def main(comm, buf, b, dt):
    n = 8
    if comm.rank == 0:
        MPI_Send(buf, dest=1, datatype=dt, count=n)
    if comm.rank == 1:
        return MPI_Recv(source=0, datatype=dt, buf=b, count=n // 2)
    return None
