"""MPI-4 previews: persistent collectives and partitioned communication.

Beyond the MPI-3.0 conformance line (api.MPI_Get_version), two MPI-4
features whose shapes fit this framework naturally:

* **Persistent collectives** (MPI_Bcast_init & co. [S: MPI-4 ch.6.11]):
  plan a collective once, ``start()`` it many times.  Each handle owns
  ONE isolated child context (the same deterministic counter scheme as
  nonblocking collectives), so repeated starts can never cross-match —
  and, per MPI, every rank must create and start its persistent
  collectives in the same order.  Buffer CONTENT is read at start time
  (the handle holds references, like send_init).

* **Partitioned point-to-point** (MPI_Psend_init / Precv_init / Pready /
  Parrived [S: MPI-4 ch.4]): one logical message whose partitions are
  contributed (e.g. by different producer threads) and consumed
  independently.  Each matched psend/precv pair gets its own context
  derived from a per-(peer, tag) counter maintained symmetrically on
  both sides — MPI's in-order matching of partitioned inits, spelled as
  context isolation, so concurrent pairs on one (peer, tag) can never
  interleave.  Partitions travel as individual internal messages
  ``(index, payload)``; ``pready(i)`` reads partition ``i`` at call
  time and ships it; ``parrived(i)`` polls without blocking.

* **Sessions** (MPI_Session_init / pset discovery /
  MPI_Group_from_session_pset / MPI_Comm_create_from_group [S: MPI-4
  ch.11]): the modern init story — a library acquires its OWN runtime
  handle, discovers process sets by name, builds a group from a pset,
  and derives a communicator from the group without ever touching
  MPI_Init or MPI_COMM_WORLD.  Here the runtime instance is the
  launcher-provided transport (the same discovery MPI_Init uses);
  sessions share it but derive every communicator on a
  session-namespaced context keyed by the MPI-mandated
  ``(group members, stringtag)`` pair, so session traffic can never
  cross-match world traffic — context isolation IS the session
  boundary, the same scheme nonblocking/persistent collectives use.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .communicator import (Communicator, P2PCommunicator, Request,
                           _ThreadRequest, snapshot_payload)

__all__ = [
    "PersistentCollective", "persistent_collective",
    "PsendRequest", "PrecvRequest", "psend_init", "precv_init",
    "Session", "session_init",
]

_TAG_PART = -41  # partitioned traffic (negative: invisible to wildcards)


def _require_p2p(comm, what: str) -> P2PCommunicator:
    if not isinstance(comm, P2PCommunicator):
        raise NotImplementedError(
            f"{what} is a process-backend feature; on the SPMD backend a "
            "collective inside jit is already a plan (XLA compiles it "
            "once) — just call it")
    return comm


class PersistentCollective(Request):
    """A planned collective: ``start()`` runs one round on the handle's
    private context; ``wait()``/``test()`` complete the current round."""

    def __init__(self, comm: P2PCommunicator, method: str,
                 args: tuple, kwargs: dict):
        self._comm = comm._nbc_comm()  # one private context for all rounds
        self._method = method
        self._args, self._kwargs = args, kwargs
        self._req: Optional[Request] = None

    def start(self) -> "PersistentCollective":
        if self._req is not None and not self._req.test()[0]:
            raise RuntimeError(
                "start() while the previous round of this persistent "
                "collective is still in flight (wait() it first)")
        fn = getattr(self._comm, self._method)
        self._req = _ThreadRequest(lambda: fn(*self._args, **self._kwargs))
        return self

    def wait(self) -> Any:
        if self._req is None:
            raise RuntimeError("wait() before start() on a persistent "
                               "collective")
        return self._req.wait()

    def test(self) -> Tuple[bool, Any]:
        if self._req is None:
            return False, None
        return self._req.test()


def persistent_collective(comm: Communicator, method: str, *args: Any,
                          **kwargs: Any):
    """Generic MPI_*_init for collectives: ``method`` is the Communicator
    method name ('bcast', 'allreduce', 'reduce', 'allgather', 'alltoall',
    'barrier', ...).  The plannable kinds (allreduce/bcast/alltoall/
    reduce_scatter) return the engine-owned handle (mpi_tpu/nbc.py,
    ISSUE 12) — compiled schedule, hoisted child context + tuned-table
    resolution + verifier signature, zero-thread ``start()`` re-fires on
    progress-engine worlds; everything else keeps the generic
    one-thread-per-round handle with identical start/wait discipline."""
    c = _require_p2p(comm, "persistent collectives")
    if not callable(getattr(c, method, None)):
        raise ValueError(f"unknown collective method {method!r}")
    from . import nbc as _nbc

    if method in _nbc.PERSISTENT_KINDS:
        return _nbc.persistent_init(c, method, *args, **kwargs)
    return PersistentCollective(c, method, args, kwargs)


# -- partitioned point-to-point ---------------------------------------------


def _pair_ctx_comm(comm: P2PCommunicator, peer: int, tag: int,
                   side_counter: str) -> P2PCommunicator:
    """A private context for ONE matched psend/precv pair.  Both sides
    advance a per-(peer, tag) counter at init time, so the k-th
    psend_init(dest, tag) matches the k-th precv_init(source, tag) —
    MPI's in-order matching, enforced structurally."""
    with comm._lock:
        table = comm.__dict__.setdefault(side_counter, {})
        k = table.get((peer, tag), 0)
        table[(peer, tag)] = k + 1
    return P2PCommunicator(comm._t, comm._group,
                           (comm._ctx, "part", tag, k),
                           recv_timeout=comm.recv_timeout)


class PsendRequest:
    """Sender side of a partitioned send (MPI_Psend_init)."""

    def __init__(self, comm: P2PCommunicator, buf: Any, partitions: int,
                 dest: int, tag: int):
        if partitions < 1:
            raise ValueError(f"partitions must be >= 1, got {partitions}")
        self._c = _pair_ctx_comm(comm, dest, tag, "_psend_counters")
        self._buf = buf
        self._n = int(partitions)
        self._dest = dest
        self._active = False
        self._ready: set = set()
        self._lock = threading.Lock()

    def start(self) -> "PsendRequest":
        with self._lock:
            if self._active:
                raise RuntimeError("start() on an active partitioned send "
                                   "(wait() the previous round first)")
            self._active = True
            self._ready = set()
        return self

    def pready(self, i: int) -> None:
        """Mark partition ``i`` ready: its CURRENT content ships now.
        Thread-safe — different producer threads may ready different
        partitions (the MPI-4 use case)."""
        with self._lock:
            if not self._active:
                raise RuntimeError("pready() outside an active round "
                                   "(call start() first)")
            if not (0 <= i < self._n):
                raise ValueError(f"partition {i} out of range "
                                 f"(0..{self._n - 1})")
            if i in self._ready:
                raise RuntimeError(f"partition {i} already marked ready "
                                   "this round")
            # send INSIDE the lock: marking ready and enqueueing must be
            # atomic, or a racing test()/start() could begin the next
            # round and enqueue ITS sends first — FIFO would then hand
            # the receiver a next-round payload inside this round
            # (review round 3).  Snapshot rules shared with
            # PersistentRequest.start (communicator.snapshot_payload).
            part = snapshot_payload(self._c._t, self._buf[i])
            self._c._send_internal((int(i), part), self._dest, _TAG_PART)
            self._ready.add(i)

    def pready_range(self, lo: int, hi: int) -> None:
        """MPI_Pready_range marks ``lo``..``hi`` INCLUSIVE [S: MPI-4]."""
        for i in range(lo, hi + 1):
            self.pready(i)

    def wait(self) -> None:
        """Complete the round; every partition must have been readied
        (a silent partial send would deadlock the receiver)."""
        with self._lock:
            if not self._active:
                raise RuntimeError("wait() outside an active round")
            missing = [i for i in range(self._n) if i not in self._ready]
            if missing:
                raise RuntimeError(
                    f"wait() with partitions never marked ready: "
                    f"{missing[:8]}{'...' if len(missing) > 8 else ''} — "
                    "the receiver would block forever")
            self._active = False  # sends are buffered: complete on enqueue

    def test(self) -> Tuple[bool, Any]:
        """MPI semantics: an inactive request tests True; a completed
        test DEACTIVATES the round (like wait), so start() may follow."""
        with self._lock:
            if not self._active:
                return True, None
            if len(self._ready) == self._n:
                self._active = False
                return True, None
            return False, None


class PrecvRequest:
    """Receiver side (MPI_Precv_init): partitions complete independently;
    ``parrived(i)`` polls, ``wait()`` assembles the full message."""

    def __init__(self, comm: P2PCommunicator, partitions: int,
                 source: int, tag: int):
        if partitions < 1:
            raise ValueError(f"partitions must be >= 1, got {partitions}")
        self._c = _pair_ctx_comm(comm, source, tag, "_precv_counters")
        self._n = int(partitions)
        self._source = source
        self._got: Dict[int, Any] = {}
        self._active = False
        self._result: Optional[List[Any]] = None
        self._lock = threading.Lock()  # consumer threads poll concurrently

    def start(self) -> "PrecvRequest":
        with self._lock:
            if self._active:
                raise RuntimeError("start() on an active partitioned recv")
            self._active = True
            self._got = {}
            self._result = None
        return self

    def _drain_nowait_locked(self) -> None:
        # caller holds self._lock.  Bounded to THIS round's partition
        # count: an unbounded (or un-serialized, with concurrent
        # consumer threads) drain would steal the sender's next-round
        # messages, corrupting this round and deadlocking the next
        # (review round 3 — reproduced)
        while len(self._got) < self._n:
            hit = self._c._t.poll(self._c._world(self._source),
                                  self._c._ctx, _TAG_PART)
            if hit is None:
                return
            (i, part), _, _ = hit
            self._got[i] = part

    def parrived(self, i: int) -> bool:
        """MPI_Parrived: has partition ``i`` landed? (non-blocking;
        thread-safe — consumer threads may poll concurrently)"""
        if not (0 <= i < self._n):
            raise ValueError(f"partition {i} out of range (0..{self._n - 1})")
        with self._lock:
            if not self._active:
                raise RuntimeError("parrived() outside an active round")
            self._drain_nowait_locked()
            return i in self._got

    def partition(self, i: int) -> Any:
        """Partition ``i``'s payload (must have arrived)."""
        if not self.parrived(i):
            raise RuntimeError(f"partition {i} has not arrived yet")
        with self._lock:
            return self._got[i]

    def wait(self) -> List[Any]:
        """Block until every partition landed; returns them in partition
        order (stacked by the caller if desired).  After a successful
        test() completed the round, wait() returns the same result."""
        import time

        with self._lock:
            if not self._active:
                if self._result is not None:
                    return self._result
                raise RuntimeError("wait() outside an active round")
        # poll under the lock rather than blocking in transport recv: a
        # concurrent parrived() could consume the last missing message
        # and leave a blocking recv stuck waiting for (and then
        # stealing) a NEXT-round message
        timeout = self._c.recv_timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                self._drain_nowait_locked()
                if len(self._got) >= self._n:
                    return self._finish_locked()
                missing = [i for i in range(self._n) if i not in self._got]
            if deadline is not None and time.monotonic() > deadline:
                from .transport.base import RecvTimeout

                raise RecvTimeout(
                    f"partitioned recv: partitions {missing[:8]} from rank "
                    f"{self._source} never arrived within {timeout}s")
            time.sleep(0.0005)

    def _finish_locked(self) -> List[Any]:
        # caller holds self._lock
        self._active = False
        self._result = [self._got[i] for i in range(self._n)]
        return self._result

    def test(self) -> Tuple[bool, Any]:
        """Inactive tests True; completion DEACTIVATES the round and
        caches the assembled result for a subsequent wait()."""
        with self._lock:
            if not self._active:
                return True, self._result
            self._drain_nowait_locked()
            if len(self._got) == self._n:
                return True, self._finish_locked()
            return False, None


# -- sessions (MPI-4 ch.11) ---------------------------------------------------

# generation counters for comm_create_from_group contexts, keyed by
# (calling world rank, world_ranks, stringtag) — module-global (NOT
# per-Session: context isolation must hold across sessions) but
# rank-scoped via the key, so thread-backed ranks sharing one process
# count independently (see Session.comm_create_from_group).
#
# All three tables are guarded by _CFG_LOCK (ADVICE r5 #2: the bare
# get-then-set raced under MPI_THREAD_MULTIPLE — two threads could claim
# the same generation and silently cross-match traffic).  _CFG_IN_FLIGHT
# holds keys whose creation is between generation claim and communicator
# wiring: a second creation with an identical key inside that window is
# the "concurrent calls with an identical (group, stringtag) pair" case
# MPI-4 §11.6 declares erroneous, and it now raises instead of handing
# out a generation whose cross-rank ordering is undefined.  _CFG_LIVE
# refcounts, per key, the sessions that created under it; a key's
# generation counter is pruned when the LAST such session finalizes
# (its communicators must already be out of use per MPI-4, so restarting
# at generation 0 cannot collide with live traffic — while any sharing
# session is still live the counter survives).
_CFG_GENERATIONS: Dict[Tuple, int] = {}
_CFG_IN_FLIGHT: set = set()
_CFG_LIVE: Dict[Tuple, int] = {}
_CFG_LOCK = threading.Lock()


def _cfg_prune(keys) -> None:
    """Drop one session's refcount on each of ``keys``; forget generation
    counters whose last holder is gone (session-finalize prune)."""
    with _CFG_LOCK:
        for key in keys:
            n = _CFG_LIVE.get(key, 0) - 1
            if n <= 0:
                _CFG_LIVE.pop(key, None)
                _CFG_GENERATIONS.pop(key, None)
            else:
                _CFG_LIVE[key] = n


def _cfg_prune_all() -> None:
    """World-finalize prune of counters no LIVE session still holds.

    Finalizing the process world must not clear keys of unfinalized
    sessions on OTHER worlds (run_local thread worlds take any
    base_comm) — restarting their counters at generation 0 could
    collide with a still-open communicator's context.  Sessions that
    were garbage-collected without finalize() drop out of the weak
    registry, so exactly the leaked keys get swept here."""
    with _CFG_LOCK:
        held = set()
        for sess in list(_LIVE_SESSIONS):
            if not sess._finalized:
                held.update(sess._cfg_keys)
        for key in [k for k in _CFG_GENERATIONS if k not in held]:
            _CFG_GENERATIONS.pop(key, None)
            _CFG_LIVE.pop(key, None)
        _CFG_IN_FLIGHT.difference_update(
            k for k in list(_CFG_IN_FLIGHT) if k not in held)


_LIVE_SESSIONS: "weakref.WeakSet" = weakref.WeakSet()


class Session:
    """An MPI-4 session: a private handle to the runtime.

    Construct via :func:`session_init`.  The world process model and the
    sessions model coexist (MPI-4 §11.4): both tap the same underlying
    transport, but a session never touches the COMM_WORLD singleton and
    all communicators it derives live on session-namespaced contexts.

    ``base_comm`` injects the runtime access explicitly (how the local
    thread backend's per-rank sessions are built — and how tests drive
    multi-rank sessions); by default the launcher-provided environment
    is discovered exactly as ``MPI_Init`` would.
    """

    #: the two predefined process sets every session exposes (MPI-4
    #: §11.9.2; additional runtime-defined psets would list after these)
    _PSETS = ("mpi://WORLD", "mpi://SELF")

    def __init__(self, info: Optional[dict] = None, errhandler=None,
                 base_comm: Optional[P2PCommunicator] = None):
        if base_comm is None:
            import mpi_tpu as _m

            base_comm = _m.init()
        self._base = _require_p2p(base_comm, "sessions")
        self._info = dict(info or {})
        self._errhandler = errhandler
        self._finalized = False
        # comm_create_from_group keys this session holds live (with
        # multiplicity) — released at finalize, see _cfg_prune
        self._cfg_keys: List[Tuple] = []
        _LIVE_SESSIONS.add(self)

    # -- pset discovery ----------------------------------------------------

    def get_num_psets(self, info: Optional[dict] = None) -> int:
        """MPI_Session_get_num_psets."""
        self._check_live()
        return len(self._PSETS)

    def get_nth_pset(self, n: int, info: Optional[dict] = None) -> str:
        """MPI_Session_get_nth_pset."""
        self._check_live()
        if not (0 <= n < len(self._PSETS)):
            raise ValueError(
                f"pset index {n} out of range (0..{len(self._PSETS) - 1})")
        return self._PSETS[n]

    def get_info(self) -> dict:
        """MPI_Session_get_info (hints echoed back; advisory)."""
        self._check_live()
        return dict(self._info)

    # -- group / communicator derivation -----------------------------------

    def group_from_pset(self, pset_name: str):
        """MPI_Group_from_session_pset: the ordered member set of the
        named pset, as a Group of runtime (world) ranks."""
        self._check_live()
        from .group import Group

        if pset_name == "mpi://WORLD":
            return Group(range(self._base.size))
        if pset_name == "mpi://SELF":
            return Group([self._base.rank])
        raise ValueError(
            f"unknown process set {pset_name!r}; this session has "
            f"{list(self._PSETS)}")

    def comm_create_from_group(self, group, stringtag: str = "",
                               info: Optional[dict] = None,
                               errhandler=None) -> P2PCommunicator:
        """MPI_Comm_create_from_group: a communicator over ``group``
        (runtime ranks, in group order) — collective over the GROUP
        MEMBERS only, no parent communicator involved.  Matching follows
        MPI-4: concurrent calls are disambiguated by the
        ``(group members, stringtag)`` pair; every member must pass the
        same group and stringtag, and CONCURRENT calls with an
        identical pair are erroneous.

        SEQUENTIAL calls with the same pair are legal and must yield
        ISOLATED communicators (ADVICE r4 #1: a static context would
        cross-match their traffic, e.g. a stale unmatched isend on the
        first comm received by the second).  A per-RANK generation
        counter keyed by (calling world rank, world_ranks, stringtag)
        is mixed into the context: every member participates in every
        creation with this key, creations with one key are ordered
        (they are collectives over the same members, and concurrent
        identical pairs are erroneous per MPI-4), so each member's Nth
        call counts N on its own key — the contexts agree across
        members with no extra traffic, and repeated creations get
        distinct contexts.  The calling rank must be part of the KEY
        but not the context: on the threaded local backend all ranks
        share one process, so a process-global counter would advance
        once per MEMBER and disagree across ranks (found by this
        change's own isolation test deadlocking)."""
        self._check_live()
        ranks = tuple(int(r) for r in group.ranks)
        if self._base.rank not in ranks:
            raise ValueError(
                f"calling rank {self._base.rank} is not in the group "
                f"{list(ranks)} (comm_create_from_group is collective "
                f"over the group members themselves)")
        # group ranks are BASE-comm-local (what group_from_pset hands
        # out); the transport speaks world ranks — translate, so a base
        # comm that is itself a split/reordered view of the world still
        # derives a correctly-wired communicator (review round 4).  The
        # context must also be spelled in world ranks: it has to be
        # byte-identical across member processes whose local numbering
        # may differ.
        world_ranks = tuple(self._base._world(r) for r in ranks)
        key = (self._base._t.world_rank, world_ranks, str(stringtag))
        with _CFG_LOCK:
            if key in _CFG_IN_FLIGHT:
                raise RuntimeError(
                    f"concurrent MPI_Comm_create_from_group calls with an "
                    f"identical (group={list(ranks)}, "
                    f"stringtag={str(stringtag)!r}) pair on rank "
                    f"{self._base.rank} — erroneous per MPI-4 §11.6: "
                    f"identical concurrent creations cannot be matched "
                    f"across members (disambiguate with distinct "
                    f"stringtags, or order the calls)")
            _CFG_IN_FLIGHT.add(key)
            gen = _CFG_GENERATIONS.get(key, 0)
            _CFG_GENERATIONS[key] = gen + 1
            _CFG_LIVE[key] = _CFG_LIVE.get(key, 0) + 1
            self._cfg_keys.append(key)
        try:
            return P2PCommunicator(
                self._base._t, world_ranks,
                context=("sess", world_ranks, str(stringtag), gen),
                recv_timeout=self._base.recv_timeout)
        finally:
            with _CFG_LOCK:
                _CFG_IN_FLIGHT.discard(key)

    # -- lifecycle ---------------------------------------------------------

    def finalize(self) -> None:
        """MPI_Session_finalize: the session handle becomes unusable.
        Communicators derived from it must already be out of use (MPI
        erroneous otherwise); the shared runtime transport is NOT closed
        — it belongs to the process (world model finalize / launcher
        teardown owns it).  Generation counters this session held are
        released (and forgotten once no live session shares them), so
        long-running processes that churn sessions don't grow the
        module-global table without bound."""
        if not self._finalized:
            self._finalized = True
            keys, self._cfg_keys = self._cfg_keys, []
            _cfg_prune(keys)

    @property
    def finalized(self) -> bool:
        return self._finalized

    def _check_live(self) -> None:
        if self._finalized:
            raise RuntimeError("operation on a finalized MPI session")

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.finalize()


def session_init(info: Optional[dict] = None, errhandler=None,
                 base_comm: Optional[P2PCommunicator] = None) -> Session:
    """MPI_Session_init (see :class:`Session`)."""
    return Session(info, errhandler, base_comm)


def psend_init(comm: Communicator, buf: Any, partitions: int, dest: int,
               tag: int = 0) -> PsendRequest:
    """MPI_Psend_init: ``buf[i]`` is partition ``i`` (any indexable —
    a [partitions, ...] array or a list)."""
    return PsendRequest(_require_p2p(comm, "partitioned communication"),
                        buf, partitions, dest, tag)


def precv_init(comm: Communicator, partitions: int, source: int,
               tag: int = 0) -> PrecvRequest:
    return PrecvRequest(_require_p2p(comm, "partitioned communication"),
                        partitions, source, tag)
