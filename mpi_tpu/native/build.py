"""Build-on-first-use for the native shm ring (g++ -> _shmring.so).

Many rank processes may import concurrently (the launcher spawns them in a
burst), so the compile is serialized with an exclusive flock and lands via
atomic rename; losers of the race find the finished .so.  The .so is cached
next to the source and rebuilt whenever shmring.cpp is newer.

Sanitizer builds: ``MPI_TPU_SANITIZE=address|undefined|thread`` adds the
matching ``-fsanitize=`` flags and caches the result under a
mode-specific name (``_shmring.asan.so`` etc.) so sanitized and plain
builds never overwrite each other.  Loading an ASan build into an
un-instrumented python usually needs ``LD_PRELOAD=$(gcc
-print-file-name=libasan.so)`` — see tests/test_sanitize_native.py for
the working recipe.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
import tempfile

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "shmring.cpp")
_SO = os.path.join(_DIR, "_shmring.so")

# sanitizer mode -> (cache-name infix, -fsanitize= flag list); the env
# knob is read per build so one process builds exactly one mode
_SANITIZERS = {
    "address": ("asan", ["-fsanitize=address", "-fno-omit-frame-pointer",
                         "-g"]),
    "undefined": ("ubsan", ["-fsanitize=undefined",
                            "-fno-sanitize-recover=undefined", "-g"]),
    "thread": ("tsan", ["-fsanitize=thread", "-g"]),
}

_lib = None


class NativeBuildError(RuntimeError):
    pass


def sanitize_mode() -> str:
    """The MPI_TPU_SANITIZE env knob, validated ('' = plain build)."""
    mode = os.environ.get("MPI_TPU_SANITIZE", "").strip()
    if mode and mode not in _SANITIZERS:
        raise NativeBuildError(
            f"unknown MPI_TPU_SANITIZE={mode!r}; accepted: "
            f"{sorted(_SANITIZERS)} (or unset for a plain build)")
    return mode


def _so_path(mode: str) -> str:
    if not mode:
        return _SO
    return os.path.join(_DIR, f"_shmring.{_SANITIZERS[mode][0]}.so")


def ensure_built(force: bool = False) -> str:
    """Compile shmring.cpp if needed; return the path to the .so.

    ``force`` rebuilds even when the cached .so looks fresh — the recovery
    path for a .so carried over from a host with a different glibc layout
    (dlopen fails with an unresolved symbol; see load_shmring)."""
    mode = sanitize_mode()
    so = _so_path(mode)
    if (not force and os.path.exists(so)
            and os.path.getmtime(so) >= os.path.getmtime(_SRC)):
        return so
    lock_path = os.path.join(_DIR, ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if (not force and os.path.exists(so)
                    and os.path.getmtime(so) >= os.path.getmtime(_SRC)):
                return so  # another process built it while we waited
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            # -lrt: shm_open/shm_unlink live in librt on pre-2.34 glibc
            # (a stub librt still exists on newer ones, so the flag is
            # portable both ways)
            cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                   *(_SANITIZERS[mode][1] if mode else []),
                   "-o", tmp, _SRC, "-pthread", "-lrt"]
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True)
            except FileNotFoundError as e:
                os.unlink(tmp)
                raise NativeBuildError(
                    "g++ not found; the shm backend needs the native "
                    "toolchain (fall back to backend=socket)") from e
            if proc.returncode != 0:
                os.unlink(tmp)
                raise NativeBuildError(
                    f"shmring.cpp failed to compile"
                    f"{f' (MPI_TPU_SANITIZE={mode})' if mode else ''}:\n"
                    f"{proc.stderr[-2000:]}")
            os.replace(tmp, so)
            return so
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def load_shmring() -> ctypes.CDLL:
    """Load (building if necessary) and type the native library."""
    global _lib
    if _lib is not None:
        return _lib
    try:
        lib = ctypes.CDLL(ensure_built())
    except OSError:
        # a cached .so from a host with a different glibc (e.g. shm_open
        # moved between librt and libc) fails at dlopen, not at build —
        # recompile against THIS toolchain and retry once
        lib = ctypes.CDLL(ensure_built(force=True))
    lib.shmring_create.restype = ctypes.c_void_p
    lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shmring_open.restype = ctypes.c_void_p
    lib.shmring_open.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.shmring_avail.restype = ctypes.c_uint64
    lib.shmring_avail.argtypes = [ctypes.c_void_p]
    # buf params are c_void_p: accepts bytes, ctypes buffers, AND raw
    # integer addresses (ndarray.ctypes.data) — the zero-copy array path
    lib.shmring_write.restype = ctypes.c_int
    lib.shmring_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_uint64, ctypes.c_double]
    lib.shmring_read.restype = ctypes.c_int
    lib.shmring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_uint64, ctypes.c_double]
    lib.shmring_read_some.restype = ctypes.c_int64
    lib.shmring_read_some.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_uint64, ctypes.c_double]
    lib.shmring_write_some.restype = ctypes.c_int64
    lib.shmring_write_some.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_uint64, ctypes.c_double]
    lib.shmring_close.restype = None
    lib.shmring_close.argtypes = [ctypes.c_void_p]
    lib.shmring_unlink.restype = ctypes.c_int
    lib.shmring_unlink.argtypes = [ctypes.c_char_p]
    lib.shmdb_create.restype = ctypes.c_void_p
    lib.shmdb_create.argtypes = [ctypes.c_char_p]
    lib.shmdb_open.restype = ctypes.c_void_p
    lib.shmdb_open.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.shmdb_read.restype = ctypes.c_uint32
    lib.shmdb_read.argtypes = [ctypes.c_void_p]
    lib.shmdb_ring.restype = None
    lib.shmdb_ring.argtypes = [ctypes.c_void_p]
    lib.shmdb_wait.restype = ctypes.c_uint32
    lib.shmdb_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                               ctypes.c_double]
    lib.shmdb_close.restype = None
    lib.shmdb_close.argtypes = [ctypes.c_void_p]
    lib.shmdb_unlink.restype = ctypes.c_int
    lib.shmdb_unlink.argtypes = [ctypes.c_char_p]
    # collective arena (coll/sm): one segment per shm communicator, with
    # per-rank flag lines driven by the shmflag_* ops (mpi_tpu/coll_sm.py)
    lib.shmarena_create.restype = ctypes.c_void_p
    lib.shmarena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shmarena_open.restype = ctypes.c_void_p
    lib.shmarena_open.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.shmarena_addr.restype = ctypes.c_uint64
    lib.shmarena_addr.argtypes = [ctypes.c_void_p]
    lib.shmarena_size.restype = ctypes.c_uint64
    lib.shmarena_size.argtypes = [ctypes.c_void_p]
    lib.shmarena_close.restype = None
    lib.shmarena_close.argtypes = [ctypes.c_void_p]
    lib.shmarena_unlink.restype = ctypes.c_int
    lib.shmarena_unlink.argtypes = [ctypes.c_char_p]
    lib.shmflag_read.restype = ctypes.c_uint32
    lib.shmflag_read.argtypes = [ctypes.c_uint64]
    lib.shmflag_post.restype = None
    lib.shmflag_post.argtypes = [ctypes.c_uint64, ctypes.c_uint32]
    lib.shmflag_wait_ge.restype = ctypes.c_uint32
    lib.shmflag_wait_ge.argtypes = [ctypes.c_uint64, ctypes.c_uint32,
                                    ctypes.c_double]
    _lib = lib
    return lib
