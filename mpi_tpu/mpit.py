"""MPI_T tool interface [S: MPI-3 ch.14] — the introspection chapter.

A deliberately small, honest implementation of the two variable kinds:

* **Control variables (cvar)**: named knobs a tool can read and set.
  Registered here are the real, load-bearing ones this library already
  has (collective algorithm crossover, the collective-IO buffering
  limit, receive timeout default).
* **Performance variables (pvar)**: counters a tool can read/reset.
  Counted (thread-safely) at the one choke point every process backend
  shares — P2PCommunicator._send_internal / _recv_internal, plus every
  collective entry point — so message/collective counts are exact
  regardless of transport.  ``bytes_sent`` counts SIZED payloads
  (arrays / bytes); opaque pickled objects count 0 there (their wire
  size is a transport detail).

Sessions are the MPI_T scoping object; handles are (session, variable)
pairs, pythonically collapsed — a session simply records which pvars it
reset, so reads are session-relative like the standard requires.
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "cvar_register", "cvar_list", "cvar_read", "cvar_write",
    "pvar_list", "pvar_read", "pvar_reset",
    "hist_record", "pvar_hist_list", "pvar_hist_read",
    "pvar_hist_reset", "hist_quantile", "hist_cumulative",
    "Session", "session_create",
]

_lock = threading.Lock()


# -- performance variables (exact transport-level counters) ------------------

class _Counters:
    __slots__ = ("sends", "send_bytes", "recvs", "collectives",
                 "pallas_fallbacks", "bytes_raw", "bytes_pickled", "copies",
                 "proc_failed", "revokes", "shrinks",
                 "faulty_dropped", "faulty_duplicated", "attention_oob",
                 "sm_hits", "sm_bytes", "sm_fallbacks",
                 "v_deadlocks", "v_mismatches", "v_leaked", "v_double_waits",
                 "v_buf_overlaps", "v_comms_unfreed",
                 "v_wildcard_races", "v_clock_bytes",
                 "prog_wakeups", "prog_completions", "prog_idle_parks",
                 "rejoins", "epoch_skews",
                 "comp_saved", "comp_fallbacks",
                 "tuned_hits", "tuned_fallbacks",
                 "link_reconnects", "link_replayed", "link_masked",
                 "link_retained", "link_cow_snaps", "link_cow_bytes",
                 "link_syscalls", "link_rsyscalls", "link_torn",
                 "nbc_threads", "nbc_sms", "persist_starts",
                 "trace_events",
                 "rp_hits", "rp_misses", "rp_rdv", "rp_steered",
                 "rp_fold", "rp_user_in", "rp_user_fb",
                 "store_elections", "store_truncated", "store_dropped")

    def __init__(self) -> None:
        self.sends = 0
        self.send_bytes = 0
        self.recvs = 0
        self.collectives = 0
        self.pallas_fallbacks = 0
        self.bytes_raw = 0
        self.bytes_pickled = 0
        self.copies = 0
        self.proc_failed = 0
        self.revokes = 0
        self.shrinks = 0
        self.faulty_dropped = 0
        self.faulty_duplicated = 0
        self.attention_oob = 0
        self.sm_hits = 0
        self.sm_bytes = 0
        self.sm_fallbacks = 0
        self.v_deadlocks = 0
        self.v_mismatches = 0
        self.v_leaked = 0
        self.v_double_waits = 0
        self.v_buf_overlaps = 0
        self.v_comms_unfreed = 0
        self.v_wildcard_races = 0
        self.v_clock_bytes = 0
        self.prog_wakeups = 0
        self.prog_completions = 0
        self.prog_idle_parks = 0
        self.rejoins = 0
        self.epoch_skews = 0
        self.comp_saved = 0
        self.comp_fallbacks = 0
        self.tuned_hits = 0
        self.tuned_fallbacks = 0
        self.link_reconnects = 0
        self.link_replayed = 0
        self.link_masked = 0
        self.link_retained = 0
        self.link_cow_snaps = 0
        self.link_cow_bytes = 0
        self.link_syscalls = 0
        self.link_rsyscalls = 0
        self.link_torn = 0
        self.nbc_threads = 0
        self.nbc_sms = 0
        self.persist_starts = 0
        self.trace_events = 0
        self.rp_hits = 0
        self.rp_misses = 0
        self.rp_rdv = 0
        self.rp_steered = 0
        self.rp_fold = 0
        self.rp_user_in = 0
        self.rp_user_fb = 0
        self.store_elections = 0
        self.store_truncated = 0
        self.store_dropped = 0


counters = _Counters()  # incremented by communicator.py / codec.py (count())


def count(sends: int = 0, send_bytes: int = 0, recvs: int = 0,
          collectives: int = 0, pallas_fallbacks: int = 0,
          bytes_raw: int = 0, bytes_pickled: int = 0, copies: int = 0,
          proc_failed: int = 0, revokes: int = 0, shrinks: int = 0,
          faulty_dropped: int = 0, faulty_duplicated: int = 0,
          attention_oob: int = 0, coll_sm_hits: int = 0,
          coll_sm_bytes: int = 0, coll_sm_fallbacks: int = 0,
          verify_deadlocks: int = 0, verify_mismatches: int = 0,
          verify_requests_leaked: int = 0, verify_double_waits: int = 0,
          verify_buffer_overlaps: int = 0,
          verify_comms_unfreed: int = 0,
          verify_wildcard_races: int = 0,
          verify_clock_bytes: int = 0,
          progress_wakeups: int = 0, progress_completions: int = 0,
          progress_idle_parks: int = 0,
          rejoins: int = 0, epoch_skews: int = 0,
          bytes_compressed_saved: int = 0,
          compress_fallbacks: int = 0,
          tuned_table_hits: int = 0,
          tuned_table_fallbacks: int = 0,
          link_reconnects: int = 0,
          link_frames_replayed: int = 0,
          link_faults_masked: int = 0,
          link_bytes_retained: int = 0,
          link_cow_snapshots: int = 0,
          link_cow_bytes: int = 0,
          link_send_syscalls: int = 0,
          link_recv_syscalls: int = 0,
          link_torn_frames: int = 0,
          nbc_threads_spawned: int = 0,
          nbc_state_machines: int = 0,
          persistent_starts: int = 0,
          trace_events: int = 0,
          recv_pool_hits: int = 0,
          recv_pool_misses: int = 0,
          recv_pool_rendezvous: int = 0,
          recv_bytes_steered: int = 0,
          recv_pool_fold_fallbacks: int = 0,
          recv_user_inplace: int = 0,
          recv_user_fallbacks: int = 0,
          store_elections: int = 0,
          store_entries_truncated: int = 0,
          store_partition_dropped: int = 0) -> None:
    """Thread-safe increment (rank-threads of the local backend share
    this process's counters; unsynchronized += would lose updates)."""
    with _lock:
        counters.sends += sends
        counters.send_bytes += send_bytes
        counters.recvs += recvs
        counters.collectives += collectives
        counters.pallas_fallbacks += pallas_fallbacks
        counters.bytes_raw += bytes_raw
        counters.bytes_pickled += bytes_pickled
        counters.copies += copies
        counters.proc_failed += proc_failed
        counters.revokes += revokes
        counters.shrinks += shrinks
        counters.faulty_dropped += faulty_dropped
        counters.faulty_duplicated += faulty_duplicated
        counters.attention_oob += attention_oob
        counters.sm_hits += coll_sm_hits
        counters.sm_bytes += coll_sm_bytes
        counters.sm_fallbacks += coll_sm_fallbacks
        counters.v_deadlocks += verify_deadlocks
        counters.v_mismatches += verify_mismatches
        counters.v_leaked += verify_requests_leaked
        counters.v_double_waits += verify_double_waits
        counters.v_buf_overlaps += verify_buffer_overlaps
        counters.v_comms_unfreed += verify_comms_unfreed
        counters.v_wildcard_races += verify_wildcard_races
        counters.v_clock_bytes += verify_clock_bytes
        counters.prog_wakeups += progress_wakeups
        counters.prog_completions += progress_completions
        counters.prog_idle_parks += progress_idle_parks
        counters.rejoins += rejoins
        counters.epoch_skews += epoch_skews
        counters.comp_saved += bytes_compressed_saved
        counters.comp_fallbacks += compress_fallbacks
        counters.tuned_hits += tuned_table_hits
        counters.tuned_fallbacks += tuned_table_fallbacks
        counters.link_reconnects += link_reconnects
        counters.link_replayed += link_frames_replayed
        counters.link_masked += link_faults_masked
        counters.link_retained += link_bytes_retained
        counters.link_cow_snaps += link_cow_snapshots
        counters.link_cow_bytes += link_cow_bytes
        counters.link_syscalls += link_send_syscalls
        counters.link_rsyscalls += link_recv_syscalls
        counters.link_torn += link_torn_frames
        counters.nbc_threads += nbc_threads_spawned
        counters.nbc_sms += nbc_state_machines
        counters.persist_starts += persistent_starts
        counters.trace_events += trace_events
        counters.rp_hits += recv_pool_hits
        counters.rp_misses += recv_pool_misses
        counters.rp_rdv += recv_pool_rendezvous
        counters.rp_steered += recv_bytes_steered
        counters.rp_fold += recv_pool_fold_fallbacks
        counters.rp_user_in += recv_user_inplace
        counters.rp_user_fb += recv_user_fallbacks
        counters.store_elections += store_elections
        counters.store_truncated += store_entries_truncated
        counters.store_dropped += store_partition_dropped

_PVARS: Dict[str, Callable[[], int]] = {
    "msgs_sent": lambda: counters.sends,
    "bytes_sent": lambda: counters.send_bytes,
    "msgs_received": lambda: counters.recvs,
    "collectives_started": lambda: counters.collectives,
    # times a pallas_ring call executed the vma/multi-axis ppermute
    # fallback instead of the kernel (pallas_ring.py _fallback; VERDICT
    # r3 weak #4 — sim benchmarks must not silently measure the wrong
    # implementation)
    "pallas_ring_fallbacks": lambda: counters.pallas_fallbacks,
    # wire-plane byte accounting (codec.py): array payload bytes that
    # shipped as raw frames vs bytes that went through the pickler, plus
    # host-side payload copies (self-send value copies, non-contiguous
    # compactions).  These are the counters that PROVE a hot path stayed
    # zero-copy — e.g. the segmented allreduce asserts 0 pickled array
    # bytes at bandwidth sizes (ISSUE 1 acceptance).
    "bytes_raw_sent": lambda: counters.bytes_raw,
    "bytes_pickled_sent": lambda: counters.bytes_pickled,
    "payload_copies": lambda: counters.copies,
    # ULFM fault-tolerance events (mpi_tpu/ft.py): distinct world ranks
    # this process declared dead (detector hit or transport evidence);
    # revocations applied to a communicator (local revoke() + delivered
    # remote notifications); shrinks that completed agreement and built
    # a survivor communicator.
    "proc_failures_detected": lambda: counters.proc_failed,
    "revokes_delivered": lambda: counters.revokes,
    "shrinks_completed": lambda: counters.shrinks,
    # fault-injection tallies (transport/faulty.py): messages the chaos
    # wrapper dropped / delivered twice — lets a chaos sweep assert the
    # injection actually fired without a handle on every wrapper.
    "faulty_dropped": lambda: counters.faulty_dropped,
    "faulty_duplicated": lambda: counters.faulty_duplicated,
    # ring-attention forwards that ran the ppermute fallback because no
    # tile fit the VMEM budget (tpu/pallas_attention.py — graceful
    # degradation instead of NotImplementedError; ROADMAP r5 #4)
    "attention_fallbacks": lambda: counters.attention_oob,
    # shared-memory collective arena (mpi_tpu/coll_sm.py): collectives
    # served entirely by arena load/store (zero ring frames), per-rank
    # payload bytes moved through it, and eligible requests that fell
    # back to the wire algorithms (non-array payload, payload larger
    # than a slot, nbc clone, mismatched reduction geometry)
    "coll_sm_hits": lambda: counters.sm_hits,
    "coll_sm_bytes": lambda: counters.sm_bytes,
    "coll_sm_fallbacks": lambda: counters.sm_fallbacks,
    # runtime correctness verifier (mpi_tpu/verify): deadlocks proven
    # (DeadlockError raised instead of a hang), collective-signature
    # mismatches (CollectiveMismatchError), and the finalize-report
    # lints — requests leaked (GC'd/finalized unwaited), second wait()
    # on a completed request, overlapping live nonblocking buffers (the
    # message-race case), and split/dup comms never freed.
    "verify_deadlocks_detected": lambda: counters.v_deadlocks,
    "verify_collective_mismatches": lambda: counters.v_mismatches,
    "verify_requests_leaked": lambda: counters.v_leaked,
    "verify_double_waits": lambda: counters.v_double_waits,
    "verify_buffer_overlaps": lambda: counters.v_buf_overlaps,
    "verify_comms_unfreed": lambda: counters.v_comms_unfreed,
    # wildcard-race detector (mpi_tpu/verify/vclock.py): ANY_SOURCE
    # receives whose consumed message was CONCURRENT (no happens-before
    # edge, per the piggybacked vector clocks) with another eligible
    # pending sender — the nondeterministic match MPL009 flags
    # statically, observed at runtime; and the clock bytes piggybacked
    # on frames to prove it.  Both exactly 0 outside verify mode (the
    # off-mode zero-cost contract).
    "verify_wildcard_races": lambda: counters.v_wildcard_races,
    "verify_clock_bytes": lambda: counters.v_clock_bytes,
    # async progress engine (mpi_tpu/progress.py): engine-thread wakeups
    # (the added cost the ``progress`` cvar prices), nonblocking
    # requests completed in the BACKGROUND (by the engine rather than a
    # caller's wait/test), and parks that expired with no traffic (the
    # engine's idle duty cycle).  All exactly 0 with progress=none —
    # the off-mode zero-cost contract (bench.py --verify-overhead
    # --progress and tests/test_progress.py assert it).
    "progress_wakeups": lambda: counters.prog_wakeups,
    "progress_completions": lambda: counters.prog_completions,
    "progress_idle_parks": lambda: counters.prog_idle_parks,
    # elastic membership (mpi_tpu/membership.py): rejoin handshakes this
    # process completed (either side), and stale-epoch handshakes it
    # rejected/diagnosed (EpochSkewError — the false-suspicion group
    # split surfacing as an error instead of a cross-wired hang)
    "rejoins_completed": lambda: counters.rejoins,
    "epoch_skews_detected": lambda: counters.epoch_skews,
    # compressed collectives (mpi_tpu/compress.py): logical fold-dtype
    # bytes minus actual wire bytes, accumulated at encode time (bf16
    # halves, scaled-int quarters; a top-k ratio that overshoots dense
    # counts NEGATIVE — honest accounting), and eligible
    # algorithm="compressed" requests that declined to the classic path
    # (non-float dtype, unsupported op).  bytes_raw_sent keeps counting
    # the actual wire bytes, so the halving claim is assertable.
    "bytes_compressed_saved": lambda: counters.comp_saved,
    "compress_fallbacks": lambda: counters.comp_fallbacks,
    # tuned dispatch (mpi_tpu/tuning): algorithm="auto" decisions served
    # by a matching tuning-table row vs decisions that fell back to the
    # built-in seed constants (no table / no matching row / row not
    # applicable to this group).  With no table configured every auto
    # decision is a fallback and dispatch is byte-identical to the
    # constants (asserted in tests/test_tuning.py).
    "tuned_table_hits": lambda: counters.tuned_hits,
    "tuned_table_fallbacks": lambda: counters.tuned_fallbacks,
    # resilient socket links (mpi_tpu/resilience.py + transport/
    # socket.py): connections re-established after an ESTABLISHED one
    # was lost (link faults healed by reconnect, not initial setup),
    # retained frames replayed through a resume handshake, send-path
    # OSErrors classified as link faults and masked end-to-end (the
    # caller's send completed despite the fault), and the bytes copied
    # into the retained replay window (the honest price of
    # replay-after-reset — the user-space analogue of the kernel
    # socket buffer a reset discards).
    "link_reconnects": lambda: counters.link_reconnects,
    "link_frames_replayed": lambda: counters.link_replayed,
    "link_faults_masked": lambda: counters.link_masked,
    "link_bytes_retained": lambda: counters.link_retained,
    # refcounted buffer ownership (mpi_tpu/bufpool.py, ISSUE 11):
    # retained frames are now by-REFERENCE views of the caller's
    # buffers, so link_bytes_retained prices retention (pinned memory,
    # replay bound) without a copy; these two price exactly the
    # copy-on-write snapshots that buffer REUSE forced (fold into /
    # conflicting send over / posted write buffer on a still-unacked
    # region).  Zero on the no-reuse path — the decoupling the ISSUE 11
    # acceptance demands.  link_send_syscalls counts data-plane socket
    # write calls (one vectored sendmsg per frame on the batched path,
    # vs one write per header/meta/segment before it).
    "link_cow_snapshots": lambda: counters.link_cow_snaps,
    "link_cow_bytes": lambda: counters.link_cow_bytes,
    "link_send_syscalls": lambda: counters.link_syscalls,
    # receive twin of link_send_syscalls (ISSUE 19): data-plane socket
    # READ calls on the raw-body path — one vectored recvmsg_into per
    # multi-segment frame (scatter-gather receive) vs one recv_into per
    # segment before it.  Headers/meta keep their own exact reads and
    # are not counted.
    "link_recv_syscalls": lambda: counters.link_rsyscalls,
    # torn frames (ISSUE 17 small fix): reader-side disconnects that
    # landed MID-FRAME (partial header/meta/body bytes then EOF or
    # error) — a reset the replay protocol must heal, distinguished
    # from a clean between-frames close (graceful shutdown /
    # membership departure), which is not counted.
    "link_torn_frames": lambda: counters.link_torn,
    # engine-owned nonblocking collectives (mpi_tpu/nbc.py, ISSUE 12):
    # per-call _ThreadRequest threads actually SPAWNED (the cost the
    # state machines remove — exactly 0 when every i-collective rode
    # the engine), schedule state machines launched in their place, and
    # persistent-collective start() re-fires (the hot-loop path that
    # skips per-call compile/resolve/verify work).
    "nbc_threads_spawned": lambda: counters.nbc_threads,
    "nbc_state_machines": lambda: counters.nbc_sms,
    "persistent_starts": lambda: counters.persist_starts,
    # flight recorder (mpi_tpu/telemetry, ISSUE 13): events recorded
    # into the per-rank ring buffer.  Exactly 0 with tracing off — the
    # off-mode zero-cost contract (every instrumented seam is one
    # `telemetry.REC is None` attribute test; bench.py --verify-overhead
    # --trace asserts it alongside the unchanged wire accounting).
    "trace_events": lambda: counters.trace_events,
    # receive-side zero-copy (mpi_tpu/recvpool.py, ISSUE 17): pool
    # requests served by a recycled size-class buffer vs fresh
    # allocations (the page-fault pass the pool removes), frames the
    # reader STEERED directly into a posted irecv's destination buffer
    # (the rendezvous path — the intermediate receive copy removed
    # entirely), and the body bytes that moved that way.  The socket
    # 16MB allreduce asserts payload_copies drops by exactly the
    # steered stores (tests/test_recvpool.py).
    "recv_pool_hits": lambda: counters.rp_hits,
    "recv_pool_misses": lambda: counters.rp_misses,
    "recv_pool_rendezvous": lambda: counters.rp_rdv,
    "recv_bytes_steered": lambda: counters.rp_steered,
    # rendezvous steering races LOST (ISSUE 18 satellite, the ISSUE 17
    # residual (c)): frames whose exact-match channel had no posted
    # entry yet (reader beat the poster) or whose posted destination
    # was steering-ineligible, so the body folded through the pool
    # instead of a direct store.  A visibility counter only — the
    # deterministic payload_copies assertions are NOT derived from it.
    "recv_pool_fold_fallbacks": lambda: counters.rp_fold,
    # user-buffer rendezvous (ISSUE 19): irecv(buf=)/recv_init(buf=)
    # completions whose payload WAS the registered buffer (bytes landed
    # in place, the final store skipped) vs armed completions that had
    # to copy (the match raced the reader, a heal replay re-presented
    # the frame, or a wildcard/probe stole the steered frame — the
    # named fallback the tentpole demands).  Unarmed buf= completions
    # count neither.
    "recv_user_inplace": lambda: counters.rp_user_in,
    "recv_user_fallbacks": lambda: counters.rp_user_fb,
    # replicated namespace store (mpi_tpu/federation_store.py, ISSUE
    # 18): store-leader elections STARTED by a node in this process,
    # uncommitted log entries truncated away by a new leader's
    # conflict check (the minority's stale intents being discarded at
    # heal), and node-to-node frames dropped by an installed partition
    # map (proof the injection actually fired).  All exactly 0 in
    # file-store / non-federated runs.
    "store_elections": lambda: counters.store_elections,
    "store_entries_truncated": lambda: counters.store_truncated,
    "store_partition_dropped": lambda: counters.store_dropped,
    # gauges, not counters: current max term / commit index over this
    # process's live store nodes (0 with none).  Lazy sys.modules
    # lookup — reading a pvar must not import the federation tier.
    "store_term": lambda: _store_gauge("term"),
    "store_commit_index": lambda: _store_gauge("commit_index"),
}


def _store_gauge(field: str) -> int:
    mod = sys.modules.get("mpi_tpu.federation_store")
    return 0 if mod is None else int(mod.store_gauge(field))


def pvar_list() -> List[str]:
    """MPI_T_pvar_get_info over all indices: the variable names."""
    return sorted(_PVARS)


def pvar_read(name: str) -> int:
    """Absolute (process-lifetime) value of a performance variable."""
    try:
        return int(_PVARS[name]())
    except KeyError:
        raise KeyError(f"unknown pvar {name!r}; have {pvar_list()}") from None


def pvar_reset(name: str) -> int:
    """MPI_T semantics put reset in the session; module-level reset just
    returns the current value to subtract (see Session)."""
    return pvar_read(name)


# -- histogram pvars (ISSUE 13: distributions beside the counters) -----------
#
# Log-bucketed (base-2) histograms for the latencies a mean would lie
# about: bucket k holds values in [2^(k-1), 2^k) nanoseconds, so 64
# buckets span sub-ns to ~292 years with zero configuration and O(1)
# record cost (one bit_length + one increment under the module lock).
# Quantiles are estimated from the bucket boundaries (geometric
# midpoint 2^(k-0.5), clamped to the observed min/max) — the standard
# HDR-style tradeoff: <= ~41% relative error per estimate, which is
# exactly enough to tell a 1.5ms lease p99 from a 6s one.
#
# Recording sites: every traced collective (coll_latency_s — gated on
# the flight recorder, it is the HOT path), every serve lease grant
# (lease_acquire_s — always on, the grant is a control round-trip) and
# every socket link heal (link_heal_s — always on, healing is already
# a multi-ms reconnect).  hist_record() accepts any name, so new
# distributions need no registry edit.

_HIST_BUCKETS = 64


class _Hist:
    __slots__ = ("counts", "n", "total_ns", "min_ns", "max_ns")

    def __init__(self) -> None:
        self.counts = [0] * _HIST_BUCKETS
        self.n = 0
        self.total_ns = 0
        self.min_ns = None  # type: Optional[int]
        self.max_ns = 0

    def add(self, ns: int) -> None:
        self.counts[min(_HIST_BUCKETS - 1, ns.bit_length())] += 1
        self.n += 1
        self.total_ns += ns
        if self.min_ns is None or ns < self.min_ns:
            self.min_ns = ns
        if ns > self.max_ns:
            self.max_ns = ns


# pre-seeded so pvar_hist_list() is stable before any event fires (the
# three distributions the README documents); hist_record creates others
# on demand.
_HISTS: Dict[str, _Hist] = {
    "coll_latency_s": _Hist(),
    "lease_acquire_s": _Hist(),
    "link_heal_s": _Hist(),
}


def hist_record(name: str, seconds: float) -> None:
    """Record one sample (seconds; negative clamps to 0) into the named
    log-bucketed histogram, creating it on first use."""
    ns = max(0, int(seconds * 1e9))
    with _lock:
        h = _HISTS.get(name)
        if h is None:
            h = _HISTS[name] = _Hist()
        h.add(ns)


def pvar_hist_list() -> List[str]:
    with _lock:
        return sorted(_HISTS)


def pvar_hist_read(name: str) -> Dict[str, Any]:
    """Snapshot of a histogram pvar: count/sum/min/max plus the
    non-empty buckets as ``{upper_bound_seconds: count}``."""
    with _lock:
        h = _HISTS.get(name)
        if h is None:
            raise KeyError(f"unknown histogram pvar {name!r}; have "
                           f"{sorted(_HISTS)}")
        return {
            "count": h.n,
            "sum_s": h.total_ns / 1e9,
            "min_s": (h.min_ns or 0) / 1e9,
            "max_s": h.max_ns / 1e9,
            "buckets": {(1 << k) / 1e9: c
                        for k, c in enumerate(h.counts) if c},
        }


def pvar_hist_reset(name: str) -> None:
    with _lock:
        if name in _HISTS:
            _HISTS[name] = _Hist()


def hist_cumulative(name: str) -> List[Tuple[float, int]]:
    """Cumulative (upper_bound_seconds, count<=bound) pairs over the
    non-empty prefix — the Prometheus ``le`` bucket series."""
    with _lock:
        h = _HISTS.get(name)
        if h is None:
            raise KeyError(f"unknown histogram pvar {name!r}")
        out: List[Tuple[float, int]] = []
        cum = 0
        top = max((k for k, c in enumerate(h.counts) if c), default=-1)
        for k in range(top + 1):
            cum += h.counts[k]
            out.append(((1 << k) / 1e9, cum))
        return out


def hist_quantile(name: str, q: float) -> Optional[float]:
    """Estimated q-quantile (seconds) from the bucket boundaries, or
    None for an empty histogram."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    with _lock:
        h = _HISTS.get(name)
        if h is None:
            raise KeyError(f"unknown histogram pvar {name!r}")
        if h.n == 0:
            return None
        target = q * h.n
        cum = 0
        for k, c in enumerate(h.counts):
            cum += c
            if cum >= target and c:
                # geometric midpoint of [2^(k-1), 2^k), clamped to the
                # observed extremes so a single-sample histogram reads
                # back its own value
                est = 2.0 ** (k - 0.5)
                return min(max(est, float(h.min_ns or 0)),
                           float(h.max_ns)) / 1e9
        return h.max_ns / 1e9  # pragma: no cover - cum==n on last bucket


# -- control variables -------------------------------------------------------

_CVARS: Dict[str, Tuple[Callable[[], Any], Optional[Callable[[Any], None]],
                        str]] = {}


def cvar_register(name: str, reader: Callable[[], Any],
                  writer: Optional[Callable[[Any], None]],
                  desc: str) -> None:
    with _lock:
        _CVARS[name] = (reader, writer, desc)


def cvar_list() -> Dict[str, str]:
    """name -> description (MPI_T_cvar_get_info)."""
    _ensure_builtin_cvars()
    return {k: v[2] for k, v in sorted(_CVARS.items())}


def cvar_read(name: str) -> Any:
    _ensure_builtin_cvars()
    try:
        return _CVARS[name][0]()
    except KeyError:
        raise KeyError(f"unknown cvar {name!r}; have "
                       f"{sorted(_CVARS)}") from None


def cvar_write(name: str, value: Any) -> None:
    _ensure_builtin_cvars()
    try:
        reader, writer, _ = _CVARS[name]
    except KeyError:
        raise KeyError(f"unknown cvar {name!r}; have "
                       f"{sorted(_CVARS)}") from None
    if writer is None:
        raise PermissionError(f"cvar {name!r} is read-only")
    writer(value)


_builtin_done = False

# replicated-gather footprint warning threshold (bytes of the full
# [size, ...] stack PER DEVICE).  Held here (not in tpu/communicator)
# so reading/writing the cvar never needs a jax import; list-wrapped so
# the closures below share one mutable cell.
_GATHER_WARN_BYTES = [64 * 2 ** 20]


def _ensure_builtin_cvars() -> None:
    """The knobs that actually steer this library — registered LAZILY so
    importing mpit from the transports cannot cycle back through io/
    communicator at module-import time."""
    global _builtin_done
    if _builtin_done:
        return
    # imports OUTSIDE the lock (they can run user-level module code);
    # registration + flag UNDER it, flag LAST — a concurrent reader must
    # never observe done=True with the registry still empty
    from . import coll_sm as _sm
    from . import communicator as _c
    from . import compress as _compress
    from . import ft as _ft
    from . import io as _io
    from . import membership as _membership
    from . import nbc as _nbc
    from . import progress as _prog
    from . import recvpool as _recvpool
    from . import resilience as _resilience
    from . import tuning as _tuning
    from .transport import shm as _shm
    from .transport import socket as _socket
    from .verify import state as _vstate

    def _set_sm_arena(v):
        if int(v) < 0:
            raise ValueError("coll_sm_arena_bytes must be >= 0 (0 = off)")
        _sm._ARENA_BYTES = int(v)

    def _set_sm_eager(v):
        if int(v) < 0:
            raise ValueError("coll_sm_eager_bytes must be >= 0")
        _sm._EAGER_BYTES = int(v)

    def _get_limit():
        return _io._COLLECTIVE_BUFFER_LIMIT

    def _set_limit(v):
        _io._COLLECTIVE_BUFFER_LIMIT = int(v)

    def _get_cross():
        return _c._RING_CROSSOVER_BYTES

    def _set_cross(v):
        _c._RING_CROSSOVER_BYTES = int(v)

    def _get_raben():
        return _c._RABENSEIFNER_CROSSOVER_BYTES

    def _set_raben(v):
        _c._RABENSEIFNER_CROSSOVER_BYTES = int(v)

    def _get_seg():
        return _c._SEGMENT_BYTES

    def _set_seg(v):
        if int(v) < 0:
            raise ValueError(
                "collective_segment_bytes must be >= 0 (0 = per-transport)")
        _c._SEGMENT_BYTES = int(v)

    def _get_recv_timeout():
        return _c._RECV_TIMEOUT_DEFAULT or 0.0

    def _set_recv_timeout(v):
        if float(v) < 0:
            raise ValueError("recv_timeout_s must be >= 0 (0 = no timeout)")
        _c._RECV_TIMEOUT_DEFAULT = float(v) or None

    def _get_shm_wt():
        return _shm._WRITE_TIMEOUT

    def _set_shm_wt(v):
        if float(v) <= 0:
            raise ValueError("shm_write_timeout_s must be > 0")
        _shm._WRITE_TIMEOUT = float(v)

    def _set_detect(v):
        if float(v) <= 0:
            raise ValueError("fault_detect_timeout_s must be > 0")
        _ft._DETECT_TIMEOUT_S = float(v)

    def _set_heartbeat(v):
        if float(v) <= 0:
            raise ValueError("fault_heartbeat_interval_s must be > 0")
        _ft._HEARTBEAT_S = float(v)

    def _set_verify_stall(v):
        if float(v) <= 0:
            raise ValueError("verify_stall_timeout_s must be > 0")
        _vstate._STALL_TIMEOUT_S = float(v)

    def _set_progress(v):
        if v not in _prog.MODES:
            raise ValueError(
                f"progress must be one of {list(_prog.MODES)}, got {v!r}")
        _prog._DEFAULT_MODE = v

    def _set_nbc_mode(v):
        if v not in _nbc.MODES:
            raise ValueError(
                f"nbc_mode must be one of {list(_nbc.MODES)}, got {v!r}")
        _nbc._MODE = v

    def _set_nbc_fold_workers(v):
        if int(v) < 1:
            raise ValueError("nbc_fold_workers must be >= 1")
        _nbc._FOLD_WORKERS = int(v)

    def _set_nbc_sm_max(v):
        if int(v) < 0:
            raise ValueError("nbc_sm_max_bytes must be >= 0 (0 = no cap)")
        _nbc._SM_MAX_BYTES = int(v)

    with _lock:
        if _builtin_done:
            return
        _CVARS["io_collective_buffer_limit_bytes"] = (
            _get_limit, _set_limit,
            "write_at_all aggregates at rank 0 below this total (two-"
            "phase collective buffering); above it ranks write "
            "independently")
        _CVARS["allreduce_ring_crossover_bytes"] = (
            _get_cross, _set_cross,
            "CPU-backend allreduce auto algorithm picks latency-optimal "
            "recursive halving below this payload size (pow2 groups), "
            "bandwidth-optimal ring at or above it")
        _CVARS["allreduce_rabenseifner_crossover_bytes"] = (
            _get_raben, _set_raben,
            "CPU-backend allreduce auto algorithm hands payloads at or "
            "above this size to the Rabenseifner composition (block-ring "
            "reduce_scatter + ring allgather, any group size) instead of "
            "the classic ring; derived from the measured host sweep "
            "(benchmarks/results/host_sweep2_post.json)")
        _CVARS["collective_segment_bytes"] = (
            _get_seg, _set_seg,
            "pipeline segment size of the host collective engine: element "
            "ranges above this many bytes ship as multiple raw frames so "
            "the receiver's fold of segment k overlaps the transport "
            "streaming segment k+1.  0 (default) defers to each "
            "transport's coll_segment_hint (shm: stay inside the ring; "
            "socket: amortize per-frame host work); nonzero overrides "
            "every transport (keep window*segment below the shm ring "
            "capacity; see communicator._SEG_WINDOW) and also lowers "
            "reduce_scatter's segmented-path gate to any payload "
            "spanning more than one segment (default gate: "
            "communicator._RS_SEGMENT_MIN_BYTES)")
        _CVARS["recv_timeout_s"] = (
            _get_recv_timeout, _set_recv_timeout,
            "default recv_timeout of newly created communicators: a "
            "blocking receive with no matching message raises RecvTimeout "
            "after this many seconds instead of hanging (0 = wait "
            "forever).  Per-communicator .recv_timeout still overrides")
        _CVARS["shm_write_timeout_s"] = (
            _get_shm_wt, _set_shm_wt,
            "shm transport no-progress stall bound: a ring write (full "
            "ring, nobody draining) or mid-frame read with no progress "
            "for this long declares the peer dead (TransportError).  The "
            "data plane's last-resort constant — the ft.py detector "
            "(fault_detect_timeout_s) should fire far earlier")
        _CVARS["fault_detect_timeout_s"] = (
            lambda: _ft._DETECT_TIMEOUT_S, _set_detect,
            "ULFM failure-detection bound (mpi_tpu/ft.py): a peer whose "
            "heartbeat is stale this long is declared dead and every "
            "fault-tolerant blocking wait on it raises ProcFailedError "
            "(MPI_ERR_PROC_FAILED).  Read at ft.enable() time")
        _CVARS["fault_heartbeat_interval_s"] = (
            lambda: _ft._HEARTBEAT_S, _set_heartbeat,
            "how often each fault-tolerant rank publishes its heartbeat "
            "and scans its peers' (mpi_tpu/ft.py); keep well below "
            "fault_detect_timeout_s.  Read at ft.enable() time")
        _CVARS["verify_stall_timeout_s"] = (
            lambda: _vstate._STALL_TIMEOUT_S, _set_verify_stall,
            "runtime-verifier stall bound (mpi_tpu/verify): a verified "
            "blocking wait (or nonblocking polling loop) stuck this long "
            "publishes its pending op out-of-band and runs the wait-for "
            "deadlock analysis — a proven cross-rank cycle/knot raises "
            "DeadlockError naming every rank, its pending op, and its "
            "call site.  Read at verify.enable() time")
        _CVARS["progress"] = (
            lambda: _prog._DEFAULT_MODE, _set_progress,
            "default async-progress mode of newly created worlds "
            "(mpi_tpu/progress.py): 'thread' starts one dedicated "
            "progress engine per world — background completion for "
            "nonblocking ops, doorbell-parked transport draining, "
            "pure-polling drain loops join deadlock detection; 'none' "
            "(default) keeps progress caller-financed with a single "
            "attribute test per operation.  Explicit run_local("
            "progress=...) and the MPI_TPU_PROGRESS environment "
            "variable override; read at world creation")
        _CVARS["nbc_mode"] = (
            lambda: _nbc._MODE, _set_nbc_mode,
            "nonblocking-collective dispatch mode (mpi_tpu/nbc.py): "
            "'auto' compiles i-collectives into schedule state machines "
            "advanced by the async progress engine whenever the world "
            "runs one (zero per-call threads — nbc_threads_spawned "
            "stays 0, nbc_state_machines counts); 'thread' forces "
            "today's one-_ThreadRequest-per-call semantics everywhere "
            "(the escape hatch, and the honest pre/post bench toggle).  "
            "Worlds without the engine always take the thread path.  "
            "MPI_TPU_NBC seeds the default")
        _CVARS["nbc_fold_workers"] = (
            lambda: _nbc._FOLD_WORKERS, _set_nbc_fold_workers,
            "width of the per-world fold pool that advances collective "
            "state machines (mpi_tpu/nbc.py): receive completions "
            "enqueue the machine, a pool worker applies its folds/"
            "copies and posts the sends they unlock — so reductions "
            "never run on the engine thread.  2 (default) keeps one "
            "worker free while another blocks in a ring-full forward.  "
            "Read at a world's first state machine; "
            "MPI_TPU_NBC_FOLD_WORKERS seeds the default")
        _CVARS["nbc_sm_max_bytes"] = (
            lambda: _nbc._SM_MAX_BYTES, _set_nbc_sm_max,
            "payload ceiling of the state-machine i-collective path "
            "(mpi_tpu/nbc.py): reductions whose working buffer — or "
            "ialltoall calls whose largest block — exceeds this many "
            "bytes keep the threaded blocking algorithms, whose "
            "SEGMENTED pipelines own the bandwidth regime, while "
            "latency-bound calls below it ride the engine with zero "
            "per-call threads.  0 removes the cap.  Must agree across "
            "the group for the reductions (geometry-congruent plans); "
            "the alltoall gate is rank-local by design (both paths "
            "emit the identical pairwise frame sequence).  "
            "MPI_TPU_NBC_SM_MAX_BYTES seeds the default")
        _CVARS["coll_sm_arena_bytes"] = (
            lambda: _sm._ARENA_BYTES, _set_sm_arena,
            "size of the per-communicator shared-memory collective arena "
            "(mpi_tpu/coll_sm.py): P flag lines + P data slots; a rank's "
            "slot is the P-th share, the ceiling of the in-place block "
            "paths.  0 disables the arena (every sm/auto request falls "
            "back to the wire algorithms).  Read at arena creation — set "
            "it before the communicator's first sm collective")
        _CVARS["coll_sm_eager_bytes"] = (
            lambda: _sm._EAGER_BYTES, _set_sm_eager,
            "flat-path gate of the arena reductions: payloads at or "
            "below this are folded whole from every peer's slot "
            "(latency-optimal); above it allreduce switches to the "
            "chunked in-place fold and reduce stays on the binomial "
            "tree")
        def _set_wire_dtype(v):
            if v not in _compress.FORMATS:
                raise ValueError(
                    f"compress_wire_dtype must be one of "
                    f"{sorted(_compress.FORMATS)}, got {v!r}")
            _compress._WIRE_DTYPE = v

        def _set_topk_ratio(v):
            if float(v) <= 0:
                raise ValueError("compress_topk_ratio must be > 0")
            _compress._TOPK_RATIO = float(v)

        _CVARS["compress_wire_dtype"] = (
            lambda: _compress._WIRE_DTYPE, _set_wire_dtype,
            "wire encoding the plain algorithm='compressed' spelling "
            "resolves to (mpi_tpu/compress.py): 'bf16' (2 bytes/elem, "
            "RNE) or 'int8' (fp8-style per-segment max-abs scale + int8 "
            "mantissas, 1 byte/elem).  Folds stay f32 (f64 payloads "
            "f64).  Must agree across the group — the runtime "
            "verifier's collective signature carries the RESOLVED wire "
            "dtype, so skew raises CollectiveMismatchError before data "
            "moves.  Explicit 'compressed:bf16'/'compressed:int8' "
            "override per call")
        _CVARS["compress_topk_ratio"] = (
            lambda: _compress._TOPK_RATIO, _set_topk_ratio,
            "fraction of gradient entries algorithm='compressed:topk' "
            "transmits per rank (ceil(ratio*n), >= 1, clamped to n — "
            "ratios >= 1 degrade to dense).  The unsent remainder "
            "accumulates in the per-(shape,dtype,op) error-feedback "
            "residual on the communicator "
            "(mpi_tpu.compress.reset_residuals clears).  Must agree "
            "across the group: the resolved k rides the verifier "
            "signature's counts field")

        def _set_link_retry(v):
            if float(v) < 0:
                raise ValueError(
                    "link_retry_timeout_s must be >= 0 (0 = healing off)")
            _resilience._RETRY_TIMEOUT_S = float(v)

        def _set_link_window(v):
            if int(v) <= 0:
                raise ValueError("link_window_bytes must be > 0")
            _resilience._WINDOW_BYTES = int(v)

        def _set_connect_retry(v):
            if float(v) < 0:
                raise ValueError(
                    "connect_retry_timeout_s must be >= 0 "
                    "(0 = first-failure raise)")
            _resilience._CONNECT_RETRY_TIMEOUT_S = float(v)

        def _set_retain_copy(v):
            _resilience._RETAIN_COPY = int(bool(int(v)))

        def _set_recv_steering(v):
            _recvpool._STEERING = int(bool(int(v)))

        def _set_keepalive(v):
            if float(v) < 0:
                raise ValueError(
                    "link_keepalive_s must be >= 0 (0 = no probing)")
            _resilience._KEEPALIVE_S = float(v)

        def _set_epoch_grace(v):
            if float(v) < 0:
                raise ValueError("epoch_grace_s must be >= 0")
            # one knob, both byte-stream transports: the grace window
            # exists wherever an epoch stamp is compared (socket hello
            # acks, shm readiness files)
            _socket._EPOCH_GRACE_S = float(v)
            _shm._EPOCH_GRACE_S = float(v)

        _CVARS["link_retry_timeout_s"] = (
            lambda: _resilience._RETRY_TIMEOUT_S, _set_link_retry,
            "socket link-healing budget (mpi_tpu/resilience.py): a "
            "send-path OSError whose destination is NOT failure-"
            "suspected enters a reconnect loop (exponential backoff + "
            "jitter, resume handshake, retained-frame replay) bounded "
            "by this many seconds; also the no-ack-progress bound of a "
            "full retained window.  Keep it BELOW "
            "fault_detect_timeout_s so a dead peer resolves to "
            "ProcFailedError, never a masked hang.  0 disables healing "
            "(every link fault is terminal, frames stream unretained — "
            "the pre-resilience behavior; set it BEFORE the world's "
            "first send: frames sent while healing was off were never "
            "retained and cannot be replayed by a later enable).  "
            "MPI_TPU_LINK_RETRY_S seeds the default")
        _CVARS["link_window_bytes"] = (
            lambda: _resilience._WINDOW_BYTES, _set_link_window,
            "per-destination retained-frame window of the resilient "
            "socket link: sends block once this many unacked bytes "
            "are outstanding (one oversized frame may proceed alone); "
            "the window is what a reconnect replays, so it bounds both "
            "memory and replay time.  MPI_TPU_LINK_WINDOW_BYTES seeds "
            "the default")
        _CVARS["link_retain_copy"] = (
            lambda: _resilience._RETAIN_COPY, _set_retain_copy,
            "retained-window ownership mode (mpi_tpu/bufpool.py): 0 "
            "(default) retains frame bodies BY REFERENCE with "
            "copy-on-write on proven reuse — zero copies on the "
            "no-reuse hot path, but a buffer mutated outside any "
            "mpi_tpu operation while its frames are unacked needs "
            "bufpool.note_write() first (the borrow contract); 1 "
            "restores the eager per-frame snapshot (strict MPI "
            "buffered-send reusability, one memcpy per frame).  "
            "MPI_TPU_LINK_RETAIN_COPY seeds the default")
        _CVARS["recv_steering"] = (
            lambda: _recvpool._STEERING, _set_recv_steering,
            "receive-side rendezvous steering of the socket transport "
            "(mpi_tpu/recvpool.py): 1 (default) lets the reader thread "
            "recv() a matching frame's body DIRECTLY into the posted "
            "irecv's destination buffer — zero intermediate copy, "
            "priced by recv_pool_rendezvous / recv_bytes_steered; 0 "
            "forces every frame through the pool-fallback path (the "
            "honest pre/post bench toggle).  Channel accounting stays "
            "on either way, so toggling mid-run cannot desync the "
            "frame/consumer pairing.  MPI_TPU_RECV_STEERING seeds the "
            "default")
        _CVARS["link_keepalive_s"] = (
            lambda: _resilience._KEEPALIVE_S, _set_keepalive,
            "idle-link keepalive cadence of the resilient socket "
            "transport: connections that sent nothing for this long "
            "are probed with a header-only ack frame by the ack "
            "flusher, so a link torn while IDLE heals proactively "
            "instead of spiking the next send's latency.  0 disables "
            "probing; ignored entirely when link healing is off "
            "(link_retry_timeout_s = 0).  MPI_TPU_LINK_KEEPALIVE_S "
            "seeds the default")
        _CVARS["connect_retry_timeout_s"] = (
            lambda: _resilience._CONNECT_RETRY_TIMEOUT_S,
            _set_connect_retry,
            "initial server-connect retry budget of mpi_tpu.connect() "
            "/ serve.ServerClient: ConnectionRefusedError (the server "
            "is still binding) is retried with backoff + jitter for "
            "this long instead of raising on first failure.  0 "
            "restores first-failure raise.  MPI_TPU_CONNECT_RETRY_S "
            "seeds the default")
        _CVARS["epoch_grace_s"] = (
            lambda: _socket._EPOCH_GRACE_S, _set_epoch_grace,
            "grace window before an ahead-of-us membership epoch is "
            "declared EpochSkewError (socket hello acks AND shm "
            "readiness stamps): a healthy member applying a broadcast "
            "epoch transition milliseconds late keeps retrying with "
            "its own epoch re-read until the grace expires; a "
            "genuinely ousted straggler never catches up and still "
            "raises.  MPI_TPU_EPOCH_GRACE_S seeds the default")

        def _set_rejoin_timeout(v):
            if float(v) <= 0:
                raise ValueError("rejoin_timeout_s must be > 0")
            _membership._REJOIN_TIMEOUT_S = float(v)

        _CVARS["rejoin_timeout_s"] = (
            lambda: _membership._REJOIN_TIMEOUT_S, _set_rejoin_timeout,
            "default bound on an elastic-membership rejoin handshake "
            "(mpi_tpu/membership.py): claim -> admit -> epoch-stamped "
            "endpoints -> ready -> barrier, on BOTH the joiner "
            "(rejoin()) and survivor (accept_rejoin()) sides; explicit "
            "timeout= arguments override per call")
        _CVARS["tuning_table_path"] = (
            _tuning.table_path,
            lambda v: _tuning.set_table_path(str(v) if v else None),
            "path of the active per-machine tuning table (mpi_tpu/"
            "tuning): measured (transport, nranks, collective, payload-"
            "band) -> algorithm rows that algorithm='auto' consults "
            "before the built-in seed constants (tuned_table_hits / "
            "tuned_table_fallbacks pvars).  Empty = no table (seed "
            "constants only).  Writing loads + validates immediately "
            "(malformed tables raise TuningTableError); a table whose "
            "machine fingerprint does not match this host loads but "
            "never serves.  Must agree across the group, like every "
            "algorithm-steering cvar.  MPI_TPU_TUNING_TABLE / run_local("
            "tuning_table=) / launcher --tuning-table set it per world")
        _CVARS["gather_replicated_warn_bytes"] = (
            lambda: _GATHER_WARN_BYTES[0],
            lambda v: _GATHER_WARN_BYTES.__setitem__(0, int(v)),
            "SPMD gather/gatherv warn when the replicated [size, ...] "
            "stack exceeds this many bytes PER DEVICE (O(size x payload) "
            "HBM); use gather(..., sharded=True) to keep per-device HBM "
            "O(payload)")
        _builtin_done = True


# -- sessions ----------------------------------------------------------------

class Session:
    """MPI_T session: pvar reads are relative to the session's resets."""

    def __init__(self) -> None:
        self._base: Dict[str, int] = {}

    def read(self, name: str) -> int:
        return pvar_read(name) - self._base.get(name, 0)

    def reset(self, name: str) -> None:
        self._base[name] = pvar_read(name)

    def reset_all(self) -> None:
        for name in pvar_list():
            self.reset(name)


def session_create() -> Session:
    """MPI_T_pvar_session_create."""
    return Session()
