"""One-sided communication (MPI RMA): Window, Put/Get/Accumulate, Fence.

Capability contract [S]: MPI-2 active-target RMA — ``MPI_Win_create`` exposes
a local buffer; inside a fence epoch ranks issue ``MPI_Put`` / ``MPI_Get`` /
``MPI_Accumulate`` at remote windows; all operations complete at the closing
``MPI_Win_fence``.  (The reference checkout at /root/reference is empty this
session — SURVEY.md §0 — so the MPI standard is the behavioral contract; the
reference itself shows no RMA, making this a widening beyond parity.)

Portable API (identical on the process backends and the SPMD/TPU backend):

* operations take a static (src, dst) *pattern* — the same ``pairs`` list on
  every rank, exactly like ``Communicator.exchange``.  That is the subset of
  RMA expressible as one SPMD program (a ppermute per call); the process
  backends additionally accept a plain ``int`` destination for classic
  rank-dynamic MPI code (the TPU backend diagnoses that with
  SpmdSemanticsError, per the framework's never-misdeliver rule).
* ``get`` returns a :class:`GetFuture`; its ``.value`` is defined after the
  closing fence on every backend.

Epoch semantics (deterministic, identical across backends):

1. operations are applied at the closing ``fence()``, in *issue order* —
   the k-th RMA call of the epoch is applied before the (k+1)-th on every
   backend (SPMD programs issue the same calls on all ranks, so issue order
   is globally well defined; a per-call pattern is a partial permutation, so
   there are no intra-call conflicts);
2. within the epoch, puts/accumulates are applied to the window *before*
   gets are serviced — a get in the same epoch observes the epoch's writes
   (MPI leaves overlapping put+get undefined; we pick this refinement so the
   backends agree bit-for-bit);
3. ``fence()`` is collective over the communicator.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from . import ops as _ops
from .checker import validate_perm

Pair = Tuple[int, int]

# Internal tags (see communicator.py's internal-tag convention: negative,
# never matched by user-level ANY_TAG).
_TAG_RMA = -6
_TAG_RMA_REPLY = -7
_TAG_PASSIVE = -8        # origin -> target window server
_TAG_PASSIVE_REPLY = -9  # server -> origin (lock grant / get data / acks)
_TAG_PSCW_POST = -10     # target -> origin: window posted (MPI_Win_start waits)


class GetFuture:
    """Result of ``Window.get``: defined after the closing fence."""

    def __init__(self) -> None:
        self._resolved = False
        self._value: Any = None

    def _resolve(self, value: Any) -> None:
        self._value = value
        self._resolved = True

    @property
    def value(self) -> Any:
        if not self._resolved:
            raise RuntimeError(
                "GetFuture read before the closing fence: one-sided gets "
                "complete at Window.fence() [S: MPI-2 active-target RMA]")
        return self._value

    def wait(self) -> Any:
        return self.value


def _normalize_pairs(pairs, my_rank: int, size: int,
                     allow_int: bool) -> List[Pair]:
    """Pattern form: validate the partial permutation. Int form (process
    backends only): this rank targets ``pairs``; other ranks' targets are
    unknown here, which is fine for the message-based backends."""
    if isinstance(pairs, (int, np.integer)):
        if not allow_int:
            raise TypeError(
                "rank-dynamic RMA (int destination) is only available on the "
                "process backends; the SPMD backend needs the static pattern "
                "form: pairs=[(src, dst), ...]")
        dest = int(pairs)
        if not (0 <= dest < size):
            raise ValueError(f"target rank {dest} out of range for size {size}")
        return [(my_rank, dest)]
    pairs = [(int(s), int(d)) for s, d in pairs]
    validate_perm(pairs, size)
    return pairs


class _RmaRequest:
    """Request-based RMA handle (MPI_Rput/Raccumulate): wait() completes
    the op at the target via flush (surfacing its error there).  Stamped
    with the window's per-target flush epoch at creation: a flush/
    flush_all issued AFTER the op makes later waits genuinely local
    (no redundant round-trip per drained request)."""

    def __init__(self, win: "P2PWindow", rank: int):
        self._win, self._rank = win, rank
        self._epoch = win._flush_epoch(rank)
        self._done = False

    def wait(self):
        if not self._done:
            if self._win._flush_epoch(self._rank) == self._epoch:
                self._win.flush(self._rank)
            self._done = True

    def test(self):
        # make progress like every other Request type: completing here
        # is at most one bounded flush ack, so request-set pollers
        # terminate
        self.wait()
        return True, None


class P2PWindow:
    """RMA window over a :class:`~mpi_tpu.communicator.P2PCommunicator`.

    The local buffer is a numpy array (copied from ``init``).  Operations
    are queued and shipped at ``fence()`` with one message per peer (FIFO
    per-pair transport ordering keeps epochs aligned without a barrier:
    each rank sends exactly one RMA message per peer per epoch, and the
    fence receives exactly one from each peer — source-specific receives,
    NOT any-source, so a fast peer's next fence can never be consumed by a
    slow peer's current one), followed by get replies.  Messages carry a
    (window id, epoch) stamp that is asserted on receipt: fences of
    different windows on one communicator must be identically ordered on
    all ranks [S: collective-call ordering], and a violation is diagnosed,
    never misdelivered.  Exiting ``fence()`` implies this rank's window has
    its final epoch value — every peer's ops were received and applied.
    """

    def __init__(self, comm, init: Any):
        self._comm = comm
        self._buf = np.array(init)  # owned copy [S: MPI_Win_create memory]
        self._wid = getattr(comm, "_win_counter", 0)
        comm._win_counter = self._wid + 1
        self._epoch = 0
        # queued outgoing ops: per target comm-rank, list of
        # (issue_idx, kind, payload, loc, opname)
        self._out: dict = {}
        # queued gets: (issue_idx, source_rank_or_None, loc, fill, future)
        self._gets: List[Tuple] = []
        self._issue = 0
        self._freed = False
        # per-target completed-flush counter (request-based RMA stamps)
        self._flush_epochs: dict = {}
        # passive-target server (win_create is collective [S], so the
        # context allocation below is deterministic on every rank, and
        # every rank has a live server before any origin can lock it)
        self._ensure_server()

    # -- epoch ops ---------------------------------------------------------

    @property
    def local(self) -> np.ndarray:
        """The local window buffer (valid to read between fences)."""
        return self._buf

    def put(self, data: Any, pairs, loc: Any = None) -> None:
        """Queue a put: for each (src, dst), src's ``data`` overwrites
        dst's window (at ``loc`` if given, numpy basic-indexing)."""
        self._check_open()
        for s, d in _normalize_pairs(pairs, self._comm.rank,
                                     self._comm.size, allow_int=True):
            if s == self._comm.rank:
                self._queue(d, "put", np.asarray(data), loc, None)
        self._issue += 1

    def accumulate(self, data: Any, pairs, op: _ops.ReduceOp = _ops.SUM,
                   loc: Any = None) -> None:
        """Queue an accumulate: dst's window[loc] = op(window[loc], data)."""
        self._check_open()
        for s, d in _normalize_pairs(pairs, self._comm.rank,
                                     self._comm.size, allow_int=True):
            if s == self._comm.rank:
                self._queue(d, "acc", np.asarray(data), loc, op)
        self._issue += 1

    def get(self, pairs, fill: Any = 0, loc: Any = None) -> GetFuture:
        """Queue a get: for each (src, dst), src's window[loc] arrives at
        dst.  Returns a GetFuture (``.value`` after the closing fence, on
        every rank).  Ranks that are not a dst in the pattern resolve to
        ``fill`` (default 0, matching the SPMD backend, which must produce
        a value on every rank)."""
        self._check_open()
        fut = GetFuture()
        me = self._comm.rank
        norm = _normalize_pairs(pairs, me, self._comm.size, allow_int=True)
        srcs = [s for s, d in norm if d == me]
        if isinstance(pairs, (int, np.integer)):
            srcs = [int(pairs)]  # int form: I am the origin, reading pairs
        src = srcs[0] if srcs else None  # None: resolve to fill at fence
        self._gets.append((self._issue, src, loc, fill, fut))
        self._issue += 1
        return fut

    def fence(self) -> None:
        """Close the epoch: ship+apply all queued ops, resolve gets."""
        self._check_open()
        comm = self._comm
        me, size = comm.rank, comm.size
        # phase 1: one ops-message to every peer (possibly empty)
        for r in range(size):
            if r == me:
                continue
            ops_r = self._out.get(r, [])
            gets_r = [(idx, loc) for idx, s, loc, _f, _ in self._gets
                      if s == r]
            comm._send_internal(
                (self._wid, self._epoch, ops_r, gets_r), r, _TAG_RMA)
        # phase 2: exactly one message from EACH peer (source-specific —
        # see class docstring for why any-source would race)
        incoming: List[Tuple[int, int, str, Any, Any, Optional[str]]] = []
        get_reqs: dict = {}
        for r in range(size):
            if r == me:
                continue
            wid, epoch, ops_r, gets_r = comm._recv_internal(r, _TAG_RMA)
            if (wid, epoch) != (self._wid, self._epoch):
                raise RuntimeError(
                    f"RMA fence mismatch: rank {r} is fencing window "
                    f"{wid} epoch {epoch}, this rank window {self._wid} "
                    f"epoch {self._epoch} — fences of windows on one "
                    f"communicator must be identically ordered on all ranks")
            for idx, kind, data, loc, op in ops_r:
                incoming.append((idx, r, kind, data, loc, op))
            if gets_r:
                get_reqs[r] = gets_r
        # my own ops targeting myself
        for idx, kind, data, loc, op in self._out.get(me, []):
            incoming.append((idx, me, kind, data, loc, op))
        # apply puts/accumulates: issue order first (global in SPMD-aligned
        # programs), source rank as the tie-break — see module docstring
        for idx, src_rank, kind, data, loc, op in sorted(
                incoming, key=lambda t: (t[0], t[1])):
            self._apply(kind, data, loc, op)
        # phase 3: service get requests against the post-write window
        for r, reqs in get_reqs.items():
            comm._send_internal(
                [self._read(loc) for idx, loc in reqs], r, _TAG_RMA_REPLY)
        by_src: dict = {}
        for idx, s, loc, fill, fut in self._gets:
            by_src.setdefault(s, []).append((loc, fill, fut))
        for s, entries in by_src.items():
            if s is None:  # no source in the pattern: the boundary fill
                for loc, fill, fut in entries:
                    fut._resolve(fill)
                continue
            if s == me:
                for loc, fill, fut in entries:
                    fut._resolve(self._read(loc))
                continue
            replies = comm._recv_internal(s, _TAG_RMA_REPLY)
            for (loc, fill, fut), val in zip(entries, replies):
                fut._resolve(val)
        self._out.clear()
        self._gets.clear()
        self._issue = 0
        self._epoch += 1

    # -- passive target (MPI-2 MPI_Win_lock/unlock) [S] --------------------
    # A per-window SERVER THREAD on an isolated child context services
    # lock/put/get/accumulate/unlock requests without the target's user
    # code participating — true one-sided access, unlike the fence epochs
    # above.  Exclusive locks serialize writers; shared locks admit
    # concurrent readers (readers-writer with FIFO handoff).  Ops issued
    # inside a lock epoch are applied at the target in issue order (FIFO
    # per-pair transport ordering); ``unlock`` acks only after everything
    # sent under the lock has been applied — MPI's completion-at-unlock.
    # Self-targeted epochs bypass messaging and apply under the server's
    # mutex (deadlock-free on every transport).

    def _atomic_runnable(self, src: int) -> bool:
        """Caller holds _srv_mutex.  An atomic may run unless some OTHER
        rank holds the exclusive lock (its epoch must stay isolated);
        concurrent shared holders are fine — application is a single
        mutex-guarded step."""
        s = self._lock_state
        return s["excl"] is None or s["excl"] == src

    def _atomic_exec(self, msg) -> tuple:
        """Caller holds _srv_mutex; returns the ('ok', old)/('err', txt)
        reply — ONE implementation for the server path, the deferred
        drain, and the self-rank path."""
        try:
            if msg[0] == "fetch_op":
                _, data, op, loc = msg
                old = self._read(loc)
                self._apply("acc", data, loc, op)
            else:  # "cas"
                _, compare, new_val, loc = msg
                old = self._read(loc)
                if np.array_equal(old, compare):
                    self._apply("put", new_val, loc, None)
            return ("ok", old)
        except Exception as e:  # noqa: BLE001 - surfaces at the origin
            return ("err", f"{type(e).__name__}: {e}")

    def _ensure_server(self):
        import threading

        from .communicator import P2PCommunicator

        if getattr(self, "_srv_thread", None) is not None:
            return
        # TWO isolated child contexts (deterministic: same _alloc_context
        # sequence on every rank since win_create is collective):
        # * _srv_comm — requests + lock grants.  NO recv_timeout: the
        #   server idles between requests by design, and a lock wait is
        #   unbounded by design (another rank may hold the lock
        #   arbitrarily long — a timeout there would be a false failure).
        # * _org_comm — unlock acks + get replies, BOUNDED work at a live
        #   target.  Inherits the parent's recv_timeout so a crashed
        #   target surfaces as RecvTimeout, not a hang (the framework's
        #   failure-detection contract).
        ctx = self._comm._alloc_context()
        ctx2 = self._comm._alloc_context()
        self._srv_comm = P2PCommunicator(self._comm._t, self._comm._group,
                                         ctx, recv_timeout=None)
        self._org_comm = P2PCommunicator(self._comm._t, self._comm._group,
                                         ctx2,
                                         recv_timeout=self._comm.recv_timeout)
        self._srv_mutex = threading.Lock()   # buffer + lock-state guard
        self._lock_state: dict = {"holders": set(), "excl": None,
                                  "queue": []}
        self._srv_errors: dict = {}
        self._pscw_cv = threading.Condition(self._srv_mutex)
        self._pscw_pending: set = set()      # origins my post still waits on
        self._pscw_targets = None            # my open access epoch
        t = threading.Thread(target=self._serve, daemon=True,
                             name=f"win{self._wid}-server")
        self._srv_thread = t
        t.start()

    def _serve(self) -> None:
        from .communicator import Status
        from .transport.base import ANY_SOURCE

        c = self._srv_comm
        st = Status()
        while True:
            try:
                msg = c._recv_internal(ANY_SOURCE, _TAG_PASSIVE, st)
            except Exception:  # transport closed (finalize) → done
                return
            src = st.source
            kind = msg[0]
            if kind == "stop":
                return
            # every branch is guarded: a bad op (shape mismatch, bad loc,
            # failing combiner) must NEVER kill the server — it is recorded
            # (or replied) and re-raised at the ORIGIN, and serving
            # continues (code-review: a dead server turned one bad put
            # into a permanent hang of every later lock on this rank)
            try:
                if kind == "lock":
                    self._request_lock(
                        src, exclusive=msg[1],
                        notify=lambda r=src: c._send_internal(
                            ("granted",), r, _TAG_PASSIVE_REPLY))
                elif kind == "unlock":
                    with self._srv_mutex:
                        err = self._srv_errors.pop(src, None)
                        self._srv_release(src)
                    self._org_comm._send_internal(("unlocked", err), src,
                                                  _TAG_PASSIVE_REPLY)
                elif kind == "pscw_complete":
                    # arrives on the SAME FIFO channel as this origin's
                    # RMA ops, so every op of its epoch has been applied
                    # by the time the exposure epoch can close; the ack
                    # carries any recorded op error back to the origin
                    # (completion-at-close, like unlock)
                    with self._pscw_cv:
                        err = self._srv_errors.pop(src, None)
                        self._pscw_pending.discard(src)
                        self._pscw_cv.notify_all()
                    self._org_comm._send_internal(("pscw_done", err), src,
                                                  _TAG_PASSIVE_REPLY)
                elif kind in ("fetch_op", "cas"):
                    # MPI-3 atomic: apply + reply the OLD value in one
                    # message.  An exclusive lock held by ANOTHER rank
                    # defers it (queued; drained at lock release) so
                    # atomics cannot pierce an exclusive epoch.
                    with self._srv_mutex:
                        if self._atomic_runnable(src):
                            reply = self._atomic_exec(msg)
                        else:
                            self._lock_state.setdefault(
                                "atomics", []).append((src, msg))
                            # the origin learns the wait is application-
                            # bound (foreign exclusive lock): crash
                            # detection stays on the first reply, only
                            # the post-deferral wait is untimed.  Sent
                            # UNDER the mutex — the release-drain also
                            # sends under it, so the notice can never
                            # be overtaken by the real reply (review:
                            # a stale notice would poison the channel)
                            self._org_comm._send_internal(
                                ("deferred", None), src,
                                _TAG_PASSIVE_REPLY)
                            reply = None
                    if reply is not None:
                        self._org_comm._send_internal(
                            reply, src, _TAG_PASSIVE_REPLY)
                elif kind == "flush":
                    # FIFO position => all prior ops from src are applied;
                    # ack carries (and clears) any recorded error
                    with self._srv_mutex:
                        err = self._srv_errors.pop(src, None)
                    self._org_comm._send_internal(("flushed", err), src,
                                                  _TAG_PASSIVE_REPLY)
                elif kind == "get":
                    try:
                        with self._srv_mutex:
                            val = self._read(msg[1])
                        reply = ("ok", val)
                    except Exception as e:  # noqa: BLE001 - to origin
                        reply = ("err", f"{type(e).__name__}: {e}")
                    self._org_comm._send_internal(reply, src,
                                                  _TAG_PASSIVE_REPLY)
                else:  # "put" / "acc": no reply — errors surface at unlock
                    try:
                        _, data, loc, op = msg
                        with self._srv_mutex:
                            self._apply("put" if kind == "put" else "acc",
                                        data, loc, op)
                    except Exception as e:  # noqa: BLE001 - to origin
                        with self._srv_mutex:
                            self._srv_errors.setdefault(
                                src, f"{type(e).__name__}: {e}")
            except Exception:  # reply-send failure: peer gone; keep serving
                pass

    def _request_lock(self, src: int, exclusive: bool, notify) -> None:
        """Single grant path for remote AND self requesters: grant now if
        admissible, else join the FIFO queue; ``notify`` fires (under no
        lock) when granted."""
        with self._srv_mutex:
            s = self._lock_state
            ok = (s["excl"] is None and not s["holders"]) if exclusive \
                else (s["excl"] is None and not s["queue"])
            if ok:
                s["holders"].add(src)
                if exclusive:
                    s["excl"] = src
            else:
                s["queue"].append((src, exclusive, notify))
        if ok:
            notify()

    def _srv_release(self, src: int) -> None:
        # caller holds _srv_mutex
        s = self._lock_state
        s["holders"].discard(src)
        if s["excl"] == src:
            s["excl"] = None
        granted = []
        while s["queue"]:
            nxt, excl, notify = s["queue"][0]
            can = (s["excl"] is None and not s["holders"]) if excl \
                else s["excl"] is None
            if not can:
                break
            s["queue"].pop(0)
            s["holders"].add(nxt)
            if excl:
                s["excl"] = nxt
            granted.append(notify)
            if excl:
                break
        # drain atomics that the released lock was blocking (they run
        # before notify-sends, still under the caller's mutex)
        pend = s.get("atomics", [])
        if pend:
            still = []
            for a_src, a_msg in pend:
                if self._atomic_runnable(a_src):
                    self._org_comm._send_internal(
                        self._atomic_exec(a_msg), a_src, _TAG_PASSIVE_REPLY)
                else:
                    still.append((a_src, a_msg))
            s["atomics"] = still
        if getattr(self, "_pscw_cv", None) is not None:
            self._pscw_cv.notify_all()  # wake self-rank atomic waiters
        for notify in granted:
            notify()

    def lock(self, rank: int, exclusive: bool = True) -> None:
        """MPI_Win_lock [S]: open a passive-target access epoch at
        ``rank``'s window (blocks until granted).  ``exclusive=False`` is
        MPI_LOCK_SHARED."""
        self._check_open()
        self._ensure_server()
        if rank == self._comm.rank:
            # self-lock joins the SAME FIFO queue as remote requesters
            # (fair handoff; an out-of-queue spin could starve under
            # sustained remote contention)
            import threading

            granted = threading.Event()
            self._request_lock(self._comm.rank, exclusive, granted.set)
            granted.wait()
            return
        self._srv_comm._send_internal(("lock", exclusive), rank,
                                      _TAG_PASSIVE)
        reply = self._srv_comm._recv_internal(rank, _TAG_PASSIVE_REPLY)
        assert reply == ("granted",)

    def unlock(self, rank: int) -> None:
        """MPI_Win_unlock [S]: close the epoch; on return every op issued
        under the lock has been applied at the target.  An op that FAILED
        at the target (bad loc/shape/op) re-raises here, at the origin."""
        self._check_open()
        if rank == self._comm.rank:
            with self._srv_mutex:
                err = self._srv_errors.pop(self._comm.rank, None)
                self._srv_release(self._comm.rank)
            if err:
                raise RuntimeError(f"passive RMA op failed at target "
                                   f"{rank}: {err}")
            return
        self._srv_comm._send_internal(("unlock",), rank, _TAG_PASSIVE)
        reply = self._org_comm._recv_internal(rank, _TAG_PASSIVE_REPLY)
        assert reply[0] == "unlocked"
        if reply[1]:
            raise RuntimeError(
                f"passive RMA op failed at target {rank}: {reply[1]}")

    def put_at(self, rank: int, data: Any, loc: Any = None) -> None:
        """Passive put at ``rank`` (call between lock/unlock; applied in
        issue order, complete at unlock)."""
        self._check_open()
        if rank == self._comm.rank:
            with self._srv_mutex:
                try:
                    self._apply("put", np.asarray(data), loc, None)
                except Exception as e:  # noqa: BLE001 - surfaces at unlock
                    self._srv_errors.setdefault(
                        rank, f"{type(e).__name__}: {e}")
            return
        self._srv_comm._send_internal(("put", np.asarray(data), loc, None),
                                      rank, _TAG_PASSIVE)

    def accumulate_at(self, rank: int, data: Any,
                      op: _ops.ReduceOp = _ops.SUM, loc: Any = None) -> None:
        self._check_open()
        if rank == self._comm.rank:
            with self._srv_mutex:
                try:
                    self._apply("acc", np.asarray(data), loc, op)
                except Exception as e:  # noqa: BLE001 - surfaces at unlock
                    self._srv_errors.setdefault(
                        rank, f"{type(e).__name__}: {e}")
            return
        self._srv_comm._send_internal(("acc", np.asarray(data), loc, op),
                                      rank, _TAG_PASSIVE)

    def get_at(self, rank: int, loc: Any = None) -> Any:
        """Passive get from ``rank``'s window; returns the value
        immediately (a strengthening of MPI's complete-at-unlock)."""
        self._check_open()
        if rank == self._comm.rank:
            try:
                with self._srv_mutex:
                    return self._read(loc)
            except Exception as e:  # noqa: BLE001 - same contract as remote
                raise RuntimeError(f"passive RMA get failed at target "
                                   f"{rank}: {type(e).__name__}: {e}")
        self._srv_comm._send_internal(("get", loc), rank, _TAG_PASSIVE)
        tag, val = self._org_comm._recv_internal(rank, _TAG_PASSIVE_REPLY)
        if tag == "err":
            raise RuntimeError(f"passive RMA get failed at target "
                               f"{rank}: {val}")
        return val

    def sync(self) -> None:
        """MPI_Win_sync: the memory-model ordering point.  This window's
        ops are applied by the server under a mutex (no private/public
        copy split), so the call is a correct no-op — valid on ANY
        window, kept for portable MPI code."""
        self._check_open()

    # -- MPI-3 atomics + flush (passive/PSCW epochs) ------------------------

    def fetch_and_op(self, rank: int, data: Any,
                     op: _ops.ReduceOp = _ops.SUM, loc: Any = None):
        """MPI_Fetch_and_op [S: MPI-3]: atomically combine ``data`` into
        ``rank``'s window and return the PREVIOUS value — one server
        round-trip (the fetch-add every distributed counter wants)."""
        return self._atomic_origin(
            rank, ("fetch_op", np.asarray(data), op, loc), "fetch_and_op")

    def compare_and_swap(self, rank: int, compare: Any, new: Any,
                         loc: Any = None):
        """MPI_Compare_and_swap [S: MPI-3]: if the target location equals
        ``compare``, replace it with ``new``; returns the previous value
        either way."""
        return self._atomic_origin(
            rank, ("cas", np.asarray(compare), np.asarray(new), loc),
            "compare_and_swap")

    def _atomic_origin(self, rank: int, msg, what: str):
        self._check_open()
        self._ensure_server()
        if rank == self._comm.rank:
            with self._pscw_cv:  # the general server-state condition
                while not self._atomic_runnable(rank):
                    self._pscw_cv.wait()  # released lock notifies
                tag, val = self._atomic_exec(msg)
        else:
            self._srv_comm._send_internal(msg, rank, _TAG_PASSIVE)
            # first reply is BOUNDED by recv_timeout (a dead target must
            # surface, same contract as get/flush); a live target that
            # queued the atomic behind a foreign exclusive lock answers
            # ("deferred", ...) immediately, and only then do we wait
            # untimed — the remaining wait is application-controlled,
            # like lock()
            tag, val = self._org_comm._recv_internal(rank,
                                                     _TAG_PASSIVE_REPLY)
            if tag == "deferred":
                oc = self._org_comm
                (tag, val), _, _ = oc._t.recv(oc._world(rank), oc._ctx,
                                              _TAG_PASSIVE_REPLY,
                                              timeout=None)
        if tag == "err":  # same contract on the self path as remote
            raise RuntimeError(f"{what} failed at target {rank}: {val}")
        return val

    def flush(self, rank: int) -> None:
        """MPI_Win_flush [S: MPI-3]: complete all outstanding ops at
        ``rank`` WITHOUT closing the epoch; a recorded op error raises
        here (and is cleared) instead of waiting for unlock."""
        self._check_open()
        self._ensure_server()
        me = self._comm.rank
        if rank == me:
            with self._srv_mutex:
                err = self._srv_errors.pop(me, None)
            if err:
                raise RuntimeError(f"RMA op failed at target {rank}: {err}")
            self._bump_flush_epoch(rank)
            return
        self._srv_comm._send_internal(("flush",), rank, _TAG_PASSIVE)
        tag, err = self._org_comm._recv_internal(rank, _TAG_PASSIVE_REPLY)
        assert tag == "flushed"
        if err:
            raise RuntimeError(f"RMA op failed at target {rank}: {err}")
        self._bump_flush_epoch(rank)

    def _flush_epoch(self, rank: int) -> int:
        return self._flush_epochs.get(rank, 0)

    def _bump_flush_epoch(self, rank: int) -> None:
        self._flush_epochs[rank] = self._flush_epochs.get(rank, 0) + 1

    def lock_all(self) -> None:
        """MPI_Win_lock_all [S: MPI-3]: a SHARED lock at every rank's
        window — deadlock-free because shared grants don't exclude each
        other (rank order only matters against queued exclusives)."""
        for r in range(self._comm.size):
            self.lock(r, exclusive=False)

    def unlock_all(self) -> None:
        for r in range(self._comm.size):
            self.unlock(r)

    def flush_all(self) -> None:
        """MPI_Win_flush_all: complete outstanding ops at every target."""
        for r in range(self._comm.size):
            self.flush(r)

    # flush_local(_all): our origin side buffers nothing (ops ship
    # immediately), so local completion is trivially true — but the
    # TARGET-completion spelling is what callers usually mean; alias it.
    flush_local = flush
    flush_local_all = flush_all

    def get_accumulate(self, rank: int, data: Any,
                       op: _ops.ReduceOp = _ops.SUM, loc: Any = None):
        """MPI_Get_accumulate [S: MPI-3]: fetch the target location and
        accumulate into it, atomically — fetch_and_op generalized to
        array payloads (this implementation never restricted the payload
        to one element, so they coincide)."""
        return self.fetch_and_op(rank, data, op, loc)

    def rput(self, rank: int, data: Any, loc: Any = None):
        """MPI_Rput [S: MPI-3 request-based RMA]: returns a Request whose
        wait() flushes the target (op completion there)."""
        self.put_at(rank, data, loc)
        return _RmaRequest(self, rank)

    def raccumulate(self, rank: int, data: Any,
                    op: _ops.ReduceOp = _ops.SUM, loc: Any = None):
        self.accumulate_at(rank, data, op, loc)
        return _RmaRequest(self, rank)

    def rget(self, rank: int, loc: Any = None):
        """MPI_Rget: get_at is synchronous here, so the request is
        complete at creation and carries the value."""
        from .communicator import _CompletedRequest

        return _CompletedRequest(self.get_at(rank, loc))

    # -- generalized active target (PSCW [S: MPI_Win_post/start/
    # complete/wait]) — the third RMA synchronization mode, alongside
    # fence (active) and lock/unlock (passive).  Target side: post(group)
    # exposes the window to those origins, wait() blocks until they all
    # completed.  Origin side: start(group) opens an access epoch at
    # those targets (blocks until each posted), issue put_at/get_at/
    # accumulate_at, complete() closes it.  The completion notification
    # rides the same FIFO server channel as the epoch's ops, so a
    # target's wait() cannot return before the ops are applied.

    def post(self, group) -> None:
        """MPI_Win_post: expose my window to origin ranks ``group``
        (non-blocking)."""
        self._check_open()
        self._ensure_server()
        ranks = [int(r) for r in getattr(group, "ranks", group)]
        with self._pscw_cv:
            if self._pscw_pending:
                raise RuntimeError(
                    "MPI_Win_post while a previous exposure epoch is "
                    "still open (call win.wait() first)")
            self._pscw_pending = set(ranks)
        me = self._comm.rank
        for r in ranks:
            if r != me:
                self._org_comm._send_internal(("posted",), r,
                                              _TAG_PSCW_POST)

    def start(self, group) -> None:
        """MPI_Win_start: open an access epoch at target ranks ``group``;
        blocks until each target posted."""
        self._check_open()
        self._ensure_server()
        if self._pscw_targets is not None:
            raise RuntimeError("MPI_Win_start while a previous access "
                               "epoch is still open (call win.complete())")
        ranks = [int(r) for r in getattr(group, "ranks", group)]
        me = self._comm.rank
        oc = self._org_comm
        for t in ranks:
            if t != me:
                # UNTIMED by design, like lock(): waiting for the target
                # to reach its post() is waiting on application code, not
                # on a bounded service (recv_timeout would false-positive
                # on a slow-but-healthy peer)
                obj, _, _ = oc._t.recv(oc._world(t), oc._ctx,
                                       _TAG_PSCW_POST, timeout=None)
                assert obj == ("posted",)
        self._pscw_targets = ranks

    def complete(self) -> None:
        """MPI_Win_complete: close the access epoch; ops are applied at
        each target before its wait() returns."""
        self._check_open()
        if getattr(self, "_pscw_targets", None) is None:
            raise RuntimeError("MPI_Win_complete without MPI_Win_start")
        me = self._comm.rank
        targets, self._pscw_targets = self._pscw_targets, None
        errs = []
        for t in targets:
            if t == me:
                with self._pscw_cv:
                    err = self._srv_errors.pop(me, None)
                    self._pscw_pending.discard(me)
                    self._pscw_cv.notify_all()
                if err:
                    errs.append((me, err))
            else:
                self._srv_comm._send_internal(("pscw_complete",), t,
                                              _TAG_PASSIVE)
        for t in targets:
            if t != me:
                tag, err = self._org_comm._recv_internal(
                    t, _TAG_PASSIVE_REPLY)
                assert tag == "pscw_done"
                if err:
                    errs.append((t, err))
        if errs:
            raise RuntimeError(
                "PSCW op(s) failed at target(s): " +
                "; ".join(f"rank {t}: {e}" for t, e in errs))

    def wait(self) -> None:
        """MPI_Win_wait: close the exposure epoch — blocks until every
        posted origin called complete()."""
        self._check_open()
        if getattr(self, "_pscw_cv", None) is None:
            return  # no exposure epoch was ever opened
        import time

        deadline = (None if self._comm.recv_timeout is None
                    else time.monotonic() + self._comm.recv_timeout)
        with self._pscw_cv:
            while self._pscw_pending:
                budget = None
                if deadline is not None:
                    budget = deadline - time.monotonic()
                    if budget <= 0:
                        from .transport.base import RecvTimeout

                        raise RecvTimeout(
                            f"MPI_Win_wait: origins {sorted(self._pscw_pending)} "
                            f"never completed within {self._comm.recv_timeout}s")
                self._pscw_cv.wait(budget)

    def test(self) -> bool:
        """MPI_Win_test: nonblocking wait — True iff the exposure epoch
        is closed."""
        self._check_open()
        if getattr(self, "_pscw_cv", None) is None:
            return True
        with self._pscw_cv:
            return not self._pscw_pending

    def free(self) -> None:
        if getattr(self, "_srv_thread", None) is not None:
            try:
                self._srv_comm._send_internal(
                    ("stop",), self._comm.rank, _TAG_PASSIVE)
            except Exception:
                pass
            self._srv_thread.join(timeout=2.0)
            self._srv_thread = None
        self._freed = True

    # -- internals ---------------------------------------------------------

    def _check_open(self) -> None:
        if self._freed:
            raise RuntimeError("operation on a freed Window")

    def _queue(self, target: int, kind: str, data: np.ndarray, loc: Any,
               op: Optional[_ops.ReduceOp]) -> None:
        # the op object rides the transport with the data (built-in ops and
        # module-level user combiners pickle; lambda user ops need the
        # in-process 'local' backend)
        self._out.setdefault(target, []).append(
            (self._issue, kind, data, loc, op))

    def _read(self, loc: Any) -> np.ndarray:
        return np.copy(self._buf if loc is None else self._buf[loc])

    def _apply(self, kind: str, data: np.ndarray, loc: Any,
               op: Optional[_ops.ReduceOp]) -> None:
        if kind == "put":
            if loc is None:
                self._buf[...] = data
            else:
                self._buf[loc] = data
        elif loc is None:
            self._buf[...] = op.combine(self._buf, data)
        else:
            self._buf[loc] = op.combine(self._buf[loc], data)


class DynamicWindow(P2PWindow):
    """MPI_Win_create_dynamic [S: MPI-3 ch.11.2.4]: a window with NO
    initial memory; regions are attached and detached at runtime.  MPI
    addresses attached regions by base pointer; the value-semantics
    spelling here addresses them by KEY — ``loc`` in every RMA op is the
    region key, or ``(key, subindex)`` for a part of a region.

    attach/detach are LOCAL calls, per MPI; an op targeting a region the
    target has not attached fails at the target and surfaces through the
    usual completion points (unlock/flush/complete)."""

    def __init__(self, comm):
        super().__init__(comm, np.zeros(0))
        self._regions: dict = {}

    # -- local region management -------------------------------------------

    def attach(self, key: str, array: Any) -> np.ndarray:
        """Expose ``array`` (copied in, MPI_Win_create memory semantics)
        under ``key``; returns the live region (reads show remote
        writes after the usual synchronization).  Local call [S]."""
        region = np.array(array)
        with self._srv_mutex:  # serialized against the window server
            if key in self._regions:
                raise ValueError(f"region {key!r} already attached")
            self._regions[key] = region
        return region

    def detach(self, key: str) -> np.ndarray:
        """Withdraw the region; returns its final contents.  Local [S]."""
        with self._srv_mutex:
            if key not in self._regions:
                raise ValueError(f"region {key!r} is not attached")
            return self._regions.pop(key)

    def region(self, key: str) -> np.ndarray:
        return self._regions[key]

    # -- storage override: loc = key | (key, subindex) ----------------------

    def _resolve(self, loc: Any):
        if loc is None:
            raise ValueError(
                "dynamic-window ops need loc=<region key> or "
                "(key, subindex) — there is no base buffer")
        if isinstance(loc, tuple) and len(loc) == 2:
            key, sub = loc
        else:
            key, sub = loc, None
        try:
            return self._regions[key], sub
        except (KeyError, TypeError):  # unknown key, or unhashable loc
            raise KeyError(f"region {key!r} is not attached at this "
                           "target") from None

    def _read(self, loc: Any) -> np.ndarray:
        buf, sub = self._resolve(loc)
        return np.copy(buf if sub is None else buf[sub])

    def _apply(self, kind: str, data: np.ndarray, loc: Any,
               op: Optional[_ops.ReduceOp]) -> None:
        buf, sub = self._resolve(loc)
        if kind == "put":
            if sub is None:
                buf[...] = data
            else:
                buf[sub] = data
        elif sub is None:
            buf[...] = op.combine(buf, data)
        else:
            buf[sub] = op.combine(buf[sub], data)
