"""One-sided communication (MPI RMA): Window, Put/Get/Accumulate, Fence.

Capability contract [S]: MPI-2 active-target RMA — ``MPI_Win_create`` exposes
a local buffer; inside a fence epoch ranks issue ``MPI_Put`` / ``MPI_Get`` /
``MPI_Accumulate`` at remote windows; all operations complete at the closing
``MPI_Win_fence``.  (The reference checkout at /root/reference is empty this
session — SURVEY.md §0 — so the MPI standard is the behavioral contract; the
reference itself shows no RMA, making this a widening beyond parity.)

Portable API (identical on the process backends and the SPMD/TPU backend):

* operations take a static (src, dst) *pattern* — the same ``pairs`` list on
  every rank, exactly like ``Communicator.exchange``.  That is the subset of
  RMA expressible as one SPMD program (a ppermute per call); the process
  backends additionally accept a plain ``int`` destination for classic
  rank-dynamic MPI code (the TPU backend diagnoses that with
  SpmdSemanticsError, per the framework's never-misdeliver rule).
* ``get`` returns a :class:`GetFuture`; its ``.value`` is defined after the
  closing fence on every backend.

Epoch semantics (deterministic, identical across backends):

1. operations are applied at the closing ``fence()``, in *issue order* —
   the k-th RMA call of the epoch is applied before the (k+1)-th on every
   backend (SPMD programs issue the same calls on all ranks, so issue order
   is globally well defined; a per-call pattern is a partial permutation, so
   there are no intra-call conflicts);
2. within the epoch, puts/accumulates are applied to the window *before*
   gets are serviced — a get in the same epoch observes the epoch's writes
   (MPI leaves overlapping put+get undefined; we pick this refinement so the
   backends agree bit-for-bit);
3. ``fence()`` is collective over the communicator.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from . import ops as _ops
from .checker import validate_perm

Pair = Tuple[int, int]

# Internal tags (see communicator.py's internal-tag convention: negative,
# never matched by user-level ANY_TAG).
_TAG_RMA = -6
_TAG_RMA_REPLY = -7


class GetFuture:
    """Result of ``Window.get``: defined after the closing fence."""

    def __init__(self) -> None:
        self._resolved = False
        self._value: Any = None

    def _resolve(self, value: Any) -> None:
        self._value = value
        self._resolved = True

    @property
    def value(self) -> Any:
        if not self._resolved:
            raise RuntimeError(
                "GetFuture read before the closing fence: one-sided gets "
                "complete at Window.fence() [S: MPI-2 active-target RMA]")
        return self._value

    def wait(self) -> Any:
        return self.value


def _normalize_pairs(pairs, my_rank: int, size: int,
                     allow_int: bool) -> List[Pair]:
    """Pattern form: validate the partial permutation. Int form (process
    backends only): this rank targets ``pairs``; other ranks' targets are
    unknown here, which is fine for the message-based backends."""
    if isinstance(pairs, (int, np.integer)):
        if not allow_int:
            raise TypeError(
                "rank-dynamic RMA (int destination) is only available on the "
                "process backends; the SPMD backend needs the static pattern "
                "form: pairs=[(src, dst), ...]")
        dest = int(pairs)
        if not (0 <= dest < size):
            raise ValueError(f"target rank {dest} out of range for size {size}")
        return [(my_rank, dest)]
    pairs = [(int(s), int(d)) for s, d in pairs]
    validate_perm(pairs, size)
    return pairs


class P2PWindow:
    """RMA window over a :class:`~mpi_tpu.communicator.P2PCommunicator`.

    The local buffer is a numpy array (copied from ``init``).  Operations
    are queued and shipped at ``fence()`` with one message per peer (FIFO
    per-pair transport ordering keeps epochs aligned without a barrier:
    each rank sends exactly one RMA message per peer per epoch, and the
    fence receives exactly one from each peer — source-specific receives,
    NOT any-source, so a fast peer's next fence can never be consumed by a
    slow peer's current one), followed by get replies.  Messages carry a
    (window id, epoch) stamp that is asserted on receipt: fences of
    different windows on one communicator must be identically ordered on
    all ranks [S: collective-call ordering], and a violation is diagnosed,
    never misdelivered.  Exiting ``fence()`` implies this rank's window has
    its final epoch value — every peer's ops were received and applied.
    """

    def __init__(self, comm, init: Any):
        self._comm = comm
        self._buf = np.array(init)  # owned copy [S: MPI_Win_create memory]
        self._wid = getattr(comm, "_win_counter", 0)
        comm._win_counter = self._wid + 1
        self._epoch = 0
        # queued outgoing ops: per target comm-rank, list of
        # (issue_idx, kind, payload, loc, opname)
        self._out: dict = {}
        # queued gets: (issue_idx, source_rank_or_None, loc, fill, future)
        self._gets: List[Tuple] = []
        self._issue = 0
        self._freed = False

    # -- epoch ops ---------------------------------------------------------

    @property
    def local(self) -> np.ndarray:
        """The local window buffer (valid to read between fences)."""
        return self._buf

    def put(self, data: Any, pairs, loc: Any = None) -> None:
        """Queue a put: for each (src, dst), src's ``data`` overwrites
        dst's window (at ``loc`` if given, numpy basic-indexing)."""
        self._check_open()
        for s, d in _normalize_pairs(pairs, self._comm.rank,
                                     self._comm.size, allow_int=True):
            if s == self._comm.rank:
                self._queue(d, "put", np.asarray(data), loc, None)
        self._issue += 1

    def accumulate(self, data: Any, pairs, op: _ops.ReduceOp = _ops.SUM,
                   loc: Any = None) -> None:
        """Queue an accumulate: dst's window[loc] = op(window[loc], data)."""
        self._check_open()
        for s, d in _normalize_pairs(pairs, self._comm.rank,
                                     self._comm.size, allow_int=True):
            if s == self._comm.rank:
                self._queue(d, "acc", np.asarray(data), loc, op)
        self._issue += 1

    def get(self, pairs, fill: Any = 0, loc: Any = None) -> GetFuture:
        """Queue a get: for each (src, dst), src's window[loc] arrives at
        dst.  Returns a GetFuture (``.value`` after the closing fence, on
        every rank).  Ranks that are not a dst in the pattern resolve to
        ``fill`` (default 0, matching the SPMD backend, which must produce
        a value on every rank)."""
        self._check_open()
        fut = GetFuture()
        me = self._comm.rank
        norm = _normalize_pairs(pairs, me, self._comm.size, allow_int=True)
        srcs = [s for s, d in norm if d == me]
        if isinstance(pairs, (int, np.integer)):
            srcs = [int(pairs)]  # int form: I am the origin, reading pairs
        src = srcs[0] if srcs else None  # None: resolve to fill at fence
        self._gets.append((self._issue, src, loc, fill, fut))
        self._issue += 1
        return fut

    def fence(self) -> None:
        """Close the epoch: ship+apply all queued ops, resolve gets."""
        self._check_open()
        comm = self._comm
        me, size = comm.rank, comm.size
        # phase 1: one ops-message to every peer (possibly empty)
        for r in range(size):
            if r == me:
                continue
            ops_r = self._out.get(r, [])
            gets_r = [(idx, loc) for idx, s, loc, _f, _ in self._gets
                      if s == r]
            comm._send_internal(
                (self._wid, self._epoch, ops_r, gets_r), r, _TAG_RMA)
        # phase 2: exactly one message from EACH peer (source-specific —
        # see class docstring for why any-source would race)
        incoming: List[Tuple[int, int, str, Any, Any, Optional[str]]] = []
        get_reqs: dict = {}
        for r in range(size):
            if r == me:
                continue
            wid, epoch, ops_r, gets_r = comm._recv_internal(r, _TAG_RMA)
            if (wid, epoch) != (self._wid, self._epoch):
                raise RuntimeError(
                    f"RMA fence mismatch: rank {r} is fencing window "
                    f"{wid} epoch {epoch}, this rank window {self._wid} "
                    f"epoch {self._epoch} — fences of windows on one "
                    f"communicator must be identically ordered on all ranks")
            for idx, kind, data, loc, op in ops_r:
                incoming.append((idx, r, kind, data, loc, op))
            if gets_r:
                get_reqs[r] = gets_r
        # my own ops targeting myself
        for idx, kind, data, loc, op in self._out.get(me, []):
            incoming.append((idx, me, kind, data, loc, op))
        # apply puts/accumulates: issue order first (global in SPMD-aligned
        # programs), source rank as the tie-break — see module docstring
        for idx, src_rank, kind, data, loc, op in sorted(
                incoming, key=lambda t: (t[0], t[1])):
            self._apply(kind, data, loc, op)
        # phase 3: service get requests against the post-write window
        for r, reqs in get_reqs.items():
            comm._send_internal(
                [self._read(loc) for idx, loc in reqs], r, _TAG_RMA_REPLY)
        by_src: dict = {}
        for idx, s, loc, fill, fut in self._gets:
            by_src.setdefault(s, []).append((loc, fill, fut))
        for s, entries in by_src.items():
            if s is None:  # no source in the pattern: the boundary fill
                for loc, fill, fut in entries:
                    fut._resolve(fill)
                continue
            if s == me:
                for loc, fill, fut in entries:
                    fut._resolve(self._read(loc))
                continue
            replies = comm._recv_internal(s, _TAG_RMA_REPLY)
            for (loc, fill, fut), val in zip(entries, replies):
                fut._resolve(val)
        self._out.clear()
        self._gets.clear()
        self._issue = 0
        self._epoch += 1

    def free(self) -> None:
        self._freed = True

    # -- internals ---------------------------------------------------------

    def _check_open(self) -> None:
        if self._freed:
            raise RuntimeError("operation on a freed Window")

    def _queue(self, target: int, kind: str, data: np.ndarray, loc: Any,
               op: Optional[_ops.ReduceOp]) -> None:
        # the op object rides the transport with the data (built-in ops and
        # module-level user combiners pickle; lambda user ops need the
        # in-process 'local' backend)
        self._out.setdefault(target, []).append(
            (self._issue, kind, data, loc, op))

    def _read(self, loc: Any) -> np.ndarray:
        return np.copy(self._buf if loc is None else self._buf[loc])

    def _apply(self, kind: str, data: np.ndarray, loc: Any,
               op: Optional[_ops.ReduceOp]) -> None:
        if kind == "put":
            if loc is None:
                self._buf[...] = data
            else:
                self._buf[loc] = data
        elif loc is None:
            self._buf[...] = op.combine(self._buf, data)
        else:
            self._buf[loc] = op.combine(self._buf[loc], data)
