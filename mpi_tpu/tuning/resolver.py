"""The process-wide tuned-dispatch resolver (ISSUE 9 tentpole).

`pick` is the one call every ``algorithm="auto"`` decision point makes
(communicator.allreduce / reduce_scatter / alltoall, plus the arena's
``sm_allreduce`` / ``sm_reduce`` internal gates): given the request's
(transport, group size, collective, payload bytes) it returns the
active table's algorithm for that cell — counted in the
``tuned_table_hits`` pvar — or None, meaning "no matching row": the
caller runs the built-in seed policy (the measured-once constants the
table replaces), counted in ``tuned_table_fallbacks``.  With no table
configured every auto decision is a fallback and behavior is
byte-identical to the constants.

Activation: the ``tuning_table_path`` mpit cvar, the
``MPI_TPU_TUNING_TABLE`` environment variable (read lazily, once),
``run_local(tuning_table=...)``, or ``mpi_tpu.launcher
--tuning-table``.  A table whose machine fingerprint does not match
this host LOADS but never serves (`reason` says why) — per-machine
tables are the whole point; re-run ``tools/tune.py`` on the new box.

Group coherence: like the crossover cvars this replaces, the table is
process-wide state that MUST agree across the group (same path on every
rank).  The dispatch key is a pure function of congruent inputs for the
reduction collectives; alltoall's consumer keeps coherence structurally
(a tuned "pairwise" row declines INSIDE the arena negotiation, so band
skew from ragged payloads can never split the group — see
communicator.alltoall).

Introspection: `last_decision()` returns the most recent decision
(collective, key, chosen algorithm, and whether a trusted row, an
untrusted row, or the seed policy served it); `explain` answers the
same question for a hypothetical request without counting it.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

from .. import mpit as _mpit
from .table import TuningTable, TuningTableError, fingerprint

ENV_TABLE = "MPI_TPU_TUNING_TABLE"

_lock = threading.Lock()
_path: Optional[str] = None
_table: Optional[TuningTable] = None
_reason: Optional[str] = None  # why the configured table is not serving
_env_done = False
_last: Optional[Dict] = None


def set_table_path(path: Optional[str]) -> None:
    """Load ``path`` as the process's active tuning table (strict: a
    malformed table raises :class:`TuningTableError` and leaves the
    previous table in place).  ``None``/"" clears the table — every
    auto decision falls back to the seed constants again."""
    global _path, _table, _reason, _env_done
    if not path:
        with _lock:
            _path, _table, _reason, _env_done = None, None, None, True
        return
    tab = TuningTable.load(path)  # outside the lock; may raise
    reason = None
    if not tab.matches_machine():
        fp = fingerprint()
        reason = (f"fingerprint mismatch: table measured on "
                  f"{tab.fingerprint.get('hostname')!r}/"
                  f"{tab.fingerprint.get('cpu_count')}cpu, this machine is "
                  f"{fp['hostname']!r}/{fp['cpu_count']}cpu — falling back "
                  f"to seed constants (re-run tools/tune.py here)")
    with _lock:
        _path, _table, _reason, _env_done = path, tab, reason, True


def table_path() -> str:
    """The configured table path ('' when none) — the cvar's reader."""
    _ensure_env()
    with _lock:
        return _path or ""


def reason() -> Optional[str]:
    """Why the configured table is not serving (None when it is, or
    when no table is configured)."""
    _ensure_env()
    with _lock:
        return _reason


def active_table() -> Optional[TuningTable]:
    """The table picks are served from: loaded AND fingerprint-matched."""
    _ensure_env()
    with _lock:
        return None if _reason is not None else _table


def _ensure_env() -> None:
    """Lazy init from MPI_TPU_TUNING_TABLE.  Unlike the strict cvar
    writer this must never kill world creation: a bad env-named table
    is reported on stderr and recorded in `reason`.  ``_env_done``
    flips only AFTER the table is configured — rank threads race into
    their first pick concurrently, and an early flip would hand the
    losers a fallback on a world the env var meant to tune (duplicate
    loads in that window are idempotent and harmless)."""
    global _env_done, _path, _reason
    with _lock:
        if _env_done:
            return
        path = os.environ.get(ENV_TABLE)
        if not path:
            _env_done = True
            return
    try:
        set_table_path(path)  # flips _env_done under its lock
    except TuningTableError as e:
        with _lock:
            _path, _env_done = path, True
            _reason = f"table from ${ENV_TABLE} rejected: {e}"
        sys.stderr.write(f"mpi_tpu.tuning: {_reason}\n")


def _record(decision: Dict) -> None:
    global _last
    with _lock:
        _last = decision


def last_decision() -> Optional[Dict]:
    """The most recent `pick` outcome UNDER AN ACTIVE TABLE:
    ``{"collective", "transport", "nranks", "nbytes", "algorithm",
    "source"}`` where source is ``"table:trusted"``,
    ``"table:untrusted"`` or ``"seed"`` (algorithm None for seed — no
    row matched, the caller's constants decided).  With no active
    table, picks take the recording-free fast path and this keeps the
    last recorded decision (use `explain` for hypotheticals)."""
    with _lock:
        return dict(_last) if _last is not None else None


def explain(transport: str, nranks: int, collective: str,
            nbytes: int) -> Dict:
    """What `pick` WOULD decide for one request, without counting it —
    the introspection entry point (README "Tuned dispatch")."""
    tab = active_table()
    row = (tab.match(transport, nranks, collective, nbytes)
           if tab is not None else None)
    return {
        "collective": collective, "transport": transport,
        "nranks": nranks, "nbytes": int(nbytes),
        "algorithm": None if row is None else row.algorithm,
        "source": ("seed" if row is None
                   else "table:trusted" if row.trusted
                   else "table:untrusted"),
        "row": None if row is None else row.as_dict(),
        "table": None if tab is None else tab.path,
        "inactive_reason": reason(),
    }


def pick(comm, collective: str, nbytes: int,
         allowed: Sequence[str]) -> Optional[str]:
    """The dispatch consult: the matching row's algorithm when the
    active table has one AND it is applicable here (``allowed`` — the
    caller's real algorithm set for this group: e.g. no
    recursive_halving on non-pow2 groups, no "sm" off the shm
    transport), else None = run the seed policy.  Exactly one of
    ``tuned_table_hits`` / ``tuned_table_fallbacks`` is counted per
    consult."""
    # The no-table fast path (the overwhelmingly common one): a
    # LOCK-FREE read of the module cells — _env_done flips exactly once
    # and _table is written before it under the lock, so a stale read
    # only ever sends a racer down the slow path, never past a
    # configured table.  One counter tick, no decision record —
    # last_decision()/explain() describe ACTIVE-table resolution, and
    # taking the resolver lock (twice) plus a dict allocation here
    # would tax a path that used to be a constant comparison.
    if _env_done and _table is None:
        _mpit.count(tuned_table_fallbacks=1)
        return None
    tab = active_table()
    if tab is None:  # inactive (fingerprint mismatch / env rejection)
        _mpit.count(tuned_table_fallbacks=1)
        return None
    transport = getattr(comm._t, "tuning_transport", None)
    nranks = comm.size
    row = None
    if transport is not None:
        row = tab.match(transport, nranks, collective, int(nbytes))
        if row is not None and row.algorithm not in allowed:
            row = None
    if row is not None:
        _mpit.count(tuned_table_hits=1)
        _record({"collective": collective, "transport": transport,
                 "nranks": nranks, "nbytes": int(nbytes),
                 "algorithm": row.algorithm,
                 "source": ("table:trusted" if row.trusted
                            else "table:untrusted")})
        return row.algorithm
    _mpit.count(tuned_table_fallbacks=1)
    _record({"collective": collective, "transport": transport,
             "nranks": nranks, "nbytes": int(nbytes),
             "algorithm": None, "source": "seed"})
    return None


def _reset_for_tests() -> None:
    """Drop every module-level cell (tests only)."""
    global _path, _table, _reason, _env_done, _last
    with _lock:
        _path = _table = _reason = _last = None
        _env_done = False


__all__ = ["ENV_TABLE", "set_table_path", "table_path", "reason",
           "active_table", "pick", "explain", "last_decision"]
