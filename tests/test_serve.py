"""Resident world server (ISSUE 7 tentpole): lease, heal, survive.

The acceptance story lives here: a worker ``os._exit``ing
mid-collective inside a leased world surfaces MPI_ERR_PROC_FAILED to
the client within the detection bound, the pool shrinks it out, a
replacement rejoins under a strictly larger membership epoch, and the
NEXT lease on the same pool completes a correct allreduce.  Pools are
small (3 workers, socket) and detection tight so the whole file stays
tier-1-runnable on a loaded 2-core box.
"""

import os
import subprocess
import sys
import time

import pytest

from mpi_tpu import serve
from mpi_tpu.errors import (MPI_ERR_PROC_FAILED, ProcFailedError,
                            error_class)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DETECT_S = 1.5
# worker procs + server + pytest exceed this box's cores: the margins
# mirror tests/test_fault_tolerance.py's load-scaled bound
LOAD_MARGIN_S = 25.0 if (os.cpu_count() or 1) < 4 else 8.0


def _pool(**kw):
    kw.setdefault("pool_size", 3)
    kw.setdefault("backend", "socket")
    kw.setdefault("detect_timeout_s", DETECT_S)
    kw.setdefault("heartbeat_s", 0.2)
    kw.setdefault("rejoin_timeout_s", 20.0)
    return serve.WorldServer(**kw)


def _wait_healed(client, pool_size, timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = client.stats()
        if st["idle"] == pool_size and not st["healing"]:
            return st
        time.sleep(0.2)
    raise AssertionError(f"pool did not heal in {timeout}s: {st}")


def test_lease_runs_correct_worlds():
    """Leases of every size up to the pool produce correct collectives;
    acquire is a reservation, not a handshake (sub-second even here)."""
    with _pool() as srv:
        client = serve.connect(srv)
        try:
            for nranks in (1, 2, 3):
                t0 = time.monotonic()
                lease = client.acquire(nranks, timeout=10.0)
                acquire_s = time.monotonic() - t0
                assert len(lease.slots) == nranks
                got = lease.run(serve.job_allreduce, 128, timeout=30.0)
                assert got == sum(range(1, nranks + 1))
                lease.release()
                # a warm acquire must never cost anything like a cold
                # fork+handshake; 1s is ~3 orders above the measured
                # p99 and still far below launch() on this box
                assert acquire_s < 1.0, acquire_s
            st = client.stats()
            assert st["leases_granted"] == 3 and st["jobs_ok"] == 3
            assert st["epoch"] == 0 and st["heals_completed"] == 0
        finally:
            client.close()


def test_concurrent_leases_are_isolated():
    """Two disjoint leases from one pool run concurrently with correct,
    independent results (per-job contexts over the shared warm
    transport)."""
    with _pool() as srv:
        a = serve.connect(srv)
        b = serve.connect(srv)
        try:
            la = a.acquire(2, timeout=10.0)
            lb = b.acquire(1, timeout=10.0)
            assert not (set(la.slots) & set(lb.slots))
            import threading

            results = {}
            ta = threading.Thread(target=lambda: results.__setitem__(
                "a", la.run(serve.job_allreduce, 64, timeout=30.0)))
            tb = threading.Thread(target=lambda: results.__setitem__(
                "b", lb.run(serve.job_allreduce, 64, timeout=30.0)))
            ta.start(); tb.start(); ta.join(60); tb.join(60)
            assert results == {"a": 3.0, "b": 1.0}
            la.release(); lb.release()
        finally:
            a.close()
            b.close()


def test_acquire_beyond_pool_rejected_and_timeout_named():
    with _pool() as srv:
        client = serve.connect(srv)
        try:
            with pytest.raises(RuntimeError, match="nranks"):
                client.acquire(4)
            hog = client.acquire(3, timeout=5.0)
            t0 = time.monotonic()
            with pytest.raises(TimeoutError, match="idle workers"):
                client.acquire(1, timeout=1.0)
            assert time.monotonic() - t0 < 10.0
            hog.release()
            # freed: the next acquire succeeds
            client.acquire(3, timeout=10.0).release()
        finally:
            client.close()


def test_kill_mid_lease_acceptance():
    """THE acceptance criterion: kill mid-collective inside a leased
    world -> client sees MPI_ERR_PROC_FAILED within the detection
    bound; the pool self-heals (replacement rejoins under a STRICTLY
    larger epoch); the next lease on the same pool completes a correct
    allreduce."""
    with _pool() as srv:
        client = serve.connect(srv)
        try:
            lease = client.acquire(2, timeout=10.0)
            t0 = time.monotonic()
            with pytest.raises(ProcFailedError) as ei:
                lease.run(serve.job_kill_rank, 1, 2048,
                          timeout=3 * DETECT_S + LOAD_MARGIN_S)
            took = time.monotonic() - t0
            bound = 3 * DETECT_S + LOAD_MARGIN_S
            assert took < bound, f"diagnosis took {took:.1f}s (> {bound}s)"
            assert error_class(ei.value) == MPI_ERR_PROC_FAILED
            lease.release()
            st = _wait_healed(client, 3,
                              timeout=30.0 + LOAD_MARGIN_S)
            assert st["epoch"] >= 1  # strictly larger than the pre-kill 0
            assert st["heals_completed"] >= 1
            assert st["workers_lost"] >= 1
            # the SAME pool serves a correct full-size world again
            got = client.run(serve.job_allreduce, 128, nranks=3,
                             timeout=30.0)
            assert got == 6.0
        finally:
            client.close()


def test_pool_survives_repeated_kills():
    """Sequential kills (one per healing round) never take the pool
    down: every failed lease raises a named FT error and every healing
    round lands a strictly increasing epoch."""
    with _pool() as srv:
        client = serve.connect(srv)
        try:
            last_epoch = 0
            for round_no in range(2):
                lease = client.acquire(2, timeout=15.0)
                with pytest.raises(ProcFailedError):
                    lease.run(serve.job_kill_rank, 1, 1024,
                              timeout=3 * DETECT_S + LOAD_MARGIN_S)
                lease.release()
                st = _wait_healed(client, 3,
                                  timeout=30.0 + LOAD_MARGIN_S)
                assert st["epoch"] > last_epoch
                last_epoch = st["epoch"]
            assert client.run(serve.job_allreduce, 64, nranks=3,
                              timeout=30.0) == 6.0
        finally:
            client.close()


def test_lease_timeout_quarantines_wedged_worker():
    """A worker that blows the lease timeout is still wedged in the old
    job (its job loop is serial), so the server must KILL it into the
    healing path rather than hand it back to the idle pool — where it
    would poison every subsequent lease it joins."""
    with _pool() as srv:
        client = serve.connect(srv)
        try:
            lease = client.acquire(1, timeout=10.0)
            with pytest.raises(TimeoutError, match="did not complete"):
                lease.run(serve.job_sleep, 30.0, timeout=1.0)
            lease.release()
            st = _wait_healed(client, 3, timeout=30.0 + LOAD_MARGIN_S)
            assert st["workers_lost"] >= 1 and st["epoch"] >= 1
            # the healed pool serves correct full-size worlds again —
            # no lease ever lands on the wedged worker
            assert client.run(serve.job_allreduce, 64, nranks=3,
                              timeout=30.0) == 6.0
        finally:
            client.close()


def test_client_disconnect_releases_leases():
    with _pool() as srv:
        a = serve.connect(srv)
        a.acquire(3, timeout=10.0)
        a.close()  # leases owned by the connection die with it
        b = serve.connect(srv)
        try:
            b.acquire(3, timeout=10.0).release()
        finally:
            b.close()


def test_launcher_serve_subcommand(tmp_path):
    """The deployment spelling: ``python -m mpi_tpu.launcher serve
    --addr-file F`` brings a pool up; ``mpi_tpu.connect(F)`` reaches it
    and leases a world; client shutdown stops the daemon."""
    addr_file = tmp_path / "serve.addr"
    proc = subprocess.Popen(
        [sys.executable, "-m", "mpi_tpu.launcher", "serve",
         "--pool-size", "2", "--addr-file", str(addr_file),
         "--detect-timeout", str(DETECT_S)],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.monotonic() + 90.0
        while not addr_file.exists():
            assert proc.poll() is None, proc.communicate()[1][-900:]
            assert time.monotonic() < deadline, "server never published"
            time.sleep(0.1)
        import mpi_tpu

        client = mpi_tpu.connect(str(addr_file))
        assert client.run(serve.job_allreduce, 64, nranks=2,
                          timeout=30.0) == 3.0
        client.shutdown()
        client.close()
        assert proc.wait(timeout=30.0) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(10.0)


def test_idle_worker_pvars_reported_without_jobs():
    """ISSUE 15 satellite (PR-13 metrics residual): a worker that never
    completes a job must still show up in stats() — the pvar snapshot
    piggybacks on the control-channel heartbeat push, not only on
    job_done.  Lease NOTHING; the aggregated worker pvars appear."""
    with _pool() as srv:
        client = serve.connect(srv)
        try:
            deadline = time.monotonic() + 15.0
            agg = {}
            while time.monotonic() < deadline:
                agg = client.stats()["worker_pvars"]
                if agg:
                    break
                time.sleep(0.2)
            assert agg, "idle workers reported no pvars"
            # the snapshot carries the documented slots (values may be
            # zero on an idle pool — presence is the contract)
            for key in ("msgs_sent", "collectives_started",
                        "proc_failures_detected"):
                assert key in agg, (key, agg)
            assert client.stats()["jobs_ok"] == 0  # really no jobs
        finally:
            client.close()


def test_connect_addr_file_retry_delayed_and_partial(tmp_path):
    """ISSUE 15 satellite: connect() retries a MISSING addr file and a
    PARTIALLY-WRITTEN one (unparseable content) within the
    connect_retry budget — the just-started/just-elected server
    publishing its record loses the race routinely.  A file that never
    materializes raises a NAMED TransportError, not a parse crash."""
    import threading

    from mpi_tpu.transport.base import TransportError

    with _pool(pool_size=1) as srv:
        path = str(tmp_path / "late.addr")

        def publish():
            time.sleep(0.4)
            with open(path, "w") as f:
                f.write("garbage-not-an-addr")  # partially written
            time.sleep(0.4)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(srv.addr)
            os.replace(tmp, path)

        th = threading.Thread(target=publish, daemon=True)
        th.start()
        client = serve.connect(path)
        try:
            assert client.run(serve.job_allreduce, 64, nranks=1,
                              timeout=30.0) == 1.0
        finally:
            client.close()
        th.join(5.0)
    # never-published: a named error inside the (shrunk) budget
    from mpi_tpu import mpit

    old = mpit.cvar_read("connect_retry_timeout_s")
    mpit.cvar_write("connect_retry_timeout_s", 0.5)
    try:
        with pytest.raises(TransportError, match="not published"):
            serve.connect(str(tmp_path / "never.addr"))
    finally:
        mpit.cvar_write("connect_retry_timeout_s", old)


def test_silent_server_bounded_by_request_timeout(monkeypatch):
    """ISSUE 17 ride-along: a server that ACCEPTS but never replies —
    the SIGSTOP-frozen-leader shape, where the TCP connection stays
    ESTABLISHED in the kernel so there is no EOF and no error — must
    not wedge a timeout-bearing request forever.  The client bounds its
    reply wait by the op timeout the SERVER itself enforces (plus
    slack) and surfaces the stall as the named ServerLostError (the
    federated client's failover signal).  Timeout-less ops keep the
    blocking-read semantics — only the named-bound path changes."""
    import socket
    import threading

    monkeypatch.setattr(serve, "_RPC_GRACE_S", 1.0)
    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    conns = []

    def frozen_accept():
        try:
            while True:
                c, _ = lst.accept()
                conns.append(c)  # hold it open, never reply
        except OSError:
            pass

    th = threading.Thread(target=frozen_accept, daemon=True)
    th.start()
    try:
        client = serve.ServerClient("127.0.0.1", lst.getsockname()[1])
        t0 = time.monotonic()
        # the stalled read surfaces as a ServerLostError either way it
        # is classified (recv timeout wrapped, or the frame reader
        # reporting no reply) — both are the failover signal
        with pytest.raises(serve.ServerLostError):
            client.acquire(1, timeout=0.5)
        assert time.monotonic() - t0 < 10.0, \
            "the stall must resolve within timeout + grace, not hang"
    finally:
        lst.close()
        for c in conns:
            c.close()
        th.join(2.0)


# -- pooled coll/sm arena across leases (ISSUE 11 tentpole #3) ----------------


def test_lease_allreduce_rides_pooled_arena():
    """Closes PR-7 residual (a): on a shm pool a lease allreduce routes
    through the POOLED collective arena (``coll_sm_hits > 0`` inside
    ``lease.run``) instead of skipping the fastest tier; the SECOND
    lease over the same worker set reuses the very same segment (same
    live-arena name, no per-lease /dev/shm churn); and a kill-mid-lease
    is still diagnosed as MPI_ERR_PROC_FAILED, after which the healed
    pool's next lease rides a FRESH arena under the bumped epoch."""
    with _pool(pool_size=2, backend="shm") as srv:
        client = serve.connect(srv)
        try:
            val, hits, names = client.run(serve.job_allreduce_arena, 512,
                                          nranks=2, timeout=30.0)
            assert val == 3.0
            assert hits > 0, "lease allreduce did not ride the arena"
            assert len(names) == 1
            val2, hits2, names2 = client.run(serve.job_allreduce_arena,
                                             512, nranks=2, timeout=30.0)
            assert (val2, True) == (3.0, hits2 > 0)
            # reuse, not churn: the same pooled segment served both
            assert names2 == names
            # kill-under-fire diagnosis is unchanged by the pooling
            lease = client.acquire(2, timeout=15.0)
            with pytest.raises(ProcFailedError) as ei:
                lease.run(serve.job_kill_rank, 1, 1024,
                          timeout=3 * DETECT_S + LOAD_MARGIN_S)
            assert error_class(ei.value) == MPI_ERR_PROC_FAILED
            lease.release()
            st = _wait_healed(client, 2, timeout=30.0 + LOAD_MARGIN_S)
            assert st["epoch"] >= 1
            val3, hits3, names3 = client.run(serve.job_allreduce_arena,
                                             512, nranks=2, timeout=30.0)
            assert (val3, True) == (3.0, hits3 > 0)
            # the bumped epoch retired the old segment name
            assert names3 != names
        finally:
            client.close()
