"""Payload framing shared by the byte-stream transports (socket, shm).

Two frame formats ride the same length-prefixed stream, distinguished by
the top bit of the u64 length word (RAW_FLAG):

* pickle frames — arbitrary picklable envelopes ``(ctx, tag, obj)``; the
  reference's wire format (SURVEY.md §2 #2 [B: "socket/pickle path"]).
* raw-array frames — contiguous numpy arrays ship as a tiny pickled meta
  header ``(ctx, tag, dtype.str, shape)`` followed by the array's raw
  bytes.  The hot payload is never pickled: the sender hands the buffer
  pointer straight to the ring/socket (ONE copy, into the transport) and
  the receiver reads straight into the freshly-allocated result array
  (ONE copy, out) — this is what makes the native data plane actually
  faster than pickle-over-TCP at bandwidth sizes (VERDICT round 1,
  "what's weak" #2).

Eligibility for the raw path: any ``np.ndarray`` without Python-object
fields (object dtypes and structured/void dtypes fall back to pickle,
which handles them correctly).  Non-contiguous arrays are compacted with
``ascontiguousarray`` first — still cheaper than pickling.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Optional, Tuple

import numpy as np

# u64 length word: top bit = raw-array frame, low 63 bits = body length
RAW_FLAG = 1 << 63
LEN_MASK = RAW_FLAG - 1
META = struct.Struct("<I")  # meta-pickle length prefix inside a raw body

_PROTO = pickle.HIGHEST_PROTOCOL


def as_raw_array(payload: Any) -> Optional[np.ndarray]:
    """The contiguous ndarray to ship raw, or None → use pickle.

    Exact-type check: ndarray SUBCLASSES (MaskedArray, np.matrix, ...)
    carry state the raw frame cannot represent — they keep the pickle
    path, which round-trips them faithfully."""
    if (type(payload) is np.ndarray and not payload.dtype.hasobject
            and payload.dtype.kind != "V"):
        if payload.flags["C_CONTIGUOUS"]:
            return payload
        # compact a strided view (ascontiguousarray would also promote
        # 0-dim to 1-dim, but 0-dim arrays are always contiguous)
        return np.ascontiguousarray(payload)
    return None


def pack_raw_meta(ctx, tag: int, arr: np.ndarray) -> bytes:
    """``<u32 meta_len><meta pickle>`` — everything in the raw body except
    the array bytes themselves."""
    meta = pickle.dumps((ctx, tag, arr.dtype.str, arr.shape), protocol=_PROTO)
    return META.pack(len(meta)) + meta


def unpack_raw_meta(meta: bytes) -> Tuple[Any, int, np.ndarray]:
    """Decode a raw frame's meta pickle; returns (ctx, tag, empty array to
    read the raw bytes into)."""
    ctx, tag, dtype_str, shape = pickle.loads(meta)
    return ctx, tag, np.empty(shape, dtype=np.dtype(dtype_str))


def parse_raw_body(body: bytes) -> Tuple[Any, int, np.ndarray]:
    """Decode an entire small raw body pulled in one read: meta prefix +
    array bytes → (ctx, tag, array).  The .copy() both compacts and makes
    the result writable/owned."""
    (mlen,) = META.unpack_from(body)
    ctx, tag, dtype_str, shape = pickle.loads(body[META.size:META.size + mlen])
    dtype = np.dtype(dtype_str)
    arr = np.frombuffer(body, dtype=dtype, offset=META.size + mlen).reshape(
        shape).copy() if dtype.itemsize else np.empty(shape, dtype)
    return ctx, tag, arr


def pack_pickle_body(ctx, tag: int, obj: Any) -> bytes:
    return pickle.dumps((ctx, tag, obj), protocol=_PROTO)


def value_copy(payload: Any) -> Any:
    """Self-send copy with message (value) semantics: cheap ndarray copy,
    pickle round-trip for everything else."""
    if isinstance(payload, np.ndarray):
        return payload.copy()
    return pickle.loads(pickle.dumps(payload, protocol=_PROTO))
