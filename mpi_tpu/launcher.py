"""mpirun-alike launcher (SURVEY.md §2 component #1).

Spawns N rank processes of a user script, assigns ranks 0..N-1 via
environment, hands them a file-based rendezvous directory for port exchange
(see transport/socket.py), propagates the first nonzero exit code, and
kills the remaining ranks on failure — the L0 contract of SURVEY.md §1.

Usage::

    python -m mpi_tpu.launcher -n 4 examples/pi.py [script args...]
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence

ENV_RANK = "MPI_TPU_RANK"
ENV_SIZE = "MPI_TPU_SIZE"
ENV_RDV = "MPI_TPU_RDV"
ENV_BACKEND = "MPI_TPU_BACKEND"


def launch(
    nranks: int,
    argv: Sequence[str],
    env_extra: Optional[dict] = None,
    timeout: Optional[float] = None,
    backend: Optional[str] = None,
    restarts: int = 0,
) -> int:
    """Run ``python argv...`` as ``nranks`` rank processes; return exit code.

    ``backend`` picks the rank transport ('socket' or 'shm'); default is the
    MPI_TPU_BACKEND env var, then 'socket'.

    ``restarts``: the elastic-recovery knob (SURVEY.md §5 failure story) —
    after a nonzero exit or a hang (timeout), the WHOLE world is killed and
    relaunched up to this many times.  Paired with crash-safe checkpoints
    (mpi_tpu.checkpoint: generation-committed save/load), a rank program
    that reloads its last checkpoint at startup resumes where the crashed
    attempt left off — the same restart-from-checkpoint model a TPU slice
    preemption needs.  MPI_TPU_ATTEMPT carries the attempt number to the
    ranks."""
    last = 0
    for attempt in range(restarts + 1):
        extra = dict(env_extra or {})
        extra["MPI_TPU_ATTEMPT"] = str(attempt)
        try:
            last = _launch_once(nranks, argv, extra, timeout, backend)
        except TimeoutError:
            if attempt == restarts:
                raise
            continue
        if last == 0:
            return 0
    return last


def cpu_pinned_env(env: dict, want: Optional[str] = None) -> dict:
    """Pin a child rank's jax to CPU (in place) unless ``want`` names
    another platform: N rank processes must not each claim the (single,
    possibly tunneled) TPU — concurrent claims serialize or wedge the
    pool, hanging every rank at ``import jax``.  The ONE shared helper
    for the launcher, comm_spawn, and bench fallbacks; the platform-
    trigger scrub only applies when pinning to cpu, so an explicit
    ``want='tpu'`` keeps the accelerator registration vars intact."""
    want = want or env.pop("MPI_TPU_RANK_JAX_PLATFORMS", None) or "cpu"
    if want == "cpu":
        for k in list(env):
            if k.startswith(("PALLAS_AXON", "AXON_")):
                del env[k]
    env["JAX_PLATFORMS"] = want
    return env


def _launch_once(
    nranks: int,
    argv: Sequence[str],
    env_extra: Optional[dict] = None,
    timeout: Optional[float] = None,
    backend: Optional[str] = None,
) -> int:
    if nranks < 1:
        raise ValueError(f"nranks must be >= 1, got {nranks}")
    backend = backend or os.environ.get(ENV_BACKEND, "socket")
    if backend == "shm":
        # compile the native ring once, up front, instead of N ranks racing
        # to the flock at import time
        from .native import ensure_built

        ensure_built()
    # the rendezvous dir is the membership service's root (port/
    # readiness/heartbeat/incarnation/claim files — mpi_tpu/membership)
    from . import membership

    rdv = membership.new_rendezvous_dir()
    procs: List[subprocess.Popen] = []
    try:
        for r in range(nranks):
            env = dict(os.environ)
            # the escape hatch may arrive via env_extra OR the caller's
            # environment — honor both before pinning
            want = (env_extra or {}).get("MPI_TPU_RANK_JAX_PLATFORMS")
            cpu_pinned_env(env, want)
            env.update(
                {
                    ENV_RANK: str(r),
                    ENV_SIZE: str(nranks),
                    ENV_RDV: rdv,
                    ENV_BACKEND: backend,
                }
            )
            if env_extra:
                env.update(env_extra)
            procs.append(subprocess.Popen([sys.executable, *argv], env=env))
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            codes = [p.poll() for p in procs]
            bad = [c for c in codes if c not in (None, 0)]
            if bad:
                _kill_all(procs)
                sys.stderr.write(_exit_summary(procs))
                return bad[0]
            if all(c == 0 for c in codes):
                return 0
            if deadline is not None and time.monotonic() > deadline:
                _kill_all(procs)
                sys.stderr.write(_exit_summary(procs))
                raise TimeoutError(f"ranks still running after {timeout}s")
            time.sleep(0.02)
    finally:
        _kill_all(procs)
        membership.cleanup_rendezvous(rdv)


def _kill_all(procs: List[subprocess.Popen]) -> None:
    """TERM → bounded wait → KILL → reap.  The escalation matters: a rank
    wedged in native code (shm ring memcpy, a jammed jax runtime) ignores
    SIGTERM, and a launcher that only TERMs leaves it holding /dev/shm
    segments and the TPU lock.  The final wait reaps the KILLed zombies
    so the exit summary below reports real wait statuses, not None."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + 5.0
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.send_signal(signal.SIGKILL)
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(2.0)
            except subprocess.TimeoutExpired:  # pragma: no cover - kernel
                pass  # unkillable (D-state); the summary reports it


def _exit_summary(procs: List[subprocess.Popen]) -> str:
    """Per-rank outcome table, printed on any non-zero outcome so a
    failure-story log is diagnosable without spelunking: WHICH rank died
    first-order (its own exit code / signal) vs which were merely killed
    by the launcher's TERM→KILL escalation."""
    lines = ["mpi_tpu.launcher: per-rank exit summary:"]
    for r, p in enumerate(procs):
        code = p.poll()
        if code is None:
            what = "still running (unkillable?)"
        elif code < 0:
            try:
                name = signal.Signals(-code).name
            except ValueError:
                name = f"signal {-code}"
            what = f"killed by {name}"
        else:
            what = f"exit code {code}"
        lines.append(f"  rank {r}: {what}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "serve":
        # resident world server (mpi_tpu/serve.py): pools warm worker
        # processes and leases worlds to clients in one round-trip;
        # dead workers are shrunk out and replaced under a fresh
        # membership epoch.  `python -m mpi_tpu.launcher serve --help`
        from . import serve

        return serve.main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="mpi_tpu.launcher",
        description="mpirun-alike launcher for mpi_tpu (or "
                    "'... launcher serve' for the resident world server)"
    )
    parser.add_argument("-n", "--np", type=int, required=True, dest="nranks",
                        help="number of rank processes")
    parser.add_argument("--timeout", type=float, default=None,
                        help="kill all ranks after this many seconds")
    parser.add_argument("--backend", choices=("socket", "shm"), default=None,
                        help="rank transport (default: MPI_TPU_BACKEND or socket)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="relaunch the world up to N times after a "
                             "crash/hang (resume from checkpoints)")
    parser.add_argument("--verify", action="store_true",
                        help="enable the runtime correctness verifier "
                             "(MPI_TPU_VERIFY=1 on every rank): deadlock "
                             "detection, collective-matching signatures, "
                             "request lints — see mpi_tpu/verify")
    parser.add_argument("--progress", choices=("none", "thread"),
                        default=None,
                        help="async progress mode for every rank "
                             "(MPI_TPU_PROGRESS): 'thread' starts one "
                             "dedicated progress engine per rank — "
                             "background completion for nonblocking ops "
                             "(mpi_tpu/progress.py)")
    parser.add_argument("--link-retry-timeout", type=float, default=None,
                        metavar="S",
                        help="socket link-healing budget for every rank "
                             "(MPI_TPU_LINK_RETRY_S -> the "
                             "link_retry_timeout_s cvar): a send-path "
                             "OSError whose peer is not failure-"
                             "suspected reconnects with backoff for up "
                             "to this many seconds, replaying unacked "
                             "frames (mpi_tpu/resilience.py).  Keep it "
                             "below fault_detect_timeout_s; 0 disables "
                             "healing (every link fault terminal)")
    parser.add_argument("--trace-dir", default=None, metavar="DIR",
                        help="enable the flight recorder on every rank "
                             "(MPI_TPU_TRACE=1, mpi_tpu/telemetry) and "
                             "export one Chrome-trace/Perfetto JSON per "
                             "rank into DIR at exit; merge them onto "
                             "one aligned timeline with "
                             "tools/tracecat.py DIR -o merged.json")
    parser.add_argument("--tuning-table", default=None, metavar="PATH",
                        help="per-machine tuned-dispatch table for every "
                             "rank (MPI_TPU_TUNING_TABLE): measured "
                             "(transport, nranks, collective, payload-"
                             "band) -> algorithm rows that "
                             "algorithm='auto' consults before the "
                             "built-in constants (mpi_tpu/tuning; "
                             "generate with tools/tune.py)")
    parser.add_argument("script", help="python script to run on every rank")
    parser.add_argument("script_args", nargs=argparse.REMAINDER,
                        help="arguments passed to the script")
    args = parser.parse_args(argv)
    env_extra = {}
    if args.verify:
        env_extra["MPI_TPU_VERIFY"] = "1"
    if args.progress is not None:
        env_extra["MPI_TPU_PROGRESS"] = args.progress
    if args.link_retry_timeout is not None:
        env_extra["MPI_TPU_LINK_RETRY_S"] = str(args.link_retry_timeout)
    if args.trace_dir is not None:
        env_extra["MPI_TPU_TRACE"] = "1"
        env_extra["MPI_TPU_TRACE_DIR"] = os.path.abspath(args.trace_dir)
    if args.tuning_table is not None:
        env_extra["MPI_TPU_TUNING_TABLE"] = os.path.abspath(
            args.tuning_table)
    return launch(args.nranks, [args.script, *args.script_args],
                  env_extra=env_extra or None,
                  timeout=args.timeout, backend=args.backend,
                  restarts=args.restarts)


if __name__ == "__main__":
    sys.exit(main())
