"""tools/tier1_guard.py — the mechanical "no worse than seed" gate:
parse DOTS_PASSED from a tier-1 log exactly like the ROADMAP verify
line, compare against the committed floor in tests/baseline_count.json."""

import json
import os
import subprocess
import sys

from tools.tier1_guard import count_dots, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LOG = """\
============================= test session starts ==============================
....F..s                                                                 [ 40%]
..x.E.                                                                   [ 70%]
tests/test_a.py .... not a -q progress line (has a path prefix)
not a progress line .... with dots
.......                                                                  [100%]
=========================== short test summary info ============================
"""


def test_count_dots_matches_verify_line(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text(_LOG)
    got = count_dots(str(log))
    # 6+4+7 dots on the three BARE -q progress lines; path-prefixed and
    # prose lines must NOT count (the verify grep anchors on ^[.FEsx]+)
    assert got == {"dots_passed": 17, "dots_failed": 1, "dots_errors": 1,
                   "dots_skipped": 2}


def test_guard_enforces_floor(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text(_LOG)
    baseline = tmp_path / "baseline.json"
    # --update records the baseline; a same-count run passes
    assert main([str(log), "--baseline", str(baseline), "--update"]) == 0
    assert json.loads(baseline.read_text())["dots_passed"] == 17
    assert main([str(log), "--baseline", str(baseline)]) == 0
    # a shrunken run fails
    baseline.write_text(json.dumps({"dots_passed": 18}))
    assert main([str(log), "--baseline", str(baseline)]) == 1
    # a grown run still passes (the floor is a minimum, not an equality)
    baseline.write_text(json.dumps({"dots_passed": 10}))
    assert main([str(log), "--baseline", str(baseline)]) == 0


def test_guard_rejects_empty_log(tmp_path):
    log = tmp_path / "empty.log"
    log.write_text("no progress lines here\n")
    assert main([str(log), "--baseline", str(tmp_path / "b.json")]) == 2


def test_committed_baseline_exists_and_is_sane():
    """The committed floor the CI comparison runs against."""
    path = os.path.join(REPO, "tests", "baseline_count.json")
    with open(path) as f:
        base = json.load(f)
    assert base["dots_passed"] >= 634  # the PR-3 tier-1 count on this box


def test_cli_entrypoint(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text(_LOG)
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"dots_passed": 1}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tier1_guard.py"),
         str(log), "--baseline", str(baseline)],
        capture_output=True, text=True)
    assert proc.returncode == 0
    assert "DOTS_PASSED=17" in proc.stdout
