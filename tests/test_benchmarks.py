"""Benchmarks-as-tests (SURVEY.md §4 item 5): the OSU sweep runs in smoke
mode under pytest — tiny sizes, assert completion and sane numbers; full
sweeps are the CLI."""

import numpy as np
import pytest

from benchmarks.osu import busbw_gbps, parse_size, parse_sizes, run_bench
from mpi_tpu.transport.local import run_local


def test_parse_size():
    assert parse_size("1024") == 1024
    assert parse_size("4KB") == 4096
    assert parse_size("2MB") == 2 << 20
    assert parse_size("1GB") == 1 << 30


def test_parse_sizes_sweep():
    assert parse_sizes("1KB:16KB:4") == [1024, 4096, 16384]
    assert parse_sizes("100,200") == [100, 200]
    with pytest.raises(ValueError):
        parse_sizes("1KB:1MB:1")


def test_busbw_convention():
    # allreduce: bytes * 2(P-1)/P / t  (NCCL convention, SURVEY.md §6)
    assert busbw_gbps("allreduce", 8 << 30, 8, 2.0) == pytest.approx(
        (8 << 30) * 1.75 / 2 / 1e9, rel=1e-6)
    assert busbw_gbps("allgather", 1 << 30, 4, 1.0) == pytest.approx(0.75 * (1 << 30) / 1e9)
    assert busbw_gbps("bcast", 10**9, 4, 1.0) == pytest.approx(1.0)


def test_overlap_local_smoke():
    """The overlap bench's row schema on the local backend: percentages
    in range, the fixed compute target recorded, the progress mode
    labeled (local backend without enable = none)."""
    rows = run_bench("overlap", "local", 2, [4096], None, iters=2, warmup=0)
    assert rows, "no overlap rows"
    for r in rows:
        assert r["bench"] == "overlap" and r["progress"] == "none"
        assert 0.0 <= r["overlap_pct"] <= 100.0
        assert 0.0 <= r["availability_pct"] <= 100.0
        assert r["compute_target_us"] >= 200.0
        assert np.isfinite(r["pure_us"]) and r["pure_us"] > 0


def test_persist_local_smoke():
    """The persistent-collective bench's row schema on the local
    backend (ISSUE 12): fresh and re-fire columns positive, the
    dispatch mode stamped, and the re-fires counted by the
    ``persistent_starts`` pvar."""
    from mpi_tpu import mpit

    base = mpit.pvar_read("persistent_starts")
    rows = run_bench("persist", "local", 2, [1024], None, iters=2, warmup=0)
    assert rows, "no persist rows"
    for r in rows:
        assert r["bench"] == "persist" and r["nbc"] in ("auto", "thread")
        assert r["progress"] in ("none", "thread")
        assert r["fresh_us"] > 0 and r["refire_us"] > 0
        assert r["p50_us"] == r["refire_us"]
        assert np.isfinite(r["refire_speedup"]) and r["refire_speedup"] > 0
    # 2 ranks x (1 warm + 2 measured) starts
    assert mpit.pvar_read("persistent_starts") - base == 6


@pytest.mark.parametrize("bench", ["latency", "allreduce", "allgather", "alltoall",
                                   "reduce_scatter"])
def test_local_smoke(bench):
    algos = {"latency": None, "allreduce": ["ring", "rabenseifner"],
             "allgather": ["ring"], "alltoall": ["pairwise"],
             "reduce_scatter": ["ring"]}[bench]
    rows = run_bench(bench, "local", 4, [1024], algos, iters=3, warmup=1)
    rows = [r for r in rows if "skipped" not in r]
    assert rows, "no benchmark rows produced"
    if algos:
        assert {r["algorithm"] for r in rows} == set(algos)
    for r in rows:
        assert r["p50_us"] > 0
        assert np.isfinite(r["p50_us"])


def test_host_sweep_quick_smoke():
    """The OSU host sweep harness end to end in --quick mode (the
    ``bench.py --sweep --quick`` CI spelling): real launcher-spawned rank
    processes on BOTH transports, every swept bench present, and the
    crossover derivations run over the measured rows — so the sweep
    can't bit-rot between perf PRs."""
    from benchmarks import host_sweep

    result = host_sweep.run_sweep("smoke", quick=True)
    assert result["quick"] and result["nranks"] == 2
    for key in ("allreduce_rows", "alltoall_rows", "reduce_scatter_rows"):
        rows = [r for r in result[key] if "p50_us" in r]
        assert {r["backend"] for r in rows} == {"socket", "shm"}, (key, rows)
        for r in rows:
            assert r["p50_us"] > 0 and np.isfinite(r["p50_us"])
    # all three allreduce algorithms measured (rabenseifner exists now)
    assert {r["algorithm"] for r in result["allreduce_rows"]
            if "p50_us" in r} == {"ring", "recursive_halving", "rabenseifner"}
    assert set(result["crossover"]) == {"socket", "shm"}
    assert set(result["rabenseifner_crossover"]) == {"socket", "shm",
                                                    "combined_bytes"}
    # ISSUE 4 satellites: the small-message band (osu_latency /
    # osu_barrier / small allreduce — the arena's artifact legs) rode
    # along, and every result row is oversubscription-stamped
    small = [r for r in result["small_message_rows"] if "p50_us" in r]
    assert {r["leg"] for r in small} == {"osu_latency", "osu_barrier",
                                         "osu_allreduce"}
    assert {r["backend"] for r in small} == {"socket", "shm"}
    # ISSUE 6 satellite: the compute/comm overlap leg rode along, under
    # BOTH progress modes on both host transports, with sane percentages
    ov = [r for r in result["overlap_rows"] if "overlap_pct" in r]
    assert {r["backend"] for r in ov} == {"socket", "shm"}
    assert {r["progress"] for r in ov} == {"none", "thread"}
    for r in ov:
        assert 0.0 <= r["overlap_pct"] <= 100.0, r
        assert 0.0 <= r["availability_pct"] <= 100.0, r
        assert r["pure_us"] > 0 and r["compute_us"] > 0
    # ISSUE 12 satellite: the persistent-collective leg rode along on
    # both transports — fresh vs re-fire columns populated, dispatch
    # mode stamped (the sweep runs the shipping nbc=auto side)
    pe = [r for r in result["persist_rows"] if "refire_us" in r]
    assert {r["backend"] for r in pe} == {"socket", "shm"}
    for r in pe:
        assert r["progress"] == "thread" and r["nbc"] == "auto", r
        assert r["fresh_us"] > 0 and r["refire_us"] > 0
        assert np.isfinite(r["refire_speedup"])
    assert "oversubscribed" in result
    for key in ("allreduce_rows", "small_message_rows", "overlap_rows",
                "persist_rows"):
        for r in result[key]:
            if "p50_us" in r:
                assert isinstance(r["oversubscribed"], bool), r


def test_tune_quick_smoke():
    """The tuned-dispatch sweep generator end to end in --quick mode
    (the ``bench.py --tune --quick`` CI spelling): real launcher-spawned
    ranks on both host transports, every grid collective measured
    (including the arena 'sm' leg on shm), the emitted document passes
    the same strict validation tools/tune.py --check enforces, and
    every row is trust-stamped from its leg's oversubscription."""
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    try:
        import tune
    finally:
        sys.path.pop(0)
    from mpi_tpu import tuning

    doc = tune.sweep(quick=True)
    rows = tuning.validate(doc)  # raises on any malformation
    assert rows, "quick sweep emitted no rows"
    cells = {(r.transport, r.collective) for r in rows}
    for t in ("socket", "shm"):
        for coll in ("allreduce", "reduce_scatter", "alltoall"):
            assert (t, coll) in cells, (t, coll, cells)
    for r in rows:
        assert r.nranks == 2
        assert isinstance(r.extra["p50_us"], dict) and r.extra["p50_us"]
        assert all(v > 0 for v in r.extra["p50_us"].values())
        assert r.extra["seed"] in tuning.KNOWN_ALGORITHMS[r.collective]
    # the arena rode the shm legs as a measured algorithm
    shm_allreduce = [r for r in rows
                    if (r.transport, r.collective) == ("shm", "allreduce")]
    assert any("sm" in r.extra["p50_us"] for r in shm_allreduce)
    assert doc["generated"]["oversubscribed"] == (3 > (os.cpu_count() or 1))


def test_chaos_quick_smoke():
    """The chaos harness end to end in --quick mode (the ``bench.py
    --chaos --quick`` CI spelling): FaultyTransport drop/delay/duplicate
    over the collective family — every cell completes or fails
    DIAGNOSABLY (no hangs), and the injection pvars prove faults
    actually fired."""
    from benchmarks import chaos

    result = chaos.run_chaos(quick=True)
    assert result["ok"], result["hangs"]
    assert result["hangs"] == []
    assert result["cells"], "no chaos cells ran"
    for cell in result["cells"]:
        assert (cell["outcome"] in ("ok", "wrong_result")
                or cell["outcome"].startswith("diagnosed:")), cell
    assert result["injected"]["dropped"] >= 1
    assert result["injected"]["duplicated"] >= 1


def test_serve_chaos_quick_smoke():
    """The resident-pool chaos leg (ISSUE 7 satellite; the ``bench.py
    --chaos --serve --quick`` CI spelling): continuous SIGKILL against
    a live world server — every lease completes or raises a NAMED FT
    error, worlds/sec never reaches zero (every observation window
    completes >= 1 world), and the pool ends healed with the epoch
    advanced past every kill."""
    from benchmarks import chaos

    result = chaos.run_serve_chaos(quick=True)
    assert result["ok"], {k: result[k] for k in
                          ("unnamed_failures", "windows_completed",
                           "healed", "final_allreduce_ok", "kills")}
    assert result["kills"] >= 1
    assert result["completed_worlds"] >= 1
    assert all(w > 0 for w in result["windows_completed"])
    assert result["final_epoch"] >= 1
    assert result["unnamed_failures"] == []


def test_federation_chaos_quick_smoke():
    """The federated-serve kill-storm leg (ISSUE 15; the ``bench.py
    --chaos --federation --quick`` CI spelling): SIGKILL one of two
    ``launcher serve --federation`` servers under an open-loop fleet of
    concurrent connect() clients.  The acceptance contract: aggregate
    worlds/s never reaches zero in any window, every client-visible
    failure is a NAMED error, the dead server's orphaned workers
    re-register with the survivor (adopted pool visible, roll-up
    converges to full strength), the leader-authority log shows no
    split-brain overlap, and a final cross-server lease is correct."""
    from benchmarks import chaos

    result = chaos.run_federation_chaos(quick=True)
    assert result["ok"], {k: result.get(k) for k in
                          ("kills", "windows_completed",
                           "unnamed_failures", "healed_to_full_strength",
                           "adopted_pools_visible", "no_leader_overlap",
                           "final_cross_server_allreduce_ok",
                           "final_error", "leader_overlap_error")}
    assert result["kills"], "no server was killed"
    assert all(w > 0 for w in result["windows_completed"])
    assert result["unnamed_failures"] == []
    assert result["adopted_pools_visible"] >= 1
    assert result["orphans_reregistered_on_polled_server"] >= 1 or \
        result["healed_to_full_strength"]
    assert result["no_leader_overlap"]


def test_federation_partition_quick_smoke():
    """The consensus-tier partition leg (ISSUE 18; the ``bench.py
    --chaos --federation --partition --quick`` CI spelling): a 3-server
    federated fabric whose leases live in a replicated 3-node Raft
    store gets its raft leader isolated into a minority partition, then
    its serve leader SIGKILLed after heal.  The contract: the minority
    server refuses new leases with a NAMED NoQuorumError (never a stale
    grant), the majority side keeps electing and serving (no window
    hits zero), heal converges the log (truncated entries observed),
    and the leader-authority log shows no split-brain overlap."""
    from benchmarks import chaos

    result = chaos.run_federation_partition(quick=True)
    assert result["ok"], {k: result.get(k) for k in
                          ("kills", "windows_completed",
                           "unnamed_failures", "minority_probe",
                           "truncated_entries", "healed_to_full_strength",
                           "no_leader_overlap",
                           "final_cross_server_allreduce_ok",
                           "final_error", "leader_overlap_error")}
    assert result["minority_probe"]["refused_with_noquorum"]
    assert not result["minority_probe"]["stale_grant_succeeded"]
    assert result["truncated_entries"] > 0
    assert result["kills"], "no serve leader was killed post-heal"
    assert all(w > 0 for w in result["windows_completed"])
    assert result["unnamed_failures"] == []
    assert result["no_leader_overlap"]


def test_links_chaos_quick_smoke(tmp_path):
    """The link-fault chaos leg (ISSUE 10; the ``bench.py --chaos
    --links --quick`` CI spelling): connection resets — between frames
    AND mid-frame — hammered into a 3-rank socket world running a
    mixed-collective stream.  The contract: bit-identical per-rank
    digests vs an uninjected run, zero ProcFailedError, every reset
    healed by a counted reconnect (link_reconnects >= resets), and a
    genuine mid-run death under the SAME harness still surfaces
    MPI_ERR_PROC_FAILED within the detection bound — healing never
    masks real death.

    ISSUE 13 rides the same leg under the flight recorder
    (``--trace-dir``): the merged 3-rank Chrome trace must SHOW the
    injected fault story — reset → reconnect → replay — with aligned
    cross-rank timestamps (this is also the tier-1 wiring for the
    trace-export quick leg + the tools/tracecat.py merge)."""
    from benchmarks import chaos

    result = chaos.run_links_chaos(quick=True,
                                   trace_dir=str(tmp_path))
    assert result["ok"], {k: result[k] for k in
                          ("resets_injected", "link_reconnects",
                           "bit_parity_vs_uninjected",
                           "zero_proc_failed", "kill_still_diagnosed",
                           "injected", "kill")}
    assert result["resets_injected"] >= 6
    assert result["link_reconnects"] >= result["resets_injected"]
    assert result["bit_parity_vs_uninjected"]
    assert result["kill_still_diagnosed"]
    trace = result["trace"]
    assert trace["ranks"] == 3
    for evt in ("link.reset_injected", "link.reconnect", "link.replay",
                "link.heal"):
        assert trace["link_events"].get(evt, 0) >= 1, trace
    # the fault story is causally ordered on the merged timeline: the
    # replayed frames' send/recv matching yields sub-ms offsets with
    # no frame arriving before it was sent
    assert trace["coll_events"] > 0 and trace["frame_events"] > 0
    assert trace["negative_latency_frames"] == 0, trace


def test_hotpath_quick_smoke():
    """The zero-copy hot-path leg (ISSUE 11; the ``bench.py --hotpath
    --quick`` CI spelling): the socket allreduce under healing-off /
    eager-retain / zero-copy retention modes plus the lease-arena
    check.  The sharp acceptance is structural: retention bytes > 0
    with ZERO cow snapshots and payload_copies identical to the
    no-retention floor (link_bytes_retained decoupled from
    payload_copies), one vectored sendmsg per frame, and a lease
    allreduce showing coll_sm_hits > 0 on the SAME pooled arena across
    two leases."""
    from benchmarks import hotpath

    result = hotpath.run_hotpath(quick=True)
    assert result["ok"], {k: result[k] for k in
                          ("retention_without_copy", "lease_arena",
                           "healing_on_over_off_p50")}
    zc = result["legs"]["healing_on_zero_copy"]
    assert zc["pvars"]["link_bytes_retained"] > 0
    assert zc["pvars"]["link_cow_snapshots"] == 0
    assert zc["syscalls_per_frame"] <= 1.25
    assert result["legs"]["healing_off"]["pvars"][
        "link_bytes_retained"] == 0
    lease = result["lease_arena"]
    assert lease["coll_sm_hits_first"] > 0 and lease["arena_reused"]


def test_recvpool_shm_quick_smoke():
    """The zero-copy-everywhere band end to end in --quick mode (the
    ``bench.py --recvpool --shm --quick`` CI spelling): the pvar-carrying
    ``steer`` bench on BOTH host transports with steering on.  The
    structural acceptance rides the row pvars: the user-buffer
    rendezvous legs land IN PLACE (post-before-send handshake makes the
    match deterministic — zero pool fallbacks), the scatter-gather leg
    on socket reads multi-segment frames with vectored syscalls, and
    no leg anywhere pays a pool-stage payload copy."""
    from benchmarks import host_sweep

    result = host_sweep.run_recvpool_shm_sweep("post", quick=True)
    assert result["quick"] and result["nranks"] == 2
    rows = [r for r in result["recvpool_shm_rows"] if "p50_us" in r]
    assert {(r["backend"], r["leg"]) for r in rows} == {
        (b, leg) for b in ("socket", "shm")
        for leg in ("allreduce_ring", "user_irecv", "scatter_gather")}
    for r in rows:
        assert r["bench"] == "steer" and r["recv_steering"] == 1
        assert r["p50_us"] > 0 and np.isfinite(r["p50_us"])
        pv = r["pvars"]
        assert pv["payload_copies"] == 0, r
        if r["leg"] == "user_irecv":
            assert pv["recv_user_inplace"] >= 1, r
            assert pv["recv_user_fallbacks"] == 0, r
            assert pv["recv_bytes_steered"] >= r["bytes"], r
        if r["leg"] == "scatter_gather":
            assert pv["recv_user_inplace"] >= 1, r
            assert pv["recv_bytes_steered"] >= r["bytes"], r
            if r["backend"] == "socket":
                assert pv["link_recv_syscalls"] >= 1, r
        if r["leg"] == "allreduce_ring":
            assert pv["recv_bytes_steered"] > 0, r


def test_serve_bench_quick_smoke():
    """The world-churn harness end to end in --quick mode (the
    ``bench.py --serve-bench --quick`` CI spelling): cold launch() vs
    resident-pool leases on the same job, asserting the acceptance
    ratio — a warm world-acquire must beat a cold fork+handshake by
    >= 10x at p99 (measured ~4000x on this box; 10x holds under any
    plausible load)."""
    from benchmarks import serve_bench

    cold = serve_bench.cold_leg(2, "socket")
    warm = serve_bench.serve_leg(10, "socket")
    assert cold["worlds"] == 2 and warm["worlds"] == 10
    assert warm["server_stats"]["jobs_ok"] == 10
    assert warm["acquire"]["p99_ms"] * 10 < cold["acquire"]["p99_ms"], (
        warm["acquire"], cold["acquire"])


@pytest.mark.parametrize("bench", ["allreduce", "bcast", "alltoall"])
def test_tpu_smoke(bench):
    algos = {"allreduce": ["ring", "fused"], "bcast": ["tree"],
             "alltoall": ["fused"]}[bench]
    rows = run_bench(bench, "tpu", 8, [1024], algos, iters=2, warmup=1)
    rows = [r for r in rows if "skipped" not in r]
    assert len(rows) == len(algos)
    for r in rows:
        assert r["p50_us"] > 0
        assert r["busbw_gbps"] >= 0


@pytest.mark.slow
def test_gen_baseline_quick_regenerates(tmp_path, monkeypatch):
    """The BASELINE.md generator runs its full matrix end-to-end in quick
    mode and renders every section (the no-hand-edited-numbers contract)."""
    import benchmarks.gen_baseline as gb

    monkeypatch.setattr(gb, "RESULTS", str(tmp_path))
    monkeypatch.setattr(gb, "JSONL", str(tmp_path / "baseline.jsonl"))
    rows = gb.measure(quick=True)
    ok = [r for r in rows if "error" not in r and "skipped" not in r]
    assert len(ok) > 20, rows
    text = gb.render(rows, quick=True)
    for section in ("Ring vs recursive-halving", "Tree bcast / reduce",
                    "Allgather / alltoall", "latency + windowed bandwidth",
                    "North-star"):
        assert section in text
    # every backend family reported
    assert {r.get("backend") for r in ok} >= {"local", "tpu", "socket", "shm"}


def test_io_bench_smoke():
    """The IOR-style MPI-IO bench runs every pattern with sane
    bandwidths; the bench's read epochs assert content correctness
    themselves (own-record fill values; cross-rank clobbers fail)."""
    import benchmarks.io_bench as iob

    class A:
        sizes = [4096]
        blocks = 3
        iters = 1
        patterns = list(iob.PATTERNS)

    rows_by_rank = run_local(lambda c: iob.worker(c, A), 4)
    rows = rows_by_rank[0]
    assert len(rows) == 3
    for r in rows:
        assert r["write_gbps"] > 0 and r["read_gbps"] > 0
        assert r["nranks"] == 4


def test_compress_quick_smoke():
    """The compressed-collectives bench harness end to end in --quick
    mode (the ``bench.py --compress --quick`` CI spelling): real
    launcher-spawned rank processes on BOTH transports, every leg
    present, and the acceptance ratios hold at smoke size — bf16 raw
    bytes exactly half of ring's (same spans, 2 bytes/element; the
    committed 64MB artifacts show the same exact ratio), int8 about a
    quarter, zero pickled array bytes everywhere."""
    from benchmarks import compress_bench

    result = compress_bench.run(quick=True)
    assert result["quick"] and result["nranks"] == 2
    rows = result["rows"]
    assert {r["backend"] for r in rows} == {"socket", "shm"}
    assert {(r["bench"], r["algorithm"]) for r in rows} == set(
        compress_bench.LEGS)
    for r in rows:
        assert r["p50_us"] > 0 and np.isfinite(r["p50_us"])
        assert r["pickled_bytes_per_call"] == 0, r
        if r["algorithm"] != "ring":
            assert r["saved_bytes_per_call"] > 0, r
    for backend, ratios in result["allreduce_raw_byte_ratio_vs_ring"].items():
        assert abs(ratios["compressed:bf16"] - 0.5) <= 0.05 * 0.5, ratios
        assert ratios["compressed:int8"] <= 0.27, ratios
        assert ratios["compressed:topk"] < 0.5, ratios
