"""Transport plugin boundary (L1) + the shared message-matching engine.

SURVEY.md §1/§2: the load-bearing seam of the reference is the Communicator
plugin boundary — collectives are written against Communicator, Communicators
own a swappable Transport.  A Transport moves opaque payloads between world
ranks and supports MPI-style matching by (source, context, tag) with FIFO
ordering per (src, dst) channel [S].

The matching engine (Mailbox) is shared by every CPU transport so matching
semantics — including wildcard rules — are identical across them:
* ANY_SOURCE matches any source rank.
* ANY_TAG matches only *user* tags (>= 0); internal negative tags (used by
  collectives/barrier, see mpi_tpu/communicator.py) must be matched exactly,
  so user wildcard receives can never steal collective traffic.
"""

from __future__ import annotations

import threading
import time
from abc import ABC, abstractmethod
from typing import Any, List, Optional, Tuple

ANY_SOURCE = -1
ANY_TAG = -1


class TransportError(RuntimeError):
    pass


class RecvTimeout(TransportError):
    pass


class Mailbox:
    """Thread-safe matching queue of (src, ctx, tag, payload) messages."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items: List[Tuple[int, int, int, Any]] = []
        self._closed = False

    def deliver(self, src: int, ctx: int, tag: int, payload: Any) -> None:
        with self._cv:
            self._items.append((src, ctx, tag, payload))
            self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @staticmethod
    def _matches(item, source: int, ctx, tag: int) -> bool:
        s, c, t, _ = item
        if c != ctx:
            return False
        if source != ANY_SOURCE and s != source:
            return False
        if tag == ANY_TAG:
            return t >= 0  # wildcards never match internal (negative) tags
        return t == tag

    def match(
        self, source: int, ctx, tag: int, timeout: Optional[float] = None
    ) -> Tuple[Any, int, int]:
        """Block until the oldest message matching (source, ctx, tag) arrives;
        return (payload, src, tag)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                for i, item in enumerate(self._items):
                    if self._matches(item, source, ctx, tag):
                        s, _, t, payload = self._items.pop(i)
                        return payload, s, t
                if self._closed:
                    raise TransportError(
                        f"transport closed while waiting for recv(source={source}, "
                        f"ctx={ctx}, tag={tag})"
                    )
                if deadline is None:
                    self._cv.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        pending = [(s, c, t) for s, c, t, _ in self._items[:16]]
                        raise RecvTimeout(
                            f"recv(source={source}, ctx={ctx}, tag={tag}) timed "
                            f"out after {timeout}s; pending={pending}"
                        )
                    self._cv.wait(remaining)

    def pending_summary(self) -> List[Tuple[int, int, int]]:
        with self._lock:
            return [(s, c, t) for s, c, t, _ in self._items[:16]]

    def drain(self) -> List[Tuple[int, int, int]]:
        """Return and clear all pending (src, ctx, tag) — used by the finalize
        'unexpected message' check (sanitizer analogue, SURVEY.md §5)."""
        with self._lock:
            items = [(s, c, t) for s, c, t, _ in self._items]
            self._items.clear()
            return items


class Transport(ABC):
    """Moves payloads between world ranks; owns a Mailbox for incoming traffic."""

    def __init__(self, world_rank: int, world_size: int) -> None:
        self.world_rank = world_rank
        self.world_size = world_size
        self.mailbox = Mailbox()

    @abstractmethod
    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        """Buffered (non-blocking w.r.t. the receiver) send to world rank
        ``dest``.  FIFO order per (self, dest) channel is guaranteed.
        ``ctx`` is any hashable communicator-context id (the tree-path tuples
        allocated by Communicator.split/dup — collision-free by construction)."""

    def recv(
        self, source: int, ctx, tag: int, timeout: Optional[float] = None
    ) -> Tuple[Any, int, int]:
        return self.mailbox.match(source, ctx, tag, timeout=timeout)

    def close(self) -> None:
        self.mailbox.close()
