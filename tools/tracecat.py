#!/usr/bin/env python
"""tracecat — merge per-rank flight-recorder traces onto ONE timeline.

Each rank of a traced run (``MPI_TPU_TRACE=1`` / launcher
``--trace-dir``) exports its own Chrome-trace JSON
(``trace.r<rank>.<pid>.json``, mpi_tpu/telemetry/recorder.py).  This
tool merges them so a 3-rank run renders as one Perfetto timeline —
rank per process row, thread per track — with **cross-rank clock
alignment** in two layers:

1. **Wall anchor** (coarse): every trace carries a ``(time_ns,
   perf_counter_ns)`` anchor pair taken at enable; export already maps
   monotonic timestamps onto the wall clock, which is shared on a
   single host up to the anchor-read jitter.
2. **Message matching** (fine, ``--no-align`` disables): the sequenced
   socket frames are recorded on BOTH ends (``frame send`` carries
   (dest, seq), ``frame recv`` carries (src, seq) — the resilient
   link layer's per-destination sequence numbers make the match
   exact).  For each rank pair, every matched frame gives a one-way
   bound on the clock offset (a frame cannot arrive before it was
   sent); the two directions bracket the offset and the midpoint is
   the classic round-trip estimate — the same offset the hello/
   heartbeat round-trips would give, computed post-hoc from events
   that already exist instead of a wire change.  Offsets are solved
   relative to the lowest rank across the connectivity graph and each
   rank's events are shifted by ITS constant — per-rank event order
   (monotonicity) is preserved by construction.

Usage::

    python tools/tracecat.py TRACE_DIR -o merged.json
    python tools/tracecat.py a.json b.json c.json -o merged.json
    python tools/tracecat.py TRACE_DIR --report        # offsets only
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

MERGED_DEFAULT = "merged.json"


def load_traces(paths: List[str]) -> List[dict]:
    """Expand directories to their per-rank trace files and parse.
    A merged output sitting in the same directory is skipped (it has
    no per-rank ``mpi_tpu`` metadata — and re-merging a merge would
    double events)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "trace.r*.json"))))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no trace files under {paths!r}")
    docs = []
    for f in files:
        with open(f) as fh:
            doc = json.load(fh)
        if "pid" not in doc.get("mpi_tpu", {}):
            # not a per-rank flight-recorder export: a merged output's
            # own mpi_tpu block carries merge metadata, never a pid —
            # re-merging a merge would double events
            continue
        doc["_path"] = f
        docs.append(doc)
    if not docs:
        raise ValueError(f"no flight-recorder traces among {files!r}")
    return docs


def _rank_of(doc: dict):
    r = doc["mpi_tpu"].get("rank")
    return doc["mpi_tpu"]["pid"] if r is None else r


def _frame_endpoints(doc: dict) -> Tuple[Dict, Dict]:
    """(sends, recvs) of this rank's frame events, keyed by the
    globally unique (src_rank, dst_rank, seq) triple."""
    me = _rank_of(doc)
    sends: Dict[Tuple, float] = {}
    recvs: Dict[Tuple, float] = {}
    for e in doc["traceEvents"]:
        if e.get("cat") != "frame":
            continue
        a = e.get("args") or {}
        if e.get("name") == "send" and "seq" in a:
            sends[(me, a.get("dest"), a["seq"])] = e["ts"]
        elif e.get("name") == "recv" and "seq" in a:
            recvs[(a.get("src"), me, a["seq"])] = e["ts"]
    return sends, recvs


def estimate_offsets(docs: List[dict]) -> Dict:
    """Per-rank clock offsets (microseconds, added to that rank's
    timestamps) from matched frame send/recv pairs, solved relative to
    the lowest rank.  Ranks with no usable message path to the
    reference keep offset 0 (the wall anchor already landed them
    close)."""
    ranks = [_rank_of(d) for d in docs]
    if len(set(ranks)) != len(ranks):
        # two process generations share a rank id (serve workers and
        # relaunched worlds export into one dir, pid-suffixed): their
        # clocks AND seq spaces alias, so message matching would pair
        # frames across unrelated runs — keep the wall anchors only
        sys.stderr.write("tracecat: duplicate rank ids across traces; "
                         "skipping message-matching alignment\n")
        return {r: 0.0 for r in ranks}
    ends = {_rank_of(d): _frame_endpoints(d) for d in docs}
    # pairwise bounds: d[a][b] = off_b - off_a bracketed by [lo, hi]
    bounds: Dict[Tuple, List[Optional[float]]] = {}
    for a in ranks:
        sends_a, _ = ends[a]
        for (src, dst, seq), ts_send in sends_a.items():
            if dst not in ends:
                continue
            ts_recv = ends[dst][1].get((src, dst, seq))
            if ts_recv is None:
                continue
            # recv_ts + off_dst >= send_ts + off_src
            #   => (off_dst - off_src) >= send_ts - recv_ts
            key = (min(a, dst), max(a, dst))
            lo_hi = bounds.setdefault(key, [None, None])
            gap = ts_send - ts_recv
            if a == key[0]:  # bound on off_hi - off_lo from lo->hi
                if lo_hi[0] is None or gap > lo_hi[0]:
                    lo_hi[0] = gap
            else:            # reverse direction bounds it from above
                if lo_hi[1] is None or -gap < lo_hi[1]:
                    lo_hi[1] = -gap
    pair_est: Dict[Tuple, float] = {}
    for (a, b), (lo, hi) in bounds.items():
        if lo is not None and hi is not None:
            pair_est[(a, b)] = (lo + hi) / 2.0
        elif lo is not None:
            pair_est[(a, b)] = lo
        elif hi is not None:
            pair_est[(a, b)] = hi
    # BFS the pair graph from the reference rank (midpoint seed) ...
    offsets: Dict = {r: 0.0 for r in ranks}
    if pair_est:
        ref = min(ranks)
        seen = {ref}
        frontier = [ref]
        while frontier:
            cur = frontier.pop()
            for (a, b), d in pair_est.items():
                for nxt, sign, anchor in ((b, 1.0, a), (a, -1.0, b)):
                    if anchor == cur and nxt not in seen:
                        offsets[nxt] = offsets[cur] + sign * d
                        seen.add(nxt)
                        frontier.append(nxt)
        # ... then alternating projection onto the hard bounds: pair
        # midpoints need not be consistent around a triangle (loaded-
        # box delivery latency is asymmetric), but the TRUE offsets
        # satisfy every [lo, hi] bracket simultaneously (each bound is
        # a matched frame's arithmetic), so the feasible set is a
        # nonempty convex polytope and projecting per-pair converges
        # into it — after which no aligned frame arrives before it was
        # sent.
        for _ in range(200):
            worst = 0.0
            for (a, b), (lo, hi) in bounds.items():
                d = offsets[b] - offsets[a]
                adj = 0.0
                if lo is not None and d < lo:
                    adj = lo - d
                elif hi is not None and d > hi:
                    adj = hi - d
                if adj:
                    offsets[b] += adj / 2.0
                    offsets[a] -= adj / 2.0
                    worst = max(worst, abs(adj))
            if worst < 1e-3:  # 1ns in us units
                break
        base = offsets[ref]
        for r in offsets:
            offsets[r] -= base  # the reference rank stays unshifted
    return offsets


def negative_latency_frames(docs: List[dict],
                            offsets: Dict) -> int:
    """Matched frames whose aligned recv still precedes their send —
    the alignment residual the report prints (0 is ideal; a handful at
    sub-ms scale is scheduler jitter on an oversubscribed box)."""
    ends = {_rank_of(d): _frame_endpoints(d) for d in docs}
    bad = 0
    for a, (sends, _) in ends.items():
        for (src, dst, seq), ts_send in sends.items():
            peer = ends.get(dst)
            if peer is None:
                continue
            ts_recv = peer[1].get((src, dst, seq))
            if ts_recv is None:
                continue
            if ts_recv + offsets.get(dst, 0.0) \
                    < ts_send + offsets.get(a, 0.0):
                bad += 1
    return bad


def merge(docs: List[dict], align: bool = True) -> dict:
    """One merged Chrome-trace document: per-rank offsets applied,
    events sorted by aligned timestamp, per-rank metadata preserved
    under ``mpi_tpu.ranks``."""
    offsets = estimate_offsets(docs) if align else {}
    events: List[dict] = []
    for doc in docs:
        off = offsets.get(_rank_of(doc), 0.0)
        for e in doc["traceEvents"]:
            if "ts" in e:
                e = dict(e)
                e["ts"] = e["ts"] + off
            events.append(e)
    events.sort(key=lambda e: e.get("ts", -1.0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "mpi_tpu": {
            "merged_from": [d["_path"] for d in docs],
            "aligned": bool(align),
            "offsets_us": {str(r): round(o, 3)
                           for r, o in offsets.items()},
            "negative_latency_frames": negative_latency_frames(
                docs, offsets),
            "ranks": {str(_rank_of(d)): d["mpi_tpu"] for d in docs},
        },
    }


def merge_paths(paths: List[str], out: str, align: bool = True) -> dict:
    """Library entry (benchmarks/chaos.py, tests): load + merge +
    write; returns the merged document."""
    doc = merge(load_traces(paths), align=align)
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+",
                    help="trace dir(s) and/or per-rank trace files")
    ap.add_argument("-o", "--out", default=None,
                    help=f"merged output (default: <first dir>/"
                         f"{MERGED_DEFAULT})")
    ap.add_argument("--no-align", action="store_true",
                    help="skip message-matching offset refinement "
                         "(keep the wall-clock anchors only)")
    ap.add_argument("--report", action="store_true",
                    help="print the alignment report, write nothing")
    args = ap.parse_args(argv)
    docs = load_traces(args.paths)
    if args.report:
        offsets = estimate_offsets(docs)
        print(json.dumps({
            "traces": [d["_path"] for d in docs],
            "offsets_us": {str(r): round(o, 3)
                           for r, o in offsets.items()},
            "negative_latency_frames": negative_latency_frames(
                docs, offsets),
        }, indent=2))
        return 0
    out = args.out
    if out is None:
        first = args.paths[0]
        base = first if os.path.isdir(first) else os.path.dirname(first)
        out = os.path.join(base, MERGED_DEFAULT)
    doc = merge(docs, align=not args.no_align)
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    meta = doc["mpi_tpu"]
    print(f"tracecat: merged {len(meta['ranks'])} rank trace(s), "
          f"{len(doc['traceEvents'])} events -> {out}")
    if meta["aligned"]:
        print(f"tracecat: offsets_us={meta['offsets_us']} "
              f"negative_latency_frames="
              f"{meta['negative_latency_frames']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
