"""Elastic membership: epochs, the rendezvous service, and rejoin.

PR 3's ULFM layer can detect, revoke, and ``shrink()`` a failure, but a
shrunken world could never grow back — a rank death was terminal for its
slot.  This module is the grow-back half (the MPICH / Open MPI "elastic
recovery" shape, SURVEY §5): membership changes become **epoch
transitions**.

* Every world carries a monotone **membership epoch**
  (``Transport.epoch``, surfaced as ``comm.membership_epoch``).  It
  starts at 0; ``shrink()`` bumps it in survivor lockstep (the bump
  rides the shrink agreement, so every survivor lands on the same
  number while the ousted rank — which raised inside shrink — stays on
  the old one).
* The epoch is **stamped into every transport hello**: the socket
  connection handshake carries (rank, epoch) and answers with the
  acceptor's epoch; the shm readiness file *contains* the epoch its
  rings were created under.  A stale-epoch straggler — the
  falsely-suspected live rank of FT residual (b) — is therefore
  rejected LOUDLY (:class:`~mpi_tpu.errors.EpochSkewError`) instead of
  cross-wiring two world generations through recycled rendezvous files.
* A **rejoin protocol** on the rendezvous dir lets a fresh process fill
  a vacant slot under the next epoch:

  1. the survivors (``comm.accept_rejoin()``, collective on the
     shrunken communicator) or the resident world server
     (mpi_tpu/serve.py) write an *announce* file
     ``rejoin.<epoch>.json`` listing the vacant slots;
  2. a joiner (:func:`rejoin` — module-level: a fresh process has no
     communicator yet) *claims* a slot with an atomic ``O_EXCL`` create
     naming its incarnation id;
  3. the announcer validates claims — an ousted-but-LIVE incarnation
     (the false suspicion) is **refused** until its failure was
     ``failure_ack``ed (:class:`~mpi_tpu.errors.RejoinRefusedError` on
     the claimer; re-admitting it would resurrect the split) — and
     *admits* the rest; a claimer that died mid-handshake (dead pid, no
     readiness) has its claim cleared so the slot can be re-claimed
     (no epoch fork);
  4. the admitted joiner creates FRESH transport endpoints stamped with
     the new epoch (the socket port file / shm rings + readiness are
     atomically re-published over the corpse's), publishes *ready*, and
     both sides build the full-world communicator under context
     ``("epoch", E)`` and barrier.

The rendezvous-dir helpers at the bottom (:func:`new_rendezvous_dir`,
:func:`cleanup_rendezvous`) are the launcher's former private plumbing,
refactored here so the launcher, the resident world server, and tests
share ONE membership service (ROADMAP direction #1's unlocking
refactor).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import uuid
from typing import Dict, Optional, Sequence, Tuple

from . import mpit as _mpit
from . import telemetry as _telemetry
from .errors import EpochSkewError, RejoinRefusedError  # noqa: F401 (re-export)
from .transport.base import Transport, TransportError

# Default bound on a rejoin handshake (claim -> admit -> endpoints ->
# ready -> barrier) for BOTH sides.  mpit cvar: rejoin_timeout_s.
_REJOIN_TIMEOUT_S = 30.0

_POLL_S = 0.01  # rendezvous-file poll cadence (cheap stat/read)

# Per-process incarnation id: the identity a claim presents.  ONE per
# process (not per call): a falsely-suspected live rank re-claiming its
# slot must present the SAME identity it was ousted under, so the
# survivors can refuse it until failure_ack — a fresh uuid per call
# would let the ousted process sneak back in as a "new" worker.
_PROCESS_INCARNATION: Optional[str] = None


def incarnation() -> str:
    global _PROCESS_INCARNATION
    if _PROCESS_INCARNATION is None:
        _PROCESS_INCARNATION = uuid.uuid4().hex
    return _PROCESS_INCARNATION


# -- small atomic-file helpers ------------------------------------------------


def _write_json(path: str, obj: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # absent / mid-replace: caller re-polls


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - exists, not ours
        return True
    return True


# -- incarnation registry -----------------------------------------------------


def publish_incarnation(rdv_dir: str, rank: int,
                        inc: Optional[str] = None) -> str:
    """Record which incarnation currently holds world slot ``rank``
    (file ``inc.<rank>``) — what accept_rejoin reads to know WHO was
    ousted, so the refusal gate can tell the corpse's identity from a
    fresh replacement's."""
    inc = inc or incarnation()
    _write_json(os.path.join(rdv_dir, f"inc.{rank}"),
                {"incarnation": inc, "pid": os.getpid()})
    return inc


def read_incarnation(rdv_dir: str, rank: int) -> Optional[str]:
    rec = _read_json(os.path.join(rdv_dir, f"inc.{rank}"))
    return rec.get("incarnation") if rec else None


def heartbeat_age(rdv_dir: str, rank: int,
                  now: Optional[float] = None) -> Optional[float]:
    """Age (seconds) of slot ``rank``'s FT heartbeat file under the
    rendezvous dir, or None when it was never published.  The liveness
    read every membership AUTHORITY shares — the resident world server
    for its own pool, and (ISSUE 15) a federation survivor judging the
    workers of a pool it adopted, whose processes were never its
    children (no Popen handle to poll): the heartbeat file is the one
    liveness signal that survives a change of ownership."""
    try:
        st = os.stat(os.path.join(rdv_dir, f"hb.{rank}"))
    except OSError:
        return None
    return (time.time() if now is None else now) - st.st_mtime


# -- announce / claim / admit / ready protocol files --------------------------


def _announce_path(rdv: str, epoch: int) -> str:
    return os.path.join(rdv, f"rejoin.{epoch}.json")


def announce_rejoin(rdv_dir: str, epoch: int, slots: Dict[int, dict],
                    size: int, backend: str) -> None:
    """Write the vacancy announcement for ``epoch``.  ``slots`` maps
    vacant world rank -> {"ousted": incarnation-or-None, "acked": bool};
    ``size``/``backend`` let a bare joiner (only MPI_TPU_RDV in hand)
    construct the right transport."""
    _write_json(_announce_path(rdv_dir, epoch), {
        "epoch": int(epoch), "size": int(size), "backend": backend,
        "slots": {str(s): dict(meta) for s, meta in slots.items()},
    })


def read_announce(rdv_dir: str, epoch: int) -> Optional[dict]:
    return _read_json(_announce_path(rdv_dir, epoch))


def latest_announce(rdv_dir: str) -> Optional[dict]:
    """Newest (highest-epoch) announcement in the rendezvous dir."""
    best = None
    try:
        names = os.listdir(rdv_dir)
    except OSError:
        return None
    for name in names:
        if name.startswith("rejoin.") and name.endswith(".json"):
            rec = _read_json(os.path.join(rdv_dir, name))
            if rec and (best is None or rec["epoch"] > best["epoch"]):
                best = rec
    return best


def _claim_path(rdv: str, epoch: int, slot: int) -> str:
    return os.path.join(rdv, f"claim.{epoch}.{slot}")


def claim_slot(rdv_dir: str, epoch: int, slot: int,
               inc: Optional[str] = None,
               pid: Optional[int] = None) -> bool:
    """Atomically claim a vacant slot (``O_EXCL`` create): exactly one
    claimer wins; a double-claim (including a double-REJOIN of the same
    worker id against a stale announce) fails cleanly."""
    inc = inc or incarnation()
    try:
        fd = os.open(_claim_path(rdv_dir, epoch, slot),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
    except FileExistsError:
        return False
    with os.fdopen(fd, "w") as f:
        json.dump({"incarnation": inc,
                   "pid": int(pid if pid is not None else os.getpid())}, f)
    return True


def read_claim(rdv_dir: str, epoch: int, slot: int) -> Optional[dict]:
    return _read_json(_claim_path(rdv_dir, epoch, slot))


def _admit_path(rdv: str, epoch: int, slot: int) -> str:
    return os.path.join(rdv, f"admit.{epoch}.{slot}")


def _refused_path(rdv: str, epoch: int, slot: int) -> str:
    return os.path.join(rdv, f"refused.{epoch}.{slot}")


def _ready_path(rdv: str, epoch: int, slot: int) -> str:
    return os.path.join(rdv, f"ready.{epoch}.{slot}")


def publish_ready(rdv_dir: str, epoch: int, slot: int,
                  inc: Optional[str] = None) -> None:
    _write_json(_ready_path(rdv_dir, epoch, slot),
                {"incarnation": inc or incarnation(),
                 "pid": os.getpid()})


def process_claims(rdv_dir: str, epoch: int, slots: Dict[int, dict],
                   acked_extra: Sequence[int] = ()) -> None:
    """One validation pass over the claims of ``epoch`` — the
    announcer-side step (rank-0 survivor in accept_rejoin, or the
    resident world server), run every poll tick:

    * a claim presenting the OUSTED incarnation of an un-acked slot is
      REFUSED (written to ``refused.<epoch>.<slot>`` and the claim
      cleared, so a legitimate replacement can claim): re-admitting a
      falsely-suspected-but-live rank before ``failure_ack`` would
      resurrect the very group split the epoch protocol prevents;
    * a claimer that DIED mid-handshake (claim present, readiness
      absent, pid gone) has its claim + admit cleared — the pool
      recovers by re-claiming, no epoch fork;
    * every other claim is ADMITTED (``admit.<epoch>.<slot>`` names the
      admitted incarnation; the joiner waits on it before touching any
      endpoint file, so a refused claimer can never trash the real
      replacement's rendezvous files).
    """
    acked_extra = set(acked_extra)
    for slot, meta in slots.items():
        slot = int(slot)
        claim = read_claim(rdv_dir, epoch, slot)
        if claim is None:
            continue
        inc, pid = claim.get("incarnation"), claim.get("pid")
        dead = pid is not None and not _pid_alive(int(pid))
        ready = _read_json(_ready_path(rdv_dir, epoch, slot))
        handshaken = ready is not None and ready.get("incarnation") == inc
        if dead:
            # Killed during (claim -> ... -> ready) OR just after ready:
            # clear EVERYTHING — including a published readiness file —
            # so the slot can be re-claimed under the same epoch (the
            # announce stays valid, no epoch fork).  Leaving a dead
            # claimer's ready behind would wedge healing forever: a
            # respawned replacement's O_EXCL claim could never succeed.
            for p in (_claim_path(rdv_dir, epoch, slot),
                      _admit_path(rdv_dir, epoch, slot),
                      _ready_path(rdv_dir, epoch, slot)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            continue
        if handshaken:
            continue  # live and complete; nothing to validate
        ousted = meta.get("ousted")
        acked = bool(meta.get("acked")) or slot in acked_extra
        if ousted is not None and inc == ousted and not acked:
            _write_json(_refused_path(rdv_dir, epoch, slot), {
                "incarnation": inc,
                "reason": "suspected-but-live incarnation: re-admission "
                          "refused until its failure is acknowledged "
                          "(failure_ack)"})
            for p in (_claim_path(rdv_dir, epoch, slot),
                      _admit_path(rdv_dir, epoch, slot)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            continue
        admit = _read_json(_admit_path(rdv_dir, epoch, slot))
        if admit is None or admit.get("incarnation") != inc:
            _write_json(_admit_path(rdv_dir, epoch, slot),
                        {"incarnation": inc})


def wait_admitted(rdv_dir: str, epoch: int, slot: int, inc: str,
                  deadline: float) -> None:
    """Joiner-side: block until our claim is admitted (or refused)."""
    while True:
        admit = _read_json(_admit_path(rdv_dir, epoch, slot))
        if admit is not None and admit.get("incarnation") == inc:
            return
        refused = _read_json(_refused_path(rdv_dir, epoch, slot))
        if refused is not None and refused.get("incarnation") == inc:
            raise RejoinRefusedError(
                f"rejoin of slot {slot} at epoch {epoch} refused: "
                f"{refused.get('reason', 'unspecified')}")
        if time.monotonic() > deadline:
            raise TransportError(
                f"rejoin claim for slot {slot} (epoch {epoch}) not "
                f"admitted in time")
        time.sleep(_POLL_S)


def wait_ready(rdv_dir: str, epoch: int, slots: Dict[int, dict],
               deadline: float, validate: bool = False) -> None:
    """Announcer/survivor-side: block until EVERY vacant slot's
    replacement published readiness.  With ``validate`` (the announcer:
    rank-0 survivor or the server) each tick also runs the claim
    validation pass — refusals, dead-claimer cleanup, admissions."""
    pending = {int(s) for s in slots}
    while pending:
        if validate:
            process_claims(rdv_dir, epoch, slots)
        for s in list(pending):
            if _read_json(_ready_path(rdv_dir, epoch, s)) is not None:
                pending.discard(s)
        if not pending:
            return
        if time.monotonic() > deadline:
            raise TransportError(
                f"rejoin at epoch {epoch}: slots {sorted(pending)} "
                f"published no replacement in time")
        time.sleep(_POLL_S)


# -- transport-level transitions ----------------------------------------------


def make_transport(backend: str, rank: int, size: int, rdv_dir: str,
                   epoch: int = 0) -> Transport:
    """Construct a process-world transport for ``rank`` with fresh
    endpoints stamped at ``epoch`` (the one constructor the launcher
    init path, rejoin, and the world server all share)."""
    if backend == "socket":
        from .transport.socket import SocketTransport

        return SocketTransport(rank, size, rdv_dir, epoch=epoch)
    if backend == "shm":
        from .transport.shm import ShmTransport

        return ShmTransport(rank, size, rdv_dir, epoch=epoch)
    raise ValueError(f"unknown process-world backend {backend!r} "
                     f"(accepted: socket, shm)")


def survivor_transition(transport: Transport, epoch: int,
                        dead: Sequence[int]) -> None:
    """Apply an epoch transition on a surviving rank's transport: adopt
    the new epoch, require replaced slots to present it (their corpse's
    leftover endpoints become unreachable), drop cached connections/
    rings to them, and (shm) re-stamp our readiness so stale stragglers
    doing fresh opens read the skew.

    RESUME vs REJOIN (ISSUE 10): the socket link layer's resume
    handshake (mpi_tpu/resilience.py — replay unacked frames over a
    rebuilt connection) heals faults WITHIN one membership epoch: same
    incarnation, same streams.  An epoch transition is the boundary
    where resume must NOT happen — the replaced slot's replacement is a
    different incarnation with fresh streams, so membership_invalidate
    purges the per-dest resilience state (retained replay window, seq
    counters, delivery marks) along with the connections.  A stale
    incarnation attempting to resume across the boundary is already
    rejected by the epoch-checked hello (min_peer_epoch / EpochSkew),
    and the purge guarantees the survivor offers a rejoiner
    ``resume(0)`` — never the corpse's replay."""
    rec = _telemetry.REC
    if rec is not None:
        rec.emit("ft", "epoch_bump",
                 attrs={"epoch": int(epoch), "dead": list(map(int, dead))})
    transport.epoch = max(transport.epoch, int(epoch))
    for d in dead:
        transport.min_peer_epoch[int(d)] = int(epoch)
    transport.membership_invalidate(list(dead))
    republish = getattr(transport, "membership_republish", None)
    if republish is not None:
        republish()


# -- the joiner (fresh process) ----------------------------------------------


def rejoin_transport(rdv_dir: str, slot: Optional[int] = None,
                     epoch: Optional[int] = None,
                     backend: Optional[str] = None,
                     timeout: Optional[float] = None
                     ) -> Tuple[Transport, dict]:
    """Claim a vacant slot and bring up epoch-stamped endpoints for it;
    returns (transport, announce).  The communicator-building half
    lives in :func:`rejoin`; the resident world server's replacement
    workers use this directly (their lease communicators are built per
    job, no full-world barrier needed)."""
    timeout = _REJOIN_TIMEOUT_S if timeout is None else timeout
    deadline = time.monotonic() + timeout
    inc = incarnation()
    ann = None
    while True:
        ann = (read_announce(rdv_dir, epoch) if epoch is not None
               else latest_announce(rdv_dir))
        if ann is not None:
            break
        if time.monotonic() > deadline:
            raise TransportError(
                f"rejoin: no vacancy announcement in {rdv_dir} "
                f"(epoch={'latest' if epoch is None else epoch})")
        time.sleep(_POLL_S)
    claimed = None
    while claimed is None:
        e = int(ann["epoch"])
        size = int(ann["size"])
        backend = backend or ann.get("backend") or "socket"
        candidates = ([int(slot)] if slot is not None
                      else sorted(int(s) for s in ann["slots"]))
        for s in candidates:
            ready = _read_json(_ready_path(rdv_dir, e, s))
            if ready is not None and ready.get("incarnation") == inc:
                raise RejoinRefusedError(
                    f"double rejoin: this incarnation already holds "
                    f"slot {s} at epoch {e}")
            if claim_slot(rdv_dir, e, s, inc=inc):
                claimed = s
                break
        if claimed is None:
            # every candidate claimed by someone else right now; a
            # refused/dead claimer may free one — poll until deadline.
            if time.monotonic() > deadline:
                raise TransportError(
                    f"rejoin: no claimable slot at epoch {e} "
                    f"(candidates {candidates})")
            time.sleep(_POLL_S)
            if epoch is None:
                # RE-READ the announcement each round: a completed
                # earlier heal leaves its (fully-claimed) announce
                # behind, and a NEWER vacancy published mid-wait must
                # not be missed until the deadline
                ann = latest_announce(rdv_dir) or ann
    wait_admitted(rdv_dir, e, claimed, inc, deadline)
    # ONLY an admitted claimer may touch endpoint files: construct the
    # transport (socket: bind + atomically re-publish port.<slot>; shm:
    # recreate rings + doorbell, readiness stamped with the epoch)
    t = make_transport(backend, claimed, size, rdv_dir, epoch=e)
    # require EVERY peer to have transitioned to our epoch before we
    # adopt its endpoints: on shm a survivor RECREATES its inbound
    # rings from our slot during survivor_transition (the corpse may
    # have died mid-frame into them), and re-stamps its readiness with
    # the new epoch only afterwards — opening earlier could append our
    # first frames to the corpse's desynced byte stream.  Socket
    # satisfies this trivially (survivors bumped their epoch at
    # shrink/transition, so their hello-acks already carry it).
    for p in range(size):
        if p != claimed:
            t.min_peer_epoch[p] = e
    publish_incarnation(rdv_dir, claimed, inc)
    return t, ann


def rejoin(rdv_dir: Optional[str] = None, slot: Optional[int] = None,
           epoch: Optional[int] = None, backend: Optional[str] = None,
           timeout: Optional[float] = None,
           recv_timeout: Optional[float] = None):
    """Joiner-side entry point of the rejoin protocol: run from a FRESH
    process (``rdv_dir`` defaults to the launcher's MPI_TPU_RDV), it
    claims a vacant slot from the newest announcement, brings up
    endpoints under the announced epoch, enables fault tolerance (and
    the verifier, when MPI_TPU_VERIFY is set), publishes readiness, and
    returns the FULL-SIZE world communicator — rendezvousing with the
    survivors' ``comm.accept_rejoin()`` barrier."""
    from . import ft as _ft
    from .communicator import P2PCommunicator

    rdv_dir = rdv_dir or os.environ.get("MPI_TPU_RDV")
    if rdv_dir is None:
        raise ValueError("rejoin needs a rendezvous dir: pass rdv_dir= "
                         "or set MPI_TPU_RDV")
    timeout = _REJOIN_TIMEOUT_S if timeout is None else timeout
    t, ann = rejoin_transport(rdv_dir, slot=slot, epoch=epoch,
                              backend=backend, timeout=timeout)
    e = int(ann["epoch"])
    comm = P2PCommunicator(t, range(t.world_size), ("epoch", e),
                           recv_timeout=recv_timeout)._mark_generation()
    _ft.enable(comm, rdv_dir=rdv_dir)  # fresh heartbeat over the corpse's
    if os.environ.get("MPI_TPU_VERIFY", "") not in ("", "0"):
        from . import verify as _verify

        _verify.enable(comm, rdv_dir=rdv_dir)
    publish_ready(rdv_dir, e, t.world_rank)
    comm.barrier()  # meets the survivors' accept_rejoin barrier
    _mpit.count(rejoins=1)
    return comm


# -- the survivors (accept side) ----------------------------------------------


def accept_rejoin(comm, timeout: Optional[float] = None):
    """Survivor-side half of the rejoin protocol — see
    ``P2PCommunicator.accept_rejoin`` for the user-facing contract.
    ``comm`` is the SHRUNKEN communicator (its group defines who
    survived; the transport's world size defines the slots to refill).
    Collective over the survivors; returns the full-world communicator
    under the post-shrink epoch."""
    from . import ft as _ftm
    from .communicator import P2PCommunicator

    ft = comm._require_ft("accept_rejoin")
    t = comm._t
    rdv = getattr(t, "_rdv", None)
    if rdv is None:
        raise RuntimeError(
            "accept_rejoin needs a file-rendezvous process world "
            "(socket/shm under the launcher); in-process local worlds "
            "have no rendezvous dir for a fresh process to join through")
    epoch = t.epoch
    full = tuple(range(t.world_size))
    dead = sorted(set(full) - set(comm._group))
    if not dead:
        raise ValueError("accept_rejoin: the world has no vacant slots")
    timeout = _REJOIN_TIMEOUT_S if timeout is None else timeout
    deadline = time.monotonic() + timeout
    if comm.rank == 0:
        acked = ft.world.acked_world
        slots = {s: {"ousted": read_incarnation(rdv, s),
                     "acked": s in acked} for s in dead}
        announce_rejoin(rdv, epoch, slots, t.world_size,
                        _backend_name(t))
        wait_ready(rdv, epoch, slots, deadline, validate=True)
    else:
        wait_ready(rdv, epoch, {s: {} for s in dead}, deadline)
    survivor_transition(t, epoch, dead)
    for s in dead:
        ft.world.reset_rank(s)
    new = P2PCommunicator(t, full, ("epoch", epoch),
                          recv_timeout=comm.recv_timeout)._mark_generation()
    new._ft = _ftm.CommFT(ft.world, ("epoch", epoch))
    if comm._verify is not None:
        from .verify.state import CommVerify

        new._verify = CommVerify(comm._verify.world)
    new = comm._inherit_errhandler(new)
    new.barrier()  # meets every joiner's rejoin() barrier
    _mpit.count(rejoins=1)
    return new


def _backend_name(t: Transport) -> str:
    name = type(t).__name__
    return {"SocketTransport": "socket", "ShmTransport": "shm"}.get(
        name, name.lower())


# -- rendezvous-dir lifecycle (shared by launcher / serve / tests) ------------


def new_rendezvous_dir(prefix: str = "mpi_tpu_rdv_") -> str:
    """Create a fresh rendezvous directory (the membership service's
    root: port/readiness/heartbeat/pending/claim files all live here)."""
    return tempfile.mkdtemp(prefix=prefix)


def cleanup_rendezvous(rdv: str) -> None:
    """Tear a rendezvous dir down, unlinking any /dev/shm segments a
    crashed rank left behind (ranks unlink their own on clean close;
    this is the crash path) — the launcher's former private cleanup,
    shared with the resident world server."""
    import glob
    import shutil

    try:
        from .transport.shm import shm_prefix

        session = os.path.basename(rdv.rstrip("/"))
        for path in glob.glob("/dev/shm/" + shm_prefix(session) + "*"):
            try:
                os.unlink(path)
            except OSError:
                pass
    except Exception:  # noqa: BLE001 - native layer absent: nothing mapped
        pass
    shutil.rmtree(rdv, ignore_errors=True)
