"""Dynamic process management (mpi_tpu/spawn.py): comm_spawn children get
a working world of their own plus the parent-child intercomm."""

import os
import sys
import textwrap

import pytest

import mpi_tpu
from mpi_tpu import spawn
from mpi_tpu.transport.local import run_local

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import mpi_tpu
    from mpi_tpu import spawn

    comm = mpi_tpu.COMM_WORLD          # the CHILD world
    parent = spawn.comm_get_parent()
    assert parent is not None and parent.is_inter
    assert spawn.comm_get_parent() is parent  # cached
    assert parent.remote_size == {nparents}
    assert parent.size == comm.size
    x = parent.recv(source=0)          # work item from parent rank 0
    total = comm.allreduce(x + comm.rank)   # child-world collective works
    if comm.rank == 0:
        parent.send(("result", total), dest=0)
    """)


def _worker_script(tmp_path, nparents: int) -> str:
    path = tmp_path / "spawn_worker.py"
    path.write_text(WORKER.format(repo=REPO, nparents=nparents))
    return str(path)


def test_spawn_from_standalone_parent(tmp_path):
    script = _worker_script(tmp_path, nparents=1)
    parent = mpi_tpu.comm_self()
    inter = spawn.comm_spawn([script], 2, comm=parent)
    assert inter.remote_size == 2 and inter.size == 1
    for j in range(2):
        inter.send(10, dest=j)
    kind, total = inter.recv(source=0)
    # children allreduce (10 + rank) over their 2-rank world: 10+0 + 10+1
    assert (kind, total) == ("result", 21)
    inter.free()


def test_spawn_from_multirank_parent(tmp_path):
    """Two in-process parent ranks spawn one shared child world; child
    bridge addressing reaches the right parent."""
    script = _worker_script(tmp_path, nparents=2)

    def prog(comm):
        inter = spawn.comm_spawn([script], 2, comm=comm, root=0)
        assert inter.remote_size == 2 and inter.size == 2
        if comm.rank == 0:
            inter.send(5, dest=0)
            inter.send(5, dest=1)
            out = inter.recv(source=0)
        else:
            out = None
        comm.barrier()
        inter.free()
        return out

    res = run_local(prog, 2)
    assert res[0] == ("result", 11)  # (5+0) + (5+1)


def test_spawn_multiple_segments(tmp_path):
    """spawn_multiple: two different scripts share ONE child world with
    segment-ordered ranks."""
    a = tmp_path / "seg_a.py"
    b = tmp_path / "seg_b.py"
    common = textwrap.dedent(f"""\
        import sys
        sys.path.insert(0, {REPO!r})
        import mpi_tpu
        from mpi_tpu import spawn
        comm = mpi_tpu.COMM_WORLD
        parent = spawn.comm_get_parent()
        """)
    a.write_text(common + textwrap.dedent("""\
        roles = comm.allgather("a")
        if comm.rank == 0:
            parent.send(roles, dest=0)
        """))
    b.write_text(common + 'comm.allgather("b")\n')
    parent = mpi_tpu.comm_self()
    inter = spawn.comm_spawn_multiple([([str(a)], 1), ([str(b)], 2)],
                                      comm=parent)
    assert inter.remote_size == 3
    roles = inter.recv(source=0)
    assert roles == ["a", "b", "b"]
    inter.free()


def test_spawn_rejects_spmd_comm():
    def prog(comm):
        with pytest.raises(NotImplementedError, match="launcher"):
            spawn.comm_spawn(["x.py"], 1, comm=comm)
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


def test_get_parent_none_when_not_spawned():
    assert spawn.comm_get_parent() is None
