"""Elastic membership (ISSUE 7 tentpole): epoch'd worlds + rejoin.

Three layers, cheapest first:

* the rendezvous PROTOCOL FILES (claim/admit/refuse/ready) as pure
  tmp-dir unit tests — including the three rejoin edge cases the issue
  names: false-suspicion refusal until ``failure_ack``, double-rejoin
  of the same worker id, and a claimer killed mid-handshake;
* TRANSPORT epoch stamping in-process: a stale-epoch straggler's
  re-handshake is diagnosed as EpochSkewError on socket AND shm, and
  ``survivor_transition`` drops replaced endpoints;
* the END-TO-END story in real processes on both transports: rank dies
  → survivors shrink (epoch bumps in lockstep) → ``accept_rejoin`` +
  a fresh process's ``membership.rejoin()`` rebuild the full world
  under the next epoch and complete a correct allreduce.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import api, membership, mpit
from mpi_tpu.errors import EpochSkewError, RejoinRefusedError
from mpi_tpu.transport.base import TransportError
from mpi_tpu.transport.faulty import KilledRankError
from mpi_tpu.transport.local import run_local

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DETECT_S = 1.0


@pytest.fixture(autouse=True)
def _tight_detection():
    """In-process worlds use a tight detection bound (the default 5s
    would push every 6*DETECT_S assertion past its margin)."""
    old = {k: mpit.cvar_read(k) for k in
           ("fault_detect_timeout_s", "fault_heartbeat_interval_s")}
    mpit.cvar_write("fault_detect_timeout_s", DETECT_S)
    mpit.cvar_write("fault_heartbeat_interval_s", 0.05)
    yield
    for k, v in old.items():
        mpit.cvar_write(k, v)


# -- protocol files (pure unit) ----------------------------------------------


def test_claim_is_exclusive(tmp_path):
    rdv = str(tmp_path)
    assert membership.claim_slot(rdv, 1, 2, inc="aaa")
    # double-claim (same or different worker id) fails cleanly
    assert not membership.claim_slot(rdv, 1, 2, inc="aaa")
    assert not membership.claim_slot(rdv, 1, 2, inc="bbb")
    # other slots / epochs are independent
    assert membership.claim_slot(rdv, 1, 3, inc="bbb")
    assert membership.claim_slot(rdv, 2, 2, inc="ccc")


def test_announce_roundtrip_and_latest(tmp_path):
    rdv = str(tmp_path)
    membership.announce_rejoin(rdv, 1, {2: {"ousted": None,
                                            "acked": False}}, 4, "socket")
    membership.announce_rejoin(rdv, 3, {1: {"ousted": "xyz",
                                            "acked": True}}, 4, "shm")
    assert membership.read_announce(rdv, 1)["backend"] == "socket"
    latest = membership.latest_announce(rdv)
    assert latest["epoch"] == 3 and latest["backend"] == "shm"
    assert latest["slots"]["1"]["ousted"] == "xyz"


def test_false_suspicion_refused_until_acked(tmp_path):
    """The rejoin edge case FT residual (b) was carried for: a
    suspected-but-LIVE rank presenting its ousted incarnation must be
    refused re-admission until the survivors failure_ack'd it —
    re-admitting would resurrect the split.  After the ack, the same
    incarnation is admitted."""
    rdv = str(tmp_path)
    slots = {1: {"ousted": "live-zombie", "acked": False}}
    membership.announce_rejoin(rdv, 1, slots, 3, "socket")
    assert membership.claim_slot(rdv, 1, 1, inc="live-zombie")
    membership.process_claims(rdv, 1, slots)
    with pytest.raises(RejoinRefusedError, match="failure_ack"):
        membership.wait_admitted(rdv, 1, 1, "live-zombie",
                                 time.monotonic() + 5.0)
    # the refused claim was cleared: a FRESH incarnation can claim...
    assert membership.claim_slot(rdv, 1, 1, inc="fresh-worker")
    membership.process_claims(rdv, 1, slots)
    membership.wait_admitted(rdv, 1, 1, "fresh-worker",
                             time.monotonic() + 5.0)
    # ...and once ACKED, even the ousted id itself re-enters (fresh
    # announce: the survivors acknowledged the failure first)
    slots2 = {2: {"ousted": "live-zombie", "acked": True}}
    membership.announce_rejoin(rdv, 2, slots2, 3, "socket")
    assert membership.claim_slot(rdv, 2, 2, inc="live-zombie")
    membership.process_claims(rdv, 2, slots2)
    membership.wait_admitted(rdv, 2, 2, "live-zombie",
                             time.monotonic() + 5.0)


def test_kill_during_rejoin_handshake_reclaims(tmp_path):
    """A claimer that died between claim and ready (dead pid, no
    readiness) is swept by the validation pass so the slot can be
    re-claimed under the SAME epoch — the pool recovers, no epoch
    fork."""
    rdv = str(tmp_path)
    slots = {0: {"ousted": None, "acked": False}}
    # a pid that cannot exist (pid_max is < 2**22 by default)
    dead_pid = 2 ** 22 + 17
    assert membership.claim_slot(rdv, 1, 0, inc="doomed", pid=dead_pid)
    membership.process_claims(rdv, 1, slots)
    # claim swept -> re-claimable; the replacement is admitted
    assert membership.claim_slot(rdv, 1, 0, inc="second")
    membership.process_claims(rdv, 1, slots)
    membership.wait_admitted(rdv, 1, 0, "second", time.monotonic() + 5.0)
    membership.publish_ready(rdv, 1, 0, inc="second")
    membership.wait_ready(rdv, 1, slots, time.monotonic() + 5.0,
                          validate=True)


def test_claimer_dead_after_ready_is_swept(tmp_path):
    """The nastier mid-handshake death window: the claimer published
    READY and then died (before the pool/survivors could use it).  The
    validation pass must sweep claim+admit+ready — a leftover ready
    from a corpse would make every future O_EXCL claim fail and wedge
    the slot's healing forever."""
    rdv = str(tmp_path)
    slots = {0: {"ousted": None, "acked": False}}
    dead_pid = 2 ** 22 + 23
    assert membership.claim_slot(rdv, 1, 0, inc="ghost", pid=dead_pid)
    membership.publish_ready(rdv, 1, 0, inc="ghost")
    membership.process_claims(rdv, 1, slots)
    # the slot is claimable again under the SAME epoch, and the fresh
    # claimer completes the whole handshake
    assert membership.claim_slot(rdv, 1, 0, inc="replacement")
    membership.process_claims(rdv, 1, slots)
    membership.wait_admitted(rdv, 1, 0, "replacement",
                             time.monotonic() + 5.0)
    membership.publish_ready(rdv, 1, 0, inc="replacement")
    membership.wait_ready(rdv, 1, slots, time.monotonic() + 5.0,
                          validate=True)


def test_double_rejoin_same_worker_id_refused(tmp_path, monkeypatch):
    """A worker id that already completed a rejoin (its readiness file
    names its incarnation) must not re-enter through the same stale
    announce."""
    rdv = str(tmp_path)
    membership.announce_rejoin(rdv, 1, {0: {"ousted": None,
                                            "acked": False}}, 2, "socket")
    monkeypatch.setattr(membership, "_PROCESS_INCARNATION", "me-again")
    membership.publish_ready(rdv, 1, 0, inc="me-again")
    with pytest.raises(RejoinRefusedError, match="double rejoin"):
        membership.rejoin_transport(rdv, slot=0, epoch=1, timeout=2.0)


def test_incarnation_registry(tmp_path):
    rdv = str(tmp_path)
    inc = membership.publish_incarnation(rdv, 3)
    assert membership.read_incarnation(rdv, 3) == inc
    assert membership.read_incarnation(rdv, 4) is None
    # per-process singleton: a second publish reuses the same identity
    assert membership.publish_incarnation(rdv, 5) == inc


# -- epoch bookkeeping (local world) -----------------------------------------


def test_shrink_bumps_membership_epoch():
    """Every survivor's shrink bumps the transport's membership epoch
    in lockstep; the epoch is visible as comm.membership_epoch and via
    the MPIX mirror."""
    def fn(comm):
        assert comm.membership_epoch == 0
        assert api.MPIX_Comm_get_epoch(comm) == 0
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        t0 = time.monotonic()
        while comm.get_failed() != [1]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        new = comm.shrink()
        assert comm.membership_epoch == 1
        assert new.membership_epoch == 1
        return comm._t.epoch

    res = run_local(fn, 3, fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == 1


def test_failure_ack_records_world_level():
    """failure_ack feeds the membership layer's re-admission gate
    (WorldFT.acked_world carries WORLD ranks)."""
    def fn(comm):
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        t0 = time.monotonic()
        while comm.get_failed() != [1]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        assert comm._ft.world.acked_world == set()
        comm.failure_ack()
        assert comm._ft.world.acked_world == {1}
        return "ok"

    res = run_local(fn, 3, fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == "ok"


def test_subcomm_shrink_does_not_bump_epoch():
    """The membership epoch counts WORLD transitions: shrinking a
    proper sub-communicator must NOT bump the shared transport epoch —
    healthy members of other subgroups would otherwise read as stale
    stragglers at their next handshake.  Shrinking a world-generation
    comm (and chained shrinks of its results) does bump."""
    def fn(comm):
        # split is collective: every rank participates; rank 2 opts out
        sub = comm.split(0 if comm.rank < 2 else None)
        if comm.rank == 2:
            return "bystander"  # not in the shrinking subgroup
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        t0 = time.monotonic()
        # rank 2 returned already and stops heartbeating — it may
        # legitimately join the failed set too; we only need rank 1
        while 1 not in comm.get_failed():
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        shrunk_sub = sub.shrink()
        assert shrunk_sub.size == 1
        assert comm.membership_epoch == 0  # sub-comm shrink: no bump
        new = comm.shrink()  # the WORLD's shrink is the transition
        assert comm.membership_epoch == 1
        # chained: the shrunken world comm is itself a generation comm
        assert new._ctx in comm._t._gen_ctxs
        return "ok"

    res = run_local(fn, 3, fault_tolerance=True, timeout=60)
    assert res[0] == "ok" and res[2] == "bystander"


# -- transport epoch stamping (in-process) -----------------------------------


def test_socket_stale_straggler_diagnosed(tmp_path):
    """A stale-epoch straggler's NEW connection is rejected loudly on
    both sides of the hello: the straggler raises EpochSkewError (the
    diagnosed spelling of the false-suspicion split), the survivor
    refuses the reader; the pvar counts."""
    from mpi_tpu.transport.socket import SocketTransport

    base = mpit.pvar_read("epoch_skews_detected")
    rdv = str(tmp_path)
    survivor = SocketTransport(0, 2, rdv, epoch=2)
    survivor.min_peer_epoch[1] = 2
    straggler = SocketTransport(1, 2, rdv, epoch=0)
    try:
        with pytest.raises(EpochSkewError) as ei:
            straggler.send(0, 0, 5, b"stale hello")
        assert ei.value.local_epoch == 0 and ei.value.peer_epoch == 2
        assert mpit.pvar_read("epoch_skews_detected") > base
    finally:
        survivor.close()
        straggler.close()


def test_socket_survivor_transition_drops_endpoints(tmp_path):
    from mpi_tpu.transport.socket import SocketTransport

    rdv = str(tmp_path)
    a = SocketTransport(0, 2, rdv)
    b = SocketTransport(1, 2, rdv)
    try:
        a.send(1, 0, 7, b"warm the connection")
        assert b.recv(0, 0, 7)[0] == b"warm the connection"
        assert 1 in a._conns
        membership.survivor_transition(a, 1, [1])
        assert a.epoch == 1 and a.min_peer_epoch[1] == 1
        assert 1 not in a._conns  # dropped: next send re-handshakes
        # the replaced slot's OLD incarnation (epoch 0) can no longer
        # be adopted: reconnect demands epoch >= 1 and times out
        a._connect_timeout = 1.0
        with pytest.raises(TransportError, match="epoch >= 1"):
            a.send(1, 0, 8, b"nobody new there yet")
    finally:
        a.close()
        b.close()


def test_shm_stale_straggler_diagnosed(tmp_path):
    from mpi_tpu.native import ensure_built

    try:
        ensure_built()
    except Exception as e:  # pragma: no cover - no toolchain
        pytest.skip(f"native shm ring unavailable: {e}")
    from mpi_tpu.transport.shm import ShmTransport

    rdv = str(tmp_path)
    survivor = ShmTransport(0, 2, rdv, epoch=3)
    straggler = ShmTransport(1, 2, rdv, epoch=1)
    try:
        with pytest.raises(EpochSkewError) as ei:
            straggler.send(0, 0, 5, b"stale open")
        assert ei.value.peer_epoch == 3 and ei.value.local_epoch == 1
    finally:
        survivor.close()
        straggler.close()


def test_shm_transition_recreates_inbound_rings(tmp_path):
    """An shm epoch transition must RECREATE the survivor's inbound
    rings from replaced slots (the corpse may have died mid-frame,
    desyncing the byte stream) and clear their quarantine, and only
    then re-stamp readiness — so a replacement that honors the epoch
    gate always appends to a fresh ring and its frames arrive clean."""
    from mpi_tpu.native import ensure_built

    try:
        ensure_built()
    except Exception as e:  # pragma: no cover - no toolchain
        pytest.skip(f"native shm ring unavailable: {e}")
    from mpi_tpu.transport.shm import ShmTransport

    rdv = str(tmp_path)
    survivor = ShmTransport(0, 2, rdv)
    first = ShmTransport(1, 2, rdv)
    try:
        first.send(0, 0, 7, b"from the first incarnation")
        assert survivor.recv(1, 0, 7)[0] == b"from the first incarnation"
        # leave UNDRAINED bytes in the inbound ring (as the corpse's
        # half-written frame would), then quarantine the channel
        survivor._dead_srcs.add(1)
        first.send(0, 0, 7, b"leftover garbage from the corpse")
        membership.survivor_transition(survivor, 1, [1])
        # recreated: the fresh ring is EMPTY (the garbage is gone) and
        # the quarantine is lifted
        assert survivor._lib.shmring_avail(survivor._in_rings[1]) == 0
        assert 1 not in survivor._dead_srcs
        first.close()
        # the replacement (epoch 1, gated on the survivor's re-stamp)
        # talks over the FRESH ring
        replacement = ShmTransport(1, 2, rdv, epoch=1)
        replacement.min_peer_epoch[0] = 1
        try:
            replacement.send(0, 0, 8, b"fresh generation")
            assert survivor.recv(1, 0, 8)[0] == b"fresh generation"
        finally:
            replacement.close()
    finally:
        survivor.close()


# -- end-to-end: kill -> shrink -> accept_rejoin + rejoin --------------------

_SURVIVOR_PROG = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit
from mpi_tpu.errors import ProcFailedError, RevokedError

mpit.cvar_write("fault_detect_timeout_s", 2.0)
mpit.cvar_write("fault_heartbeat_interval_s", 0.2)
comm = mpi_tpu.init()
if comm.rank == 1:
    time.sleep(0.5)
    os._exit(42)
t0 = time.monotonic()
try:
    if comm.rank == 0:
        comm.allreduce(np.ones(1024, np.float32), algorithm="ring")
        sys.exit(7)
    else:
        comm.recv(source=0, tag=9)
        sys.exit(7)
except ProcFailedError:
    comm.revoke()
except RevokedError:
    pass
new = comm.shrink()
assert comm.membership_epoch == 1, comm.membership_epoch
full = new.accept_rejoin(timeout=40.0)
assert full.size == 3 and full.membership_epoch == 1
assert full.rank == comm.rank  # slots keep their identity
out = full.allreduce(np.full(8, float(full.rank + 1), np.float32))
assert float(out[0]) == 6.0, out[0]
assert mpit.pvar_read("rejoins_completed") == 1
print(f"rank {{comm.rank}} grew back in {{time.monotonic()-t0:.1f}}s",
      flush=True)
"""

_JOINER_PROG = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit

mpit.cvar_write("fault_detect_timeout_s", 2.0)
mpit.cvar_write("fault_heartbeat_interval_s", 0.2)
comm = mpi_tpu.membership.rejoin(timeout=40.0)
assert comm.size == 3 and comm.rank == 1, (comm.size, comm.rank)
assert comm.membership_epoch == 1, comm.membership_epoch
out = comm.allreduce(np.full(8, float(comm.rank + 1), np.float32))
assert float(out[0]) == 6.0, out[0]
assert mpit.pvar_read("rejoins_completed") == 1
print("joiner filled the slot", flush=True)
"""


@pytest.mark.parametrize("backend", ["socket", "shm"])
def test_rejoin_e2e(tmp_path, backend):
    """The grow-back acceptance story: a 3-rank process world loses
    rank 1; survivors detect/revoke/shrink (epoch 0 -> 1) and
    accept_rejoin; a FRESH process rejoins through the rendezvous dir
    into slot 1 under epoch 1; the rebuilt full world completes a
    correct allreduce on every member.  Socket AND shm."""
    if backend == "shm":
        from mpi_tpu.native import ensure_built

        try:
            ensure_built()
        except Exception as e:  # pragma: no cover - no toolchain
            pytest.skip(f"native shm ring unavailable: {e}")
    surv = tmp_path / "survivor.py"
    surv.write_text(_SURVIVOR_PROG.format(repo=REPO))
    join = tmp_path / "joiner.py"
    join.write_text(_JOINER_PROG.format(repo=REPO))
    rdv = tmp_path / "rdv"
    rdv.mkdir()
    base = {"MPI_TPU_RDV": str(rdv), "MPI_TPU_BACKEND": backend,
            "JAX_PLATFORMS": "cpu"}
    procs = []
    for r in range(3):
        env = dict(os.environ, **base, MPI_TPU_RANK=str(r),
                   MPI_TPU_SIZE="3", MPI_TPU_FT="1")
        procs.append(subprocess.Popen(
            [sys.executable, str(surv)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    # the joiner needs no rank env: everything comes from the announce
    joiner = subprocess.Popen(
        [sys.executable, str(join)], env=dict(os.environ, **base),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    outs = {}
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=120.0)
        outs[r] = (p.returncode, out, err)
    jout, jerr = joiner.communicate(timeout=120.0)
    assert outs[1][0] == 42
    for r in (0, 2):
        code, out, err = outs[r]
        assert code == 0, f"rank {r}: {err[-900:]}"
        assert "grew back" in out, out
    assert joiner.returncode == 0, jerr[-900:]
    assert "joiner filled the slot" in jout
