#!/usr/bin/env python
"""Verifier overhead leg: prove verify=False costs nothing on the hot
path, and measure what verify=True costs when you opt in.

Two claims, checked mechanically (ISSUE 5 acceptance):

* **Off-mode is free**: with the verifier off, the segmented allreduce's
  zero-copy pvar contracts are bit-identical to the committed ones —
  zero pickled array bytes and the engine's expected ``payload_copies``
  — and the p50 is the plain data plane's (the verifier is one ``is
  None`` attribute test per operation; nothing else runs).
* **On-mode cost is bounded and visible**: the same loop under
  ``verify=True`` reports its p50 next to the off p50 and the measured
  overhead factor (the signature ring adds 2(P-1) tiny control messages
  per collective plus the per-op progress stamp), so "what does the
  checker cost" has a number instead of a guess.

Usage::

    python benchmarks/verify_overhead.py            # JSON to stdout
    python benchmarks/verify_overhead.py --quick    # tier-1 smoke
    python bench.py --verify-overhead [--quick]     # the CI spelling
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Dict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_tpu import mpit  # noqa: E402
from mpi_tpu.transport.local import run_local  # noqa: E402


def _allreduce_loop(comm, nbytes: int, iters: int):
    arr = np.ones(max(1, nbytes // 4), np.float32)
    comm.barrier()
    t0 = time.perf_counter()
    for _ in range(iters):
        comm.allreduce(arr, algorithm="ring")
    dt = time.perf_counter() - t0
    return (dt / iters) * 1e6  # us per op


def _leg(nranks: int, nbytes: int, iters: int, samples: int,
         verify: bool, progress: str = "none",
         trace: bool = False) -> Dict:
    p50s = []
    for _ in range(samples):
        per_rank = run_local(_allreduce_loop, nranks, args=(nbytes, iters),
                             verify=verify, progress=progress, trace=trace)
        p50s.append(statistics.median(per_rank))
    return {"p50_us": round(min(p50s), 1),
            "samples_us": [round(s, 1) for s in p50s]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="tier-1 smoke: tiny sizes, 1 sample")
    ap.add_argument("--nranks", type=int, default=2)
    ap.add_argument("--progress", action="store_true",
                    help="also run the allreduce loop under "
                         "progress=thread (verify off) and assert the "
                         "off-mode pvar contracts hold with the engine "
                         "running: 0 pickled array bytes, payload-copy "
                         "count unchanged")
    ap.add_argument("--trace", action="store_true",
                    help="also run the allreduce loop under the flight "
                         "recorder (mpi_tpu/telemetry, ISSUE 13) and "
                         "price it; the trace-OFF leg's contract — 0 "
                         "trace events, unchanged payload_copies/"
                         "bytes_pickled_sent — is asserted either way")
    args = ap.parse_args(argv)
    iters = 20 if args.quick else 200
    samples = 1 if args.quick else 5
    nbytes = 1 << 10

    ses = mpit.session_create()
    ses.reset_all()
    off = _leg(args.nranks, nbytes, iters, samples, verify=False)
    # THE off-mode contract: the verifier must not have touched the wire
    # accounting — no pickled array bytes beyond the plain engine's (the
    # ring allreduce ships raw frames only) and zero verify events
    off_pickled = ses.read("bytes_pickled_sent")
    off_copies = ses.read("payload_copies")
    off_events = sum(ses.read(p) for p in mpit.pvar_list()
                     if p.startswith("verify_"))
    off_prog = sum(ses.read(p) for p in mpit.pvar_list()
                   if p.startswith("progress_"))
    off_trace = ses.read("trace_events")
    trace_leg = None
    if args.trace:
        # ISSUE 13: the flight recorder must not perturb the wire
        # accounting — same zero-pickled-bytes and payload-copy
        # contracts with the ring buffer recording; its own cost is
        # the recorded p50 delta, priced not promised
        from mpi_tpu import telemetry

        ses.reset_all()
        trace_leg = _leg(args.nranks, nbytes, iters, samples,
                         verify=False, trace=True)
        trace_leg["trace_events"] = ses.read("trace_events")
        trace_leg["bytes_pickled_sent"] = ses.read("bytes_pickled_sent")
        trace_leg["payload_copies"] = ses.read("payload_copies")
        telemetry.disable()
        assert trace_leg["trace_events"] > 0, \
            "tracing on recorded zero events"
        assert trace_leg["bytes_pickled_sent"] == 0, \
            (f"traced ring allreduce pickled "
             f"{trace_leg['bytes_pickled_sent']} bytes")
        assert trace_leg["payload_copies"] == off_copies, \
            (f"tracing changed the payload-copy count: "
             f"{trace_leg['payload_copies']} != {off_copies}")
    progress_leg = None
    if args.progress:
        # ISSUE 6 satellite: the dedicated progress engine must not
        # perturb the data plane's accounting — same zero-pickled-bytes
        # and payload-copy contracts with the engine's thread running
        # (its completions consume already-delivered payloads; no new
        # wire traffic, no new copies)
        ses.reset_all()
        progress_leg = _leg(args.nranks, nbytes, iters, samples,
                            verify=False, progress="thread")
        progress_leg["bytes_pickled_sent"] = ses.read("bytes_pickled_sent")
        progress_leg["payload_copies"] = ses.read("payload_copies")
        progress_leg["progress_wakeups"] = ses.read("progress_wakeups")
        progress_leg["progress_completions"] = \
            ses.read("progress_completions")
        assert progress_leg["bytes_pickled_sent"] == 0, \
            (f"progress=thread ring allreduce pickled "
             f"{progress_leg['bytes_pickled_sent']} bytes")
        assert progress_leg["payload_copies"] == off_copies, \
            (f"progress=thread changed the payload-copy count: "
             f"{progress_leg['payload_copies']} != {off_copies}")
    ses.reset_all()
    on = _leg(args.nranks, nbytes, iters, samples, verify=True)
    on_pickled = ses.read("bytes_pickled_sent")

    result = {
        "metric": "verify_overhead_allreduce_1kf32_ring_p50",
        "nranks": args.nranks,
        "payload_bytes": nbytes,
        "iters_per_sample": iters,
        "off": off,
        "on": on,
        "overhead_x": round(on["p50_us"] / max(off["p50_us"], 1e-9), 3),
        # off-mode zero-cost evidence (hard assertions below)
        "off_bytes_pickled_sent": off_pickled,
        "off_payload_copies": off_copies,
        "off_verify_events": off_events,
        "off_progress_events": off_prog,
        "off_trace_events": off_trace,
        # the signature ring is pickled control traffic — nonzero ON is
        # expected and recorded, never part of the off-mode contract
        "on_bytes_pickled_sent": on_pickled,
        "oversubscribed": (args.nranks + 1) > (os.cpu_count() or 1),
    }
    if progress_leg is not None:
        result["progress_thread"] = progress_leg
    if trace_leg is not None:
        result["trace_on"] = trace_leg
        result["trace_overhead_x"] = round(
            trace_leg["p50_us"] / max(off["p50_us"], 1e-9), 3)
    assert off_events == 0, f"verifier ran with verify=False: {off_events}"
    assert off_prog == 0, \
        f"progress engine ran with progress=none: {off_prog} events"
    assert off_trace == 0, \
        f"flight recorder ran with tracing off: {off_trace} events"
    assert off_pickled == 0, \
        f"off-mode ring allreduce pickled {off_pickled} bytes"
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
