"""Two independently started programs couple through a port.

The classic MPI-2 use case for connect/accept: an "ocean" model and an
"atmosphere" model are SEPARATE jobs (their own launchers, their own
COMM_WORLDs; launch both with the same -n) that find each other via
the name service and exchange boundary data every step over the
intercommunicator.

Run (two shells, or backgrounded):

    python -m mpi_tpu.launcher -n 2 examples/coupled_models.py ocean &
    python -m mpi_tpu.launcher -n 2 examples/coupled_models.py atmosphere
"""

import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mpi_tpu
from mpi_tpu import spawn

ROLE = sys.argv[1] if len(sys.argv) > 1 else "ocean"
SERVICE = "coupled-demo"
STEPS = 5
N = 8  # boundary points per rank

comm = mpi_tpu.COMM_WORLD

# pairing below is rank<->rank: both jobs must be launched with the SAME
# -n (a many-to-one boundary-routing scheme is a modeling choice, not a
# transport one)

if ROLE == "ocean":
    # server side: open a port, publish it, accept the atmosphere
    port = spawn.open_port() if comm.rank == 0 else None
    port = comm.bcast(port, 0)
    if comm.rank == 0:
        spawn.publish_name(SERVICE, port)
    inter = spawn.comm_accept(port, comm=comm)
    assert inter.remote_size == comm.size, "launch both jobs with the same -n"
    sst = np.full(N, 290.0) + comm.rank  # sea-surface temperature
    for step in range(STEPS):
        # each ocean rank exchanges boundaries with its peer atmosphere rank
        peer = comm.rank % inter.remote_size
        flux = inter.sendrecv(sst, peer, source=peer)
        sst = sst + 0.1 * (flux - sst)  # relax toward the forcing
    if comm.rank == 0:
        spawn.unpublish_name(SERVICE)
        spawn.close_port(port)
        print(f"ocean: coupled {STEPS} steps, final sst[0] = {sst[0]:.3f}")
    inter.free()
else:
    # client side: look the service up (waiting for the server), connect
    port = spawn.lookup_name(SERVICE, timeout=60) if comm.rank == 0 else None
    port = comm.bcast(port, 0)
    inter = spawn.comm_connect(port, comm=comm)
    assert inter.remote_size == comm.size, "launch both jobs with the same -n"
    air = np.full(N, 285.0) + comm.rank
    for step in range(STEPS):
        peer = comm.rank % inter.remote_size
        sst = inter.sendrecv(air, peer, source=peer)
        air = air + 0.05 * (sst - air)
    if comm.rank == 0:
        print(f"atmosphere: coupled {STEPS} steps, final air[0] = {air[0]:.3f}")
    inter.free()
