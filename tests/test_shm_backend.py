"""Native shared-memory transport tests (mpi_tpu/native/shmring.cpp +
mpi_tpu/transport/shm.py): the C++ SPSC ring itself, the transport over it
(real shm segments, transports living in threads), and one launcher-spawned
multi-process end-to-end run."""

import ctypes
import os
import struct
import tempfile
import textwrap
import threading

import numpy as np
import pytest

from mpi_tpu import ops
from mpi_tpu.communicator import P2PCommunicator
from mpi_tpu.native import load_shmring
from mpi_tpu.transport.shm import ShmTransport

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the native ring itself ------------------------------------------------


def test_ring_roundtrip_small():
    lib = load_shmring()
    name = b"/mt_test_ring_rt"
    ring_c = lib.shmring_create(name, 4096)
    assert ring_c
    ring_p = lib.shmring_open(name, 5.0)
    assert ring_p
    msg = b"hello, ring"
    assert lib.shmring_write(ring_p, msg, len(msg), 5.0) == 0
    assert lib.shmring_avail(ring_c) == len(msg)
    buf = ctypes.create_string_buffer(len(msg))
    assert lib.shmring_read(ring_c, buf, len(msg), 5.0) == 0
    assert buf.raw == msg
    lib.shmring_close(ring_p)
    lib.shmring_close(ring_c)
    lib.shmring_unlink(name)


def test_ring_streams_frames_larger_than_capacity():
    """A frame bigger than the ring must stream through (writer and reader
    chunk concurrently) — the no-deadlock property the transport relies on."""
    lib = load_shmring()
    name = b"/mt_test_ring_big"
    cap = 64 * 1024
    ring_c = lib.shmring_create(name, cap)
    ring_p = lib.shmring_open(name, 5.0)
    payload = np.random.RandomState(0).bytes(cap * 4 + 12345)
    out = ctypes.create_string_buffer(len(payload))
    err = []

    def reader():
        if lib.shmring_read(ring_c, out, len(payload), 30.0) != 0:
            err.append("read timeout")

    t = threading.Thread(target=reader)
    t.start()
    assert lib.shmring_write(ring_p, payload, len(payload), 30.0) == 0
    t.join(30.0)
    assert not err and not t.is_alive()
    assert out.raw == payload
    lib.shmring_close(ring_p)
    lib.shmring_close(ring_c)
    lib.shmring_unlink(name)


def test_ring_write_timeout_when_full():
    lib = load_shmring()
    name = b"/mt_test_ring_full"
    ring_c = lib.shmring_create(name, 1024)
    ring_p = lib.shmring_open(name, 5.0)
    data = bytes(1024)
    assert lib.shmring_write(ring_p, data, len(data), 5.0) == 0  # fills it
    assert lib.shmring_write(ring_p, b"x", 1, 0.2) == -1  # nobody drains
    lib.shmring_close(ring_p)
    lib.shmring_close(ring_c)
    lib.shmring_unlink(name)


# -- the transport over real shm segments ----------------------------------


def run_shm_world(fn, nranks, timeout=60.0):
    """Run fn(comm) on nranks ShmTransports living in threads (real shm)."""
    rdv = tempfile.mkdtemp(prefix="mpi_tpu_shm_test_")
    results = [None] * nranks
    errors = []
    transports = [None] * nranks

    def runner(r):
        try:
            t = ShmTransport(r, nranks, rdv, ring_bytes=256 * 1024)
            transports[r] = t
            comm = P2PCommunicator(t, range(nranks))
            results[r] = fn(comm)
        except BaseException as e:  # noqa: BLE001
            import traceback

            errors.append((r, e, traceback.format_exc()))

    threads = [threading.Thread(target=runner, args=(r,), daemon=True)
               for r in range(nranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    alive = [i for i, t in enumerate(threads) if t.is_alive()]
    for t in transports:
        if t is not None:
            t.close()
    if errors:
        r, e, tb = errors[0]
        raise RuntimeError(f"rank {r} failed:\n{tb}") from e
    if alive:
        raise TimeoutError(f"shm ranks did not finish: {alive}")
    return results


def test_shm_p2p_roundtrip():
    def prog(comm):
        if comm.rank == 0:
            comm.send(np.arange(1000), dest=1, tag=3)
            return comm.recv(source=1, tag=4)
        got = comm.recv(source=0, tag=3)
        comm.send(got.sum(), dest=0, tag=4)
        return None

    res = run_shm_world(prog, 2)
    assert res[0] == np.arange(1000).sum()


def test_shm_large_message_through_small_ring():
    big = np.random.RandomState(0).bytes(3 * 1024 * 1024)  # 12x the test ring

    def prog(comm):
        if comm.rank == 0:
            comm.send(big, dest=1)
            return None
        return comm.recv(source=0)

    res = run_shm_world(prog, 2)
    assert res[1] == big


def test_shm_self_send():
    def prog(comm):
        comm.send("to-myself", dest=comm.rank, tag=1)
        return comm.recv(source=comm.rank, tag=1)

    assert run_shm_world(prog, 2) == ["to-myself", "to-myself"]


@pytest.mark.parametrize("algo", ["ring", "recursive_halving"])
def test_shm_allreduce(algo):
    data = np.random.RandomState(1).randn(4, 50)

    def prog(comm):
        return comm.allreduce(data[comm.rank], op=ops.SUM, algorithm=algo)

    for got in run_shm_world(prog, 4):
        np.testing.assert_allclose(got, data.sum(axis=0), rtol=1e-10)


def test_shm_split_and_rma():
    def prog(comm):
        sub = comm.split(color=comm.rank % 2, key=comm.rank)
        win = comm.win_create(np.zeros(1))
        if comm.rank != 0:
            win.accumulate(np.array([1.0]), 0)
        win.fence()
        return sub.allreduce(comm.rank), float(win.local[0])

    res = run_shm_world(prog, 4)
    assert [r[0] for r in res] == [2, 4, 2, 4]
    assert [r[1] for r in res] == [3.0, 0.0, 0.0, 0.0]


def test_shm_segments_cleaned_up():
    rdv = tempfile.mkdtemp(prefix="mpi_tpu_shm_gc_")
    session = os.path.basename(rdv)
    t0 = ShmTransport(0, 2, rdv, ring_bytes=64 * 1024)
    t1 = ShmTransport(1, 2, rdv, ring_bytes=64 * 1024)
    # 2 directed rings + 2 doorbells
    assert len([f for f in os.listdir("/dev/shm")
                if f.startswith(f"mt_{session}_")]) == 4
    t0.close()
    t1.close()
    assert not [f for f in os.listdir("/dev/shm")
                if f.startswith(f"mt_{session}_")]


@pytest.mark.slow
def test_shm_launcher_end_to_end(tmp_path):
    """Full L0 path over the native data plane: real rank processes, shm
    rings between them."""
    script = tmp_path / "prog.py"
    out = tmp_path / "out"
    out.mkdir()
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import mpi_tpu

        comm = mpi_tpu.init()
        got = comm.allreduce(np.full(10, comm.rank + 1.0))
        with open({str(out)!r} + f"/rank{{comm.rank}}.txt", "w") as f:
            f.write(str(float(got.sum())))
        mpi_tpu.finalize()
    """))
    from mpi_tpu.launcher import launch

    rc = launch(3, [str(script)], timeout=90.0, backend="shm")
    assert rc == 0
    expect = 10 * (1.0 + 2.0 + 3.0)
    for r in range(3):
        assert float((out / f"rank{r}.txt").read_text()) == expect


def test_shm_symmetric_big_sendrecv_no_deadlock():
    """Regression: both ranks sendrecv frames bigger than the ring's free
    space at once.  Without a dedicated drainer (the buffered-send
    invariant of communicator.py), both would block in their sends."""
    big = np.arange(300_000, dtype=np.float64)  # ~2.4MB through 256KB rings

    def prog(comm):
        peer = 1 - comm.rank
        got = comm.sendrecv(big * (comm.rank + 1), peer)
        return float(got[-1])

    res = run_shm_world(prog, 2, timeout=60.0)
    assert res[0] == big[-1] * 2 and res[1] == big[-1]


def test_shm_random_frame_sizes_roundtrip():
    """Frame sizes straddling the tiny-concat threshold, the ring capacity,
    and multiples thereof all roundtrip bit-exactly (framing property)."""
    rng = np.random.RandomState(7)
    sizes = [1, 100, 8191, 8192, 8193, 100_000, 256 * 1024 - 8,
             256 * 1024, 256 * 1024 + 1, 700_000]
    payloads = [rng.bytes(s) for s in sizes]

    def prog(comm):
        if comm.rank == 0:
            for p in payloads:
                comm.send(p, dest=1)
            ok = comm.recv(source=1)
            return ok
        got = [comm.recv(source=0) for _ in payloads]
        comm.send(all(g == p for g, p in zip(got, payloads)), dest=0)
        return True

    res = run_shm_world(prog, 2)
    assert res[0] is True
