"""Federated serve fabric: leader election, pool takeover, failover.

ISSUE 15 tentpole — the last single point of failure in the stack.
``launcher serve`` (mpi_tpu/serve.py) survives any WORKER death, but the
server process itself was one process fronting one warm pool: kill it
and every client, lease, and worker orphans.  This module federates N
servers over a shared **namespace** (the Ray-GCS / ZooKeeper-lease
shape).  ISSUE 18 put the namespace behind the pluggable
:class:`~mpi_tpu.federation_store.NamespaceStore` seam: every record
below lives in a small versioned KV with ATOMIC compare-and-swap —
backed by a directory (:class:`~mpi_tpu.federation_store.FileStore`,
the PR-15 single-host/NFS mode, its takeover race now structurally
closed) or by a replicated Raft-shaped quorum store
(:class:`~mpi_tpu.federation_store.RaftStore`, N servers on N hosts
with no shared filesystem).  A federation "namespace" argument is a
SPEC: a directory path, or ``raft:<idx>@host:port,...`` (server
member) / ``raft:host:port,...`` (client).

* **Endpoint records** — every server renews ``server.<id>`` (pid,
  host, control addr, metrics addr, a light stats summary) each tick;
  a record whose pid is dead (same-host only — pids don't travel) or
  whose renewal is stale past the lease bound IS a dead server.
* **Leader election** (:class:`LeaderLease`) — one ``leader.lease``
  record, acquired and RENEWED by compare-and-swap (the content —
  holder id, pid, term — is immutable per acquisition; a renewal
  re-commits it, refreshing the record's write stamp).  A lease whose
  stamp is stale past ``lease_timeout_s`` is taken over by CAS'ing
  against its exact version — two racing takeovers (or a takeover
  racing a frozen holder's thawed renewal) target the same version
  and exactly ONE wins; the PR-15 re-stat→unlink window no longer
  exists.  The safety half: a holder's AUTHORITY expires
  ``validity_s = lease_timeout_s/2`` after its last successful renew,
  strictly before any takeover can fire, so a leader frozen past the
  bound (SIGSTOP, the PR-10 rank-freeze story at the server tier) has
  provably lapsed before its usurper begins — and on thaw its next
  renew loses the CAS and DEMOTES.  On the replicated store there is
  a second lapse mode: a minority-side holder's renew raises
  :class:`~mpi_tpu.errors.NoQuorumError` — it does NOT demote (it may
  still be the rightful holder after heal) but it also cannot extend,
  so its authority lapses within ``validity_s`` — the Chubby-bounded
  degradation "minority refuses authority, majority serves".  Every
  acquire/renew appends a ``[from, until]`` authority interval to an
  append-only per-server log (an extension that cannot be LOGGED is
  not granted); :func:`assert_no_leader_overlap` is the split-brain
  assertion the tests run.
* **Pool takeover** — the leader watches the endpoint records; a dead
  server's pools (``pool.<id>`` ownership records) are assigned to
  the least-loaded survivor via a ``takeover.<dead>`` assignment.
  The survivor adopts the pool (serve.py grows multi-pool
  bookkeeping), rewrites the ownership record, and the dead server's
  ORPHANED WORKERS — whose transports, arenas, and FT detectors are
  all still warm — re-register with it over the control channel
  (:func:`wait_pool_owner` is the worker-side resolve).  Worker-level
  healing on an adopted pool rides the existing announce/claim/admit
  rejoin protocol against the adopted rendezvous dir unchanged.
  Double-serving is structurally excluded: a worker serves exactly one
  master at a time (its control connection is the token), and a thawed
  ex-owner that finds a newer ownership record relinquishes — closing
  those connections is precisely what releases the workers to the
  usurper.
* **Client failover** (:class:`FederatedClient`) — ``mpi_tpu.connect``
  grows a server-list / namespace mode: acquire and stats re-resolve
  live endpoints and retry with backoff on a dead-server
  ``ServerLostError`` — or a minority-side server's ``NoQuorumError``
  (re-acquire is idempotent — a lease whose server died, died with
  it); an in-flight ``lease.run`` surfaces the named error instead of
  transparently re-running a possibly-side-effecting job.
* **Roll-up** (:func:`federation_stats`) — the per-server summaries in
  the endpoint records aggregate into one namespace-level document, so
  the PR-13 Prometheus endpoint stays truthful when pools move between
  servers.

Chaos: ``python bench.py --chaos --federation [--quick]`` SIGKILLs
servers under an open-loop fleet of concurrent clients and asserts
aggregate worlds/s never reaches zero with every failure named;
``--partition`` adds the replicated-store leg — an injected store
partition must make the minority refuse (named ``NoQuorumError``)
while the majority serves, and heal must rejoin it with its stale
intents discarded (committed
``benchmarks/results/federation_partition_{pre,post}.json``).
"""

from __future__ import annotations

import os
import socket as _socket
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from . import federation_store as _fstore
from . import resilience as _resilience
from . import telemetry as _telemetry
from .errors import NoQuorumError
from .membership import _pid_alive
from .transport.base import TransportError

# One leadership/liveness knob: a leader lease (and a server endpoint
# record) untouched for this long belongs to a dead or frozen process
# and is taken over.  Authority self-expires at HALF this bound
# (_VALIDITY_FRACTION), so an ex-holder's authority provably lapses
# before any takeover can begin — the no-overlap invariant the
# split-brain test asserts.  Per-server override: WorldServer
# fed_lease_timeout_s / ``launcher serve --fed-lease-timeout``.
_LEASE_TIMEOUT_S = 3.0
_VALIDITY_FRACTION = 0.5

# Endpoint records are judged dead a bit later than the leader lease
# (renewals ride the same tick; the margin absorbs one missed tick
# under load before a takeover storm starts).
_SERVER_STALE_FACTOR = 1.5

_TICK_S = 0.25          # federation member duty cadence
_LEASE_KEY = "leader.lease"
_OWNER_POLL_S = 0.1     # orphaned-worker resolve cadence

# Client-side liveness filter for endpoint records: liberal (a dial
# failure skips a dead candidate anyway); the pid check does the fast
# discrimination for same-host records.
_CLIENT_RECORD_STALE_S = 10.0

_HOSTNAME = _socket.gethostname()

# store-read failures helpers swallow (a raft client store with every
# node briefly unreachable raises OSError; a directory listing of a
# torn-down namespace likewise) — reads degrade to "nothing visible",
# mutations surface their errors to the caller
_READ_ERRORS = (OSError, NoQuorumError)


def _store(ns: Any) -> "_fstore.NamespaceStore":
    """Namespace spec (dir path / raft: spec / store instance) → store
    handle.  Cached per spec inside federation_store.resolve_store."""
    return _fstore.resolve_store(ns)


def _ns_name(ns: Any) -> str:
    return ns.describe() if isinstance(ns, _fstore.NamespaceStore) \
        else str(ns)


# -- namespace record helpers -------------------------------------------------


def _server_key(sid: str) -> str:
    return f"server.{sid}"


def _pool_key(pool_id: str) -> str:
    return f"pool.{pool_id}"


def _takeover_key(sid: str) -> str:
    return f"takeover.{sid}"


def _log_key(sid: str) -> str:
    return f"leader.log.{sid}"


def read_server_records(ns: Any) -> Dict[str, dict]:
    """All ``server.<id>`` endpoint records in the namespace."""
    out: Dict[str, dict] = {}
    try:
        recs = _store(ns).scan("server.")
    except _READ_ERRORS:
        return out
    for rec in recs.values():
        val = rec.value
        if val and val.get("id"):
            out[val["id"]] = val
    return out


def read_server_record(ns: Any, sid: str) -> Optional[dict]:
    try:
        rec = _store(ns).get(_server_key(sid))
    except _READ_ERRORS:
        return None
    return None if rec is None else rec.value


def read_leader(ns: Any) -> Optional[dict]:
    """The current ``leader.lease`` content (holder id/pid/term), or
    None with no leader elected — a RELEASED lease (clean shutdown
    left the record as a term tombstone) reads as no leader.  Record
    ownership only — whether the holder's AUTHORITY is still valid is
    its own clock's business (LeaderLease.is_leader)."""
    try:
        rec = _store(ns).get(_LEASE_KEY)
    except _READ_ERRORS:
        return None
    if rec is None or rec.value is None or rec.value.get("released"):
        return None
    return rec.value


def record_live(rec: dict, now: Optional[float] = None,
                stale_s: float = _CLIENT_RECORD_STALE_S) -> bool:
    """Is this endpoint record's server alive?  Dead pid → dead NOW
    (kill -9 detection is one stat) — but only for a record written on
    THIS host; a pid from another host is meaningless here, so remote
    records are judged by renewal staleness alone (the frozen-server
    case: SIGSTOP keeps the pid but stops the renewals)."""
    host = rec.get("host")
    if host is None or host == _HOSTNAME:
        pid = rec.get("pid")
        if pid is not None and not _pid_alive(int(pid)):
            return False
    now = time.time() if now is None else now
    return now - float(rec.get("renewed_at", 0)) <= stale_s


def write_pool_owner(ns: Any, pool_id: str, owner: str, ctrl: str,
                     rdv: str, backend: str, size: int, epoch: int,
                     term: int, since: Optional[float] = None) -> None:
    """Publish/replace the ownership record of one pool.  ``since`` is
    the wall time ownership began — an ex-owner relinquishes on seeing
    a record with a different owner and a ``since`` at or past its own
    (the thawed-usurped-server demotion path)."""
    _store(ns).put(_pool_key(pool_id), {
        "pool": pool_id, "owner": owner, "ctrl": ctrl, "rdv": rdv,
        "backend": backend, "size": int(size), "epoch": int(epoch),
        "term": int(term),
        "since": time.time() if since is None else float(since)})


def read_pool_owner(ns: Any, pool_id: str) -> Optional[dict]:
    try:
        rec = _store(ns).get(_pool_key(pool_id))
    except _READ_ERRORS:
        return None
    return None if rec is None else rec.value


def read_pool_owners(ns: Any) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    try:
        recs = _store(ns).scan("pool.")
    except _READ_ERRORS:
        return out
    for rec in recs.values():
        if rec.value and rec.value.get("pool"):
            out[rec.value["pool"]] = rec.value
    return out


def read_takeovers(ns: Any) -> List[dict]:
    try:
        recs = _store(ns).scan("takeover.")
    except _READ_ERRORS:
        return []
    return [rec.value for rec in recs.values() if rec.value]


def wait_pool_owner(ns: Any, pool_id: str, not_ctrl: Optional[str],
                    timeout: float,
                    stale_s: float = _CLIENT_RECORD_STALE_S
                    ) -> Optional[str]:
    """Orphaned-worker resolve: block until the pool's ownership record
    names a control address other than ``not_ctrl`` (the address whose
    ESTABLISHED registration just died; None excludes nothing — a
    merely-unreachable owner may resolve again) and its owner's
    endpoint record, when present, reads live — or the orphan budget
    runs out (→ None: the worker exits rather than leak).  Each
    death round passes its own just-dead address, so a chain of server
    deaths keeps resolving forward."""
    st = _store(ns)
    deadline = time.monotonic() + timeout
    while True:
        try:
            prec = st.get(_pool_key(pool_id))
            rec = None if prec is None else prec.value
            if rec is not None and rec.get("ctrl") \
                    and rec["ctrl"] != not_ctrl:
                srec = st.get(_server_key(str(rec.get("owner"))))
                srv = None if srec is None else srec.value
                if srv is None or record_live(srv, stale_s=stale_s):
                    return rec["ctrl"]
        except _READ_ERRORS:
            pass  # store briefly unreachable: the budget is the bound
        if time.monotonic() > deadline:
            return None
        time.sleep(_OWNER_POLL_S)


# -- the leader lease ---------------------------------------------------------


class LeaderLease:
    """Store-lease leader election (the FileBoard
    ``pending.summary.lock`` idiom, grown the two properties an
    AUTHORITY needs that a compaction lock does not — and, since
    ISSUE 18, rebuilt on the store CAS so both properties are
    arbitration, not timing):

    * **bounded authority** — holding the record is necessary but not
      sufficient; :meth:`is_leader` is true only within ``validity_s``
      of the last *successful* renew, and ``validity_s`` is strictly
      below the takeover bound, so a frozen holder's authority lapses
      before a usurper's can begin.  On the replicated store a
      minority-side renew raises ``NoQuorumError``: the holder does
      not demote (post-heal it may still rightfully hold) but cannot
      extend either — authority lapses, the minority refuses.
    * **immutable content per term** — the lease content (id, pid,
      host, term) is fixed at acquisition; a renew re-commits the SAME
      content by CAS against the exact version last observed, which
      refreshes the record's write stamp (the staleness clock).  A
      takeover CAS'es against a stale record's version with term+1.
      Any interleaving of a thawed holder's renew and a takeover is a
      single-winner CAS race — the PR-15 accepted window (takeover
      re-stat → unlink straddled by a renew) is structurally gone.

    Every acquire and renew appends the authority interval
    ``[from, until]`` to the ``leader.log.<id>`` append-only log (one
    writer per log — no contention) BEFORE the validity extension
    takes effect: an interval that cannot be logged is not granted.
    :func:`assert_no_leader_overlap` checks the whole namespace's
    history for the split-brain condition."""

    def __init__(self, ns: Any, owner_id: str,
                 lease_timeout_s: float = _LEASE_TIMEOUT_S) -> None:
        self.ns = ns
        self.store = _store(ns)
        self.owner_id = owner_id
        self.lease_timeout_s = float(lease_timeout_s)
        self.validity_s = _VALIDITY_FRACTION * self.lease_timeout_s
        self.term = 0
        self.takeovers = 0        # stale leases reclaimed by US
        self.demotions = 0        # times we discovered usurpation
        self.quorum_stalls = 0    # renews refused by NoQuorumError
        self._held = False
        self._valid_until_mono = 0.0
        self._content: dict = {}

    def is_leader(self) -> bool:
        """Authority check — NOT just record ownership: false the
        moment ``validity_s`` elapses since the last successful renew,
        which is how a frozen (or minority-partitioned) leader knows
        it must re-verify before acting."""
        return self._held and time.monotonic() < self._valid_until_mono

    def _mine(self, val: Optional[dict]) -> bool:
        return (val is not None and not val.get("released")
                and val.get("id") == self.owner_id
                and val.get("pid") == os.getpid()
                and val.get("host", _HOSTNAME) == _HOSTNAME
                and int(val.get("term", -1)) == self.term)

    def _log_interval(self, now_wall: float) -> None:
        # raises on failure (quorum loss / namespace teardown): the
        # caller treats an unlogged extension as no extension
        self.store.append(_log_key(self.owner_id), {
            "id": self.owner_id, "term": self.term,
            "from": now_wall, "until": now_wall + self.validity_s})

    def tick(self) -> bool:
        """Acquire-or-renew; returns whether we hold valid authority
        after the tick.  Called on the federation member cadence."""
        return self._renew() if self._held else self._try_acquire()

    def _try_acquire(self) -> bool:
        st = self.store
        try:
            cur = st.get(_LEASE_KEY)
        except _READ_ERRORS:
            return False
        next_term = self.term + 1
        expect = None
        takeover = False
        if cur is not None and cur.value is not None:
            val = cur.value
            next_term = max(next_term, int(val.get("term", 0)) + 1)
            expect = cur.ver
            if not val.get("released"):
                # a released lease is a term TOMBSTONE (clean
                # shutdown): immediately claimable — and the term
                # history survives it.  A live one must be stale.
                if time.time() - cur.stamp < self.lease_timeout_s:
                    return False  # live holder
                takeover = True
        now_mono, now_wall = time.monotonic(), time.time()
        content = {"id": self.owner_id, "pid": os.getpid(),
                   "host": _HOSTNAME, "term": next_term,
                   "acquired_at": now_wall}
        try:
            # THE arbitration: against the exact version we judged
            # stale (or absence).  A renew that landed since — or a
            # rival takeover — moved the version, and we lose cleanly.
            rec = st.cas(_LEASE_KEY, expect, content)
        except NoQuorumError:
            self.quorum_stalls += 1
            return False  # minority side: authority refused, by design
        except OSError:
            return False  # store unreachable / namespace teardown
        if rec is None:
            return False  # lost the CAS race
        self.term = next_term
        self._content = content
        self._lease_ver = rec.ver
        if takeover:
            self.takeovers += 1
        self._held = True
        try:
            self._log_interval(now_wall)
        except _READ_ERRORS:
            # we hold the record but could not log the interval: grant
            # ZERO validity (we never act on unlogged authority); the
            # next tick renews and retries the log
            self._valid_until_mono = 0.0
            return False
        # authority anchored BEFORE the write: conservative
        self._valid_until_mono = now_mono + self.validity_s
        rec_t = _telemetry.REC
        if rec_t is not None:
            rec_t.emit("serve", "leader_elected",
                       attrs={"id": self.owner_id, "term": self.term,
                              "takeover": self.takeovers > 0})
        return True

    def _renew(self) -> bool:
        st = self.store
        now_mono, now_wall = time.monotonic(), time.time()
        try:
            cur = st.get(_LEASE_KEY)
        except _READ_ERRORS:
            return False  # cannot verify: no extension, let it lapse
        if cur is None or not self._mine(cur.value):
            return self._demote("usurped")
        try:
            rec = st.cas(_LEASE_KEY, cur.ver, self._content)
        except NoQuorumError:
            # minority side of a partition: we may STILL be the
            # rightful holder (the majority has judged nothing yet) —
            # do not demote, but do not extend: authority lapses
            # within validity_s and this side refuses leadership
            self.quorum_stalls += 1
            return False
        except OSError:
            return False
        if rec is None:
            # single-winner CAS: a takeover landed between our read
            # and our write — the structural replacement for the
            # PR-15 re-stat window
            return self._demote("usurped")
        self._lease_ver = rec.ver
        try:
            self._log_interval(now_wall)
        except _READ_ERRORS:
            return False  # unlogged extension = no extension
        self._valid_until_mono = now_mono + self.validity_s
        return True

    def _demote(self, why: str) -> bool:
        self._held = False
        self._valid_until_mono = 0.0
        self.demotions += 1
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("serve", "leader_demoted",
                     attrs={"id": self.owner_id, "term": self.term,
                            "why": why})
        return False

    def release(self) -> None:
        """Clean handoff at shutdown: mark the lease RELEASED (a term
        tombstone the next acquirer claims immediately and bumps past —
        deleting would lose the term history) and log the reign's end,
        capping our authority interval at NOW rather than letting the
        last renew's ``until`` imply authority we gave up."""
        held, self._held = self._held, False
        self._valid_until_mono = 0.0
        if not held:
            return
        now_wall = time.time()
        try:
            cur = self.store.get(_LEASE_KEY)
            if cur is not None and self._mine(cur.value):
                self.store.cas(_LEASE_KEY, cur.ver,
                               {**self._content, "released": True})
            self.store.append(_log_key(self.owner_id), {
                "id": self.owner_id, "term": self.term,
                "release": True, "until": now_wall})
        except _READ_ERRORS:
            pass


def assert_no_leader_overlap(ns: Any) -> List[dict]:
    """THE split-brain assertion: parse every server's authority-
    interval log and verify no two DIFFERENT servers' intervals
    overlap.  Returns the parsed intervals (sorted) for diagnostics;
    raises AssertionError naming the clash.  The intervals are what
    each server believed its authority to be (from its own renews),
    logged conservatively — an overlap here means two servers could
    both have acted as leader at one instant."""
    raw: List[dict] = []
    try:
        logs = _store(ns).log_scan("leader.log.")
    except _READ_ERRORS:
        logs = {}
    for entries in logs.values():
        raw.extend(entries)
    # a release record caps its (id, term) reign at the release instant
    # — authority voluntarily given up must not read as held through
    # the last renew's validity window
    releases: Dict[tuple, float] = {}
    for e in raw:
        if e.get("release"):
            key = (e["id"], e.get("term"))
            releases[key] = min(releases.get(key, float("inf")),
                                float(e["until"]))
    intervals = []
    for e in raw:
        if e.get("release"):
            continue
        cap = releases.get((e["id"], e.get("term")))
        e = dict(e)
        if cap is not None:
            e["until"] = min(float(e["until"]), cap)
        if e["until"] > e["from"]:
            intervals.append(e)
    intervals.sort(key=lambda e: e["from"])
    # merge per-id runs first (renews of one reign overlap by design)
    merged: List[dict] = []
    for e in intervals:
        if merged and merged[-1]["id"] == e["id"] \
                and e["from"] <= merged[-1]["until"]:
            merged[-1]["until"] = max(merged[-1]["until"], e["until"])
        else:
            merged.append(dict(e))
    for a, b in zip(merged, merged[1:]):
        if a["id"] != b["id"] and b["from"] < a["until"]:
            raise AssertionError(
                f"leader authority overlap: {a['id']} (term {a['term']}) "
                f"held until {a['until']:.3f} but {b['id']} (term "
                f"{b['term']}) began at {b['from']:.3f} "
                f"({a['until'] - b['from']:.3f}s overlap)")
    return merged


# -- the per-server federation member ----------------------------------------


class FederationMember:
    """The federation duties of ONE server, run on a daemon thread at
    ``_TICK_S``: renew the endpoint record, tick the leader lease,
    publish/verify pool ownership (relinquishing pools a usurper took
    while we were frozen), consume takeover assignments addressed to
    us, and — while holding valid leader authority — assign dead
    servers' pools to survivors and garbage-collect their records.
    A tick that raises logs a structured line and keeps ticking (the
    serve monitor-loop rule: the fabric's lifeline must not die of one
    exception).  On a ``raft:<idx>@...`` namespace spec the member
    STARTS its embedded store node; a tick on the minority side of a
    partition (store unhealthy) skips every mutation — the lease
    lapses, the admission fence in serve.py refuses clients, and the
    majority side carries the fabric."""

    def __init__(self, server, ns: Any,
                 server_id: Optional[str] = None,
                 lease_timeout_s: float = _LEASE_TIMEOUT_S,
                 tick_s: float = _TICK_S) -> None:
        self.server = server
        self.ns = ns
        self.store, self._owns_store = _fstore.resolve_member_store(ns)
        self.server_id = server_id or ("srv-" + uuid.uuid4().hex[:8])
        self.lease = LeaderLease(self.store, self.server_id,
                                 lease_timeout_s)
        self.tick_s = float(tick_s)
        self.server_stale_s = _SERVER_STALE_FACTOR * float(lease_timeout_s)
        self.started_at = time.time()
        self.unhealthy_ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def is_leader(self) -> bool:
        return self.lease.is_leader()

    def healthy(self) -> bool:
        """Can this member's store commit (quorum reachability)?  The
        serve.py admission fence consults this: a minority-side server
        refuses new leases with the named ``NoQuorumError``."""
        return self.store.healthy()

    def start(self) -> "FederationMember":
        self._tick_safe()  # register synchronously: visible on return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"fed-{self.server_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # clean departure: release the lease, retract our records (the
        # pools die with an orderly stop() — serve shuts the workers
        # down — so their ownership records retract too)
        try:
            self.lease.release()
            for pool_id, rec in read_pool_owners(self.store).items():
                if rec.get("owner") == self.server_id:
                    self.store.delete(_pool_key(pool_id))
            self.store.delete(_server_key(self.server_id))
        except _READ_ERRORS:
            pass  # partitioned/torn-down at exit: records go stale
        if self._owns_store:
            self.store.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            self._tick_safe()

    def _tick_safe(self) -> None:
        try:
            self._tick()
        except Exception as e:  # noqa: BLE001 - the fabric's lifeline
            if self._stop.is_set():
                return
            import sys
            import traceback

            sys.stderr.write(
                f"mpi_tpu.federation: member tick failed "
                f"({type(e).__name__}: {str(e)[:200]}) — ticking on:\n"
                f"{traceback.format_exc()}")

    # -- duties ------------------------------------------------------------

    def _tick(self) -> None:
        now = time.time()
        if not self.store.healthy():
            # minority side: every mutation below would burn a propose
            # timeout and fail with NoQuorumError anyway.  Skip the
            # tick wholesale — the lease lapses by not renewing
            # (is_leader() goes false within validity_s), the stale
            # endpoint record steers clients at the majority, and the
            # admission fence names the refusal.
            self.unhealthy_ticks += 1
            rec = _telemetry.REC
            if rec is not None:
                rec.emit("serve", "fed_tick_no_quorum",
                         attrs={"id": self.server_id})
            return
        self._write_server_record(now)
        leading = self.lease.tick()
        # ONE pool-record snapshot per tick, shared by every duty
        # (each used to rescan the namespace itself — 3-4 scans per
        # 250ms tick per server, multiplied across the fabric);
        # staleness within a tick is harmless, every consumer
        # re-checks live server state before acting
        owners = read_pool_owners(self.store)
        self._verify_pool_ownership(owners)
        self._reclaim_ghost_pools(owners)
        self._consume_assignments()
        if leading and self.lease.is_leader():
            self._leader_duties(now, owners)

    def _write_server_record(self, now: float) -> None:
        self.store.put(_server_key(self.server_id), {
            "id": self.server_id, "pid": os.getpid(),
            "host": _HOSTNAME,
            "ctrl": self.server.addr,
            "metrics": getattr(self.server, "metrics_addr", None),
            "started_at": self.started_at, "renewed_at": now,
            "is_leader": self.lease.is_leader(),
            "term": self.lease.term,
            "summary": self.server.fed_summary()})

    def _verify_pool_ownership(self, owners: Dict[str, dict]) -> None:
        """Publish ownership for pools we hold; RELINQUISH any pool the
        namespace says a usurper took over while we were frozen (the
        split-brain-avoidance half of pool handover: our closing of the
        worker control connections is what releases the workers)."""
        for pool_id, meta in self.server.owned_pool_records().items():
            rec = owners.get(pool_id)
            if rec is None:
                write_pool_owner(
                    self.store, pool_id, owner=self.server_id,
                    ctrl=self.server.addr, rdv=meta["rdv"],
                    backend=meta["backend"], size=meta["size"],
                    epoch=meta["epoch"], term=self.lease.term,
                    since=meta["since"])
            elif (rec.get("owner") != self.server_id
                  and float(rec.get("since", 0)) >= float(meta["since"])):
                self.server.relinquish_pool(pool_id, rec.get("owner"))

    def _reclaim_ghost_pools(self, owners: Dict[str, dict]) -> None:
        """A pool record naming US that we do not actually serve is a
        ghost of our PREVIOUS incarnation (a restart under a stable
        ``--server-id``): the record reads live to the leader (our new
        pid renews ``server.<id>``), so no takeover will ever fire for
        it — reclaim it ourselves.  The old incarnation's warm orphans
        are excluding its DEAD control address in their re-resolve;
        rewriting the record with our new address is what brings them
        home."""
        owned = self.server.owned_pool_records()
        for pool_id, rec in owners.items():
            if rec.get("owner") != self.server_id or pool_id in owned:
                continue
            if self.server.adopt_pool(pool_id, rec,
                                      term=self.lease.term):
                write_pool_owner(
                    self.store, pool_id, owner=self.server_id,
                    ctrl=self.server.addr, rdv=rec["rdv"],
                    backend=rec.get("backend", "socket"),
                    size=int(rec["size"]),
                    epoch=int(rec.get("epoch", 0)),
                    term=self.lease.term)

    def _consume_assignments(self) -> None:
        for t in read_takeovers(self.store):
            if t.get("to") != self.server_id:
                continue
            for pool_id, prec in (t.get("pools") or {}).items():
                cur = read_pool_owner(self.store, pool_id)
                if cur is not None and cur.get("owner") not in (
                        t.get("dead"), self.server_id):
                    continue  # moved again since: stale assignment
                if cur is not None and cur.get("owner") == self.server_id:
                    continue  # already adopted
                if self.server.adopt_pool(pool_id, prec,
                                          term=int(t.get("term", 0))):
                    write_pool_owner(
                        self.store, pool_id, owner=self.server_id,
                        ctrl=self.server.addr, rdv=prec["rdv"],
                        backend=prec.get("backend", "socket"),
                        size=int(prec["size"]),
                        epoch=int(prec.get("epoch", 0)),
                        term=int(t.get("term", 0)))

    def _leader_duties(self, now: float,
                       owners: Dict[str, dict]) -> None:
        records = read_server_records(self.store)
        live = {sid for sid, r in records.items()
                if sid == self.server_id
                or record_live(r, now, self.server_stale_s)}
        for sid, r in records.items():
            if sid in live:
                continue
            dead_pools = {pid: rec for pid, rec in owners.items()
                          if rec.get("owner") == sid}
            if dead_pools:
                existing = None
                trec = self.store.get(_takeover_key(sid))
                if trec is not None:
                    existing = trec.value
                if existing is None or existing.get("to") not in live:
                    target = self._choose_survivor(live, owners)
                    if target is not None and self.lease.is_leader():
                        # assignments carry the term they were decided
                        # under — written ONLY with valid authority
                        self.store.put(_takeover_key(sid), {
                            "dead": sid, "to": target,
                            "term": self.lease.term, "at": now,
                            "pools": dead_pools})
                        rec_t = _telemetry.REC
                        if rec_t is not None:
                            rec_t.emit("serve", "takeover_assigned",
                                       attrs={"dead": sid, "to": target,
                                              "pools":
                                              sorted(dead_pools)})
            else:
                # fully relieved (or never owned a pool): GC the corpse
                self.store.delete(_server_key(sid))
                self.store.delete(_takeover_key(sid))

    def _choose_survivor(self, live: set,
                         owners: Dict[str, dict]) -> Optional[str]:
        """Least-loaded live server (fewest owned pools, id tiebreak) —
        the leader may assign to itself."""
        if not live:
            return None
        load = {sid: 0 for sid in live}
        for rec in owners.values():
            if rec.get("owner") in load:
                load[rec["owner"]] += 1
        return min(sorted(load), key=lambda sid: load[sid])


# -- namespace roll-up --------------------------------------------------------


def federation_stats(ns: Any) -> dict:
    """Aggregate the namespace: one document summing the live servers'
    summaries (worlds/s, workers, idle, pools, waiting) plus the
    current leader — what keeps the PR-13 Prometheus endpoint truthful
    when pools move between servers.  Pure store reads: scrape-safe,
    callable with zero servers reachable (and on the MINORITY side of
    a store partition, where it reports the last applied state)."""
    now = time.time()
    records = read_server_records(ns)
    lease = read_leader(ns)
    servers = {}
    totals = {"worlds_per_s": 0.0, "workers": 0, "idle": 0, "pools": 0,
              "leases_active": 0, "waiting": 0}
    live = 0
    for sid, r in sorted(records.items()):
        alive = record_live(r, now)
        summary = r.get("summary") or {}
        servers[sid] = {"live": alive, "ctrl": r.get("ctrl"),
                        "is_leader": bool(r.get("is_leader")),
                        **summary}
        if alive:
            live += 1
            for k in totals:
                totals[k] = totals[k] + summary.get(k, 0)
    totals["worlds_per_s"] = round(totals["worlds_per_s"], 3)
    return {"namespace": _ns_name(ns), "servers_total": len(records),
            "servers_live": live,
            "leader": lease.get("id") if lease else None,
            "leader_term": int(lease.get("term", 0)) if lease else 0,
            "servers": servers, **totals}


# -- the failover client ------------------------------------------------------


class FederatedClient:
    """Client handle to a FEDERATION of world servers: resolve live
    endpoints from a namespace (dir or ``raft:`` spec, and/or a static
    address list), and fail acquire/stats over to a survivor on a
    dead-server ``ServerLostError`` — or a partitioned minority
    server's ``NoQuorumError`` — with backoff, bounded by the
    ``connect_retry_timeout_s`` budget.  Lease semantics are the
    single-server ones: re-acquire after a failover is idempotent (the
    lost lease died with its server), and an in-flight ``lease.run``
    surfaces the named error — jobs are not transparently re-run."""

    def __init__(self, namespace: Optional[str] = None,
                 addrs: Optional[List[Any]] = None,
                 timeout: float = 30.0, priority: int = 0,
                 failover_timeout_s: Optional[float] = None) -> None:
        if not namespace and not addrs:
            raise ValueError("FederatedClient needs a namespace "
                             "and/or a server address list")
        self._ns = namespace
        self._static = ["%s:%s" % tuple(a) if isinstance(a, (tuple, list))
                        else str(a) for a in (addrs or [])]
        self._timeout = float(timeout)
        self._priority = int(priority)
        self._id = uuid.uuid4().hex  # one fair-share identity across servers
        self._failover_s = failover_timeout_s
        self._client = None
        self._addr: Optional[str] = None
        self._rr = 0
        self.failovers = 0

    # -- endpoint resolution ----------------------------------------------

    def _budget(self) -> float:
        if self._failover_s is not None:
            return float(self._failover_s)
        from . import mpit as _mpit

        return float(_mpit.cvar_read("connect_retry_timeout_s"))

    def _candidates(self) -> List[str]:
        out = list(self._static)
        if self._ns:
            now = time.time()
            # freshest renewal first: a SIGSTOP-frozen server's record
            # passes record_live until it ages past the stale bound,
            # but its renewals have already stopped — ordering by
            # recency steers a fresh client at the actively-renewing
            # survivor instead of the silent not-yet-stale ex-leader
            # (id order was the tiebreak that dialed the frozen one
            # first every time).  Ties (all healthy) stay deterministic
            # via the id in the sort key.  The same ordering is the
            # partition play: minority-side servers stop renewing
            # their records, so clients drain toward the majority.
            recs = sorted(read_server_records(self._ns).items(),
                          key=lambda kv: (-float(
                              kv[1].get("renewed_at", 0)), kv[0]))
            for sid, rec in recs:
                if rec.get("ctrl") and record_live(rec, now) \
                        and rec["ctrl"] not in out:
                    out.append(rec["ctrl"])
        return out

    def _ensure(self):
        if self._client is not None:
            return self._client
        from . import serve as _serve

        deadline = time.monotonic() + max(self._budget(), 0.0)
        delays = _resilience.backoff_delays()
        last_err: Optional[BaseException] = None
        while True:
            cands = self._candidates()
            for i in range(len(cands)):
                addr = cands[(self._rr + i) % len(cands)]
                host, _, port = addr.rpartition(":")
                try:
                    # a short per-candidate dial budget: OUR loop is
                    # the patience; a dead candidate must not eat the
                    # whole failover budget before the next is tried.
                    # The cap applies to the SINGLE connect attempt
                    # too (timeout=), not just the retry loop — a
                    # SYN-blackholed candidate would otherwise block
                    # the full client timeout before the live survivor
                    # is ever dialed
                    c = _serve.ServerClient(
                        host, int(port),
                        timeout=min(self._timeout, 2.0),
                        priority=self._priority, client_id=self._id,
                        dial_retry_s=0.5)
                except OSError as e:
                    last_err = e
                    continue
                self._client, self._addr = c, addr
                self._rr = (self._rr + i + 1) % max(1, len(cands))
                return c
            if time.monotonic() > deadline:
                raise _serve.ServerLostError(
                    f"no live federation server reachable "
                    f"(candidates {cands or 'none'}; last: "
                    f"{type(last_err).__name__ if last_err else 'none'}: "
                    f"{last_err})")
            time.sleep(min(next(delays), 0.5))

    def _drop(self) -> None:
        c, self._client, self._addr = self._client, None, None
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def _with_failover(self, op):
        from .serve import ServerLostError

        deadline = time.monotonic() + max(self._budget(), 0.0)
        delays = _resilience.backoff_delays()
        while True:
            client = self._ensure()
            try:
                return op(client)
            except (ServerLostError, NoQuorumError, OSError) as e:
                if isinstance(e, TimeoutError) \
                        and not isinstance(e, ServerLostError):
                    # a LEASE timeout (TimeoutError is an OSError
                    # subclass!) is the live server's named verdict,
                    # not a dead server — never a failover signal
                    raise
                # NoQuorumError IS a failover signal: the server is
                # alive but on the minority side of a store partition —
                # refusing by design; a majority-side server can serve
                self._drop()
                self.failovers += 1
                if time.monotonic() > deadline:
                    raise
                time.sleep(min(next(delays), 0.25))

    # -- the ServerClient surface ------------------------------------------

    @property
    def addr(self) -> Optional[str]:
        """Control address currently connected (None when dropped)."""
        return self._addr

    def acquire(self, nranks: int, timeout: Optional[float] = None,
                priority: Optional[int] = None):
        """Lease ``nranks`` warm workers from any live server —
        failover-transparent (re-acquire is idempotent).  Named
        non-failover errors propagate: ``ServerBusyError`` (admission
        rejection), ``TimeoutError`` (pool busy past the bound)."""
        return self._with_failover(
            lambda c: c.acquire(nranks, timeout=timeout,
                                priority=priority))

    def run(self, fn, *args: Any, nranks: int = 2,
            timeout: Optional[float] = None) -> Any:
        """acquire (with failover) + run + release.  A server death
        MID-JOB raises the named ``ServerLostError`` — the job may have
        side effects, so re-running it is the caller's decision."""
        lease = self.acquire(nranks, timeout=timeout)
        try:
            return lease.run(fn, *args, timeout=timeout)
        finally:
            try:
                lease.release()
            except (TransportError, OSError):
                pass  # server gone: the lease died with it

    def stats(self) -> dict:
        """One live server's stats document (failover-transparent);
        federated servers embed the namespace roll-up under
        ``"federation"``."""
        return self._with_failover(lambda c: c.stats())

    def federation_stats(self) -> dict:
        """The namespace roll-up directly (no server round-trip)."""
        if not self._ns:
            return self.stats().get("federation") or {}
        return federation_stats(self._ns)

    def close(self) -> None:
        self._drop()

    def __enter__(self) -> "FederatedClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
