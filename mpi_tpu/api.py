"""Flat MPI_* function layer (L4 of SURVEY.md §1; BASELINE.json:5 API surface).

Thin wrappers over the world communicator so classic MPI-style programs read
naturally::

    from mpi_tpu.api import *
    MPI_Init()
    rank = MPI_Comm_rank()
    if rank == 0:
        MPI_Send(data, dest=1)
    ...
    MPI_Finalize()
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from . import ops
from .communicator import Communicator, Status
from .transport.base import ANY_SOURCE, ANY_TAG

__all__ = [
    "MPI_Init", "MPI_Finalize", "MPI_Initialized", "MPI_COMM_WORLD",
    "MPI_Comm_rank", "MPI_Comm_size", "MPI_Send", "MPI_Recv", "MPI_Sendrecv",
    "MPI_Bcast", "MPI_Reduce", "MPI_Allreduce", "MPI_Allgather", "MPI_Alltoall",
    "MPI_Barrier", "MPI_Comm_split", "MPI_Comm_dup", "MPI_Scatter", "MPI_Gather",
    "MPI_Scan", "MPI_Reduce_scatter", "MPI_Isend", "MPI_Irecv", "MPI_Wait",
    "MPI_Test", "MPI_Waitall", "MPI_Probe", "MPI_Iprobe", "MPI_Wtime",
    "ANY_SOURCE", "ANY_TAG", "SUM", "PROD", "MAX", "MIN", "Status",
]

SUM, PROD, MAX, MIN = ops.SUM, ops.PROD, ops.MAX, ops.MIN


def _world(comm: Optional[Communicator]) -> Communicator:
    if comm is not None:
        return comm
    from . import init

    return init()


def MPI_Init(backend: Optional[str] = None) -> Communicator:
    from . import init

    return init(backend)


def MPI_Initialized() -> bool:
    from . import is_initialized

    return is_initialized()


def MPI_Finalize() -> None:
    from . import finalize

    finalize()


def MPI_COMM_WORLD() -> Communicator:
    return _world(None)


def MPI_Comm_rank(comm: Optional[Communicator] = None) -> int:
    return _world(comm).rank


def MPI_Comm_size(comm: Optional[Communicator] = None) -> int:
    return _world(comm).size


def MPI_Send(obj: Any, dest: int, tag: int = 0, comm: Optional[Communicator] = None) -> None:
    _world(comm).send(obj, dest, tag)


def MPI_Recv(source: int = ANY_SOURCE, tag: int = ANY_TAG,
             comm: Optional[Communicator] = None,
             status: Optional[Status] = None) -> Any:
    return _world(comm).recv(source, tag, status)


def MPI_Sendrecv(sendobj: Any, dest: int, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG,
                 comm: Optional[Communicator] = None) -> Any:
    return _world(comm).sendrecv(sendobj, dest, source, sendtag, recvtag)


def MPI_Bcast(obj: Any, root: int = 0, comm: Optional[Communicator] = None) -> Any:
    return _world(comm).bcast(obj, root)


def MPI_Reduce(obj: Any, op: ops.ReduceOp = ops.SUM, root: int = 0,
               comm: Optional[Communicator] = None) -> Any:
    return _world(comm).reduce(obj, op, root)


def MPI_Allreduce(obj: Any, op: ops.ReduceOp = ops.SUM, algorithm: str = "auto",
                  comm: Optional[Communicator] = None) -> Any:
    return _world(comm).allreduce(obj, op, algorithm)


def MPI_Allgather(obj: Any, comm: Optional[Communicator] = None) -> Any:
    return _world(comm).allgather(obj)


def MPI_Alltoall(objs: Sequence[Any], comm: Optional[Communicator] = None) -> Any:
    return _world(comm).alltoall(objs)


def MPI_Barrier(comm: Optional[Communicator] = None) -> None:
    _world(comm).barrier()


def MPI_Comm_split(color: Optional[int], key: int = 0,
                   comm: Optional[Communicator] = None) -> Optional[Communicator]:
    return _world(comm).split(color, key)


def MPI_Comm_dup(comm: Optional[Communicator] = None) -> Communicator:
    return _world(comm).dup()


def MPI_Scatter(objs: Optional[Sequence[Any]], root: int = 0,
                comm: Optional[Communicator] = None) -> Any:
    return _world(comm).scatter(objs, root)


def MPI_Gather(obj: Any, root: int = 0, comm: Optional[Communicator] = None) -> Any:
    return _world(comm).gather(obj, root)


def MPI_Isend(obj: Any, dest: int, tag: int = 0,
              comm: Optional[Communicator] = None):
    return _world(comm).isend(obj, dest, tag)


def MPI_Irecv(source: int = ANY_SOURCE, tag: int = ANY_TAG,
              comm: Optional[Communicator] = None):
    return _world(comm).irecv(source, tag)


def MPI_Wait(request) -> Any:
    return request.wait()


def MPI_Test(request):
    return request.test()


def MPI_Waitall(requests) -> list:
    return [r.wait() for r in requests]


def MPI_Probe(source: int = ANY_SOURCE, tag: int = ANY_TAG,
              comm: Optional[Communicator] = None, status=None) -> None:
    _world(comm).probe(source, tag, status)


def MPI_Iprobe(source: int = ANY_SOURCE, tag: int = ANY_TAG,
               comm: Optional[Communicator] = None, status=None) -> bool:
    return _world(comm).iprobe(source, tag, status)


def MPI_Wtime() -> float:
    import time

    return time.perf_counter()


def MPI_Scan(obj: Any, op: ops.ReduceOp = ops.SUM,
             comm: Optional[Communicator] = None) -> Any:
    return _world(comm).scan(obj, op)


def MPI_Reduce_scatter(blocks: Any, op: ops.ReduceOp = ops.SUM,
                       comm: Optional[Communicator] = None) -> Any:
    return _world(comm).reduce_scatter(blocks, op)
