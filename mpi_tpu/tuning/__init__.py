"""Topology-aware tuned dispatch (ISSUE 9): measured per-machine
algorithm-selection tables replacing the hardcoded crossovers.

* :mod:`~mpi_tpu.tuning.table` — the versioned JSON table format
  (machine fingerprint, trust-stamped (transport, nranks, collective,
  payload-band) -> algorithm rows) + strict validation.
* :mod:`~mpi_tpu.tuning.resolver` — the process-wide `pick` every
  ``algorithm="auto"`` decision consults (``tuned_table_hits`` /
  ``tuned_table_fallbacks`` pvars; ``tuning_table_path`` cvar /
  ``MPI_TPU_TUNING_TABLE`` / ``run_local(tuning_table=)`` / launcher
  ``--tuning-table``).
* ``tools/tune.py`` — the sweep generator that measures and emits a
  table for THIS machine (``--check`` validates committed ones in CI).
"""

from .resolver import (ENV_TABLE, active_table, explain, last_decision,
                       pick, reason, set_table_path, table_path)
from .table import (FORMAT, KNOWN_ALGORITHMS, VERSION, Row, TuningTable,
                    TuningTableError, band_edges, fingerprint, new_doc,
                    validate)

__all__ = [
    "ENV_TABLE", "active_table", "explain", "last_decision", "pick",
    "reason", "set_table_path", "table_path",
    "FORMAT", "KNOWN_ALGORITHMS", "VERSION", "Row", "TuningTable",
    "TuningTableError", "band_edges", "fingerprint", "new_doc", "validate",
]
