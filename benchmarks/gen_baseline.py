"""Regenerate BASELINE.md mechanically from measured JSON (VERDICT round 1
next-step #4: "BASELINE.md tables carry 1MB+ rows with a stated generation
command, no hand-edited numbers").

Runs the contract measurement matrix (BASELINE.json:7-10) on this host,
appends every row to ``benchmarks/results/baseline.jsonl``, and rewrites
``BASELINE.md`` from those rows.  Usage::

    python -m benchmarks.gen_baseline            # full matrix (minutes)
    python -m benchmarks.gen_baseline --quick    # tiny sizes (CI smoke)

The matrix (sizes capped by this box's RAM/1-core reality; the 1GB tail of
the BASELINE.json:10 sweep and the ★ north-star need the v5e-8 slice —
bench.py runs those automatically when ≥2 real chips appear):

* ring-vs-halving allreduce crossover: local 4 ranks, 4KB→64MB (:10)
* bcast/reduce tree: local 4 ranks, 4KB→1MB (:8)
* allgather + alltoall OSU sweep: local 4 ranks, 4KB→16MB (:9)
* the same allreduce/allgather/alltoall sweeps on the TPU backend
  (8-device CPU sim on this box; real ICI when chips are attached)
* pingpong latency 1KB + windowed bw 16MB: socket AND shm rank processes
  under the launcher (:7 + the native-transport comparison)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "benchmarks", "results")
JSONL = os.path.join(RESULTS, "baseline.jsonl")


def _env_cpu(ndev: int = 8) -> dict:
    # bench.py owns the force-CPU recipe (site-hook scrubbing etc.) —
    # one copy, shared
    sys.path.insert(0, REPO)
    import bench

    return bench._cpu_env(ndev)


def _run_rows(cmd: List[str], env: dict, label: str,
              timeout: float = 1800.0) -> List[Dict]:
    """Run a subprocess that prints JSON-line rows; collect them."""
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=timeout)
    if proc.returncode != 0:
        return [{"error": proc.stderr[-400:], "cmd": label}]
    return [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith("{")]


def _osu(args: List[str], env: dict) -> List[Dict]:
    return _run_rows([sys.executable, "-m", "benchmarks.osu", *args], env,
                     " ".join(args))


def _launched_osu(backend: str, args: List[str], env: dict) -> List[Dict]:
    """osu under the launcher (2 real rank processes); rank 0 prints rows."""
    return _run_rows(
        [sys.executable, "-m", "mpi_tpu.launcher", "-n", "2",
         "--backend", backend, "benchmarks/osu.py", "--backend", "socket",
         *args],
        env, backend + ": " + " ".join(args))


def measure(quick: bool) -> List[Dict]:
    env = _env_cpu()
    big = "4KB:64KB:4" if quick else "4KB:64MB:4"
    mid = "4KB:64KB:4" if quick else "4KB:16MB:4"
    small = "4KB,64KB" if quick else "4KB,1MB"
    it = ["--iters", "5", "--warmup", "2"] if quick else \
         ["--iters", "15", "--warmup", "3"]
    rows: List[Dict] = []
    t0 = time.time()

    def log(msg):
        print(f"[gen_baseline +{time.time()-t0:6.0f}s] {msg}", flush=True)

    log("allreduce crossover (local, 4 ranks)")
    rows += _osu(["--bench", "allreduce", "--backend", "local", "-n", "4",
                  "--sizes", big,
                  "--algorithms", "ring,recursive_halving", *it], env)
    log("bcast/reduce tree (local, 4 ranks)")
    rows += _osu(["--bench", "bcast", "--backend", "local", "-n", "4",
                  "--sizes", small, "--algorithms", "tree", *it], env)
    rows += _osu(["--bench", "reduce", "--backend", "local", "-n", "4",
                  "--sizes", small, "--algorithms", "tree", *it], env)
    log("allgather/alltoall sweep (local, 4 ranks)")
    rows += _osu(["--bench", "allgather", "--backend", "local", "-n", "4",
                  "--sizes", mid, "--algorithms", "ring,doubling", *it], env)
    rows += _osu(["--bench", "alltoall", "--backend", "local", "-n", "4",
                  "--sizes", mid, "--algorithms", "pairwise", *it], env)
    log("TPU-backend sweeps (8-dev mesh)")
    rows += _osu(["--bench", "allreduce", "--backend", "tpu", "-n", "8",
                  "--sizes", big,
                  "--algorithms", "ring,recursive_halving,fused", *it], env)
    rows += _osu(["--bench", "allgather", "--backend", "tpu", "-n", "8",
                  "--sizes", mid, "--algorithms", "ring,fused", *it], env)
    rows += _osu(["--bench", "alltoall", "--backend", "tpu", "-n", "8",
                  "--sizes", mid, "--algorithms", "pairwise,fused", *it], env)
    for backend in ("socket", "shm"):
        log(f"pingpong + windowed bw ({backend} rank processes)")
        rows += _launched_osu(backend, ["--bench", "latency",
                                        "--sizes", "32,1KB", *it], env)
        rows += _launched_osu(backend, ["--bench", "bw",
                                        "--sizes", "1KB,16MB" if not quick
                                        else "1KB", *it], env)
    if not quick:
        # the BASELINE.json:10 contract names 1MB–1GB: the 128MB–1GB tail
        # is where ring vs halving vs fused diverge hardest (VERDICT r2
        # next-step #5).  Few iters — each row is minutes on one core.
        tail_it = ["--iters", "3", "--warmup", "1"]
        log("contract tail: 256MB+1GB (local 4 ranks) — slow")
        rows += _osu(["--bench", "allreduce", "--backend", "local",
                      "-n", "4", "--sizes", "256MB,1GB",
                      "--algorithms", "ring,recursive_halving", *tail_it],
                     env)
        log("contract tail: 256MB (tpu-sim 8 dev) — slow")
        rows += _osu(["--bench", "allreduce", "--backend", "tpu", "-n", "8",
                      "--sizes", "256MB",
                      "--algorithms", "ring,recursive_halving,fused",
                      *tail_it], env)
    return rows


# --------------------------------------------------------------------------
# BASELINE.md rendering
# --------------------------------------------------------------------------


def _fmt_bytes(b: int) -> str:
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if b >= div:
            v = b / div
            return f"{v:.0f}{unit}" if v == int(v) else f"{v:.1f}{unit}"
    return f"{b}B"


def _table(rows: List[Dict], cols: List[str], headers: List[str]) -> List[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    for r in rows:
        cells = []
        for c in cols:
            v = r.get(c, "")
            if c == "bytes" and v != "":
                v = _fmt_bytes(v)
            elif isinstance(v, float):
                v = f"{v:.3g}" if v < 1000 else f"{v:.0f}"
            cells.append(str(v))
        out.append("| " + " | ".join(cells) + " |")
    return out


def render(rows: List[Dict], quick: bool) -> str:
    ok = [r for r in rows if "error" not in r and "skipped" not in r]

    def pick(**kv):
        return [r for r in ok
                if all(r.get(k) == v for k, v in kv.items())]

    lines = [
        "# BASELINE",
        "",
        "**The reference (`mgawino/mpi`) has no published benchmark numbers.**",
        "`BASELINE.json:13` is `\"published\": {}`; the reference checkout at",
        "`/root/reference/` is an empty directory (zero files — SURVEY.md §0),",
        "so every number below is **measured on this repo's own backends** —",
        "the socket backend is the source-compatible reimplementation of the",
        "reference's architecture, the TPU backend is the deliverable.",
        "",
        "**Generated mechanically** — do not hand-edit numbers.  Command:",
        "",
        "```", f"python -m benchmarks.gen_baseline{' --quick' if quick else ''}",
        "```",
        "",
        f"Raw rows: `benchmarks/results/baseline.jsonl` "
        f"({len(ok)} measurements).  Conventions (BASELINE.json:2): busbw =",
        "NCCL-tests convention (allreduce `bytes×2(P−1)/P÷t`); p50 = median;",
        "collective p50 = slowest rank's median.  Hardware: this box (1 CPU",
        "core — multi-rank CPU numbers are contended upper bounds; TPU rows",
        "say which platform they actually ran on).",
        "",
        "## Ring vs recursive-halving allreduce (BASELINE.json:10)",
        "",
        "### local backend (4 rank threads)", "",
    ]
    lines += _table(pick(bench="allreduce", backend="local"),
                    ["bytes", "algorithm", "p50_us", "busbw_gbps"],
                    ["size", "algorithm", "p50 (µs)", "busbw (GB/s)"])
    lines += ["", "### tpu backend (8-device mesh)", ""]
    lines += _table(pick(bench="allreduce", backend="tpu"),
                    ["platform", "bytes", "algorithm", "p50_us", "busbw_gbps"],
                    ["platform", "size", "algorithm", "p50 (µs)", "busbw (GB/s)"])
    lines += ["", "## Tree bcast / reduce (BASELINE.json:8)", ""]
    lines += _table(pick(bench="bcast") + pick(bench="reduce"),
                    ["bench", "backend", "bytes", "algorithm", "p50_us"],
                    ["bench", "backend", "size", "algorithm", "p50 (µs)"])
    lines += ["", "## Allgather / alltoall OSU sweep (BASELINE.json:9)", ""]
    lines += _table(pick(bench="allgather") + pick(bench="alltoall"),
                    ["bench", "backend", "bytes", "algorithm", "p50_us",
                     "busbw_gbps"],
                    ["bench", "backend", "size", "algorithm", "p50 (µs)",
                     "busbw (GB/s)"])
    lines += ["", "## Point-to-point: latency + windowed bandwidth "
              "(BASELINE.json:7; socket vs native shm)", ""]
    lines += _table([r for r in ok if r["bench"] in ("latency", "bw")],
                    ["bench", "backend", "bytes", "window", "p50_us",
                     "bw_gbps"],
                    ["bench", "backend", "size", "window", "p50 (µs)",
                     "bw (GB/s)"])
    lines += [
        "",
        "## North-star (BASELINE.json:5)",
        "",
        "★ ring-allreduce on 256MB f32 ≥80% of ICI line-rate on v5e-8: needs",
        "≥2 real chips.  `bench.py` runs the measurement (NORTHSTAR_PROG +",
        "ICI line-rate probe) automatically when they are visible AND runs",
        "the identical program on an 8-device CPU sim at 8MB on every",
        "invocation (`BENCH_DETAILS.json` → `northstar_sim_8dev`), so the",
        "measurement path is rehearsed before hardware day.",
        "",
        "Errors/skips during generation:",
        "",
    ]
    errs = [r for r in rows if "error" in r or "skipped" in r]
    if errs:
        for r in errs[:20]:
            lines.append(f"- `{json.dumps(r)[:200]}`")
    else:
        lines.append("- none")
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="tiny sizes / few iters (CI smoke)")
    ap.add_argument("--render-only", action="store_true",
                    help="rewrite BASELINE.md from the existing jsonl")
    args = ap.parse_args(argv)

    os.makedirs(RESULTS, exist_ok=True)
    if args.render_only:
        with open(JSONL) as f:
            rows = [json.loads(line) for line in f if line.strip()]
    else:
        rows = measure(args.quick)
        with open(JSONL, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
    with open(os.path.join(REPO, "BASELINE.md"), "w") as f:
        f.write(render(rows, args.quick))
    print(f"BASELINE.md regenerated from {len(rows)} rows")
    return 0


if __name__ == "__main__":
    sys.exit(main())
