// Native data plane for the same-host transport: a single-producer /
// single-consumer byte ring in POSIX shared memory, one ring per directed
// rank pair, plus a per-receiver futex "doorbell" so a reader waiting on
// many rings sleeps in the kernel and is woken by any sender — the same
// wake-on-arrival behavior a blocking socket recv() gets, without the TCP
// stack on the data path (mpi_tpu/transport/shm.py owns the protocol).
//
// Design notes:
// * head/tail are monotonic byte counters (never wrapped), so fullness is
//   simply head - tail; positions wrap with % capacity.
// * Both write and read STREAM in available-space chunks, so frames larger
//   than the ring capacity flow through without deadlock (the Python layer
//   prefixes each frame with its length and reads exactly that many bytes).
// * Empty/full waits are futexes on 32-bit seq words in the shared header
//   (wseq bumps per produced chunk, rseq per consumed chunk); wakes are
//   issued only when the waiter counter says someone is sleeping, so the
//   uncontended path stays syscall-free.
// * The consumer creates the ring (unlinking any stale segment first) and
//   flips `magic` last with release ordering; producers open-and-wait.
//
// Built by mpi_tpu/native/build.py:  g++ -O3 -std=c++17 -shared -fPIC

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <linux/futex.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x4D505452;  // "MPTR"
constexpr size_t kDataOffset = 64;       // keep data cache-line separated

struct Header {
  std::atomic<uint64_t> head;   // total bytes written
  std::atomic<uint64_t> tail;   // total bytes read
  uint64_t capacity;
  std::atomic<uint32_t> magic;
  std::atomic<uint32_t> wseq;     // bumped per produced chunk
  std::atomic<uint32_t> rseq;     // bumped per consumed chunk
  std::atomic<uint32_t> wwait;    // sleepers on wseq (the reader)
  std::atomic<uint32_t> rwait;    // sleepers on rseq (the writer)
};
static_assert(sizeof(Header) <= kDataOffset, "header must fit the pad");

struct Ring {
  Header* h;
  uint8_t* data;
  size_t maplen;
  int fd;
};

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

long sys_futex(std::atomic<uint32_t>* uaddr, int op, uint32_t val,
               const struct timespec* timeout) {
  return syscall(SYS_futex, (uint32_t*)uaddr, op, val, timeout, nullptr, 0);
}

// Sleep until *seq != seen or the step timeout elapses.  `waiters` is the
// matching sleeper counter.  Returns false iff `deadline` (absolute,
// negative = never) has passed.  The wait covers the full remaining time
// (capped at 250ms as a lost-wakeup safety net) so an idle waiter costs
// ~4 syscalls/s, not a poll loop.
bool futex_wait_step(std::atomic<uint32_t>* seq, uint32_t seen,
                     std::atomic<uint32_t>* waiters, double deadline) {
  double remain = deadline < 0 ? 0.25 : deadline - now_s();
  if (remain <= 0) return false;
  if (remain > 0.25) remain = 0.25;
  struct timespec ts;
  ts.tv_sec = (time_t)remain;
  ts.tv_nsec = (long)((remain - ts.tv_sec) * 1e9);
  waiters->fetch_add(1, std::memory_order_seq_cst);
  if (seq->load(std::memory_order_seq_cst) == seen) {
    sys_futex(seq, FUTEX_WAIT, seen, &ts);
  }
  waiters->fetch_sub(1, std::memory_order_seq_cst);
  return deadline < 0 || now_s() < deadline;
}

void bump_and_wake(std::atomic<uint32_t>* seq, std::atomic<uint32_t>* waiters) {
  seq->fetch_add(1, std::memory_order_seq_cst);
  if (waiters->load(std::memory_order_seq_cst) != 0) {
    sys_futex(seq, FUTEX_WAKE, INT32_MAX, nullptr);
  }
}

// Plain polling step for the setup paths (segment not yet mapped).
bool poll_step(int& spins, double deadline) {
  if (spins < 64) {
    ++spins;
    sched_yield();
  } else {
    struct timespec ts = {0, 200 * 1000};  // 200us
    nanosleep(&ts, nullptr);
  }
  return deadline < 0 || now_s() < deadline;
}

}  // namespace

extern "C" {

// Consumer side: (re)create the segment and initialize the header.
void* shmring_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run, if any
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t maplen = kDataOffset + capacity;
  if (ftruncate(fd, (off_t)maplen) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, maplen, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Ring* r = new Ring;
  r->h = (Header*)mem;
  r->data = (uint8_t*)mem + kDataOffset;
  r->maplen = maplen;
  r->fd = fd;
  memset(mem, 0, sizeof(Header));
  r->h->capacity = capacity;
  r->h->magic.store(kMagic, std::memory_order_release);
  return r;
}

// Producer side: open an existing segment, waiting up to timeout_s for the
// consumer to create and initialize it.
void* shmring_open(const char* name, double timeout_s) {
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  int fd = -1;
  int spins = 0;
  for (;;) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd >= 0) break;
    if (errno != ENOENT || !poll_step(spins, deadline)) return nullptr;
  }
  struct stat st;  // wait for the consumer's ftruncate
  spins = 0;
  for (;;) {
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    if ((size_t)st.st_size > kDataOffset) break;
    if (!poll_step(spins, deadline)) {
      close(fd);
      return nullptr;
    }
  }
  size_t maplen = (size_t)st.st_size;
  void* mem = mmap(nullptr, maplen, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = (Header*)mem;
  spins = 0;
  while (h->magic.load(std::memory_order_acquire) != kMagic) {
    if (!poll_step(spins, deadline)) {
      munmap(mem, maplen);
      close(fd);
      return nullptr;
    }
  }
  Ring* r = new Ring;
  r->h = h;
  r->data = (uint8_t*)mem + kDataOffset;
  r->maplen = maplen;
  r->fd = fd;
  return r;
}

uint64_t shmring_avail(void* ring) {
  Ring* r = (Ring*)ring;
  return r->h->head.load(std::memory_order_acquire) -
         r->h->tail.load(std::memory_order_relaxed);
}

// Stream n bytes into the ring; 0 on success, -1 on timeout.
int shmring_write(void* ring, const void* buf, uint64_t n, double timeout_s) {
  Ring* r = (Ring*)ring;
  Header* h = r->h;
  const uint8_t* src = (const uint8_t*)buf;
  const uint64_t cap = h->capacity;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  uint64_t done = 0;
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  while (done < n) {
    uint32_t seen = h->rseq.load(std::memory_order_seq_cst);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    uint64_t space = cap - (head - tail);
    if (space == 0) {
      if (!futex_wait_step(&h->rseq, seen, &h->rwait, deadline)) return -1;
      continue;
    }
    uint64_t pos = head % cap;
    uint64_t chunk = n - done;
    if (chunk > space) chunk = space;
    if (chunk > cap - pos) chunk = cap - pos;  // contiguous run
    memcpy(r->data + pos, src + done, chunk);
    done += chunk;
    head += chunk;
    h->head.store(head, std::memory_order_release);
    bump_and_wake(&h->wseq, &h->wwait);
  }
  return 0;
}

// Stream exactly n bytes out of the ring; 0 on success, -1 on timeout.
int shmring_read(void* ring, void* buf, uint64_t n, double timeout_s) {
  Ring* r = (Ring*)ring;
  Header* h = r->h;
  uint8_t* dst = (uint8_t*)buf;
  const uint64_t cap = h->capacity;
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint64_t done = 0;
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  while (done < n) {
    uint32_t seen = h->wseq.load(std::memory_order_seq_cst);
    uint64_t head = h->head.load(std::memory_order_acquire);
    uint64_t avail = head - tail;
    if (avail == 0) {
      if (!futex_wait_step(&h->wseq, seen, &h->wwait, deadline)) return -1;
      continue;
    }
    uint64_t pos = tail % cap;
    uint64_t chunk = n - done;
    if (chunk > avail) chunk = avail;
    if (chunk > cap - pos) chunk = cap - pos;
    memcpy(dst + done, r->data + pos, chunk);
    done += chunk;
    tail += chunk;
    h->tail.store(tail, std::memory_order_release);
    bump_and_wake(&h->rseq, &h->rwait);
  }
  return 0;
}

// Read UP TO n bytes (at least 1 unless timeout): returns the count, 0 on
// timeout with nothing consumed.  The resumable half of shmring_read —
// Python loops it in short slices so a dead peer or a teardown request is
// noticed between slices instead of after one long in-C block, and large
// frames can stream straight into their final buffer at an offset.
int64_t shmring_read_some(void* ring, void* buf, uint64_t n, double timeout_s) {
  Ring* r = (Ring*)ring;
  Header* h = r->h;
  uint8_t* dst = (uint8_t*)buf;
  const uint64_t cap = h->capacity;
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  for (;;) {
    uint32_t seen = h->wseq.load(std::memory_order_seq_cst);
    uint64_t head = h->head.load(std::memory_order_acquire);
    uint64_t avail = head - tail;
    if (avail == 0) {
      if (!futex_wait_step(&h->wseq, seen, &h->wwait, deadline)) return 0;
      continue;
    }
    uint64_t chunk = n < avail ? n : avail;
    uint64_t pos = tail % cap;
    uint64_t run = cap - pos;
    if (chunk <= run) {
      memcpy(dst, r->data + pos, chunk);
    } else {  // wraps: two runs, one call
      memcpy(dst, r->data + pos, run);
      memcpy(dst + run, r->data, chunk - run);
    }
    tail += chunk;
    h->tail.store(tail, std::memory_order_release);
    bump_and_wake(&h->rseq, &h->rwait);
    return (int64_t)chunk;
  }
}

// Write UP TO n bytes: returns the count, 0 on timeout with nothing
// committed.  Resumable half of shmring_write (same rationale as
// shmring_read_some).
int64_t shmring_write_some(void* ring, const void* buf, uint64_t n,
                           double timeout_s) {
  Ring* r = (Ring*)ring;
  Header* h = r->h;
  const uint8_t* src = (const uint8_t*)buf;
  const uint64_t cap = h->capacity;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  for (;;) {
    uint32_t seen = h->rseq.load(std::memory_order_seq_cst);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    uint64_t space = cap - (head - tail);
    if (space == 0) {
      if (!futex_wait_step(&h->rseq, seen, &h->rwait, deadline)) return 0;
      continue;
    }
    uint64_t chunk = n < space ? n : space;
    uint64_t pos = head % cap;
    uint64_t run = cap - pos;
    if (chunk <= run) {
      memcpy(r->data + pos, src, chunk);
    } else {
      memcpy(r->data + pos, src, run);
      memcpy(r->data, src + run, chunk - run);
    }
    head += chunk;
    h->head.store(head, std::memory_order_release);
    bump_and_wake(&h->wseq, &h->wwait);
    return (int64_t)chunk;
  }
}

void shmring_close(void* ring) {
  Ring* r = (Ring*)ring;
  munmap((void*)r->h, r->maplen);
  close(r->fd);
  delete r;
}

int shmring_unlink(const char* name) { return shm_unlink(name); }

// ---- doorbell: one futex seq per receiving rank ---------------------------
// Senders ring it after delivering a complete frame into any of the
// receiver's rings; the receiver's reader thread sleeps here when all its
// rings are empty.  Layout: [magic][seq][waiters].

struct Doorbell {
  std::atomic<uint32_t> magic;
  std::atomic<uint32_t> seq;
  std::atomic<uint32_t> waiters;
};

void* shmdb_create(const char* name) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, sizeof(Doorbell)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, sizeof(Doorbell), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Doorbell* d = (Doorbell*)mem;
  d->seq.store(0, std::memory_order_relaxed);
  d->waiters.store(0, std::memory_order_relaxed);
  d->magic.store(kMagic, std::memory_order_release);
  return d;
}

void* shmdb_open(const char* name, double timeout_s) {
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  int fd = -1;
  int spins = 0;
  for (;;) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd >= 0) break;
    if (errno != ENOENT || !poll_step(spins, deadline)) return nullptr;
  }
  struct stat st;
  spins = 0;
  for (;;) {
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    if ((size_t)st.st_size >= sizeof(Doorbell)) break;
    if (!poll_step(spins, deadline)) {
      close(fd);
      return nullptr;
    }
  }
  void* mem = mmap(nullptr, sizeof(Doorbell), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Doorbell* d = (Doorbell*)mem;
  spins = 0;
  while (d->magic.load(std::memory_order_acquire) != kMagic) {
    if (!poll_step(spins, deadline)) {
      munmap(mem, sizeof(Doorbell));
      return nullptr;
    }
  }
  return d;
}

uint32_t shmdb_read(void* db) {
  return ((Doorbell*)db)->seq.load(std::memory_order_seq_cst);
}

void shmdb_ring(void* db) {
  Doorbell* d = (Doorbell*)db;
  bump_and_wake(&d->seq, &d->waiters);
}

// Sleep until seq != seen (or timeout); returns the current seq.
uint32_t shmdb_wait(void* db, uint32_t seen, double timeout_s) {
  Doorbell* d = (Doorbell*)db;
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  while (d->seq.load(std::memory_order_seq_cst) == seen) {
    if (!futex_wait_step(&d->seq, seen, &d->waiters, deadline)) break;
  }
  return d->seq.load(std::memory_order_seq_cst);
}

void shmdb_close(void* db) { munmap(db, sizeof(Doorbell)); }

int shmdb_unlink(const char* name) { return shm_unlink(name); }

// ---- collective arena (coll/sm) -------------------------------------------
// One POSIX segment per shm-backed communicator: a 64-byte native header
// (magic handshake, like the ring), then the Python layer's layout — P
// per-rank flag LINES (64 bytes each: [u32 seq][u32 waiters], cache-line
// separated so two ranks' posts never share a line) followed by P data
// slots ranks load/store directly.  The flag ops below are the whole
// synchronization vocabulary: a monotone per-rank sequence counter is the
// generalized sense-reversing barrier (sense = counter parity, and the
// monotone spelling needs no reset phase), posted with release semantics
// AFTER the data stores and awaited with acquire semantics BEFORE the
// data loads.  Waits spin briefly (arena peers are co-located, so the
// expected wait is sub-microsecond) and then sleep on a futex in the
// flag line itself; Python loops the wait in short slices so the ULFM
// detector can convert a dead peer into ProcFailedError.

struct ArenaHeader {
  std::atomic<uint32_t> magic;
};

struct ArenaMap {
  void* mem;
  size_t maplen;
};

void* shmarena_create(const char* name, uint64_t nbytes) {
  shm_unlink(name);  // stale segment from a crashed run, if any
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t maplen = kDataOffset + nbytes;
  if (ftruncate(fd, (off_t)maplen) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, maplen, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  memset(mem, 0, kDataOffset);  // flags/slots start zeroed lazily (fresh file)
  ArenaMap* a = new ArenaMap{mem, maplen};
  ((ArenaHeader*)mem)->magic.store(kMagic, std::memory_order_release);
  return a;
}

void* shmarena_open(const char* name, double timeout_s) {
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  int fd = -1;
  int spins = 0;
  for (;;) {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd >= 0) break;
    if (errno != ENOENT || !poll_step(spins, deadline)) return nullptr;
  }
  struct stat st;  // wait for the creator's ftruncate
  spins = 0;
  for (;;) {
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    if ((size_t)st.st_size > kDataOffset) break;
    if (!poll_step(spins, deadline)) {
      close(fd);
      return nullptr;
    }
  }
  size_t maplen = (size_t)st.st_size;
  void* mem = mmap(nullptr, maplen, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  ArenaHeader* h = (ArenaHeader*)mem;
  spins = 0;
  while (h->magic.load(std::memory_order_acquire) != kMagic) {
    if (!poll_step(spins, deadline)) {
      munmap(mem, maplen);
      return nullptr;
    }
  }
  return new ArenaMap{mem, maplen};
}

// usable base address / byte count (past the native header)
uint64_t shmarena_addr(void* a) {
  return (uint64_t)((uint8_t*)((ArenaMap*)a)->mem + kDataOffset);
}

uint64_t shmarena_size(void* a) {
  return (uint64_t)(((ArenaMap*)a)->maplen - kDataOffset);
}

void shmarena_close(void* a) {
  ArenaMap* m = (ArenaMap*)a;
  munmap(m->mem, m->maplen);
  delete m;
}

int shmarena_unlink(const char* name) { return shm_unlink(name); }

// flag line: [u32 seq][u32 waiters] at line_addr (64-byte separated by the
// Python layout).  seq comparisons are wrap-safe (signed difference), so
// 2^31 barriers fit between any two ranks' progress — unreachable skew.

uint32_t shmflag_read(uint64_t line_addr) {
  return ((std::atomic<uint32_t>*)line_addr)->load(std::memory_order_seq_cst);
}

void shmflag_post(uint64_t line_addr, uint32_t value) {
  std::atomic<uint32_t>* seq = (std::atomic<uint32_t>*)line_addr;
  std::atomic<uint32_t>* waiters = seq + 1;
  seq->store(value, std::memory_order_seq_cst);
  if (waiters->load(std::memory_order_seq_cst) != 0) {
    sys_futex(seq, FUTEX_WAKE, INT32_MAX, nullptr);
  }
}

// Wait until seq >= target (wrap-safe) or timeout; returns the current
// value either way.  Short yield-spin first: the common case is a peer a
// few instructions behind, and on an oversubscribed box the yield lets it
// run; the futex nap handles the long tail without burning the core.
uint32_t shmflag_wait_ge(uint64_t line_addr, uint32_t target,
                         double timeout_s) {
  std::atomic<uint32_t>* seq = (std::atomic<uint32_t>*)line_addr;
  std::atomic<uint32_t>* waiters = seq + 1;
  double deadline = timeout_s < 0 ? -1.0 : now_s() + timeout_s;
  int spins = 0;
  for (;;) {
    uint32_t cur = seq->load(std::memory_order_seq_cst);
    if ((int32_t)(cur - target) >= 0) return cur;
    if (spins < 64) {
      ++spins;
      sched_yield();
      continue;
    }
    if (!futex_wait_step(seq, cur, waiters, deadline)) {
      return seq->load(std::memory_order_seq_cst);
    }
  }
}

}  // extern "C"
