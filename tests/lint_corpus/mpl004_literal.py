"""Seeded bug: collective on a comm revoked earlier in the same scope,
with no error handling in sight."""


def recover(comm, x):
    comm.revoke()
    comm.allreduce(x)
