"""Hand-rolled ring allreduce as a Pallas TPU kernel (RDMA over ICI).

SURVEY.md §7 Milestone 3 anticipated this: "possibly a Pallas DMA ring if
XLA's ppermute chaining leaves bandwidth on the table".  This kernel is that
option, exposed as ``allreduce(..., algorithm='pallas_ring')``:

* the buffer lives in HBM as P chunks; the classic 2(P-1)-step ring runs
  INSIDE one kernel: reduce-scatter (P-1 inter-chip RDMAs + tiled VMEM adds)
  then allgather (P-1 RDMAs written directly into the symmetric output
  buffer on the neighbor);
* per-step chunk transfers are chip-to-chip `make_async_remote_copy` DMAs —
  no per-step kernel launches, no XLA-inserted copies between steps;
* accumulation stages HBM→VMEM in `tile_rows`×128 tiles (VMEM is ~16 MB;
  chunks can be tens of MB for the 256 MB north-star buffer);
* a neighbor barrier (barrier semaphore) closes each step so the
  double-buffered landing zone can never be overrun on hardware.  The
  barrier is skipped under the Pallas interpreter (remote semaphore signal
  is unimplemented there); interpreter runs validate the data path on the
  virtual CPU mesh.

Restrictions (v1, diagnosed): float32, SUM, the full (ungrouped) axis.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = 8  # float32 min tile height


def _kernel(x_hbm, out_hbm, comm_hbm, a_vmem, b_vmem,
            copy_sem_a, copy_sem_b, send_sem, recv_sem, *,
            axis_name: str, size: int, rows: int, tile_rows: int,
            use_barrier: bool):
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, size)
    left = lax.rem(my - 1 + size, size)

    # working copy: out <- x (HBM -> HBM local DMA)
    init = pltpu.make_async_copy(x_hbm, out_hbm, copy_sem_a)
    init.start()
    init.wait()

    def neighbor_barrier():
        if not use_barrier:
            return
        bar = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bar, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(bar, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(bar, 2)

    # entry sync: the first RDMA must not land on a chip whose kernel hasn't
    # started (execution skew would let it write scratch not yet owned)
    neighbor_barrier()

    # ---- phase 1: reduce-scatter ring --------------------------------
    for s in range(size - 1):
        slot = s % 2
        si = lax.rem(my - s + size, size)       # chunk I forward
        ri = lax.rem(my - s - 1 + size, size)   # chunk I accumulate
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_hbm.at[pl.ds(si * rows, rows)],
            dst_ref=comm_hbm.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()  # my data left AND my left neighbor's chunk landed
        for t in range(rows // tile_rows):
            row0 = ri * rows + t * tile_rows
            cp_a = pltpu.make_async_copy(
                out_hbm.at[pl.ds(row0, tile_rows)], a_vmem, copy_sem_a)
            cp_b = pltpu.make_async_copy(
                comm_hbm.at[slot, pl.ds(t * tile_rows, tile_rows)],
                b_vmem, copy_sem_b)
            cp_a.start()
            cp_b.start()
            cp_a.wait()
            cp_b.wait()
            a_vmem[:] = a_vmem[:] + b_vmem[:]
            cp_out = pltpu.make_async_copy(
                a_vmem, out_hbm.at[pl.ds(row0, tile_rows)], copy_sem_a)
            cp_out.start()
            cp_out.wait()
        neighbor_barrier()

    # ---- phase 2: allgather ring -------------------------------------
    # rank r now owns fully-reduced chunk (r+1) % P; forward it around.
    # The receiving neighbor expects exactly the chunk index we send, so the
    # RDMA writes straight into the symmetric slice of their output buffer.
    for s in range(size - 1):
        slot = s % 2
        ci = lax.rem(my + 1 - s + size, size)
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_hbm.at[pl.ds(ci * rows, rows)],
            dst_ref=out_hbm.at[pl.ds(ci * rows, rows)],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        neighbor_barrier()


def _geometry(n: int, size: int, tile_rows: int) -> Tuple[int, int]:
    """rows per chunk (multiple of tile_rows) and padded element count."""
    per_chunk = -(-n // size)
    rows = -(-per_chunk // _LANES)
    rows = -(-rows // tile_rows) * tile_rows
    return rows, size * rows * _LANES


def pallas_ring_allreduce(x: jnp.ndarray, axis_name: str, size: int,
                          tile_rows: int = 256,
                          interpret: bool = False) -> jnp.ndarray:
    """SUM-allreduce ``x`` (float32) over ``axis_name`` with the in-kernel
    RDMA ring.  Call inside shard_map over a mesh with that axis."""
    if x.dtype != jnp.float32:
        raise NotImplementedError(
            f"pallas_ring allreduce is float32-only for now, got {x.dtype}")
    if tile_rows % _SUBLANES or tile_rows < _SUBLANES:
        raise ValueError(
            f"tile_rows must be a positive multiple of {_SUBLANES} "
            f"(float32 sublane tile), got {tile_rows}")
    if size == 1:
        return x
    shape = x.shape
    n = int(np.prod(shape)) if shape else 1
    rows, padded = _geometry(n, size, tile_rows)
    flat = x.reshape(-1)
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    grid_in = flat.reshape(size * rows, _LANES)

    # vma typing may be active even when the payload is replicated; probe
    # with axis_index, which is varying exactly when check_vma is on
    try:
        vma_on = bool(jax.typeof(lax.axis_index(axis_name)).vma)
    except AttributeError:
        vma_on = False
    if vma_on:
        raise ValueError(
            "pallas_ring needs check_vma=False on the enclosing shard_map "
            "(Pallas kernels don't participate in varying-axes inference): "
            "run_spmd(..., check_vma=False) or jax.shard_map(..., "
            "check_vma=False)")

    kern = functools.partial(
        _kernel, axis_name=axis_name, size=size, rows=rows,
        tile_rows=tile_rows, use_barrier=not interpret)
    compiler_params = None if interpret else pltpu.CompilerParams(
        collective_id=13, has_side_effects=True)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((size * rows, _LANES), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pl.ANY((2, rows, _LANES), jnp.float32),      # RDMA landing zone
            pltpu.VMEM((tile_rows, _LANES), jnp.float32),
            pltpu.VMEM((tile_rows, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(grid_in)
    return out.reshape(-1)[:n].reshape(shape)
