"""mpi_tpu.telemetry — the observability layer (ISSUE 13 tentpole).

Three pieces, MPI_T + Score-P/Chrome-trace shaped:

* the per-rank **flight recorder** (:mod:`.recorder`): a fixed-size
  ring of timestamped binary events instrumented at the existing seams
  — collective begin/end with resolved algorithm + bytes
  (communicator.py), socket frame send/recv + link reconnect/replay/
  heal (transport/socket.py + resilience.py), nonblocking-collective
  state-machine transitions (nbc.py), arena hit/fallback (coll_sm.py),
  lease lifecycle (serve.py), FT suspicion + membership epoch bumps
  (ft.py / membership.py).  Exported as Chrome-trace/Perfetto JSON;
  ``tools/tracecat.py`` merges the per-rank files onto one aligned
  timeline.
* **histogram pvars** (mpi_tpu/mpit.py ``hist_record`` /
  ``pvar_hist_read`` / ``hist_quantile``): log-bucketed latency
  distributions — collective latency, lease acquire, link heal —
  beside the scalar counters.
* the **serve metrics endpoint** (:mod:`.metrics` + serve.py
  ``--metrics-port``): ``client.stats()`` grew worlds/s + lease
  p50/p99 + aggregated worker pvars, and the server optionally serves
  the same document as Prometheus text over HTTP.

Enablement mirrors verify/progress exactly: ``MPI_TPU_TRACE=1`` (init),
``run_local(..., trace=True)``, ``launcher --trace-dir``, or
:func:`enable` directly.  Off = the module singleton :data:`REC` is
``None`` and every instrumented seam is one attribute test — zero
events (``trace_events`` pvar), unchanged wire accounting
(``bench.py --verify-overhead --trace`` asserts it).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from .recorder import Recorder, WAIT_MIN_NS

__all__ = [
    "Recorder", "REC", "WAIT_MIN_NS", "enable", "disable", "enabled",
    "recorder", "export_chrome", "env_enabled", "env_trace_dir",
]

# THE off-mode gate: every instrumentation seam in the library reads
# this module attribute and returns when it is None.  Process-wide on
# purpose (like the mpit counters): local-backend rank threads share
# one recorder and are told apart by tid; process worlds each own one.
REC: Optional[Recorder] = None

_LAST: Optional[Recorder] = None  # kept after disable() for export
_lock = threading.Lock()


def enable(rank: Optional[int] = None, capacity: int = 0,
           trace_dir: Optional[str] = None) -> Recorder:
    """Start (or return) the process flight recorder.  Idempotent like
    ft/verify enable: re-enabling an active recorder returns it
    unchanged (rank/capacity of the first call win)."""
    global REC, _LAST
    with _lock:
        if REC is None:
            REC = _LAST = Recorder(capacity=capacity, rank=rank,
                                   trace_dir=trace_dir)
        return REC


def disable() -> Optional[Recorder]:
    """Stop recording.  The recorder object (and its events) survives
    as :func:`recorder`'s return value so a just-finished traced run
    can still be exported/inspected — only NEW events stop."""
    global REC
    with _lock:
        rec, REC = REC, None
        return rec


def enabled() -> bool:
    return REC is not None


def recorder() -> Optional[Recorder]:
    """The active recorder, or the most recently disabled one."""
    return REC if REC is not None else _LAST


def export_chrome(path: str, rec: Optional[Recorder] = None) -> str:
    """Export the active (or last) recorder as Chrome-trace JSON."""
    rec = rec or recorder()
    if rec is None:
        raise RuntimeError("no recorder: enable tracing first "
                           "(MPI_TPU_TRACE=1 / run_local(trace=True) / "
                           "telemetry.enable())")
    return rec.export_chrome(path)


# -- environment enablement (init() / worker processes) ----------------------


def env_enabled() -> bool:
    return os.environ.get("MPI_TPU_TRACE", "") not in ("", "0")


def env_trace_dir() -> Optional[str]:
    return os.environ.get("MPI_TPU_TRACE_DIR") or None


def enable_from_env(rank: Optional[int] = None) -> Optional[Recorder]:
    """init()-time enablement: ``MPI_TPU_TRACE=1`` starts the recorder,
    ``MPI_TPU_TRACE_DIR`` (launcher ``--trace-dir``) makes it export at
    process exit — atexit rather than finalize-only, because chaos/
    bench rank programs routinely ``sys.exit`` without a finalize and
    their trace is exactly the one worth keeping."""
    if not env_enabled():
        return None
    rec = enable(rank=rank, trace_dir=env_trace_dir())
    if rec.trace_dir:
        import atexit

        atexit.register(rec.export_to_dir)
    return rec
