#!/usr/bin/env python
"""Compressed-collectives bench (ISSUE 8): the 64MB gradient-traffic leg.

Runs 2-rank worlds on BOTH host transports (socket, shm) and measures
``allreduce`` and ``reduce_scatter`` at 64MB f32 under the classic ring
versus the compressed wire formats (bf16, scaled-int8, top-k), recording
per-call p50 AND the byte-plane pvars — so the artifact carries the
acceptance evidence directly: ``bytes_raw_sent`` halves (exactly, same
spans at 2 bytes/element) at bf16 with zero pickled array bytes, and
``bytes_compressed_saved`` prices every format.

Artifacts (oversubscribed-stamped like every bench JSON):

* ``benchmarks/results/compress_pre.json``  — the uncompressed ring rows
  (the contemporary baseline: byte-identical code path to a pre-ISSUE-8
  checkout's ring);
* ``benchmarks/results/compress_post.json`` — the compressed rows plus
  the derived per-transport byte ratios.

Usage::

    python bench.py --compress            # full 64MB run, writes artifacts
    python bench.py --compress --quick    # tier-1 smoke (256KB, stdout only)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NBYTES = 64 << 20
QUICK_NBYTES = 256 << 10
TRANSPORTS = ("socket", "shm")
# (bench, algorithm) legs; ring rows are the 'pre' side of the artifact
LEGS = (
    ("allreduce", "ring"),
    ("allreduce", "compressed:bf16"),
    ("allreduce", "compressed:int8"),
    ("allreduce", "compressed:topk"),
    ("reduce_scatter", "ring"),
    ("reduce_scatter", "compressed:bf16"),
)

RANK_PROG = """
import json, os, statistics, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit

comm = mpi_tpu.init()
nbytes = int(os.environ["CB_NBYTES"])
iters = int(os.environ["CB_ITERS"])
n = nbytes // 4
rng = np.random.RandomState(1234 + comm.rank)
x = rng.randn(n).astype(np.float32)
p = comm.size
blocks = x.reshape(p, n // p)
legs = json.loads(os.environ["CB_LEGS"])
pv = ("bytes_raw_sent", "bytes_pickled_sent", "bytes_compressed_saved")
rows = []
for bench, algo in legs:
    call = ((lambda: comm.allreduce(x, algorithm=algo))
            if bench == "allreduce"
            else (lambda: comm.reduce_scatter(blocks, algorithm=algo)))
    call()  # warmup
    base = {{k: mpit.pvar_read(k) for k in pv}}
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        call()
        ts.append(time.perf_counter() - t0)
    d = {{k: mpit.pvar_read(k) - base[k] for k in pv}}
    rows.append({{
        "bench": bench, "algorithm": algo, "backend": os.environ["CB_BACKEND"],
        "nbytes": nbytes, "nranks": p, "iters": iters,
        "p50_us": statistics.median(ts) * 1e6,
        # this rank's wire-plane bytes PER CALL (2-rank symmetric: the
        # global volume is p x this)
        "raw_bytes_per_call": d["bytes_raw_sent"] // iters,
        "pickled_bytes_per_call": d["bytes_pickled_sent"] // iters,
        "saved_bytes_per_call": d["bytes_compressed_saved"] // iters,
    }})
if comm.rank == 0:
    with open(os.environ["CB_OUT"], "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\\n")
mpi_tpu.finalize()
"""


def _transport_rows(backend: str, nbytes: int, iters: int) -> List[Dict]:
    from mpi_tpu.launcher import launch

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "rows.jsonl")
        prog = os.path.join(td, "prog.py")
        with open(prog, "w") as f:
            f.write(RANK_PROG.format(repo=REPO))
        rc = launch(2, [prog], timeout=1800.0, backend=backend,
                    env_extra={"CB_OUT": out, "CB_BACKEND": backend,
                               "CB_NBYTES": str(nbytes),
                               "CB_ITERS": str(iters),
                               "CB_LEGS": json.dumps(LEGS)})
        if rc != 0:
            raise RuntimeError(f"{backend} compress bench exited {rc}")
        with open(out) as f:
            return [json.loads(line) for line in f if line.strip()]


def run(quick: bool = False) -> Dict:
    nbytes = QUICK_NBYTES if quick else NBYTES
    iters = 1 if quick else 3
    rows: List[Dict] = []
    for backend in TRANSPORTS:
        rows += _transport_rows(backend, nbytes, iters)
    ratios = {}
    for backend in TRANSPORTS:
        by_algo = {r["algorithm"]: r for r in rows
                   if r["backend"] == backend and r["bench"] == "allreduce"}
        base = by_algo["ring"]["raw_bytes_per_call"]
        ratios[backend] = {
            a: round(by_algo[a]["raw_bytes_per_call"] / base, 4)
            for a in by_algo if a != "ring" and base}
    return {"quick": quick, "nbytes": nbytes, "nranks": 2, "rows": rows,
            "allreduce_raw_byte_ratio_vs_ring": ratios,
            "oversubscribed": 3 > (os.cpu_count() or 1)}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out-pre")
    ap.add_argument("--out-post")
    args = ap.parse_args(argv)
    result = run(quick=args.quick)
    pre_rows = [r for r in result["rows"] if r["algorithm"] == "ring"]
    post_rows = [r for r in result["rows"] if r["algorithm"] != "ring"]
    shared = {k: v for k, v in result.items() if k != "rows"}
    pre = {**shared, "label": "pre", "rows": pre_rows}
    pre.pop("allreduce_raw_byte_ratio_vs_ring", None)
    post = {**shared, "label": "post", "rows": post_rows}
    if args.quick or not (args.out_pre and args.out_post):
        print(json.dumps({**post, "pre_rows": pre_rows}, indent=2))
        return 0
    for path, doc in ((args.out_pre, pre), (args.out_post, post)):
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
