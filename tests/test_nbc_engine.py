"""Engine-owned nonblocking collectives (ISSUE 12 tentpole —
mpi_tpu/nbc.py): schedule state machines advanced by the async progress
engine instead of one ``_ThreadRequest`` thread per call, plus the MPI-4
persistent collectives built on the same compiled-schedule object.

Five contracts:

* zero per-call threads — 1000 concurrent iallreduce on one
  ``progress=thread`` world complete correctly with
  ``nbc_threads_spawned == 0`` (pvar-asserted) while
  ``nbc_state_machines`` counts every call;
* parity — the whole i-collective family produces bit-identical results
  on the state-machine path (``progress=thread``) and the thread path
  (``progress=none``), across ops, dtypes, roots, and group sizes, with
  the size gate (``nbc_sm_max_bytes``) and the ``nbc_mode=thread`` cvar
  both restoring today's one-thread-per-call semantics exactly;
* persistent collectives — ``allreduce_init`` & co. hoist compile/
  resolve/verify out of the loop: ``start()`` re-reads the bound buffer
  (MPI buffer-reuse idiom), geometry changes raise, re-fire works on
  engine AND engine-less worlds, and ``mpi4.persistent_collective``
  routes the plannable kinds here;
* diagnostics — a polled state machine publishes its EXACT pending
  OR-set on the deadlock board (the per-Waitany-call tightening, ISSUE
  12 satellite), and a rank killed mid-persistent-round surfaces
  ProcFailedError on the survivors within the detection bound;
* lifecycle — the per-world fold pool dies with the progress engine
  (no thread accumulation across worlds).
"""

import threading
import time

import numpy as np
import pytest

from mpi_tpu import mpi4, mpit, nbc, ops
from mpi_tpu.errors import ProcFailedError
from mpi_tpu.transport.faulty import FaultyTransport
from mpi_tpu.transport.local import KILLED, run_local

DETECT_S = 1.0


def _deltas(prog, nranks, names, **kw):
    base = {n: mpit.pvar_read(n) for n in names}
    res = run_local(prog, nranks, **kw)
    return res, {n: mpit.pvar_read(n) - base[n] for n in names}


# -- zero per-call thread creation -------------------------------------------


def test_thousand_concurrent_iallreduce_zero_threads():
    """The headline acceptance: 1000 in-flight iallreduces on one
    engine world are 1000 state machines, not 1000 OS threads."""

    def prog(comm):
        reqs = [comm.iallreduce(np.full(4, float(i + comm.rank)))
                for i in range(1000)]
        for i, req in enumerate(reqs):
            out = req.wait()
            exp = comm.size * i + sum(range(comm.size))
            assert out[0] == exp, (i, out[0], exp)
        return True

    res, d = _deltas(prog, 2, ("nbc_threads_spawned", "nbc_state_machines"),
                     progress="thread", timeout=240)
    assert res == [True, True]
    assert d["nbc_threads_spawned"] == 0, d
    assert d["nbc_state_machines"] == 2 * 1000, d


def test_fold_pool_dies_with_the_engine():
    """The fixed-cost pool is per-world machinery: after run_local tears
    the world down no nbc fold worker survives."""

    def prog(comm):
        comm.iallreduce(np.ones(8)).wait()
        return True

    assert run_local(prog, 2, progress="thread") == [True, True]
    deadline = time.time() + 5.0  # stop() handshake: workers drain a sentinel
    while time.time() < deadline:
        alive = [t.name for t in threading.enumerate()
                 if t.name.startswith("mpi-tpu-nbc-fold")]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, alive


# -- parity: state machines vs the thread path -------------------------------


def _family(comm):
    p = comm.size
    out = {}
    out["allreduce"] = comm.iallreduce(np.arange(8.0) + comm.rank).wait()
    out["allreduce_max"] = float(
        comm.iallreduce(np.float64(comm.rank), op=ops.MAX).wait())
    out["allreduce_i32"] = comm.iallreduce(
        np.arange(4, dtype=np.int32) + comm.rank, op=ops.PROD).wait()
    r = comm.ireduce(np.full(3, comm.rank + 1.0), root=p - 1).wait()
    out["reduce"] = None if r is None else r.tolist()
    out["bcast"] = comm.ibcast({"k": 1} if comm.rank == 0 else None).wait()
    out["barrier"] = comm.ibarrier().wait()
    out["gather"] = comm.igather(comm.rank * 3, root=0).wait()
    out["scatter"] = comm.iscatter(
        [f"s{i}" for i in range(p)] if comm.rank == 1 else None,
        root=1).wait()
    out["allgather"] = comm.iallgather(np.full(2, float(comm.rank))).wait()
    out["alltoall"] = comm.ialltoall(
        [np.full(2, float(comm.rank * p + d)) for d in range(p)]).wait()
    return out


def _canon(res):
    return [[(k, np.asarray(v).tolist() if v is not None else None)
             for k, v in r.items()] for r in res]


@pytest.mark.parametrize("p", [2, 3, 4])
def test_family_parity_engine_vs_thread(p):
    sm_res, d_sm = _deltas(_family, p, ("nbc_threads_spawned",),
                           progress="thread")
    th_res, d_th = _deltas(_family, p, ("nbc_threads_spawned",),
                           progress="none")
    assert _canon(sm_res) == _canon(th_res)
    assert d_sm["nbc_threads_spawned"] == 0, d_sm
    assert d_th["nbc_threads_spawned"] > 0  # engine-less worlds: threads


def test_nbc_mode_thread_cvar_is_the_escape_hatch():
    """nbc_mode=thread under a live engine keeps today's semantics —
    every i-collective spawns its thread, no machine is compiled."""
    old = mpit.cvar_read("nbc_mode")
    mpit.cvar_write("nbc_mode", "thread")
    try:
        res, d = _deltas(_family, 3,
                         ("nbc_threads_spawned", "nbc_state_machines"),
                         progress="thread")
    finally:
        mpit.cvar_write("nbc_mode", old)
    assert d["nbc_state_machines"] == 0, d
    assert d["nbc_threads_spawned"] > 0
    assert _canon(res) == _canon(run_local(_family, 3, progress="none"))


def test_size_gate_keeps_bandwidth_payloads_on_segmented_threads():
    """Payloads above nbc_sm_max_bytes ride the threaded SEGMENTED
    algorithms (the bandwidth regime); 0 removes the cap.  The
    ialltoall spelling gates on the largest BLOCK (one value-plan
    frame) — the overlap bench's large symmetric exchange must keep
    the caller-financed windowed blocking path."""
    big = 1 << 18  # 2MB float64 > the 1MB default ceiling

    def prog(comm):
        blocks = [np.ones(big) for _ in range(comm.size)]  # 2MB frames
        a2a = comm.ialltoall(blocks).wait()
        assert float(np.asarray(a2a[0])[0]) == 1.0
        return comm.iallreduce(np.ones(big)).wait()[0]

    res, d = _deltas(prog, 2, ("nbc_threads_spawned", "nbc_state_machines"),
                     progress="thread")
    assert res == [2.0, 2.0]
    assert d["nbc_state_machines"] == 0, d
    assert d["nbc_threads_spawned"] == 4  # ialltoall + iallreduce per rank
    old = mpit.cvar_read("nbc_sm_max_bytes")
    mpit.cvar_write("nbc_sm_max_bytes", 0)
    try:
        res, d = _deltas(prog, 2,
                         ("nbc_threads_spawned", "nbc_state_machines"),
                         progress="thread")
    finally:
        mpit.cvar_write("nbc_sm_max_bytes", old)
    assert res == [2.0, 2.0]
    assert d["nbc_state_machines"] == 4, d
    assert d["nbc_threads_spawned"] == 0


# -- MPI-4 persistent collectives --------------------------------------------


@pytest.mark.parametrize("p", [2, 3])
def test_persistent_allreduce_parity_across_ops_dtypes(p):
    """One handle per (op, dtype), three re-fires each, against the
    blocking oracle — on the engine path."""

    def prog(comm):
        outs = []
        for op in (ops.SUM, ops.MAX, ops.PROD):
            for dt in (np.float64, np.float32, np.int64):
                x = np.arange(1, 5, dtype=dt)
                h = comm.allreduce_init(x, op=op)
                for rd in range(3):
                    x[:] = np.arange(1, 5, dtype=dt) * (rd + comm.rank + 1)
                    got = h.start().wait()
                    ref = comm.allreduce(x, op=op)
                    assert got.dtype == ref.dtype, (op, dt)
                    np.testing.assert_array_equal(got, ref)
                    outs.append(got.sum())
        return [float(o) for o in outs]

    res, d = _deltas(prog, p, ("nbc_threads_spawned", "persistent_starts"),
                     progress="thread", timeout=240)
    assert all(r == res[0] for r in res)
    assert d["nbc_threads_spawned"] == 0, d
    assert d["persistent_starts"] == p * 9 * 3


def test_persistent_family_refire_and_engineless_fallback():
    """bcast/alltoall/reduce_scatter handles re-fire with refilled
    buffers on BOTH progress modes (engine-less start() falls back to
    one thread per round on the same hoisted context)."""

    def prog(comm):
        p = comm.size
        rounds = []
        payload = {"r": None}
        hb = comm.bcast_init(payload if comm.rank == 0 else None, root=0)
        blocks = np.zeros((p, 2))
        hrs = comm.reduce_scatter_init(blocks)
        objs = [None] * p
        ha = comm.alltoall_init(objs)
        for rd in range(3):
            payload["r"] = rd          # bcast re-reads bound CONTENT
            blocks[:] = rd + comm.rank
            objs[:] = [(comm.rank, d, rd) for d in range(p)]
            b = hb.start().wait()
            rs = hrs.start().wait()
            a = ha.start().wait()
            assert b == {"r": rd}
            np.testing.assert_array_equal(
                rs, np.full(2, sum(rd + r for r in range(p))))
            assert a == [(s, comm.rank, rd) for s in range(p)]
            rounds.append(rd)
        return rounds

    for mode in ("thread", "none"):
        assert run_local(prog, 3, progress=mode) == [[0, 1, 2]] * 3


def test_persistent_size1_refire_reads_bound_buffer():
    """The MPI buffer-reuse idiom holds on size-1 worlds too: start()
    must re-read the bound buffer, not hand back the init-time
    snapshot the compiled 'done' build captured."""

    def prog(comm):
        x = np.ones(4)
        h = comm.allreduce_init(x)
        a = h.start().wait()
        x[:] = 5.0
        b = h.start().wait()
        return float(np.asarray(a)[0]), float(np.asarray(b)[0])

    for mode in ("thread", "none"):
        assert run_local(prog, 1, progress=mode) == [(1.0, 5.0)]


def test_persistent_ragged_reduce_scatter_init_falls_back():
    """Ragged per-destination blocks (supported by the blocking
    generic reduce_scatter) must not crash persistent init's geometry
    probe — the handle falls back to thread rounds and re-fires."""

    def prog(comm):
        blocks = [np.full(2 + d, float(comm.rank + 1))
                  for d in range(comm.size)]
        h = comm.reduce_scatter_init(blocks)
        outs = []
        for rd in range(2):
            for d in range(comm.size):
                blocks[d][:] = comm.rank + 1 + rd
            outs.append(h.start().wait().tolist())
        return outs

    res = run_local(prog, 2, progress="thread")
    assert res[0] == [[3.0, 3.0], [5.0, 5.0]]
    assert res[1] == [[3.0] * 3, [5.0] * 3]


def test_persistent_geometry_bound_and_start_discipline():
    def prog(comm):
        x = np.ones(4)
        h = comm.allreduce_init(x)
        with pytest.raises(RuntimeError, match="before start"):
            h.wait()
        h.start()
        h.wait()
        h2 = comm.allreduce_init(np.ones(4))
        h2._args = (np.ones(5),)  # rebind: geometry changed since init
        with pytest.raises(ValueError, match="geometry"):
            h2.start()
        # leave h2's group coherent: peers compiled for n=4
        h2._args = (np.ones(4),)
        h2.start().wait()
        return True

    assert run_local(prog, 2, progress="thread") == [True, True]


def test_mpi4_persistent_collective_routes_plannable_kinds():
    """The generic MPI_*_init surface returns the engine-owned handle
    for allreduce/bcast/alltoall/reduce_scatter and the thread-backed
    generic one for everything else — same start/wait discipline."""

    def prog(comm):
        h = mpi4.persistent_collective(comm, "allreduce", np.ones(4))
        assert isinstance(h, nbc.PersistentColl), type(h)
        v = h.start().wait()
        hr = mpi4.persistent_collective(comm, "reduce", np.ones(2), ops.SUM)
        assert isinstance(hr, mpi4.PersistentCollective), type(hr)
        r = hr.start().wait()
        hbar = mpi4.persistent_collective(comm, "barrier")
        hbar.start().wait()
        return float(v[0]), None if r is None else float(r[0])

    res = run_local(prog, 2, progress="thread")
    assert res == [(2.0, 2.0), (2.0, None)], res  # reduce root=0


# -- diagnostics -------------------------------------------------------------


@pytest.fixture
def _fast_stall():
    old = mpit.cvar_read("verify_stall_timeout_s")
    mpit.cvar_write("verify_stall_timeout_s", 1.0)
    yield
    mpit.cvar_write("verify_stall_timeout_s", old)


def test_sm_poll_publishes_exact_per_call_or_set(_fast_stall):
    """ISSUE 12 satellite (verifier residual (d)): the engine publishes
    the polled state machine's OWN pending sources — rank 0's ring
    allreduce pends only on its left neighbor (rank 2), and the entry
    pins exactly that, NOT the union with the unrelated tracked irecv
    from rank 1 (which the old union-over-all-requests would include —
    and without the req hand-off the untracked SM internals would
    publish nothing at all)."""

    def prog(comm):
        h = comm.allreduce_init(np.ones(4), algorithm="ring")
        if comm.rank == 0:
            stray = comm.irecv(1, tag=9)  # tracked, never polled
            h.start()
            entry, deadline = None, time.time() + 8.0
            while time.time() < deadline:
                done, _ = h.test()
                if done:
                    break
                e = comm._verify.world.board.read_all().get(comm.rank)
                if e and e.get("kind") == "waitany-poll":
                    entry = dict(e)
                    break
                time.sleep(0.002)
            out = h.wait()
            return entry, float(out[0]), stray.wait()
        time.sleep(2.5)  # long enough for rank 0's episode to publish
        if comm.rank == 1:
            comm.send(b"stray", 0, tag=9)
        return float(h.start().wait()[0])

    res = run_local(prog, 3, verify=True, progress="thread", timeout=60)
    entry, val, stray = res[0]
    assert (val, stray) == (3.0, b"stray")
    assert res[1] == res[2] == 3.0
    assert entry is not None, "stalled SM poll never published"
    assert entry["targets"] == [2], entry      # exact OR-set, not {1, 2}
    assert entry["mode"] == "OR"
    assert entry["coll"] == "iallreduce"
    assert "state machine" in entry["site"]


def test_ft_kill_mid_persistent_diagnosed_in_bound():
    """Rank 1 dies mid-round of a persistent allreduce: the survivors'
    wait() converts the detector hit into ProcFailedError naming the
    corpse within the usual multiple of the detection bound."""
    old = {k: mpit.cvar_read(k) for k in ("fault_detect_timeout_s",
                                          "fault_heartbeat_interval_s")}
    mpit.cvar_write("fault_detect_timeout_s", DETECT_S)
    mpit.cvar_write("fault_heartbeat_interval_s", 0.05)
    try:
        def kill_rank1(inner):
            return (FaultyTransport(inner, kill_after_n=2)
                    if inner.world_rank == 1 else inner)

        def prog(comm):
            h = comm.allreduce_init(np.ones(1 << 10), algorithm="ring")
            h.start()  # rank 1 dies inside this round's sends
            if comm.rank == 1:
                return h.wait()  # re-raises its own KilledRankError
            t0 = time.monotonic()
            with pytest.raises(ProcFailedError) as ei:
                h.wait()
            assert time.monotonic() - t0 < 6 * DETECT_S
            assert 1 in ei.value.failed
            return "diagnosed"

        out = run_local(prog, 3, transport_wrapper=kill_rank1,
                        fault_tolerance=True, progress="thread",
                        timeout=60)
        assert out[0] == out[2] == "diagnosed"
        assert out[1] is KILLED
    finally:
        for k, v in old.items():
            mpit.cvar_write(k, v)
