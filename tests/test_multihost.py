"""Multi-host (DCN) path: REAL multi-process jax runtime on CPU (gloo
cross-process collectives — the code path a TPU pod's DCN traffic takes,
minus the wires).  The launcher spawns one process per simulated host;
TpuCommunicator spans them through the global mesh unchanged — the plugin
seam absorbing scale-out is the point (SURVEY.md §5: distributed
communication backend)."""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np

    from mpi_tpu.tpu import multihost

    assert multihost.auto_init(), "launcher env missing"

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from mpi_tpu import ops
    from mpi_tpu.tpu import TpuCommunicator

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()          # global
    assert len(jax.local_devices()) == 2                    # per host

    mesh = multihost.global_mesh()
    comm = TpuCommunicator("world", mesh)
    PW = 4

    def prog():
        r = comm.rank
        total = comm.allreduce(r, algorithm="fused")            # DCN psum
        ring = comm.allreduce(jnp.zeros(8) + r, algorithm="ring")  # ppermute ring
        nbr = comm.shift((r * 10.0)[None], offset=1, wrap=True)  # cross-host hop
        sub = comm.split_by(lambda i: i % 2)                    # even/odd split
        subtotal = sub.allreduce(r, algorithm="fused")
        return total, ring.sum(), nbr, subtotal[None]

    f = jax.jit(jax.shard_map(
        prog, mesh=mesh, in_specs=(),
        out_specs=(P(), P(), P("world"), P("world")),
        check_vma=False))
    total, ringsum, nbr, subtotal = f()
    # replicated outputs are locally addressable on every host
    assert int(total) == 0 + 1 + 2 + 3, total
    assert float(ringsum) == 8 * (0 + 1 + 2 + 3), ringsum
    # sharded outputs: check this host's shards only
    me = jax.process_index()
    for s in nbr.addressable_shards:
        got = float(np.asarray(s.data)[0])
        expect = ((s.index[0].start - 1) % PW) * 10.0
        assert got == expect, (got, expect)
    for s in subtotal.addressable_shards:
        rank = s.index[0].start
        assert int(np.asarray(s.data)[0]) == (2 if rank % 2 == 0 else 4)
    print("MULTIHOST-OK proc=" + str(me), flush=True)
""")


@pytest.mark.slow
def test_multihost_two_sim_hosts(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO))
    from mpi_tpu.tpu.multihost import launch_sim_hosts

    rc = launch_sim_hosts(2, [str(script)], devices_per_host=2, timeout=240.0)
    assert rc == 0


@pytest.mark.slow
def test_multihost_cli(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "mpi_tpu.tpu.multihost", "-n", "2",
         "--devices-per-host", "2", "--timeout", "240", str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]


HYBRID_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np

    from mpi_tpu.tpu import multihost

    assert multihost.auto_init(), "launcher env missing"

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from mpi_tpu.tpu import TpuCommunicator

    assert jax.process_count() == 2
    # the REAL hybrid branch: dcn_shape=(2, 1) spans the two hosts on the
    # 'dcn' axis, ici_shape=(1, 2) packs each host's devices on 'ici'
    mesh = multihost.hybrid_mesh((1, 2), (2, 1), ("dcn", "ici"))
    assert mesh.shape["dcn"] == 2 and mesh.shape["ici"] == 2, mesh.shape

    # device placement: along 'ici' one host (same process), along 'dcn'
    # different hosts — the layout contract that keeps heavy collectives
    # off the data-center network
    devs = mesh.devices
    for d in range(2):
        assert devs[d, 0].process_index == devs[d, 1].process_index, devs
    for i in range(2):
        assert devs[0, i].process_index != devs[1, i].process_index, devs

    # one collective OVER THE DCN AXIS (gloo cross-process reduce)
    comm_dcn = TpuCommunicator("dcn", mesh)
    f = jax.jit(jax.shard_map(
        lambda x: comm_dcn.allreduce(x, algorithm="fused"),
        mesh=mesh, in_specs=P("dcn", "ici"), out_specs=P(None, "ici")))
    x = np.arange(4.0, dtype=np.float32).reshape(2, 2)
    out = np.asarray(jax.device_get(f(jnp.asarray(x))))
    np.testing.assert_allclose(out, x.sum(0, keepdims=True))
    print("HYBRID-OK proc=" + str(jax.process_index()), flush=True)
""")


@pytest.mark.slow
def test_hybrid_mesh_real_dcn_branch(tmp_path):
    """The create_hybrid_device_mesh branch (dcn_shape != all-ones) on a
    real 2-process runtime: placement asserted + a collective over the
    DCN axis (VERDICT r2 next-step #6b — previously dead code)."""
    script = tmp_path / "hybrid_worker.py"
    script.write_text(HYBRID_WORKER.format(repo=REPO))
    from mpi_tpu.tpu.multihost import launch_sim_hosts

    rc = launch_sim_hosts(2, [str(script)], devices_per_host=2, timeout=240.0)
    assert rc == 0


def test_hybrid_mesh_single_granule():
    """hybrid_mesh with an all-ones dcn shape falls back to a plain mesh
    (host-side shape logic; no multi-process runtime needed)."""
    import jax

    from mpi_tpu.tpu.multihost import hybrid_mesh

    if len(jax.devices()) < 1:
        pytest.skip("no devices")
    mesh = hybrid_mesh((1, len(jax.devices())), (1, 1), ("dp", "mp"))
    assert mesh.shape["dp"] == 1
    assert mesh.shape["mp"] == len(jax.devices())
    with pytest.raises(ValueError, match="one entry per mesh axis"):
        hybrid_mesh((2,), (1, 1), ("a", "b"))
