"""run_spmd — execute a portable MPI program as one SPMD trace over a Mesh.

SURVEY.md §7 Milestone 1: the TPU-native translation of "N processes
exchanging messages" is ``jax.shard_map`` over a device mesh; the launcher's
job (L0) is done by the TPU runtime.  ``run_spmd(fn, *args)`` gives ``fn`` a
TpuCommunicator and runs it on every device of the mesh; per-rank results
come back stacked on a leading axis (rank order), mirroring
``run_local``'s list-of-results.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .communicator import TpuCommunicator


def _install_shard_map_compat() -> None:
    """``jax.shard_map`` across the jax version drift this repo tolerates
    (see _brand_sharded_slice for the same policy on pvary/pcast):
    pre-0.5 jax ships shard_map only as ``jax.experimental.shard_map``,
    whose equivalent of ``check_vma`` is still called ``check_rep``.
    Install a translating alias at the top-level spelling so every call
    site — library, benchmarks, tools, tests — runs unchanged on either
    vintage.  No-op when jax already has the real thing."""
    if getattr(jax, "shard_map", None) is not None:
        return
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)

    jax.shard_map = shard_map


# True when the ANY-memory-space alias below was applied (pre-0.5 jax)
_PALLAS_MEMSPACE_SHIMMED = False


def _install_pallas_compat() -> None:
    """Pallas memory-space drift on the jax-0.4.37 vintage (ROADMAP
    "remaining jax 0.4.37 drift"): modern kernels write ``pl.ANY((shape),
    dtype)`` for scratch shapes, but 0.4.37's ``pl.ANY`` is the plain
    (non-callable) pallas-core ``MemorySpace`` enum — only the mosaic
    ``TPUMemorySpace`` members are callable there.  Alias ``pl.ANY`` to
    ``TPUMemorySpace.ANY`` (accepted by BlockSpec AND callable for
    scratch), and alias the renamed ``pltpu.CompilerParams`` to the
    vintage ``TPUCompilerParams``, dropping kwargs it doesn't know
    (``has_side_effects`` — only consulted on real-TPU lowering, where
    the collective_id it DOES understand carries the semantics).  Same
    policy as the shard_map alias above: patch the top-level spelling
    once so every call site runs unchanged on either vintage."""
    global _PALLAS_MEMSPACE_SHIMMED
    try:
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pragma: no cover - no pallas on this build
        return
    any_space = getattr(pl, "ANY", None)
    if any_space is not None and not callable(any_space):
        legacy_spaces = getattr(pltpu, "TPUMemorySpace", None)
        tpu_any = getattr(legacy_spaces, "ANY", None)
        if callable(tpu_any):
            pl.ANY = tpu_any
            # Consulted by tests: a handful of tiled-interpret attention
            # programs hit a fatal XLA-CPU CHECK (array.h reshape of a
            # 0-element buffer) on this vintage once the shim lets them
            # build — they must SKIP rather than abort the whole suite.
            _PALLAS_MEMSPACE_SHIMMED = True
    if getattr(pltpu, "CompilerParams", None) is None:
        legacy = getattr(pltpu, "TPUCompilerParams", None)
        if legacy is not None:
            import dataclasses

            known = {f.name for f in dataclasses.fields(legacy)}

            def compiler_params(**kw):
                return legacy(**{k: v for k, v in kw.items() if k in known})

            pltpu.CompilerParams = compiler_params


_install_shard_map_compat()
_install_pallas_compat()


def default_mesh(nranks: Optional[int] = None, axis_name: str = "world") -> Mesh:
    """1-D mesh over the first ``nranks`` local devices (all, if None).

    On a CPU host, virtual devices come from
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (SURVEY.md §4
    item 2 — the standard fake-multi-device fixture)."""
    # Honor JAX_PLATFORMS even on hosts whose site hook force-registers a
    # platform via jax.config (e.g. the axon TPU tunnel), which silently
    # overrides the env var and would hide the virtual CPU devices.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and jax.config.jax_platforms != plat:
        jax.config.update("jax_platforms", plat)
    devs = jax.devices()
    # config.update never raises post-init; detect a silently-ignored
    # platform switch by inspecting what we actually got.  Plugin names
    # that are tunnels to a real platform (axon → tpu) count as applied —
    # warning on them flagged every legitimate real-chip run.
    _ALIASES = {"axon": "tpu"}
    wanted = set(plat.split(",")) if plat else set()
    wanted |= {_ALIASES[p] for p in list(wanted) if p in _ALIASES}
    if plat and devs and devs[0].platform not in wanted:
        import warnings

        warnings.warn(
            f"JAX_PLATFORMS={plat!r} could not be applied (a "
            f"{devs[0].platform!r} backend was already initialized); "
            f"devices stay on the already-initialized platform")
    n = len(devs) if nranks is None else nranks
    if n > len(devs):
        raise ValueError(
            f"requested {n} ranks but only {len(devs)} devices are visible; "
            f"on CPU set XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    return Mesh(np.array(devs[:n]), (axis_name,))


def run_spmd(
    fn: Callable,
    *args: Any,
    nranks: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = "world",
    jit: bool = True,
    check_vma: bool = True,
    **kwargs: Any,
):
    """Run ``fn(comm, *args, **kwargs)`` as one SPMD program.

    ``args`` are replicated to every rank; each rank's return value gets a
    length-1 leading axis and the stacked [nranks, ...] result is returned
    (index it by rank to mirror ``run_local``'s per-rank list).

    ``check_vma=False`` disables shard_map's varying-axes typing.  Every
    algorithm, including ``'pallas_ring'``, now works with the checker ON
    (the kernel declares its result varying; see pallas_ring docstrings) —
    the flag remains for users who want the typing overhead gone."""
    if mesh is None:
        mesh = default_mesh(nranks, axis_name)
    comm = TpuCommunicator(axis_name, mesh)

    def shard_fn(*a):
        res = fn(comm, *a, **kwargs)
        return jax.tree.map(lambda r: jnp.asarray(r)[None], res)

    in_specs = tuple(P() for _ in args)
    f = jax.shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                      out_specs=P(axis_name), check_vma=check_vma)
    if jit:
        f = jax.jit(f)
    return f(*args)
