"""Pipeline parallelism: GPipe-style microbatch streaming over shift().

The PP strategy from the checklist (SURVEY.md §2 strategy table): rank r
holds stage r of an L=P-layer network; microbatches enter at rank 0 and
flow down the pipeline with one non-wrapping ``shift`` per tick (lowered
to a single ``lax.ppermute`` hop on the TPU backend).  The classic GPipe
fill-and-drain schedule: M microbatches complete in M + P − 1 ticks, each
tick being [receive activations | apply my stage | pass along] — a static
schedule, so the whole pipeline traces into one SPMD program.

    python examples/pipeline.py --backend tpu -n 8
"""

import argparse
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np


def _stage(x, w, b):
    return jax.nn.tanh(x @ w + b)


def pipeline_forward(comm, micro_x, w, b):
    """Run M microbatches through a P-stage pipeline.

    micro_x: [M, B, D] — the full input stream (same array on every rank;
    only rank 0 actually feeds it in).  w: [D, D], b: [D] — THIS rank's
    stage parameters.  Returns [M, B, D]: the final outputs, valid on the
    LAST rank (zeros elsewhere — SPMD produces a value on every rank)."""
    P, rank = comm.size, comm.rank
    M, B, D = micro_x.shape
    is_first = rank == 0  # traced bool on the TPU backend
    is_last = rank == P - 1

    carry = jnp.zeros((B, D), micro_x.dtype)  # activation moving through me
    outs = jnp.zeros((M, B, D), micro_x.dtype)
    for tick in range(M + P - 1):
        # feed: rank 0 injects microbatch `tick` (if any) — every other
        # rank takes what arrived from upstream last tick
        feed = micro_x[tick] if tick < M else jnp.zeros((B, D), micro_x.dtype)
        x_in = jnp.where(is_first, feed, carry)
        y = _stage(x_in, w, b)
        # a stage only holds valid data for ticks in [rank, rank + M)
        valid = (tick >= rank) & (tick < rank + M)  # traced bool on TPU
        y = jnp.where(jnp.asarray(valid), y, 0.0)
        # drain: the last stage records its finished microbatch
        mb = tick - (P - 1)
        if 0 <= mb < M:
            outs = outs.at[mb].set(jnp.where(is_last, y, outs[mb]))
        # pass along: one ppermute hop down the pipeline
        carry = comm.shift(y, offset=1, wrap=False, fill=0.0)
    return outs


def pipeline_oracle(micro_x, ws, bs):
    """Serial reference: apply all P stages to each microbatch."""
    out = []
    for m in range(micro_x.shape[0]):
        x = np.asarray(micro_x[m])
        for w, b in zip(ws, bs):
            x = np.asarray(_stage(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)))
        out.append(x)
    return np.stack(out)


def pipeline_program(comm, micro: int = 6, batch: int = 4, d: int = 8):
    root = jax.random.PRNGKey(7)
    micro_x = jax.random.normal(jax.random.fold_in(root, 999),
                                (micro, batch, d), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(root, comm.rank), (d, d),
                          jnp.float32) * 0.5
    b = jax.random.normal(jax.random.fold_in(root, 100 + comm.rank), (d,),
                          jnp.float32) * 0.1
    return pipeline_forward(comm, micro_x, w, b)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None,
                    choices=[None, "socket", "shm", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--micro", type=int, default=6)
    args = ap.parse_args()

    out = mpi_tpu.run(pipeline_program, backend=args.backend,
                      nranks=args.nranks, micro=args.micro)
    # run() returns a per-rank list (local backend) or a stacked
    # [nranks, M, B, D] array (tpu backend) — both want the LAST rank's
    # output — but on process backends (socket/shm) it is already THIS
    # rank's [M, B, D] result
    if isinstance(out, list) or np.ndim(out) == 4:
        last = out[-1]
    else:
        last = out
    o = np.asarray(jax.device_get(last))
    print(f"pipeline OK: outputs {o.shape} on the last stage, "
          f"|out| = {np.abs(o).mean():.4f}")


if __name__ == "__main__":
    main()
