"""Fault-injection transport wrapper (SURVEY.md §5: 'a transport wrapper
that drops/permutes in the CPU simulator').

Wraps any Transport and injects configurable faults on the send path:

* ``drop_every`` — silently drop every k-th message (models a lossy link;
  the receiver's RecvTimeout then surfaces the hang the way a failure
  detector would);
* ``delay_s`` — sleep before delivering (models congestion; exposes
  ordering assumptions that only hold under low latency);
* ``duplicate_every`` — deliver every k-th message twice (models retry
  storms; exposes non-idempotent receive logic).

FIFO order per channel is preserved for non-faulted messages.  Use with
``run_local(..., transport_wrapper=FaultyTransport.wrapper(...))`` and a
recv ``timeout`` to turn silent deadlocks into diagnosable failures.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from .base import Transport


class FaultyTransport(Transport):
    def __init__(self, inner: Transport, drop_every: int = 0,
                 delay_s: float = 0.0, duplicate_every: int = 0) -> None:
        self.inner = inner
        self.world_rank = inner.world_rank
        self.world_size = inner.world_size
        self.mailbox = inner.mailbox
        self.aliases_payloads = inner.aliases_payloads
        # decorate, don't re-tune: collectives through the fault injector
        # must segment exactly like the wrapped data plane
        self.coll_segment_hint = inner.coll_segment_hint
        self.drop_every = drop_every
        self.delay_s = delay_s
        self.duplicate_every = duplicate_every
        self._n = 0
        self._lock = threading.Lock()
        self.dropped = 0
        self.duplicated = 0

    @classmethod
    def wrapper(cls, **kwargs):
        """For run_local's transport_wrapper hook."""
        return lambda inner: cls(inner, **kwargs)

    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        with self._lock:
            self._n += 1
            n = self._n
        if self.drop_every and n % self.drop_every == 0:
            self.dropped += 1
            return
        if self.delay_s:
            time.sleep(self.delay_s)
        self.inner.send(dest, ctx, tag, payload)
        if self.duplicate_every and n % self.duplicate_every == 0:
            self.duplicated += 1
            self.inner.send(dest, ctx, tag, payload)

    def recv(self, source: int, ctx, tag: int, timeout: Optional[float] = None):
        return self.inner.recv(source, ctx, tag, timeout)

    def close(self) -> None:
        self.inner.close()
