#!/usr/bin/env python
"""Static MPI linter CLI (mpi_tpu/verify/lint.py — MPI-Checker style).

Flags, over any .py files or directories:

* MPL001 — rank-conditional collective with no matching call in the
  other branch (divergent collective schedule);
* MPL002 — send-send cycles between literal rank pairs (deadlock under
  synchronous sends);
* MPL003 — literal recv-count < send-count truncation (typed
  MPI_Send/MPI_Recv);
* MPL004 — operations on a revoked comm without an error handler.

Suppress a deliberate pattern with ``# mpilint: ok`` on (or right
above) the flagged line.  Exit code 1 iff findings remain.

Usage::

    python tools/mpilint.py examples/ mpi_tpu/
    python tools/mpilint.py --select MPL001,MPL002 myprog.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_tpu.verify.lint import lint_paths  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+", help=".py files or directories")
    ap.add_argument("--select", default=None,
                    help="comma-separated codes to report (default: all)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the OK line")
    args = ap.parse_args(argv)
    findings = lint_paths(args.paths)
    if args.select:
        keep = {c.strip() for c in args.select.split(",")}
        findings = [f for f in findings if f.code in keep]
    for f in findings:
        print(f.render())
    if findings:
        print(f"mpilint: {len(findings)} finding(s)")
        return 1
    if not args.quiet:
        print("mpilint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
