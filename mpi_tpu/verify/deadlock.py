"""Runtime deadlock detection: publish pending ops on stall, analyze the
cross-rank wait-for graph, raise :class:`~mpi_tpu.errors.DeadlockError`
instead of hanging.

Protocol (driven from the communicator's sliced blocking waits, the same
plumbing the FT detector rides — communicator._sliced_wait):

1. A wait blocked past ``verify_stall_timeout_s`` publishes its
   pending-op entry on the world's Board: who it waits for (world
   ranks), AND/OR semantics (specific source vs ANY_SOURCE / waitany
   sets), the tag, the enclosing collective, the user call site, and a
   progress stamp (ops counter + block id + mailbox delivery count).
2. Every further check reads all peers' entries and runs the pure
   AND-OR analysis (mpi_tpu.checker.find_deadlock) over the blocked +
   exited ranks.
3. A positive result is CONFIRMED before raising: re-read after one
   poll slice and require every implicated entry unchanged (same block
   id, same ops count, same mailbox deliveries) — a rank that made any
   progress in between invalidates the diagnosis and the wait resumes.

The raise happens independently on every deadlocked rank (each sees the
same closed picture), so no rank is left hanging on a peer that
errored out.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from .. import mpit as _mpit
from ..checker import find_deadlock
from ..errors import DeadlockError
from .state import WorldVerify, report_add

# Cadence of full board reads while stalled (every read is P file reads
# on process worlds); the confirm pass sleeps one slice of this.
_CHECK_SLICE_S = 0.25
# A 'blocked' entry not refreshed within this window is treated as
# absent: genuinely stalled ranks republish every check slice, while a
# wait that ENDS retracts its entry promptly (success → note_progress;
# RecvTimeout/ProcFailed/Revoked → clear_published), so only leftovers
# from a rank that died mid-stall ever reach the TTL — the last-resort
# stale-entry guard.  Exited entries never expire (termination is
# forever).
_ENTRY_TTL_S = 2.0


def make_entry(world: WorldVerify, comm, targets_world: Tuple[int, ...],
               mode: str, tag: int, kind: str, coll: Optional[str],
               site: str, block_id: int) -> dict:
    return {
        "state": "blocked",
        "rank": world.rank,
        "ctx": repr(comm._ctx),
        "targets": sorted(targets_world),
        "mode": mode,
        "tag": tag,
        "kind": kind,
        "coll": coll,
        "site": site,
        "block_id": block_id,
        "ops": world.ops,
        "deliveries": getattr(world.t.mailbox, "deliveries", 0),
        "pending": [list(p) for p in world.t.mailbox.pending_summary()[:8]],
    }


def _stamp(entry: dict) -> tuple:
    return (entry.get("state"), entry.get("block_id"), entry.get("ops"),
            entry.get("deliveries"))


def _analyze(tables: Dict[int, dict], size: int):
    waits = {}
    exited = []
    for r, e in tables.items():
        if e.get("state") == "exited":
            exited.append(r)
        elif (e.get("state") == "blocked"
              and e.get("_age_s", 0.0) <= _ENTRY_TTL_S):
            waits[r] = (e.get("mode", "AND"), tuple(e.get("targets", ())))
    return find_deadlock(waits, range(size), exited=exited), tables, exited


def _describe(r: int, e: dict) -> str:
    if e.get("state") == "exited":
        return f"rank {r}: exited (program returned / finalized)"
    coll = f" [in {e['coll']}]" if e.get("coll") else ""
    src = e.get("targets", ())
    src_s = (f"source={src[0]}" if e.get("mode") == "AND" and len(src) == 1
             else f"sources={list(src)} ({e.get('mode')})")
    pend = e.get("pending") or []
    pend_s = (f"; {len(pend)} unmatched message(s) queued "
              f"{[tuple(p) for p in pend[:4]]}" if pend else "")
    return (f"rank {r}: blocked in {e.get('kind', 'recv')}({src_s}, "
            f"tag={e.get('tag')}){coll} at {e.get('site')}{pend_s}")


def check_stalled(world: WorldVerify, comm, targets_world: Tuple[int, ...],
                  mode: str, tag: int, kind: str, coll: Optional[str],
                  site: str, block_id: int) -> None:
    """One stalled-wait tick: (re)publish our pending op, and at the
    check cadence run the wait-for analysis; raises DeadlockError when a
    confirmed cycle/knot includes this rank.  Returning means 'keep
    waiting' — the picture is still open."""
    now = time.monotonic()
    if world.published and now - world._last_check < _CHECK_SLICE_S:
        # the common stalled tick: two comparisons, no entry build, no
        # board traffic — this runs every 50ms slice while blocked
        return
    entry = make_entry(world, comm, targets_world, mode, tag, kind, coll,
                       site, block_id)
    if not world.published:
        world.published = True
        world.board.publish(world.rank, entry)
    if now - world._last_check < _CHECK_SLICE_S:
        return
    world._last_check = now
    # our own entry may have gone stale (ops advanced by sends): refresh
    world.board.publish(world.rank, entry)
    deadlocked, tables, exited = _analyze(world.board.read_all(), world.size)
    if world.rank not in deadlocked:
        return
    # confirm: one slice later the implicated picture must be unchanged
    stamps = {r: _stamp(tables[r]) for r in deadlocked if r in tables}
    for r in exited:
        stamps.setdefault(r, ("exited", None, None, None))
    time.sleep(_CHECK_SLICE_S)
    deadlocked2, tables2, _ = _analyze(world.board.read_all(), world.size)
    if world.rank not in deadlocked2 or set(deadlocked2) != set(deadlocked):
        return
    for r, s in stamps.items():
        if r not in tables2 or _stamp(tables2[r]) != s:
            return  # somebody moved: not a closed picture after all
    ranks = sorted(set(deadlocked) | (set(exited) & {
        t for r in deadlocked for t in tables[r].get("targets", ())}))
    lines = [_describe(r, tables2.get(r, tables.get(r, {"state": "exited"})))
             for r in ranks]
    msg = ("deadlock detected: wait-for cycle/knot across "
           f"{len(ranks)} rank(s):\n  " + "\n  ".join(lines))
    _mpit.count(verify_deadlocks=1)
    report_add(msg)
    raise DeadlockError(msg, ranks=ranks,
                        table={r: tables2.get(r, tables.get(r)) for r in ranks})
