"""Cartesian process topologies — MPI_Cart_create / shift / sub [S].

SURVEY.md §2 component #14 motivates this: the Jacobi stencil's natural
decomposition is an N-D grid of ranks with halo exchanges along each
dimension.  MPI spells that MPI_Cart_create + MPI_Cart_shift + Sendrecv; the
TPU-native spelling of the same shift is ONE ``lax.ppermute`` whose pairs are
a *static* permutation of the mesh axis.  ``CartComm`` therefore reduces
every topology operation to two portable Communicator primitives —
``exchange(obj, pairs, fill)`` (static-pattern p2p) and
``split_by_rank(color_fn, key_fn)`` (host-computable split) — and works
unchanged over the socket, thread, and SPMD backends.

Rank-to-coordinate numbering is row-major (C order), matching MPI's
MPI_Cart_coords convention [S].
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

from .communicator import Communicator

Pair = Tuple[int, int]


def dims_create(nnodes: int, ndims: int) -> List[int]:
    """MPI_Dims_create [S]: factor ``nnodes`` into ``ndims`` balanced,
    non-increasing dimensions."""
    if nnodes <= 0 or ndims <= 0:
        raise ValueError("nnodes and ndims must be positive")
    dims = [1] * ndims
    n = nnodes
    # repeatedly peel the largest prime factor onto the smallest dimension
    factors: List[int] = []
    f = 2
    while f * f <= n:
        while n % f == 0:
            factors.append(f)
            n //= f
        f += 1
    if n > 1:
        factors.append(n)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return sorted(dims, reverse=True)


class CartComm:
    """A communicator with an attached N-D Cartesian topology.

    Wraps (never mutates) an existing communicator whose size must equal
    ``prod(dims)`` — MPI_Cart_create's "allow fewer ranks" escape hatch is
    not portable to SPMD, where every device runs the program.
    """

    def __init__(self, comm: Communicator, dims: Sequence[int],
                 periods: Optional[Sequence[bool]] = None):
        dims = tuple(int(d) for d in dims)
        if any(d <= 0 for d in dims):
            raise ValueError(f"dims must be positive, got {dims}")
        if math.prod(dims) != comm.size:
            raise ValueError(
                f"prod(dims)={math.prod(dims)} must equal comm.size={comm.size}")
        periods = (tuple(bool(p) for p in periods) if periods is not None
                   else (False,) * len(dims))
        if len(periods) != len(dims):
            raise ValueError("periods must have one entry per dimension")
        self.comm = comm
        self.dims = dims
        self.periods = periods
        # row-major strides: stride[i] = prod(dims[i+1:])
        self._strides = tuple(
            math.prod(dims[i + 1:]) for i in range(len(dims)))

    # -- identity ----------------------------------------------------------

    @property
    def rank(self):
        return self.comm.rank

    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def ndims(self) -> int:
        return len(self.dims)

    @property
    def coords(self):
        """This rank's coordinates.  Plain ints on process backends; traced
        scalars on the SPMD backend (pure arithmetic on the traced rank)."""
        r = self.comm.rank
        return tuple((r // s) % d for s, d in zip(self._strides, self.dims))

    # -- pure coordinate math (host-side, any rank) ------------------------

    def coords_of(self, rank: int) -> Tuple[int, ...]:
        """MPI_Cart_coords [S]."""
        if not (0 <= rank < self.size):
            raise ValueError(f"rank {rank} out of range for size {self.size}")
        return tuple((rank // s) % d for s, d in zip(self._strides, self.dims))

    def rank_of(self, coords: Sequence[int]) -> Optional[int]:
        """MPI_Cart_rank [S]: periodic dimensions wrap; out-of-range
        coordinates on non-periodic dimensions return None (MPI_PROC_NULL)."""
        if len(coords) != self.ndims:
            raise ValueError(f"need {self.ndims} coordinates, got {len(coords)}")
        rank = 0
        for c, d, p, s in zip(coords, self.dims, self.periods, self._strides):
            c = int(c)
            if p:
                c %= d
            elif not (0 <= c < d):
                return None
            rank += c * s
        return rank

    def shift(self, dim: int, disp: int = 1) -> Tuple[Optional[int], Optional[int]]:
        """MPI_Cart_shift [S]: (source, dest) ranks for a displacement along
        ``dim`` — the ranks this rank receives-from / sends-to.  None is
        MPI_PROC_NULL.  Needs a concrete integer rank, so on the SPMD backend
        (traced rank) use ``exchange`` / ``shift_perm`` instead."""
        if not (0 <= dim < self.ndims):
            raise ValueError(f"dim {dim} out of range for {self.ndims}-D topology")
        r = self.comm.rank
        if not isinstance(r, int):
            raise TypeError(
                "CartComm.shift needs a concrete rank; inside an SPMD trace "
                "the rank is traced — use cart.exchange(obj, dim, disp) "
                "(the whole-mesh halo exchange) instead")
        me = list(self.coords_of(r))
        me[dim] += disp
        dest = self.rank_of(me)
        me = list(self.coords_of(r))
        me[dim] -= disp
        src = self.rank_of(me)
        return src, dest

    def shift_perm(self, dim: int, disp: int = 1) -> List[Pair]:
        """The full static (src, dst) permutation of a shift along ``dim`` —
        exactly the pairs of the one ``lax.ppermute`` the exchange lowers to."""
        if not (0 <= dim < self.ndims):
            raise ValueError(f"dim {dim} out of range for {self.ndims}-D topology")
        pairs: List[Pair] = []
        for r in range(self.size):
            c = list(self.coords_of(r))
            c[dim] += disp
            dst = self.rank_of(c)
            if dst is not None:
                pairs.append((r, dst))
        return pairs

    # -- communication -----------------------------------------------------

    def exchange(self, obj: Any, dim: int, disp: int = 1, fill: Any = None) -> Any:
        """Halo exchange along one dimension: every rank sends ``obj`` to its
        ``+disp`` neighbor and returns the payload from its ``-disp``
        neighbor; boundary holes (non-periodic) are ``fill``."""
        return self.comm.exchange(obj, self.shift_perm(dim, disp), fill=fill)

    def sendrecv_shift(self, obj: Any, dim: int, disp: int = 1,
                       fill: Any = None) -> Any:
        """Alias of :meth:`exchange` under its MPI name (Cart_shift +
        Sendrecv fused)."""
        return self.exchange(obj, dim, disp, fill)

    # -- neighborhood collectives [S: MPI-3 MPI_Neighbor_*] ----------------

    def neighbors_of(self, rank: int) -> List[Optional[int]]:
        """Neighbor ranks of ``rank`` in MPI's Cartesian neighbor order:
        for each dimension, the −1 neighbor then the +1 neighbor
        (None = MPI_PROC_NULL at a non-periodic boundary)."""
        out: List[Optional[int]] = []
        for dim in range(self.ndims):
            for disp in (-1, +1):
                c = list(self.coords_of(rank))
                c[dim] += disp
                out.append(self.rank_of(c))
        return out

    def neighbor_allgather(self, obj: Any, fill: Any = None) -> List[Any]:
        """MPI_Neighbor_allgather [S]: every rank contributes ``obj``; each
        rank returns ``[from −dim0, from +dim0, from −dim1, ...]`` — one
        entry per neighbor (``fill`` at non-periodic boundaries).  Lowers to
        2·ndims ppermutes on the SPMD backend."""
        out: List[Any] = []
        for dim in range(self.ndims):
            # receive from the −dim neighbor = everyone ships one hop +dim
            out.append(self.exchange(obj, dim, +1, fill=fill))
            out.append(self.exchange(obj, dim, -1, fill=fill))
        return out

    def neighbor_alltoall(self, objs: Sequence[Any], fill: Any = None) -> List[Any]:
        """MPI_Neighbor_alltoall [S]: ``objs`` holds one distinct payload per
        neighbor in neighbor order (−dim0, +dim0, −dim1, ...); returns the
        payloads received from each neighbor, same order.  The item you
        address to your +dim neighbor arrives there as its −dim item."""
        if len(objs) != 2 * self.ndims:
            raise ValueError(
                f"need one payload per neighbor (2·ndims = {2 * self.ndims}), "
                f"got {len(objs)}")
        out: List[Any] = []
        for dim in range(self.ndims):
            # my item for the +dim neighbor rides the +1 shift; what lands
            # here on that shift is the −dim neighbor's +dim item
            out.append(self.exchange(objs[2 * dim + 1], dim, +1, fill=fill))
            out.append(self.exchange(objs[2 * dim], dim, -1, fill=fill))
        return out

    # -- topology management ----------------------------------------------

    def sub(self, remain_dims: Sequence[bool]) -> "CartComm":
        """MPI_Cart_sub [S]: drop the dimensions where ``remain_dims`` is
        False; ranks sharing the dropped coordinates form each new
        communicator, which keeps the remaining dimensions' topology."""
        remain = tuple(bool(k) for k in remain_dims)
        if len(remain) != self.ndims:
            raise ValueError(f"need {self.ndims} remain flags, got {len(remain)}")
        kept = [i for i, k in enumerate(remain) if k]
        dropped = [i for i, k in enumerate(remain) if not k]

        def color(rank: int) -> int:
            c = self.coords_of(rank)
            out = 0
            for i in dropped:
                out = out * self.dims[i] + c[i]
            return out

        def key(rank: int) -> int:
            c = self.coords_of(rank)
            out = 0
            for i in kept:
                out = out * self.dims[i] + c[i]
            return out

        sub = self.comm.split_by_rank(color, key)
        return CartComm(sub,
                        [self.dims[i] for i in kept] or [1],
                        [self.periods[i] for i in kept] or [False])

    def dup(self) -> "CartComm":
        return CartComm(self.comm.dup(), self.dims, self.periods)


def cart_create(comm: Communicator, dims: Sequence[int],
                periods: Optional[Sequence[bool]] = None) -> CartComm:
    """MPI_Cart_create [S] (reorder is meaningless here: ranks are mesh
    positions already)."""
    return CartComm(comm, dims, periods)


class GraphComm:
    """Arbitrary directed process graphs — MPI_(Dist_)graph topologies [S].

    SPMD-compatible spelling: the GLOBAL edge list is given (identical on
    every rank), so the whole neighborhood structure is static — exactly
    what one traced program needs.  ``dist_graph_create_adjacent`` builds
    it from MPI's per-rank adjacency spelling on the process backends (an
    allgather of local edges, as real MPI implementations do internally).

    Communication decomposes into partial-permutation rounds
    (``schedules.graph_rounds`` — greedy edge coloring), each lowering to
    one ``comm.exchange`` (= one ``lax.ppermute`` on the SPMD backend):
    the same portable-primitives-only recipe as :class:`CartComm`.

    Result convention (matches the vector collectives): the process
    backends return exact in-neighbor-ordered lists; the SPMD backend,
    whose shapes are static, returns a stacked ``[max_in_degree, ...]``
    array padded with ``fill`` — rows ``[:in_degree(r)]`` match the list.
    """

    def __init__(self, comm: Communicator, edges: Sequence[Pair],
                 in_order: Optional[Sequence[Sequence[int]]] = None,
                 out_order: Optional[Sequence[Sequence[int]]] = None):
        from . import schedules

        self.comm = comm
        size = comm.size
        # neighbor order is the INPUT edge-list order — never the
        # coloring's round order, which would silently permute results;
        # dist_graph_create_adjacent overrides with each rank's OWN
        # sources/destinations order (the MPI contract) via
        # in_order/out_order
        self.edges = schedules.dedupe_edges(edges, size)
        self._rounds = schedules.graph_rounds(self.edges, size)
        self._in: List[List[int]] = [[] for _ in range(size)]
        self._out: List[List[int]] = [[] for _ in range(size)]
        for s, d in self.edges:  # one O(E) pass
            self._in[d].append(s)
            self._out[s].append(d)
        for given, derived, what in ((in_order, self._in, "in_order"),
                                     (out_order, self._out, "out_order")):
            if given is None:
                continue
            for r in range(size):
                if sorted(given[r]) != sorted(derived[r]):
                    raise ValueError(
                        f"{what}[{r}]={list(given[r])} names a different "
                        f"neighbor set than the edges ({derived[r]})")
                derived[r] = [int(x) for x in given[r]]
        # round index of each (src, dst) edge
        self._round_of = {e: k for k, rnd in enumerate(self._rounds)
                          for e in rnd}

    # -- static queries (host-side) ----------------------------------------

    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def rank(self):
        return self.comm.rank

    @property
    def n_rounds(self) -> int:
        return len(self._rounds)

    @property
    def max_in_degree(self) -> int:
        return max((len(n) for n in self._in), default=0)

    @property
    def max_out_degree(self) -> int:
        return max((len(n) for n in self._out), default=0)

    def in_neighbors_of(self, rank: int) -> List[int]:
        """MPI_Dist_graph_neighbors, incoming half (edge-list order)."""
        return list(self._in[rank])

    def out_neighbors_of(self, rank: int) -> List[int]:
        return list(self._out[rank])

    # -- neighborhood collectives [S: MPI-3 MPI_Neighbor_* over graphs] ----

    def _spmd(self) -> bool:
        return not isinstance(self.comm.rank, int)

    def _spmd_gather_receipts(self, receipts: List[Any], fill: Any):
        """Reorder per-round receipts into per-in-neighbor slots (SPMD
        result shape: stacked [max_in_degree, ...] padded with fill —
        slot k of rank r's output = the round its k-th in-edge ran in;
        padded rows point at round 0 and are overwritten with fill)."""
        import jax.numpy as jnp

        from jax import lax

        size, maxd = self.size, self.max_in_degree
        if not receipts or maxd == 0:  # edgeless graph: static empty stack
            shape = () if not receipts else jnp.asarray(receipts[0]).shape
            return jnp.zeros((0,) + shape)
        table = [[self._round_of[(s, r)] for s in self._in[r]]
                 + [0] * (maxd - len(self._in[r])) for r in range(size)]
        me = lax.axis_index(self.comm.axis_name)
        stacked = jnp.stack([jnp.asarray(x) for x in receipts])
        out = jnp.take(stacked, jnp.asarray(table)[me], axis=0)
        deg = jnp.asarray([len(self._in[r]) for r in range(size)])[me]
        mask = (jnp.arange(maxd) < deg).reshape(
            (maxd,) + (1,) * (out.ndim - 1))
        return jnp.where(mask, out, jnp.full_like(out, fill))

    def neighbor_allgather(self, obj: Any, fill: Any = 0):
        """Every rank contributes ``obj``; each rank receives one payload
        per IN-neighbor (see class docstring for the per-backend result
        shape).  ``n_rounds`` exchanges total."""
        receipts = [self.comm.exchange(obj, rnd, fill=fill)
                    for rnd in self._rounds]
        if not self._spmd():
            r = self.comm.rank
            return [receipts[self._round_of[(s, r)]] for s in self._in[r]]
        return self._spmd_gather_receipts(receipts, fill)

    def neighbor_alltoall(self, objs: Sequence[Any], fill: Any = 0):
        """One DISTINCT payload per OUT-neighbor (out-neighbor order;
        stacked [max_out_degree, ...] on the SPMD backend); returns the
        payloads received from each in-neighbor (allgather conventions)."""
        receipts = []
        if not self._spmd():
            r = self.comm.rank
            if len(objs) != len(self._out[r]):
                raise ValueError(
                    f"rank {r}: need one payload per out-neighbor "
                    f"({len(self._out[r])}), got {len(objs)}")
            for k, rnd in enumerate(self._rounds):
                mine = next((d for (s, d) in rnd if s == r), None)
                payload = (objs[self._out[r].index(mine)]
                           if mine is not None else None)
                receipts.append(self.comm.exchange(payload, rnd, fill=fill))
            return [receipts[self._round_of[(s, r)]] for s in self._in[r]]
        import jax.numpy as jnp

        from jax import lax

        x = jnp.asarray(objs)
        size, maxd = self.size, self.max_out_degree
        if x.shape[0] != maxd:
            raise ValueError(
                f"SPMD neighbor_alltoall payload needs leading dim == "
                f"max_out_degree ({maxd}), got {x.shape}")
        # which out-block each rank ships in round k (0 when idle: the
        # exchange pattern has no edge from an idle rank, so the payload
        # choice is irrelevant — nothing is sent)
        send_slot = [[next((self._out[r].index(d) for (s, d) in rnd
                            if s == r), 0) for r in range(size)]
                     for rnd in self._rounds]
        me = lax.axis_index(self.comm.axis_name)
        receipts = []
        for k, rnd in enumerate(self._rounds):
            slot = jnp.asarray(send_slot[k])[me]
            payload = lax.dynamic_index_in_dim(x, slot, 0, keepdims=False)
            receipts.append(self.comm.exchange(payload, rnd, fill=fill))
        return self._spmd_gather_receipts(receipts, fill)


def graph_create(comm: Communicator, edges: Sequence[Pair]) -> GraphComm:
    """MPI_Dist_graph_create with the global edge list [S] (the
    SPMD-compatible spelling; identical on every rank)."""
    return GraphComm(comm, edges)


def split_hierarchical(comm: Communicator, node_key=None
                       ) -> Tuple[Communicator, Optional[Communicator],
                                  List[int]]:
    """The two-level split behind hierarchical collectives (Open MPI
    HAN's shape): ``(intra, leaders, node_of)`` where ``intra`` groups
    the ranks sharing ``node_key(rank)`` (ordered by old rank, so the
    node's lowest rank is intra rank 0 — the node leader), ``leaders``
    contains exactly the leaders (None on non-leader ranks), and
    ``node_of[r]`` is rank r's dense node id (nodes numbered in
    first-appearance order, which makes node n's rank in ``leaders``
    exactly n).

    ``node_key`` must be a pure function of the comm rank, identical on
    every rank (the split_by_rank contract).  Default: the shared-memory
    domain — worlds this library's launcher starts are single-host, so
    every rank shares node 0; mixed worlds pass their real host key, and
    tests pass synthetic keys to exercise the composition on one box."""
    if node_key is None:
        node_key = lambda r: 0  # noqa: E731 - the single-host domain
    keys = [node_key(r) for r in range(comm.size)]
    order: dict = {}
    for k in keys:
        order.setdefault(k, len(order))
    node_of = [order[k] for k in keys]
    my_node = node_of[comm.rank]
    intra = comm.split(my_node, key=comm.rank)
    is_leader = intra.rank == 0
    leaders = comm.split(0 if is_leader else None, key=comm.rank)
    return intra, leaders, node_of


class HierarchicalComm:
    """Hierarchical collective dispatch over a two-level split: the
    intra-node tier runs on each node's own communicator — where the shm
    transport's collective arena (mpi_tpu/coll_sm.py) serves collectives
    by load/store — and the inter-node tier runs the measured wire
    algorithms (ring / Rabenseifner via ``inter_algorithm``) between the
    node leaders only.  An allreduce therefore moves each payload once
    per node over the wire instead of once per rank: intra reduce →
    leaders allreduce → intra bcast.

    Wraps (never mutates) an existing communicator, like CartComm."""

    def __init__(self, comm: Communicator, node_key=None,
                 inter_algorithm: str = "auto"):
        self.comm = comm
        self.intra, self.leaders, self._node_of = split_hierarchical(
            comm, node_key)
        self._members: List[List[int]] = [
            [] for _ in range(max(self._node_of) + 1)]
        for r, n in enumerate(self._node_of):
            self._members[n].append(r)
        self._leader_of = [m[0] for m in self._members]
        self._inter = inter_algorithm

    # -- identity ----------------------------------------------------------

    @property
    def rank(self):
        return self.comm.rank

    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def n_nodes(self) -> int:
        return len(self._members)

    def _to_leader(self, obj: Any, root: int) -> Any:
        """Hop a payload from ``root`` to its node leader (identity when
        root IS the leader).  Rides ``comm.exchange`` — the static-pattern
        p2p primitive every backend provides — so bystander ranks no-op."""
        leader = self._leader_of[self._node_of[root]]
        if leader == root:
            return obj
        got = self.comm.exchange(obj, [(root, leader)])
        return got if self.comm.rank == leader else obj

    # -- collectives -------------------------------------------------------

    def barrier(self) -> None:
        """Gather phase in every node, one inter-node round among the
        leaders, release phase in every node."""
        self.intra.barrier()
        if self.leaders is not None:
            self.leaders.barrier()
        self.intra.barrier()

    def allreduce(self, obj: Any, op: Any = None) -> Any:
        from . import ops as _ops

        op = op or _ops.SUM
        part = self.intra.reduce(obj, op, root=0)
        if self.leaders is not None:
            part = self.leaders.allreduce(part, op,
                                          algorithm=self._inter)
        return self.intra.bcast(part, root=0)

    def reduce(self, obj: Any, op: Any = None, root: int = 0) -> Any:
        from . import ops as _ops

        op = op or _ops.SUM
        part = self.intra.reduce(obj, op, root=0)
        rn = self._node_of[root]
        val = (self.leaders.reduce(part, op, root=rn)
               if self.leaders is not None else part)
        if self._node_of[self.comm.rank] != rn:
            return None
        # root's node: ship the total from the node leader to root
        # (intra bcast keeps it collective-only; non-roots drop it)
        val = self.intra.bcast(val, root=0)
        return val if self.comm.rank == root else None

    def bcast(self, obj: Any, root: int = 0) -> Any:
        obj = self._to_leader(obj, root)
        if self.leaders is not None:
            obj = self.leaders.bcast(obj, root=self._node_of[root])
        return self.intra.bcast(obj, root=0)

    def allgather(self, obj: Any) -> Any:
        from .communicator import _maybe_stack

        node_items = self.intra.gather(obj, root=0)
        full: List[Any] = [None] * self.comm.size
        if self.leaders is not None:  # exactly the leaders (intra rank 0)
            per_node = self.leaders.allgather([list(node_items)])
            for n, (items,) in enumerate(per_node):
                for i, r in enumerate(self._members[n]):
                    full[r] = items[i]
        full = self.intra.bcast(full, root=0)
        return _maybe_stack(obj, full)


def dist_graph_create_adjacent(comm: Communicator,
                               sources: Sequence[int],
                               destinations: Sequence[int]) -> GraphComm:
    """MPI_Dist_graph_create_adjacent [S]: every rank names ITS incoming
    ``sources`` and outgoing ``destinations``; the global edge list is the
    allgathered union (what MPI implementations build internally).
    Process backends only — the allgather of per-rank Python lists has no
    SPMD analogue; use :func:`graph_create` there."""
    r = comm.rank
    if not isinstance(r, int):
        raise TypeError(
            "dist_graph_create_adjacent needs per-rank adjacency lists, "
            "which an SPMD trace cannot collect — pass the global edge "
            "list to graph_create instead")
    local = ([int(s) for s in sources], [int(d) for d in destinations])
    gathered = comm.allgather(local)  # [(sources, destinations)] per rank
    seen, edges = set(), []
    for rk, (srcs, dsts) in enumerate(gathered):
        for e in ([(s, rk) for s in srcs] + [(rk, d) for d in dsts]):
            if e not in seen:
                seen.add(e)
                edges.append(e)
    # each rank's neighbor ORDER is its own sources/destinations order
    # (the MPI contract), not the union scan order
    return GraphComm(comm, edges,
                     in_order=[srcs for srcs, _ in gathered],
                     out_order=[dsts for _, dsts in gathered])
