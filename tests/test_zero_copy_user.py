"""Zero-copy everywhere (ISSUE 19): shm ring steering, user-buffer
rendezvous (``irecv(buf=...)``), and scatter-gather receives.

World-level legs run the real transports through the thread harnesses
(``run_socket_world`` / ``run_shm_world``) and assert the pvar deltas
the acceptance criteria name: ``recv_bytes_steered`` > 0 on shm with
``payload_copies`` at the arena-only floor, ``recv_user_inplace``
ticking with ZERO pool stores on the steered user path, and the named
``recv_user_fallbacks`` pool fallback whenever the match races the
reader (including across an shm membership purge — no cross-generation
byte may land in a user buffer through a stale claim).

Registry unit tests pin the user-channel pairing algebra: activation
backlog seeds the lag, probe steals decrement it, ``claimable=False``
posts decline without polluting the fold-race pvar, and the aliasing
guard (sanitize / pre_overwrite / steer_abort) turns every mispairing
into a copy, never corruption.
"""

import os
import sys
import threading

import numpy as np

from mpi_tpu import mpit, ops
from mpi_tpu.recvpool import PostedRecvRegistry

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_resilience import run_socket_world  # noqa: E402
from test_shm_backend import run_shm_world    # noqa: E402

SRC, CTX = 1, ("c", 0)

_NAMES = ("recv_user_inplace", "recv_user_fallbacks", "recv_bytes_steered",
          "recv_pool_rendezvous", "recv_pool_hits", "recv_pool_misses",
          "payload_copies", "link_recv_syscalls")


def _deltas(runner, prog, nranks, **kw):
    base = {n: mpit.pvar_read(n) for n in _NAMES}
    res = runner(prog, nranks, **kw)
    return res, {n: mpit.pvar_read(n) - base[n] for n in _NAMES}


def _plan(shape, ds="<f8"):
    return ("arr", ds, tuple(shape))


# -- shm acceptance: the 16MB ring allreduce ----------------------------------


def test_shm_16mb_allreduce_steers_to_the_arena_only_floor():
    """The shm edition of the socket acceptance leg: steering off, the
    ring drain pool-stages every body and each fold-site store is
    priced into ``payload_copies``; steering on, the drain consults the
    same posted-recv registry and copies each in-order frame ONCE from
    the ring directly into its destination span."""
    data = [np.random.RandomState(i).randn(1 << 21) for i in range(2)]  # 16MB
    want = data[0] + data[1]

    def prog(comm):
        out = comm.allreduce(data[comm.rank], ops.SUM)
        np.testing.assert_allclose(out, want)
        return True

    old = mpit.cvar_read("recv_steering")
    try:
        mpit.cvar_write("recv_steering", 0)
        res, off = _deltas(run_shm_world, prog, 2)
        assert all(res)
        mpit.cvar_write("recv_steering", 1)
        res, on = _deltas(run_shm_world, prog, 2)
        assert all(res)
    finally:
        mpit.cvar_write("recv_steering", old)
    # off: every received span is a priced store (the shm ring's 256KB
    # segments sit BELOW the pool's 1MB class floor, so unlike the 4MB
    # socket segments they allocate plain — no hit/miss tick)
    assert off["recv_bytes_steered"] == 0
    assert off["payload_copies"] >= 2
    # on: stores leave the copy counter, bytes land straight in spans
    assert on["payload_copies"] == 0
    assert on["recv_pool_rendezvous"] > 0
    assert on["recv_bytes_steered"] >= 4 << 20


# -- user-buffer rendezvous: irecv(buf=...) -----------------------------------


def _user_inplace_prog(payload, tag):
    """Receiver posts BEFORE the sender fires (tag-99 handshake), so the
    posted entry provably precedes the frame and the steer must win."""

    def prog(comm):
        if comm.rank == 0:
            comm.recv(1, 99)
            comm.send(payload, dest=1, tag=tag)
            return True
        buf = np.zeros_like(payload)
        req = comm.irecv(0, tag, buf=buf)
        comm.send(b"posted", dest=0, tag=99)
        got = req.wait()
        assert got is buf, "user rendezvous did not deliver in place"
        np.testing.assert_array_equal(buf, payload)
        return True

    return prog


def test_user_irecv_lands_in_place_on_socket():
    payload = np.random.RandomState(7).randn(1 << 17)
    res, d = _deltas(run_socket_world, _user_inplace_prog(payload, 21), 2)
    assert all(res)
    assert d["recv_user_inplace"] == 1 and d["recv_user_fallbacks"] == 0
    assert d["recv_bytes_steered"] >= payload.nbytes
    # zero pool stores on the steered path (handshake frames are pickled)
    assert d["recv_pool_hits"] + d["recv_pool_misses"] == 0


def test_user_irecv_lands_in_place_on_shm():
    payload = np.random.RandomState(8).randn(1 << 17)
    res, d = _deltas(run_shm_world, _user_inplace_prog(payload, 22), 2)
    assert all(res)
    assert d["recv_user_inplace"] == 1 and d["recv_user_fallbacks"] == 0
    assert d["recv_bytes_steered"] >= payload.nbytes
    assert d["recv_pool_hits"] + d["recv_pool_misses"] == 0


def test_recv_init_user_buffer_refires_in_place():
    """Persistent-recv handles re-arm the SAME buffer every start():
    each round's frame steers into it with no per-round allocation."""
    rounds = 3
    payloads = [np.random.RandomState(30 + i).randn(1 << 14)
                for i in range(rounds)]

    def prog(comm):
        if comm.rank == 0:
            comm.recv(1, 99)
            for p in payloads:
                comm.send(p, dest=1, tag=23)
            return True
        buf = np.zeros(1 << 14)
        h = comm.recv_init(0, 23, buf=buf)
        comm.send(b"armed", dest=0, tag=99)
        for p in payloads:
            got = h.start().wait()
            np.testing.assert_array_equal(np.asarray(got), p)
            np.testing.assert_array_equal(buf, p)
        return True

    res, d = _deltas(run_socket_world, prog, 2)
    assert all(res)
    assert d["recv_user_inplace"] >= 1


# -- scatter-gather: multi-segment frames into a view list --------------------


def _sg_prog(segs, tag):
    def prog(comm):
        if comm.rank == 0:
            comm.recv(1, 99)
            comm.send(list(segs), dest=1, tag=tag)
            return True
        bufs = [np.zeros_like(s) for s in segs]
        req = comm.irecv(0, tag, buf=bufs)
        comm.send(b"posted", dest=0, tag=99)
        got = req.wait()
        assert got is bufs, "multi-segment frame did not steer per-segment"
        for b, s in zip(bufs, segs):
            np.testing.assert_array_equal(b, s)
        return True

    return prog


def test_scatter_gather_irecv_on_socket_uses_vectored_reads():
    segs = (np.random.RandomState(1).randn(1 << 15),
            np.random.RandomState(2).randn(1 << 14),
            np.random.RandomState(3).randn(1 << 13))
    res, d = _deltas(run_socket_world, _sg_prog(segs, 31), 2)
    assert all(res)
    assert d["recv_user_inplace"] == 1 and d["recv_user_fallbacks"] == 0
    assert d["recv_bytes_steered"] == sum(s.nbytes for s in segs)
    # the segments arrived through recvmsg_into, not one read per view
    assert d["link_recv_syscalls"] >= 1


def test_scatter_gather_irecv_on_shm():
    segs = (np.random.RandomState(4).randn(1 << 15),
            np.random.RandomState(5).randn(1 << 14))
    res, d = _deltas(run_shm_world, _sg_prog(segs, 32), 2)
    assert all(res)
    assert d["recv_user_inplace"] == 1 and d["recv_user_fallbacks"] == 0
    assert d["recv_bytes_steered"] == sum(s.nbytes for s in segs)


# -- fallbacks: the match racing the reader -----------------------------------


def test_user_irecv_beaten_by_frame_takes_pool_path():
    """The frame is already QUEUED when the irecv posts (tag-12 sentinel
    rides the same FIFO link, so delivery order is deterministic): the
    activation backlog keeps the pairing aligned, the steer never
    happens, and the completion falls back to one sanctioned copy into
    the user's buffer with the named pvar ticking."""
    payload = np.random.RandomState(9).randn(1 << 14)

    def prog(comm):
        if comm.rank == 0:
            comm.send(payload, dest=1, tag=41)
            comm.send(b"sent", dest=1, tag=42)
            return True
        comm.recv(0, 42)              # tag-41 frame is now in the mailbox
        buf = np.zeros_like(payload)
        got = comm.irecv(0, 41, buf=buf).wait()
        assert got is not buf         # pool path, then copied in
        np.testing.assert_array_equal(buf, payload)
        np.testing.assert_array_equal(np.asarray(got), payload)
        return True

    res, d = _deltas(run_socket_world, prog, 2)
    assert all(res)
    assert d["recv_user_fallbacks"] == 1 and d["recv_user_inplace"] == 0


def test_shm_purge_fences_user_buffer_across_generations():
    """Membership purge/rejoin with a user buffer armed: the purge
    clears the posted entry and fences the watermark to the bumped
    generation, so the post-heal frame can never claim the stale entry
    — it takes the pool path (fallback pvar) and the buffer ends with
    exactly the new-generation bytes, placed by the completion copy,
    not by a cross-generation steer."""
    payload = np.random.RandomState(11).randn(1 << 14)
    bar = threading.Barrier(2)

    def prog(comm):
        if comm.rank == 0:
            bar.wait()                              # peer armed its buf
            comm._t.membership_invalidate([1])      # symmetric link flap
            bar.wait()
            comm.send(payload, dest=1, tag=51)
            return True
        buf = np.zeros_like(payload)
        req = comm.irecv(0, 51, buf=buf)
        bar.wait()
        comm._t.membership_invalidate([0])          # purge + ring recreate
        bar.wait()
        got = req.wait()
        assert got is not buf
        np.testing.assert_array_equal(buf, payload)
        np.testing.assert_array_equal(np.asarray(got), payload)
        return True

    res, d = _deltas(run_shm_world, prog, 2)
    assert all(res)
    assert d["recv_user_fallbacks"] == 1 and d["recv_user_inplace"] == 0


# -- registry unit tests: user-channel pairing algebra ------------------------


def test_backlog_seeds_lag_so_queued_frames_skip_the_first_post():
    """A pre-activation mailbox backlog of 1 means consumer #1 will pop
    the queued (uncounted) message: the first COUNTED frame must pair
    with consumer #2, never scribble consumer #1's buffer."""
    reg = PostedRecvRegistry()
    d1, d2 = np.zeros(4), np.zeros(4)
    t1 = reg.note_post_user(SRC, CTX, 5, backlog=1)
    reg.attach(t1, d1)
    t2 = reg.note_post_user(SRC, CTX, 5)
    reg.attach(t2, d2)
    got = reg.note_frame(SRC, CTX, 5, 1, 0, _plan((4,)))
    assert got is d2
    reg.steer_done(d2)
    assert reg.sanitize(d2, d2) is d2   # owner pop closes the lifecycle


def test_probe_steal_shifts_pairing_back_by_one():
    """A matched probe popped frame N: its consumer is still waiting, so
    frame N+1 belongs to it (no entry left -> pool path, a copy), and
    frame N+2 pairs with the NEXT posted entry."""
    reg = PostedRecvRegistry()
    d1, d2 = np.zeros(4), np.zeros(4)
    t1 = reg.note_post_user(SRC, CTX, 6)
    reg.attach(t1, d1)
    assert reg.note_frame(SRC, CTX, 6, 1, 0, _plan((4,))) is d1
    reg.steer_done(d1)
    assert reg.sanitize(d1) is not d1   # the probe's pop: a private copy
    reg.note_steal(SRC, CTX, 6)
    t2 = reg.note_post_user(SRC, CTX, 6)
    reg.attach(t2, d2)
    # frame 2 re-pairs with consumer 1 (entry gone -> pool, copy only)
    assert reg.note_frame(SRC, CTX, 6, 2, 0, _plan((4,))) is None
    # frame 3 pairs with consumer 2's entry
    assert reg.note_frame(SRC, CTX, 6, 3, 0, _plan((4,))) is d2


def test_unclaimable_post_declines_without_a_fold_race_tick():
    """A bufferless user irecv on an active channel is a DECISION, not
    a race: its frame folds through the pool silently."""
    reg = PostedRecvRegistry()
    reg.attach(reg.note_post_user(SRC, CTX, 7), np.zeros(4))  # activate
    reg.note_frame(SRC, CTX, 7, 1, 0, _plan((4,)))
    tok = reg.note_post_user(SRC, CTX, 7, claimable=False)
    c0 = mpit.pvar_read("recv_pool_fold_fallbacks")
    assert reg.note_frame(SRC, CTX, 7, 2, 0, _plan((4,))) is None
    assert mpit.pvar_read("recv_pool_fold_fallbacks") == c0
    # a later attach on the same token re-arms the entry
    d = np.zeros(4)
    tok2 = reg.note_post_user(SRC, CTX, 7, claimable=False)
    reg.attach(tok2, d)
    assert reg.note_frame(SRC, CTX, 7, 3, 0, _plan((4,))) is d
    reg.steer_done(d)
    reg.cancel(tok)


def test_pre_overwrite_rescues_steered_bytes_for_the_foreign_popper():
    """Owner completes through the fallback while its steered view is
    still queued for someone else: the rescue snapshot preserves the
    frame's bytes across the owner's overwrite."""
    reg = PostedRecvRegistry()
    d = np.zeros(4)
    tok = reg.note_post_user(SRC, CTX, 8)
    reg.attach(tok, d)
    assert reg.note_frame(SRC, CTX, 8, 1, 0, _plan((4,))) is d
    d[:] = [1.0, 2.0, 3.0, 4.0]        # the frame's bytes
    reg.steer_done(d)
    reg.pre_overwrite(d)               # owner takes the fallback path
    d[:] = 9.0                         # ...and overwrites its buffer
    out = reg.sanitize(d)              # the foreign popper arrives late
    assert out is not d
    np.testing.assert_array_equal(out, [1.0, 2.0, 3.0, 4.0])
    assert reg.live_count == 0         # lifecycle closed


def test_steer_abort_drops_the_guard_without_a_copy():
    reg = PostedRecvRegistry()
    d = np.zeros(4)
    tok = reg.note_post_user(SRC, CTX, 9)
    reg.attach(tok, d)
    assert reg.note_frame(SRC, CTX, 9, 1, 0, _plan((4,))) is d
    reg.steer_abort(d)                 # torn frame: view never delivered
    assert reg.live_count == 0
    assert reg.sanitize(d) is d        # outside the guard: identity
