"""TpuCommunicator — MPI semantics bound to a jax.sharding.Mesh axis.

The headline backend (BASELINE.json:5): MPI_COMM_WORLD binds to a mesh over
the TPU slice; point-to-point lowers to ``lax.ppermute``; collectives
re-emit as ``lax.psum`` / ``lax.all_gather`` / ``lax.all_to_all`` over ICI
('fused'), or as hand-scheduled ppermute algorithms ('ring',
'recursive_halving', 'tree', 'doubling', 'pairwise' — mpi_tpu/tpu/
collectives.py) preserving the reference's algorithm-selection dimension.

The governing design decision (SURVEY.md §7): an MPI "rank" is a mesh-axis
index inside ONE SPMD program, not an OS process.  Methods must be called
inside the traced program (under ``run_spmd`` / ``jax.shard_map`` over this
communicator's mesh); ``rank`` is a traced scalar, ``size`` is static.

comm.split() maps to XLA's ``axis_index_groups``: sibling groups all execute
the same program, each group communicating internally (SURVEY.md §3.4).
Restrictions this implies — diagnosed loudly, never silently misdelivered
(SURVEY.md §7 hard parts 1-3):

* groups produced by split must be equal-sized (SPMD shapes are uniform);
* per-rank dynamic control flow (``if rank == 0: comm.send(...)``) cannot be
  traced; use the portable patterns instead: ``shift`` (halo exchange),
  ``exchange`` (static pairwise pattern), or collectives;
* arbitrary picklable payloads become arrays (jax pytrees) — the CPU
  backends keep full pickle generality;
* hand-scheduled algorithms ('ring', 'recursive_halving', 'tree', ...) build
  their result out of ppermute steps, so shard_map's varying-manual-axes
  tracker sees them as rank-varying even though the values are replicated;
  promise a replicated out_spec only for 'fused' results, or route
  hand-scheduled results through per-rank (sharded) out_specs as
  ``run_spmd`` does.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from .. import ops as _ops
from .. import schedules
from ..checker import validate_perm
from ..communicator import Communicator, _CompletedRequest
from . import collectives as algos

Pair = Tuple[int, int]


def _pallas_op_name(op: _ops.ReduceOp) -> str:
    """The pallas kernel's combiner key for ``op`` — gated by object
    IDENTITY against the built-ins, so a user ``make_op`` that happens to
    reuse the name 'max' can never be silently swapped for jnp.maximum."""
    for builtin in (_ops.SUM, _ops.MAX, _ops.MIN):
        if op is builtin:
            return op.name
    raise NotImplementedError(
        f"pallas_ring supports the built-in SUM/MAX/MIN ops, got {op!r}; "
        f"use a ppermute algorithm ('ring'/'recursive_halving') for other "
        f"reductions")


class SpmdSemanticsError(NotImplementedError):
    """An MPI idiom with no SPMD analogue was used on the TPU backend."""


def _unsupported(what: str, alternative: str):
    return SpmdSemanticsError(
        f"{what} has no per-rank analogue inside one traced SPMD program "
        f"(SURVEY.md §7 hard parts): every rank executes the same trace, so "
        f"rank-dependent message initiation cannot be expressed. {alternative}"
    )


class TpuCommunicator(Communicator):
    """MPI communicator over one named axis of a jax Mesh.

    ``groups=None`` covers the whole axis (MPI_COMM_WORLD).  After split(),
    ``groups`` is a partition of the axis indices into equal-sized groups;
    every method then operates group-locally (XLA axis_index_groups).
    """

    def __init__(self, axis_name: str, mesh: Mesh,
                 groups: Optional[List[List[int]]] = None,
                 pallas_interpret: Optional[bool] = None):
        if axis_name not in mesh.axis_names:
            raise ValueError(f"axis {axis_name!r} not in mesh axes {mesh.axis_names}")
        self.axis_name = axis_name
        self.mesh = mesh
        self._axis_size = mesh.shape[axis_name]
        # pallas_interpret: None → auto (interpret on CPU platforms);
        # False forces the compiled kernel — needed when CROSS-LOWERING
        # for TPU from a CPU host (jax.export platforms=['tpu']), where
        # the trace-time platform probe would otherwise bake in the
        # interpreter fallback instead of the RDMA kernel
        self._pallas_interpret = pallas_interpret
        if groups is not None:
            sizes = {len(g) for g in groups}
            if len(sizes) != 1:
                raise ValueError(
                    f"SPMD sub-communicators must be equal-sized, got group sizes "
                    f"{sorted(len(g) for g in groups)}; pad your split colors "
                    f"(XLA axis_index_groups requires a uniform partition)"
                )
            covered = sorted(i for g in groups for i in g)
            if covered != list(range(self._axis_size)):
                raise ValueError(
                    f"groups must partition the whole axis 0..{self._axis_size - 1} "
                    f"exactly once (every device executes the SPMD program); got {groups}"
                )
        self._groups = groups
        # rank/group lookup tables, indexed by world axis-index
        rank_of = np.arange(self._axis_size)
        group_of = np.zeros(self._axis_size, dtype=np.int32)
        if groups is not None:
            for gi, g in enumerate(groups):
                for pos, world in enumerate(g):
                    rank_of[world] = pos
                    group_of[world] = gi
        self._rank_table = rank_of
        self._group_table = group_of

    # -- identity ----------------------------------------------------------

    @property
    def rank(self):
        """Group-local rank — a *traced* scalar (valid inside the SPMD trace)."""
        idx = lax.axis_index(self.axis_name)
        if self._groups is None:
            return idx
        return jnp.asarray(self._rank_table)[idx]

    @property
    def size(self) -> int:
        return self._axis_size if self._groups is None else len(self._groups[0])

    @property
    def group_id(self):
        """Which sibling group this shard belongs to (traced; 0 if unsplit)."""
        idx = lax.axis_index(self.axis_name)
        return jnp.asarray(self._group_table)[idx]

    @property
    def axis_index_groups(self) -> Optional[List[List[int]]]:
        return self._groups

    @property
    def _on_cpu(self) -> bool:
        try:
            devices = self.mesh.devices
        except ValueError:  # AbstractMesh (AOT lowering): target backend
            import jax

            return jax.default_backend() == "cpu"
        return devices.flat[0].platform == "cpu"

    @property
    def _pallas_interp(self) -> bool:
        """Whether pallas_ring calls run under the interpreter: the
        constructor's explicit ``pallas_interpret`` if given, else the
        platform probe (see ``__init__``)."""
        if self._pallas_interpret is not None:
            return self._pallas_interpret
        return self._on_cpu

    def _world_pairs(self, group_pairs: Sequence[Pair]) -> List[Pair]:
        """Expand group-local (src, dst) pairs to world-level ppermute pairs
        across all sibling groups; validated (checker = trace-time sanitizer)."""
        if self._groups is None:
            pairs = list(group_pairs)
        else:
            pairs = [(g[s], g[d]) for g in self._groups for (s, d) in group_pairs]
        validate_perm(pairs, self._axis_size)
        return pairs

    # -- point-to-point ----------------------------------------------------

    def send(self, obj: Any, dest: int, tag: int = 0) -> None:
        raise _unsupported(
            "MPI_Send", "Use comm.shift(x, offset) for neighbor patterns, "
            "comm.exchange(x, pairs) for an arbitrary static pattern, or a collective."
        )

    def recv(self, source: int = -1, tag: int = -1, status=None) -> Any:
        raise _unsupported(
            "MPI_Recv", "Use comm.shift(x, offset) for neighbor patterns, "
            "comm.exchange(x, pairs) for an arbitrary static pattern, or a collective."
        )

    def sendrecv(self, sendobj: Any, dest: int, source: int = -1,
                 sendtag: int = 0, recvtag: int = -1, status=None) -> Any:
        raise _unsupported(
            "MPI_Sendrecv with per-rank dest/source",
            "If the pattern is a uniform ring offset use comm.shift(x, offset); "
            "if it is a fixed pattern use comm.exchange(x, pairs).",
        )

    def isend(self, obj: Any, dest: int, tag: int = 0):
        raise _unsupported(
            "MPI_Isend", "SPMD communication is compiled into the program; "
            "use comm.shift / comm.exchange / collectives (XLA already "
            "overlaps the DMAs).")

    def irecv(self, source: int = -1, tag: int = -1):
        raise _unsupported(
            "MPI_Irecv", "SPMD communication is compiled into the program; "
            "use comm.shift / comm.exchange / collectives (XLA already "
            "overlaps the DMAs).")

    def isendrecv(self, sendobj: Any, dest: int, source: int = -1,
                  sendtag: int = 0, recvtag: int = -1):
        raise _unsupported(
            "MPI_Isendrecv with per-rank dest/source",
            "If the pattern is a uniform ring offset use comm.shift(x, "
            "offset); if it is a fixed pattern use comm.exchange(x, pairs) "
            "(XLA already overlaps the DMAs).")

    def isendrecv_replace(self, buf, dest: int, source: int = -1,
                          sendtag: int = 0, recvtag: int = -1):
        raise _unsupported(
            "MPI_Isendrecv_replace with per-rank dest/source",
            "Use comm.shift(x, offset) / comm.exchange(x, pairs) and "
            "rebind the result (SPMD arrays are immutable).")

    def send_init(self, buf: Any, dest: int, tag: int = 0):
        raise _unsupported(
            "MPI_Send_init", "the persistent-request idiom IS the compiled "
            "program on this backend: jit the exchange once "
            "(f = jax.jit(shard_map(lambda x: comm.exchange(x, pairs), ...)))"
            " and call it repeatedly — start() is f(x).")

    def recv_init(self, source: int = -1, tag: int = -1, buf: Any = None):
        raise _unsupported(
            "MPI_Recv_init", "the persistent-request idiom IS the compiled "
            "program on this backend: jit the exchange once and call it "
            "repeatedly.")

    def probe(self, source: int = -1, tag: int = -1, status=None):
        raise _unsupported(
            "MPI_Probe", "SPMD message arrival is static — there is nothing "
            "to probe; restructure with shift/exchange/collectives.")

    def iprobe(self, source: int = -1, tag: int = -1, status=None):
        raise _unsupported(
            "MPI_Iprobe", "SPMD message arrival is static — there is nothing "
            "to probe; restructure with shift/exchange/collectives.")

    def shift(self, obj, offset: int = 1, wrap: bool = True, fill: Any = None):
        """Neighbor exchange as exactly one ``lax.ppermute`` (SURVEY.md §3.2:
        the boundary crossing becomes an ICI DMA scheduled by XLA)."""
        if not wrap and fill is None:
            raise SpmdSemanticsError(
                "shift(wrap=False) needs an explicit numeric fill on the TPU "
                "backend: SPMD has no 'None at the boundary' (the CPU backends "
                "return None there) — pass fill=<boundary value> so all "
                "backends agree"
            )
        x = jnp.asarray(obj)
        p = self.size
        pairs = self._world_pairs(schedules.ring_perm(p, offset, wrap=wrap))
        recvd = lax.ppermute(x, self.axis_name, pairs)
        if not wrap and fill is not None:
            receivers = [r for r in range(p) if 0 <= r - offset < p]
            has_src = algos._mask_of(
                [g[r] for g in (self._groups or [list(range(p))]) for r in receivers],
                self._axis_size, self.axis_name)
            recvd = jnp.where(has_src, recvd, jnp.full_like(recvd, fill))
        return recvd

    def localize(self, obj):
        """Brand a (replicated) value as rank-varying over this comm's axis.

        See Communicator.localize: without this, ``jax.grad`` w.r.t. a
        replicated closure constant inside shard_map yields the psum of
        per-rank gradients (jax's varying-axes-typed AD), silently breaking
        the MPI mental model where gradients are local until explicitly
        reduced."""
        import jax as _jax

        return _jax.tree.map(
            lambda x: algos._ensure_varying(jnp.asarray(x), self.axis_name), obj)

    def replicate(self, obj, root: int = 0):
        """Brand a VALUE-replicated but vma-varying pytree as replicated
        over this comm's axis — the inverse of :meth:`localize`.

        Hand-scheduled collectives (``algorithm='ring'`` / ``'tree'`` /
        ``'pallas_ring'``) produce results that equal on every rank but are
        opaque to shard_map's varying-axes inference, so a replicated
        out_spec rejects them under ``check_vma=True``.  This routes the
        value through ONE fused masked-psum (take root's copy, sum the
        zeros elsewhere) — value-preserving, and typed replicated.  Costs
        one real collective; skip it (or use ``check_vma=False``) on paths
        where that matters."""
        idx = lax.axis_index(self.axis_name) if self._groups is None else self.rank
        root_t = jnp.asarray(root)

        def one(x):
            x = jnp.asarray(x)
            masked = jnp.where(idx == root_t, x, jnp.zeros_like(x))
            if self._groups is None:
                return lax.psum(masked, self.axis_name)
            return self._grouped_psum(masked)

        import jax as _jax

        return _jax.tree.map(one, obj)

    def exchange(self, obj, pairs: Sequence[Pair], fill: Any = None):
        """Static-pattern p2p: every (src, dst) in ``pairs`` (group-local
        ranks) ships src's payload to dst in one ppermute.  This is the SPMD
        spelling of a set of matched MPI_Send/MPI_Recv calls; ranks not
        receiving get zeros (or ``fill`` when given)."""
        x = jnp.asarray(obj)
        world = self._world_pairs(pairs)
        out = lax.ppermute(x, self.axis_name, world)
        if fill is not None:
            receivers = [d for _, d in world]
            has_src = algos._mask_of(receivers, self._axis_size, self.axis_name)
            out = jnp.where(has_src, out, jnp.full_like(out, fill))
        return out

    # -- nonblocking collectives -------------------------------------------
    # In one traced SPMD program, "nonblocking" IS the compiler's job: XLA
    # already overlaps independent collectives with compute in its schedule.
    # The i* entry points therefore build the collective immediately and
    # return an already-complete Request holding the traced value — the
    # request/wait shape of portable MPI programs is preserved, and
    # reordering for overlap is left to XLA, which does it better.

    def ibcast(self, obj, root: int = 0):
        return _CompletedRequest(self.bcast(obj, root))

    def ireduce(self, obj, op: _ops.ReduceOp = _ops.SUM, root: int = 0):
        return _CompletedRequest(self.reduce(obj, op, root))

    def iallreduce(self, obj, op: _ops.ReduceOp = _ops.SUM,
                   algorithm: str = "auto"):
        return _CompletedRequest(self.allreduce(obj, op, algorithm))

    def iallgather(self, obj):
        return _CompletedRequest(self.allgather(obj))

    def ialltoall(self, objs):
        return _CompletedRequest(self.alltoall(objs))

    def ibarrier(self):
        self.barrier()
        return _CompletedRequest(None)

    def iscatter(self, objs, root: int = 0):
        return _CompletedRequest(self.scatter(objs, root))

    def igather(self, obj, root: int = 0):
        return _CompletedRequest(self.gather(obj, root))

    # -- one-sided (RMA) ---------------------------------------------------

    def win_create(self, init: Any):
        from .window import TpuWindow

        return TpuWindow(self, init)

    # -- collectives -------------------------------------------------------

    def bcast(self, obj, root: int = 0, algorithm: str = "auto"):
        x = jnp.asarray(obj)
        if algorithm == "auto":
            algorithm = "fused"
        if self.size == 1:
            return self._degenerate(x)
        if algorithm == "fused":
            # masked psum: transfers one payload-sized reduction instead of
            # materializing P gathered copies per device
            if x.dtype == jnp.bool_:
                return self.bcast(x.astype(jnp.uint8), root, "fused").astype(jnp.bool_)
            masked = jnp.where(self.rank == root, x, jnp.zeros_like(x))
            if self._groups is None:
                return lax.psum(masked, self.axis_name)
            return self._grouped_psum(masked)
        if algorithm == "tree":
            return algos.tree_bcast(x, self.axis_name, self.size, self.rank,
                                    self._world_pairs, self._axis_size, root)
        raise ValueError(f"unknown bcast algorithm {algorithm!r}")

    def reduce(self, obj, op: _ops.ReduceOp = _ops.SUM, root: int = 0,
               algorithm: str = "auto"):
        """Root holds the reduction; all other ranks hold the op identity
        (SPMD returns a value everywhere — the CPU backends return None off
        root)."""
        x = jnp.asarray(obj)
        if algorithm == "auto":
            algorithm = "tree"
        if self.size == 1:
            return self._degenerate(x)
        if algorithm == "fused":
            full = self.allreduce(x, op, algorithm="fused")
            ident = jnp.full(x.shape, op.identity(np.dtype(x.dtype)), x.dtype)
            return jnp.where(self.rank == root, full, ident)
        if algorithm == "tree":
            return algos.tree_reduce(x, self.axis_name, self.size, self.rank,
                                     self._world_pairs, self._axis_size, op, root)
        raise ValueError(f"unknown reduce algorithm {algorithm!r}")

    def allreduce(self, obj, op: _ops.ReduceOp = _ops.SUM, algorithm: str = "auto"):
        """``algorithm='auto'`` resolves to 'fused' at every size: on the
        measured 8-dev sim sweep (BASELINE.md, regenerated by
        benchmarks/gen_baseline.py) the fused XLA collective beats the
        hand schedules across 4KB-256MB (e.g. 16MB: 0.61 GB/s busbw vs
        ring 0.22 / halving 0.35; 256MB: 0.29 vs 0.12 / 0.11) — XLA's own
        ring is pipelined and fuses with neighbors, which the explicit
        ppermute schedules forgo.  On real ICI re-measure before changing
        this (the CPU backend's auto has a measured size crossover,
        communicator.py; the pallas_ring exists for where XLA's choice
        leaves ICI bandwidth unused)."""
        x = jnp.asarray(obj)
        if algorithm == "auto":
            algorithm = "fused"
        if self.size == 1:
            return self._degenerate(x)
        if algorithm == "fused":
            return self._fused_allreduce(x, op)
        if algorithm == "ring":
            return algos.ring_allreduce(x, self.axis_name, self.size, self.rank,
                                        self._world_pairs, op)
        if algorithm == "pallas_ring":
            # in-kernel pipelined RDMA ring (mpi_tpu/tpu/pallas_ring.py):
            # f32/bf16 sum/max/min; split comms run one ring per group
            from .pallas_ring import pallas_ring_allreduce

            return pallas_ring_allreduce(x, self.axis_name, self.size,
                                         interpret=self._pallas_interp,
                                         groups=self._groups,
                                         op=_pallas_op_name(op))
        if algorithm == "recursive_halving":
            return algos.halving_allreduce(x, self.axis_name, self.size, self.rank,
                                           self._world_pairs, op)
        if algorithm == "reduce_bcast":
            return self.bcast(self.reduce(x, op, 0, "tree"), 0, "tree")
        raise ValueError(f"unknown allreduce algorithm {algorithm!r}")

    def _degenerate(self, x):
        """Size-1 communicator: the value is its own reduction, but a real
        (no-op) collective must still be emitted on an unsplit comm so the
        result is marked replicated over the axis (shard_map's VMA check);
        with singleton groups the value genuinely stays rank-varying."""
        if self._groups is None and x.dtype != jnp.bool_:
            return lax.psum(x, self.axis_name)
        return x

    def _grouped_psum(self, x):
        """Grouped fused SUM, spelled as reduce-scatter + all-gather.

        jax 0.9's varying-axes (vma) typing has no grouped psum at all:
        ``bind_psum_invariant`` raises ``NotImplementedError`` whenever
        ``axis_index_groups is not None`` — on every platform, for varying
        and invariant operands alike (this was the round-2 red real-TPU
        test, VERDICT weak #1).  ``psum_scatter`` and ``all_gather`` DO
        accept groups under the checker, so the grouped fused sum is
        emitted as its classic decomposition — the same traffic pattern as
        a ring allreduce, and XLA fuses/schedules both halves over ICI."""
        g = len(self._groups[0])
        flat = x.reshape(-1)
        n = flat.shape[0]
        padded = -(-n // g) * g if n else g
        if padded != n:
            flat = jnp.pad(flat, (0, padded - n))
        rs = lax.psum_scatter(flat, self.axis_name, scatter_dimension=0,
                              axis_index_groups=self._groups, tiled=True)
        out = lax.all_gather(rs, self.axis_name,
                             axis_index_groups=self._groups, tiled=True)
        return out[:n].reshape(x.shape)

    def _fused_allreduce(self, x, op: _ops.ReduceOp):
        groups = self._groups
        if op.name == "sum" and x.dtype != jnp.bool_:
            if groups is None:
                return lax.psum(x, self.axis_name)
            return self._grouped_psum(x)
        elif op.name == "max":
            return lax.pmax(x, self.axis_name, axis_index_groups=groups)
        elif op.name == "min":
            return lax.pmin(x, self.axis_name, axis_index_groups=groups)
        return algos.tree_reduce_local(op, self._fused_allgather(x))

    def _fused_allgather(self, x):
        return lax.all_gather(x, self.axis_name, axis_index_groups=self._groups,
                              tiled=False)

    def allgather(self, obj, algorithm: str = "auto"):
        """Returns the stacked [size, ...] array in group-rank order (the CPU
        backends return a list; jnp.stack of that list is identical)."""
        x = jnp.asarray(obj)
        if algorithm == "auto":
            algorithm = "fused"
        if algorithm == "fused":
            return self._fused_allgather(x)
        if algorithm == "ring":
            return algos.ring_allgather(x, self.axis_name, self.size, self.rank,
                                        self._world_pairs)
        if algorithm == "doubling":
            return algos.doubling_allgather(x, self.axis_name, self.size, self.rank,
                                            self._world_pairs)
        if algorithm == "pallas_ring":
            # allgather-only mode of the in-kernel RDMA ring: P-1 pipelined
            # land-direct steps (mpi_tpu/tpu/pallas_ring.py)
            from .pallas_ring import pallas_ring_allgather

            return pallas_ring_allgather(x, self.axis_name, self.size,
                                         interpret=self._pallas_interp,
                                         groups=self._groups)
        raise ValueError(f"unknown allgather algorithm {algorithm!r}")

    def alltoall(self, objs, algorithm: str = "auto"):
        """``objs``: stacked [size, ...] array, block i destined for group
        rank i; returns [size, ...] with block j received from rank j — the
        Ulysses / expert-parallel primitive (SURVEY.md §2 strategy table)."""
        x = jnp.asarray(objs)
        if x.shape[0] != self.size:
            raise ValueError(
                f"alltoall payload needs leading dim == communicator size "
                f"({self.size}), got {x.shape}"
            )
        if algorithm == "auto":
            algorithm = "fused"
        if self.size == 1:
            return x
        if algorithm == "fused":
            return lax.all_to_all(x, self.axis_name, split_axis=0, concat_axis=0,
                                  axis_index_groups=self._groups, tiled=False)
        if algorithm == "pairwise":
            return algos.pairwise_alltoall(x, self.axis_name, self.size, self.rank,
                                           self._world_pairs)
        raise ValueError(f"unknown alltoall algorithm {algorithm!r}")

    def barrier(self) -> None:
        """SPMD programs are globally scheduled; emit a tiny psum as an
        explicit synchronization point (also an ICI liveness probe)."""
        lax.psum(jnp.zeros((), jnp.float32), self.axis_name)

    def scan(self, obj, op: _ops.ReduceOp = _ops.SUM):
        """Hillis-Steele inclusive prefix reduction: log2(P) masked-ppermute
        rounds; boundary holes are filled with the op identity so the
        unconditional combine is exact."""
        x = jnp.asarray(obj)
        if self.size == 1:
            return x
        acc = x
        # keep the identity as the dtype-typed numpy scalar — a float() round
        # trip corrupts 64-bit integer identities (iinfo(int64).max etc.)
        ident = op.identity(np.dtype(x.dtype))
        d = 1
        while d < self.size:
            recvd = self.shift(acc, offset=d, wrap=False, fill=ident)
            acc = op.combine(recvd, acc)  # received prefix goes LEFT
            d *= 2
        return acc

    def _allreduce_loc(self, obj, op: _ops.ReduceOp):
        # traced-rank spelling of Communicator._allreduce_loc (np.where can't
        # consume the traced rank scalar)
        x = jnp.asarray(obj)
        best = self.allreduce(x, op=op)
        cand = jnp.where(x == best, self.rank, self.size).astype(jnp.int32)
        return best, self.allreduce(cand, op=_ops.MIN)

    def reduce_scatter(self, blocks, op: _ops.ReduceOp = _ops.SUM,
                       algorithm: str = "auto"):
        """``blocks``: stacked [size, ...]; returns this rank's reduced block.
        'fused' lowers to one ``lax.psum_scatter`` (reduce-scatter over ICI —
        half of the ring-allreduce, and the gradient-sharding primitive of
        ZeRO/FSDP-style training); 'ring' is the hand schedule."""
        x = jnp.asarray(blocks)
        if x.shape[0] != self.size:
            raise ValueError(
                f"reduce_scatter payload needs leading dim == communicator "
                f"size ({self.size}), got {x.shape}")
        if algorithm == "auto":
            algorithm = "fused"
        if self.size == 1:
            return self._degenerate(x[0])
        if algorithm == "fused":
            if op.name == "sum":
                return lax.psum_scatter(x, self.axis_name, scatter_dimension=0,
                                        axis_index_groups=self._groups,
                                        tiled=False)
            # non-SUM: reduce locally after a fused alltoall of blocks
            return algos.tree_reduce_local(op, self.alltoall(x, "fused"))
        if algorithm == "ring":
            return algos.ring_reduce_scatter(x, self.axis_name, self.size,
                                             self.rank, self._world_pairs, op)
        if algorithm == "pallas_ring":
            # in-kernel RDMA ring, reduce-scatter half only (the ZeRO
            # gradient-sharding primitive at half the allreduce traffic)
            from .pallas_ring import pallas_ring_reduce_scatter

            return pallas_ring_reduce_scatter(x, self.axis_name, self.size,
                                              interpret=self._pallas_interp,
                                              groups=self._groups,
                                              op=_pallas_op_name(op))
        raise ValueError(f"unknown reduce_scatter algorithm {algorithm!r}")

    def scatter(self, objs, root: int = 0):
        """``objs``: stacked [size, ...] meaningful at root; every rank gets
        block ``rank``.

        Lowered as a masked reduce-scatter (zero everywhere but root, then
        ``psum_scatter``): O(payload) wire bytes per device — NOT the naive
        bcast-the-whole-stack, whose O(size × payload) per-device traffic
        and HBM footprint defeats scatter's purpose at large sizes
        (VERDICT r2 weak #6)."""
        x = jnp.asarray(objs)
        if x.shape[0] != self.size:
            raise ValueError(
                f"scatter payload needs leading dim == communicator size "
                f"({self.size}), got {x.shape}")
        if x.dtype == jnp.bool_:
            return self.scatter(x.astype(jnp.uint8), root).astype(jnp.bool_)
        if not jnp.issubdtype(x.dtype, jnp.floating) and \
                not jnp.issubdtype(x.dtype, jnp.integer):
            # exotic dtypes: fall back to the bcast spelling
            blocks = self.bcast(x, root)
            return lax.dynamic_index_in_dim(blocks, self.rank, 0,
                                            keepdims=False)
        masked = jnp.where(self.rank == root, x, jnp.zeros_like(x))
        return self.reduce_scatter(masked, op=_ops.SUM, algorithm="fused")

    def _warn_replicated_gather(self, x, what: str) -> None:
        """Loud diagnostic for the replicated-gather HBM blow-up
        (VERDICT r3 missing #3): every device materializes the full
        [size, ...] stack — O(size × payload) HBM per device.  Fires at
        trace time when the stack exceeds the writable
        ``gather_replicated_warn_bytes`` mpit cvar."""
        import warnings

        from .. import mpit

        nbytes = int(np.prod(x.shape or (1,))) * x.dtype.itemsize * self.size
        if nbytes > mpit.cvar_read("gather_replicated_warn_bytes"):
            warnings.warn(
                f"{what}: the replicated [size={self.size}, ...] stack is "
                f"{nbytes / 2**20:.0f} MiB PER DEVICE (O(size x payload) "
                f"HBM).  Use comm.{what}(..., sharded=True) to keep "
                f"per-device HBM O(payload) (compose with "
                f"out_specs=P(axis) — zero wire traffic), or raise the "
                f"gather_replicated_warn_bytes mpit cvar to silence this.",
                RuntimeWarning, stacklevel=3)

    def _brand_sharded_slice(self, x):
        """Brand a sharded-gather output slice as VARYING over this
        communicator's axis (VERDICT r4 weak #5): the slice is
        per-device data, so an enclosing shard_map whose caller forgot
        ``out_specs=P(axis)`` (e.g. wrote the replicated ``P()``) now
        gets a TYPED vma error at trace time instead of a silently
        wrong [1, ...] where a [size, ...] stack was expected.  Even a
        REPLICATED input value is branded — the contract of the
        sharded gather is 'my slice of the stack', which is positional
        and therefore varying by definition.  No protection exists
        under ``check_vma=False`` (there is no typing to flag against);
        that caveat is documented at every sharded-gather call site."""
        try:  # already varying over the axis (the usual case: the
            # gathered value is per-rank data) — nothing to brand
            if self.axis_name in jax.typeof(x).vma:
                return x
        except AttributeError:
            pass  # no vma typing on this value/jax
        # pcast is the current spelling (a no-op outside shard_map, so
        # no exception guard: real API breakage must FAIL the tests,
        # not silently un-brand the slice — review round 5)
        if hasattr(lax, "pcast"):
            return lax.pcast(x, self.axis_name, to="varying")
        try:  # pre-pcast jax: pvary raises on an unbound axis name
            return lax.pvary(x, self.axis_name)
        except (NameError, ValueError):
            # outside shard_map: nothing to brand against.  Which exception
            # an unbound axis raises has moved between jax releases
            # (NameError historically, ValueError in newer trace-context
            # plumbing — ADVICE r5 #3), so both mean the same benign thing
            return x

    def gather(self, obj, root: int = 0, sharded: bool = False):
        """Stacked [size, ...] — contract guarantees it only at root (other
        ranks get it too; SPMD gathers are symmetric).

        ``sharded=True`` is the honest large-payload spelling (VERDICT r3
        missing #3): each device returns ONLY its own [1, ...] slice of
        the stack — in SPMD a gather whose output stays sharded over the
        axis is the identity, so it costs ZERO wire traffic and O(payload)
        HBM per device.  Compose with ``out_specs=P(axis_name)`` on the
        enclosing shard_map and the caller sees the same global [size, ...]
        stack the replicated form produces, assembled by the output
        sharding instead of by an all-gather.  The slice is branded
        vma-VARYING over the axis, so forgetting the sharded out_spec
        fails the vma typecheck loudly (under ``check_vma=False`` no
        typing exists — the composition is then on the caller).

        ``sharded=False`` (the MPI-shaped default) materializes the full
        stack on EVERY device — O(size × payload) HBM, unlike the process
        backends where only root pays; above the
        ``gather_replicated_warn_bytes`` mpit cvar it warns and points
        here.  For reductions, prefer ``reduce_scatter`` (data stays
        sharded); XLA can also DCE non-root slices if the caller
        immediately takes ``stack[root]``."""
        x = jnp.asarray(obj)
        if sharded:
            return self._brand_sharded_slice(x[None])
        self._warn_replicated_gather(x, "gather")
        return self.allgather(x)

    # -- vector (variable-count) collectives -------------------------------
    # Static counts + padded payloads: the SPMD spelling of MPI_*v (see
    # Communicator.allgatherv docstring for the shared contract).

    def allgatherv(self, obj, counts: Sequence[int]):
        """Padded input [max(counts), ...]; returns the exact ragged
        concatenation [sum(counts), ...] (static shape), replicated."""
        self._check_counts(counts)
        counts = [int(c) for c in counts]
        x = jnp.asarray(obj)
        maxc = max(counts) if counts else 0
        if x.shape[0] < maxc:
            raise ValueError(
                f"allgatherv payload must be padded to max(counts)={maxc} "
                f"rows (got {x.shape[0]}); SPMD shapes are static")
        g = self.allgather(x[:maxc], algorithm="fused")
        return jnp.concatenate(
            [g[i, : counts[i]] for i in range(self.size)], axis=0)

    def gatherv(self, obj, counts: Sequence[int], root: int = 0,
                sharded: bool = False):
        """SPMD gathers are symmetric: every rank gets the concatenation.

        ``sharded=True`` routes through the sharded-output gather: each
        device returns its OWN block zero-padded to [max(counts), ...] —
        O(max(counts)) HBM, zero wire traffic.  Compose with
        ``out_specs=P(axis)`` for the global [size*max(counts), ...]
        padded stack, then ``TpuCommunicator.ragged_concat(stack, counts)``
        (host-side) recovers the exact ragged concatenation at root
        only — so no device ever holds O(sum(counts)).  The padded
        block is branded vma-VARYING like ``gather(sharded=True)``, so
        a non-sharded out_spec fails the typecheck loudly."""
        if sharded:
            self._check_counts(counts)
            counts = [int(c) for c in counts]
            x = jnp.asarray(obj)
            maxc = max(counts) if counts else 0
            if x.shape[0] < maxc:
                raise ValueError(
                    f"gatherv payload must be padded to max(counts)={maxc} "
                    f"rows (got {x.shape[0]}); SPMD shapes are static")
            x = x[:maxc]
            cnt = jnp.asarray(np.asarray(counts, np.int32))[self.rank]
            mask = jnp.arange(maxc) < cnt
            return self._brand_sharded_slice(jnp.where(
                mask.reshape((-1,) + (1,) * (x.ndim - 1)), x,
                jnp.zeros_like(x)))
        x = jnp.asarray(obj)
        self._warn_replicated_gather(x, "gatherv")
        return self.allgatherv(x, counts)

    @staticmethod
    def ragged_concat(stack, counts: Sequence[int]):
        """Host-side finisher for ``gatherv(..., sharded=True)``: given
        the assembled [size*max(counts), ...] (or [size, max(counts), ...])
        padded stack and the counts, return the exact ragged
        concatenation [sum(counts), ...].  Pure numpy — run it where the
        stack actually lives (root), not inside the SPMD program."""
        counts = [int(c) for c in counts]
        arr = np.asarray(stack)
        maxc = max(counts) if counts else 0
        if arr.ndim >= 2 and arr.shape[0] == len(counts) and \
                arr.shape[1] == maxc:
            blocks = arr
        else:
            blocks = arr.reshape((len(counts), maxc) + arr.shape[1:])
        return np.concatenate(
            [blocks[i, : counts[i]] for i in range(len(counts))], axis=0)

    def scatterv(self, obj, counts: Sequence[int], root: int = 0):
        """Root's [sum(counts), ...] concatenation; every rank gets its slice
        padded to [max(counts), ...] with zeros (static shapes)."""
        self._check_counts(counts)
        counts = [int(c) for c in counts]
        x = jnp.asarray(obj)
        total, maxc = sum(counts), (max(counts) if counts else 0)
        if x.shape[0] != total:
            raise ValueError(
                f"scatterv payload needs sum(counts)={total} rows, got {x.shape[0]}")
        if maxc == 0:
            return x[:0]
        blocks = self.bcast(x, root)
        # tail padding so the dynamic slice never clamps away a short tail
        pad = jnp.zeros((maxc,) + blocks.shape[1:], blocks.dtype)
        padded = jnp.concatenate([blocks, pad], axis=0)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
        start = jnp.asarray(starts)[self.rank]
        sliced = lax.dynamic_slice_in_dim(padded, start, maxc, axis=0)
        cnt = jnp.asarray(np.asarray(counts, np.int32))[self.rank]
        mask = jnp.arange(maxc) < cnt
        return jnp.where(mask.reshape((-1,) + (1,) * (sliced.ndim - 1)),
                         sliced, jnp.zeros_like(sliced))

    def alltoallv(self, blocks, counts: Sequence[Sequence[int]]):
        """``blocks``: [size, maxc, ...] padded, block d for group rank d
        with ``counts[rank][d]`` valid rows; returns [size, maxc, ...] where
        block j (from rank j) has ``counts[j][rank]`` valid rows, the rest
        zeroed.  maxc = global max of the counts matrix."""
        self._check_counts_matrix(counts)
        cmat = np.asarray([[int(c) for c in row] for row in counts], np.int32)
        x = jnp.asarray(blocks)
        maxc = int(cmat.max()) if cmat.size else 0
        if x.shape[0] != self.size or (maxc and x.shape[1] < maxc):
            raise ValueError(
                f"alltoallv payload needs shape [size={self.size}, "
                f">=max(counts)={maxc}, ...], got {x.shape}")
        x = x[:, :maxc]
        # zero this rank's padding rows so garbage never travels
        cnt_row = jnp.asarray(cmat)[self.rank]  # [size]
        mask = jnp.arange(maxc)[None, :] < cnt_row[:, None]
        x = jnp.where(mask.reshape(mask.shape + (1,) * (x.ndim - 2)),
                      x, jnp.zeros_like(x))
        return self.alltoall(x, algorithm="fused")

    # -- communicator management (host-side, outside the trace) ------------

    def split(self, color, key: int = 0):
        raise _unsupported(
            "comm.split(color, key) with per-rank color values",
            "Colors must be known for every rank on the host: call "
            "comm.split_all(colors, keys) with one color per world axis index, "
            "or comm.split_by(lambda world_idx: color) — outside the jitted "
            "program (SURVEY.md §3.4: split is host-side bookkeeping).",
        )

    def split_all(self, colors: Sequence[Optional[int]],
                  keys: Optional[Sequence[int]] = None) -> "TpuCommunicator":
        """MPI_Comm_split with the full color/key vectors (host-side).

        ``colors[i]`` is the color of world axis-index i (``None`` is not
        supported: every device runs the SPMD program, so the partition must
        be total).  Each current group partitions internally by color,
        ordered by (key, current group rank); resulting groups must be
        equal-sized."""
        if len(colors) != self._axis_size:
            raise ValueError(
                f"need one color per world axis index ({self._axis_size}), "
                f"got {len(colors)}"
            )
        if any(c is None for c in colors):
            raise ValueError(
                "color=None (MPI_UNDEFINED) is not expressible in SPMD: every "
                "device executes the program; give every rank a color"
            )
        keys = list(keys) if keys is not None else [0] * self._axis_size
        parent_groups = self._groups or [list(range(self._axis_size))]
        new_groups: List[List[int]] = []
        for g in parent_groups:
            buckets: dict = {}
            for pos, world in enumerate(g):
                buckets.setdefault(colors[world], []).append((keys[world], pos, world))
            for c in sorted(buckets):
                new_groups.append([w for _, _, w in sorted(buckets[c])])
        return self._inherit_errhandler(
            TpuCommunicator(self.axis_name, self.mesh, new_groups,
                            pallas_interpret=self._pallas_interpret))

    def split_by(self, color_fn, key_fn=None) -> "TpuCommunicator":
        """split_all with functions of the world axis index."""
        n = self._axis_size
        return self.split_all(
            [color_fn(i) for i in range(n)],
            [key_fn(i) for i in range(n)] if key_fn else None,
        )

    def split_type(self, split_type: str = "shared",
                   key: int = 0) -> "TpuCommunicator":
        """MPI_Comm_split_type(COMM_TYPE_SHARED), SPMD shape: peers whose
        devices live on the SAME HOST (jax process).  On a multi-host
        mesh the whole communicator does NOT share memory, so the split
        groups axis indices by the process indices of their devices
        (ADVICE r3 #4); on a single host it degenerates to the whole
        communicator, matching the base-class semantics."""
        if split_type != "shared":
            raise ValueError(f"unknown split_type {split_type!r}")
        try:
            devs = self.mesh.devices
        except ValueError:
            raise NotImplementedError(
                "COMM_TYPE_SHARED needs the mesh's device→host table; an "
                "AbstractMesh (AOT lowering) has none — split on the "
                "concrete mesh, or use split_by with your own host "
                "mapping") from None
        axis_pos = list(self.mesh.axis_names).index(self.axis_name)
        per_index = np.moveaxis(np.asarray(devs), axis_pos, 0)
        per_index = per_index.reshape(per_index.shape[0], -1)
        # an axis index's "host" is the set of processes its devices span
        # (a slice crossing hosts shares memory with no single host —
        # those indices group together only with identically-spanning ones)
        span = [tuple(sorted({d.process_index for d in row}))
                for row in per_index]
        palette = {s: c for c, s in enumerate(dict.fromkeys(span))}
        # ``key`` is accepted for MPI signature parity only: in one SPMD
        # call every rank necessarily passes the same constant, and a
        # uniform key cannot change split_all's (key, pos) ordering
        del key
        return self.split_by(lambda i: palette[span[i]])

    def split_by_rank(self, color_fn, key_fn=None) -> "TpuCommunicator":
        """``split`` with color/key as pure functions of the *group-local*
        rank — the host evaluates them for every rank (the portable spelling
        shared with the process backends; Communicator.split_by_rank)."""
        n = self._axis_size
        local = [int(self._rank_table[w]) for w in range(n)]
        return self.split_all(
            [color_fn(r) for r in local],
            [key_fn(r) for r in local] if key_fn else None,
        )

    def create(self, group) -> "TpuCommunicator":
        """MPI_Comm_create_group, SPMD shape: every device must keep running
        the program, so non-members can't get None — instead the complement
        ranks form sibling communicator(s) of the same size (required by the
        uniform-partition rule) and every rank gets its own group's handle.
        Equal-size complement is the SPMD-expressible subset of the MPI
        semantics; anything else raises."""
        self._check_group(group)
        ranks = list(group.ranks)
        others = [r for r in range(self.size) if r not in set(ranks)]
        if others and len(others) % len(ranks) != 0:
            raise SpmdSemanticsError(
                f"create(group) needs the non-member count ({len(others)}) to "
                f"split into groups of the member size ({len(ranks)}): every "
                f"device executes the SPMD program, so the complement must "
                f"form equal-sized sibling communicators")

        def color(r: int) -> int:
            return 0 if r in set(ranks) else 1 + others.index(r) // len(ranks)

        def key(r: int) -> int:
            return ranks.index(r) if r in set(ranks) else others.index(r) % len(ranks)

        return self.split_by_rank(color, key)

    def dup(self) -> "TpuCommunicator":
        # SPMD collectives carry no message-matching state, so a dup is a
        # fresh handle over the same groups.
        return self._copy_attrs_to(
            TpuCommunicator(self.axis_name, self.mesh, self._groups,
                            pallas_interpret=self._pallas_interpret))

    def free(self) -> None:
        pass
