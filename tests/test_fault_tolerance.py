"""ULFM fault-tolerance semantics (ISSUE 3 tentpole): bounded-time
detection, revoke propagation, shrink/agree recovery — tier-1, in
process, over the local transport with FaultyTransport kill injection;
plus the end-to-end subprocess kill story on BOTH process transports
(socket and shm), asserting a detection bound DERIVED from the
fault_detect_timeout_s cvar plus a load-scaled margin (the 120s shm
stall constant used to make any bound impossible; the old hard 15s was
the suite's one load flake on this oversubscribed 2-core box)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import api, checkpoint, mpit
from mpi_tpu.errors import (ERRORS_RETURN, ErrorCode, MPI_ERR_PROC_FAILED,
                            MPI_ERR_REVOKED, ProcFailedError, RevokedError)
from mpi_tpu.transport.faulty import FaultyTransport, KilledRankError
from mpi_tpu.transport.local import KILLED, run_local

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# In-process detection knobs: tight bound, fast heartbeat.  The assert
# ceilings below are several multiples of the bound — generous enough
# for a loaded CI box, far below the 120s shm stall constant.
DETECT_S = 1.0


@pytest.fixture(autouse=True)
def _fast_detection():
    old = {k: mpit.cvar_read(k) for k in ("fault_detect_timeout_s",
                                          "fault_heartbeat_interval_s")}
    mpit.cvar_write("fault_detect_timeout_s", DETECT_S)
    mpit.cvar_write("fault_heartbeat_interval_s", 0.05)
    yield
    for k, v in old.items():
        mpit.cvar_write(k, v)


def _kill_rank(rank, **kw):
    """transport_wrapper injecting death on exactly one rank."""
    return lambda inner: (FaultyTransport(inner, **kw)
                          if inner.world_rank == rank else inner)


# -- detection ---------------------------------------------------------------


def test_detection_bound_converts_blocked_collective(monkeypatch=None):
    """Rank 1 dies mid-allreduce; BOTH survivors' blocked collective
    waits convert the detector hit into ProcFailedError naming the dead
    rank and the collective, within a small multiple of the bound."""
    def fn(comm):
        if comm.rank == 1:
            comm.allreduce(np.ones(8), algorithm="ring")  # dies on send 2
            return "unreachable"
        t0 = time.monotonic()
        with pytest.raises(ProcFailedError) as ei:
            comm.allreduce(np.ones(8), algorithm="ring")
        took = time.monotonic() - t0
        assert took < 6 * DETECT_S
        assert ei.value.failed == (1,)
        assert ei.value.collective == "allreduce"
        return "diagnosed"

    res = run_local(fn, 3, transport_wrapper=_kill_rank(1, kill_after_n=2),
                    fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == "diagnosed"
    assert res[1] is KILLED


def test_detection_independent_of_recv_timeout():
    """The detector bound applies even with NO recv_timeout set — the
    survivor is not rescued by a timeout knob it never turned."""
    def fn(comm):
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        assert comm.recv_timeout is None
        t0 = time.monotonic()
        with pytest.raises(ProcFailedError):
            comm.recv(source=1, tag=0)
        assert time.monotonic() - t0 < 6 * DETECT_S
        return "ok"

    res = run_local(fn, 2, fault_tolerance=True, timeout=60)
    assert res[0] == "ok" and res[1] is KILLED


def test_segment_named_in_segmented_collective_failure():
    """A death mid-segmented-exchange names the collective AND the
    stalled pipeline segment (the _seg_exchange annotation)."""
    old = mpit.cvar_read("collective_segment_bytes")
    mpit.cvar_write("collective_segment_bytes", 64)  # force multi-segment

    def fn(comm):
        if comm.rank == 1:
            comm.allreduce(np.ones(256), algorithm="ring")
            return "unreachable"
        with pytest.raises(ProcFailedError) as ei:
            comm.allreduce(np.ones(256), algorithm="ring")
        assert ei.value.collective == "allreduce"
        assert ei.value.segment is not None
        return "ok"

    try:
        res = run_local(fn, 2, transport_wrapper=_kill_rank(1, kill_after_n=3),
                        fault_tolerance=True, timeout=60)
    finally:
        mpit.cvar_write("collective_segment_bytes", old)
    assert res[0] == "ok"


# -- revocation --------------------------------------------------------------


def test_revoke_unblocks_rank_not_talking_to_corpse():
    """Rank 2 is blocked on LIVE rank 0 when rank 1 dies: only the
    revocation can unblock it — and does, within the poll slice."""
    def fn(comm):
        if comm.rank == 1:
            comm.send(b"x", 0, tag=3)  # crash_on_send_to=0: dies first
            return "unreachable"
        if comm.rank == 2:
            with pytest.raises(RevokedError):
                comm.recv(source=0, tag=7)  # rank 0 never sends this
            # entering ANY further op on the revoked comm raises too
            with pytest.raises(RevokedError):
                comm.barrier()
            return "revoked"
        with pytest.raises(ProcFailedError):
            comm.recv(source=1, tag=3)
        comm.revoke()
        assert comm.revoked
        return "detected"

    res = run_local(fn, 3, transport_wrapper=_kill_rank(1, crash_on_send_to=0),
                    fault_tolerance=True, timeout=60)
    assert res[0] == "detected"
    assert res[2] == "revoked"


def test_revoke_does_not_leak_across_dup():
    """Revocation is per-communicator: a dup'd sibling keeps working."""
    def fn(comm):
        child = comm.dup()
        comm.barrier()
        if comm.rank == 0:
            comm.revoke()
        else:
            with pytest.raises(RevokedError):
                # blocked on the revoked parent until the notice lands
                comm.recv(source=0, tag=1)
        # the sibling context is untouched
        assert float(child.allreduce(1.0)) == float(comm.size)
        return "ok"

    assert run_local(fn, 2, fault_tolerance=True, timeout=60) == ["ok"] * 2


# -- shrink / agree ----------------------------------------------------------


def test_shrink_agreement_and_post_shrink_collectives():
    """Survivors of a death agree on the failed set, and the shrunk
    communicator runs the full collective family correctly; the
    detection/shrink pvars count."""
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        # wait until the detector has flagged rank 1 (bounded)
        t0 = time.monotonic()
        while comm.get_failed() != [1]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        new = comm.shrink()
        assert new.size == 2 and new.rank == (0 if comm.rank == 0 else 1)
        out = new.allreduce(np.full(4, new.rank + 1.0))
        np.testing.assert_allclose(out, np.full(4, 3.0))
        assert [int(x) for x in new.allgather(new.rank)] == [0, 1]
        new.barrier()
        return "ok"

    res = run_local(fn, 3, fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == "ok"
    # 2 survivors each detect the death once and complete one shrink
    assert ses.read("proc_failures_detected") == 2
    assert ses.read("shrinks_completed") == 2


def test_agree_raises_until_failures_acked():
    """MPIX_Comm_agree semantics: completes despite the death, raises
    ProcFailedError (carrying the agreed value) while the failure is
    unacknowledged, returns normally after failure_ack; False anywhere
    makes the agreed AND False."""
    def fn(comm):
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        t0 = time.monotonic()
        while comm.get_failed() != [1]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        with pytest.raises(ProcFailedError) as ei:
            comm.agree(True)
        assert ei.value.value is True  # agreed AND, carried on the error
        assert comm.failure_ack() == [1]
        assert comm.agree(True) is True
        assert comm.agree(comm.rank != 0) is False
        return "ok"

    res = run_local(fn, 3, fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == "ok"


def test_checkpoint_save_agree_demo(tmp_path):
    """The checkpoint wiring: a death before commit makes every survivor
    raise and leaves NO manifest (the old/none checkpoint stays the
    committed one); after shrink, the survivors' save commits and
    loads."""
    path = str(tmp_path / "ckpt")

    def fn(comm):
        state = {"rank": comm.rank}
        # rank 1 dies on its first agreement send (after its state file
        # is written — the failure is in the COMMIT decision)
        raised = None
        try:
            checkpoint.save(path, state, comm, agree=True)
        except (ProcFailedError, KilledRankError) as e:
            raised = e
        assert raised is not None, "save committed despite the death"
        if comm.rank == 1:
            return "dead"  # the injected death, absorbed for this test
        assert not checkpoint.exists(path)  # commit correctly withheld
        new = comm.shrink()
        checkpoint.save(path, {"rank": new.rank}, new, agree=True)
        assert checkpoint.exists(path)
        assert checkpoint.load(path, new) == {"rank": new.rank}
        return "ok"

    res = run_local(fn, 3,
                    transport_wrapper=_kill_rank(1, crash_on_send_to=0),
                    fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == "ok"


def test_nonblocking_test_and_iprobe_see_the_detector():
    """The NONBLOCKING completion paths honor FT too: a test()/iprobe
    polling loop over a dead peer raises ProcFailedError within the
    bound instead of spinning on (False, None) forever — but a message
    the peer sent BEFORE dying stays receivable."""
    def fn(comm):
        if comm.rank == 1:
            comm.send(b"last words", 0, tag=5)
            raise KilledRankError("dead after one send")
        t0 = time.monotonic()
        while comm.get_failed() != [1]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        # the pre-death message completes normally
        req = comm.irecv(source=1, tag=5)
        done, got = req.test()
        assert done and got == b"last words"
        # an empty poll on the corpse raises, boundedly
        with pytest.raises(ProcFailedError):
            comm.irecv(source=1, tag=6).test()
        with pytest.raises(ProcFailedError):
            comm.iprobe(source=1, tag=6)
        return "ok"

    res = run_local(fn, 2, fault_tolerance=True, timeout=60)
    assert res[0] == "ok"


def test_two_shrinks_get_distinct_contexts():
    """Two successive shrinks with the SAME failed set must not produce
    colliding message contexts (the Mailbox matches by ctx alone)."""
    def fn(comm):
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        t0 = time.monotonic()
        while comm.get_failed() != [1]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        a = comm.shrink()
        b = comm.shrink()
        assert a._ctx != b._ctx
        # both are independently usable collectives
        assert float(a.allreduce(1.0)) == 2.0
        assert float(b.allreduce(1.0)) == 2.0
        return "ok"

    res = run_local(fn, 3, fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == "ok"


def test_checkpoint_agree_refuses_commit_even_after_ack(tmp_path):
    """failure_ack re-arms ANY_SOURCE receives — it must NOT re-arm
    checkpoint commits: a full-world save with a member's state missing
    can never swing the manifest (it would sweep the last good
    generation)."""
    path = str(tmp_path / "ckpt")

    def fn(comm):
        if comm.rank == 1:
            raise KilledRankError("dead on arrival")
        t0 = time.monotonic()
        while comm.get_failed() != [1]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        comm.failure_ack()
        with pytest.raises(ProcFailedError):
            checkpoint.save(path, {"r": comm.rank}, comm, agree=True)
        assert not checkpoint.exists(path)
        return "ok"

    res = run_local(fn, 3, fault_tolerance=True, timeout=60)
    assert res[0] == res[2] == "ok"


# -- ERRORS_RETURN across the collective algorithm gates ---------------------

_GATES = [
    ("bcast", lambda c: api.MPI_Bcast(np.ones(4), root=0, comm=c)),
    ("reduce", lambda c: api.MPI_Reduce(np.ones(4), root=0, comm=c)),
    ("allreduce-ring", lambda c: api.MPI_Allreduce(
        np.ones(4), algorithm="ring", comm=c)),
    ("allreduce-halving", lambda c: api.MPI_Allreduce(
        np.ones(4), algorithm="recursive_halving", comm=c)),
    ("allreduce-rabenseifner", lambda c: api.MPI_Allreduce(
        np.ones(4), algorithm="rabenseifner", comm=c)),
    ("allreduce-reduce_bcast", lambda c: api.MPI_Allreduce(
        np.ones(4), algorithm="reduce_bcast", comm=c)),
    ("allgather-ring", lambda c: c.allgather(np.ones(4), algorithm="ring")),
    ("allgather-doubling", lambda c: c.allgather(np.ones(4),
                                                 algorithm="doubling")),
    ("alltoall", lambda c: api.MPI_Alltoall(
        [np.ones(2)] * 4, comm=c)),
    ("reduce_scatter", lambda c: api.MPI_Reduce_scatter(
        np.ones((4, 2)), comm=c)),
    ("gather", lambda c: api.MPI_Gather(np.ones(2), root=0, comm=c)),
    ("scatter", lambda c: api.MPI_Scatter(
        [np.ones(2)] * 4 if c.rank == 0 else None, root=0, comm=c)),
    ("scan", lambda c: api.MPI_Scan(np.ones(2), comm=c)),
    ("barrier", lambda c: api.MPI_Barrier(comm=c)),
]


@pytest.mark.parametrize("name,call", _GATES, ids=[g[0] for g in _GATES])
def test_errors_return_with_dead_member(name, call):
    """Every collective algorithm gate with a dead member under
    ERRORS_RETURN: no survivor hangs, every survivor gets either a
    normal completion (the schedule never touched the corpse — e.g. a
    bcast subtree that excludes it) or an ErrorCode carrying
    MPI_ERR_PROC_FAILED — never an uncaught exception.  At least one
    survivor must hit the error (the corpse is somebody's peer in every
    schedule here).

    The direct ``c.allgather(...)`` entries exercise the gates the flat
    API doesn't parameterize, routed through the same errhandler."""
    from mpi_tpu import errors as _errors

    def fn(comm):
        if comm.rank == 3:
            raise KilledRankError("dead on arrival")
        t0 = time.monotonic()
        while comm.get_failed() != [3]:
            assert time.monotonic() - t0 < 6 * DETECT_S
            time.sleep(0.02)
        comm.set_errhandler(ERRORS_RETURN)
        if name.startswith("allgather"):  # object API: route by hand
            try:
                got = call(comm)
            except Exception as exc:  # noqa: BLE001 - handler boundary
                got = _errors.invoke_handler(comm, exc)
        else:
            got = call(comm)
        if isinstance(got, ErrorCode):
            assert int(got) == MPI_ERR_PROC_FAILED, got
            return "error-code"
        return "completed"

    res = run_local(fn, 4, fault_tolerance=True, timeout=60)
    outcomes = [res[r] for r in (0, 1, 2)]
    assert set(outcomes) <= {"error-code", "completed"}
    assert "error-code" in outcomes, outcomes


# -- fault-injection pvars ---------------------------------------------------


def test_faulty_transport_counters_are_pvars():
    ses = mpit.session_create()
    ses.reset_all()

    def fn(comm):
        if comm.rank == 0:
            for i in range(6):
                comm.send(i, 1, tag=1)
        else:
            comm.recv_timeout = 1.0
            got = []
            for _ in range(6):
                try:
                    got.append(comm.recv(source=0, tag=1))
                except Exception:  # noqa: BLE001 - dropped message
                    break
            return got

    run_local(fn, 2, transport_wrapper=lambda t: FaultyTransport(
        t, drop_every=3, duplicate_every=4))
    assert ses.read("faulty_dropped") >= 1
    assert ses.read("faulty_duplicated") >= 1


# -- end-to-end: subprocess kill on socket AND shm ---------------------------

_E2E_PROG = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit
from mpi_tpu.errors import ProcFailedError, RevokedError

mpit.cvar_write("fault_detect_timeout_s", 2.0)
mpit.cvar_write("fault_heartbeat_interval_s", 0.2)
comm = mpi_tpu.init()   # MPI_TPU_FT=1: heartbeat files + detector

# Detection-bound assertion derived from the configured detector, not a
# hard constant: the detector needs ~detect_timeout to notice plus one
# restarted window when its own thread was descheduled (the documented
# stall-forgiveness path), so 3x the cvar is the protocol bound; the
# additive margin covers scheduler delay on an oversubscribed box (3
# rank processes + the pytest driver on this 2-core host) — the load
# flake the old hard 15s kept tripping over.
_detect = float(mpit.cvar_read("fault_detect_timeout_s"))
BOUND = 3.0 * _detect + (25.0 if (os.cpu_count() or 1) < 4 else 8.0)

if comm.rank == 1:
    time.sleep(0.5)     # let the survivors block first
    os._exit(42)        # no cleanup, no goodbye

t0 = time.monotonic()
try:
    if comm.rank == 0:
        # blocked INSIDE the collective on the corpse
        comm.allreduce(np.ones(1 << 12, np.float32), algorithm="ring")
        sys.exit(7)     # impossibly completed
    else:
        # rank 2: NOT talking to the corpse — blocked on live rank 0;
        # only rank 0's revoke can (and must) unblock it
        comm.recv(source=0, tag=9)
        sys.exit(7)
except ProcFailedError as e:
    took = time.monotonic() - t0
    assert comm.rank == 0, f"unexpected ProcFailedError on {{comm.rank}}"
    assert 1 in e.failed, e.failed
    assert took < BOUND, f"detection took {{took:.1f}}s (> {{BOUND:.0f}}s bound)"
    assert mpit.pvar_read("proc_failures_detected") >= 1
    comm.revoke()
except RevokedError:
    took = time.monotonic() - t0
    assert comm.rank == 2, f"unexpected RevokedError on {{comm.rank}}"
    assert took < BOUND, f"revoke took {{took:.1f}}s (> {{BOUND:.0f}}s bound)"
    assert mpit.pvar_read("revokes_delivered") >= 1

new = comm.shrink()
assert mpit.pvar_read("shrinks_completed") >= 1
assert new.size == 2, new.size
out = new.allreduce(np.full(8, float(new.rank + 1), np.float32))
assert float(out[0]) == 3.0, out[0]
print(f"rank {{comm.rank}} recovered in {{time.monotonic() - t0:.1f}}s",
      flush=True)
sys.exit(0)
"""


@pytest.mark.parametrize("backend", ["socket", "shm"])
def test_kill_mid_allreduce_detect_revoke_shrink(tmp_path, backend):
    """The acceptance story end to end: rank 1 os._exit(42)s under a
    3-rank process world; rank 0 (blocked in the allreduce) surfaces
    MPI_ERR_PROC_FAILED and rank 2 (blocked on live rank 0)
    MPI_ERR_REVOKED, both inside the cvar-derived detection bound (3x
    fault_detect_timeout_s + load margin) — NOT via the 120s shm stall —
    then shrink() completes a correct allreduce among the survivors,
    with the detection/revoke/shrink pvars counted.  On socket AND shm."""
    if backend == "shm":
        from mpi_tpu.native import ensure_built

        try:
            ensure_built()
        except Exception as e:  # pragma: no cover - no toolchain
            pytest.skip(f"native shm ring unavailable: {e}")
    script = tmp_path / "e2e.py"
    script.write_text(_E2E_PROG.format(repo=REPO))
    rdv = tmp_path / "rdv"
    rdv.mkdir()
    procs = []
    for r in range(3):
        env = dict(os.environ)
        env.update({"MPI_TPU_RANK": str(r), "MPI_TPU_SIZE": "3",
                    "MPI_TPU_RDV": str(rdv), "MPI_TPU_BACKEND": backend,
                    "MPI_TPU_FT": "1", "JAX_PLATFORMS": "cpu"})
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = {}
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=90.0)
        outs[r] = (p.returncode, out, err)
    assert outs[1][0] == 42
    for r in (0, 2):
        code, out, err = outs[r]
        assert code == 0, f"rank {r}: {err[-900:]}"
        assert "recovered in" in out, out


_RING_FULL_PROG = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit
from mpi_tpu.errors import ProcFailedError

mpit.cvar_write("fault_detect_timeout_s", 2.0)
mpit.cvar_write("fault_heartbeat_interval_s", 0.2)
comm = mpi_tpu.init()
if comm.rank == 1:
    # die with endpoints up but NOTHING ever draining: no recv, and the
    # helper thread dies with the process — the sender's ring stays full
    os._exit(9)
payload = np.ones(1 << 20, np.float32)  # 4MB frames = one whole ring
_detect = float(mpit.cvar_read("fault_detect_timeout_s"))
BOUND = 3.0 * _detect + (25.0 if (os.cpu_count() or 1) < 4 else 8.0)
t0 = time.monotonic()
try:
    # the corpse's helper may drain a frame or two in its last instants;
    # 50 x 4MB into a 4MB ring wedges mid-write regardless
    for i in range(50):
        comm.send(payload, 1, tag=5)
    sys.exit(7)  # impossibly enqueued 200MB into a ring nobody drains
except ProcFailedError as e:
    took = time.monotonic() - t0
    assert 1 in e.failed, e.failed
    assert took < BOUND, f"sender stuck {{took:.1f}}s (> {{BOUND:.0f}}s)"
    assert "dead" in str(e), e
print(f"sender unstuck in {{time.monotonic() - t0:.1f}}s", flush=True)
sys.exit(0)
"""


def test_shm_sender_unstuck_from_dead_consumers_full_ring(tmp_path):
    """FT residual (a), converted: a sender mid-write into a DEAD
    consumer's full shm ring used to spin out the full 120s
    shm_write_timeout_s stall constant (the detector could fire but
    nothing consulted it between native write slices).  Now the
    ring-full wait path checks the FT suspect set every slice, so the
    send surfaces ProcFailedError within the detection bound."""
    from mpi_tpu.native import ensure_built

    try:
        ensure_built()
    except Exception as e:  # pragma: no cover - no toolchain
        pytest.skip(f"native shm ring unavailable: {e}")
    script = tmp_path / "ringfull.py"
    script.write_text(_RING_FULL_PROG.format(repo=REPO))
    rdv = tmp_path / "rdv"
    rdv.mkdir()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({"MPI_TPU_RANK": str(r), "MPI_TPU_SIZE": "2",
                    "MPI_TPU_RDV": str(rdv), "MPI_TPU_BACKEND": "shm",
                    "MPI_TPU_FT": "1", "JAX_PLATFORMS": "cpu"})
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = {}
    for r, p in enumerate(procs):
        out, err = p.communicate(timeout=90.0)
        outs[r] = (p.returncode, out, err)
    assert outs[1][0] == 9
    code, out, err = outs[0]
    assert code == 0, f"sender: {err[-900:]}"
    assert "sender unstuck" in out, out


_FREEZE_PROG = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit
from mpi_tpu.errors import ProcFailedError, RevokedError

mpit.cvar_write("fault_detect_timeout_s", 2.5)
mpit.cvar_write("fault_heartbeat_interval_s", 0.2)
comm = mpi_tpu.init()
mode = os.environ["MPI_TPU_FREEZE_MODE"]   # "within" | "past"
_detect = float(mpit.cvar_read("fault_detect_timeout_s"))
BOUND = 3.0 * _detect + (25.0 if (os.cpu_count() or 1) < 4 else 8.0)
comm.barrier()
# tell the driver this rank is inside the collective loop era
open(os.path.join(os.environ["MPI_TPU_RDV"],
                  f"frozen_ready.{{comm.rank}}"), "w").close()
t0 = time.monotonic()
try:
    # small payloads on purpose: a frozen peer must stall SLICED
    # receives (FT-checked), not fill kernel socket buffers and wedge
    # an unsliceable sendall
    for i in range(70):
        out = comm.allreduce(np.full(512, 1.0), algorithm="ring")
        assert float(out[0]) == float(comm.size), out[0]
        time.sleep(0.05)
    outcome = "ok"
except ProcFailedError as e:
    took = time.monotonic() - t0
    assert mode == "past", f"false shrink of a resumed-in-bound rank: {{e}}"
    assert 1 in e.failed, e.failed
    assert took < BOUND, f"freeze diagnosis took {{took:.1f}}s (> {{BOUND}}s)"
    outcome = "diagnosed"
    try:
        comm.revoke()   # unblock the survivor not facing the corpse
    except Exception:
        pass
except RevokedError:
    assert mode == "past", "false revoke in a resumed-in-bound world"
    outcome = "diagnosed"
print(f"OUTCOME rank={{comm.rank}} {{outcome}}", flush=True)
sys.exit(0)
"""


def _spawn_freeze_world(tmp_path, mode):
    script = tmp_path / "freeze.py"
    script.write_text(_FREEZE_PROG.format(repo=REPO))
    rdv = tmp_path / "rdv"
    rdv.mkdir()
    procs = []
    for r in range(3):
        env = dict(os.environ)
        env.update({"MPI_TPU_RANK": str(r), "MPI_TPU_SIZE": "3",
                    "MPI_TPU_RDV": str(rdv), "MPI_TPU_BACKEND": "socket",
                    "MPI_TPU_FT": "1", "JAX_PLATFORMS": "cpu",
                    "MPI_TPU_FREEZE_MODE": mode})
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        if all((rdv / f"frozen_ready.{r}").exists() for r in range(3)):
            break
        if any(p.poll() is not None for p in procs):
            break  # a rank died during startup: fall through to asserts
        time.sleep(0.02)
    return procs


def test_freeze_within_bound_not_falsely_shrunk(tmp_path):
    """satellite (ISSUE 10): SIGSTOP a rank for LESS than the detection
    bound, then SIGCONT — the detector's staleness window must tolerate
    the pause (and its own-stall restart must keep the resumed rank
    from counter-accusing the survivors): NOBODY raises, every rank
    finishes the collective stream clean."""
    import signal as _signal

    procs = _spawn_freeze_world(tmp_path, "within")
    try:
        os.kill(procs[1].pid, _signal.SIGSTOP)
        time.sleep(0.8)   # well inside the 2.5s detection bound
        os.kill(procs[1].pid, _signal.SIGCONT)
        outs = {}
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=90.0)
            outs[r] = (p.returncode, out, err)
        for r in range(3):
            code, out, err = outs[r]
            assert code == 0, f"rank {r}: {err[-900:]}"
            assert f"OUTCOME rank={r} ok" in out, (r, out, err[-400:])
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.kill(p.pid, _signal.SIGCONT)
                except OSError:
                    pass
                p.kill()


def test_freeze_past_bound_named_proc_failed(tmp_path):
    """satellite (ISSUE 10): a rank paused PAST the detection bound is
    indistinguishable from death and must be NAMED — the survivors
    surface ProcFailedError/RevokedError listing rank 1 within the
    derived bound (the link layer's healing must not convert a frozen
    peer into an unbounded retry)."""
    import signal as _signal

    procs = _spawn_freeze_world(tmp_path, "past")
    try:
        os.kill(procs[1].pid, _signal.SIGSTOP)   # ... and never CONT
        outs = {}
        for r in (0, 2):
            out, err = procs[r].communicate(timeout=90.0)
            outs[r] = (procs[r].returncode, out, err)
        for r in (0, 2):
            code, out, err = outs[r]
            assert code == 0, f"rank {r}: {err[-900:]}"
            assert f"OUTCOME rank={r} diagnosed" in out, (r, out,
                                                          err[-400:])
    finally:
        for p in procs:
            if p.poll() is None:
                try:
                    os.kill(p.pid, _signal.SIGCONT)
                except OSError:
                    pass
                p.kill()
                p.wait(5.0)


def test_launcher_exit_summary(tmp_path):
    """Any nonzero outcome prints the per-rank exit table (rank, code,
    signal) so failure-story logs are diagnosable without spelunking."""
    script = tmp_path / "crash0.py"
    script.write_text(
        f"import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        f"import mpi_tpu\n"
        f"comm = mpi_tpu.init()\n"
        f"if comm.rank == 0:\n"
        f"    os._exit(3)\n"
        f"comm.recv(source=0, tag=1)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "mpi_tpu.launcher", "-n", "2", str(script)],
        capture_output=True, text=True, cwd=REPO, timeout=120.0,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 3
    assert "per-rank exit summary" in proc.stderr, proc.stderr[-900:]
    assert "rank 0: exit code 3" in proc.stderr
    assert "rank 1:" in proc.stderr
