"""One-sided RMA (Window / put / get / accumulate / fence) on both backends.

Contract [S]: MPI-2 active-target RMA (mpi_tpu/window.py module docstring
for the deterministic refinements).  Parity: the same portable program must
produce identical windows on the process backends and the SPMD backend.
"""

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import ops
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import SpmdSemanticsError, run_spmd

P = 4


# -- portable programs (run on every backend) ------------------------------


def ring_put_prog(comm):
    """Each rank puts its rank-stamped vector into its right neighbor."""
    win = comm.win_create(np.zeros(3, np.float32))
    data = np.ones(3, np.float32) * (comm.rank + 1)  # rank-varying on TPU
    pairs = [(r, (r + 1) % P) for r in range(P)]
    win.put(data, pairs)
    win.fence()
    return win.local


def accumulate_prog(comm):
    """All ranks accumulate into rank pattern; two calls stack in issue order."""
    win = comm.win_create(np.ones(2, np.float32))
    mine = np.ones(2, np.float32) * comm.rank
    pairs = [(r, (r + 1) % P) for r in range(P)]
    win.accumulate(mine, pairs, op=ops.SUM)
    win.accumulate(mine, pairs, op=ops.SUM)
    win.fence()
    return win.local


def get_after_put_prog(comm):
    """A get in the same epoch observes the epoch's puts (the documented
    refinement)."""
    win = comm.win_create(np.zeros((), np.float32))
    val = np.float32(10.0) * comm.rank
    put_pairs = [(r, (r + 1) % P) for r in range(P)]
    get_pairs = [((r + 1) % P, r) for r in range(P)]  # read it back
    win.put(val, put_pairs)
    fut = win.get(get_pairs, fill=-1.0)
    win.fence()
    return fut.value


def multi_epoch_prog(comm):
    """Fences separate epochs; window state persists across them."""
    win = comm.win_create(np.zeros(2, np.float32))
    one = comm.localize(np.ones(2, np.float32))
    all_self = [(r, r) for r in range(P)]
    win.accumulate(one, all_self)
    win.fence()
    win.accumulate(one, all_self)
    win.fence()
    return win.local


def loc_prog(comm):
    """Sub-window addressing with a static loc."""
    win = comm.win_create(np.zeros(4, np.float32))
    v = np.ones(2, np.float32) * (comm.rank + 1)
    pairs = [(r, (r + 1) % P) for r in range(P)]
    win.put(v, pairs, loc=np.s_[1:3])
    win.fence()
    return win.local


RING_PUT_EXPECT = np.stack(
    [np.full(3, float((r - 1) % P) + 1.0, np.float32) for r in range(P)])


@pytest.mark.parametrize("prog,expect", [
    (ring_put_prog, RING_PUT_EXPECT),
    (accumulate_prog, np.stack(
        [1.0 + 2.0 * float((r - 1) % P) * np.ones(2, np.float32)
         for r in range(P)])),
    (get_after_put_prog, np.array(
        [float(r) * 10.0 for r in range(P)], np.float32)),
    (multi_epoch_prog, np.full((P, 2), 2.0, np.float32)),
    (loc_prog, np.stack(
        [np.array([0, (r - 1) % P + 1, (r - 1) % P + 1, 0], np.float32)
         for r in range(P)])),
])
def test_rma_parity_local_vs_spmd(prog, expect):
    got_local = np.stack([np.asarray(x) for x in run_local(prog, P)])
    got_spmd = np.stack([np.asarray(x) for x in run_spmd(prog, nranks=P)])
    np.testing.assert_allclose(got_local, np.asarray(expect), rtol=0, atol=0)
    np.testing.assert_allclose(got_spmd, np.asarray(expect), rtol=0, atol=0)


# -- process-backend-only behaviors ----------------------------------------


def test_rma_dynamic_int_target_local():
    """Classic rank-dynamic MPI RMA (int target) on the process backend."""

    def prog(comm):
        win = comm.win_create(np.zeros(1, np.float64))
        if comm.rank != 0:
            win.accumulate(np.array([float(comm.rank)]), 0)  # all into rank 0
        win.fence()
        return win.local[0]

    res = run_local(prog, P)
    assert res[0] == sum(range(1, P))
    assert all(res[r] == 0.0 for r in range(1, P))


def test_rma_dynamic_get_local():
    def prog(comm):
        win = comm.win_create(np.array([comm.rank * 2.0]))
        fut = win.get((comm.rank + 1) % comm.size)  # read right neighbor
        win.fence()
        return fut.value[0]

    res = run_local(prog, P)
    assert res == [((r + 1) % P) * 2.0 for r in range(P)]


def test_get_future_before_fence_raises():
    def prog(comm):
        win = comm.win_create(np.zeros(1))
        fut = win.get((comm.rank + 1) % comm.size)
        with pytest.raises(RuntimeError, match="closing fence"):
            _ = fut.value
        win.fence()
        return fut.value is not None

    assert all(run_local(prog, 2))


def test_freed_window_rejected():
    def prog(comm):
        win = comm.win_create(np.zeros(1))
        win.fence()
        win.free()
        with pytest.raises(RuntimeError, match="freed"):
            win.fence()
        return True

    assert all(run_local(prog, 2))


# -- SPMD-only diagnostics --------------------------------------------------


def test_spmd_rejects_dynamic_int_target():
    def prog(comm):
        win = comm.win_create(np.zeros(1, np.float32))
        try:
            win.put(np.ones(1, np.float32), 0)
        except SpmdSemanticsError:
            return comm.rank * 0 + 1
        return comm.rank * 0

    assert np.all(np.asarray(run_spmd(prog, nranks=P)) == 1)


def test_spmd_rma_inside_jit_compiles_once():
    """The whole epoch lowers into one jitted program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as Pspec

    from mpi_tpu.tpu import TpuCommunicator, default_mesh

    mesh = default_mesh(P)
    comm = TpuCommunicator("world", mesh)

    def step(x):
        win = comm.win_create(x)
        win.accumulate(x, [(r, (r + 1) % P) for r in range(P)])
        win.fence()
        return win.local

    f = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=Pspec("world"),
                              out_specs=Pspec("world")))
    x = jnp.arange(P * 2, dtype=jnp.float32).reshape(P, 2)
    out = np.asarray(f(x))
    expect = x + np.roll(np.asarray(x), 1, axis=0)
    np.testing.assert_allclose(out, expect)


def test_two_windows_interleaved_epochs_race():
    """Regression: a fast rank's next fence (second window, same epoch
    number) must not be consumed by a slow peer's current fence — phase-2
    receives are source-specific, not any-source."""
    import time

    def prog(comm):
        win1 = comm.win_create(np.zeros(2, np.float64))
        win2 = comm.win_create(np.zeros(2, np.float64))
        Pn = comm.size
        ring = [(r, (r + 1) % Pn) for r in range(Pn)]
        win1.put(np.full(2, comm.rank + 1.0), ring)
        win1.fence()
        if comm.rank == 1:
            time.sleep(0.05)  # skew: rank 1 lags between the two fences
        win2.put(np.full(2, comm.rank + 10.0), ring)
        win2.fence()
        return float(win1.local[0]), float(win2.local[0])

    res = run_local(prog, P)
    for r in range(P):
        assert res[r] == ((r - 1) % P + 1.0, (r - 1) % P + 10.0), (r, res[r])
