"""Seeded bug: divergent collective behind a rank-variable guard —
invisible to a literal-only ``comm.rank == 0`` pattern match."""


def main(comm, x):
    r = comm.rank
    if r == 0:
        comm.allreduce(x)
