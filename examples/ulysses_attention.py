"""Ulysses-style (DeepSpeed-Ulysses) sequence parallelism via all-to-all.

The second long-context strategy the framework's primitives support
(SURVEY.md §2 strategy table: "MPI_Alltoall (the Ulysses primitive) IS in
scope"): ranks start sequence-sharded with all heads; one all-to-all
re-shards to head-sharded with the full sequence; attention runs locally
per head (exact, no online-softmax needed); a second all-to-all restores
sequence sharding.  Communication is 2 all-to-alls per attention call
instead of P-1 ring hops — the better trade when heads >= ranks and the
interconnect favors all-to-all (ICI does).

    python examples/ulysses_attention.py --backend tpu -n 8
"""

import argparse
import math
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np


def _seq_to_heads(comm, x):
    """[s_local, H, d] → [S, H/P, d] via one all-to-all."""
    s, H, d = x.shape
    P = comm.size
    blocks = x.reshape(s, P, H // P, d).transpose(1, 0, 2, 3)  # [P, s, H/P, d]
    gathered = comm.alltoall(blocks)                           # [P, s, H/P, d]
    return jnp.asarray(gathered).reshape(P * s, H // P, d)


def _heads_to_seq(comm, x, s_local):
    """[S, H/P, d] → [s_local, H, d] via the inverse all-to-all."""
    S, Hp, d = x.shape
    P = comm.size
    blocks = x.reshape(P, s_local, Hp, d)                      # [P, s, H/P, d]
    scattered = comm.alltoall(blocks)                          # [P, s, H/P, d]
    return jnp.asarray(scattered).transpose(1, 0, 2, 3).reshape(s_local, P * Hp, d)


def ulysses_attention(comm, q, k, v):
    """Exact multi-head attention, sequence-sharded in and out.

    q, k, v: [s_local, H, d] with H divisible by comm.size."""
    s_local, H, d = q.shape
    if H % comm.size:
        raise ValueError(f"heads ({H}) must be divisible by ranks ({comm.size})")
    qh, kh, vh = (_seq_to_heads(comm, t) for t in (q, k, v))   # [S, H/P, d]
    scores = jnp.einsum("shd,thd->hst", qh, kh) / math.sqrt(d)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hst,thd->shd", probs, vh)                # [S, H/P, d]
    return _heads_to_seq(comm, out, s_local)


def ulysses_program(comm, seq_per_rank: int = 32, heads: int = 8, d: int = 16):
    key = jax.random.fold_in(jax.random.PRNGKey(11), comm.rank)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (seq_per_rank, heads, d)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    return ulysses_attention(comm, q, k, v), q, k, v


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None, choices=[None, "socket", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--seq-per-rank", type=int, default=32)
    ap.add_argument("--heads", type=int, default=8)
    args = ap.parse_args()

    out = mpi_tpu.run(ulysses_program, backend=args.backend, nranks=args.nranks,
                      seq_per_rank=args.seq_per_rank, heads=args.heads)
    first = out[0] if isinstance(out, list) else out
    o = np.asarray(jax.device_get(first[0] if isinstance(first, tuple) else first))
    print(f"ulysses attention OK: local {o.shape}, |out| = {np.abs(o).mean():.4f}")


if __name__ == "__main__":
    main()
