"""Topology-aware tuned dispatch (ISSUE 9 — mpi_tpu/tuning + the
three-level hierarchy in mpi_tpu/topology.py).

Contracts:

* table load/validate — malformed, stale-version, unknown-algorithm and
  bad-band tables raise TuningTableError naming the offence (the
  ``tools/tune.py --check`` CI gate);
* trust — a trusted row always beats an untrusted row for the same
  cell; untrusted rows serve when nothing trusted matches;
* fingerprint — a table measured on another machine loads but never
  serves (every auto decision falls back to the seed constants);
* mechanical dispatch — with a pinned table ``algorithm="auto"``
  resolves to the row's entry (observable in the wire schedule: ring
  sends 2(P-1) messages per rank where recursive halving sends log2 P)
  and ``tuned_table_hits`` counts it; with no table behavior is the
  seed constants and ``tuned_table_fallbacks`` counts it;
* arena gates — "sm_allreduce"/"sm_reduce" rows steer the arena's
  flat-vs-chunked and arena-vs-tree splits; an alltoall "pairwise" row
  declines INSIDE the arena negotiation (group-coherent under band
  skew);
* three-level hierarchy — NUMA → node → DCN-leaders parity with
  injected keys, each level's auto call consulting the resolver.
"""

import json
import os
import socket as _socket
import subprocess
import sys

import numpy as np
import pytest

from mpi_tpu import coll_sm, mpit, topology, tuning
from mpi_tpu.transport.local import run_local
from tests.test_shm_backend import run_shm_world

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _doc(rows, hostname=None, cpu_count=None, version=tuning.VERSION,
         fmt=tuning.FORMAT):
    return {
        "format": fmt,
        "version": version,
        "fingerprint": {
            "hostname": _socket.gethostname() if hostname is None
            else hostname,
            "cpu_count": (os.cpu_count() or 1) if cpu_count is None
            else cpu_count,
            "transports": ["local", "shm"],
        },
        "rows": rows,
    }


def _row(transport="local", nranks=2, collective="allreduce", lo=0,
         hi=None, algorithm="ring", trusted=True, **extra):
    d = {"transport": transport, "nranks": nranks,
         "collective": collective, "lo_bytes": lo, "hi_bytes": hi,
         "algorithm": algorithm, "trusted": trusted}
    d.update(extra)
    return d


@pytest.fixture()
def table(tmp_path):
    """Write a doc, activate it via the cvar, deactivate afterwards."""
    paths = []

    def activate(doc):
        p = tmp_path / f"table{len(paths)}.json"
        p.write_text(json.dumps(doc))
        paths.append(p)
        mpit.cvar_write("tuning_table_path", str(p))
        return str(p)

    try:
        yield activate
    finally:
        mpit.cvar_write("tuning_table_path", "")


# -- format / validation -----------------------------------------------------


def test_load_validate_and_band_match(tmp_path):
    doc = _doc([
        _row(lo=0, hi=1024, algorithm="recursive_halving"),
        _row(lo=1024, hi=None, algorithm="ring"),
    ])
    p = tmp_path / "t.json"
    p.write_text(json.dumps(doc))
    tab = tuning.TuningTable.load(str(p))
    assert tab.matches_machine()
    assert tab.match("local", 2, "allreduce", 16).algorithm == \
        "recursive_halving"
    assert tab.match("local", 2, "allreduce", 1024).algorithm == "ring"
    assert tab.match("local", 2, "allreduce", 1 << 30).algorithm == "ring"
    # no row for other transports / sizes / collectives
    assert tab.match("shm", 2, "allreduce", 16) is None
    assert tab.match("local", 3, "allreduce", 16) is None
    assert tab.match("local", 2, "alltoall", 16) is None


@pytest.mark.parametrize("mutate, msg", [
    (lambda d: d.update(version=99), "version"),
    (lambda d: d.update(format="nope"), "not a tuning table"),
    (lambda d: d.update(fingerprint={"hostname": 3}), "fingerprint"),
    (lambda d: d.update(rows="x"), "rows must be a list"),
    (lambda d: d["rows"].append(_row(collective="frobnicate")),
     "unknown collective"),
    (lambda d: d["rows"].append(_row(algorithm="quantum")),
     "unknown allreduce algorithm"),
    (lambda d: d["rows"].append(_row(lo=-1)), "lo_bytes"),
    (lambda d: d["rows"].append(_row(lo=64, hi=64)), "hi_bytes"),
    (lambda d: d["rows"].append(_row(nranks=1)), "nranks"),
    (lambda d: d["rows"].append(
        _row(nranks=3, algorithm="recursive_halving")), "power-of-two"),
    (lambda d: d["rows"].append(_row(trusted="yes")), "trusted"),
])
def test_reject_malformed(tmp_path, mutate, msg):
    doc = _doc([_row()])
    mutate(doc)
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(doc))
    with pytest.raises(tuning.TuningTableError, match=msg):
        tuning.TuningTable.load(str(p))
    # the strict cvar writer surfaces the same error and keeps the
    # previous (empty) configuration
    with pytest.raises(tuning.TuningTableError):
        mpit.cvar_write("tuning_table_path", str(p))
    assert mpit.cvar_read("tuning_table_path") == ""


def test_reject_non_json(tmp_path):
    p = tmp_path / "nope.json"
    p.write_text("{not json")
    with pytest.raises(tuning.TuningTableError, match="JSON"):
        tuning.TuningTable.load(str(p))


def test_trusted_beats_untrusted(tmp_path):
    doc = _doc([
        _row(algorithm="ring", trusted=False),
        _row(algorithm="rabenseifner", trusted=True),
    ])
    p = tmp_path / "t.json"
    p.write_text(json.dumps(doc))
    tab = tuning.TuningTable.load(str(p))
    # trusted wins regardless of file order...
    assert tab.match("local", 2, "allreduce", 16).algorithm == \
        "rabenseifner"
    # ...and untrusted serves where nothing trusted matches
    doc2 = _doc([_row(algorithm="ring", trusted=False)])
    p.write_text(json.dumps(doc2))
    assert tuning.TuningTable.load(str(p)).match(
        "local", 2, "allreduce", 16).algorithm == "ring"


# -- resolver / auto integration ---------------------------------------------


def _allreduce_sends(nranks, payload, **run_kwargs):
    """msgs_sent of one P-rank allreduce world (the schedule
    fingerprint: ring = 2(P-1) sends per rank, halving = log2 P)."""
    before = mpit.pvar_read("msgs_sent")
    res = run_local(lambda c: c.allreduce(payload), nranks, **run_kwargs)
    for r in res:
        np.testing.assert_allclose(r, payload * nranks)
    return mpit.pvar_read("msgs_sent") - before


def test_auto_cites_pinned_row(table):
    """THE acceptance contract: with a pinned table the resolved
    algorithm equals the row's entry — observable in the wire schedule
    at P=4, where ring sends 2(P-1)=6 messages per rank and the seed's
    recursive halving sends 2·log2(P)=4 — tuned_table_hits counts it,
    and the decision is introspectable."""
    payload = np.ones(8, np.float32)  # 32B: seed picks halving at P=4
    seed_sends = _allreduce_sends(4, payload)
    assert seed_sends == 16  # halving: 4 sends per rank
    table(_doc([_row(nranks=4, algorithm="ring")]))
    h0 = mpit.pvar_read("tuned_table_hits")
    ring_sends = _allreduce_sends(4, payload)
    assert ring_sends == 24  # ring: 6 sends per rank
    assert mpit.pvar_read("tuned_table_hits") - h0 == 4  # one per rank
    last = tuning.last_decision()
    assert last["algorithm"] == "ring"
    assert last["source"] == "table:trusted"
    exp = tuning.explain("local", 4, "allreduce", payload.nbytes)
    assert exp["algorithm"] == "ring" and exp["row"]["trusted"] is True


def test_no_table_is_seed_constants_and_counted():
    mpit.cvar_write("tuning_table_path", "")
    f0 = mpit.pvar_read("tuned_table_fallbacks")
    assert _allreduce_sends(4, np.ones(8, np.float32)) == 16  # halving
    assert mpit.pvar_read("tuned_table_fallbacks") - f0 == 4
    # the no-table fast path records nothing; explain() still answers
    assert tuning.explain("local", 4, "allreduce", 32)["source"] == "seed"


def test_active_table_unmatched_row_records_seed(table):
    """With a table active but no matching row, the fallback IS
    recorded (source 'seed') — the introspectable half of the
    fallbacks counter."""
    table(_doc([_row(collective="alltoall", algorithm="pairwise")]))
    f0 = mpit.pvar_read("tuned_table_fallbacks")
    run_local(lambda c: c.allreduce(np.ones(8, np.float32)), 2)
    assert mpit.pvar_read("tuned_table_fallbacks") - f0 == 2
    last = tuning.last_decision()
    assert last["source"] == "seed" and last["collective"] == "allreduce"


def test_fingerprint_mismatch_falls_back_to_seed(table):
    table(_doc([_row(algorithm="ring")], hostname="definitely-not-here"))
    assert tuning.reason() is not None
    assert "fingerprint mismatch" in tuning.reason()
    h0 = mpit.pvar_read("tuned_table_hits")
    # seed halving: 2·log2(2) = 2 sends per rank
    assert _allreduce_sends(2, np.ones(8, np.float32)) == 4
    assert mpit.pvar_read("tuned_table_hits") == h0


def test_inapplicable_row_falls_back(table):
    """A row whose algorithm cannot run here (halving at P=3) is skipped
    — validation already rejects it keyed to nranks=3, so pin a P=3
    'sm' row against the arena-less local transport instead."""
    table(_doc([_row(nranks=3, algorithm="sm", collective="allreduce")]))
    h0 = mpit.pvar_read("tuned_table_hits")
    res = run_local(lambda c: c.allreduce(np.ones(4, np.float32)), 3)
    for r in res:
        np.testing.assert_allclose(r, np.full(4, 3.0))
    assert mpit.pvar_read("tuned_table_hits") == h0  # never served


def test_run_local_tuning_table_param(tmp_path):
    p = tmp_path / "t.json"
    p.write_text(json.dumps(_doc([_row(algorithm="ring")])))
    h0 = mpit.pvar_read("tuned_table_hits")
    run_local(lambda c: c.allreduce(np.ones(8, np.float32)), 2,
              tuning_table=str(p))
    assert mpit.pvar_read("tuned_table_hits") - h0 == 2
    # process state restored: the table no longer serves
    assert mpit.cvar_read("tuning_table_path") == ""


def test_env_var_activates_table(tmp_path):
    """MPI_TPU_TUNING_TABLE is read lazily once per process — assert in
    a fresh interpreter (the launcher's --tuning-table rides this)."""
    p = tmp_path / "t.json"
    p.write_text(json.dumps(_doc([_row(algorithm="ring")])))
    prog = (
        "import numpy as np\n"
        "from mpi_tpu.transport.local import run_local\n"
        "from mpi_tpu import mpit\n"
        "run_local(lambda c: c.allreduce(np.ones(8, np.float32)), 2)\n"
        "print('HITS', mpit.pvar_read('tuned_table_hits'))\n"
    )
    env = dict(os.environ, MPI_TPU_TUNING_TABLE=str(p),
               JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "HITS 2" in out.stdout, out.stdout


def test_pick_counts_exactly_one_per_consult(table):
    table(_doc([_row(algorithm="ring")]))
    h0 = mpit.pvar_read("tuned_table_hits")
    f0 = mpit.pvar_read("tuned_table_fallbacks")
    run_local(lambda c: c.allreduce(np.ones(8, np.float32)), 2)
    dh = mpit.pvar_read("tuned_table_hits") - h0
    df = mpit.pvar_read("tuned_table_fallbacks") - f0
    assert (dh, df) == (2, 0)


# -- arena gates (shm) -------------------------------------------------------


def test_sm_eager_gate_from_table(table):
    """An "sm_allreduce" row overrides the coll_sm_eager_bytes constant:
    a 1KB payload (seed: flat) folds via the CHUNKED path when the
    table says so — parity held, decision introspectable."""
    table(_doc([
        _row(transport="shm", collective="sm_allreduce",
             algorithm="chunked"),
        # keep auto routed into the arena for the outer decision
        _row(transport="shm", collective="allreduce", algorithm="sm"),
    ]))

    def prog(comm):
        return comm.allreduce(np.full(256, 1.0 + comm.rank), algorithm="sm")

    h0 = mpit.pvar_read("coll_sm_hits")
    for out in run_shm_world(prog, 2):
        np.testing.assert_allclose(out, np.full(256, 3.0))
    assert mpit.pvar_read("coll_sm_hits") > h0  # arena served it
    last = tuning.last_decision()
    assert last["collective"] == "sm_allreduce"
    assert last["algorithm"] == "chunked"


def test_sm_reduce_gate_from_table(table):
    """An "sm_reduce" -> "tree" row pushes an eager-size reduce off the
    arena onto the binomial tree (group-coherently: the arena declines
    via its meta round, counted in coll_sm_fallbacks)."""
    table(_doc([
        _row(transport="shm", collective="sm_reduce", algorithm="tree"),
    ]))

    def prog(comm):
        return comm.reduce(np.full(64, 1.0), algorithm="sm")

    f0 = mpit.pvar_read("coll_sm_fallbacks")
    res = run_shm_world(prog, 2)
    np.testing.assert_allclose(res[0], np.full(64, 2.0))
    assert res[1] is None
    assert mpit.pvar_read("coll_sm_fallbacks") > f0


def test_alltoall_pairwise_row_declines_inside_arena(table):
    """A tuned "pairwise" alltoall row must not skip the arena's group
    negotiation (band skew on ragged payloads could split the group):
    the rank enters with no payload, everyone lands on pairwise
    together — no arena hit, one negotiated fallback, full parity."""
    table(_doc([
        _row(transport="shm", collective="alltoall",
             algorithm="pairwise"),
    ]))

    def prog(comm):
        blocks = [np.full(16, float(comm.rank * 10 + d)) for d in range(2)]
        return comm.alltoall(blocks)

    h0 = mpit.pvar_read("coll_sm_hits")
    f0 = mpit.pvar_read("coll_sm_fallbacks")
    th0 = mpit.pvar_read("tuned_table_hits")
    res = run_shm_world(prog, 2)
    for r, out in enumerate(res):
        np.testing.assert_array_equal(
            np.asarray(out), np.stack([np.full(16, float(q * 10 + r))
                                       for q in range(2)]))
    assert mpit.pvar_read("tuned_table_hits") > th0
    assert mpit.pvar_read("coll_sm_hits") == h0, \
        "tuned pairwise row still rode the arena"
    assert mpit.pvar_read("coll_sm_fallbacks") > f0


def test_tune_arena_capacity_mirror():
    """tools/tune.py's sm size cap must track coll_sm's real slot
    arithmetic — a drift would make the sweep measure the wire fallback
    under the 'sm' label."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import tune
    finally:
        sys.path.pop(0)
    for p in (2, 3, 4, 8):
        slot = ((coll_sm._ARENA_BYTES - coll_sm._LINE * p) // p) \
            // coll_sm._LINE * coll_sm._LINE
        assert tune._arena_capacity(p) == slot - coll_sm._META_MAX


# -- three-level hierarchy ---------------------------------------------------


def test_three_level_parity_with_injected_keys():
    """NUMA -> node -> DCN-leaders on 8 local ranks (2 nodes x 2 NUMA
    x 2): allreduce/bcast/reduce/allgather/barrier parity."""
    def prog(comm):
        h = topology.HierarchicalComm(comm, node_key=lambda r: r // 4,
                                      numa_key=lambda r: (r // 2) % 2)
        x = np.arange(6.0) + comm.rank
        out = {"ar": h.allreduce(x),
               "bc": h.bcast(np.full(3, 9.0) if comm.rank == 5 else None,
                             root=5),
               "rd": h.reduce(x, root=3),
               "ag": h.allgather(np.full(2, float(comm.rank))),
               "sizes": (h.numa.size,
                         None if h.node_leaders is None
                         else h.node_leaders.size,
                         None if h.dcn_leaders is None
                         else h.dcn_leaders.size)}
        h.barrier()
        assert h.n_nodes == 2
        return out

    res = run_local(prog, 8)
    want = np.arange(6.0) * 8 + sum(range(8))
    for r, o in enumerate(res):
        np.testing.assert_allclose(o["ar"], want)
        np.testing.assert_array_equal(o["bc"], np.full(3, 9.0))
        if r == 3:
            np.testing.assert_allclose(o["rd"], want)
        else:
            assert o["rd"] is None
        np.testing.assert_array_equal(
            np.asarray(o["ag"]),
            np.stack([np.full(2, float(q)) for q in range(8)]))
        assert o["sizes"][0] == 2
    # NUMA leaders (0,2,4,6) sit in 2-member node tiers; node leaders
    # (0,4) in the 2-member DCN tier; everyone else in neither
    assert [o["sizes"][1] for o in res] == [2, None, 2, None,
                                            2, None, 2, None]
    assert [o["sizes"][2] for o in res] == [2, None, None, None,
                                            2, None, None, None]


def test_three_level_routes_dcn_level_through_resolver(table):
    """Each hierarchy level's auto call keys the resolver with its OWN
    communicator: pin a (local, P=2, allreduce) row and the DCN-leader
    tier's allreduce cites it (one hit per DCN member), while the
    NUMA/node tiers (reduce/bcast) key their own decisions."""
    table(_doc([_row(algorithm="ring")]))

    def prog(comm):
        h = topology.HierarchicalComm(comm, node_key=lambda r: r // 2,
                                      numa_key=lambda r: 0)
        return h.allreduce(np.ones(4, np.float32))

    h0 = mpit.pvar_read("tuned_table_hits")
    for out in run_local(prog, 4):
        np.testing.assert_allclose(out, np.full(4, 4.0))
    assert mpit.pvar_read("tuned_table_hits") - h0 == 2  # the 2 DCN leaders
    assert tuning.last_decision()["algorithm"] == "ring"


def test_two_level_hierarchy_unchanged():
    def prog(comm):
        h = topology.HierarchicalComm(comm, node_key=lambda r: r // 2)
        assert h.numa is None and h.dcn_leaders is None
        return h.allreduce(np.ones(4))

    for out in run_local(prog, 4):
        np.testing.assert_allclose(out, np.full(4, 4.0))


def test_multihost_node_key_single_process():
    """Without a multi-process jax runtime every rank lands on node 0 —
    the honest single-host truth the docstring promises."""
    def prog(comm):
        key = topology.multihost_node_key(comm)
        return [key(r) for r in range(comm.size)]

    assert run_local(prog, 3) == [[0, 0, 0]] * 3
