"""Schedule-generator unit tests (SURVEY.md §4 item 1): pure functions from
(rank, size) to message schedules, property-tested so that every payload is
delivered exactly once — the 'every message sent is received exactly once'
invariant."""

import numpy as np
import pytest
pytest.importorskip(
    "hypothesis",
    reason="property tests need hypothesis, absent from this environment")
from hypothesis import given, settings
from hypothesis import strategies as st

from mpi_tpu import checker, schedules

sizes = st.integers(min_value=1, max_value=16)
pow2_sizes = st.sampled_from([1, 2, 4, 8, 16])


@given(size=sizes, root=st.integers(0, 15))
@settings(max_examples=60, deadline=None)
def test_binomial_bcast_covers_all_ranks(size, root):
    root = root % size
    rounds = schedules.binomial_bcast_rounds(size, root)
    checker.validate_rounds(rounds, size)
    have = {root}
    for pairs in rounds:
        for s, d in pairs:
            assert s in have, "sender must already hold the value"
            assert d not in have, "receiver must not receive twice"
            have.add(d)
    assert have == set(range(size))
    assert len(rounds) == max(0, (size - 1)).bit_length()


@given(size=sizes, root=st.integers(0, 15))
@settings(max_examples=60, deadline=None)
def test_binomial_reduce_reaches_root(size, root):
    root = root % size
    rounds = schedules.binomial_reduce_rounds(size, root)
    checker.validate_rounds(rounds, size)
    # simulate: each rank holds a set of contributions; senders retire
    holding = {r: {r} for r in range(size)}
    for pairs in rounds:
        for s, d in pairs:
            holding[d] |= holding.pop(s)
    assert set(holding) == {root}
    assert holding[root] == set(range(size))


@given(size=sizes)
@settings(max_examples=40, deadline=None)
def test_ring_allreduce_chunk_bookkeeping(size):
    p = size
    # simulate the ring: chunks[r][i] = set of contributions to chunk i at rank r
    chunks = [[{r} for _ in range(p)] for r in range(p)]
    for step in range(p - 1):
        sent = {
            r: (schedules.ring_rs_send_chunk(r, step, p),
                chunks[r][schedules.ring_rs_send_chunk(r, step, p)])
            for r in range(p)
        }
        for r in range(p):
            src = (r - 1) % p
            si, payload = sent[src]
            ri = schedules.ring_rs_recv_chunk(r, step, p)
            assert si == ri, "sent chunk index must equal receiver's expected index"
            chunks[r][ri] = chunks[r][ri] | payload
    # after reduce-scatter rank r fully owns chunk (r+1) % p
    for r in range(p):
        assert chunks[r][(r + 1) % p] == set(range(p))
    # allgather phase distributes the reduced chunks everywhere
    for step in range(p - 1):
        sent = {
            r: (schedules.ring_ag_send_chunk(r, step, p),
                chunks[r][schedules.ring_ag_send_chunk(r, step, p)])
            for r in range(p)
        }
        for r in range(p):
            src = (r - 1) % p
            si, payload = sent[src]
            ri = schedules.ring_ag_recv_chunk(r, step, p)
            assert si == ri
            chunks[r][ri] = payload
    for r in range(p):
        for i in range(p):
            assert chunks[r][i] == set(range(p)), f"rank {r} chunk {i} incomplete"


@given(size=pow2_sizes)
@settings(max_examples=20, deadline=None)
def test_halving_masks_end_at_own_chunk(size):
    if size == 1:
        assert schedules.halving_masks(1) == []
        return
    masks = schedules.halving_masks(size)
    assert len(masks) == size.bit_length() - 1
    for r in range(size):
        lo, hi = 0, size
        for m in masks:
            checker.validate_perm(schedules.xor_perm(size, m), size)
            mid = (lo + hi) // 2
            lo, hi = (mid, hi) if r & m else (lo, mid)
        assert (lo, hi) == (r, r + 1)


def test_halving_rejects_non_pow2():
    with pytest.raises(ValueError):
        schedules.halving_masks(6)


@given(size=sizes)
@settings(max_examples=40, deadline=None)
def test_alltoall_rounds_deliver_every_block_once(size):
    p = size
    delivered = [[None] * p for _ in range(p)]  # delivered[dst][src] = block
    for r in range(p):
        delivered[r][r] = (r, r)
    for k in schedules.alltoall_rounds(p):
        checker.validate_perm(schedules.ring_perm(p, k), p)
        for r in range(p):
            dst = (r + k) % p
            assert delivered[dst][r] is None
            delivered[dst][r] = (r, dst)
    for dst in range(p):
        for src in range(p):
            assert delivered[dst][src] == (src, dst)


@given(size=sizes)
@settings(max_examples=40, deadline=None)
def test_dissemination_offsets_synchronize(size):
    # knowledge-propagation argument: after all rounds every rank has
    # (transitively) heard from every other rank
    know = [{r} for r in range(size)]
    for off in schedules.dissemination_offsets(size):
        new = [set(k) for k in know]
        for r in range(size):
            new[r] |= know[(r - off) % size]
        know = new
    for r in range(size):
        assert know[r] == set(range(size))


def test_validate_perm_catches_duplicates():
    with pytest.raises(checker.ScheduleError):
        checker.validate_perm([(0, 1), (0, 2)], 4)
    with pytest.raises(checker.ScheduleError):
        checker.validate_perm([(0, 1), (2, 1)], 4)
    with pytest.raises(checker.ScheduleError):
        checker.validate_perm([(0, 9)], 4)
    checker.validate_perm([(0, 1), (1, 0), (2, 3)], 4)


def test_verify_matching():
    logs = [
        [("send", 1, 5)],
        [("recv", 0, 5)],
    ]
    assert checker.verify_matching(logs) == []
    logs = [[("send", 1, 5)], []]
    assert len(checker.verify_matching(logs)) == 1
    logs = [[], [("recv", 0, 5)]]
    assert len(checker.verify_matching(logs)) == 1
    # wildcard recv matches any source
    logs = [[("send", 1, 3)], [("recv", -1, -1)]]
    assert checker.verify_matching(logs) == []
