"""OSU-style micro-benchmark suite (SURVEY.md §2 component #12;
BASELINE.json:2,7-10).

Benchmarks: ``latency`` (ping-pong — the classic ``osu_latency``),
``barrier`` (``osu_barrier``: p50 of a full barrier round), ``bcast``,
``reduce``, ``allreduce``, ``allgather``, ``alltoall``,
``reduce_scatter`` — swept over message sizes and algorithm variants on
any backend.  Output is JSON lines so BASELINE.md tables regenerate
mechanically (SURVEY.md §5 observability row).  Every row carries
``oversubscribed`` (ranks > cpu cores) so the known ±2-3x noise cells of
an oversubscribed box are machine-identifiable.

Bus-bandwidth follows the NCCL-tests convention (SURVEY.md §6):
allreduce ``bytes × 2(P−1)/P ÷ t``; allgather/alltoall/reduce_scatter
``bytes × (P−1)/P ÷ t`` where bytes is the full gathered/exchanged/
reduced payload; bcast/reduce ``bytes ÷ t``.

Usage::

    python -m benchmarks.osu --bench allreduce --backend local -n 4 \
        --sizes 1KB:1MB:4 --algorithms ring,recursive_halving
    python -m benchmarks.osu --bench latency --backend socket -n 2
    python -m benchmarks.osu --bench allreduce --backend tpu -n 8 --sizes 4KB:4MB:4
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Dict, List, Optional

import numpy as np

try:
    import mpi_tpu
except ModuleNotFoundError:  # fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

_UNITS = {"": 1, "B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}


def parse_size(token: str) -> int:
    token = token.strip().upper()
    for suffix in ("GB", "MB", "KB", "B"):
        if token.endswith(suffix):
            return int(float(token[: -len(suffix)]) * _UNITS[suffix])
    return int(token)


def parse_sizes(spec: str) -> List[int]:
    """``lo:hi:factor`` geometric sweep, or a comma list of sizes (bytes,
    with optional KB/MB/GB suffix)."""
    if ":" in spec:
        lo_s, hi_s, fac_s = spec.split(":")
        lo, hi, fac = parse_size(lo_s), parse_size(hi_s), float(fac_s)
        if fac <= 1:
            raise ValueError("sweep factor must be > 1")
        if lo < 1:
            raise ValueError(f"sweep start must be >= 1 byte, got {lo}")
        sizes, cur = [], lo
        while cur <= hi:
            sizes.append(int(cur))
            cur *= fac
        return sizes
    return [parse_size(t) for t in spec.split(",")]


def busbw_gbps(bench: str, nbytes: int, p: int, seconds: float) -> float:
    if seconds <= 0:
        return float("inf")
    if bench == "allreduce":
        moved = nbytes * 2 * (p - 1) / p
    elif bench in ("allgather", "alltoall", "reduce_scatter"):
        moved = nbytes * (p - 1) / p
    else:  # bcast, reduce
        moved = nbytes
    return moved / seconds / 1e9


# ---------------------------------------------------------------------------
# CPU backends: the benchmark is itself a portable MPI program
# ---------------------------------------------------------------------------


# Arena-gate spellings (ISSUE 11 satellite: measured rows for the
# coll_sm INTERNAL gates, PR-9's consult-only residual).  Each maps a
# pseudo-algorithm to (real algorithm, forced coll_sm_eager_bytes): the
# gate under sweep is the eager constant itself, so the leg pins it to
# one side around an ``algorithm="sm"`` run — every rank applies the
# same override in the same cell order, keeping the group coherent.
# ``sm_reduce``'s "tree" side needs no spelling: it IS the plain wire
# algorithm ("tree"), measured as such.
_GATE_LEGS = {
    ("allreduce", "sm_flat"): ("sm", 1 << 62),   # flat P·N slot folds
    ("allreduce", "sm_chunked"): ("sm", 0),      # block in-place folds
    ("reduce", "sm_arena"): ("sm", 1 << 62),     # flat root fold
}


def _cpu_collective_call(comm, bench: str, x: np.ndarray, algo: str):
    if bench == "allreduce":
        return comm.allreduce(x, algorithm=algo)
    if bench == "bcast":
        return comm.bcast(x if comm.rank == 0 else None, root=0, algorithm=algo)
    if bench == "reduce":
        return comm.reduce(x, root=0, algorithm=algo)
    if bench == "allgather":
        return comm.allgather(x, algorithm=algo)
    if bench == "alltoall":
        blocks = np.array_split(x, comm.size)
        return comm.alltoall(blocks, algorithm=algo)
    if bench == "reduce_scatter":
        # nbytes is the TOTAL per-rank input (one block per destination
        # rank), matching the alltoall convention
        blocks = np.array_split(x, comm.size)
        return comm.reduce_scatter(blocks, algorithm=algo)
    raise ValueError(f"unknown benchmark {bench!r}")


def cpu_bench_program(comm, bench: str, sizes: List[int], algos: List[str],
                      iters: int, warmup: int) -> List[Dict]:
    """Runs on every rank; returns rows on rank 0, [] elsewhere."""
    rows: List[Dict] = []
    if bench == "latency":
        # classic osu_latency: ping-pong between ranks 0 and 1
        for nbytes in sizes:
            payload = np.zeros(max(1, nbytes // 4), np.float32)
            comm.barrier()
            samples = []
            for i in range(warmup + iters):
                t0 = time.perf_counter()
                if comm.rank == 0:
                    comm.send(payload, dest=1, tag=1)
                    comm.recv(source=1, tag=2)
                elif comm.rank == 1:
                    comm.recv(source=0, tag=1)
                    comm.send(payload, dest=0, tag=2)
                if i >= warmup:
                    samples.append((time.perf_counter() - t0) / 2)  # one-way
            comm.barrier()
            if comm.rank == 0:
                rows.append({"bench": "latency", "nranks": comm.size,
                             "bytes": nbytes,
                             "p50_us": statistics.median(samples) * 1e6})
        return rows

    if bench == "barrier":
        # osu_barrier: p50 of one full barrier round (no payload, so the
        # sizes sweep collapses to a single row).  The slowest rank's
        # median is the barrier completion time, like the collectives.
        comm.barrier()
        samples = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            comm.barrier()
            dt = time.perf_counter() - t0
            if i >= warmup:
                samples.append(dt)
        p50 = float(np.asarray(comm.allreduce(
            np.float64(statistics.median(samples)), op=mpi_tpu.MAX,
            algorithm="reduce_bcast")))
        if comm.rank == 0:
            rows.append({"bench": "barrier", "nranks": comm.size,
                         "bytes": 0, "p50_us": p50 * 1e6})
        return rows

    if bench == "bw":
        # classic osu_bw: rank 0 streams a WINDOW of nonblocking sends,
        # rank 1 receives them all and acks once per window; unidirectional
        # bandwidth = window_bytes / window_time.  The window keeps the
        # pipe full — a single in-flight message (the latency test) can
        # never saturate a transport.
        for nbytes in sizes:
            # cap in-flight bytes at 32MB so huge sizes don't exhaust RAM
            window = max(2, min(64, (32 << 20) // max(1, nbytes)))
            payload = np.zeros(max(1, nbytes // 4), np.float32)
            comm.barrier()
            samples = []
            for i in range(warmup + iters):
                t0 = time.perf_counter()
                if comm.rank == 0:
                    reqs = [comm.isend(payload, dest=1, tag=w)
                            for w in range(window)]
                    for r in reqs:
                        r.wait()
                    comm.recv(source=1, tag=10_000)  # window ack
                elif comm.rank == 1:
                    reqs = [comm.irecv(source=0, tag=w)
                            for w in range(window)]
                    for r in reqs:
                        r.wait()
                    comm.send(b"ack", dest=0, tag=10_000)
                if i >= warmup:
                    samples.append(time.perf_counter() - t0)
            comm.barrier()
            if comm.rank == 0:
                t = statistics.median(samples)
                rows.append({"bench": "bw", "nranks": comm.size,
                             "bytes": nbytes, "window": window,
                             "bw_gbps": window * nbytes / t / 1e9,
                             "p50_us": t * 1e6})
        return rows

    if bench == "bibw":
        # classic osu_bibw: BOTH ranks stream a window of nonblocking
        # sends at each other simultaneously, then drain their posted
        # receives; bidirectional bandwidth = 2·window·nbytes / time.
        # The shape receive-side steering (ISSUE 17) targets: with
        # traffic flowing both ways each rank's reader thread competes
        # with its sender for the GIL, so the removed pool-stage copy
        # (and its page faults) is paid twice per exchange here.
        for nbytes in sizes:
            window = max(2, min(64, (32 << 20) // max(1, nbytes)))
            payload = np.zeros(max(1, nbytes // 4), np.float32)
            comm.barrier()
            samples = []
            for i in range(warmup + iters):
                t0 = time.perf_counter()
                if comm.rank in (0, 1):
                    peer = 1 - comm.rank
                    rreqs = [comm.irecv(source=peer, tag=w)
                             for w in range(window)]
                    sreqs = [comm.isend(payload, dest=peer, tag=w)
                             for w in range(window)]
                    for r in sreqs:
                        r.wait()
                    for r in rreqs:
                        r.wait()
                if i >= warmup:
                    samples.append(time.perf_counter() - t0)
            comm.barrier()
            if comm.rank == 0:
                t = statistics.median(samples)
                rows.append({"bench": "bibw", "nranks": comm.size,
                             "bytes": nbytes, "window": window,
                             "bw_gbps": 2 * window * nbytes / t / 1e9,
                             "p50_us": t * 1e6})
        return rows

    if bench == "overlap":
        return _overlap_bench(comm, sizes, iters, warmup)

    if bench == "persist":
        return _persist_bench(comm, sizes, iters, warmup)

    if bench == "steer":
        return _steer_bench(comm, sizes, iters, warmup)

    for nbytes in sizes:
        if bench == "allgather":
            # nbytes is the TOTAL gathered payload (busbw convention; matches
            # the TPU path): each rank contributes nbytes/P
            x = np.zeros(max(1, nbytes // 4 // comm.size), np.float32)
        else:
            x = np.zeros(max(1, nbytes // 4), np.float32)
        for algo in algos:
            real_algo, forced_eager = _GATE_LEGS.get((bench, algo),
                                                     (algo, None))
            try:
                if forced_eager is not None:
                    old_eager = mpi_tpu.mpit.cvar_read(
                        "coll_sm_eager_bytes")
                    mpi_tpu.mpit.cvar_write("coll_sm_eager_bytes",
                                            forced_eager)
                try:
                    comm.barrier()
                    samples = []
                    for i in range(warmup + iters):
                        t0 = time.perf_counter()
                        _cpu_collective_call(comm, bench, x, real_algo)
                        dt = time.perf_counter() - t0
                        if i >= warmup:
                            samples.append(dt)
                    # report the slowest rank's median (collective
                    # completion time)
                    p50 = float(np.asarray(comm.allreduce(
                        np.float64(statistics.median(samples)),
                        op=mpi_tpu.MAX, algorithm="reduce_bcast")))
                finally:
                    if forced_eager is not None:
                        mpi_tpu.mpit.cvar_write("coll_sm_eager_bytes",
                                                old_eager)
            except ValueError as e:
                if comm.rank == 0:
                    rows.append({"bench": bench, "bytes": nbytes, "algorithm": algo,
                                 "skipped": str(e)})
                continue
            if comm.rank == 0:
                rows.append({
                    "bench": bench, "nranks": comm.size, "bytes": nbytes,
                    "algorithm": algo, "p50_us": p50 * 1e6,
                    "busbw_gbps": busbw_gbps(bench, nbytes, comm.size, p50),
                })
    return rows


# ---------------------------------------------------------------------------
# Compute/communication overlap (osu_ialltoall-style; ISSUE 6)
# ---------------------------------------------------------------------------
#
# For each size: measure the pure nonblocking alltoall (post + immediate
# wait), calibrate a fixed compute loop, then measure post -> compute ->
# wait.  Reported per row:
#
#   overlap_pct      = 100 * max(0, 1 - (t_total - t_compute) / t_pure)
#   availability_pct = 100 * t_compute / t_total   (CPU left to the app)
#
# The compute window is FIXED per size — ``nbytes`` at a nominal 4 GB/s
# line rate (floor 200us), NOT scaled to the measured pure time — so
# progress modes hide the SAME workload.  This matters: a mode whose
# pure time is inflated by idle latency (the helper-paced shm stall)
# would trivially "hide" its own slack under a pure-time-sized compute
# loop, and the metric would reward slowness.  Against a fixed window
# the question each row answers is the honest one: does a short compute
# phase between post and wait buy anything, or does the communication
# only progress once the caller blocks?  (MPI_TPU_PROGRESS governs the
# mode; the row records it.)

# nominal line rate that sizes the fixed compute window
_OVERLAP_LINE_RATE = 4e9
_OVERLAP_MIN_COMPUTE_S = 200e-6


def _overlap_compute(n_iters: int, a: np.ndarray, b: np.ndarray) -> None:
    """The dummy compute: small BLAS matmuls — numpy releases the GIL
    around each, like real numerical compute, so background threads CAN
    run; whether communication finishes inside the window is exactly
    what the benchmark measures."""
    for _ in range(n_iters):
        np.dot(a, b)


def _overlap_bench(comm, sizes: List[int], iters: int,
                   warmup: int) -> List[Dict]:
    a = np.zeros((64, 64), np.float32)
    b = np.zeros((64, 64), np.float32)
    _overlap_compute(32, a, b)  # warm the BLAS path
    t0 = time.perf_counter()
    _overlap_compute(64, a, b)
    unit_s = (time.perf_counter() - t0) / 64

    def red_max(x: float) -> float:
        return float(np.asarray(comm.allreduce(
            np.float64(x), op=mpi_tpu.MAX, algorithm="reduce_bcast")))

    mode = "thread" if getattr(comm, "_progress", None) is not None \
        else "none"
    # warm the transport path (ring mappings, connection setup, recv
    # pool) before the first measured size — first-touch page faults
    # otherwise land entirely in the first cell's pure leg
    warm = np.array_split(np.zeros(1 << 14, np.float32), comm.size)
    for _ in range(3):
        comm.ialltoall(warm).wait()
    rows: List[Dict] = []
    for nbytes in sizes:
        x = np.zeros(max(comm.size, nbytes // 4), np.float32)
        blocks = np.array_split(x, comm.size)

        comm.barrier()
        samples = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            comm.ialltoall(blocks).wait()
            if i >= warmup:
                samples.append(time.perf_counter() - t0)
        t_pure = red_max(statistics.median(samples))

        target_s = max(_OVERLAP_MIN_COMPUTE_S, nbytes / _OVERLAP_LINE_RATE)
        n_units = max(1, int(round(target_s / unit_s)))
        comm.barrier()
        samples = []
        for _ in range(max(3, min(7, warmup + iters))):
            t0 = time.perf_counter()
            _overlap_compute(n_units, a, b)
            samples.append(time.perf_counter() - t0)
        t_comp = red_max(statistics.median(samples))

        comm.barrier()
        samples = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            req = comm.ialltoall(blocks)
            _overlap_compute(n_units, a, b)
            req.wait()
            if i >= warmup:
                samples.append(time.perf_counter() - t0)
        t_total = red_max(statistics.median(samples))

        if comm.rank == 0:
            rows.append({
                "bench": "overlap", "nranks": comm.size, "bytes": nbytes,
                "progress": mode,
                "pure_us": t_pure * 1e6,
                "compute_us": t_comp * 1e6,
                "compute_target_us": target_s * 1e6,
                "total_us": t_total * 1e6,
                "p50_us": t_total * 1e6,
                "overlap_pct": min(100.0, 100.0 * max(
                    0.0, 1.0 - (t_total - t_comp) / max(t_pure, 1e-12))),
                "availability_pct": min(100.0, 100.0 * t_comp
                                        / max(t_total, 1e-12)),
            })
    return rows


# ---------------------------------------------------------------------------
# Persistent collectives (osu_allreduce_persistent shape; ISSUE 12)
# ---------------------------------------------------------------------------
#
# For each size: p50 of a FRESH ``iallreduce(x).wait()`` (post + wait,
# the per-call path — schedule compile, child-context creation, tuned
# resolution every call) against p50 of ``h.start().wait()`` re-fires of
# one ``allreduce_init`` handle (everything hoisted to init).  Both legs
# run whatever dispatch the environment selects (MPI_TPU_PROGRESS /
# MPI_TPU_NBC) and each row records it, so the same harness prices both
# sides of the PR: with the engine the re-fire is the hot-loop win;
# without it both legs spawn a thread per round and the handle buys
# nothing — the honest 'pre' rows.


def _persist_bench(comm, sizes: List[int], iters: int,
                   warmup: int) -> List[Dict]:
    from mpi_tpu import nbc

    def red_max(x: float) -> float:
        return float(np.asarray(comm.allreduce(
            np.float64(x), op=mpi_tpu.MAX, algorithm="reduce_bcast")))

    mode = "thread" if getattr(comm, "_progress", None) is not None \
        else "none"
    rows: List[Dict] = []
    for nbytes in sizes:
        x = np.zeros(max(1, nbytes // 4), np.float32)

        comm.barrier()
        samples = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            comm.iallreduce(x).wait()
            if i >= warmup:
                samples.append(time.perf_counter() - t0)
        t_fresh = red_max(statistics.median(samples))

        h = comm.allreduce_init(x)
        h.start().wait()  # warm the handle (first-round lazy work)
        comm.barrier()
        samples = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            h.start().wait()
            if i >= warmup:
                samples.append(time.perf_counter() - t0)
        t_refire = red_max(statistics.median(samples))

        if comm.rank == 0:
            rows.append({
                "bench": "persist", "nranks": comm.size, "bytes": nbytes,
                "progress": mode, "nbc": nbc.mode(),
                "fresh_us": t_fresh * 1e6,
                "refire_us": t_refire * 1e6,
                "p50_us": t_refire * 1e6,
                "refire_speedup": t_fresh / max(t_refire, 1e-12),
            })
    return rows


_STEER_PVARS = ("payload_copies", "recv_bytes_steered",
                "recv_pool_rendezvous", "recv_user_inplace",
                "recv_user_fallbacks", "recv_pool_hits",
                "recv_pool_misses", "recv_pool_fold_fallbacks",
                "link_recv_syscalls")


def _steer_bench(comm, sizes: List[int], iters: int,
                 warmup: int) -> List[Dict]:
    """Receive-plane steering legs (ISSUE 19): each leg brackets its
    loop with pvar reads and ships the world-SUMMED deltas home on the
    row, so the committed artifact PROVES the zero-copy claims (bytes
    steered, stores at the floor, zero pool traffic on the user path)
    instead of inferring them from timing.  Three legs per size:

    * ``allreduce_ring`` — the 16MB acceptance shape: internal-tag
      segmented collective, both transports.
    * ``user_irecv`` — ``irecv(buf=)`` rendezvous, post-before-send
      (a tag-99 handshake pins the in-order case).
    * ``scatter_gather`` — a two-segment frame into a view list (the
      vectored-read path on socket).
    """
    def leg_rows(run_iter):
        comm.barrier()
        base = {n: mpi_tpu.mpit.pvar_read(n) for n in _STEER_PVARS}
        samples = []
        for i in range(warmup + iters):
            t0 = time.perf_counter()
            run_iter()
            if i >= warmup:
                samples.append(time.perf_counter() - t0)
        comm.barrier()
        local = np.array([mpi_tpu.mpit.pvar_read(n) - base[n]
                          for n in _STEER_PVARS], np.int64)
        tot = np.asarray(comm.allreduce(local, algorithm="ring"))
        return (statistics.median(samples) * 1e6,
                {n: int(v) for n, v in zip(_STEER_PVARS, tot)})

    rows: List[Dict] = []
    for nbytes in sizes:
        n = max(2, nbytes // 8)
        data = np.arange(n, dtype=np.float64) + comm.rank
        payload = np.ones(n, np.float64)
        buf = np.zeros(n, np.float64)
        segs = [np.ones(n // 2, np.float64),
                np.ones(n - n // 2, np.float64)]
        bufs = [np.zeros_like(s) for s in segs]

        def ar_iter():
            comm.allreduce(data, algorithm="ring")

        def user_iter():
            if comm.rank == 0:
                comm.recv(source=1, tag=99)
                comm.send(payload, dest=1, tag=7)
            elif comm.rank == 1:
                req = comm.irecv(source=0, tag=7, buf=buf)
                comm.send(b"p", dest=0, tag=99)
                req.wait()

        def sg_iter():
            if comm.rank == 0:
                comm.recv(source=1, tag=99)
                comm.send(segs, dest=1, tag=8)
            elif comm.rank == 1:
                req = comm.irecv(source=0, tag=8, buf=bufs)
                comm.send(b"p", dest=0, tag=99)
                req.wait()

        for leg, run_iter in (("allreduce_ring", ar_iter),
                              ("user_irecv", user_iter),
                              ("scatter_gather", sg_iter)):
            p50, pvars = leg_rows(run_iter)
            if comm.rank == 0:
                rows.append({"bench": "steer", "leg": leg,
                             "nranks": comm.size, "bytes": nbytes,
                             "p50_us": p50, "pvars": pvars})
    return rows


# ---------------------------------------------------------------------------
# TPU backend: one jitted shard_map program per (bench, size, algorithm)
# ---------------------------------------------------------------------------


def tpu_bench(bench: str, sizes: List[int], algos: List[str], iters: int,
              warmup: int, nranks: Optional[int]) -> List[Dict]:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_tpu.tpu import TpuCommunicator, default_mesh

    mesh = default_mesh(nranks)
    p = mesh.shape["world"]
    comm = TpuCommunicator("world", mesh)
    sharded = NamedSharding(mesh, P("world"))
    rows: List[Dict] = []

    def timed(fn, x) -> float:
        fn(x).block_until_ready()  # compile + warm
        for _ in range(max(0, warmup - 1)):
            fn(x).block_until_ready()
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            fn(x).block_until_ready()
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples)

    def my_slice(full):
        """This rank's 1/p slice of a (value-)replicated result: keeps the
        timed program's OUTPUT sharded too, so HBM stays O(n), and keeps
        every hand-scheduled algorithm vma-clean (the output is allowed to
        vary — no replication proof needed)."""
        r = lax_axis(comm)
        flat = full.reshape(p, -1)
        return jax.lax.dynamic_slice(flat, (r, 0), (1, flat.shape[1]))

    def lax_axis(c):
        import jax.lax as lax

        return lax.axis_index(c.axis_name)

    for nbytes in sizes:
        n = max(1, nbytes // 4)
        for algo in algos:
            try:
                # inputs are SHARDED one per-rank buffer per device (a
                # replicated in_spec would inflate HBM p× at north-star
                # sizes — the SURVEY §7 trap VERDICT round 1 flagged)
                if bench == "latency":
                    # round-trip ppermute ring step there and back
                    def body(x):
                        y = comm.shift(x.reshape(-1), offset=1, wrap=True)
                        return comm.shift(y, offset=-1, wrap=True)[None]
                    xg = jnp.zeros((p, n), jnp.float32)
                elif bench == "allreduce":
                    def body(x, a=algo):
                        return my_slice(comm.allreduce(
                            x.reshape(-1), algorithm=a))
                    xg = jnp.zeros((p, n), jnp.float32)
                elif bench == "bcast":
                    def body(x, a=algo):
                        return my_slice(comm.bcast(
                            x.reshape(-1), root=0, algorithm=a))
                    xg = jnp.zeros((p, n), jnp.float32)
                elif bench == "reduce":
                    def body(x, a=algo):
                        return my_slice(comm.reduce(
                            x.reshape(-1), root=0, algorithm=a))
                    xg = jnp.zeros((p, n), jnp.float32)
                elif bench == "allgather":
                    def body(x, a=algo):
                        return my_slice(comm.allgather(
                            x.reshape(-1), algorithm=a))
                    xg = jnp.zeros((p, max(1, n // p)), jnp.float32)
                elif bench == "alltoall":
                    def body(x, a=algo):
                        return comm.alltoall(x[0], algorithm=a)[None]
                    xg = jnp.zeros((p, p, max(1, n // p)), jnp.float32)
                elif bench == "reduce_scatter":
                    def body(x, a=algo):
                        return comm.reduce_scatter(x[0], algorithm=a)[None]
                    xg = jnp.zeros((p, p, max(1, n // p)), jnp.float32)
                else:
                    raise ValueError(f"unknown benchmark {bench!r}")

                xg = jax.jit(lambda s=xg.shape: jnp.zeros(s, jnp.float32),
                             out_shardings=sharded)()
                fn = jax.jit(jax.shard_map(
                    body, mesh=mesh, in_specs=P("world"),
                    out_specs=P("world"),
                    check_vma=(algo != "pallas_ring")))
                t = timed(fn, xg)
            except ValueError as e:
                rows.append({"bench": bench, "bytes": nbytes, "algorithm": algo,
                             "skipped": str(e)})
                continue
            row = {"bench": bench, "backend": "tpu",
                   "platform": mesh.devices.flat[0].platform,
                   "nranks": p, "bytes": nbytes, "algorithm": algo,
                   "p50_us": t * 1e6}
            if bench == "latency":
                row["p50_us"] = t * 1e6 / 2
            else:
                row["busbw_gbps"] = busbw_gbps(bench, nbytes, p, t)
            rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

ALL_BENCHES = ["latency", "bw", "bibw", "barrier", "bcast", "reduce",
               "allreduce", "allgather", "alltoall", "reduce_scatter",
               "overlap", "persist", "steer"]
DEFAULT_ALGOS = {
    "allreduce": ["ring", "recursive_halving", "fused"],  # + pallas_ring (tpu, opt-in)
    "bcast": ["tree", "fused"],
    "reduce": ["tree", "fused"],
    "allgather": ["ring", "doubling", "fused"],
    "alltoall": ["pairwise", "fused"],
    "reduce_scatter": ["ring", "fused"],
    "latency": ["-"],
    "bw": ["-"],
    "bibw": ["-"],
    "barrier": ["-"],
    "overlap": ["-"],
    "persist": ["-"],
    "steer": ["-"],
}


def run_bench(bench: str, backend: str, nranks: int, sizes: List[int],
              algos: List[str], iters: int, warmup: int,
              algos_explicit: bool = False) -> List[Dict]:
    if backend == "tpu":
        if bench in ("bw", "bibw", "barrier", "overlap", "persist",
                     "steer"):
            # SPMD has no standalone p2p stream, its barrier is a
            # device-fused psum, and its nonblocking ops are XLA's to
            # schedule; all are process-backend benches
            return [{"bench": bench, "backend": "tpu",
                     "skipped": f"{bench} is a process-backend bench"}]
        return tpu_bench(bench, sizes, algos, iters, warmup, nranks)
    if not algos_explicit:
        # 'fused'/'pallas_ring' are TPU-backend tiers; drop them from the
        # DEFAULT list on CPU backends ('fused' would alias to a size-
        # dependent schedule — mislabeled rows).  Explicitly requested
        # algorithms pass through and fail loudly per-row instead.
        algos = [a for a in (algos or [])
                 if a not in ("fused", "pallas_ring")] or ["auto"]
    if backend == "local":
        results = mpi_tpu.run_local(
            cpu_bench_program, nranks,
            args=(bench, sizes, algos, iters, warmup))
        rows = results[0]
    else:  # socket/shm: must already be under the launcher
        if "MPI_TPU_RANK" in os.environ:
            rows = cpu_bench_program(mpi_tpu.init(), bench, sizes, algos,
                                     iters, warmup)
            # label with the transport the launcher actually selected
            backend = os.environ.get("MPI_TPU_BACKEND", backend)
        else:
            raise SystemExit(
                "backend=socket must run under the launcher:\n"
                f"  python -m mpi_tpu.launcher -n {nranks} benchmarks/osu.py ..."
            )
    ncpu = os.cpu_count() or 1
    # Process backends run N rank PROCESSES plus the driving process —
    # that +1 is exactly what makes the 2-rank sweeps contend on the
    # 2-core reference box (the documented ±2-3x noise band), so it must
    # count or the stamp reads false on the very box it was built for.
    # Thread/SPMD backends share the driver's process.
    extra = 0 if backend in ("local", "tpu") else 1
    for r in rows:
        r.setdefault("backend", backend)
        # the row's own rank count when present — under the launcher the
        # CLI -n default is not the world size
        r.setdefault("oversubscribed",
                     int(r.get("nranks", nranks)) + extra > ncpu)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--bench", default="allreduce",
                    choices=ALL_BENCHES + ["all"])
    ap.add_argument("--backend", default="local",
                    choices=["socket", "shm", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=4)
    ap.add_argument("--sizes", default="1KB:1MB:8")
    ap.add_argument("--algorithms", default=None,
                    help="comma list; default: all for the chosen benchmark")
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--out", default=None, help="also append JSON lines here")
    args = ap.parse_args(argv)

    sizes = parse_sizes(args.sizes)
    benches = ALL_BENCHES if args.bench == "all" else [args.bench]
    sink = open(args.out, "a") if args.out else None
    for bench in benches:
        algos = (args.algorithms.split(",") if args.algorithms
                 else DEFAULT_ALGOS[bench])
        rows = run_bench(bench, args.backend, args.nranks, sizes, algos,
                         args.iters, args.warmup,
                         algos_explicit=args.algorithms is not None)
        for row in rows:
            line = json.dumps(row)
            print(line)
            if sink:
                sink.write(line + "\n")
    if sink:
        sink.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
