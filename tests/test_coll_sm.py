"""Shared-memory collective arena (ISSUE 4 tentpole — mpi_tpu/coll_sm.py).

Four contracts:

* parity — ``algorithm="sm"`` (and ``auto``, which routes to the arena on
  shm transports) matches the wire algorithms and the numpy oracle for
  bcast/reduce/allreduce/allgather/barrier/reduce_scatter, across group
  sizes, ops, the flat↔block boundary, and ragged/object payloads (which
  must FALL BACK through the in-arena negotiation, not deadlock);
* the copy contract — pvars prove an arena collective moves ZERO ring
  frames (``msgs_sent``), ZERO pickled payload bytes
  (``bytes_pickled_sent``), and ≤2 payload copies per rank
  (``payload_copies``), with ``coll_sm_hits``/``coll_sm_bytes`` counting;
* lifecycle — the ``algorithm="sm"`` gate error on non-shm transports,
  per-communicator arenas for disjoint split children (the ctx-sharing
  regression), refcount/unlink at world finalize, the cvar kill switch;
* fault tolerance — a rank dying mid-barrier surfaces ProcFailedError on
  the survivors within the detection bound (the FaultyTransport-style
  ``killed`` injection), never a deadlock.
"""

import glob
import time

import numpy as np
import pytest

from mpi_tpu import coll_sm, ft, mpit, ops, topology
from mpi_tpu.errors import ProcFailedError
from mpi_tpu.transport.local import run_local
from tests.test_shm_backend import run_shm_world
from tests.test_socket_backend import run_socket_world

NRANKS = [2, 3, 4, 5]


def _deltas(world, prog, nranks, names):
    base = {n: mpit.pvar_read(n) for n in names}
    res = world(prog, nranks)
    return res, {n: mpit.pvar_read(n) - base[n] for n in names}


# -- parity ------------------------------------------------------------------


@pytest.mark.parametrize("algo", ["sm", "auto"])
def test_allreduce_parity_flat_and_block(algo):
    """Both arena paths (flat at <=eager, chunked in-place above) match
    the oracle for every group size, op, and scalar payloads."""
    for n in NRANKS:
        for nelem in (1, 7, 1 << 10, (coll_sm._EAGER_BYTES // 8) + 13):
            data = [np.random.RandomState(100 * n + i).randn(nelem)
                    for i in range(n)]

            def prog(comm):
                return comm.allreduce(data[comm.rank], ops.SUM,
                                      algorithm=algo)

            for res in run_shm_world(prog, n):
                np.testing.assert_allclose(res, sum(data),
                                           err_msg=f"n={n} nelem={nelem}")


def test_allreduce_ops_and_scalars():
    def prog(comm):
        mx = comm.allreduce(np.float64(comm.rank), ops.MAX, algorithm="sm")
        s = comm.allreduce(float(comm.rank + 1), algorithm="sm")
        return mx, s

    for mx, s in run_shm_world(prog, 4):
        assert float(mx) == 3.0
        assert float(s) == 10.0
        assert np.asarray(mx).ndim == 0


def test_bcast_reduce_allgather_barrier_parity():
    n = 4
    data = np.random.RandomState(5).randn(n, 9)

    def prog(comm):
        out = {}
        out["bcast"] = comm.bcast(
            data[0] if comm.rank == 0 else None, root=0, algorithm="sm")
        out["reduce"] = comm.reduce(data[comm.rank], ops.SUM, root=2,
                                    algorithm="sm")
        out["ag"] = comm.allgather(data[comm.rank], algorithm="sm")
        comm.barrier(algorithm="sm")
        out["rs"] = comm.reduce_scatter(
            np.tile(data[comm.rank], (comm.size, 1)), ops.SUM,
            algorithm="sm")
        return out

    for r, out in enumerate(run_shm_world(prog, n)):
        np.testing.assert_array_equal(out["bcast"], data[0])
        if r == 2:
            np.testing.assert_allclose(out["reduce"], data.sum(0))
        else:
            assert out["reduce"] is None
        np.testing.assert_array_equal(np.asarray(out["ag"]), data)
        np.testing.assert_allclose(out["rs"], data.sum(0))


def test_allgather_ragged_and_object_payloads_fall_back():
    """Ragged arrays ride the arena (per-slot geometry); object payloads
    make the WHOLE group fall back to the wire path via the in-arena
    negotiation — same results, no deadlock, fallbacks counted."""
    def prog(comm):
        ragged = comm.allgather(np.arange(comm.rank + 1.0), algorithm="sm")
        objs = comm.allgather({"r": comm.rank}, algorithm="sm")
        return ragged, objs

    f0 = mpit.pvar_read("coll_sm_fallbacks")
    for r, (ragged, objs) in enumerate(run_shm_world(prog, 3)):
        for q in range(3):
            np.testing.assert_array_equal(ragged[q], np.arange(q + 1.0))
        assert objs == [{"r": q} for q in range(3)]
    assert mpit.pvar_read("coll_sm_fallbacks") - f0 >= 3  # object leg


@pytest.mark.parametrize("algo", ["sm", "auto"])
def test_alltoall_parity(algo):
    """Arena alltoall (write-all-blocks → flag round → read-your-column,
    ISSUE 6 satellite) matches the pairwise wire exchange for every
    group size, including [P, ...] ndarray inputs."""
    for n in NRANKS:
        def prog(comm):
            blocks = [np.full(9, comm.rank * 100 + d, np.float64)
                      for d in range(comm.size)]
            out = comm.alltoall(blocks, algorithm=algo)
            stacked = comm.alltoall(
                np.stack(blocks), algorithm=algo)  # ndarray spelling
            return np.asarray(out)[:, 0].tolist(), \
                np.asarray(stacked)[:, 0].tolist()

        for r, (got, got2) in enumerate(run_shm_world(prog, n)):
            want = [q * 100.0 + r for q in range(n)]
            assert got == want, (n, r, got)
            assert got2 == want, (n, r, got2)


@pytest.mark.parametrize("algo", ["sm", "auto"])
def test_scan_parity(algo):
    """Arena scan (write-own → flag round → fold slots 0..rank in
    place) matches the distance-doubling wire scan, scalars included."""
    for n in NRANKS:
        data = [np.random.RandomState(30 * n + i).randn(17)
                for i in range(n)]

        def prog(comm):
            v = comm.scan(data[comm.rank], ops.SUM, algorithm=algo)
            s = comm.scan(float(comm.rank + 1), algorithm=algo)
            return v, s

        for r, (v, s) in enumerate(run_shm_world(prog, n)):
            np.testing.assert_allclose(v, sum(data[:r + 1]),
                                       err_msg=f"n={n} r={r}")
            assert float(s) == sum(range(1, r + 2))


def test_alltoall_scan_zero_frames_and_hits():
    """The new arena paths keep the arena's contract: zero ring frames,
    zero pickled bytes, ≤2 payload copies per rank, hits counted."""
    n = 3

    def prog(comm):
        blocks = [np.full(64, comm.rank * 10 + d, np.float64)
                  for d in range(comm.size)]
        a2a = comm.alltoall(blocks, algorithm="sm")
        sc = comm.scan(np.full(64, float(comm.rank)), algorithm="sm")
        assert np.asarray(a2a)[:, 0].tolist() == \
            [q * 10.0 + comm.rank for q in range(comm.size)]
        np.testing.assert_allclose(
            sc, np.full(64, float(sum(range(comm.rank + 1)))))
        return True

    names = ("msgs_sent", "bytes_pickled_sent", "payload_copies",
             "coll_sm_hits", "bytes_raw_sent")
    res, d = _deltas(run_shm_world, prog, n, names)
    assert all(res)
    assert d["msgs_sent"] == 0, f"arena alltoall/scan sent frames: {d}"
    assert d["bytes_pickled_sent"] == 0 and d["bytes_raw_sent"] == 0
    assert d["coll_sm_hits"] == 2 * n
    assert d["payload_copies"] <= 2 * 2 * n  # ≤2 per rank per collective


def test_alltoall_object_and_ragged_fall_back():
    """Object payloads and ragged per-destination blocks decline the
    arena THROUGH the in-arena negotiation (no deadlock, no wrong
    answer) and complete on the pairwise wire path."""
    def prog(comm):
        objs = [{"from": comm.rank, "to": d} for d in range(comm.size)]
        got = comm.alltoall(objs)  # auto: negotiation must decline
        ragged = [np.arange(d + 1, dtype=np.float64)
                  for d in range(comm.size)]
        got_r = comm.alltoall(ragged)
        return ([o["from"] for o in got],
                [g.shape[0] for g in got_r])

    for r, (froms, shapes) in enumerate(run_shm_world(prog, 3)):
        assert froms == [0, 1, 2]
        assert shapes == [r + 1] * 3


def test_scan_gate_rejects_sm_off_shm():
    def prog(comm):
        with pytest.raises(ValueError, match="scan algorithm"):
            comm.scan(1.0, algorithm="sm")
        with pytest.raises(ValueError, match="alltoall algorithm"):
            comm.alltoall([1.0] * comm.size, algorithm="sm")
        return True

    assert all(run_local(prog, 2))


def test_mismatched_reduction_geometry_falls_back():
    """Cross-rank dtype drift must not misfold in place: the metas
    disagree, every rank declines together, and the generic wire path's
    numpy-promotion semantics are preserved (reduce_scatter is the one
    collective whose seed path tolerated drift — same contract as
    test_reduce_scatter_mixed_dtypes_promote_like_seed, now via the
    arena negotiation on shm)."""
    def prog(comm):
        dtype = np.float64 if comm.rank == 0 else np.int64
        blocks = [np.arange(1, 5, dtype=dtype) * (comm.rank + 1)
                  for _ in range(comm.size)]
        return comm.reduce_scatter(blocks, op=ops.SUM, algorithm="sm")

    f0 = mpit.pvar_read("coll_sm_fallbacks")
    for res in run_shm_world(prog, 2):
        np.testing.assert_allclose(np.asarray(res, dtype=np.float64),
                                   np.arange(1, 5) * 3.0)
    assert mpit.pvar_read("coll_sm_fallbacks") - f0 >= 2


def test_oversized_payload_falls_back():
    """A payload larger than a slot declines into the segmented wire
    engine — still correct, counted as a fallback."""
    def prog(comm):
        arena = coll_sm.arena_for(comm)
        big = np.ones(arena.capacity // 8 + 64)
        return comm.allreduce(big, algorithm="sm")

    f0 = mpit.pvar_read("coll_sm_fallbacks")
    for res in run_shm_world(prog, 2):
        assert float(np.asarray(res)[0]) == 2.0
    assert mpit.pvar_read("coll_sm_fallbacks") - f0 >= 2


# -- the copy contract (zero frames, zero pickle, <=2 copies) ----------------


def test_arena_zero_frames_zero_pickle_two_copies():
    n, nelem = 4, 1 << 9  # 4KB: flat path
    data = [np.random.RandomState(i).randn(nelem) for i in range(n)]

    def prog(comm):
        out = comm.allreduce(data[comm.rank], ops.SUM, algorithm="sm")
        np.testing.assert_allclose(out, sum(data))
        return True

    names = ("msgs_sent", "bytes_pickled_sent", "payload_copies",
             "coll_sm_hits", "coll_sm_bytes", "bytes_raw_sent")
    res, d = _deltas(run_shm_world, prog, n, names)
    assert all(res)
    assert d["msgs_sent"] == 0, f"arena allreduce sent {d['msgs_sent']} frames"
    assert d["bytes_pickled_sent"] == 0
    assert d["bytes_raw_sent"] == 0  # no wire traffic at all
    assert d["coll_sm_hits"] == n
    assert d["coll_sm_bytes"] >= n * nelem * 8
    assert d["payload_copies"] <= 2 * n, \
        f"more than 2 copies per rank: {d['payload_copies']}"


def test_arena_block_path_copy_contract():
    """The >eager in-place chunk fold keeps the same contract: zero
    frames, zero pickled bytes, one copy in + one copy out per rank."""
    n = 2
    nelem = coll_sm._EAGER_BYTES // 8 * 4  # 4x eager: block path
    data = [np.random.RandomState(i).randn(nelem) for i in range(n)]

    def prog(comm):
        out = comm.allreduce(data[comm.rank], ops.SUM, algorithm="sm")
        np.testing.assert_allclose(out, sum(data))
        return True

    names = ("msgs_sent", "bytes_pickled_sent", "payload_copies",
             "coll_sm_hits")
    res, d = _deltas(run_shm_world, prog, n, names)
    assert all(res)
    assert d["msgs_sent"] == 0 and d["bytes_pickled_sent"] == 0
    assert d["coll_sm_hits"] == n
    assert d["payload_copies"] <= 2 * n


def test_barrier_is_message_free():
    def prog(comm):
        for _ in range(10):
            comm.barrier()
        return True

    res, d = _deltas(run_shm_world, lambda c: prog(c), 3, ("msgs_sent",))
    assert all(res)
    assert d["msgs_sent"] == 0, "shm auto barrier still sends messages"


# -- dispatch gate and lifecycle --------------------------------------------


def test_socket_and_local_reject_sm_with_gate_error():
    def prog(comm):
        msgs = {}
        for coll, call in {
            "allreduce": lambda: comm.allreduce(np.ones(4), algorithm="sm"),
            "bcast": lambda: comm.bcast(np.ones(4), algorithm="sm"),
            "reduce": lambda: comm.reduce(np.ones(4), algorithm="sm"),
            "allgather": lambda: comm.allgather(np.ones(4), algorithm="sm"),
            "barrier": lambda: comm.barrier(algorithm="sm"),
            "reduce_scatter": lambda: comm.reduce_scatter(
                np.ones((comm.size, 2)), algorithm="sm"),
        }.items():
            try:
                call()
            except ValueError as e:
                msgs[coll] = str(e)
        return msgs

    for world in (run_socket_world, run_local):
        for msgs in world(prog, 2):
            assert len(msgs) == 6, f"some gates accepted 'sm': {msgs}"
            for coll, m in msgs.items():
                assert m.startswith(f"unknown {coll} algorithm 'sm'"), m
                assert "accepted: [" in m and "'sm'" not in m.split(
                    "accepted: [")[1], m


def test_disjoint_split_children_get_distinct_arenas():
    """split() children deliberately share a context (the mailbox keys
    on source); their ARENAS must not — regression for the name
    collision that deadlocked hierarchical intra-node groups."""
    def prog(comm):
        half = comm.split(comm.rank // 2, key=comm.rank)
        out = half.allreduce(np.full(4, float(comm.rank)), algorithm="sm")
        names = {half._coll_sm_arena.name, comm._coll_sm_arena.name
                 if comm.__dict__.get("_coll_sm_arena") else None}
        return np.asarray(out)[0], half._coll_sm_arena.name

    res = run_shm_world(prog, 4)
    sums = [r[0] for r in res]
    assert sums == [1.0, 1.0, 5.0, 5.0]
    assert res[0][1] == res[1][1]
    assert res[2][1] == res[3][1]
    assert res[0][1] != res[2][1], "disjoint children shared one arena"


def test_arena_refcount_and_unlink_at_finalize():
    seen = {}

    def prog(comm):
        comm.allreduce(np.ones(8), algorithm="sm")
        if comm.rank == 0:
            name = comm._coll_sm_arena.name
            seen["live"] = dict(coll_sm.live_arenas())
            seen["file"] = glob.glob("/dev/shm" + name)
        comm.barrier()
        return True

    assert all(run_shm_world(prog, 3))
    # mid-world: 3 handles on one segment, the name present in /dev/shm
    assert list(seen["live"].values()) == [3]
    assert len(seen["file"]) == 1
    # world closed (run_shm_world closes every transport): registry
    # pruned, name unlinked
    assert coll_sm.live_arenas() == {}
    assert glob.glob(seen["file"][0]) == []


def test_cvar_kill_switch_and_eager_gate():
    old = mpit.cvar_read("coll_sm_arena_bytes")
    try:
        mpit.cvar_write("coll_sm_arena_bytes", 0)

        def prog(comm):
            # auto must fall back to the wire engine; explicit "sm" is
            # still an accepted NAME on shm (capability is per
            # transport), it just cannot be served
            a = comm.allreduce(np.ones(4))
            b = comm.allreduce(np.ones(4), algorithm="sm")
            return float(np.asarray(a)[0]), float(np.asarray(b)[0])

        h0 = mpit.pvar_read("coll_sm_hits")
        for a, b in run_shm_world(prog, 2):
            assert a == b == 2.0
        assert mpit.pvar_read("coll_sm_hits") == h0, \
            "kill switch did not disable the arena"
    finally:
        mpit.cvar_write("coll_sm_arena_bytes", old)
    assert mpit.cvar_read("coll_sm_eager_bytes") > 0  # registered


def test_nonblocking_collectives_skip_the_arena():
    """nbc clones are single-use: they must not map an arena per call
    (and must still complete on the wire path)."""
    def prog(comm):
        req = comm.iallreduce(np.full(4, float(comm.rank + 1)))
        comm.allreduce(np.ones(2), algorithm="sm")  # parent arena is fine
        return float(np.asarray(req.wait())[0])

    before = len(coll_sm.live_arenas())
    for got in run_shm_world(prog, 2):
        assert got == 3.0
    assert len(coll_sm.live_arenas()) == before  # no leaked nbc arenas


def test_retire_pooled_sweeps_lease_arenas_at_finalize():
    """ISSUE 12 satellite (closes PR-11 residual (d)): a POOLED lease
    arena whose worker set never re-leases is retired by nothing — only
    a NEW same-group lease under a bumped epoch sweeps it — so until
    the ``retire_pooled`` finalize sweep it held its /dev/shm segment
    mapped for the life of the worker process.  The sweep must retire
    exactly the pooled arenas (every handle force-unlinks: the creator
    may be a long-dead worker) and leave per-communicator arenas to the
    normal refcounted close path."""
    seen = {}

    def prog(comm):
        comm.allreduce(np.ones(4), algorithm="sm")  # per-comm arena
        lease = comm.split(0, key=comm.rank)
        lease._coll_sm_pool_ctx = ("lease-pool", 0)  # the serve stamp
        out = lease.allreduce(np.full(2, float(comm.rank)), algorithm="sm")
        pooled = lease._coll_sm_arena
        assert pooled._pooled and not comm._coll_sm_arena._pooled
        if comm.rank == 0:
            seen["file"] = glob.glob("/dev/shm" + pooled.name)
        comm.barrier()
        retired = coll_sm.retire_pooled(comm._t)
        comm.barrier()  # every handle closed before the unlink check
        if comm.rank == 0:
            seen["gone"] = glob.glob("/dev/shm" + pooled.name)
            seen["live"] = dict(coll_sm.live_arenas())
            seen["world_name"] = comm._coll_sm_arena.name
        # idempotent: the pool registry was pruned, a second sweep
        # (e.g. transport close re-walking _coll_arenas) finds nothing
        return float(np.asarray(out)[0]), retired, coll_sm.retire_pooled(
            comm._t)

    res = run_shm_world(prog, 3)
    assert [r[0] for r in res] == [3.0, 3.0, 3.0]
    assert [r[1] for r in res] == [1, 1, 1], "sweep missed a pooled arena"
    assert [r[2] for r in res] == [0, 0, 0]
    # the pooled segment existed mid-world and is unlinked by the sweep
    # while the world (and its per-communicator arena) is still alive
    assert len(seen["file"]) == 1
    assert seen["gone"] == []
    assert seen["world_name"] in seen["live"]
    # finalize then prunes the per-communicator arena as always
    assert coll_sm.live_arenas() == {}


def test_stale_arena_from_crashed_run_is_not_opened():
    """A crashed earlier run with the same session basename leaves its
    arena segment behind (ranks that die never close); the NEXT run's
    openers must not map it — the rendezvous readiness file (written by
    the creator AFTER unlink+create, like the ring handshake) closes the
    window that silently split the group across two same-named segments
    (regression: the FT kill e2e deadlock)."""
    import os
    import tempfile
    import threading

    from mpi_tpu.communicator import P2PCommunicator
    from mpi_tpu.native import load_shmring
    from mpi_tpu.transport.shm import ShmTransport

    rdv = tempfile.mkdtemp(prefix="mpi_tpu_stale_arena_")
    session = os.path.basename(rdv)
    # forge the stale segment a crashed run would leave: same name the
    # world communicator (ctx=0, group=(0,1)) will derive, magic set,
    # flags pre-poisoned so accidentally joining it would misbehave
    name = coll_sm._arena_name(session, 0, (0, 1))
    lib = load_shmring()
    stale = lib.shmarena_create(name.encode(), 1 << 16)
    assert stale
    lib.shmflag_post(int(lib.shmarena_addr(stale)) + 64, 999)
    lib.shmarena_close(stale)

    results, errors, transports = [None, None], [], [None, None]

    def runner(r):
        try:
            t = ShmTransport(r, 2, rdv, ring_bytes=256 * 1024)
            transports[r] = t
            comm = P2PCommunicator(t, range(2))
            results[r] = comm.allreduce(np.full(4, float(r + 1)),
                                        algorithm="sm")
        except BaseException as e:  # noqa: BLE001
            errors.append((r, e))

    threads = [threading.Thread(target=runner, args=(r,), daemon=True)
               for r in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(30.0)
    alive = any(th.is_alive() for th in threads)
    for t in transports:
        if t is not None:
            t.close()
    assert not errors, errors
    assert not alive, "ranks deadlocked across a stale arena segment"
    for res in results:
        np.testing.assert_allclose(res, np.full(4, 3.0))


# -- hierarchical composition (topology.split_hierarchical) ------------------


def test_hierarchical_dispatch_arena_intra_wire_inter():
    """Synthetic 2-nodes-of-2 on one box: each node's intra communicator
    serves collectives from its own arena while the leaders run the wire
    algorithms — allreduce/bcast/reduce/allgather/barrier parity."""
    def prog(comm):
        h = topology.HierarchicalComm(comm, node_key=lambda r: r // 2,
                                      inter_algorithm="rabenseifner")
        x = np.arange(6.0) + comm.rank
        out = {"ar": h.allreduce(x),
               "bc": h.bcast(np.full(3, 9.0) if comm.rank == 3 else None,
                             root=3),
               "rd": h.reduce(x, root=2),
               "ag": h.allgather(np.full(2, float(comm.rank)))}
        h.barrier()
        assert h.n_nodes == 2
        return out

    want = np.arange(6.0) * 4 + 6
    h0 = mpit.pvar_read("coll_sm_hits")
    for r, o in enumerate(run_shm_world(prog, 4)):
        np.testing.assert_allclose(o["ar"], want)
        np.testing.assert_array_equal(o["bc"], np.full(3, 9.0))
        if r == 2:
            np.testing.assert_allclose(o["rd"], want)
        else:
            assert o["rd"] is None
        np.testing.assert_array_equal(
            np.asarray(o["ag"]),
            np.stack([np.full(2, float(q)) for q in range(4)]))
    assert mpit.pvar_read("coll_sm_hits") > h0, \
        "hierarchical intra tier never hit the arena"


# -- fault tolerance: death mid-barrier is bounded ---------------------------


def test_kill_mid_barrier_raises_proc_failed_within_bound():
    """The FaultyTransport-style injection: the victim flips its
    transport's ``killed`` flag (detector stops beating) and never
    enters the barrier; survivors blocked in the arena flag wait get
    ProcFailedError naming the collective within the detection bound —
    never the shm stall constant, never a deadlock."""
    liveness = ft.MemoryLiveness(3)
    outcomes = {}

    def prog(comm):
        ft.enable(comm, liveness=liveness, detect_timeout_s=1.0,
                  heartbeat_s=0.1)
        comm.allreduce(np.ones(4), algorithm="sm")  # arena up, all alive
        if comm.rank == 2:
            comm._t.killed = True  # crash-stop: stops heartbeating
            return "died"
        t0 = time.monotonic()
        try:
            comm.barrier(algorithm="sm")
        except ProcFailedError as e:
            took = time.monotonic() - t0
            outcomes[comm.rank] = (took, e)
            return "detected"
        return "hung?"

    res = run_shm_world(prog, 3, timeout=30.0)
    assert res == ["detected", "detected", "died"]
    for rank, (took, exc) in outcomes.items():
        assert took < 10.0, f"rank {rank} took {took:.1f}s (bound is ~1s)"
        assert 2 in exc.failed
        assert exc.collective == "barrier"
