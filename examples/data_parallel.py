"""Data-parallel training via allreduce — the DP strategy expressed through
the message-passing library (SURVEY.md §2 strategy table: "the library
provides the collective, not the strategy; a DP demo belongs in examples/").

A small MLP regression trained with per-rank batch shards: each rank
computes local gradients with ``jax.grad``, gradients are averaged with the
hand-scheduled ring-allreduce (the north-star schedule), and every rank
applies the identical SGD step — the textbook DP loop.  A ZeRO-style
variant is one substitution away: ``comm.reduce_scatter`` + ``allgather``
instead of ``allreduce`` (both provided).

    python -m mpi_tpu.launcher -n 4 examples/data_parallel.py
    python examples/data_parallel.py --backend tpu -n 8
"""

import argparse
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np

from mpi_tpu import ops


def dp_train_program(comm, steps: int = 20, batch_per_rank: int = 32,
                     d_in: int = 8, d_hidden: int = 16, lr: float = 0.05):
    """Returns (final loss averaged over ranks, final params checksum)."""
    # identical init on every rank; comm.localize marks the params as
    # rank-LOCAL state so gradients stay local until the explicit allreduce
    # (on TPU, un-localized replicated params get auto-psum'd cotangents —
    # see Communicator.localize)
    kp = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(kp)
    params = comm.localize({
        "w1": jax.random.normal(k1, (d_in, d_hidden), jnp.float32) * 0.3,
        "w2": jax.random.normal(k2, (d_hidden, 1), jnp.float32) * 0.3,
    })
    # rank-local data shard of a fixed synthetic regression task
    kd = jax.random.fold_in(jax.random.PRNGKey(1), comm.rank)
    x = jax.random.normal(kd, (batch_per_rank, d_in), jnp.float32)
    y = jnp.sin(x.sum(axis=1, keepdims=True))

    def loss_fn(p):
        h = jnp.tanh(x @ p["w1"])
        return jnp.mean((h @ p["w2"] - y) ** 2)

    grad_fn = jax.value_and_grad(loss_fn)
    loss = jnp.float32(0.0)
    for _ in range(steps):
        loss, grads = grad_fn(params)
        # gradient sync: ring-allreduce then average — the DP collective
        grads = jax.tree.map(
            lambda g: comm.allreduce(g, op=ops.SUM, algorithm="ring") / comm.size,
            grads)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    mean_loss = comm.allreduce(loss, op=ops.SUM) / comm.size
    checksum = sum(jnp.sum(jnp.abs(v)) for v in params.values())
    return mean_loss, checksum


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None, choices=[None, "socket", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    out = mpi_tpu.run(dp_train_program, backend=args.backend, nranks=args.nranks,
                      steps=args.steps)
    first = out[0] if isinstance(out, list) else out
    loss = float(np.ravel(np.asarray(jax.device_get(first[0] if isinstance(first, tuple) else first)))[0])
    print(f"data-parallel training: final mean loss {loss:.5f} after {args.steps} steps")


if __name__ == "__main__":
    main()
