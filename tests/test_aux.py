"""Aux subsystem tests (SURVEY.md §5): comm tracing + matching verification,
fault injection, failure detection via recv timeouts, profiling helpers."""

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import ops
from mpi_tpu import checker
from mpi_tpu.trace import verify_run
from mpi_tpu.transport.base import RecvTimeout
from mpi_tpu.transport.faulty import FaultyTransport
from mpi_tpu.transport.local import run_local


# -- tracing / matching verification ---------------------------------------


def test_verify_run_clean_program():
    def prog(comm):
        v = comm.bcast("x" if comm.rank == 0 else None, root=0)
        s = comm.allreduce(np.float32(comm.rank))
        comm.barrier()
        return v, float(np.asarray(s))

    results, problems = verify_run(prog, 4)
    assert problems == []
    assert all(r == ("x", 6.0) for r in results)


def test_verify_run_detects_unreceived_send():
    def prog(comm):
        if comm.rank == 0:
            comm.send("orphan", dest=1, tag=7)  # rank 1 never receives

    _, problems = verify_run(prog, 2)
    assert any("never received" in p for p in problems)


def test_verify_run_traces_p2p_pattern():
    def prog(comm):
        right = (comm.rank + 1) % comm.size
        left = (comm.rank - 1) % comm.size
        return comm.sendrecv(comm.rank, dest=right, source=left)

    results, problems = verify_run(prog, 3)
    assert problems == []
    assert results == [2, 0, 1]


# -- fault injection + failure detection -----------------------------------


def test_dropped_message_surfaces_as_recv_timeout():
    def prog(comm):
        if comm.rank == 0:
            comm.send("will-be-dropped", dest=1, tag=0)
        else:
            return comm.recv(source=0, tag=0)

    with pytest.raises(RuntimeError, match="RecvTimeout|timed out"):
        run_local(prog, 2,
                  transport_wrapper=FaultyTransport.wrapper(drop_every=1),
                  recv_timeout=0.3)


def test_delay_injection_does_not_break_semantics():
    def prog(comm):
        return comm.allreduce(np.arange(4.0) + comm.rank, op=ops.SUM,
                              algorithm="ring")

    res = run_local(prog, 3,
                    transport_wrapper=FaultyTransport.wrapper(delay_s=0.002))
    expect = sum(np.arange(4.0) + r for r in range(3))
    for got in res:
        np.testing.assert_allclose(got, expect)


def test_duplicate_injection_detected_by_trace_matcher():
    """Duplicated messages leave unconsumed traffic behind — visible via the
    trace matcher (the sanitizer-style check).  The faulty layer must sit
    ABOVE tracing so the duplicate send is recorded."""
    import threading

    from mpi_tpu import checker
    from mpi_tpu.trace import TracingTransport

    traces = {}
    lock = threading.Lock()

    def wrapper(t):
        tt = TracingTransport(t)
        with lock:
            traces[t.world_rank] = tt
        return FaultyTransport(tt, duplicate_every=1)

    def prog(comm):
        if comm.rank == 0:
            comm.send("dup", dest=1, tag=0)
        else:
            comm.recv(source=0, tag=0)

    run_local(prog, 2, transport_wrapper=wrapper)
    logs = [traces[r].as_match_log() if r in traces else [] for r in range(2)]
    problems = checker.verify_matching(logs)
    assert any("never received" in p for p in problems), problems


def test_recv_timeout_reports_pending_messages():
    def prog(comm):
        if comm.rank == 0:
            comm.send("wrong-tag", dest=1, tag=5)
        else:
            comm.recv(source=0, tag=6)  # never sent

    with pytest.raises(RuntimeError, match="pending"):
        run_local(prog, 2, recv_timeout=0.3)


# -- profiling -------------------------------------------------------------


def test_timeit_measures():
    from mpi_tpu.profiling import timeit

    t = timeit(lambda: sum(range(1000)), iters=10, warmup=2)
    assert t.p50_s > 0
    assert t.p10_s <= t.p50_s <= t.p90_s
    assert t.n == 10


def test_comm_stats_json():
    from mpi_tpu.profiling import CommStats

    s = CommStats()
    s.record("allreduce", 4096)
    s.record("allreduce", 4096)
    s.record("bcast", 128)
    data = s.to_json()
    assert '"allreduce": 2' in data and '"bcast": 128' in data.replace("'", '"')


def test_jax_profiler_trace_smoke(tmp_path):
    import jax.numpy as jnp

    from mpi_tpu.profiling import trace

    with trace(str(tmp_path)):
        (jnp.arange(128.0) * 2).block_until_ready()
    assert any(tmp_path.iterdir()), "no profiler output written"


def test_verify_matching_flags_out_of_fifo_tag_match():
    """VERDICT r1 weak #6 / r2 weak #5 regression: a specific-tag recv
    whose tag only matches a send BEHIND the channel head must be flagged
    in strict mode (such a program deadlocks on a strict-FIFO channel
    transport), and accepted under envelope semantics."""
    logs = [
        [("send", 1, 1), ("send", 1, 2)],   # rank 0: tag 1 first, then 2
        [("recv", 0, 2), ("recv", 0, 1)],   # rank 1 pulls tag 2 FIRST
    ]
    problems = checker.verify_matching(logs)  # strict_fifo default
    assert any("out-of-FIFO" in p for p in problems), problems
    # MPI envelope semantics: legal, both matched, nothing left over
    assert checker.verify_matching(logs, strict_fifo=False) == []


def test_verify_matching_strict_passes_in_order_tags():
    """Differently-tagged traffic consumed in posted order stays clean."""
    logs = [
        [("send", 1, 1), ("send", 1, 2)],
        [("recv", 0, 1), ("recv", 0, 2)],
    ]
    assert checker.verify_matching(logs) == []
    # wildcards always take the head — clean in strict mode too
    logs = [
        [("send", 1, 7), ("send", 1, 8)],
        [("recv", -1, -1), ("recv", 0, 8)],
    ]
    assert checker.verify_matching(logs) == []


def test_verify_matching_wildcard_prefers_head_across_channels():
    """A wildcard-source recv whose tag matches another channel's HEAD is
    clean in strict mode even if the first candidate channel only matches
    deep in its queue (code-review regression: no false out-of-FIFO)."""
    logs = [
        [("send", 2, 3), ("send", 2, 5)],   # rank 0 -> 2: head tag 3
        [("send", 2, 5)],                   # rank 1 -> 2: head tag 5
        [("recv", -1, 5), ("recv", 0, 3), ("recv", 0, 5)],
    ]
    assert checker.verify_matching(logs) == []


# -- MPI_COMM_SELF + MPI_Get_count (round 3) --------------------------------


def test_comm_self_is_size_one_and_cached():
    import mpi_tpu
    from mpi_tpu import api

    s1 = api.MPI_COMM_SELF()
    assert s1.size == 1 and s1.rank == 0
    assert api.MPI_COMM_SELF() is s1
    assert mpi_tpu.COMM_SELF is s1
    # collectives are identities; p2p to self works
    assert s1.allreduce(5) == 5
    s1.send("x", dest=0, tag=3)
    assert s1.recv(source=0, tag=3) == "x"


def test_get_count_and_elements():
    import numpy as np

    from mpi_tpu import Status, api
    from mpi_tpu import datatypes as dt
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        if comm.rank == 0:
            comm.send(np.zeros(12, np.float64), dest=1)
            comm.send({"opaque": True}, dest=1)
            return None
        st = Status()
        comm.recv(source=0, status=st)
        pair = dt.type_contiguous(2, np.float64).commit()
        counts = (api.MPI_Get_count(st, np.float64),
                  api.MPI_Get_count(st, pair),
                  api.MPI_Get_count(st, np.float32),
                  api.MPI_Get_elements(st, pair))
        st2 = Status()
        comm.recv(source=0, status=st2)
        return counts, api.MPI_Get_count(st2, np.float64)

    res = run_local(prog, 2)
    (n64, npair, n32, nelem), opaque = res[1]
    assert n64 == 12 and npair == 6 and nelem == 12
    assert n32 == 24  # 96 bytes / 4
    assert opaque is None  # pickled dict: MPI_UNDEFINED


def test_comm_split_type_shared():
    from mpi_tpu import api

    def prog(comm):
        node = api.MPI_Comm_split_type(comm=comm)
        assert node.size == comm.size  # single-host worlds: whole comm
        assert node.allreduce(1) == comm.size
        with pytest.raises(ValueError, match="split_type"):
            api.MPI_Comm_split_type("numa", comm=comm)
        return True

    assert all(run_local(prog, 3))


def test_comm_split_type_shared_spmd_by_host(monkeypatch):
    """On the SPMD backend COMM_TYPE_SHARED splits by jax process
    (ADVICE r3 #4): a mesh whose axis spans two hosts yields per-host
    sub-communicators, not the whole comm."""
    from types import SimpleNamespace

    import numpy as np_

    from mpi_tpu.tpu import TpuCommunicator, default_mesh

    mesh = default_mesh(8)
    comm = TpuCommunicator("world", mesh)
    # all-CPU devices are one process: degenerates to the whole comm
    assert comm.split_type().size == 8
    # simulate a 2-host mesh (4 devices per process)
    fake = np_.array([SimpleNamespace(process_index=i // 4, id=i)
                      for i in range(8)])
    monkeypatch.setattr(comm, "mesh",
                        SimpleNamespace(axis_names=("world",), devices=fake,
                                        shape={"world": 8}))
    node = comm.split_type()
    assert node.size == 4
    assert node.axis_index_groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
    with pytest.raises(ValueError, match="split_type"):
        comm.split_type("numa")


def test_probe_reports_queued_count_not_stale(tmp_path):
    """probe/iprobe set count_bytes to the QUEUED message's real size
    (ADVICE r4 #2 — the canonical probe+get_count+recv buffer-sizing
    idiom), overwriting any stale count from a prior recv on a reused
    Status (the ADVICE r3 #1 leak stays fixed: the probed count is the
    probed MESSAGE's, never the previous receive's)."""
    import numpy as np_

    import mpi_tpu

    def prog(comm):
        if comm.rank == 0:
            comm.send(np_.zeros(16, np_.float64), 1, tag=5)
            comm.send(np_.zeros(4, np_.float64), 1, tag=6)
            comm.send({"opaque": True}, 1, tag=7)
            return True
        st = mpi_tpu.Status()
        comm.recv(0, tag=5, status=st)
        assert st.count_bytes == 128
        comm.probe(0, tag=6, status=st)
        # the queued tag-6 message's size — NOT the stale 128
        assert st.count_bytes == 32
        assert st.tag == 6
        # iprobe path too
        st2 = mpi_tpu.Status()
        st2.count_bytes = 999
        assert comm.iprobe(0, tag=6, status=st2)
        assert st2.count_bytes == 32
        # probe does not consume; recv agrees with the probed count
        comm.recv(0, tag=6, status=st)
        assert st.count_bytes == 32
        # opaque payloads still probe as MPI_UNDEFINED (None)
        comm.probe(0, tag=7, status=st)
        assert st.count_bytes is None
        comm.recv(0, tag=7)
        return True

    assert all(run_local(prog, 2))


def test_spawn_cleanup_preserves_live_child_world_dirs(tmp_path):
    """The parent's atexit cleanup must not delete a child WORLD's
    rendezvous dir while children still run (ADVICE r3 #3); the bridge
    dir (dead with the parent) always goes."""
    from types import SimpleNamespace

    from mpi_tpu import spawn as sp

    bridge = tmp_path / "bridge"; bridge.mkdir()
    child = tmp_path / "child"; child.mkdir()
    monkeypatch_state = (list(sp._spawned), list(sp._bridge_dirs),
                         list(sp._child_dirs))
    try:
        sp._spawned[:] = [SimpleNamespace(poll=lambda: None)]  # alive
        sp._bridge_dirs[:] = [str(bridge)]
        sp._child_dirs[:] = [str(child)]
        sp._cleanup()
        assert not bridge.exists()   # bridge reaped
        assert child.exists()        # child world preserved
        sp._spawned[:] = [SimpleNamespace(poll=lambda: 0)]  # all exited
        sp._cleanup()
        assert not child.exists()    # now safe to reap
    finally:
        sp._spawned[:], sp._bridge_dirs[:], sp._child_dirs[:] = \
            monkeypatch_state
