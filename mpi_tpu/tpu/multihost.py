"""Multi-host (DCN) support: COMM_WORLD over every host's devices.

On a TPU pod each host owns a subset of the chips; one process runs per
host, ``jax.distributed.initialize()`` wires them into one runtime, and a
``Mesh`` over ``jax.devices()`` (the GLOBAL device list) makes every
mpi_tpu communicator span hosts transparently — ``shard_map`` collectives
over a mesh axis compile to ICI transfers inside a host/slice and DCN
transfers across them.  Nothing in TpuCommunicator changes: the plugin
seam (SURVEY.md §1 L2/L1) absorbs the scale-out exactly as the north-star
demands.

Axis-layout guidance (the scaling-book recipe): put axes that carry the
heavy, latency-sensitive collectives (tensor/sequence parallel) on ICI —
the *inner* mesh dims — and bandwidth-tolerant axes (data/pipeline
parallel) on DCN — the *outer* dims.  ``hybrid_mesh`` builds exactly that
split from per-slice and cross-slice shapes.

Simulated multi-host on one machine: ``python -m mpi_tpu.tpu.multihost
-n 2 --devices-per-host 2 script.py`` spawns one clean CPU process per
"host" (gloo cross-process collectives — jax's real multi-process runtime,
the same code path a DCN pod exercises, minus the wires).  Inside the
script, ``auto_init()`` + ``global_mesh()`` are all that is needed; the
same two calls are correct unchanged on a real pod.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

ENV_COORD = "MPI_TPU_COORD"
ENV_NPROCS = "MPI_TPU_NPROCS"
ENV_PROC_ID = "MPI_TPU_PROC_ID"


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join the multi-process jax runtime (idempotent).

    On a real TPU pod all arguments are discovered from the environment —
    call with none.  On CPU (simulated hosts) pass coordinator/n/id, and
    cross-process collectives go through gloo."""
    import jax

    # N.B. nothing here may touch the backend (jax.devices/process_count/
    # default_backend all initialize it, and distributed init must come
    # first); decide the platform from config/env only.
    plat = (jax.config.jax_platforms or
            os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" in plat.split(","):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:  # second call in one process: keep the first
        if "already" not in str(e):
            raise


def auto_init() -> bool:
    """``init_distributed`` from the env the simulated-host launcher sets
    (no-op when absent → single-host).  Returns True iff multi-process."""
    coord = os.environ.get(ENV_COORD)
    if not coord:
        return False
    init_distributed(coord, int(os.environ[ENV_NPROCS]),
                     int(os.environ[ENV_PROC_ID]))
    return True


def global_mesh(axis_name: str = "world"):
    """1-D Mesh over ALL hosts' devices (jax.devices() is global after
    ``init_distributed``) — MPI_COMM_WORLD for the whole pod."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis_name,))


def hybrid_mesh(ici_shape: Sequence[int], dcn_shape: Sequence[int],
                axis_names: Tuple[str, ...]):
    """ICI×DCN mesh: ``ici_shape`` partitions each slice's devices (inner,
    fast), ``dcn_shape`` spans slices (outer, over the data-center
    network).  Heavy collectives belong on the ici axes."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if len(ici_shape) != len(dcn_shape) or len(ici_shape) != len(axis_names):
        raise ValueError(
            f"ici_shape {ici_shape}, dcn_shape {dcn_shape} and axis_names "
            f"{axis_names} must have one entry per mesh axis")
    if all(d == 1 for d in dcn_shape):
        # single slice/host: plain device mesh (hybrid helper requires >1
        # granule); same layout contract
        devs = mesh_utils.create_device_mesh(tuple(ici_shape),
                                             devices=jax.devices())
        return Mesh(devs, axis_names)
    # Multi-slice TPU devices carry distinct slice_index values (the DCN
    # granule).  CPU/sim devices all report slice 0, so there the process
    # is the granule — one simulated host == one DCN endpoint (matches
    # launch_sim_hosts' model).
    all_devs = jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) for d in all_devs}
    devs = mesh_utils.create_hybrid_device_mesh(
        tuple(ici_shape), tuple(dcn_shape), devices=all_devs,
        process_is_granule=len(slice_ids) <= 1)
    return Mesh(devs, axis_names)


# ---- simulated-host launcher ---------------------------------------------


def launch_sim_hosts(nhosts: int, argv: Sequence[str],
                     devices_per_host: int = 2,
                     timeout: Optional[float] = None) -> int:
    """Spawn ``nhosts`` clean CPU processes running ``python argv...``,
    wired into one jax runtime (the user script calls ``auto_init()``).
    Returns the first nonzero exit code, else 0."""
    import socket
    import subprocess
    import sys
    import time

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    # a clean CPU environment: site hooks that force-register accelerator
    # platforms read env at interpreter start, so scrub their trigger vars
    # and replace PYTHONPATH (which may carry the hook's site dir) with the
    # directory this mpi_tpu checkout lives in, so worker scripts can
    # `import mpi_tpu` without installing the package
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PALLAS_AXON", "AXON_"))}
    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_parent
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_host}")
    env[ENV_COORD] = f"127.0.0.1:{port}"
    env[ENV_NPROCS] = str(nhosts)

    procs = []
    for pid in range(nhosts):
        penv = dict(env)
        penv[ENV_PROC_ID] = str(pid)
        procs.append(subprocess.Popen([sys.executable, *argv], env=penv))
    deadline = None if timeout is None else time.monotonic() + timeout
    try:
        while True:
            codes = [p.poll() for p in procs]
            bad = [c for c in codes if c not in (None, 0)]
            if bad:
                return bad[0]
            if all(c == 0 for c in codes):
                return 0
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"hosts still running after {timeout}s")
            time.sleep(0.02)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="mpi_tpu.tpu.multihost",
        description="simulated multi-host launcher (one CPU process per "
                    "'host', gloo cross-process collectives)")
    parser.add_argument("-n", "--hosts", type=int, required=True)
    parser.add_argument("--devices-per-host", type=int, default=2)
    parser.add_argument("--timeout", type=float, default=None)
    parser.add_argument("script")
    parser.add_argument("script_args", nargs="*")
    args = parser.parse_args(argv)
    return launch_sim_hosts(args.hosts, [args.script, *args.script_args],
                            devices_per_host=args.devices_per_host,
                            timeout=args.timeout)


if __name__ == "__main__":
    import sys

    sys.exit(main())
