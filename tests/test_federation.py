"""Federated serve fabric (ISSUE 15): leader election, pool takeover,
admission policy, and the server-tier freeze matrix.

The unit half exercises the file-lease state machine and the admission
order in-process (deterministic, no subprocesses).  The e2e half runs
REAL ``launcher serve --federation`` subprocesses and mirrors the PR-10
rank-freeze matrix one tier up: a briefly-frozen leader keeps its lease
and NOBODY fails over; frozen past the bound → takeover + pool
adoption, and the thawed ex-leader detects usurpation and DEMOTES
(relinquishing its pool) instead of split-brain double-serving — the
leader-authority interval log is the split-brain assertion."""

import os
import signal
import subprocess
import sys
import time

import pytest

from mpi_tpu import federation, serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DETECT_S = 1.5
FED_LEASE_S = 2.0
# server + workers + pytest exceed this box's cores: the margins mirror
# tests/test_fault_tolerance.py's load-scaled bound
LOAD_MARGIN_S = 25.0 if (os.cpu_count() or 1) < 4 else 8.0


# -- leader lease (unit) ------------------------------------------------------


def test_leader_lease_lifecycle(tmp_path):
    """Acquire, contested tick, validity lapse, stale takeover with a
    term bump, thawed-holder demotion, clean release → re-acquire —
    and the interval log stays overlap-free throughout."""
    ns = str(tmp_path)
    a = federation.LeaderLease(ns, "A", lease_timeout_s=0.8)
    b = federation.LeaderLease(ns, "B", lease_timeout_s=0.8)
    assert a.tick() and a.is_leader()
    assert not b.tick() and not b.is_leader()
    assert a.tick()  # renew extends authority
    assert federation.read_leader(ns)["id"] == "A"
    # A freezes (stops ticking): authority lapses at validity_s, the
    # file goes stale at lease_timeout_s — strictly later
    time.sleep(0.5)
    assert not a.is_leader(), "authority must self-expire"
    assert not b.tick(), "takeover before the stale bound is forbidden"
    time.sleep(0.5)
    assert b.tick() and b.is_leader(), "stale lease must be taken over"
    assert b.term == a.term + 1
    assert b.takeovers == 1
    # the thawed ex-holder discovers foreign content and demotes
    assert not a.tick() and not a.is_leader()
    assert a.demotions == 1
    merged = federation.assert_no_leader_overlap(ns)
    assert [m["id"] for m in merged] == ["A", "B"]
    b.release()
    assert federation.read_leader(ns) is None
    assert a.tick() and a.is_leader()  # clean re-acquire after release
    # the released lease is a term tombstone: monotonicity survives it
    assert a.term == b.term + 1
    federation.assert_no_leader_overlap(ns)


def test_leader_takeover_race_single_winner(tmp_path):
    """Two contenders racing one stale lease: both unlink (idempotent),
    the O_EXCL create arbitrates — exactly one wins."""
    import threading

    ns = str(tmp_path)
    dead = federation.LeaderLease(ns, "dead", lease_timeout_s=0.3)
    assert dead.tick()
    time.sleep(0.5)  # stale now
    contenders = [federation.LeaderLease(ns, f"c{i}", lease_timeout_s=0.3)
                  for i in range(4)]
    barrier = threading.Barrier(len(contenders))
    results = {}

    def race(lease):
        barrier.wait()
        results[lease.owner_id] = lease.tick()

    threads = [threading.Thread(target=race, args=(c,))
               for c in contenders]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert sum(results.values()) == 1, results
    winner = [cid for cid, won in results.items() if won][0]
    assert federation.read_leader(ns)["id"] == winner
    federation.assert_no_leader_overlap(ns)


# -- admission policy (unit) --------------------------------------------------


def test_admission_order_policy():
    """The lease scheduler's pure ordering: strict priority first, then
    fair share (fewest grants per client), then FIFO."""
    w = [
        {"client": "a", "priority": 0, "nranks": 1, "seq": 1},
        {"client": "b", "priority": 0, "nranks": 1, "seq": 2},
        {"client": "vip", "priority": 2, "nranks": 1, "seq": 3},
        {"client": "a", "priority": 0, "nranks": 1, "seq": 4},
    ]
    # no grants yet: priority wins, then FIFO
    order = serve._admission_order(w, {})
    assert [x["seq"] for x in order] == [3, 1, 2, 4]
    # client a already got 5 grants: b (0 grants) outranks BOTH of a's
    # waiters at equal priority — that is the fair share
    order = serve._admission_order(w, {"a": 5})
    assert [x["seq"] for x in order] == [3, 2, 1, 4]


def test_priority_bumps_full_admission_queue():
    """The priority-aware door: with the bounded queue full of
    priority-0 waiters, a priority-1 acquire BUMPS the worst waiter
    (which raises the named ServerBusyError) instead of being locked
    out; the prioritized acquire then gets the next free slot."""
    import threading

    from mpi_tpu.errors import ServerBusyError

    with serve.WorldServer(pool_size=1, backend="socket",
                           detect_timeout_s=DETECT_S, heartbeat_s=0.2,
                           max_pending=1) as srv:
        hog = serve.connect(srv)
        low = serve.connect(srv)
        vip = serve.connect(srv, priority=1)
        try:
            hold = hog.acquire(1, timeout=10.0)  # pool now empty
            outcome = {}

            def low_wait():
                try:
                    lease = low.acquire(1, timeout=20.0)
                    outcome["low"] = "granted"
                    lease.release()
                except ServerBusyError:
                    outcome["low"] = "busy"

            th = threading.Thread(target=low_wait, daemon=True)
            th.start()
            deadline = time.monotonic() + 10.0
            while srv.stats()["waiting"] < 1:  # low is queued (full)
                assert time.monotonic() < deadline
                time.sleep(0.05)

            def vip_wait():
                lease = vip.acquire(1, timeout=20.0)
                outcome["vip"] = "granted"
                lease.release()

            tv = threading.Thread(target=vip_wait, daemon=True)
            tv.start()
            th.join(15.0)
            assert outcome.get("low") == "busy", outcome
            hold.release()  # frees the one slot → the vip waiter
            tv.join(15.0)
            assert outcome.get("vip") == "granted", outcome
            st = srv.stats()
            assert st["busy_rejected"] >= 1
        finally:
            hog.close()
            low.close()
            vip.close()


def test_relinquish_fails_queued_acquires_with_failover_signal():
    """A QUEUED acquire whose only possible pool is relinquished must
    fail immediately with the named ServerLostError (the failover
    signal), not stall to a LeaseTimeout the federated client treats
    as a live-server verdict."""
    import threading

    from mpi_tpu.serve import ServerLostError

    with serve.WorldServer(pool_size=1, backend="socket",
                           detect_timeout_s=DETECT_S,
                           heartbeat_s=0.2) as srv:
        hog = serve.connect(srv)
        waiter = serve.connect(srv)
        try:
            hold = hog.acquire(1, timeout=10.0)  # pool now empty
            outcome = {}

            def wait_acquire():
                t0 = time.monotonic()
                try:
                    waiter.acquire(1, timeout=30.0)
                    outcome["r"] = "granted"
                except ServerLostError:
                    outcome["r"] = "lost"
                except Exception as e:  # noqa: BLE001
                    outcome["r"] = type(e).__name__
                outcome["took"] = time.monotonic() - t0

            th = threading.Thread(target=wait_acquire, daemon=True)
            th.start()
            deadline = time.monotonic() + 10.0
            while srv.stats()["waiting"] < 1:
                assert time.monotonic() < deadline
                time.sleep(0.05)
            srv.relinquish_pool(srv._home, "usurper")
            th.join(15.0)
            assert outcome.get("r") == "lost", outcome
            assert outcome["took"] < 10.0, outcome  # no timeout stall
            hold  # the hog's lease died with the pool (named path
            # covered by the in-flight-job relinquish error synthesis)
        finally:
            hog.close()
            waiter.close()


def test_saturation_bounded_queue_and_fair_share():
    """The acceptance saturation row, small: beyond-capacity offered
    load yields bounded queue depth and named ServerBusyError
    rejections while the in-bound prioritized client keeps completing
    leases (its fair-share throughput never starves to zero)."""
    from benchmarks import chaos

    result = chaos.run_federation_saturation(quick=True)
    assert result["ok"], result
    assert result["busy_rejected_total"] > 0
    assert result["max_waiting_seen"] <= result["max_pending"]
    assert result["good_ok"] >= result["good_client_floor"]
    assert result["flood_timeout"] + result["flood_ok"] \
        + result["flood_busy"] > 0


# -- the server-tier freeze matrix (e2e, subprocess servers) ------------------


def _spawn_server(idx, ns, tmp, pool=2):
    addr_file = os.path.join(tmp, f"s{idx}.addr")
    log = open(os.path.join(tmp, f"s{idx}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "mpi_tpu.launcher", "serve",
         "--pool-size", str(pool), "--addr-file", addr_file,
         "--detect-timeout", str(DETECT_S), "--heartbeat", "0.2",
         "--federation", ns, "--fed-lease-timeout", str(FED_LEASE_S),
         "--server-id", f"s{idx}", "--orphan-timeout", "60"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=log, stderr=log)
    return {"proc": proc, "addr_file": addr_file, "log": log,
            "id": f"s{idx}"}


def _wait(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def _fabric_up(ns, servers):
    for s in servers:
        _wait(lambda: os.path.exists(s["addr_file"])
              and s["proc"].poll() is None,
              90.0 + LOAD_MARGIN_S, f"{s['id']} addr file")
        with open(s["addr_file"]) as f:
            s["addr"] = f.read().strip()
    _wait(lambda: len([r for r in
                       federation.read_server_records(ns).values()
                       if federation.record_live(r)]) == len(servers),
          30.0 + LOAD_MARGIN_S, "all endpoint records live")
    _wait(lambda: federation.read_leader(ns) is not None,
          15.0 + LOAD_MARGIN_S, "a leader")


def _teardown(servers):
    for s in servers:
        if s["proc"].poll() is None:
            s["proc"].kill()
    for s in servers:
        try:
            s["proc"].wait(10.0)
        except Exception:  # noqa: BLE001
            pass
        s["log"].close()


def test_leader_freeze_brief_keeps_lease(tmp_path):
    """SIGSTOP the leader for well under the lease bound, SIGCONT:
    NOBODY fails over — same leader, no takeover assignment, pool
    ownership unchanged, the fabric keeps serving, and the authority
    log shows no overlap (mirrors the PR-10 brief-rank-freeze row)."""
    ns = str(tmp_path / "ns")
    servers = [_spawn_server(i, ns, str(tmp_path)) for i in range(2)]
    try:
        _fabric_up(ns, servers)
        leader_id = federation.read_leader(ns)["id"]
        leader = next(s for s in servers if s["id"] == leader_id)
        owners_before = {p: r["owner"] for p, r
                         in federation.read_pool_owners(ns).items()}
        os.kill(leader["proc"].pid, signal.SIGSTOP)
        time.sleep(0.4 * FED_LEASE_S)
        os.kill(leader["proc"].pid, signal.SIGCONT)
        time.sleep(2.0 * FED_LEASE_S)  # several renew ticks
        assert federation.read_leader(ns)["id"] == leader_id, \
            "a brief freeze must not cost the lease"
        assert not [n for n in os.listdir(ns)
                    if n.startswith("takeover.")], "nobody failed over"
        owners_after = {p: r["owner"] for p, r
                        in federation.read_pool_owners(ns).items()}
        assert owners_after == owners_before
        federation.assert_no_leader_overlap(ns)
        with federation.FederatedClient(namespace=ns) as client:
            assert client.run(serve.job_allreduce, 64, nranks=2,
                              timeout=30.0) == 3.0
    finally:
        _teardown(servers)


def test_leader_freeze_past_bound_takeover_then_demote(tmp_path):
    """SIGSTOP the leader past the lease bound: the follower takes the
    lease (term bump) AND — the frozen server's endpoint record going
    stale is indistinguishable from death — adopts its pool.  On
    SIGCONT the thawed ex-leader must DEMOTE and RELINQUISH (its next
    renew sees foreign content; the namespace names a usurper with a
    newer ownership stamp), its orphaned workers re-register with the
    survivor, and at no point do two servers hold overlapping leader
    authority — two live leaders never both admit."""
    ns = str(tmp_path / "ns")
    servers = [_spawn_server(i, ns, str(tmp_path)) for i in range(2)]
    try:
        _fabric_up(ns, servers)
        leader_id = federation.read_leader(ns)["id"]
        leader = next(s for s in servers if s["id"] == leader_id)
        follower = next(s for s in servers if s["id"] != leader_id)
        os.kill(leader["proc"].pid, signal.SIGSTOP)
        # takeover: lease moves to the follower with a term bump...
        new = _wait(lambda: (federation.read_leader(ns) or {}).get(
            "id") == follower["id"] and federation.read_leader(ns),
            6.0 * FED_LEASE_S + LOAD_MARGIN_S, "lease takeover")
        assert new["term"] >= 2
        # ...and the frozen server's pool is adopted by the survivor
        _wait(lambda: all(
            r["owner"] == follower["id"] for r
            in federation.read_pool_owners(ns).values()),
            20.0 + LOAD_MARGIN_S, "pool adoption")
        # the fabric still serves DURING the freeze (survivor's pool)
        with federation.FederatedClient(namespace=ns) as client:
            assert client.run(serve.job_allreduce, 64, nranks=2,
                              timeout=30.0) == 3.0
        # the FROZEN-MASTER ESCAPE: a SIGSTOP'd server keeps its
        # workers' TCP connections ESTABLISHED, so EOF alone could
        # never free them — the orphans must notice the deposed
        # ownership record themselves and DEFECT to the survivor
        # while the ex-master is still frozen
        fhost, fport = follower["addr"].rsplit(":", 1)
        fclient = serve.ServerClient(fhost, int(fport))
        try:
            _wait(lambda: fclient.stats()["idle"] == 4,
                  30.0 + LOAD_MARGIN_S,
                  "orphans defected from the still-frozen master")
        finally:
            pass
        os.kill(leader["proc"].pid, signal.SIGCONT)
        # thawed ex-leader demotes + relinquishes what it already lost
        try:
            st = fclient.stats()
            assert st["pools_adopted"] >= 1
            assert st["orphans_reregistered"] >= 2
            assert st["is_leader"] is True
        finally:
            fclient.close()
        lhost, lport = leader["addr"].rsplit(":", 1)
        lclient = serve.ServerClient(lhost, int(lport))
        try:
            _wait(lambda: lclient.stats()["pools_relinquished"] >= 1,
                  15.0 + LOAD_MARGIN_S, "ex-leader relinquish")
            st = lclient.stats()
            assert st["is_leader"] is False, "thawed ex-leader demotes"
            assert not st["pools"], "relinquished pools are dropped"
        finally:
            lclient.close()
        assert federation.read_leader(ns)["id"] == follower["id"]
        # THE split-brain assertion: no two servers' self-believed
        # authority intervals ever overlapped, freeze included
        federation.assert_no_leader_overlap(ns)
        # and the survivor serves BOTH pools: two concurrent 2-rank
        # leases land on different pools (a lease never spans pools —
        # they are separate transport worlds) and both run correctly
        fclient2 = serve.ServerClient(fhost, int(fport))
        try:
            la = fclient2.acquire(2, timeout=15.0)
            lb = fclient2.acquire(2, timeout=15.0)
            assert la.pool != lb.pool, (la.pool, lb.pool)
            assert la.run(serve.job_allreduce, 64, timeout=30.0) == 3.0
            assert lb.run(serve.job_allreduce, 64, timeout=30.0) == 3.0
            la.release()
            lb.release()
        finally:
            fclient2.close()
    finally:
        _teardown(servers)


def test_restarted_server_reclaims_ghost_pool(tmp_path):
    """Restart-under-a-stable-id regression: with NO survivor to adopt
    (N=1 fabric), a SIGKILLed server's pool record keeps naming its id;
    the restarted incarnation renews the endpoint record (so no leader
    could ever judge the owner dead) — it must RECLAIM the ghost pool
    itself, bringing the previous incarnation's warm orphans home
    alongside its fresh home pool."""
    ns = str(tmp_path / "ns")
    servers = [_spawn_server(0, ns, str(tmp_path))]
    try:
        _fabric_up(ns, servers)
        old_pool = set(federation.read_pool_owners(ns))
        assert len(old_pool) == 1
        os.kill(servers[0]["proc"].pid, signal.SIGKILL)
        servers[0]["proc"].wait(10.0)
        # restart under the SAME --server-id (fresh addr/log dir)
        os.makedirs(str(tmp_path / "restart"), exist_ok=True)
        servers.append(_spawn_server(0, ns, str(tmp_path / "restart")))
        _fabric_up(ns, servers[1:])
        host, port = servers[1]["addr"].rsplit(":", 1)
        client = serve.ServerClient(host, int(port))
        try:
            # the ghost pool is reclaimed and its warm orphans
            # re-register: 2 (fresh home) + 2 (reclaimed) idle workers
            _wait(lambda: client.stats()["idle"] == 4,
                  40.0 + LOAD_MARGIN_S, "ghost pool reclaimed")
            st = client.stats()
            assert st["pools_adopted"] >= 1
            assert set(st["pools"]) >= old_pool
            assert st["orphans_reregistered"] >= 2
        finally:
            client.close()
    finally:
        _teardown(servers)


def test_retry_connect_retries_timeout_and_refused(monkeypatch):
    """ISSUE 15 satellite: the failover dial retries a connect TIMEOUT
    (socket.timeout is TimeoutError) and a refusal with backoff inside
    the budget; a zero budget keeps first-failure raise; non-transient
    errors propagate immediately."""
    import socket as _socket

    from mpi_tpu.resilience import retry_connect

    calls = {"n": 0}

    def flaky_dial():
        calls["n"] += 1
        if calls["n"] == 1:
            raise _socket.timeout("connect timed out")
        if calls["n"] == 2:
            raise ConnectionRefusedError("refused")
        return "sock"

    assert retry_connect(flaky_dial, timeout_s=10.0) == "sock"
    assert calls["n"] == 3

    with pytest.raises(TimeoutError):
        retry_connect(lambda: (_ for _ in ()).throw(
            _socket.timeout("slow")), timeout_s=0.0)

    def fatal_dial():
        raise OSError("no route to host")

    with pytest.raises(OSError, match="no route"):
        retry_connect(fatal_dial, timeout_s=10.0)
