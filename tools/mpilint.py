#!/usr/bin/env python
"""Static MPI linter CLI (mpi_tpu/verify/lint.py — MPI-Checker style,
v2: dataflow + communication-graph engine).

Flags, over any .py files or directories:

* MPL001 — divergent collective schedule across ranks (literal OR
  symbolic rank guards: ``r = comm.rank``, rank-conditional helpers);
* MPL002 — blocking send-send cycles between resolvable rank pairs
  (deadlock under synchronous sends);
* MPL003 — recv-count < send-count truncation in a matched pair;
* MPL004 — operations on a revoked comm (incl. aliases) without an
  error handler;
* MPL005 — nonblocking request never completed along some path;
* MPL006 — buffer written while its nonblocking request may be live;
* MPL007 — tag mismatch: a send whose matched receiver can never
  accept its tag;
* MPL008 — collective inside a loop whose trip count depends on rank;
* MPL009 — ANY_SOURCE recv with 2+ concurrent eligible senders
  (nondeterministic matching — the static half of the runtime
  wildcard-race detector).

Suppress a deliberate pattern with ``# mpilint: ok`` on (or right
above) the flagged line.  Exit code 1 iff findings remain (after
baseline subtraction, when --baseline is given).

``--format json`` emits a machine-readable report; ``--baseline
FILE.json`` loads a committed allowance (grouped by (file, code) with
a count and a rationale) and fails only on findings OUTSIDE it — the
CI workflow for deliberately-seeded test scenarios: new findings fail
the gate, fixed findings show up as stale-entry warnings prompting a
baseline shrink.

Usage::

    python tools/mpilint.py examples/ mpi_tpu/
    python tools/mpilint.py --select MPL001,MPL002 myprog.py
    python tools/mpilint.py --format json --baseline tools/lint_baseline.json \
        examples mpi_tpu tests benchmarks
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_tpu.verify.lint import lint_paths  # noqa: E402


def _norm(path: str) -> str:
    """Stable baseline key: repo-relative, forward slashes."""
    return os.path.relpath(path).replace(os.sep, "/")


def load_baseline(path: str) -> dict:
    """{(file, code): {"count": int, "why": str}} from the committed
    allowance file."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out = {}
    for e in data.get("entries", []):
        out[(e["file"], e["code"])] = {
            "count": int(e.get("count", 0)),
            "why": e.get("why", ""),
        }
    return out


def apply_baseline(findings, baseline):
    """(new_findings, stale_keys): findings not covered by the
    allowance, and allowance entries no finding used at all (candidates
    for deletion).  Per (file, code) group, up to ``count`` findings
    are absorbed; the overflow — a NEW instance of a baselined pattern
    — still fails."""
    groups = {}
    for f in findings:
        groups.setdefault((_norm(f.file), f.code), []).append(f)
    new = []
    for key, fs in sorted(groups.items()):
        allowed = baseline.get(key, {"count": 0})["count"]
        if len(fs) > allowed:
            new += fs[allowed:]
    used = {k for k in groups if k in baseline}
    stale = sorted(set(baseline) - used)
    new.sort(key=lambda f: (f.file, f.line, f.code))
    return new, stale


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+", help=".py files or directories")
    ap.add_argument("--select", default=None,
                    help="comma-separated codes to report (default: all)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="output format (default: text)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="committed allowance JSON: fail only on "
                         "findings outside it")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress the OK line")
    args = ap.parse_args(argv)
    findings = lint_paths(args.paths)
    if args.select:
        keep = {c.strip() for c in args.select.split(",")}
        findings = [f for f in findings if f.code in keep]

    stale = []
    gate = findings
    if args.baseline:
        baseline = load_baseline(args.baseline)
        gate, stale = apply_baseline(findings, baseline)

    if args.format == "json":
        doc = {
            "findings": [
                {"file": _norm(f.file), "line": f.line, "code": f.code,
                 "msg": f.msg} for f in findings],
            "new": [
                {"file": _norm(f.file), "line": f.line, "code": f.code,
                 "msg": f.msg} for f in gate],
            "stale_baseline": [{"file": k[0], "code": k[1]} for k in stale],
            "ok": not gate,
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 1 if gate else 0

    for f in gate:
        print(f.render())
    for k in stale:
        print(f"mpilint: warning: stale baseline entry {k[0]} {k[1]} "
              f"(no such finding remains — shrink the baseline)")
    if gate:
        what = "new finding(s)" if args.baseline else "finding(s)"
        print(f"mpilint: {len(gate)} {what}")
        return 1
    if not args.quiet:
        n = len(findings)
        base = f" ({n} baselined)" if args.baseline and n else ""
        print(f"mpilint: OK{base}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
