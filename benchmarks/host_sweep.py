#!/usr/bin/env python
"""OSU-style host data-plane size sweep (1KB -> 64MB) over real rank
processes — the artifact trail for the segmented collective engine.

Runs 2-rank sweeps on BOTH host transports (socket, shm) for the
bandwidth-bound collective family the segmented engine now covers:

* ``allreduce`` with all three hand-scheduled algorithms (ring,
  recursive_halving, rabenseifner) — from these rows it re-derives the
  ring/halving crossover backing the ``allreduce_ring_crossover_bytes``
  mpit cvar AND the large-message rabenseifner-vs-ring crossover backing
  ``allreduce_rabenseifner_crossover_bytes``;
* ``alltoall`` (windowed nonblocking pairwise exchange);
* ``reduce_scatter`` (segmented ring on one working buffer);

plus the 1KB latency legs that ground the shm-vs-socket small-message
inversion diagnosis (VERDICT r5 weak #1 / next-round #7).

Each (transport, bench, band) combination is ONE launcher invocation of
benchmarks/osu.py, so the measured program is exactly the shipping
benchmark, not a private reimplementation.

Usage::

    python benchmarks/host_sweep.py --label pre  --out benchmarks/results/host_sweep2_pre.json
    python benchmarks/host_sweep.py --label post --out benchmarks/results/host_sweep2_post.json
    python bench.py --sweep          # the post-change spelling used by CI
    python bench.py --sweep --quick  # smoke mode: 1KB, 1 sample (tier-1 test)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# iters shrink as sizes grow: at 64MB one allreduce moves ~64MB per rank
# per call, so a handful of samples already averages thousands of ring
# segments; at 1KB the per-call noise needs the larger population.
BANDS = [
    ("1KB,4KB,16KB,64KB", 40, 5),
    ("256KB,1MB,4MB", 12, 2),
    ("16MB,64MB", 5, 1),
]
# --quick smoke bands: tiny size, one sample — proves the harness end to
# end (launcher, osu CLI, row schema, crossover derivation) in seconds
QUICK_BANDS = [("1KB", 1, 0)]
TRANSPORTS = ("socket", "shm")
# bench -> algorithms swept.  Unknown algorithms (e.g. 'rabenseifner' on
# a pre-change checkout) surface as per-row "skipped" markers, so the
# same harness records both sides of a perf PR.
SWEEP_BENCHES = (
    ("allreduce", ("ring", "recursive_halving", "rabenseifner")),
    ("alltoall", ("pairwise",)),
    ("reduce_scatter", ("ring",)),
)

# Compute/communication overlap band (ISSUE 6): the osu_ialltoall-style
# overlap leg swept 1-16MB under BOTH progress modes on both host
# transports — the async progress engine's before/after artifact
# (benchmarks/results/osu_overlap_{pre,post}.json; 'pre' is the
# progress=none rows, byte-identical to the pre-engine code path).
OVERLAP_SIZES = "1MB:16MB:2"
OVERLAP_MODES = ("none", "thread")

# Persistent-collective band (ISSUE 12): osu_allreduce_persistent-shaped
# fresh-call vs ``start()`` re-fire p50s at the SMALL payloads the
# persistent hoist targets (the latency regime — large payloads are
# bandwidth-bound and the hoisted work vanishes in the transfer).
# Always under progress=thread; MPI_TPU_NBC selects the dispatch: the
# committed 'pre' artifact pins nbc=thread (today's one-thread-per-call
# start(), where the handle buys nothing) and 'post' nbc=auto (engine
# state machines, where the re-fire is the hot-loop win).
PERSIST_SIZES = "256,1KB,4KB,16KB"

# Small-message band (ISSUE 4 satellite): osu_latency / osu_barrier plus
# small allreduce swept 8B-64KB.  Small-message p50s are far less noisy
# on an oversubscribed box than the 64MB bandwidth cells — this is the
# band where the shared-memory collective arena's win is assertable.
# 'auto' records the shipping policy on each side of a perf PR; 'ring'
# pins the segmented-ring engine as the contemporary baseline.
SMALL_SIZES = "8,64,1KB,4KB,16KB,64KB"
SMALL_ALLREDUCE_ALGOS = "auto,ring"


def _osu_rows(backend: str, bench: str, sizes: str, algos: Optional[str],
              iters: int, warmup: int,
              env_extra: Optional[Dict[str, str]] = None) -> List[Dict]:
    from mpi_tpu.launcher import launch

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "rows.jsonl")
        argv = [os.path.join(REPO, "benchmarks", "osu.py"),
                "--bench", bench, "--backend", backend, "-n", "2",
                "--sizes", sizes, "--iters", str(iters),
                "--warmup", str(warmup), "--out", out]
        if algos:
            argv += ["--algorithms", algos]
        rc = launch(2, argv, env_extra=dict(env_extra or {}),
                    timeout=1800.0, backend=backend)
        if rc != 0:
            raise RuntimeError(f"{backend} {bench} sweep leg exited {rc}")
        with open(out) as f:
            return [json.loads(line) for line in f if line.strip()]


def collective_sweep(quick: bool = False) -> Dict[str, List[Dict]]:
    """bench-name -> rows, over every transport x band x algorithm."""
    bands = QUICK_BANDS if quick else BANDS
    out: Dict[str, List[Dict]] = {}
    for bench, algos in SWEEP_BENCHES:
        rows: List[Dict] = []
        for backend in TRANSPORTS:
            for sizes, iters, warmup in bands:
                rows += _osu_rows(backend, bench, sizes, ",".join(algos),
                                  iters, warmup)
        out[bench] = rows
    return out


def small_message_sweep(quick: bool = False) -> List[Dict]:
    """osu_latency + osu_barrier + small allreduce (8B-64KB), both host
    transports — the arena's before/after artifact band.  Rows carry
    ``leg`` = ``osu_latency`` / ``osu_barrier`` / ``osu_allreduce``."""
    sizes = "1KB" if quick else SMALL_SIZES
    iters, warmup = (1, 0) if quick else (120, 20)
    rows: List[Dict] = []
    for backend in TRANSPORTS:
        for leg, bench, szs, algos in (
                ("osu_latency", "latency", sizes, None),
                ("osu_barrier", "barrier", "1", None),
                ("osu_allreduce", "allreduce", sizes,
                 SMALL_ALLREDUCE_ALGOS)):
            for r in _osu_rows(backend, bench, szs, algos, iters, warmup):
                r["leg"] = leg
                rows.append(r)
    return rows


def overlap_sweep(quick: bool = False) -> List[Dict]:
    """The compute/communication overlap leg (benchmarks/osu.py
    ``--bench overlap``) on both host transports under progress=none
    AND progress=thread; each row records its mode.  The acceptance
    artifact of the async progress engine: on shm the thread mode's
    overlap_pct at the ring-stall sizes (>=8MB) is the engine's win,
    while the none rows are today's caller-financed behavior."""
    sizes = "1KB" if quick else OVERLAP_SIZES
    iters, warmup = (1, 0) if quick else (9, 2)
    rows: List[Dict] = []
    for backend in TRANSPORTS:
        for mode in OVERLAP_MODES:
            rows += _osu_rows(backend, "overlap", sizes, None, iters,
                              warmup, env_extra={"MPI_TPU_PROGRESS": mode})
    return rows


def persist_sweep(quick: bool = False, nbc_mode: str = "auto") -> List[Dict]:
    """The persistent-collective leg (benchmarks/osu.py ``--bench
    persist``) on both host transports under progress=thread: each row
    carries the fresh-call p50, the ``start()`` re-fire p50, and their
    ratio (``refire_speedup``), plus the nbc dispatch mode that produced
    it."""
    sizes = "1KB" if quick else PERSIST_SIZES
    # small-payload calls are sub-ms: a large population is cheap and
    # the median needs it on the oversubscribed reference box
    iters, warmup = (1, 0) if quick else (300, 30)
    rows: List[Dict] = []
    for backend in TRANSPORTS:
        rows += _osu_rows(backend, "persist", sizes, None, iters, warmup,
                          env_extra={"MPI_TPU_PROGRESS": "thread",
                                     "MPI_TPU_NBC": nbc_mode})
    return rows


# Receive-side zero-copy band (ISSUE 17): socket-only large-message
# latency + bi-bandwidth + ring-allreduce rows.  The 'pre' leg pins
# MPI_TPU_RECV_STEERING=0 (claiming off, channel accounting still on —
# byte-identical frame paths, so the contrast isolates the removed
# pool-stage copy), 'post' runs the default steering-on path.  The
# rendezvous win lives on the internal-tag collective leg; the p2p
# legs bound the recv pool's own (size-class recycling) effect.
RECVPOOL_P2P_SIZES = "1MB,4MB,16MB"
RECVPOOL_ALLREDUCE_SIZES = "4MB,16MB"


def recvpool_sweep(quick: bool = False, steering: int = 1) -> List[Dict]:
    env = {"MPI_TPU_RECV_STEERING": str(steering)}
    p2p = "1MB" if quick else RECVPOOL_P2P_SIZES
    ar = "1MB" if quick else RECVPOOL_ALLREDUCE_SIZES
    iters, warmup = (1, 0) if quick else (30, 5)
    rows: List[Dict] = []
    for leg, bench, szs, algos, it in (
            ("osu_latency", "latency", p2p, None, iters),
            ("osu_bibw", "bibw", p2p, None, max(1, iters // 2)),
            ("osu_allreduce", "allreduce", ar, "ring",
             max(1, iters // 2))):
        for r in _osu_rows("socket", bench, szs, algos, it, warmup,
                           env_extra=env):
            r["leg"] = leg
            r["recv_steering"] = steering
            rows.append(r)
    return rows


# Zero-copy-everywhere band (ISSUE 19): the pvar-asserted ``steer``
# bench (benchmarks/osu.py) on BOTH host transports — the shm ring
# drain now consults the same posted-recv registry the socket reader
# does, and user-buffer rendezvous / scatter-gather receives are part
# of the contract.  Rows carry the world-summed pvar deltas, so the
# committed artifact proves bytes-steered/copies-at-floor directly.
RECVPOOL_SHM_SIZES = "1MB,16MB"


def recvpool_shm_sweep(quick: bool = False, steering: int = 1) -> List[Dict]:
    env = {"MPI_TPU_RECV_STEERING": str(steering)}
    sizes = "64KB" if quick else RECVPOOL_SHM_SIZES
    iters, warmup = (1, 0) if quick else (15, 3)
    rows: List[Dict] = []
    for backend in TRANSPORTS:
        for r in _osu_rows(backend, "steer", sizes, None, iters, warmup,
                           env_extra=env):
            r["recv_steering"] = steering
            rows.append(r)
    return rows


def latency_diagnosis_legs() -> List[Dict]:
    """1KB ping-pong p50 on socket, shm(default spin), shm(spin off) and
    shm(long spin): separates the futex-wakeup cost (the spin knob removes
    it when a spare core can run the sender) from everything else."""
    legs = []
    for backend, env, label in (
        ("socket", None, "socket"),
        ("shm", None, "shm_default"),
        ("shm", {"MPI_TPU_SHM_SPIN_US": "0"}, "shm_spin_off"),
        ("shm", {"MPI_TPU_SHM_SPIN_US": "300"}, "shm_spin_300us"),
    ):
        try:
            rows = _osu_rows(backend, "latency", "1KB", None, 200, 20,
                             env_extra=env)
            for r in rows:
                r["leg"] = label
            legs += rows
        except Exception as e:  # noqa: BLE001 - a diag leg must not kill the sweep
            legs.append({"leg": label, "error": str(e)[:200]})
    return legs


def _algo_tables(rows: List[Dict]) -> Dict[str, Dict[int, Dict[str, float]]]:
    """transport -> size -> algorithm -> p50_us (measured rows only)."""
    out: Dict[str, Dict[int, Dict[str, float]]] = {}
    for r in rows:
        if r.get("backend") in TRANSPORTS and "p50_us" in r:
            out.setdefault(r["backend"], {}).setdefault(
                r["bytes"], {})[r["algorithm"]] = r["p50_us"]
    return out


def _stable_win_from(by_size: Dict[int, Dict[str, float]], winner: str,
                     loser: str) -> Optional[int]:
    """Smallest measured size from which ``winner``'s p50 stays at or
    below ``loser``'s for every larger measured size; None if never."""
    sizes = sorted(by_size)
    for i, s in enumerate(sizes):
        if all(winner in by_size[t] and loser in by_size[t]
               and by_size[t][winner] <= by_size[t][loser]
               for t in sizes[i:]):
            return s
    return None


def derive_crossover(rows: List[Dict]) -> Dict:
    """Per transport: the smallest size from which ring's p50 stays at or
    below recursive halving's for every larger measured size (the point
    the ``auto`` policy should switch); None if halving never loses."""
    out: Dict = {}
    tables = _algo_tables(rows)
    for backend in TRANSPORTS:
        by_size = tables.get(backend, {})
        out[backend] = {
            "crossover_bytes": _stable_win_from(by_size, "ring",
                                                "recursive_halving"),
            "table": {str(s): by_size[s] for s in sorted(by_size)},
        }
    return out


# rabenseifner-vs-ring derivation knobs.  The two schedules move
# IDENTICAL volume (2(P-1)/P·N per rank), so p50 ties are the expected
# steady state and a strict <=-everywhere rule would flip on single
# noise cells (this 2-core box swings mid-size shm p50s by 2-3x between
# runs — see ROADMAP "host engine follow-ups").  The crossover is
# therefore evaluated only in the bandwidth regime the constant governs
# (>= _RABEN_MIN_BYTES), tolerates ties up to _RABEN_TIE, and demands at
# least one strict win (< _RABEN_WIN) in the tail so a pure tie never
# flips the auto policy.
_RABEN_MIN_BYTES = 1 << 20
_RABEN_TIE = 1.10
_RABEN_WIN = 0.95


def derive_rabenseifner_crossover(rows: List[Dict]) -> Dict:
    """Per transport: the smallest bandwidth-regime size from which the
    rabenseifner composition's p50 stays within _RABEN_TIE of ring's at
    every larger measured size AND strictly beats ring somewhere in that
    tail; None if it never does.  ``combined_bytes`` (the engine
    constant _RABENSEIFNER_CROSSOVER_BYTES / the
    allreduce_rabenseifner_crossover_bytes cvar) is the max over
    transports — the composition must not regress either data plane."""
    out: Dict = {}
    crossovers: List[Optional[int]] = []
    tables = _algo_tables(rows)
    for backend in TRANSPORTS:
        by_size = tables.get(backend, {})
        sizes = [s for s in sorted(by_size)
                 if s >= _RABEN_MIN_BYTES
                 and {"ring", "rabenseifner"} <= set(by_size[s])]
        crossover = None
        for i, s in enumerate(sizes):
            tail = [by_size[t]["rabenseifner"] / by_size[t]["ring"]
                    for t in sizes[i:]]
            if all(q <= _RABEN_TIE for q in tail) and \
                    any(q < _RABEN_WIN for q in tail):
                crossover = s
                break
        crossovers.append(crossover)
        out[backend] = {
            "crossover_bytes": crossover,
            "table": {str(s): by_size[s] for s in sorted(by_size)},
        }
    out["combined_bytes"] = (None if any(c is None for c in crossovers)
                             else max(crossovers))
    return out


def run_sweep(label: str, quick: bool = False) -> Dict:
    t0 = time.time()
    benches = collective_sweep(quick=quick)
    rows = benches["allreduce"]
    result = {
        "label": label,
        "quick": quick,
        "nranks": 2,
        "cpus": os.cpu_count(),
        # 2 rank processes + the sweep driver (see osu.run_bench)
        "oversubscribed": 3 > (os.cpu_count() or 1),
        "allreduce_rows": rows,
        "alltoall_rows": benches["alltoall"],
        "reduce_scatter_rows": benches["reduce_scatter"],
        "small_message_rows": small_message_sweep(quick=quick),
        "overlap_rows": overlap_sweep(quick=quick),
        "persist_rows": persist_sweep(quick=quick),
        "crossover": derive_crossover(rows),
        "rabenseifner_crossover": derive_rabenseifner_crossover(rows),
        "wall_s": round(time.time() - t0, 1),
    }
    if not quick:
        result["latency_1kb_legs"] = latency_diagnosis_legs()
    return result


def _band_result(label: str, quick: bool, rows_key: str, rows_fn) -> Dict:
    """Shared envelope of the single-band sweeps (small-message,
    overlap): one place for the nranks / oversubscription accounting so
    the committed artifacts' stamps can never diverge between bands."""
    t0 = time.time()
    return {
        "label": label,
        "quick": quick,
        "nranks": 2,
        "cpus": os.cpu_count(),
        # 2 rank processes + the sweep driver (see osu.run_bench)
        "oversubscribed": 3 > (os.cpu_count() or 1),
        rows_key: rows_fn(quick=quick),
        "wall_s": round(time.time() - t0, 1),
    }


def run_small_sweep(label: str, quick: bool = False) -> Dict:
    """Just the small-message band — the arena PR's pre/post artifact
    (committed as benchmarks/results/osu_small_{pre,post}.json)."""
    return _band_result(label, quick, "small_message_rows",
                        small_message_sweep)


def run_overlap_sweep(label: str, quick: bool = False) -> Dict:
    """Just the overlap band — the async progress engine's pre/post
    artifact (committed as benchmarks/results/osu_overlap_{pre,post}
    .json: 'pre' holds the progress=none rows, 'post' the thread
    rows)."""
    return _band_result(label, quick, "overlap_rows", overlap_sweep)


def run_persist_sweep(label: str, quick: bool = False) -> Dict:
    """Just the persistent-collective band — the engine-owned-nbc PR's
    pre/post artifact (committed as benchmarks/results/persist_{pre,
    post}.json): 'pre' pins MPI_TPU_NBC=thread (per-call threads, the
    seed semantics), 'post' nbc=auto (schedule state machines)."""
    mode = "thread" if label == "pre" else "auto"
    return _band_result(
        label, quick, "persist_rows",
        lambda quick: persist_sweep(quick=quick, nbc_mode=mode))


def run_recvpool_sweep(label: str, quick: bool = False) -> Dict:
    """Just the receive-side zero-copy band — the recv-pool/rendezvous
    PR's pre/post artifact (committed as benchmarks/results/recvpool_
    {pre,post}.json): 'pre' pins MPI_TPU_RECV_STEERING=0 (pool-stage
    copy on every receive), 'post' runs the default steering path."""
    steering = 0 if label == "pre" else 1
    return _band_result(
        label, quick, "recvpool_rows",
        lambda quick: recvpool_sweep(quick=quick, steering=steering))


def run_recvpool_shm_sweep(label: str, quick: bool = False) -> Dict:
    """The zero-copy-everywhere band — ISSUE 19's pre/post artifact
    (committed as benchmarks/results/recvpool_shm_{pre,post}.json):
    'pre' pins MPI_TPU_RECV_STEERING=0, 'post' runs the default
    steering path; rows carry world-summed pvar deltas per leg
    (allreduce_ring / user_irecv / scatter_gather, both transports)."""
    steering = 0 if label == "pre" else 1
    return _band_result(
        label, quick, "recvpool_shm_rows",
        lambda quick: recvpool_shm_sweep(quick=quick, steering=steering))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--label", default="post")
    ap.add_argument("--out", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="smoke mode: 1KB only, 1 sample, no latency legs")
    ap.add_argument("--small", action="store_true",
                    help="small-message band only (osu_latency/osu_barrier/"
                         "small allreduce) — the arena pre/post artifact")
    ap.add_argument("--overlap", action="store_true",
                    help="overlap band only (ialltoall + fixed compute, "
                         "both progress modes) — the async progress "
                         "engine's pre/post artifact")
    ap.add_argument("--persist", action="store_true",
                    help="persistent-collective band only (fresh call vs "
                         "start() re-fire; --label pre pins nbc=thread, "
                         "post nbc=auto) — the engine-owned-nbc pre/post "
                         "artifact")
    ap.add_argument("--recvpool", action="store_true",
                    help="receive-side zero-copy band only (socket "
                         "latency/bibw/ring-allreduce at 1-16MB; --label "
                         "pre pins MPI_TPU_RECV_STEERING=0) — the "
                         "recv-pool rendezvous pre/post artifact")
    ap.add_argument("--shm", action="store_true",
                    help="with --recvpool: the zero-copy-everywhere band "
                         "(pvar-asserted steer legs on BOTH transports, "
                         "incl. shm ring steering, user irecv(buf=) and "
                         "scatter-gather) — ISSUE 19's pre/post artifact")
    args = ap.parse_args(argv)
    result = (run_recvpool_shm_sweep(args.label, quick=args.quick)
              if args.recvpool and args.shm
              else run_recvpool_sweep(args.label, quick=args.quick)
              if args.recvpool
              else run_persist_sweep(args.label, quick=args.quick)
              if args.persist
              else run_overlap_sweep(args.label, quick=args.quick)
              if args.overlap
              else run_small_sweep(args.label, quick=args.quick)
              if args.small
              else run_sweep(args.label, quick=args.quick))
    text = json.dumps(result, indent=2)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
