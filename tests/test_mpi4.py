"""MPI-4 previews (mpi_tpu/mpi4.py): persistent collectives and
partitioned point-to-point."""

import threading

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import api, mpi4
from mpi_tpu.transport.local import run_local


# -- persistent collectives --------------------------------------------------


def test_persistent_allreduce_many_rounds():
    """One plan, many starts; buffer CONTENT is read at start time."""
    def prog(comm):
        x = np.ones(4)
        plan = mpi4.persistent_collective(comm, "allreduce", x)
        outs = []
        for round_ in range(3):
            x[:] = round_ + 1  # mutate between starts: start sees it
            outs.append(plan.start().wait())
        return outs

    res = run_local(prog, 3)
    for outs in res:
        for round_, out in enumerate(outs):
            assert np.array_equal(out, np.full(4, 3.0 * (round_ + 1)))


def test_persistent_bcast_and_barrier_api():
    def prog(comm):
        plan = api.MPI_Bcast_init({"v": comm.rank}, root=1, comm=comm)
        got = plan.start().wait()
        bar = api.MPI_Barrier_init(comm=comm)
        bar.start().wait()
        return got

    res = run_local(prog, 3)
    assert all(r == {"v": 1} for r in res)


def test_persistent_collective_discipline():
    def prog(comm):
        plan = mpi4.persistent_collective(comm, "barrier")
        with pytest.raises(RuntimeError, match="before start"):
            plan.wait()
        with pytest.raises(ValueError, match="unknown collective"):
            mpi4.persistent_collective(comm, "frobnicate")
        plan.start()
        plan.wait()
        plan.start()  # restart after completion is the whole point
        plan.wait()
        return True

    run_local(prog, 2)


def test_persistent_rejected_on_spmd():
    def prog(comm):
        with pytest.raises(NotImplementedError, match="already a plan"):
            mpi4.persistent_collective(comm, "allreduce", 1)
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


# -- partitioned point-to-point ----------------------------------------------


def test_partitioned_out_of_order_pready():
    """Partitions readied out of order arrive and assemble in partition
    order; parrived polls without blocking."""
    def prog(comm):
        n = 4
        if comm.rank == 0:
            buf = np.arange(n * 3.0).reshape(n, 3)
            ps = mpi4.psend_init(comm, buf, n, dest=1, tag=5)
            ps.start()
            for i in (2, 0, 3, 1):
                ps.pready(i)
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, n, source=0, tag=5)
        pr.start()
        parts = pr.wait()
        return np.stack(parts)

    res = run_local(prog, 2)
    assert np.array_equal(res[1], np.arange(12.0).reshape(4, 3))


def test_partitioned_producer_threads():
    """The MPI-4 use case: different producer threads contribute
    different partitions of ONE message."""
    def prog(comm):
        n = 6
        if comm.rank == 0:
            buf = [None] * n
            ps = mpi4.psend_init(comm, buf, n, dest=1)
            ps.start()

            def producer(lo, hi):
                for i in range(lo, hi):
                    buf[i] = ("part", i)
                    ps.pready(i)

            t1 = threading.Thread(target=producer, args=(0, 3))
            t2 = threading.Thread(target=producer, args=(3, 6))
            t1.start(); t2.start(); t1.join(); t2.join()
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, n, source=0)
        pr.start()
        return pr.wait()

    res = run_local(prog, 2)
    assert res[1] == [("part", i) for i in range(6)]


def test_partitioned_parrived_and_partition():
    def prog(comm):
        if comm.rank == 0:
            comm.recv(source=1, tag=9)  # wait for "ready 1 shipped"
            ps = mpi4.psend_init(comm, [10, 20], 2, dest=1)
            ps.start()
            ps.pready(1)
            comm.send("shipped-1", dest=1, tag=9)
            comm.recv(source=1, tag=9)
            ps.pready(0)
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        pr.start()
        comm.send("go", dest=0, tag=9)
        comm.recv(source=0, tag=9)
        # partition 1 shipped; partition 0 not yet
        for _ in range(2000):
            if pr.parrived(1):
                break
        assert pr.parrived(1) and pr.partition(1) == 20
        assert not pr.parrived(0)
        comm.send("more", dest=0, tag=9)
        out = pr.wait()
        assert out == [10, 20]
        return True

    run_local(prog, 2)


def test_partitioned_multiple_pairs_same_tag_isolated():
    """Two psend/precv pairs on the SAME (peer, tag) match in init order
    (private contexts): payloads can never interleave."""
    def prog(comm):
        if comm.rank == 0:
            a = mpi4.psend_init(comm, ["a0", "a1"], 2, dest=1, tag=1)
            b = mpi4.psend_init(comm, ["b0", "b1"], 2, dest=1, tag=1)
            a.start(); b.start()
            b.pready(0); a.pready(1); b.pready(1); a.pready(0)
            a.wait(); b.wait()
            return None
        a = mpi4.precv_init(comm, 2, source=0, tag=1)
        b = mpi4.precv_init(comm, 2, source=0, tag=1)
        a.start(); b.start()
        return a.wait(), b.wait()

    res = run_local(prog, 2)
    assert res[1] == (["a0", "a1"], ["b0", "b1"])


def test_partitioned_wait_names_missing_partitions():
    def prog(comm):
        ps = mpi4.psend_init(comm, [1, 2, 3], 3, dest=0)
        ps.start()
        ps.pready(1)
        with pytest.raises(RuntimeError, match="never marked ready"):
            ps.wait()
        # drain so finalize's sanitizer stays quiet: complete the round
        ps.pready(0); ps.pready(2); ps.wait()
        pr = mpi4.precv_init(comm, 3, source=0)
        pr.start()
        pr.wait()
        return True

    run_local(prog, 1)


def test_partitioned_rounds_do_not_cross():
    """Round 2's partitions must not be drained into round 1 (review
    round 3 — reproduced corruption before the bounded drain)."""
    def prog(comm):
        if comm.rank == 0:
            ps = mpi4.psend_init(comm, ["r1p0", "r1p1"], 2, dest=1)
            ps.start(); ps.pready(0); ps.pready(1); ps.wait()
            # race straight into round 2 before the receiver drains
            ps.start()
            ps2buf = ["r2p0", "r2p1"]
            ps._buf = ps2buf
            ps.pready(0); ps.pready(1); ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        pr.start()
        import time
        time.sleep(0.1)  # let BOTH rounds land in the mailbox
        for _ in range(1000):
            done, res = pr.test()
            if done:
                break
        assert res == ["r1p0", "r1p1"], res
        pr.start()
        assert pr.wait() == ["r2p0", "r2p1"]
        return True

    run_local(prog, 2)


def test_partitioned_test_completes_round():
    """test() returning True deactivates (MPI semantics): start() may
    follow without wait(); wait() after test returns the cached result."""
    def prog(comm):
        if comm.rank == 0:
            ps = mpi4.psend_init(comm, [1, 2], 2, dest=1)
            ps.start(); ps.pready(0); ps.pready(1)
            done, _ = ps.test()
            assert done
            ps.start()  # no wait() needed after a successful test
            ps.pready(0); ps.pready(1); ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        assert pr.test() == (True, None)  # inactive tests True
        pr.start()
        while True:
            done, res = pr.test()
            if done:
                break
        assert res == [1, 2]
        assert pr.wait() == [1, 2]  # cached result after test-completion
        pr.start()
        assert pr.wait() == [1, 2]
        return True

    run_local(prog, 2)


def test_partitioned_snapshot_on_aliasing_transport():
    """pready snapshots on by-reference transports: refilling the buffer
    after pready must not mutate what the receiver sees."""
    def prog(comm):
        if comm.rank == 0:
            buf = np.zeros((2, 3))
            ps = mpi4.psend_init(comm, buf, 2, dest=1)
            ps.start()
            buf[0] = 1.0
            ps.pready(0)
            buf[0] = 99.0  # refill immediately — receiver must see 1.0
            buf[1] = 2.0
            ps.pready(1)
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        pr.start()
        parts = pr.wait()
        return np.stack(parts)

    res = run_local(prog, 2, copy_payloads=False)
    assert np.array_equal(res[1], [[1.0] * 3, [2.0] * 3])


# -- sessions (MPI-4 ch.11, VERDICT r3 next #8) ------------------------------


def test_session_pset_discovery():
    def prog(comm):
        with mpi4.session_init(base_comm=comm) as s:
            names = [s.get_nth_pset(i) for i in range(s.get_num_psets())]
            gw = s.group_from_pset("mpi://WORLD")
            gs = s.group_from_pset("mpi://SELF")
            return names, gw.ranks, gs.ranks

    res = run_local(prog, 3)
    for r, (names, wranks, sranks) in enumerate(res):
        assert names == ["mpi://WORLD", "mpi://SELF"]
        assert list(wranks) == [0, 1, 2]
        assert list(sranks) == [r]


def test_session_comm_from_group_full_flow():
    """The sessions init story end-to-end: runtime handle → pset →
    group → communicator → collective, COMM_WORLD never touched."""
    def prog(comm):
        s = mpi4.session_init(base_comm=comm)
        g = s.group_from_pset("mpi://WORLD")
        c = s.comm_create_from_group(g, stringtag="org.example.lib")
        out = c.allreduce(c.rank + 1)
        s.finalize()
        return out

    res = run_local(prog, 4)
    assert res == [10, 10, 10, 10]


def test_session_subset_group_non_collective():
    """comm_create_from_group is collective over the GROUP ONLY: the
    even ranks build their comm while odd ranks do something else
    entirely — no parent-communicator collective anywhere."""
    def prog(comm):
        s = mpi4.session_init(base_comm=comm)
        if comm.rank % 2 == 0:
            from mpi_tpu.group import Group

            c = s.comm_create_from_group(Group([0, 2]), "evens")
            return ("even", c.allreduce(comm.rank))
        return ("odd", None)

    res = run_local(prog, 4)
    assert res[0] == ("even", 2) and res[2] == ("even", 2)
    assert res[1] == ("odd", None) and res[3] == ("odd", None)


def test_session_stringtag_isolates_contexts():
    """Two communicators over the SAME group with different stringtags
    exchange concurrently without cross-matching (the MPI-4
    (group, stringtag) disambiguation rule as context isolation)."""
    def prog(comm):
        s = mpi4.session_init(base_comm=comm)
        g = s.group_from_pset("mpi://WORLD")
        a = s.comm_create_from_group(g, "liba")
        b = s.comm_create_from_group(g, "libb")
        # interleave: start both broadcasts in opposite rank order
        ra = a.bcast(("A", comm.rank), 0)
        rb = b.bcast(("B", comm.rank), 1)
        return ra, rb

    res = run_local(prog, 3)
    for ra, rb in res:
        assert ra == ("A", 0)
        assert rb == ("B", 1)


def test_session_self_pset_and_errors():
    def prog(comm):
        s = mpi4.session_init(base_comm=comm)
        gs = s.group_from_pset("mpi://SELF")
        c = s.comm_create_from_group(gs, "private")
        assert c.size == 1 and c.allreduce(7) == 7
        with pytest.raises(ValueError, match="unknown process set"):
            s.group_from_pset("mpi://NOPE")
        # non-member cannot derive a comm from a group excluding it
        if comm.rank == 1:
            from mpi_tpu.group import Group

            with pytest.raises(ValueError, match="not in the group"):
                s.comm_create_from_group(Group([0]), "x")
        s.finalize()
        s.finalize()  # idempotent
        with pytest.raises(RuntimeError, match="finalized"):
            s.get_num_psets()
        return True

    assert all(run_local(prog, 2))


def test_session_flat_api():
    def prog(comm):
        s = api.MPI_Session_init(info={"thread_level": "single"})
        # flat default-runtime path needs the world singleton; inject by
        # swapping the base explicitly instead (the library spelling)
        s = mpi4.session_init(info={"k": "v"}, base_comm=comm)
        assert api.MPI_Session_get_num_psets(s) == 2
        assert api.MPI_Session_get_nth_pset(s, 0) == "mpi://WORLD"
        assert api.MPI_Session_get_info(s) == {"k": "v"}
        g = api.MPI_Group_from_session_pset(s, "mpi://WORLD")
        c = api.MPI_Comm_create_from_group(g, "tag", session=s)
        out = c.allreduce(1)
        api.MPI_Session_finalize(s)
        return out

    assert run_local(prog, 3) == [3, 3, 3]


def test_session_library_example_local_and_launcher(tmp_path):
    """examples/session_library.py: two session-scoped libraries + the
    application share one world without interference — identical results
    in-process (threads) and over real launcher rank processes."""
    import json
    import subprocess
    import sys

    from examples.session_library import session_program

    n = 3
    want_mean = sum(range(1, n + 1)) / n
    want_ringsum = float(sum(range(n)))
    for mean, ringsum, token in run_local(session_program, n):
        assert (mean, ringsum, token) == (want_mean, want_ringsum, "app")

    out = tmp_path / "out.jsonl"
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import json, os, sys\n"
        f"sys.path.insert(0, {repr('/root/repo')})\n"
        "import mpi_tpu\n"
        "from examples.session_library import session_program\n"
        "comm = mpi_tpu.COMM_WORLD\n"
        "res = session_program(comm)\n"
        f"open({repr(str(out))} + str(comm.rank), 'w')"
        ".write(json.dumps(res))\n")
    r = subprocess.run(
        [sys.executable, "-m", "mpi_tpu.launcher", "-n", str(n), str(prog)],
        cwd="/root/repo", capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-800:]
    for rank in range(n):
        mean, ringsum, token = json.loads(
            open(str(out) + str(rank)).read())
        assert (mean, ringsum, token) == (want_mean, want_ringsum, "app")


def test_session_on_reordered_base_comm():
    """Sessions over a base comm whose LOCAL rank order differs from the
    world's (review round 4): group ranks are base-local and must be
    translated to world ranks — untranslated they either raise at
    construction or wire the communicator to the wrong processes."""
    def prog(comm):
        rev = comm.split(0, key=-comm.rank)  # world order reversed
        s = mpi4.session_init(base_comm=rev)
        c = s.comm_create_from_group(s.group_from_pset("mpi://WORLD"),
                                     "rev")
        total = c.allreduce(comm.rank)
        cs = s.comm_create_from_group(s.group_from_pset("mpi://SELF"),
                                      "me")
        return total, cs.size, c.rank

    res = run_local(prog, 3)
    for r, (total, ssz, crank) in enumerate(res):
        assert total == 3          # full world reduced: 0+1+2
        assert ssz == 1            # SELF pset is really just me
        assert crank == 2 - r      # comm ordered by the reversed base


# -- MPI-4 nonblocking sendrecv ----------------------------------------------


def test_isendrecv_ring():
    """MPI_Isendrecv (MPI-4): nonblocking ring halo exchange — post,
    overlap 'compute', then wait for the neighbor's payload."""
    def prog(comm):
        right = (comm.rank + 1) % comm.size
        left = (comm.rank - 1) % comm.size
        req = comm.isendrecv(np.full(3, comm.rank), right, left)
        local = float(comm.rank) ** 2  # overlapped work
        got = req.wait()
        return float(got[0]), local

    res = run_local(prog, 4)
    for r, (got, _) in enumerate(res):
        assert got == (r - 1) % 4


def test_isendrecv_replace_in_place():
    def prog(comm):
        right = (comm.rank + 1) % comm.size
        left = (comm.rank - 1) % comm.size
        buf = np.full(2, comm.rank, np.float64)
        req = comm.isendrecv_replace(buf, right, left)
        got = req.wait()
        # buf now holds the neighbor's (pre-snapshot) payload
        assert np.array_equal(buf, got)
        return float(buf[0])

    res = run_local(prog, 3)
    assert res == [2.0, 0.0, 1.0]


def test_isendrecv_flat_api_and_spmd_diagnostic():
    from mpi_tpu.tpu import SpmdSemanticsError, run_spmd

    def prog(comm):
        req = api.MPI_Isendrecv(comm.rank, (comm.rank + 1) % comm.size,
                                (comm.rank - 1) % comm.size, comm=comm)
        return req.wait()

    assert run_local(prog, 3) == [2, 0, 1]

    def sprog(comm):
        with pytest.raises(SpmdSemanticsError, match="Isendrecv"):
            comm.isendrecv(1.0, 0)
        with pytest.raises(SpmdSemanticsError, match="Isendrecv_replace"):
            comm.isendrecv_replace(np.zeros(2), 0)
        return comm.allreduce(1.0)

    run_spmd(sprog, nranks=8)


def test_isendrecv_replace_shape_mismatch_raises():
    """A refill that cannot be applied must RAISE (review round 4), not
    leave the buffer silently stale."""
    def prog(comm):
        right = (comm.rank + 1) % comm.size
        left = (comm.rank - 1) % comm.size
        comm.send(np.zeros(3), right, tag=9)  # wrong-shaped payload
        buf = np.zeros(2)
        req = comm.isendrecv_replace(buf, right, left, sendtag=8,
                                     recvtag=9)
        with pytest.raises(ValueError):
            req.wait()
        # drain the sendtag-8 message so finalize stays clean
        comm.recv(left, tag=8)
        return True

    assert all(run_local(prog, 2))


def test_sequential_comm_create_from_group_isolated():
    """ADVICE r4 #1: two SEQUENTIAL comm_create_from_group calls with
    the same (group, stringtag) — legal in MPI-4; only concurrent
    identical pairs are erroneous — must produce ISOLATED
    communicators: a stale unmatched isend on the first comm must NOT
    be received by the second.  The per-process generation counter
    keyed by (world_ranks, stringtag) gives them distinct contexts
    without any extra agreement traffic (creations with one key are
    ordered collectives over the same members)."""
    def prog(comm):
        with mpi4.session_init(base_comm=comm) as sess:
            grp = sess.group_from_pset("mpi://WORLD")
            c1 = sess.comm_create_from_group(grp, "lib")
            c2 = sess.comm_create_from_group(grp, "lib")
            assert c1._ctx != c2._ctx  # distinct contexts...
            # ...agreed across ranks (same generation on every member)
            gens = c1._ctx[-1], c2._ctx[-1]
            assert comm.allreduce(gens[0], op=mpi_tpu.ops.MAX) == gens[0]
            assert comm.allreduce(gens[1], op=mpi_tpu.ops.MAX) == gens[1]
            # stale traffic on c1 must not cross into c2
            if comm.rank == 0:
                c1.isend("stale-on-c1", 1, tag=3)
                c2.send("fresh-on-c2", 1, tag=3)
                comm.barrier()
            else:
                got = c2.recv(0, tag=3) if comm.rank == 1 else None
                comm.barrier()
                if comm.rank == 1:
                    assert got == "fresh-on-c2"
                    # the stale message is still on c1, where it belongs
                    assert c1.iprobe(0, tag=3)
                    assert c1.recv(0, tag=3) == "stale-on-c1"
            # a DIFFERENT stringtag with the same group also isolates
            c3 = sess.comm_create_from_group(grp, "other")
            assert c3._ctx != c1._ctx and c3._ctx != c2._ctx
            return True

    assert all(run_local(prog, 2))
