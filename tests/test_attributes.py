"""Attribute caching (MPI-1 §5.7 keyvals): set/get/delete, delete_fn
hooks, and dup-time copy-callback semantics on both backend families."""

import os

import numpy as np
import pytest

from mpi_tpu import api, communicator as comm_mod
from mpi_tpu.transport.local import run_local


def test_set_get_delete_roundtrip():
    def prog(comm):
        kv = comm_mod.create_keyval(name="answer")
        assert comm.get_attr(kv) is None
        comm.set_attr(kv, 42)
        assert comm.get_attr(kv) == 42
        comm.delete_attr(kv)
        assert comm.get_attr(kv) is None
        comm.delete_attr(kv)  # idempotent

    run_local(prog, 2)


def test_delete_fn_runs_on_delete_and_overwrite():
    def prog(comm):
        log = []
        kv = comm_mod.create_keyval(
            delete_fn=lambda c, v: log.append(v), name="logged")
        comm.set_attr(kv, "a")
        comm.set_attr(kv, "b")  # overwrite deletes "a"
        comm.delete_attr(kv)
        return log

    res = run_local(prog, 1)
    assert res[0] == ["a", "b"]


def test_dup_copy_semantics():
    def prog(comm):
        kept = comm_mod.create_keyval(copy_fn=comm_mod.dup_fn, name="kept")
        private = comm_mod.create_keyval(name="private")  # NULL_COPY_FN
        vetoed = comm_mod.create_keyval(
            copy_fn=lambda c, v: comm_mod.NO_COPY, name="vetoed")
        doubled = comm_mod.create_keyval(
            copy_fn=lambda c, v: v * 2, name="doubled")
        for kv, v in [(kept, "k"), (private, "p"), (vetoed, "v"), (doubled, 21)]:
            comm.set_attr(kv, v)
        d = comm.dup()
        return (d.get_attr(kept), d.get_attr(private),
                d.get_attr(vetoed), d.get_attr(doubled),
                comm.get_attr(private))

    for got in run_local(prog, 2):
        assert got == ("k", None, None, 42, "p")


def test_attrs_on_tpu_backend_dup():
    import mpi_tpu

    def prog(comm):
        kv = comm_mod.create_keyval(copy_fn=comm_mod.dup_fn, name="tpu-kept")
        comm.set_attr(kv, "x")
        assert comm.dup().get_attr(kv) == "x"
        return comm.allreduce(1)

    res = mpi_tpu.run(prog, backend="tpu", nranks=None)
    assert int(np.asarray(res)[0]) >= 1


def test_attr_api_layer():
    def prog(comm):
        kv = api.MPI_Comm_create_keyval(copy_fn=api.MPI_COMM_DUP_FN)
        api.MPI_Comm_set_attr(kv, {"cfg": 1}, comm=comm)
        assert api.MPI_Comm_get_attr(kv, comm=comm) == {"cfg": 1}
        api.MPI_Comm_delete_attr(kv, comm=comm)
        assert api.MPI_Comm_get_attr(kv, comm=comm) is None
        api.MPI_Comm_free_keyval(kv)

    run_local(prog, 1)
