"""Receive-side zero-copy (ISSUE 17): the size-classed recv pool, the
posted-irecv registry, rendezvous steering on the live socket stack,
the sorted-interval CoW index (PR-11 residual c), and the persistent
double-buffered re-fire (PR-12 residual e).

The acceptance leg lives here too: a 16MB socket allreduce run with
steering off then on must show ``payload_copies`` dropping by exactly
the recv-side stores while ``recv_bytes_steered`` proves the bytes
landed directly in the posted buffers.
"""

import os
import sys
import threading

import numpy as np
import pytest

from mpi_tpu import bufpool, mpit, ops, recvpool, telemetry
from mpi_tpu.recvpool import PostedRecvRegistry, RecvPool
from mpi_tpu.resilience import LinkState

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_resilience import run_socket_world  # noqa: E402


# -- RecvPool: size classes + recycling ---------------------------------------


def test_class_bytes_pow2_rounding():
    assert RecvPool.class_bytes(1) == 1
    assert RecvPool.class_bytes(1024) == 1024
    assert RecvPool.class_bytes(1025) == 2048
    assert RecvPool.class_bytes((3 << 20) + (1 << 19)) == 4 << 20  # 3.5MB->4MB


def test_below_floor_allocations_bypass_the_pool():
    pool = RecvPool(min_bytes=1 << 12)
    h0, m0 = mpit.counters.rp_hits, mpit.counters.rp_misses
    a = pool.empty((8,), np.dtype(np.float64))
    assert a.shape == (8,) and a.base is None  # plain np.empty, no class buf
    assert (mpit.counters.rp_hits, mpit.counters.rp_misses) == (h0, m0)


def test_recycle_reuses_the_class_buffer():
    pool = RecvPool(min_bytes=1 << 12)
    a = pool.empty((1 << 12,), np.dtype(np.uint8))
    addr0 = a.base.__array_interface__["data"][0]
    h0 = mpit.counters.rp_hits
    del a  # refcount -> 0: finalize fires synchronously, recycles
    b = pool.empty((1 << 11, 2), np.dtype(np.uint8))  # same class, any shape
    assert b.base.__array_interface__["data"][0] == addr0
    assert mpit.counters.rp_hits == h0 + 1


def test_subclass_sizes_share_a_class_buffer():
    pool = RecvPool(min_bytes=1 << 12)
    a = pool.empty(((1 << 12) + 100,), np.dtype(np.uint8))  # rounds to 8192
    addr0 = a.base.__array_interface__["data"][0]
    assert a.base.nbytes == 1 << 13
    del a
    b = pool.empty((1 << 10,), np.dtype(np.float64))  # 8192 bytes exactly
    assert b.base.__array_interface__["data"][0] == addr0


def test_live_alias_vetoes_recycling():
    """A user slice keeps the backing buffer's refcount above the
    calibrated baseline: the finalize must NOT hand the memory out
    again while the alias can still read it."""
    pool = RecvPool(min_bytes=1 << 12)
    a = pool.empty((1 << 12,), np.dtype(np.uint8))
    a[:] = 7
    alias = a[16:32]  # numpy collapses .base onto the backing buffer
    addr0 = a.base.__array_interface__["data"][0]
    del a
    b = pool.empty((1 << 12,), np.dtype(np.uint8))
    b[:] = 9
    assert b.base.__array_interface__["data"][0] != addr0
    np.testing.assert_array_equal(alias, np.full(16, 7, np.uint8))


def test_free_list_bounded_per_class():
    pool = RecvPool(min_bytes=1 << 12, max_per_size=3)
    for _ in range(5):
        a = pool.empty((1 << 12,), np.dtype(np.uint8))
        del a
    assert len(pool._free[1 << 12]) <= 3


# -- PostedRecvRegistry: pairing protocol -------------------------------------


SRC, CTX, TAG = 1, ("c", 0), -2


def _plan(shape, dtype="<f8"):
    return ("arr", dtype, tuple(shape))


def test_registry_pairs_posts_with_frames_in_order():
    reg = PostedRecvRegistry()
    d1, d2 = np.empty(4), np.empty(4)
    t1 = reg.note_post(SRC, CTX, TAG)
    t2 = reg.note_post(SRC, CTX, TAG)
    reg.attach(t1, d1)
    reg.attach(t2, d2)
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is d1
    assert reg.note_frame(SRC, CTX, TAG, 2, 0, _plan((4,))) is d2


def test_registry_geometry_mismatch_falls_back():
    reg = PostedRecvRegistry()
    t = reg.note_post(SRC, CTX, TAG)
    reg.attach(t, np.empty(4))
    # wrong shape -> pool path; entry is consumed either way
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((5,))) is None
    t2 = reg.note_post(SRC, CTX, TAG)
    reg.attach(t2, np.empty(4, np.float32))
    # wrong dtype
    assert reg.note_frame(SRC, CTX, TAG, 2, 0, _plan((4,))) is None
    # non-"arr" plans (multi-segment, wire-encoded, pickled) never steer
    t3 = reg.note_post(SRC, CTX, TAG)
    reg.attach(t3, np.empty(4))
    assert reg.note_frame(SRC, CTX, TAG, 3, 0, ("segs", [])) is None


def test_registry_unattached_and_blocking_consumers_align_indices():
    reg = PostedRecvRegistry()
    t1 = reg.note_post(SRC, CTX, TAG)      # idx 1, attached
    reg.note_consume(SRC, CTX, TAG)        # idx 2, blocking recv
    t3 = reg.note_post(SRC, CTX, TAG)      # idx 3, attached
    d1, d3 = np.empty(4), np.empty(4)
    reg.attach(t1, d1)
    reg.attach(t3, d3)
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is d1
    assert reg.note_frame(SRC, CTX, TAG, 2, 0, _plan((4,))) is None
    assert reg.note_frame(SRC, CTX, TAG, 3, 0, _plan((4,))) is d3


def test_registry_frame_ahead_of_post_drops_the_stale_entry():
    """A frame that arrives before any consumer was counted claims
    nothing; the post counted AFTER it is stale for that frame and must
    not claim a LATER frame (conservative miss, never a false claim)."""
    reg = PostedRecvRegistry()
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is None
    t = reg.note_post(SRC, CTX, TAG)  # idx 1 but frame 1 already passed
    reg.attach(t, np.empty(4))
    assert reg.note_frame(SRC, CTX, TAG, 2, 0, _plan((4,))) is None
    assert reg.stats()["entries"] == 0  # stale entry was dropped


def test_registry_cancel_removes_the_entry():
    reg = PostedRecvRegistry()
    t = reg.note_post(SRC, CTX, TAG)
    reg.attach(t, np.empty(4))
    reg.cancel(t)
    assert reg.stats()["entries"] == 0
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is None
    reg.cancel(None)  # no-op by contract


def test_registry_watermark_dedups_replay_representation():
    reg = PostedRecvRegistry()
    t = reg.note_post(SRC, CTX, TAG)
    reg.attach(t, np.empty(4))
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is not None
    # the same (gen, seq) presented again (old-conn drain vs replay
    # race, or counted-then-torn steer): never recounted
    before = reg.stats()["arrived"]
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is None
    assert reg.stats()["arrived"] == before


def test_registry_purge_resyncs_and_fences():
    reg = PostedRecvRegistry()
    t1 = reg.note_post(SRC, CTX, TAG)
    t2 = reg.note_post(SRC, CTX, TAG)
    reg.attach(t1, np.empty(4))
    reg.attach(t2, np.empty(4))
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is not None
    reg.purge_src(SRC, 1)  # membership removal; gen bumped to 1
    s = reg.stats()
    assert s["entries"] == 0 and s["arrived"] == s["posted"]
    # an old-generation straggler sits below the fence: never counts
    assert reg.note_frame(SRC, CTX, TAG, 2, 0, _plan((4,))) is None
    assert reg.stats()["arrived"] == s["arrived"]
    # the replacement stream counts from (gen 1, seq 1)
    t3 = reg.note_post(SRC, CTX, TAG)
    d3 = np.empty(4)
    reg.attach(t3, d3)
    assert reg.note_frame(SRC, CTX, TAG, 1, 1, _plan((4,))) is d3


def test_registry_self_send_consumes_posted_slots():
    reg = PostedRecvRegistry()
    t = reg.note_post(SRC, CTX, TAG)
    reg.attach(t, np.empty(4))
    reg.note_local(SRC, CTX, TAG)  # loopback delivery, never steered
    assert reg.stats()["entries"] == 0


def test_registry_attach_rejects_non_steerable_views():
    reg = PostedRecvRegistry()
    t = reg.note_post(SRC, CTX, TAG)
    ro = np.empty(4)
    ro.flags.writeable = False
    reg.attach(t, ro)
    assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is None
    t2 = reg.note_post(SRC, CTX, TAG)
    reg.attach(t2, np.empty((4, 4))[:, 0])  # non-contiguous
    assert reg.note_frame(SRC, CTX, TAG, 2, 0, _plan((4,))) is None


def test_steering_cvar_disables_claiming_not_accounting():
    reg = PostedRecvRegistry()
    old = mpit.cvar_read("recv_steering")
    try:
        mpit.cvar_write("recv_steering", 0)
        assert recvpool._STEERING == 0
        t = reg.note_post(SRC, CTX, TAG)
        reg.attach(t, np.empty(4))
        # accounting continues (frame counted, entry consumed) but the
        # claim is refused — toggling can never desync the pairing
        assert reg.note_frame(SRC, CTX, TAG, 1, 0, _plan((4,))) is None
        assert reg.stats()["arrived"] == 1
    finally:
        mpit.cvar_write("recv_steering", old)
    assert recvpool._STEERING == old


def test_rx_fresh_admits_exactly_the_next_in_sequence_frame():
    ls = LinkState(2)
    assert ls.rx_fresh(1, 1, 0)          # next in sequence, current gen
    assert not ls.rx_fresh(1, 2, 0)      # gap frame: not counted
    assert not ls.rx_fresh(1, 1, 1)      # stale/future generation
    ls.rx_gate(1, 1, lambda: None)       # deliver seq 1
    assert not ls.rx_fresh(1, 1, 0)      # replay duplicate
    assert ls.rx_fresh(1, 2, 0)


# -- sorted-interval CoW live-range index (bufpool, PR-11 residual c) ---------


def _addr(arr):
    return arr.__array_interface__["data"][0]


def test_interval_index_overlap_snapshots_exactly_the_hit():
    base = np.zeros(256, np.uint8)
    a, b = base[0:64], base[128:192]
    ra, rb = bufpool.BufRef([a]), bufpool.BufRef([b])
    try:
        assert bufpool.touch(base[130:140]) == 1
        assert rb.snapshotted and not ra.snapshotted
        assert bufpool.touch(base[130:140]) == 0  # already snapshotted
    finally:
        ra.release(), rb.release()


def test_interval_index_adjacency_is_half_open():
    """[s, m) and [m, e) are adjacent, not overlapping: a write at m
    snapshots only the second ref (e > qs is strict)."""
    base = np.zeros(256, np.uint8)
    ra, rb = bufpool.BufRef([base[0:64]]), bufpool.BufRef([base[64:128]])
    try:
        assert bufpool.touch(base[64:65]) == 1
        assert rb.snapshotted and not ra.snapshotted
    finally:
        ra.release(), rb.release()


def test_interval_index_duplicate_ranges_unregister_by_identity():
    base = np.zeros(256, np.uint8)
    view = base[0:64]
    r1, r2 = bufpool.BufRef([view]), bufpool.BufRef([view])
    try:
        r1.release()  # must remove r1's record, not r2's
        assert bufpool.touch(base[10:11]) == 1
        assert r2.snapshotted
    finally:
        r1.release(), r2.release()


def test_interval_index_maxlen_window_finds_long_intervals():
    """The scan-back window: a query point deep inside a LONG interval
    whose start is far below the query must still hit (that is what
    ``_maxlen`` bounds), including after shorter refs registered."""
    big = np.zeros(1 << 16, np.uint8)
    small = np.zeros(64, np.uint8)
    rb, rs = bufpool.BufRef([big]), bufpool.BufRef([small])
    try:
        assert bufpool.touch(big[(1 << 16) - 10:(1 << 16) - 9]) == 1
        assert rb.snapshotted and not rs.snapshotted
    finally:
        rb.release(), rs.release()


def test_interval_index_purge_drains_and_resets_maxlen():
    big = np.zeros(1 << 16, np.uint8)
    small = np.zeros(64, np.uint8)
    rb, rs = bufpool.BufRef([big]), bufpool.BufRef([small])
    assert bufpool._maxlen >= 1 << 16
    rb.release()
    # grow-only while non-empty: the stale bound costs scan width only
    assert bufpool._maxlen >= 1 << 16
    assert bufpool.touch(small[3:5]) == 1  # still correct
    rs.release()
    assert bufpool._maxlen == 0 and not bufpool._ivals  # drained -> reset
    assert bufpool.touch(small[3:5]) == 0


def test_interval_index_multirange_ref_registers_every_range():
    base = np.zeros(512, np.uint8)
    ref = bufpool.BufRef([base[0:64], base[256:320]])
    try:
        assert bufpool.touch(base[257:258]) == 1  # second range hits too
        assert ref.snapshotted
    finally:
        ref.release()


# -- live socket worlds: rendezvous steering end to end -----------------------


def _steer_deltas(prog, nranks, **kw):
    names = ("recv_pool_rendezvous", "recv_bytes_steered", "recv_pool_hits",
             "recv_pool_misses", "payload_copies", "link_torn_frames")
    base = {n: mpit.pvar_read(n) for n in names}
    res = run_socket_world(prog, nranks, **kw)
    return res, {n: mpit.pvar_read(n) - base[n] for n in names}


def test_socket_16mb_allreduce_steers_and_drops_the_recv_copy():
    """THE acceptance assert: steering off, the 16MB ring allreduce
    pays one fold-site store per received store-span (counted into
    ``payload_copies``); steering on, those stores vanish from the
    counter and ``recv_bytes_steered`` shows the bytes landing directly
    in the posted working-buffer spans.  Runs with the flight recorder
    OFF — every steer/fallback seam takes its ``REC is None`` branch."""
    assert telemetry.REC is None
    data = [np.random.RandomState(i).randn(1 << 21) for i in range(2)]  # 16MB
    want = data[0] + data[1]

    def prog(comm):
        out = comm.allreduce(data[comm.rank], ops.SUM)
        np.testing.assert_allclose(out, want)
        return True

    old = mpit.cvar_read("recv_steering")
    try:
        mpit.cvar_write("recv_steering", 0)
        res, off = _steer_deltas(prog, 2)
        assert all(res)
        mpit.cvar_write("recv_steering", 1)
        res, on = _steer_deltas(prog, 2)
        assert all(res)
    finally:
        mpit.cvar_write("recv_steering", old)
    # off: no rendezvous, every store priced, every body pool-staged
    assert off["recv_pool_rendezvous"] == 0
    assert off["recv_bytes_steered"] == 0
    assert off["payload_copies"] >= 2  # the recv-side stores
    assert off["recv_pool_hits"] + off["recv_pool_misses"] >= 4
    # on: the drop — stores leave the copy counter, bytes steer direct
    assert on["payload_copies"] == 0
    assert on["recv_pool_rendezvous"] > 0
    assert on["recv_bytes_steered"] >= 4 << 20  # at least one 4MB segment


def test_steering_survives_engine_and_nbc_paths():
    """iallreduce via the progress-engine state machines on the socket
    stack: span stores steer through _SMColl._apply's identity check."""
    data = [np.random.RandomState(10 + i).randn(1 << 20) for i in range(2)]
    want = data[0] + data[1]

    def prog(comm):
        got = comm.iallreduce(data[comm.rank], ops.SUM).wait()
        np.testing.assert_allclose(got, want)
        return True

    res, d = _steer_deltas(prog, 2)
    assert all(res)
    assert d["payload_copies"] == 0


def test_trace_events_mark_steer_vs_fallback():
    """Flight-recorder visibility (satellite): with tracing ON, steered
    frames emit ``recvpool/steer`` instants that survive into the
    chrome export tracecat merges."""
    data = [np.random.RandomState(20 + i).randn(1 << 21) for i in range(2)]

    def prog(comm):
        comm.allreduce(data[comm.rank], ops.SUM)
        return True

    rec = telemetry.enable(capacity=4096)
    try:
        assert all(run_socket_world(prog, 2))
        steers = rec.find("recvpool", "steer")
        assert steers, "no steer events recorded"
        assert {"src", "seq", "tag", "nbytes"} <= set(steers[0]["attrs"])
        cats = {e.get("cat") for e in rec.chrome_trace()["traceEvents"]}
        assert "recvpool" in cats  # instants render in the merge
    finally:
        telemetry.disable()


def test_torn_frame_distinguished_from_clean_close():
    """Satellite fix: a clean world teardown must not tick
    ``link_torn_frames``; a mid-frame disconnect must."""
    def prog(comm):
        comm.allreduce(np.full(64, 1.0))
        comm.barrier()
        return True

    _, d = _steer_deltas(prog, 2)
    assert d["link_torn_frames"] == 0  # clean closes are not torn

    from mpi_tpu.transport.faulty import FaultyTransport
    big = np.arange(1 << 20, dtype=np.float64)  # 8MB

    def chaos(comm):
        FaultyTransport(comm._t, link_reset_midframe_every=2)
        if comm.rank == 0:
            comm.send(big, dest=1, tag=5)
        else:
            got = comm.recv(source=0, tag=5)
            assert np.array_equal(got, big)
        comm.barrier()
        return True

    assert telemetry.REC is None  # the torn seam's REC-off branch
    res, d = _steer_deltas(chaos, 2)
    assert all(res)
    assert d["link_torn_frames"] >= 1


# -- persistent double-buffered re-fire (PR-12 residual e) --------------------


def test_persistent_allreduce_alternates_two_preallocated_buffers():
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        x = np.arange(8, dtype=np.float64)
        h = comm.allreduce_init(x)
        outs, bases = [], []
        for rd in range(4):
            x[:] = np.arange(8, dtype=np.float64) * (rd + 1)
            got = h.start().wait()
            np.testing.assert_array_equal(
                got, np.arange(8) * (rd + 1) * comm.size)
            bases.append(id(np.asarray(got).base))
            outs.append(float(got.sum()))
        # two buffers, alternated: rounds k and k+2 share a base
        assert bases[0] == bases[2] and bases[1] == bases[3]
        assert bases[0] != bases[1]
        return outs

    res = run_local(prog, 2, progress="thread")
    assert res[0] == res[1]


def test_persistent_round_result_valid_until_round_plus_two():
    """The documented double-buffer contract: round k's result array is
    overwritten when round k+2 starts (it IS buffer k % 2)."""
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        x = np.zeros(4)
        h = comm.allreduce_init(x)
        x[:] = 1.0
        r1 = h.start().wait()
        v1 = np.asarray(r1).copy()
        x[:] = 2.0
        r2 = h.start().wait()
        np.testing.assert_array_equal(r1, v1)  # still valid: one round
        x[:] = 3.0
        r3 = h.start().wait()
        # r1's buffer was recycled for round 3
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r3))
        return float(np.asarray(r2)[0])

    assert run_local(prog, 2, progress="thread") == [4.0, 4.0]


def test_persistent_refire_allocates_no_new_work_buffers():
    """After the first two rounds the re-fire path is allocation-free
    for working buffers: the same two backing arrays carry every
    subsequent round."""
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        x = np.ones(1024)
        h = comm.allreduce_init(x)
        seen = set()
        for rd in range(6):
            got = h.start().wait()
            seen.add(id(np.asarray(got).base))
            assert float(np.asarray(got)[0]) == comm.size
        assert len(seen) == 2
        return True

    assert all(run_local(prog, 2, progress="thread"))


# -- fold-fallback visibility (ISSUE 18 satellite) ----------------------------


def _fold_delta():
    return mpit.pvar_read("recv_pool_fold_fallbacks")


def test_fold_fallback_counts_reader_beating_poster():
    """A steerable frame arriving before ANY consumer was counted is a
    genuine lost race: it folds through the pool and ticks the pvar."""
    reg = PostedRecvRegistry()
    plan = ("arr", "<f8", (4,))
    c0 = _fold_delta()
    assert reg.note_frame("s", "c", -7, 1, 1, plan=plan) is None
    assert _fold_delta() == c0 + 1


def test_fold_fallback_ignores_blocking_recvs():
    """A blocking recv (note_consume) never steers by design — its
    frame folding through the pool is not a race."""
    reg = PostedRecvRegistry()
    plan = ("arr", "<f8", (4,))
    reg.note_consume("s", "c", -7)
    c0 = _fold_delta()
    assert reg.note_frame("s", "c", -7, 1, 1, plan=plan) is None
    assert _fold_delta() == c0


def test_fold_fallback_counts_post_without_attach():
    """The other flavor: the irecv was posted but its attach() hadn't
    landed when the frame arrived (dest-less entry)."""
    reg = PostedRecvRegistry()
    plan = ("arr", "<f8", (4,))
    reg.note_post("s", "c", -7)  # posted, never attached
    c0 = _fold_delta()
    assert reg.note_frame("s", "c", -7, 1, 1, plan=plan) is None
    assert _fold_delta() == c0 + 1


def test_fold_fallback_ignores_declined_attach():
    """An explicitly declined dest (read-only / non-contiguous) is a
    decision, not a race — the pvar stays put."""
    reg = PostedRecvRegistry()
    plan = ("arr", "<f8", (4,))
    token = reg.note_post("s", "c", -7)
    ro = np.zeros(4)
    ro.flags.writeable = False
    reg.attach(token, ro)
    c0 = _fold_delta()
    assert reg.note_frame("s", "c", -7, 1, 1, plan=plan) is None
    assert _fold_delta() == c0


def test_fold_fallback_silent_on_matched_steer():
    """A matched geometry steers and counts nothing."""
    reg = PostedRecvRegistry()
    dest = np.zeros(4)
    token = reg.note_post("s", "c", -7)
    reg.attach(token, dest)
    c0 = _fold_delta()
    got = reg.note_frame("s", "c", -7, 1, 1, plan=("arr", "<f8", (4,)))
    assert got is dest
    assert _fold_delta() == c0


def test_fold_fallback_emits_trace_instant():
    reg = PostedRecvRegistry()
    rec = telemetry.enable(capacity=256)
    try:
        reg.note_frame("sX", "cX", -9, 1, 1, plan=("arr", "<f8", (2,)))
        evs = rec.find("recvpool", "fold_fallback")
        assert evs and evs[0]["attrs"] == {"src": "sX", "tag": -9}
    finally:
        telemetry.disable()


# -- persistent double-buffer fence (ISSUE 18 satellite) ----------------------


def test_persistent_fence_trips_on_round_plus_two_overwrite():
    """Verify mode: start() raises the named ``BufferPinnedError`` when
    the caller still references the round-k result at round k+2, where
    silent corruption would otherwise begin."""
    from mpi_tpu.errors import BufferPinnedError
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        x = np.ones(16)
        h = comm.allreduce_init(x)
        r0 = h.start().wait()            # round 0 result, kept alive
        h.start().wait()                 # round 1
        try:
            h.start().wait()             # round 2 would overwrite r0
        except BufferPinnedError as e:
            return ("fenced", "copy it first" in str(e), float(r0[0]))
        return ("missed", False, float(r0[0]))

    res = run_local(prog, 2, verify=True, progress="thread", timeout=60.0)
    assert res == [("fenced", True, 2.0)] * 2


def test_persistent_fence_silent_when_contract_followed():
    """Dropping the stale reference (or only ever holding the latest
    result) never trips the fence — including the reassignment idiom
    where the previous round's array dies on rebinding."""
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        x = np.ones(16)
        h = comm.allreduce_init(x)
        r0 = h.start().wait()
        r1 = h.start().wait()
        del r0, r1                       # contract honored: release early
        got = None
        for _ in range(6):               # rebinding loop: old result dies
            got = h.start().wait()
        return float(np.asarray(got)[0])

    assert run_local(prog, 2, verify=True, progress="thread",
                     timeout=60.0) == [2.0, 2.0]


def test_persistent_reduce_scatter_refires_on_preallocated_buffers():
    """ISSUE 19 satellite: the double-buffered re-fire extends to
    reduce_scatter_init on the engine's span path — round k's result is
    a VIEW of preallocated buffer k % 2 (no per-round allocation), so
    rounds two apart share backing memory."""
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        blocks = [np.full(8, float(comm.rank + 1)) for _ in range(2)]
        h = comm.reduce_scatter_init(blocks)
        r0 = np.asarray(h.start().wait())
        np.testing.assert_array_equal(r0, np.full(8, 3.0))
        h.start().wait()
        r2 = np.asarray(h.start().wait())
        return bool(np.shares_memory(r0, r2)), float(r2[0])

    assert run_local(prog, 2, progress="thread",
                     timeout=60.0) == [(True, 3.0)] * 2


def test_persistent_reduce_scatter_fence_trips_like_allreduce():
    """The BufferPinnedError fence covers the extended path: holding
    round k's reduce_scatter block across two later starts raises the
    named error instead of silently overwriting it."""
    from mpi_tpu.errors import BufferPinnedError
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        blocks = [np.ones(8) for _ in range(2)]
        h = comm.reduce_scatter_init(blocks)
        r0 = h.start().wait()                # round 0 block, kept alive
        h.start().wait()
        try:
            h.start().wait()
        except BufferPinnedError as e:
            return ("fenced", "copy it first" in str(e),
                    float(np.asarray(r0)[0]))
        return ("missed", False, 0.0)

    res = run_local(prog, 2, verify=True, progress="thread", timeout=60.0)
    assert res == [("fenced", True, 2.0)] * 2


def test_persistent_fence_off_without_verify():
    """The fence is verify-gated: the documented overwrite behavior is
    unchanged in normal runs (round k's array IS buffer k % 2)."""
    from mpi_tpu.transport.local import run_local

    def prog(comm):
        x = np.ones(8)
        h = comm.allreduce_init(x)
        r0 = h.start().wait()
        h.start().wait()
        r2 = h.start().wait()            # overwrites r0 silently: by design
        np.testing.assert_array_equal(np.asarray(r0), np.asarray(r2))
        return True

    assert all(run_local(prog, 2, progress="thread"))
