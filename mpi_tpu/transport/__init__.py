from .base import ANY_SOURCE, ANY_TAG, Mailbox, RecvTimeout, Transport, TransportError
from .faulty import FaultyTransport
from .local import LocalTransport, LocalWorld, run_local
from .socket import SocketTransport

__all__ = [
    "ANY_SOURCE",
    "ANY_TAG",
    "Mailbox",
    "RecvTimeout",
    "Transport",
    "TransportError",
    "LocalTransport",
    "LocalWorld",
    "run_local",
    "SocketTransport",
    "FaultyTransport",
]
