"""Static MPI lint (MPI-Checker style): an AST pass over user programs.

Four checks, deliberately literal-only (no dataflow guessing — every
finding is a pattern a reviewer can confirm by reading the flagged
lines; suppress a deliberate one with ``# mpilint: ok`` on the flagged
line or the line above):

* **MPL001 — rank-conditional collective**: a collective call on ``c``
  inside an ``if`` whose condition tests ``c.rank``, with no matching
  call of the same collective on ``c`` in the other branch.  Collective
  schedules must be entered by every rank; a rank-conditional entry is
  the divergent-order hang the runtime matcher catches dynamically.
* **MPL002 — send-send cycle**: literal rank-pair branches (``if c.rank
  == A: ... elif c.rank == B: ...``) where BOTH ranks blocking-send to
  each other before either receives — legal under this library's
  buffered sends, but a deadlock under MPI's synchronous/rendezvous
  sends and any bounded-buffer transport; use ``sendrecv``.
* **MPL003 — literal count truncation**: a typed ``MPI_Send(...,
  count=N)`` to literal rank B paired with B's ``MPI_Recv(...,
  count=M)`` from the sender with ``M < N`` — the receive silently
  truncates.
* **MPL004 — revoked comm without an error handler**: a p2p/collective
  call on a comm after ``c.revoke()`` appears, with no
  ``set_errhandler`` on it and outside any ``try``: every post-revoke
  call raises RevokedError, so unhandled it just moves the crash.

``lint_source``/``lint_paths`` return :class:`Finding` lists; the CLI is
``tools/mpilint.py`` (wired into ``tools/check.sh`` over ``examples/``
and ``mpi_tpu/``).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

COLLECTIVES = frozenset({
    "bcast", "reduce", "allreduce", "allgather", "allgatherv", "alltoall",
    "alltoallv", "barrier", "scan", "exscan", "reduce_scatter", "scatter",
    "scatterv", "gather", "gatherv", "maxloc", "minloc",
})
_P2P_OR_COLL = COLLECTIVES | frozenset({
    "send", "recv", "sendrecv", "isend", "irecv", "probe", "iprobe",
    "shift", "exchange", "split", "dup",
})


class Finding(NamedTuple):
    file: str
    line: int
    code: str
    msg: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.msg}"


def _method_call(node: ast.AST) -> Optional[Tuple[str, str, ast.Call]]:
    """(receiver-name, method, call) for ``name.method(...)`` nodes."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)):
        return node.func.value.id, node.func.attr, node
    return None


def _rank_cond_name(test: ast.AST) -> Optional[str]:
    """Receiver name when the expression mentions ``<name>.rank``."""
    for n in ast.walk(test):
        if (isinstance(n, ast.Attribute) and n.attr == "rank"
                and isinstance(n.value, ast.Name)):
            return n.value.id
    return None


def _rank_eq_literal(test: ast.AST) -> Optional[Tuple[str, int]]:
    """(name, K) for a test of the exact form ``name.rank == K``."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)):
        return None
    sides = [test.left, test.comparators[0]]
    name = lit = None
    for s in sides:
        if (isinstance(s, ast.Attribute) and s.attr == "rank"
                and isinstance(s.value, ast.Name)):
            name = s.value.id
        elif isinstance(s, ast.Constant) and isinstance(s.value, int):
            lit = s.value
    return (name, lit) if name is not None and lit is not None else None


def _int_arg(call: ast.Call, kw: str, pos: Optional[int]) -> Optional[int]:
    for k in call.keywords:
        if k.arg == kw and isinstance(k.value, ast.Constant) \
                and isinstance(k.value.value, int):
            return k.value.value
    if pos is not None and len(call.args) > pos:
        a = call.args[pos]
        if isinstance(a, ast.Constant) and isinstance(a.value, int):
            return a.value
    return None


def _calls_in(nodes: Sequence[ast.AST], *, into_defs: bool = False):
    """Every Call in the given statement subtrees, skipping nested
    function/class bodies unless asked (their execution time is
    unrelated to the enclosing branch)."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)) and not into_defs:
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _suppressed(src: str) -> set:
    out = set()
    for i, line in enumerate(src.splitlines(), 1):
        if "mpilint: ok" in line:
            out.add(i)
            out.add(i + 1)
    return out


def lint_source(src: str, filename: str = "<string>") -> List[Finding]:
    try:
        tree = ast.parse(src, filename)
    except SyntaxError as e:
        return [Finding(filename, e.lineno or 0, "MPL000",
                        f"syntax error: {e.msg}")]
    findings: List[Finding] = []
    findings += _check_rank_conditional_collectives(tree, filename)
    for scope in _scopes(tree):
        branches = _rank_literal_branches(scope)
        findings += _check_send_send_cycles(branches, filename)
        findings += _check_count_truncation(branches, filename)
    findings += _check_revoked_unhandled(tree, filename)
    sup = _suppressed(src)
    return sorted((f for f in findings if f.line not in sup),
                  key=lambda f: (f.line, f.code))


def _scopes(tree: ast.Module):
    yield tree
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


# -- MPL001 ------------------------------------------------------------------

def _branch_collectives(nodes: Sequence[ast.AST]) -> Dict[Tuple[str, str],
                                                          int]:
    out: Dict[Tuple[str, str], int] = {}
    for call in _calls_in(nodes):
        mc = _method_call(call)
        if mc and mc[1] in COLLECTIVES:
            out.setdefault((mc[0], mc[1]), call.lineno)
    return out


def _check_rank_conditional_collectives(tree, filename) -> List[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.If):
            continue
        comm = _rank_cond_name(node.test)
        if comm is None:
            continue
        body = _branch_collectives(node.body)
        other = _branch_collectives(node.orelse)
        for (recv_name, meth), line in sorted(body.items(),
                                              key=lambda kv: kv[1]):
            if recv_name == comm and (recv_name, meth) not in other:
                findings.append(Finding(
                    filename, line, "MPL001",
                    f"collective {recv_name}.{meth}() is conditional on "
                    f"{comm}.rank with no matching {meth}() in the other "
                    f"branch — non-calling ranks diverge from the "
                    f"collective schedule (hang/mismatch)"))
        for (recv_name, meth), line in sorted(other.items(),
                                              key=lambda kv: kv[1]):
            if recv_name == comm and (recv_name, meth) not in body:
                findings.append(Finding(
                    filename, line, "MPL001",
                    f"collective {recv_name}.{meth}() runs only when the "
                    f"{comm}.rank test is false, with no matching "
                    f"{meth}() in the taken branch — ranks diverge from "
                    f"the collective schedule (hang/mismatch)"))
    return findings


# -- rank-literal branch collection (MPL002/003) -----------------------------

class _Op(NamedTuple):
    kind: str        # 'send' | 'recv'
    peer: Optional[int]
    count: Optional[int]
    line: int


def _branch_ops(comm: str, nodes: Sequence[ast.AST]) -> List[_Op]:
    ops = []
    for call in _calls_in(nodes):
        mc = _method_call(call)
        if mc and mc[0] == comm:
            _, meth, c = mc
            if meth == "send":
                ops.append(_Op("send", _int_arg(c, "dest", 1), None,
                               c.lineno))
            elif meth == "recv":
                ops.append(_Op("recv", _int_arg(c, "source", 0), None,
                               c.lineno))
        elif isinstance(call.func, ast.Name):
            if call.func.id == "MPI_Send":
                ops.append(_Op("send", _int_arg(call, "dest", 1),
                               _int_arg(call, "count", None), call.lineno))
            elif call.func.id == "MPI_Recv":
                ops.append(_Op("recv", _int_arg(call, "source", 0),
                               _int_arg(call, "count", None), call.lineno))
    return sorted(ops, key=lambda o: o.line)


def _rank_literal_branches(scope) -> Dict[Tuple[str, int], List[_Op]]:
    """rank-literal branch bodies of one scope: (comm, K) -> ordered
    send/recv ops, merged across every ``if comm.rank == K`` in it."""
    branches: Dict[Tuple[str, int], List[_Op]] = {}
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)) and n is not scope:
            continue
        if isinstance(n, ast.If):
            hit = _rank_eq_literal(n.test)
            if hit is not None:
                comm, k = hit
                branches.setdefault((comm, k), []).extend(
                    _branch_ops(comm, n.body))
        stack.extend(ast.iter_child_nodes(n))
    for ops in branches.values():
        ops.sort(key=lambda o: o.line)
    return branches


# -- MPL002 ------------------------------------------------------------------

def _first_line(ops: List[_Op], kind: str, peer: int) -> Optional[int]:
    for o in ops:
        if o.kind == kind and o.peer == peer:
            return o.line
    return None


def _check_send_send_cycles(branches, filename) -> List[Finding]:
    findings = []
    seen = set()
    for (comm, a), ops_a in branches.items():
        for (comm_b, b), ops_b in branches.items():
            if comm_b != comm or b <= a or (comm, a, b) in seen:
                continue
            sa, ra = _first_line(ops_a, "send", b), _first_line(ops_a, "recv", b)
            sb, rb = _first_line(ops_b, "send", a), _first_line(ops_b, "recv", a)
            if None in (sa, ra, sb, rb):
                continue
            if sa < ra and sb < rb:
                seen.add((comm, a, b))
                findings.append(Finding(
                    filename, sa, "MPL002",
                    f"send-send cycle: rank {a} sends to {b} (line {sa}) "
                    f"before receiving from it (line {ra}) while rank {b} "
                    f"sends to {a} (line {sb}) before receiving (line "
                    f"{rb}) — deadlocks under synchronous/rendezvous "
                    f"sends; use {comm}.sendrecv()"))
    return findings


# -- MPL003 ------------------------------------------------------------------

def _check_count_truncation(branches, filename) -> List[Finding]:
    findings = []
    for (comm, a), ops_a in branches.items():
        for (comm_b, b), ops_b in branches.items():
            if comm_b != comm:
                continue
            sends = [o for o in ops_a if o.kind == "send" and o.peer == b
                     and o.count is not None]
            recvs = [o for o in ops_b if o.kind == "recv"
                     and o.peer in (a, None) and o.count is not None]
            for s, r in zip(sends, recvs):
                if r.count < s.count:
                    findings.append(Finding(
                        filename, r.line, "MPL003",
                        f"recv count {r.count} < matching send count "
                        f"{s.count} (rank {a} line {s.line} -> rank {b}): "
                        f"the receive truncates the message"))
    return findings


# -- MPL004 ------------------------------------------------------------------

def _check_revoked_unhandled(tree, filename) -> List[Finding]:
    revoked: Dict[str, int] = {}
    handled: set = set()
    in_try: set = set()

    def mark_try(node, inside):
        inside = inside or isinstance(node, ast.Try)
        if inside:
            in_try.add(id(node))
        for c in ast.iter_child_nodes(node):
            mark_try(c, inside)

    mark_try(tree, False)
    for call in _calls_in([tree], into_defs=True):
        mc = _method_call(call)
        if mc is None:
            continue
        name, meth, _ = mc
        if meth == "revoke":
            revoked.setdefault(name, call.lineno)
        elif meth == "set_errhandler":
            handled.add(name)
    findings = []
    if not revoked:
        return findings
    flagged = set()
    for call in _calls_in([tree], into_defs=True):
        mc = _method_call(call)
        if mc is None:
            continue
        name, meth, _ = mc
        if (name in revoked and name not in handled and name not in flagged
                and meth in _P2P_OR_COLL and call.lineno > revoked[name]
                and id(call) not in in_try):
            flagged.add(name)
            findings.append(Finding(
                filename, call.lineno, "MPL004",
                f"{name}.{meth}() after {name}.revoke() (line "
                f"{revoked[name]}) with no error handler and outside "
                f"try: every operation on a revoked comm raises "
                f"RevokedError — install set_errhandler or shrink() "
                f"first"))
    return findings


# -- driver ------------------------------------------------------------------

def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), path)


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        findings += lint_file(os.path.join(root, fn))
        elif p.endswith(".py"):
            findings += lint_file(p)
    return findings
