"""Expert parallelism: a mixture-of-experts layer over MPI_Alltoall.

The EP strategy from the checklist (SURVEY.md §2 strategy table), expressed
through the framework's primitives: each rank hosts ONE expert MLP; tokens
are routed top-1, dispatched to their expert's rank with one all-to-all,
transformed, and combined back with a second all-to-all — the exact
communication shape of Switch-Transformer-style MoE, with static
capacity-based routing so the whole layer stays one fixed-shape SPMD
program (XLA-friendly: no dynamic shapes, drops handled by masking).

    python examples/moe.py --backend tpu -n 8
"""

import argparse
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np


def moe_layer(comm, x, w_router, w_in, w_out, capacity):
    """One MoE layer, expert-parallel over ``comm``.

    x: [T, D] local tokens.  w_router: [D, P] (replicated).  w_in/w_out:
    THIS rank's expert weights ([D, F], [F, D]).  Tokens beyond
    ``capacity`` per (source rank, expert) pair are dropped (output 0 —
    combine with a residual in real models).  Returns [T, D].
    """
    P = comm.size
    T, D = x.shape
    logits = x @ w_router                                   # [T, P]
    choice = jnp.argmax(logits, axis=-1)                    # [T]
    gate = jax.nn.softmax(logits, axis=-1)[jnp.arange(T), choice]

    # position of each token within its expert's dispatch block
    onehot = (choice[:, None] == jnp.arange(P)[None, :])    # [T, P]
    pos = jnp.cumsum(onehot, axis=0) - 1                    # [T, P]
    slot = jnp.take_along_axis(pos, choice[:, None], 1)[:, 0]  # [T]
    kept = slot < capacity

    # scatter tokens into [P, C, D] blocks (out-of-capacity slots drop)
    blocks = jnp.zeros((P, capacity, D), x.dtype)
    blocks = blocks.at[choice, jnp.where(kept, slot, capacity)].set(
        x, mode="drop")
    recv = jnp.asarray(comm.alltoall(blocks))               # [P, C, D]

    # this rank's expert transforms every token it received
    h = jax.nn.gelu(recv @ w_in)                            # [P, C, F]
    y = h @ w_out                                           # [P, C, D]

    back = jnp.asarray(comm.alltoall(y))                    # [P, C, D]
    # gather each local token's transformed value from (its expert, slot)
    out = back[choice, jnp.where(kept, slot, 0)]            # [T, D]
    return jnp.where(kept[:, None], out * gate[:, None], 0.0)


def moe_oracle(x_all, w_router, w_in_all, w_out_all, capacity):
    """Single-process reference: same routing/capacity rules, no comm.
    x_all: [P, T, D]; w_in_all/w_out_all: stacked expert weights."""
    P, T, D = x_all.shape
    out = np.zeros_like(x_all)
    for src in range(P):
        x = np.asarray(x_all[src])
        logits = x @ np.asarray(w_router)
        choice = logits.argmax(-1)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        gate = (e / e.sum(-1, keepdims=True))[np.arange(T), choice]
        counts = np.zeros(P, int)
        for t in range(T):
            ex = choice[t]
            if counts[ex] < capacity:
                h = np.asarray(jax.nn.gelu(x[t] @ w_in_all[ex]))
                out[src, t] = (h @ np.asarray(w_out_all[ex])) * gate[t]
            counts[ex] += 1
    return out


def moe_program(comm, tokens_per_rank: int = 16, d: int = 8, f: int = 16,
                capacity: int = 8):
    P = comm.size
    root = jax.random.PRNGKey(5)
    x = jax.random.normal(jax.random.fold_in(root, comm.rank),
                          (tokens_per_rank, d), jnp.float32)
    w_router = jax.random.normal(jax.random.fold_in(root, 1000), (d, P),
                                 jnp.float32)
    w_in = jax.random.normal(jax.random.fold_in(root, 2000 + comm.rank),
                             (d, f), jnp.float32) * 0.3
    w_out = jax.random.normal(jax.random.fold_in(root, 3000 + comm.rank),
                              (f, d), jnp.float32) * 0.3
    return moe_layer(comm, x, w_router, w_in, w_out, capacity)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None,
                    choices=[None, "socket", "shm", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--tokens-per-rank", type=int, default=16)
    args = ap.parse_args()

    out = mpi_tpu.run(moe_program, backend=args.backend, nranks=args.nranks,
                      tokens_per_rank=args.tokens_per_rank)
    first = out[0] if isinstance(out, list) else out
    o = np.asarray(jax.device_get(first))
    print(f"moe OK: local {o.shape}, |out| = {np.abs(o).mean():.4f}")


if __name__ == "__main__":
    main()
