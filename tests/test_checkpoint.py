"""Checkpoint / resume (SURVEY.md §5: slice-restart + checkpoint is the
TPU-native failure story; detection lives in recv_timeout/FaultyTransport)."""

import numpy as np
import pytest

from mpi_tpu import checkpoint, ops
from mpi_tpu.transport.local import run_local

P = 4


def test_process_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "ck")

    def prog(comm):
        state = {"w": np.full(3, float(comm.rank)), "step": comm.rank * 10}
        checkpoint.save(path, state, comm)
        assert checkpoint.exists(path)
        got = checkpoint.load(path, comm)
        return float(got["w"][0]), got["step"]

    res = run_local(prog, P)
    assert res == [(float(r), r * 10) for r in range(P)]


def test_partial_checkpoint_rejected(tmp_path):
    path = str(tmp_path / "ck")

    def prog(comm):
        (tmp_path / "ck" / f"rank{comm.rank}").mkdir(parents=True, exist_ok=True)
        # no manifest: simulates a crash between rank writes and commit
        try:
            checkpoint.load(path, comm)
            return False
        except FileNotFoundError:
            return True

    assert all(run_local(prog, 2))


def test_world_size_mismatch_rejected(tmp_path):
    path = str(tmp_path / "ck")

    def prog(comm):
        checkpoint.save(path, {"x": 1}, comm)
        return True

    assert all(run_local(prog, 2))

    def prog4(comm):
        try:
            checkpoint.load(path, comm)
            return False
        except ValueError:
            return True

    assert all(run_local(prog4, 4))


def test_resume_equivalence_jacobi(tmp_path):
    """50 iters + checkpoint + restore + 50 iters == 100 iters straight
    (the acceptance shape of resume)."""
    from examples.jacobi import jacobi_step

    path = str(tmp_path / "ck")

    def straight(comm):
        grid = np.zeros((16, 8))
        grid[0, :] = 1.0
        for _ in range(100):
            grid = jacobi_step(comm, grid)
        return grid

    def resumed(comm):
        grid = np.zeros((16, 8))
        grid[0, :] = 1.0
        for _ in range(50):
            grid = jacobi_step(comm, grid)
        checkpoint.save(path, {"grid": grid}, comm)
        grid2 = checkpoint.load(path, comm)["grid"]
        for _ in range(50):
            grid2 = jacobi_step(comm, grid2)
        return grid2

    a = run_local(straight, 2)
    b = run_local(resumed, 2)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sharded_checkpoint_roundtrip(tmp_path):
    """orbax path: a sharded global array round-trips to the same layout."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    from mpi_tpu.tpu import default_mesh

    mesh = default_mesh()  # all visible devices
    n = len(jax.devices())
    sh = NamedSharding(mesh, Pspec("world"))
    x = jax.device_put(jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4), sh)
    state = {"w": x, "b": jnp.ones(3)}
    checkpoint.save_sharded(str(tmp_path / "sck"), state)
    tpl = {"w": jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh),
           "b": jnp.zeros(3)}
    got = checkpoint.load_sharded(str(tmp_path / "sck"), tpl)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(got["b"]), np.ones(3))
    assert got["w"].sharding == sh


def test_resave_crash_keeps_prior_generation(tmp_path, monkeypatch):
    """A crash anywhere during a re-save must leave the PRIOR checkpoint
    restorable: the new generation is written aside and the manifest swings
    atomically only after every rank has committed its state.  (1-rank
    world: a crashing rank would strand peers at the barrier, which is
    exactly the hang the manifest protocol is designed around.)"""
    import os as _os

    import mpi_tpu.checkpoint as ck

    path = str(tmp_path / "ck")

    def prog(comm):
        ck.save(path, {"step": 100}, comm)
        assert ck.exists(path)
        real_replace = _os.replace

        def boom(src, dst):
            if dst.endswith("manifest.json"):
                raise RuntimeError("crash before commit")
            return real_replace(src, dst)

        monkeypatch.setattr("os.replace", boom)
        try:
            ck.save(path, {"step": 200}, comm)
            return False
        except RuntimeError:
            pass
        finally:
            monkeypatch.setattr("os.replace", real_replace)
        # the old generation survived the crashed re-save
        assert ck.exists(path)
        assert ck.load(path, comm) == {"step": 100}
        # and a subsequent clean re-save commits the new state
        ck.save(path, {"step": 300}, comm)
        return ck.load(path, comm) == {"step": 300}

    assert all(run_local(prog, 1))
