"""Intercommunicators (MPI_Intercomm_create/merge, two-group semantics)."""

import numpy as np
import pytest

from mpi_tpu import create_intercomm, ops
from mpi_tpu.communicator import Status
from mpi_tpu.intercomm import PROC_NULL, ROOT
from mpi_tpu.transport.local import run_local

A, B = [0, 1, 2], [3, 4]  # 3-rank group coupled to a 2-rank group


def _mk(comm):
    return create_intercomm(comm, A, B)


def test_identity_and_sizes():
    def prog(comm):
        ic = _mk(comm)
        return ic.rank, ic.size, ic.remote_size, ic.is_inter

    res = run_local(prog, 5)
    assert res[0] == (0, 3, 2, True)
    assert res[2] == (2, 3, 2, True)
    assert res[3] == (0, 2, 3, True)
    assert res[4] == (1, 2, 3, True)


def test_p2p_addresses_remote_group():
    def prog(comm):
        ic = _mk(comm)
        if comm.rank in A:
            # A-rank i sends to B-rank i%2 with its own id
            ic.send(("from-A", ic.rank), dest=ic.rank % 2, tag=5)
            return None
        got = []
        st = Status()
        for _ in range(2 if ic.rank == 0 else 1):
            got.append((ic.recv(source=-1, tag=5, status=st), st.source))
        return sorted(got)

    res = run_local(prog, 5)
    # B-rank 0 (world 3) hears from A-ranks 0 and 2; B-rank 1 from A-rank 1
    assert [v for v, _ in res[3]] == [("from-A", 0), ("from-A", 2)]
    assert all(0 <= s < 3 for _, s in res[3])  # sources are REMOTE ranks
    assert res[4] == [(("from-A", 1), 1)]


def test_rooted_bcast():
    def prog(comm):
        ic = _mk(comm)
        if comm.rank in A:
            root = ROOT if ic.rank == 1 else PROC_NULL
            return ic.bcast(("payload", 42), root)
        return ic.bcast(None, 1)  # root is A-rank 1, seen from B

    res = run_local(prog, 5)
    assert res[3] == res[4] == ("payload", 42)


def test_allgather_and_allreduce_cross_group():
    def prog(comm):
        ic = _mk(comm)
        mine = 10 * (ic.rank + 1) if comm.rank in A else -(ic.rank + 1)
        return ic.allgather(mine), ic.allreduce(mine, op=ops.SUM)

    res = run_local(prog, 5)
    # A side sees B's contributions; B side sees A's
    assert res[0] == ([-1, -2], -3)
    assert res[3] == ([10, 20, 30], 60)


def test_alltoall_cross_group():
    def prog(comm):
        ic = _mk(comm)
        objs = [(ic.rank, j) for j in range(ic.remote_size)]
        return ic.alltoall(objs)

    res = run_local(prog, 5)
    assert res[0] == [(0, 0), (1, 0)]      # A-rank 0 hears from B-ranks 0,1
    assert res[3] == [(0, 0), (1, 0), (2, 0)]
    assert res[4] == [(0, 1), (1, 1), (2, 1)]


def test_merge_orders_low_group_first():
    def prog(comm):
        ic = _mk(comm)
        merged = ic.merge(high=comm.rank in B)  # A low, B high
        return merged.rank, merged.size, merged.allreduce(comm.rank)

    res = run_local(prog, 5)
    assert [res[r][0] for r in range(5)] == [0, 1, 2, 3, 4]
    assert all(r[1] == 5 and r[2] == sum(range(5)) for r in res)


def test_merge_high_group_first():
    def prog(comm):
        ic = _mk(comm)
        merged = ic.merge(high=comm.rank in A)  # B low this time
        return merged.rank

    res = run_local(prog, 5)
    assert [res[r] for r in range(5)] == [2, 3, 4, 0, 1]


def test_nonmembers_get_none_and_validation():
    def prog(comm):
        ic = create_intercomm(comm, [0], [2])
        return None if ic is None else ic.rank

    res = run_local(prog, 4)
    assert res == [0, None, 0, None]

    def bad(comm):
        try:
            create_intercomm(comm, [0, 1], [1, 2])
        except ValueError as e:
            return "disjoint" in str(e)

    assert all(run_local(bad, 3))


def test_intercomm_isolated_from_parent_traffic():
    """Intercomm p2p must never match a recv on the parent communicator
    (fresh context via split)."""
    def prog(comm):
        ic = _mk(comm)
        if comm.rank == 0:
            ic.send("inter", dest=0, tag=7)      # to B-rank 0 == world 3
            comm.send("intra", dest=3, tag=7)    # parent-path message
            return None
        if comm.rank == 3:
            intra = comm.recv(source=0, tag=7)
            inter = ic.recv(source=0, tag=7)
            return intra, inter
        return None

    res = run_local(prog, 5)
    assert res[3] == ("intra", "inter")


def test_spmd_backend_diagnostic():
    from mpi_tpu.tpu import TpuCommunicator, default_mesh

    comm = TpuCommunicator("world", default_mesh())
    with pytest.raises(NotImplementedError, match="split_by"):
        create_intercomm(comm, [0, 1], [2, 3])


def test_create_accepts_group_objects_and_validates():
    from mpi_tpu import Group

    def prog(comm):
        ic = create_intercomm(comm, Group([0, 1]), Group([2]))
        out = None if ic is None else (ic.rank, ic.remote_size)
        try:
            create_intercomm(comm, [0, 0], [1])
            dup_ok = False
        except ValueError:
            dup_ok = True
        try:
            create_intercomm(comm, [0], [])
            empty_ok = False
        except ValueError:
            empty_ok = True
        return out, dup_ok, empty_ok

    res = run_local(prog, 3)
    assert res[0] == ((0, 1), True, True)
    assert res[2] == ((0, 2), True, True)


def test_wildcard_recv_cannot_steal_collective_payload():
    """Internal collective tags are negative: a user ANY_TAG recv must
    never match a bcast payload (code-review regression)."""
    def prog(comm):
        ic = _mk(comm)
        if comm.rank in A:
            root = ROOT if ic.rank == 0 else PROC_NULL
            ic.bcast("SECRET", root)
            if ic.rank == 0:
                ic.send("user-msg", dest=0, tag=9)
            return None
        if ic.rank == 0:
            got = ic.recv(source=-1, tag=-1)   # wildcard BEFORE bcast recv
            secret = ic.bcast(None, 0)
            return got, secret
        return None, ic.bcast(None, 0)

    res = run_local(prog, 5)
    assert res[3] == ("user-msg", "SECRET")
    assert res[4][1] == "SECRET"
