"""Dynamic process management (mpi_tpu/spawn.py): comm_spawn children get
a working world of their own plus the parent-child intercomm."""

import os
import sys
import textwrap

import pytest

import mpi_tpu
from mpi_tpu import spawn
from mpi_tpu.transport.local import run_local

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    import mpi_tpu
    from mpi_tpu import spawn

    comm = mpi_tpu.COMM_WORLD          # the CHILD world
    parent = spawn.comm_get_parent()
    assert parent is not None and parent.is_inter
    assert spawn.comm_get_parent() is parent  # cached
    assert parent.remote_size == {nparents}
    assert parent.size == comm.size
    x = parent.recv(source=0)          # work item from parent rank 0
    total = comm.allreduce(x + comm.rank)   # child-world collective works
    if comm.rank == 0:
        parent.send(("result", total), dest=0)
    """)


def _worker_script(tmp_path, nparents: int) -> str:
    path = tmp_path / "spawn_worker.py"
    path.write_text(WORKER.format(repo=REPO, nparents=nparents))
    return str(path)


def test_spawn_from_standalone_parent(tmp_path):
    script = _worker_script(tmp_path, nparents=1)
    parent = mpi_tpu.comm_self()
    inter = spawn.comm_spawn([script], 2, comm=parent)
    assert inter.remote_size == 2 and inter.size == 1
    for j in range(2):
        inter.send(10, dest=j)
    kind, total = inter.recv(source=0)
    # children allreduce (10 + rank) over their 2-rank world: 10+0 + 10+1
    assert (kind, total) == ("result", 21)
    inter.free()


def test_spawn_from_multirank_parent(tmp_path):
    """Two in-process parent ranks spawn one shared child world; child
    bridge addressing reaches the right parent."""
    script = _worker_script(tmp_path, nparents=2)

    def prog(comm):
        inter = spawn.comm_spawn([script], 2, comm=comm, root=0)
        assert inter.remote_size == 2 and inter.size == 2
        if comm.rank == 0:
            inter.send(5, dest=0)
            inter.send(5, dest=1)
            out = inter.recv(source=0)
        else:
            out = None
        comm.barrier()
        inter.free()
        return out

    res = run_local(prog, 2)
    assert res[0] == ("result", 11)  # (5+0) + (5+1)


def test_spawn_multiple_segments(tmp_path):
    """spawn_multiple: two different scripts share ONE child world with
    segment-ordered ranks."""
    a = tmp_path / "seg_a.py"
    b = tmp_path / "seg_b.py"
    common = textwrap.dedent(f"""\
        import sys
        sys.path.insert(0, {REPO!r})
        import mpi_tpu
        from mpi_tpu import spawn
        comm = mpi_tpu.COMM_WORLD
        parent = spawn.comm_get_parent()
        """)
    a.write_text(common + textwrap.dedent("""\
        roles = comm.allgather("a")
        if comm.rank == 0:
            parent.send(roles, dest=0)
        """))
    b.write_text(common + 'comm.allgather("b")\n')
    parent = mpi_tpu.comm_self()
    inter = spawn.comm_spawn_multiple([([str(a)], 1), ([str(b)], 2)],
                                      comm=parent)
    assert inter.remote_size == 3
    roles = inter.recv(source=0)
    assert roles == ["a", "b", "b"]
    inter.free()


def test_spawn_rejects_spmd_comm():
    def prog(comm):
        with pytest.raises(NotImplementedError, match="launcher"):
            spawn.comm_spawn(["x.py"], 1, comm=comm)
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


def test_get_parent_none_when_not_spawned():
    assert spawn.comm_get_parent() is None


# -- connect/accept between independent jobs (MPI-2 ch.5.4, round 3) --------


def test_connect_accept_joins_independent_jobs(tmp_path):
    """A server job (2 in-process ranks) accepts a client job (thread
    world started independently); p2p + inter-collectives flow across."""
    import threading

    port = spawn.open_port()
    results = {}

    def server():
        def prog(comm):
            inter = spawn.comm_accept(port, comm=comm)
            assert inter.remote_size == 1 and inter.size == 2
            if comm.rank == 0:
                got = inter.recv(source=0)
                inter.send(got * 2, dest=0)
            comm.barrier()
            theirs = inter.allgather(("srv", comm.rank))
            inter.free()
            return theirs

        results["server"] = run_local(prog, 2)

    def client():
        def prog(comm):
            inter = spawn.comm_connect(port, comm=comm)
            assert inter.remote_size == 2 and inter.size == 1
            inter.send(21, dest=0)
            assert inter.recv(source=0) == 42
            theirs = inter.allgather(("cli", comm.rank))
            inter.free()
            return theirs

        results["client"] = run_local(prog, 1)

    ts = threading.Thread(target=server)
    tc = threading.Thread(target=client)
    ts.start(); tc.start()
    ts.join(120); tc.join(120)
    assert not ts.is_alive() and not tc.is_alive()
    # each side sees the REMOTE group's contributions in remote rank order
    assert results["server"][0] == [("cli", 0)]
    assert results["client"][0] == [("srv", 0), ("srv", 1)]
    spawn.close_port(port)


def test_connect_timeout_is_loud(tmp_path):
    port = spawn.open_port()
    with pytest.raises(TimeoutError, match="other side"):
        spawn.comm_connect(port, comm=mpi_tpu.comm_self(), timeout=0.3)
    spawn.close_port(port)


def test_port_reusable_and_close_after_accept_safe():
    """A server accepts TWO sequential clients on one port (per-round
    bridge rendezvous), and close_port after establishment does not break
    later intercomm traffic (review round 3)."""
    import threading

    port = spawn.open_port()
    results = {}

    def server():
        comm = mpi_tpu.comm_self()
        inters = [spawn.comm_accept(port, comm=comm) for _ in range(2)]
        spawn.close_port(port)  # port gone; bridges must keep working
        got = []
        for inter in inters:
            x = inter.recv(source=0)
            inter.send(x * 10, dest=0)
            got.append(x)
            inter.free()
        results["server"] = sorted(got)

    def client(k):
        comm = mpi_tpu.comm_self()
        inter = spawn.comm_connect(port, comm=comm)
        inter.send(k, dest=0)
        results[f"cli{k}"] = inter.recv(source=0)
        inter.free()

    ts = threading.Thread(target=server)
    t1 = threading.Thread(target=client, args=(1,))
    t2 = threading.Thread(target=client, args=(2,))
    ts.start(); t1.start(); t2.start()
    for t in (ts, t1, t2):
        t.join(90)
    assert not any(t.is_alive() for t in (ts, t1, t2))
    assert results["server"] == [1, 2]
    assert results["cli1"] == 10 and results["cli2"] == 20


def test_accept_timeout_raises_on_every_rank():
    """A handshake timeout must raise everywhere, not strand non-root
    ranks in the outcome bcast (review round 3)."""
    port = spawn.open_port()

    def prog(comm):
        with pytest.raises(TimeoutError, match="handshake|other side"):
            spawn.comm_accept(port, comm=comm, timeout=0.3)
        return "ok"

    assert run_local(prog, 2) == ["ok", "ok"]
    spawn.close_port(port)


def test_name_service_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv(spawn.ENV_NAMESERVICE, str(tmp_path))
    port = spawn.open_port()
    spawn.publish_name("ocean-model", port)
    assert spawn.lookup_name("ocean-model") == port
    with pytest.raises(LookupError, match="no service"):
        spawn.lookup_name("atmosphere")
    with pytest.raises(ValueError, match="plain tokens"):
        spawn.publish_name("../evil", port)
    spawn.unpublish_name("ocean-model")
    with pytest.raises(LookupError):
        spawn.lookup_name("ocean-model")
    spawn.unpublish_name("ocean-model")  # idempotent
    spawn.close_port(port)


def test_name_service_with_connect_accept(tmp_path, monkeypatch):
    """The full ch.5.4 flow: server publishes a name, client looks it up
    and connects."""
    import threading

    monkeypatch.setenv(spawn.ENV_NAMESERVICE, str(tmp_path))
    results = {}

    def server():
        port = spawn.open_port()
        spawn.publish_name("calc", port)
        inter = spawn.comm_accept(port, comm=mpi_tpu.comm_self())
        inter.send(inter.recv(source=0) ** 2, dest=0)
        inter.free()
        spawn.unpublish_name("calc")
        spawn.close_port(port)

    def client():
        port = spawn.lookup_name("calc", timeout=30)
        inter = spawn.comm_connect(port, comm=mpi_tpu.comm_self())
        inter.send(12, dest=0)
        results["got"] = inter.recv(source=0)
        inter.free()

    ts = threading.Thread(target=server)
    tc = threading.Thread(target=client)
    ts.start(); tc.start()
    ts.join(60); tc.join(60)
    assert results["got"] == 144


def test_stale_connect_request_skipped(tmp_path):
    """A timed-out client's stale request must not poison the port: the
    next accept skips it and serves the live client (review round 3)."""
    import threading

    port = spawn.open_port()
    # dead client: times out, leaves connect.<token>.json behind
    with pytest.raises(TimeoutError):
        spawn.comm_connect(port, comm=mpi_tpu.comm_self(), timeout=0.3)
    assert any(n.startswith("connect.") for n in os.listdir(port))
    results = {}

    def server():
        inter = spawn.comm_accept(port, comm=mpi_tpu.comm_self(), timeout=30)
        results["size"] = inter.remote_size
        inter.send("hi", dest=0)
        inter.free()

    def client():
        inter = spawn.comm_connect(port, comm=mpi_tpu.comm_self(), timeout=30)
        results["msg"] = inter.recv(source=0)
        inter.free()

    ts = threading.Thread(target=server)
    tc = threading.Thread(target=client)
    ts.start(); tc.start()
    ts.join(60); tc.join(60)
    assert results == {"size": 1, "msg": "hi"}
    spawn.close_port(port)


def test_name_dir_rejects_foreign_or_loose_dir(tmp_path, monkeypatch):
    loose = tmp_path / "registry"
    loose.mkdir(mode=0o777)
    os.chmod(loose, 0o777)  # umask-proof
    monkeypatch.setenv(spawn.ENV_NAMESERVICE, str(loose))
    with pytest.raises(PermissionError, match="refusing"):
        spawn.publish_name("svc", "/tmp/x")
