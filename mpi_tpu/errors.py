"""MPI error classes and error handlers (MPI-1 §7 error handling [S]).

Pythonic contract, stated honestly rather than emulated blindly:

* The object API (``comm.send(...)`` etc.) raises Python exceptions —
  that IS this library's native error reporting, and with the default
  handler an uncaught exception kills the rank, which the launcher
  escalates to kill-all (the MPI_ERRORS_ARE_FATAL behavior, SURVEY.md §2
  component #1's exit-code contract).
* The flat ``MPI_*`` layer (api.py) additionally honors per-communicator
  error handlers, like the C API:
    - :data:`ERRORS_ARE_FATAL` (default) — exceptions propagate;
    - :data:`ERRORS_RETURN` — the call returns an :class:`ErrorCode`
      (an int subclass carrying the error class and the exception) in
      place of its result, the closest value-semantics analogue of C's
      "return the code, results via out-params";
    - any callable ``handler(comm, exc)`` — its return value becomes the
      call's result (custom MPI_Errhandler).
* :func:`error_class` classifies an exception into the standard MPI
  error-class constants; :func:`error_string` renders them.
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = [
    "MPI_SUCCESS", "MPI_ERR_BUFFER", "MPI_ERR_COUNT", "MPI_ERR_TYPE",
    "MPI_ERR_TAG", "MPI_ERR_COMM", "MPI_ERR_RANK", "MPI_ERR_REQUEST",
    "MPI_ERR_ROOT", "MPI_ERR_GROUP", "MPI_ERR_OP", "MPI_ERR_TOPOLOGY",
    "MPI_ERR_DIMS", "MPI_ERR_ARG", "MPI_ERR_UNKNOWN", "MPI_ERR_TRUNCATE",
    "MPI_ERR_OTHER", "MPI_ERR_INTERN", "MPI_ERR_PENDING", "MPI_ERR_IO",
    "MPI_ERR_PROC_FAILED", "MPI_ERR_REVOKED",
    "ERRORS_ARE_FATAL", "ERRORS_RETURN", "ErrorCode",
    "ProcFailedError", "RevokedError",
    "EpochSkewError", "RejoinRefusedError",
    "DeadlockError", "CollectiveMismatchError",
    "ServerBusyError", "NoQuorumError", "BufferPinnedError",
    "error_class", "error_string",
]

MPI_SUCCESS = 0
MPI_ERR_BUFFER = 1
MPI_ERR_COUNT = 2
MPI_ERR_TYPE = 3
MPI_ERR_TAG = 4
MPI_ERR_COMM = 5
MPI_ERR_RANK = 6
MPI_ERR_REQUEST = 7
MPI_ERR_ROOT = 8
MPI_ERR_GROUP = 9
MPI_ERR_OP = 10
MPI_ERR_TOPOLOGY = 11
MPI_ERR_DIMS = 12
MPI_ERR_ARG = 13
MPI_ERR_UNKNOWN = 14
MPI_ERR_TRUNCATE = 15
MPI_ERR_OTHER = 16
MPI_ERR_INTERN = 17
MPI_ERR_PENDING = 18
MPI_ERR_IO = 19
# ULFM (MPI Forum User-Level Failure Mitigation proposal) error classes:
# a peer process is known dead / the communicator was revoked.
MPI_ERR_PROC_FAILED = 20
MPI_ERR_REVOKED = 21

_STRINGS = {
    MPI_SUCCESS: "no error",
    MPI_ERR_BUFFER: "invalid buffer",
    MPI_ERR_COUNT: "invalid count",
    MPI_ERR_TYPE: "invalid datatype",
    MPI_ERR_TAG: "invalid tag",
    MPI_ERR_COMM: "invalid communicator",
    MPI_ERR_RANK: "invalid rank",
    MPI_ERR_REQUEST: "invalid request",
    MPI_ERR_ROOT: "invalid root",
    MPI_ERR_GROUP: "invalid group",
    MPI_ERR_OP: "invalid reduce operation",
    MPI_ERR_TOPOLOGY: "invalid topology",
    MPI_ERR_DIMS: "invalid dimensions",
    MPI_ERR_ARG: "invalid argument",
    MPI_ERR_UNKNOWN: "unknown error",
    MPI_ERR_TRUNCATE: "message truncated on receive",
    MPI_ERR_OTHER: "known error not in this list",
    MPI_ERR_INTERN: "internal error",
    MPI_ERR_PENDING: "pending operation (timeout)",
    MPI_ERR_IO: "I/O error",
    MPI_ERR_PROC_FAILED: "peer process has failed",
    MPI_ERR_REVOKED: "communicator has been revoked",
}


class ProcFailedError(RuntimeError):
    """MPI_ERR_PROC_FAILED [S: ULFM]: an operation could not complete
    because a member of the communicator is dead — detected either by the
    liveness layer (mpi_tpu/ft.py heartbeat detector) or by transport
    evidence (failed send / recv timeout on a suspected peer).  Carries
    the suspected comm ranks and, for collective waits, which collective
    and pipeline segment was in flight when the death surfaced."""

    def __init__(self, msg: str, failed=(), collective: Optional[str] = None,
                 segment: Optional[int] = None):
        super().__init__(msg)
        self.failed = tuple(failed)
        self.collective = collective
        self.segment = segment

    def __str__(self) -> str:
        base = super().__str__()
        bits = []
        if self.failed:
            bits.append(f"failed ranks {list(self.failed)}")
        if self.collective:
            bits.append(f"in {self.collective}")
        if self.segment is not None:
            bits.append(f"segment {self.segment}")
        return f"{base} [{', '.join(bits)}]" if bits else base


class RevokedError(RuntimeError):
    """MPI_ERR_REVOKED [S: ULFM]: the communicator was revoked
    (``comm.revoke()`` on any rank); every pending and future p2p or
    collective operation on it raises this — the mechanism that unblocks
    survivors who were not themselves talking to a dead rank."""


class EpochSkewError(RuntimeError):
    """Elastic-membership generation mismatch (mpi_tpu/membership.py):
    this process tried to talk to a peer from a DIFFERENT membership
    epoch — it was shrunk out (false suspicion or real death) and the
    survivors moved on, or it is re-handshaking against endpoints a
    replacement re-created under a newer epoch.  Raised instead of
    silently cross-wiring two world generations (the FT residual-(b)
    group-split hang, diagnosed).  Carries both epochs and the peer."""

    def __init__(self, msg: str, local_epoch: Optional[int] = None,
                 peer_epoch: Optional[int] = None,
                 peer: Optional[int] = None):
        super().__init__(msg)
        self.local_epoch = local_epoch
        self.peer_epoch = peer_epoch
        self.peer = peer


class RejoinRefusedError(RuntimeError):
    """A rejoin claim was refused by the survivors (mpi_tpu/membership):
    most commonly a falsely-suspected-but-live incarnation trying to
    re-enter its old slot before the survivors ``failure_ack``ed its
    failure — re-admitting it would resurrect the very split the epoch
    protocol exists to prevent.  Ousted processes must come back as a
    FRESH incarnation (or wait for acknowledgement)."""


class ServerBusyError(RuntimeError):
    """Admission-control rejection from a resident world server
    (mpi_tpu/serve.py): the server's acquire queue is at its bounded
    depth (``max_pending``), so instead of joining an unboundedly long
    wait the request is rejected IMMEDIATELY with this named error —
    the client should back off, retry, or fail over to another
    federation member.  Sustained overload therefore degrades into
    explicit, named rejections with bounded queueing latency for the
    admitted requests, never into silent multi-minute acquire tails."""


class NoQuorumError(RuntimeError):
    """The replicated namespace store (mpi_tpu/federation_store.py)
    cannot commit: this node sits on the MINORITY side of a partition
    (or the Raft group has lost its majority), so no write — lease
    renew, ownership record, takeover assignment — can be
    quorum-acknowledged.  A federation server raises this on acquire
    instead of serving on stale namespace state (minority refuses,
    majority serves); a :class:`~mpi_tpu.federation.FederatedClient`
    treats it as a failover signal and moves to a majority-side
    server.  Reads are not gated (local applied state is served
    stale-but-honest); only mutations and authority claims are."""


class BufferPinnedError(RuntimeError):
    """Persistent-collective double-buffer fence (mpi_tpu/nbc.py, with
    the runtime verifier on): ``start()`` of round k would overwrite
    the working buffer that still backs round k-2's result, and the
    caller STILL HOLDS a reference to that result (or a view of it) —
    the silent-corruption half of the double-buffer contract.  Copy
    the result (``np.array(r)``) before holding it across two later
    ``start()``s."""


class DeadlockError(RuntimeError):
    """The runtime verifier (mpi_tpu/verify) proved a wait-for
    cycle/knot: every rank in ``ranks`` is blocked, and none of their
    pending operations can ever be satisfied by a rank outside the
    blocked set.  Raised INSTEAD of hanging, on every deadlocked rank,
    with the full cross-rank blocking picture (``table`` maps each
    world rank to its published pending-op entry; the message renders
    every rank, its pending op, and its call site — the MUST-style
    deadlock report)."""

    def __init__(self, msg: str, ranks=(), table: Optional[dict] = None):
        super().__init__(msg)
        self.ranks = tuple(ranks)
        self.table = dict(table or {})


class CollectiveMismatchError(RuntimeError):
    """The runtime verifier's collective-matching check failed: two
    ranks of the same communicator entered collectives with divergent
    signatures — different collective order, mismatched roots,
    mismatched reduce ops, mismatched payload geometry, or divergent
    vector counts (the truncating-recv case).  Carries both ranks,
    both signatures, and both call sites; raised on EVERY rank of the
    communicator (each sees the full signature ring), so no rank is
    left blocked inside the mismatched collective."""

    def __init__(self, msg: str, ranks=(), signatures=(), sites=()):
        super().__init__(msg)
        self.ranks = tuple(ranks)
        self.signatures = tuple(signatures)
        self.sites = tuple(sites)


class _FatalHandler:
    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return "ERRORS_ARE_FATAL"


class _ReturnHandler:
    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return "ERRORS_RETURN"


ERRORS_ARE_FATAL = _FatalHandler()
ERRORS_RETURN = _ReturnHandler()


class ErrorCode(int):
    """An MPI error code: an int (comparable to the MPI_ERR_* constants)
    that also carries the originating exception for diagnosis."""

    exception: Optional[BaseException]

    def __new__(cls, code: int, exception: Optional[BaseException] = None):
        self = super().__new__(cls, code)
        self.exception = exception
        return self

    @classmethod
    def from_exception(cls, exc: BaseException) -> "ErrorCode":
        return cls(error_class(exc), exc)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return (f"ErrorCode({int(self)}: {error_string(int(self))}"
                f"{f', from {self.exception!r}' if self.exception else ''})")


# word-pattern → class, first hit wins; keep specific words before generic
# ones.  \b boundaries so short keys don't fire inside unrelated words
# ("op" in "open", "source" in "resource", "tag" in "storage").
import re as _re

_CLASSIFY = [(_re.compile(p), c) for p, c in [
    (r"\btags?\b", MPI_ERR_TAG),
    (r"\branks?\b", MPI_ERR_RANK),
    (r"\bdest\b", MPI_ERR_RANK),
    (r"\bsource\b", MPI_ERR_RANK),
    (r"\broot\b", MPI_ERR_ROOT),
    (r"\bcounts?\b", MPI_ERR_COUNT),
    (r"truncat", MPI_ERR_TRUNCATE),
    (r"payload has", MPI_ERR_TRUNCATE),
    (r"\bdatatype\b", MPI_ERR_TYPE),
    (r"\bdtype\b", MPI_ERR_TYPE),
    (r"\bcommunicator\b", MPI_ERR_COMM),
    (r"\bgroups?\b", MPI_ERR_GROUP),
    (r"\balgorithm\b", MPI_ERR_OP),
    (r"\bops?\b", MPI_ERR_OP),
    (r"topolog", MPI_ERR_TOPOLOGY),
    (r"\bdims?\b", MPI_ERR_DIMS),
    (r"\bbuffers?\b", MPI_ERR_BUFFER),
    (r"\bfiles?\b", MPI_ERR_IO),
]]


def error_class(exc: Any) -> int:
    """Classify an exception (or an ErrorCode) into an MPI error class."""
    if isinstance(exc, ErrorCode):
        return int(exc)
    if isinstance(exc, int):
        return exc
    if isinstance(exc, ProcFailedError):
        return MPI_ERR_PROC_FAILED
    if isinstance(exc, RevokedError):
        return MPI_ERR_REVOKED
    if isinstance(exc, EpochSkewError):
        # the stale side's world generation is dead to the survivors —
        # the closest ULFM class is "your communicator was revoked"
        return MPI_ERR_REVOKED
    if isinstance(exc, RejoinRefusedError):
        return MPI_ERR_PROC_FAILED  # refused BECAUSE it is a declared corpse
    if isinstance(exc, DeadlockError):
        return MPI_ERR_PENDING  # operations pending forever: the closest class
    if isinstance(exc, CollectiveMismatchError):
        return MPI_ERR_OTHER
    if isinstance(exc, ServerBusyError):
        # overload is a transient resource condition, not an argument
        # error: the caller's request was well-formed and may succeed
        # on retry/failover — the generic class is the honest one
        return MPI_ERR_OTHER
    if isinstance(exc, NoQuorumError):
        # same shape as overload: transient fabric condition, the
        # request may succeed on a majority-side server
        return MPI_ERR_OTHER
    if isinstance(exc, BufferPinnedError):
        return MPI_ERR_BUFFER
    from .transport.base import RecvTimeout  # local import: no cycle at load

    if isinstance(exc, RecvTimeout):
        return MPI_ERR_PENDING
    if isinstance(exc, (OSError, IOError)):
        return MPI_ERR_IO
    msg = str(exc).lower()
    if isinstance(exc, (TypeError,)) and ("dtype" in msg or "datatype" in msg):
        return MPI_ERR_TYPE
    if isinstance(exc, (ValueError, KeyError, IndexError, TypeError)):
        for pat, code in _CLASSIFY:
            if pat.search(msg):
                return code
        return MPI_ERR_ARG
    return MPI_ERR_OTHER


def error_string(code: int) -> str:
    return _STRINGS.get(int(code), f"invalid error class {int(code)}")


def invoke_handler(comm: Any, exc: BaseException) -> Any:
    """Dispatch ``exc`` through ``comm``'s error handler (api.py boundary)."""
    get = getattr(comm, "get_errhandler", None)
    handler = get() if get is not None else ERRORS_ARE_FATAL
    if handler is ERRORS_ARE_FATAL:
        raise exc
    if handler is ERRORS_RETURN:
        return ErrorCode.from_exception(exc)
    return handler(comm, exc)
