"""Cartesian topology (MPI_Cart_*) semantics on both backends, plus the 2-D
Jacobi example's cross-backend / cross-decomposition parity (SURVEY.md §4
item 4: same user program, byte-for-byte, on every backend)."""

import numpy as np
import pytest

from mpi_tpu import CartComm, cart_create, dims_create, ops
from mpi_tpu.topology import Pair  # noqa: F401  (re-export sanity)
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import run_spmd

from examples.jacobi import jacobi_program
from examples.jacobi2d import jacobi2d_program

P = 8


# -- pure coordinate math --------------------------------------------------


def test_dims_create_balanced():
    assert dims_create(8, 2) == [4, 2]
    assert dims_create(8, 3) == [2, 2, 2]
    assert dims_create(12, 2) == [4, 3]
    assert dims_create(7, 2) == [7, 1]
    assert dims_create(1, 3) == [1, 1, 1]
    assert np.prod(dims_create(360, 3)) == 360


def test_coords_rank_roundtrip():
    class FakeComm:
        size, rank = 24, 0

        def exchange(self, *a, **k):  # pragma: no cover
            raise AssertionError

    cart = CartComm(FakeComm(), (2, 3, 4))
    for r in range(24):
        assert cart.rank_of(cart.coords_of(r)) == r
    assert cart.coords_of(0) == (0, 0, 0)
    assert cart.coords_of(23) == (1, 2, 3)  # row-major (C order), like MPI
    assert cart.coords_of(4) == (0, 1, 0)


def test_rank_of_periodic_wrap_and_proc_null():
    class FakeComm:
        size, rank = 6, 0

    cart = CartComm(FakeComm(), (2, 3), periods=(True, False))
    assert cart.rank_of((-1, 0)) == cart.rank_of((1, 0))  # periodic wraps
    assert cart.rank_of((0, -1)) is None  # MPI_PROC_NULL
    assert cart.rank_of((0, 3)) is None


def test_shift_perm_is_valid_partial_permutation():
    from mpi_tpu.checker import validate_perm

    class FakeComm:
        size, rank = 12, 0

    cart = CartComm(FakeComm(), (3, 4), periods=(True, False))
    for dim in (0, 1):
        for disp in (1, -1, 2):
            pairs = cart.shift_perm(dim, disp)
            validate_perm(pairs, 12)
    # periodic dim: every rank sends and receives
    assert len(cart.shift_perm(0, 1)) == 12
    # non-periodic dim, |disp|=1: one column of senders drops out
    assert len(cart.shift_perm(1, 1)) == 9


def test_cart_size_mismatch_rejected():
    class FakeComm:
        size, rank = 5, 0

    with pytest.raises(ValueError, match="prod"):
        CartComm(FakeComm(), (2, 3))


# -- shift / exchange on the process backend -------------------------------


def test_cart_shift_local():
    def prog(comm):
        cart = cart_create(comm, (2, 3), periods=(False, True))
        src0, dst0 = cart.shift(0, 1)
        src1, dst1 = cart.shift(1, 1)
        return cart.coords_of(comm.rank), src0, dst0, src1, dst1

    res = run_local(prog, 6)
    coords, src0, dst0, _, _ = res[0]  # rank 0 = (0, 0)
    assert coords == (0, 0)
    assert src0 is None and dst0 == 3  # non-periodic rows
    _, _, _, src1, dst1 = res[2]  # rank 2 = (0, 2): periodic cols wrap
    assert dst1 == 0 and src1 == 1


def test_cart_exchange_local():
    def prog(comm):
        cart = cart_create(comm, (2, 2))
        got = cart.exchange(np.float64(comm.rank), dim=1, disp=1, fill=-1.0)
        return float(np.asarray(got))

    res = run_local(prog, 4)
    # (r, c) receives from (r, c-1); c=0 holes filled
    assert res == [-1.0, 0.0, -1.0, 2.0]


def test_cart_sub_local():
    def prog(comm):
        cart = cart_create(comm, (2, 3))
        rows = cart.sub([False, True])   # keep cols: 2 comms of 3
        cols = cart.sub([True, False])   # keep rows: 3 comms of 2
        return (rows.size, rows.comm.allreduce(comm.rank),
                cols.size, cols.comm.allreduce(comm.rank))

    res = run_local(prog, 6)
    for r, (rs, rsum, cs, csum) in enumerate(res):
        row, col = divmod(r, 3)
        assert rs == 3 and cs == 2
        assert rsum == sum(3 * row + c for c in range(3))
        assert csum == sum(col + 3 * rr for rr in range(2))


# -- SPMD backend ----------------------------------------------------------


def test_cart_exchange_spmd():
    def prog(comm, _):
        cart = cart_create(comm, (2, 4))
        r = comm.rank.astype(np.float32)
        from_left = cart.exchange(r, dim=1, disp=1, fill=-1.0)
        from_above = cart.exchange(r, dim=0, disp=1, fill=-2.0)
        return from_left, from_above

    left, above = run_spmd(prog, np.zeros(1, np.float32))
    left, above = np.ravel(np.asarray(left)), np.ravel(np.asarray(above))
    for r in range(P):
        row, col = divmod(r, 4)
        assert left[r] == (r - 1 if col > 0 else -1.0)
        assert above[r] == (r - 4 if row > 0 else -2.0)


def test_cart_sub_spmd():
    def prog(comm, _):
        cart = cart_create(comm, (2, 4))
        rows = cart.sub([False, True])  # 2 comms of 4 (same process row)
        return rows.comm.allreduce(comm.rank.astype(np.float32))

    out = np.ravel(np.asarray(run_spmd(prog, np.zeros(1, np.float32))))
    assert list(out[:4]) == [0 + 1 + 2 + 3] * 4
    assert list(out[4:]) == [4 + 5 + 6 + 7] * 4


def test_cart_shift_inside_trace_raises():
    from mpi_tpu.tpu import SpmdSemanticsError  # noqa: F401

    def prog(comm, _):
        cart = cart_create(comm, (2, 4))
        with pytest.raises(TypeError, match="traced"):
            cart.shift(0, 1)
        return comm.allreduce(np.float32(0))

    run_spmd(prog, np.zeros(1, np.float32))


# -- jacobi2d parity -------------------------------------------------------


def oracle_jacobi(rows, cols, iters):
    """Single-process numpy oracle of the same boundary problem."""
    g = np.zeros((rows, cols), np.float32)
    prev = g
    for _ in range(iters):
        padded = np.zeros((rows + 2, cols + 2), np.float32)
        padded[1:-1, 1:-1] = g
        padded[0, 1:-1] = 1.0  # hot top edge
        new = 0.25 * (padded[:-2, 1:-1] + padded[2:, 1:-1]
                      + padded[1:-1, :-2] + padded[1:-1, 2:])
        new[:, 0] = 0.0
        new[:, -1] = 0.0
        g, prev = new.astype(np.float32), g
    return g, np.abs(g - prev).max()


@pytest.mark.parametrize("dims", [(2, 2), (4, 1), (1, 4)])
def test_jacobi2d_matches_oracle_local(dims):
    tr, tc = 8 // dims[0], 8 // dims[1]
    res = run_local(lambda comm: jacobi2d_program(
        comm, tile_rows=tr, tile_cols=tc, iters=30, dims=dims), 4)
    want, want_res = oracle_jacobi(8, 8, 30)
    tiles = np.zeros((8, 8), np.float32)
    for r, (tile, resid) in enumerate(res):
        row, col = divmod(r, dims[1])
        tiles[row * tr:(row + 1) * tr, col * tc:(col + 1) * tc] = np.asarray(tile)
        np.testing.assert_allclose(float(np.asarray(resid)), want_res, rtol=1e-4)
    np.testing.assert_allclose(tiles, want, atol=1e-6)


def test_jacobi2d_matches_oracle_spmd():
    dims = (2, 4)
    tr, tc = 8 // dims[0], 16 // dims[1]

    def prog(comm):
        return jacobi2d_program(comm, tile_rows=tr, tile_cols=tc,
                                iters=25, dims=dims)

    tile, resid = run_spmd(prog)
    tile = np.asarray(tile)
    want, want_res = oracle_jacobi(8, 16, 25)
    got = np.zeros((8, 16), np.float32)
    for r in range(P):
        row, col = divmod(r, dims[1])
        got[row * tr:(row + 1) * tr, col * tc:(col + 1) * tc] = tile[r]
    np.testing.assert_allclose(got, want, atol=1e-5)
    np.testing.assert_allclose(float(np.ravel(np.asarray(resid))[0]),
                               want_res, rtol=1e-3)


def test_jacobi2d_1xN_matches_jacobi1d_spmd():
    # dims (P, 1) reduces jacobi2d to the 1-D row decomposition of
    # examples/jacobi.py — the two programs must agree to the bit
    def prog2d(comm):
        return jacobi2d_program(comm, tile_rows=4, tile_cols=12, iters=20,
                                dims=(P, 1))

    def prog1d(comm):
        return jacobi_program(comm, rows_per_rank=4, cols=12, iters=20)

    t2, r2 = run_spmd(prog2d)
    t1, r1 = run_spmd(prog1d)
    np.testing.assert_array_equal(np.asarray(t2), np.asarray(t1))
    np.testing.assert_array_equal(np.asarray(r2), np.asarray(r1))


def test_cart_shift_dim_out_of_range_rejected():
    def prog(comm):
        cart = cart_create(comm, (2, 3))
        with pytest.raises(ValueError):
            cart.shift(2, 1)
        with pytest.raises(ValueError):
            cart.shift(-1, 1)

    run_local(prog, 6)


# -- neighborhood collectives [S: MPI-3 MPI_Neighbor_*] ---------------------


def _neigh_allgather_prog(comm):
    """2x4 periodic-x grid: gather rank ids from all 4 neighbors."""
    cart = cart_create(comm, (2, 4), periods=(False, True))
    got = cart.neighbor_allgather(np.float32(1.0) * comm.rank, fill=-1.0)
    return tuple(got)


def test_neighbor_allgather_parity():
    res_local = run_local(_neigh_allgather_prog, P)
    res_spmd = run_spmd(_neigh_allgather_prog, nranks=P)

    def oracle(r):
        # pure coordinate math: dims (2,4), periods (False, True)
        dims, periods = (2, 4), (False, True)
        strides = (4, 1)
        def coords_of(rank):
            return tuple((rank // s) % d for s, d in zip(strides, dims))
        def rank_of(c):
            rank = 0
            for ci, d, p, s in zip(c, dims, periods, strides):
                if p:
                    ci %= d
                elif not (0 <= ci < d):
                    return None
                rank += ci * s
            return rank
        out = []
        for dim in range(2):
            for disp in (-1, +1):
                c = list(coords_of(r))
                c[dim] += disp
                out.append(rank_of(c))
        return out

    for r in range(P):
        exp = [float(n) if n is not None else -1.0 for n in oracle(r)]
        assert [float(x) for x in res_local[r]] == exp
        assert [float(np.asarray(v)[r]) for v in res_spmd] == exp


def _neigh_alltoall_prog(comm):
    """1-D ring of P: send (rank*10+direction) to each neighbor."""
    cart = cart_create(comm, (P,), periods=(True,))
    left_item = np.float32(10.0) * comm.rank + 0.0   # for the −1 neighbor
    right_item = np.float32(10.0) * comm.rank + 1.0  # for the +1 neighbor
    got = cart.neighbor_alltoall([left_item, right_item], fill=-1.0)
    return tuple(got)


def test_neighbor_alltoall_parity():
    res_local = run_local(_neigh_alltoall_prog, P)
    res_spmd = run_spmd(_neigh_alltoall_prog, nranks=P)
    for r in range(P):
        left, right = (r - 1) % P, (r + 1) % P
        exp = [left * 10.0 + 1.0,   # the −1 neighbor's "+1-direction" item
               right * 10.0 + 0.0]  # the +1 neighbor's "−1-direction" item
        assert [float(x) for x in res_local[r]] == exp
        assert [float(np.asarray(v)[r]) for v in res_spmd] == exp


def test_neighbor_alltoall_wrong_count():
    def prog(comm):
        cart = cart_create(comm, (2, 4))
        try:
            cart.neighbor_alltoall([1.0, 2.0])
        except ValueError:
            return True
        return False

    assert all(run_local(prog, P))


def test_neighbors_of_order():
    def prog2(comm):
        cart = cart_create(comm, (2, 4), periods=(False, True))
        return cart.neighbors_of(5)

    res = run_local(prog2, P)
    # rank 5 = coords (1, 1): -x → (0,1)=1, +x → (2,1)=None, -y → (1,0)=4, +y → (1,2)=6
    assert res[0] == [1, None, 4, 6]


# -- graph topologies (MPI_(Dist_)graph) ------------------------------------


def test_graph_rounds_partial_permutations():
    from mpi_tpu import checker, schedules

    edges = [(0, 1), (0, 2), (1, 2), (2, 0), (3, 0), (1, 3), (2, 3)]
    rounds = schedules.graph_rounds(edges, 4)
    for rnd in rounds:
        checker.validate_perm(rnd, 4)
    flat = [e for rnd in rounds for e in rnd]
    assert sorted(flat) == sorted(set(edges))
    with pytest.raises(ValueError, match="self-edge"):
        schedules.graph_rounds([(1, 1)], 4)
    with pytest.raises(ValueError, match="out of range"):
        schedules.graph_rounds([(0, 9)], 4)


def test_graph_neighbor_allgather_local():
    from mpi_tpu.topology import graph_create

    edges = [(0, 1), (1, 2), (2, 3), (3, 0), (0, 2), (3, 1)]

    def prog(comm):
        g = graph_create(comm, edges)
        got = g.neighbor_allgather(("from", comm.rank))
        return g.in_neighbors_of(comm.rank), got

    res = run_local(prog, 4)
    for r in range(4):
        in_nb, got = res[r]
        assert got == [("from", s) for s in in_nb], (r, in_nb, got)


def test_graph_neighbor_alltoall_local():
    from mpi_tpu.topology import graph_create

    edges = [(0, 1), (1, 2), (2, 3), (3, 0), (0, 2), (3, 1)]

    def prog(comm):
        g = graph_create(comm, edges)
        me = comm.rank
        objs = [("pkt", me, d) for d in g.out_neighbors_of(me)]
        return g.in_neighbors_of(me), g.neighbor_alltoall(objs)

    res = run_local(prog, 4)
    for r in range(4):
        in_nb, got = res[r]
        assert got == [("pkt", s, r) for s in in_nb], (r, in_nb, got)


def test_dist_graph_create_adjacent_matches_global():
    from mpi_tpu.topology import dist_graph_create_adjacent, graph_create

    edges = [(0, 1), (1, 2), (2, 0), (0, 2)]

    def prog(comm):
        g_global = graph_create(comm, edges)
        me = comm.rank
        g_adj = dist_graph_create_adjacent(
            comm,
            sources=g_global.in_neighbors_of(me),
            destinations=g_global.out_neighbors_of(me))
        return (sorted(g_adj.edges) == sorted(g_global.edges),
                g_adj.neighbor_allgather(me * 10))

    res = run_local(prog, 3)
    for r in range(3):
        same, got = res[r]
        assert same
        in_nb = [s for (s, d) in edges if d == r]
        assert got == [s * 10 for s in in_nb]


def test_dist_graph_adjacent_respects_each_ranks_order():
    """MPI contract: results are ordered by each rank's OWN sources list,
    even when it disagrees with every other ordering (code-review
    regression: the union scan order must not leak through)."""
    from mpi_tpu.topology import dist_graph_create_adjacent

    # rank 2 receives from 0 and 1; it names them REVERSED
    def prog(comm):
        me = comm.rank
        sources = {0: [], 1: [], 2: [1, 0]}[me]
        dests = {0: [2], 1: [2], 2: []}[me]
        g = dist_graph_create_adjacent(comm, sources, dests)
        return g.neighbor_allgather(me * 10)

    res = run_local(prog, 3)
    assert res[2] == [10, 0]  # from rank 1 FIRST — rank 2's stated order


def test_graph_neighbor_allgather_tpu_parity():
    """SPMD result: stacked [max_in_degree, ...] padded with fill; rows
    [:in_degree] equal the process-backend list."""
    import jax.numpy as jnp

    from mpi_tpu.topology import graph_create
    from mpi_tpu.tpu import TpuCommunicator, default_mesh, run_spmd

    edges = [(0, 1), (1, 2), (2, 3), (3, 4), (4, 5), (5, 6), (6, 7),
             (7, 0), (0, 4), (2, 6), (5, 1)]
    mesh = default_mesh(8)
    world = TpuCommunicator("world", mesh)
    g = graph_create(world, edges)

    def prog(comm, x):
        return g.neighbor_allgather(x[comm.rank], fill=-1.0)

    data = np.arange(8.0, dtype=np.float32) * 10
    out = np.asarray(run_spmd(prog, data, mesh=mesh))
    out = out.reshape(8, g.max_in_degree)
    for r in range(8):
        in_nb = g.in_neighbors_of(r)
        np.testing.assert_allclose(out[r, :len(in_nb)],
                                   [data[s] for s in in_nb])
        np.testing.assert_allclose(out[r, len(in_nb):], -1.0)


def test_graph_neighbor_alltoall_tpu_parity():
    from mpi_tpu.topology import graph_create
    from mpi_tpu.tpu import TpuCommunicator, default_mesh, run_spmd

    edges = [(0, 1), (1, 2), (2, 3), (3, 0), (0, 2), (3, 1)]
    mesh = default_mesh(4)
    world = TpuCommunicator("world", mesh)
    g = graph_create(world, edges)
    maxo = g.max_out_degree

    # payload block for out-neighbor slot k on rank r: 100*r + k
    blocks = np.zeros((4, maxo), np.float32)
    for r in range(4):
        for k in range(maxo):
            blocks[r, k] = 100 * r + k

    def prog(comm, x):
        return g.neighbor_alltoall(x[comm.rank][:, None], fill=-1.0)

    out = np.asarray(run_spmd(prog, blocks, mesh=mesh, nranks=4))
    out = out.reshape(4, g.max_in_degree)
    for r in range(4):
        in_nb = g.in_neighbors_of(r)
        expect = [100 * s + g.out_neighbors_of(s).index(r) for s in in_nb]
        np.testing.assert_allclose(out[r, :len(in_nb)], expect)
        np.testing.assert_allclose(out[r, len(in_nb):], -1.0)
