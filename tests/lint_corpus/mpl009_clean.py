"""Near-miss twin: a wildcard receive with exactly ONE eligible sender
is deterministic — no race to report."""


def main(comm):
    if comm.rank == 0:
        return comm.recv(ANY_SOURCE, tag=2)
    if comm.rank == 1:
        comm.send(b"x", 0, tag=2)
    return None
