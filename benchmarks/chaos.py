#!/usr/bin/env python
"""Chaos smoke: FaultyTransport drop/delay/duplicate sweep over the
collective family, asserting DIAGNOSE-DON'T-HANG.

The failure story's CI tripwire (ISSUE 3 satellite): every cell runs one
in-process local world through a fault-injecting transport and records
the outcome.  A cell may *succeed* (the fault was absorbed — e.g. a
delay, or a duplicate the matching engine never mismatched) or *fail
diagnosably* (RecvTimeout / ProcFailedError / TransportError naming the
stuck channel) — what it may never do is HANG: a run_local deadlock
timeout fails the sweep.  That is exactly the library's failure-semantics
contract (README "Failure semantics"), checked across every collective
algorithm gate rather than argued about.

Duplicate-injection cells additionally record result corruption
(``wrong_result``) honestly instead of asserting it away: a duplicated
internal frame can legally mis-fold a later collective on the same
channel — the sweep documents which schedules are sensitive, it does not
promise they aren't.

Usage::

    python benchmarks/chaos.py            # full sweep, JSON to stdout
    python benchmarks/chaos.py --quick    # tier-1 smoke (fewer cells)
    python bench.py --chaos [--quick]     # the CI spelling
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_tpu import mpit  # noqa: E402
from mpi_tpu.errors import ProcFailedError, RevokedError  # noqa: E402
from mpi_tpu.transport.base import RecvTimeout, TransportError  # noqa: E402
from mpi_tpu.transport.faulty import FaultyTransport  # noqa: E402
from mpi_tpu.transport.local import run_local  # noqa: E402

NRANKS = 4  # pow2: exercises halving/doubling gates too
RECV_TIMEOUT_S = 2.0  # the diagnosis bound a dropped message hits
WORLD_TIMEOUT_S = 30.0  # run_local deadlock ceiling = the HANG verdict

# (name, per-rank collective call).  Payloads are small (latency-path
# schedules) — chaos probes control-flow robustness, not bandwidth.
COLLECTIVES = [
    ("bcast", lambda c: c.bcast(np.arange(8.0), root=0)),
    ("reduce", lambda c: c.reduce(np.ones(8), root=0)),
    ("allreduce-ring", lambda c: c.allreduce(np.ones(8), algorithm="ring")),
    ("allreduce-halving", lambda c: c.allreduce(
        np.ones(8), algorithm="recursive_halving")),
    ("allreduce-rabenseifner", lambda c: c.allreduce(
        np.ones(8), algorithm="rabenseifner")),
    ("allgather-ring", lambda c: c.allgather(
        np.full(4, c.rank), algorithm="ring")),
    ("allgather-doubling", lambda c: c.allgather(
        np.full(4, c.rank), algorithm="doubling")),
    ("alltoall", lambda c: c.alltoall([np.full(2, c.rank)] * c.size)),
    ("reduce_scatter", lambda c: c.reduce_scatter(np.ones((c.size, 4)))),
    ("scatter", lambda c: c.scatter(
        [np.full(2, d) for d in range(c.size)] if c.rank == 0 else None,
        root=0)),
    ("gather", lambda c: c.gather(np.full(2, c.rank), root=0)),
    ("scan", lambda c: c.scan(np.ones(4))),
    ("barrier", lambda c: c.barrier()),
]

FAULTS = [
    ("drop", dict(drop_every=5)),
    ("delay", dict(delay_s=0.01)),
    ("duplicate", dict(duplicate_every=5)),
]

QUICK_COLLECTIVES = ("allreduce-ring", "alltoall", "reduce_scatter",
                     "barrier")


def _oracle(name: str, comm_size: int):
    """Expected fault-free result per rank (None = don't check)."""
    if name.startswith("allreduce"):
        return lambda r, got: np.array_equal(np.asarray(got),
                                             np.full(8, float(comm_size)))
    if name == "scan":
        return lambda r, got: np.array_equal(np.asarray(got),
                                             np.full(4, float(r + 1)))
    return None


def run_cell(coll_name: str, call, fault_kw: Dict) -> Dict:
    wrapper = FaultyTransport.wrapper(**fault_kw)
    check = _oracle(coll_name, NRANKS)

    def fn(comm):
        got = call(comm)
        if check is not None and not check(comm.rank, got):
            return "wrong_result"
        return "ok"

    t0 = time.monotonic()
    try:
        res = run_local(fn, NRANKS, transport_wrapper=wrapper,
                        recv_timeout=RECV_TIMEOUT_S, timeout=WORLD_TIMEOUT_S)
        outcome = ("wrong_result" if "wrong_result" in res else "ok")
    except TimeoutError as e:
        outcome = f"HANG: {e}"  # the one unacceptable verdict
    except RuntimeError as e:
        # run_local wraps the first rank error; classify its cause
        cause = e.__cause__
        if isinstance(cause, (RecvTimeout, ProcFailedError, RevokedError,
                              TransportError)):
            outcome = f"diagnosed:{type(cause).__name__}"
        else:
            outcome = f"error:{type(cause).__name__}: {str(cause)[:120]}"
    return {"collective": coll_name, "fault": dict(fault_kw),
            "outcome": outcome,
            "wall_ms": round((time.monotonic() - t0) * 1e3, 1)}


def run_chaos(quick: bool = False) -> Dict:
    t0 = time.time()
    ses = mpit.session_create()
    ses.reset_all()
    colls = [(n, c) for n, c in COLLECTIVES
             if not quick or n in QUICK_COLLECTIVES]
    cells: List[Dict] = []
    for fault_name, fault_kw in FAULTS:
        for coll_name, call in colls:
            cell = run_cell(coll_name, call, fault_kw)
            cell["fault_name"] = fault_name
            cells.append(cell)
    hangs = [c for c in cells if c["outcome"].startswith("HANG")]
    return {
        "quick": quick,
        "nranks": NRANKS,
        "recv_timeout_s": RECV_TIMEOUT_S,
        "cells": cells,
        "hangs": hangs,
        "injected": {"dropped": ses.read("faulty_dropped"),
                     "duplicated": ses.read("faulty_duplicated")},
        "ok": not hangs,
        "wall_s": round(time.time() - t0, 1),
    }


def run_serve_chaos(quick: bool = False, backend: str = "socket") -> Dict:
    """The resident-pool chaos leg (ISSUE 7 satellite): continuous
    ``SIGKILL`` against a live world server while a client churns
    lease → allreduce → release cycles.  The contract under fire:

    * every lease either COMPLETES (with the correct result) or raises
      a NAMED error (ProcFailedError / RevokedError / the lease-timeout
      TimeoutError) — never a hang, never an anonymous crash;
    * worlds/sec never reaches zero: each observation window must
      complete at least one world (the pool self-heals faster than the
      killer drains it);
    * the pool ends the run healed (full strength, epoch advanced, and
      a final full-pool allreduce is correct).
    """
    import random
    import signal as _signal

    from mpi_tpu import serve
    from mpi_tpu.errors import EpochSkewError

    pool = 3
    duration_s = 8.0 if quick else 20.0
    kill_every_s = 2.0 if quick else 2.5
    window_s = 4.0
    rng = random.Random(1234)
    t0 = time.time()
    outcomes: List[Dict] = []
    kills = 0
    stop = [False]
    with serve.WorldServer(pool_size=pool, backend=backend,
                           detect_timeout_s=1.5, heartbeat_s=0.2,
                           world_lease_timeout_s=10.0,
                           rejoin_timeout_s=15.0) as srv:

        def killer():
            nonlocal kills
            while not stop[0]:
                time.sleep(kill_every_s)
                if stop[0]:
                    return
                with srv._lock:
                    live = [w.proc for w in srv._workers.values()
                            if w.proc is not None
                            and w.proc.poll() is None]
                if live:
                    try:
                        os.kill(rng.choice(live).pid, _signal.SIGKILL)
                        kills += 1
                    except OSError:
                        pass

        import threading

        kth = threading.Thread(target=killer, daemon=True)
        kth.start()
        client = serve.connect(srv)
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            t_cycle = time.monotonic()
            try:
                lease = client.acquire(2, timeout=6.0)
                try:
                    got = lease.run(serve.job_allreduce, 256,
                                    timeout=8.0)
                    if got != 3.0:
                        outcome = f"wrong_result:{got}"
                    else:
                        outcome = "ok"
                finally:
                    lease.release()
            except (ProcFailedError, RevokedError, EpochSkewError,
                    RecvTimeout, TransportError, TimeoutError) as e:
                outcome = f"diagnosed:{type(e).__name__}"
            except Exception as e:  # noqa: BLE001 - the failing verdict
                outcome = f"error:{type(e).__name__}: {str(e)[:120]}"
            outcomes.append({"at_s": round(time.monotonic()
                                           - (deadline - duration_s), 2),
                             "outcome": outcome,
                             "wall_ms": round((time.monotonic()
                                               - t_cycle) * 1e3, 1)})
        stop[0] = True
        kth.join(timeout=5.0)
        # the pool must HEAL once the killing stops...
        heal_deadline = time.monotonic() + 30.0
        healed = False
        while time.monotonic() < heal_deadline:
            st = client.stats()
            if st["idle"] == pool and not st["healing"]:
                healed = True
                break
            time.sleep(0.3)
        # ... and serve a correct full-pool world again
        final_ok = False
        if healed:
            try:
                final_ok = client.run(serve.job_allreduce, 256,
                                      nranks=pool, timeout=15.0) == 6.0
            except Exception:  # noqa: BLE001 - recorded below
                final_ok = False
        stats = client.stats()
    completed = [o for o in outcomes if o["outcome"] == "ok"]
    bad = [o for o in outcomes
           if o["outcome"].startswith(("wrong_result", "error"))]
    # worlds/sec never zero: every window must complete >= 1 world
    nwin = max(1, int(duration_s // window_s))
    windows = [0] * nwin
    for o in completed:
        windows[min(nwin - 1, int(o["at_s"] // window_s))] += 1
    return {
        "quick": quick, "backend": backend, "pool_size": pool,
        "duration_s": duration_s, "kills": kills,
        "cycles": len(outcomes), "completed_worlds": len(completed),
        "worlds_per_s": round(len(completed) / duration_s, 2),
        "windows_completed": windows,
        # worlds churn at O(100)/s: keep the full record only for the
        # abnormal cycles (diagnosed + failed), not thousands of "ok"s
        "outcomes_abnormal": [o for o in outcomes
                              if o["outcome"] != "ok"][:200],
        "unnamed_failures": bad,
        "healed": healed, "final_allreduce_ok": final_ok,
        "final_epoch": stats["epoch"],
        "heals_completed": stats["heals_completed"],
        "oversubscribed": (pool + 2) > (os.cpu_count() or 1),
        "ok": (not bad and healed and final_ok and kills > 0
               and all(w > 0 for w in windows)),
        "wall_s": round(time.time() - t0, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smoke mode: a subset of collectives per fault")
    ap.add_argument("--serve", action="store_true",
                    help="resident-pool leg: continuous SIGKILL against "
                         "a live world server; asserts worlds/sec never "
                         "reaches zero and every lease completes or "
                         "raises a named FT error")
    ap.add_argument("--backend", choices=("socket", "shm"),
                    default="socket")
    args = ap.parse_args(argv)
    if args.serve:
        result = run_serve_chaos(quick=args.quick, backend=args.backend)
    else:
        result = run_chaos(quick=args.quick)
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
