"""Seeded bug: a collective inside a loop whose trip count IS the
rank — every rank executes a different number of barriers."""


def main(comm):
    for _ in range(comm.rank):
        comm.barrier()
