"""MPI_*v vector collectives (static counts, padded payloads) on the thread
backend and the 8-device virtual-CPU SPMD backend — SURVEY.md §4 items 1-2.
Contract: Communicator.allgatherv docstring (mpi_tpu/communicator.py)."""

import numpy as np
import pytest

from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import run_spmd

P = 8
COUNTS = [3, 1, 4, 1, 5, 0, 2, 6]  # includes a zero-contribution rank


def ragged(n, counts, width=2, seed=0):
    rng = np.random.RandomState(seed)
    return [np.asarray(rng.randn(c, width), np.float32) for c in counts[:n]]


# -- process backend -------------------------------------------------------


@pytest.mark.parametrize("n", [1, 4, 8])
def test_allgatherv_local(n):
    counts = COUNTS[:n]
    parts = ragged(n, counts)
    want = np.concatenate(parts, axis=0)

    def prog(comm):
        return comm.allgatherv(parts[comm.rank], counts)

    for got in run_local(prog, n):
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_allgatherv_accepts_padded_input_local():
    counts = [2, 3]
    parts = ragged(2, counts, seed=1)
    padded = [np.concatenate([p, np.zeros((3 - len(p), 2), np.float32)])[:3]
              for p in parts]

    def prog(comm):
        return comm.allgatherv(padded[comm.rank], counts)

    for got in run_local(prog, 2):
        np.testing.assert_allclose(got, np.concatenate(parts), rtol=1e-6)


def test_gatherv_scatterv_roundtrip_local():
    counts = [2, 0, 3, 1]
    total = np.asarray(np.arange(6 * 4).reshape(6, 4), np.float64)

    def prog(comm):
        mine = comm.scatterv(total if comm.rank == 1 else None, counts, root=1)
        assert mine.shape == (counts[comm.rank], 4)
        back = comm.gatherv(mine, counts, root=2)
        return back

    res = run_local(prog, 4)
    np.testing.assert_array_equal(res[2], total)
    assert res[0] is None and res[1] is None and res[3] is None


def test_alltoallv_local():
    n = 4
    counts = [[(i + j) % 3 for j in range(n)] for i in range(n)]

    def prog(comm):
        blocks = [np.full((3, 2), 10 * comm.rank + d, np.float32)
                  for d in range(n)]
        return comm.alltoallv(blocks, counts)

    res = run_local(prog, n)
    for me, got in enumerate(res):
        for src in range(n):
            c = counts[src][me]
            np.testing.assert_allclose(
                np.asarray(got[src]),
                np.full((c, 2), 10 * src + me, np.float32))


def test_counts_validation_local():
    def prog(comm):
        with pytest.raises(ValueError):
            comm.allgatherv(np.zeros((2, 2)), [1])  # wrong length
        with pytest.raises(ValueError):
            comm.allgatherv(np.zeros((2, 2)), [1, -1])  # negative
        with pytest.raises(ValueError):
            comm.alltoallv([np.zeros((1, 1))] * 2, [[1, 1]])  # not square

    run_local(prog, 2)


# -- SPMD backend ----------------------------------------------------------


def test_allgatherv_spmd():
    counts = COUNTS
    parts = ragged(P, counts, seed=2)
    maxc = max(counts)
    padded = np.stack([
        np.concatenate([p, np.zeros((maxc - len(p), 2), np.float32)])
        for p in parts])  # [P, maxc, 2]
    want = np.concatenate(parts, axis=0)

    def prog(comm, x):
        return comm.allgatherv(x[comm.rank], counts)

    out = np.asarray(run_spmd(prog, padded))
    assert out.shape == (P, sum(counts), 2)
    for r in range(P):
        np.testing.assert_allclose(out[r], want, rtol=1e-6)


def test_scatterv_spmd():
    counts = [2, 1, 3, 0, 1, 2, 4, 3]
    total = np.asarray(np.random.RandomState(3).randn(sum(counts), 3), np.float32)
    maxc = max(counts)

    def prog(comm, x):
        return comm.scatterv(x, counts, root=0)

    out = np.asarray(run_spmd(prog, total))
    assert out.shape == (P, maxc, 3)
    offs = np.concatenate([[0], np.cumsum(counts)])
    for r in range(P):
        np.testing.assert_allclose(out[r, : counts[r]],
                                   total[offs[r]:offs[r + 1]], rtol=1e-6)
        np.testing.assert_array_equal(out[r, counts[r]:],
                                      np.zeros((maxc - counts[r], 3)))


def test_alltoallv_spmd():
    counts = [[(i + 2 * j) % 4 for j in range(P)] for i in range(P)]
    maxc = max(max(r) for r in counts)

    # rank i's block for dest d = value 100*i + d in every valid row
    def prog(comm, _):
        i = comm.rank
        base = (100.0 * i
                + np.arange(P, dtype=np.float32)[:, None, None]
                + np.zeros((P, maxc, 1), np.float32))
        out = comm.alltoallv(base, counts)
        return out

    out = np.asarray(run_spmd(prog, np.zeros(1, np.float32)))
    for me in range(P):
        for src in range(P):
            c = counts[src][me]
            np.testing.assert_allclose(
                out[me, src, :c],
                np.full((c, 1), 100.0 * src + me, np.float32))
            np.testing.assert_array_equal(
                out[me, src, c:], np.zeros((maxc - c, 1)))


def test_gatherv_spmd_symmetric():
    counts = [1, 2, 0, 1, 3, 2, 1, 2]
    maxc = max(counts)
    d = np.asarray(np.random.RandomState(4).randn(P, maxc, 2), np.float32)

    def prog(comm, x):
        return comm.gatherv(x[comm.rank], counts, root=3)

    out = np.asarray(run_spmd(prog, d))
    want = np.concatenate([d[i, : counts[i]] for i in range(P)], axis=0)
    for r in range(P):
        np.testing.assert_allclose(out[r], want, rtol=1e-6)


def test_undercount_payload_rejected_local():
    # declared count larger than the actual payload must raise, not truncate
    def prog(comm):
        with pytest.raises(ValueError, match="declared count"):
            comm.allgatherv(np.zeros((1, 1)), [3, 3])
        with pytest.raises(ValueError, match="declared count"):
            comm.alltoallv([np.zeros((1, 1))] * 2, [[2, 2], [2, 2]])

    run_local(prog, 2)


def test_alltoallv_negative_counts_rejected_local():
    def prog(comm):
        with pytest.raises(ValueError):
            comm.alltoallv([np.zeros((2, 1))] * 2, [[-1, 2], [2, 2]])

    run_local(prog, 2)


def test_alltoallv_all_zero_counts_spmd():
    from mpi_tpu.tpu import TpuCommunicator, default_mesh
    import jax, jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = default_mesh(8)
    comm = TpuCommunicator("world", mesh)
    counts = [[0] * 8 for _ in range(8)]

    def prog():
        x = jnp.ones((8, 2, 1), jnp.float32)
        out = comm.alltoallv(x, counts)
        return out[None]

    out = jax.jit(jax.shard_map(prog, mesh=mesh, in_specs=(),
                                out_specs=P("world")))()
    assert out.shape == (8, 8, 0, 1)
