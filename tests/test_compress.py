"""Compressed/quantized collectives (ISSUE 8 tentpole, mpi_tpu/compress.py).

Parity with error BOUNDS: the ring re-encodes partial sums at every hop,
so quantization error compounds ~linearly in P — bf16 within
``(P+1) * 2^-8`` relative, scaled-int within ``(P+1) / 127`` of the
per-segment max-abs.  Byte accounting: bf16 wire bytes are EXACTLY half
the f32 ring's raw bytes (same spans, 2 bytes/element), scaled-int about
a quarter, with zero pickled array bytes on socket AND shm — the same
pvar contract as the uncompressed engine.  Edge cases from the ISSUE
checklist: top-k with k >= n, tied magnitudes, all-zero gradients, bf16
inputs (wire == input dtype, no double-convert), MAX/MIN under
scaled-int (monotone quantization, bounded), and object-payload
group-wide fallback parity on socket and shm.
"""

import numpy as np
import pytest

from mpi_tpu import coll_sm, compress, mpit, ops
from mpi_tpu.transport import codec
from mpi_tpu.transport.local import run_local
from tests.test_shm_backend import run_shm_world
from tests.test_socket_backend import run_socket_world

WORLDS = [("local", run_local), ("socket", run_socket_world),
          ("shm", run_shm_world)]


def _deltas(names):
    return {k: mpit.pvar_read(k) for k in names}


def _payloads(p, n, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return [rng.randn(n).astype(dtype) for _ in range(p)]


def _bf16_bound(p, want):
    # one quantization per ring hop (P-1 folds + the allgather pass)
    return (p + 1) * 2.0 ** -8 * max(1e-6, float(np.max(np.abs(want))))


@pytest.fixture
def topk_ratio():
    old = mpit.cvar_read("compress_topk_ratio")
    yield lambda v: mpit.cvar_write("compress_topk_ratio", v)
    mpit.cvar_write("compress_topk_ratio", old)


# -- dense wire-format parity ------------------------------------------------


@pytest.mark.parametrize("label,world", WORLDS)
@pytest.mark.parametrize("p", [2, 3, 4])
def test_allreduce_bf16_parity(label, world, p):
    data = _payloads(p, 777, seed=p)
    want = sum(d.astype(np.float64) for d in data)
    res = world(lambda c: c.allreduce(data[c.rank],
                                      algorithm="compressed:bf16"), p)
    for r in res:
        got = np.asarray(r)
        assert got.dtype == np.float32
        assert np.max(np.abs(got.astype(np.float64) - want)) \
            <= _bf16_bound(p, want)


@pytest.mark.parametrize("p", [2, 4])
def test_allreduce_int8_parity(p):
    data = _payloads(p, 513, seed=p + 10)
    want = sum(d.astype(np.float64) for d in data)
    # per-hop bound: the partial sums' max-abs over 127, one per hop
    amax = float(max(np.max(np.abs(sum(data[:i + 1]))) for i in range(p)))
    bound = (p + 1) * amax / 127.0
    for world in (run_socket_world, run_shm_world):
        res = world(lambda c: c.allreduce(data[c.rank],
                                          algorithm="compressed:int8"), p)
        for r in res:
            assert np.max(np.abs(np.asarray(r, np.float64) - want)) <= bound


def test_allreduce_f64_folds_in_f64():
    p = 2
    data = _payloads(p, 257, seed=3, dtype=np.float64)
    want = sum(d for d in data)
    res = run_local(lambda c: c.allreduce(data[c.rank],
                                          algorithm="compressed"), p)
    for r in res:
        got = np.asarray(r)
        assert got.dtype == np.float64  # result dtype preserved
        assert np.max(np.abs(got - want)) <= _bf16_bound(p, want)


@pytest.mark.parametrize("algo", ["compressed:bf16", "compressed:int8"])
@pytest.mark.parametrize("opname,oracle", [("max", np.maximum),
                                           ("min", np.minimum)])
def test_allreduce_max_min_quantized(algo, opname, oracle):
    """MAX/MIN under both wire formats: rint/clip and RNE are MONOTONE,
    so the result is the true extremum quantized — bounded like SUM
    (the ISSUE's 'MAX/MIN under scaled-int' edge, allowed not gated)."""
    p = 3
    data = _payloads(p, 301, seed=5)
    want = oracle.reduce(data).astype(np.float64)
    op = ops.MAX if opname == "max" else ops.MIN
    amax = max(float(np.max(np.abs(d))) for d in data)
    bound = ((p + 1) * 2.0 ** -8 * amax if algo.endswith("bf16")
             else (p + 1) * amax / 127.0)
    res = run_local(lambda c: c.allreduce(data[c.rank], op,
                                          algorithm=algo), p)
    for r in res:
        assert np.max(np.abs(np.asarray(r, np.float64) - want)) <= bound


def test_bf16_input_wire_equals_input_dtype():
    """bf16 INPUTS: wire == input dtype — values exactly representable
    in bf16 survive the encode round-trip bit-for-bit (no double-convert
    loss), the result comes back AS bf16, and the wire moves 2
    bytes/element with zero pickled array bytes (the classic path
    pickles bf16 ndarrays — custom dtypes fail raw_eligible — so
    compression is also what puts bf16 payloads on raw frames)."""
    ml_dtypes = pytest.importorskip("ml_dtypes")
    p, n = 2, 256
    data = [(np.arange(n, dtype=np.float32) % 128 + r)
            .astype(ml_dtypes.bfloat16) for r in range(p)]
    want = sum(d.astype(np.float32) for d in data)  # ints < 512: exact
    b0 = _deltas(("bytes_raw_sent", "bytes_pickled_sent"))
    res = run_socket_world(
        lambda c: c.allreduce(data[c.rank], algorithm="compressed:bf16"), p)
    b1 = _deltas(("bytes_raw_sent", "bytes_pickled_sent"))
    for r in res:
        got = np.asarray(r)
        assert got.dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(got.astype(np.float32), want)
    assert b1["bytes_pickled_sent"] == b0["bytes_pickled_sent"]
    # ring: each rank sends 2(P-1)/P * n elements at 2 bytes
    assert b1["bytes_raw_sent"] - b0["bytes_raw_sent"] == p * 2 * (p - 1) * n * 2 // p


def test_bf16_bit_trick_matches_ml_dtypes():
    """The pure-numpy RNE fallback must agree with ml_dtypes exactly —
    including halfway cases, signed zeros, inf, and quieted NaNs."""
    ml_dtypes = pytest.importorskip("ml_dtypes")
    rng = np.random.RandomState(0)
    x = np.concatenate([
        (rng.randn(4096) * 10.0 ** rng.randint(-20, 20, 4096)),
        np.array([0.0, -0.0, np.inf, -np.inf, 1.0 + 2.0 ** -8,
                  1.0 + 2.0 ** -9, -1.0 - 2.0 ** -9, 3.0e38])]).astype(
                      np.float32)
    want = x.astype(ml_dtypes.bfloat16).view(np.uint16)
    b = x.view(np.uint32)
    nan = (b & np.uint32(0x7FFFFFFF)) > np.uint32(0x7F800000)
    r = b + (np.uint32(0x7FFF) + ((b >> np.uint32(16)) & np.uint32(1)))
    r = np.where(nan, b | np.uint32(0x00400000), r)
    got = (r >> np.uint32(16)).astype(np.uint16)
    np.testing.assert_array_equal(got, want)
    # NaN stays NaN through the trick
    assert np.isnan(compress.bf16_bits_to_f32(
        compress.f32_to_bf16_bits(np.array([np.nan], np.float32))))[0]


# -- top-k --------------------------------------------------------------------


def test_topk_dense_when_k_ge_n(topk_ratio):
    """ratio >= 1 (and any k >= n) clamps to dense selection — exact up
    to f32 summation order."""
    topk_ratio(2.0)  # k = 2n requested -> clamped to n
    p = 3
    data = _payloads(p, 100, seed=9)
    want = sum(d.astype(np.float64) for d in data)
    res = run_local(lambda c: c.allreduce(data[c.rank],
                                          algorithm="compressed:topk"), p)
    for r in res:
        np.testing.assert_allclose(np.asarray(r, np.float64), want,
                                   rtol=1e-5, atol=1e-5)


def test_topk_tied_magnitudes_bound(topk_ratio):
    """All-tied |values|: ANY valid top-k selection is acceptable; the
    unsent remainder per rank is (n-k) entries of the tied magnitude,
    which bounds the error whatever the tie-break."""
    topk_ratio(0.25)
    p, n = 2, 64
    data = [np.where(np.arange(n) % 2 == r, 1.0, -1.0).astype(np.float32)
            for r in range(p)]  # |x| == 1 everywhere: maximal ties
    want = sum(d.astype(np.float64) for d in data)
    k = compress.topk_k(n)
    res = run_local(lambda c: c.allreduce(data[c.rank],
                                          algorithm="compressed:topk"), p)
    for r in res:
        err = np.abs(np.asarray(r, np.float64) - want)
        assert np.sum(err) <= p * (n - k) * 1.0 + 1e-6
        # every transmitted entry is exact: at most n-k nonzero errors
        # of magnitude exactly 1 per rank contribution
        assert np.count_nonzero(err) <= p * (n - k)


def test_topk_all_zero_gradients(topk_ratio):
    topk_ratio(0.1)
    res = run_local(lambda c: c.allreduce(np.zeros(37, np.float32),
                                          algorithm="compressed:topk"), 2)
    for r in res:
        np.testing.assert_array_equal(np.asarray(r), np.zeros(37, np.float32))


def test_topk_error_feedback_residual(topk_ratio):
    """Error feedback: with the SAME gradient fed every step, the
    cumulative allreduced sum tracks t * dense within a LAG bounded by
    ~1/ratio steps of mass — i.e. the relative error of the cumulative
    sum SHRINKS as t grows (without feedback it would stay ~constant at
    the unsent fraction)."""
    topk_ratio(0.05)
    p, n = 2, 200
    data = _payloads(p, n, seed=11)
    want = sum(d.astype(np.float64) for d in data)

    def prog(c, steps):
        tot = np.zeros(n, np.float64)
        for _ in range(steps):
            tot += c.allreduce(data[c.rank],
                               algorithm="compressed:topk").astype(np.float64)
        return tot

    rel = {}
    for steps in (20, 80):
        res = run_local(lambda c: prog(c, steps), p)
        rel[steps] = (np.max(np.abs(res[0] - steps * want))
                      / (steps * np.max(np.abs(want))))
    assert rel[80] < rel[20] / 2.0  # bounded lag, not proportional loss
    assert rel[80] < 0.25


def test_topk_residual_key_and_reset(topk_ratio):
    """The residual slot is keyed by (shape, dtype, op): a second call
    with the same geometry reuses (and drains) it; reset_residuals
    clears the store."""
    topk_ratio(0.1)

    def prog(c):
        x = np.arange(1, 51, dtype=np.float32)
        c.allreduce(x, algorithm="compressed:topk")
        assert c.__dict__["_compress_residuals"]
        compress.reset_residuals(c)
        assert "_compress_residuals" not in c.__dict__
        return True

    assert all(run_local(prog, 2))


def test_topk_compress_key_isolates_residuals(topk_ratio):
    """PR-8 residual (c) regression: two DISTINCT tensors sharing a
    geometry must not cross-contaminate error-feedback residuals when
    the caller names them (allreduce(..., compress_key=...)).  With
    identity keys, B's first reduction is bit-identical to B reduced in
    a fresh world; with the default geometry key (the documented legacy
    behavior) A's residual leaks into B's — which is exactly what makes
    this test's teeth real."""
    topk_ratio(0.05)
    p, n = 2, 200
    a = _payloads(p, n, seed=21)
    b = _payloads(p, n, seed=22)

    def fresh_b(c):
        return c.allreduce(b[c.rank], algorithm="compressed:topk")

    def keyed(c):
        c.allreduce(a[c.rank], algorithm="compressed:topk",
                    compress_key="tensor-a")
        return c.allreduce(b[c.rank], algorithm="compressed:topk",
                           compress_key="tensor-b")

    def geometry_keyed(c):
        c.allreduce(a[c.rank], algorithm="compressed:topk")
        return c.allreduce(b[c.rank], algorithm="compressed:topk")

    want = run_local(fresh_b, p)
    got = run_local(keyed, p)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    # teeth: the default geometry key DOES contaminate (A's residual
    # mass rides into B's reduction), so the isolation above is the
    # new compress_key's doing, not an accident of the inputs
    legacy = run_local(geometry_keyed, p)
    assert any(not np.array_equal(np.asarray(lg), np.asarray(w))
               for lg, w in zip(legacy, want))


def test_topk_rejected_for_reduce_scatter():
    def prog(c):
        with pytest.raises(ValueError, match="reduce_scatter algorithm"):
            c.reduce_scatter([np.ones(4, np.float32)] * c.size,
                             algorithm="compressed:topk")
        return True

    assert all(run_local(prog, 2))


# -- reduce_scatter -----------------------------------------------------------


@pytest.mark.parametrize("algo", ["compressed:bf16", "compressed:int8"])
def test_reduce_scatter_parity(algo):
    p = 3
    rng = np.random.RandomState(2)
    blocks = [[rng.randn(40).astype(np.float32) for _ in range(p)]
              for _ in range(p)]
    want = [sum(blocks[q][i].astype(np.float64) for q in range(p))
            for i in range(p)]
    amax = 4.0 * p  # generous randn partial-sum bound
    bound = ((p + 1) * 2.0 ** -8 * amax if algo.endswith("bf16")
             else (p + 1) * amax / 127.0)
    for world in (run_socket_world, run_shm_world):
        res = world(lambda c: c.reduce_scatter(blocks[c.rank],
                                               algorithm=algo), p)
        for i, r in enumerate(res):
            got = np.asarray(r)
            assert got.dtype == np.float32
            assert np.max(np.abs(got.astype(np.float64) - want[i])) <= bound


def test_reduce_scatter_ragged_blocks_decline():
    """Heterogeneous per-destination blocks cannot ride the flat working
    buffer: the whole group declines (compress_fallbacks) and the
    generic path's answer matches auto's."""
    p = 2

    def prog(c, algo):
        blocks = [np.arange(i + 1, dtype=np.float64) * (c.rank + 1)
                  for i in range(c.size)]
        return c.reduce_scatter(blocks, algorithm=algo)

    f0 = mpit.pvar_read("compress_fallbacks")
    got = run_local(lambda c: prog(c, "compressed"), p)
    ref = run_local(lambda c: prog(c, "auto"), p)
    assert mpit.pvar_read("compress_fallbacks") - f0 >= p
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


# -- group-wide fallback ------------------------------------------------------


@pytest.mark.parametrize("label,world", WORLDS[1:])  # socket AND shm
def test_object_payload_fallback_parity(label, world):
    """Object payloads decline compression GROUP-WIDE (payload classes
    are congruent by the reduction contract, so every rank lands on the
    classic path together — the wire analogue of the arena meta round)
    and produce exactly auto's answer."""
    p = 2

    def prog(c, algo):
        # object-dtype payload of plain ints: reducible (python +),
        # picklable, and firmly ineligible for any wire quantizer
        x = np.array([c.rank + 1, 10 * (c.rank + 1)], object)
        return list(c.allreduce(x, algorithm=algo))

    f0 = mpit.pvar_read("compress_fallbacks")
    got = world(lambda c: prog(c, "compressed"), p)
    assert mpit.pvar_read("compress_fallbacks") - f0 >= p
    ref = world(lambda c: prog(c, "auto"), p)
    assert got == ref == [[3, 30]] * p


def test_non_float_and_unsupported_op_decline():
    p = 2
    ints = [np.arange(5, dtype=np.int64) * (r + 1) for r in range(p)]
    f0 = mpit.pvar_read("compress_fallbacks")
    res = run_local(lambda c: c.allreduce(ints[c.rank],
                                          algorithm="compressed"), p)
    np.testing.assert_array_equal(res[0], np.arange(5) * 3)
    # PROD compounds relative error multiplicatively per hop: declined
    res = run_local(lambda c: c.allreduce(np.full(4, 2.0, np.float32),
                                          ops.PROD, algorithm="compressed"),
                    p)
    np.testing.assert_array_equal(np.asarray(res[0]), np.full(4, 4.0))
    assert mpit.pvar_read("compress_fallbacks") - f0 >= 2 * p


def test_topk_non_sum_declines(topk_ratio):
    topk_ratio(0.5)
    data = _payloads(2, 20, seed=1)
    want = np.maximum(data[0], data[1])
    f0 = mpit.pvar_read("compress_fallbacks")
    res = run_local(lambda c: c.allreduce(data[c.rank], ops.MAX,
                                          algorithm="compressed:topk"), 2)
    np.testing.assert_array_equal(np.asarray(res[0]), want)  # exact: auto
    assert mpit.pvar_read("compress_fallbacks") - f0 >= 2


# -- byte accounting (the halving acceptance) --------------------------------


def test_bf16_halves_raw_bytes_zero_pickle():
    """The acceptance criterion at test scale (the 64MB leg lives in
    bench.py --compress): same spans, 2 bytes/element — bf16 wire bytes
    are EXACTLY half the f32 ring's, zero pickled array bytes, and
    bytes_compressed_saved prices the saving."""
    p, n = 2, 1 << 16
    data = _payloads(p, n, seed=0)
    names = ("bytes_raw_sent", "bytes_pickled_sent",
             "bytes_compressed_saved")
    for world in (run_socket_world, run_shm_world):
        b0 = _deltas(names)
        world(lambda c: c.allreduce(data[c.rank], algorithm="ring"), p)
        b1 = _deltas(names)
        world(lambda c: c.allreduce(data[c.rank], algorithm="compressed:bf16"),
              p)
        b2 = _deltas(names)
        plain = b1["bytes_raw_sent"] - b0["bytes_raw_sent"]
        comp = b2["bytes_raw_sent"] - b1["bytes_raw_sent"]
        assert plain == 2 * p * (p - 1) * n * 4 // p
        assert comp * 2 == plain
        assert b2["bytes_pickled_sent"] == b0["bytes_pickled_sent"]
        assert (b2["bytes_compressed_saved"] - b1["bytes_compressed_saved"]
                == plain - comp)


def test_int8_quarters_raw_bytes():
    p, n = 2, 1 << 16
    data = _payloads(p, n, seed=0)
    b0 = mpit.pvar_read("bytes_raw_sent")
    run_socket_world(lambda c: c.allreduce(data[c.rank],
                                           algorithm="compressed:int8"), p)
    comp = mpit.pvar_read("bytes_raw_sent") - b0
    dense = 2 * p * (p - 1) * n * 4 // p
    assert comp < dense * 0.27  # 1 byte/elem + per-segment scales


def test_compressed_cvar_steers_plain_spelling():
    old = mpit.cvar_read("compress_wire_dtype")
    try:
        mpit.cvar_write("compress_wire_dtype", "int8")
        p, n = 2, 4096
        data = _payloads(p, n, seed=4)
        b0 = mpit.pvar_read("bytes_raw_sent")
        run_socket_world(lambda c: c.allreduce(data[c.rank],
                                               algorithm="compressed"), p)
        comp = mpit.pvar_read("bytes_raw_sent") - b0
        assert comp < 2 * p * (p - 1) * n * 4 // p * 0.3  # int8, not bf16
        with pytest.raises(ValueError, match="compress_wire_dtype"):
            mpit.cvar_write("compress_wire_dtype", "fp4")
        with pytest.raises(ValueError, match="compress_topk_ratio"):
            mpit.cvar_write("compress_topk_ratio", 0)
    finally:
        mpit.cvar_write("compress_wire_dtype", old)


# -- the shared-memory arena tier --------------------------------------------


def test_arena_compressed_eager_hit():
    """algorithm='compressed' on an shm world routes through the arena's
    compressed eager path: zero ring frames, encoded slot writes,
    fold-dtype folds, hits counted — parity within the single-encode
    bound (each payload quantized once, folds exact)."""
    p, n = 3, 1 << 10
    data = _payloads(p, n, seed=6)
    want = sum(d.astype(np.float64) for d in data)
    names = ("msgs_sent", "bytes_pickled_sent", "coll_sm_hits",
             "bytes_raw_sent")
    b0 = _deltas(names)
    res = run_shm_world(lambda c: c.allreduce(data[c.rank],
                                              algorithm="compressed"), p)
    b1 = _deltas(names)
    assert b1["msgs_sent"] == b0["msgs_sent"]
    assert b1["bytes_raw_sent"] == b0["bytes_raw_sent"]
    assert b1["bytes_pickled_sent"] == b0["bytes_pickled_sent"]
    assert b1["coll_sm_hits"] - b0["coll_sm_hits"] == p
    for r in res:
        assert np.max(np.abs(np.asarray(r, np.float64) - want)) \
            <= 2 * 2.0 ** -8 * float(np.max(np.abs(want)))


def test_arena_compressed_above_eager_takes_wire_ring():
    """Encoded payloads above coll_sm_eager_bytes decline the arena
    (group-coherent) and run the compressed wire ring — frames move,
    still zero pickled bytes, still half raw bytes per element."""
    p = 2
    n = (coll_sm._EAGER_BYTES // 2) * 3  # encoded ~1.5x eager
    data = _payloads(p, n, seed=7)
    want = sum(d.astype(np.float64) for d in data)
    b0 = _deltas(("msgs_sent", "bytes_raw_sent", "bytes_pickled_sent"))
    res = run_shm_world(lambda c: c.allreduce(data[c.rank],
                                              algorithm="compressed"), p)
    b1 = _deltas(("msgs_sent", "bytes_raw_sent", "bytes_pickled_sent"))
    assert b1["msgs_sent"] > b0["msgs_sent"]
    assert b1["bytes_raw_sent"] - b0["bytes_raw_sent"] \
        == 2 * p * (p - 1) * n * 2 // p
    assert b1["bytes_pickled_sent"] == b0["bytes_pickled_sent"]
    for r in res:
        assert np.max(np.abs(np.asarray(r, np.float64) - want)) \
            <= _bf16_bound(p, want)


# -- pipeline / progress-engine composition ----------------------------------


def test_compressed_composes_with_segments_and_progress_engine():
    """Forced multi-segment pipelines (64B segments) under
    progress=thread: the engine's credit callbacks post ENCODED
    segments (the _SegSender wire path) and the fold decodes — parity
    bound unchanged."""
    old = mpit.cvar_read("collective_segment_bytes")
    mpit.cvar_write("collective_segment_bytes", 64)
    try:
        p = 2
        data = _payloads(p, 1000, seed=8)
        want = sum(d.astype(np.float64) for d in data)
        res = run_local(lambda c: c.allreduce(data[c.rank],
                                              algorithm="compressed:bf16"),
                        p, progress="thread")
        for r in res:
            assert np.max(np.abs(np.asarray(r, np.float64) - want)) \
                <= _bf16_bound(p, want)
    finally:
        mpit.cvar_write("collective_segment_bytes", old)


# -- codec unit ---------------------------------------------------------------


def test_codec_encoded_round_trip():
    """The wire-tagged frame kind end to end at the codec layer: meta
    pack/parse preserves the wire tag and segment geometry, value_copy
    deep-copies, nbytes sizes probes."""
    enc = codec.Encoded("int8", [np.array([0.5], np.float32),
                                 np.arange(16, dtype=np.int8)])
    assert enc.nbytes == 4 + 16
    head, bufs = codec.pack_raw_frame("ctx", 7, enc)
    body = head + b"".join(b.tobytes() for b in bufs)
    ctx, tag, got = codec.parse_raw_body(body)
    assert (ctx, tag) == ("ctx", 7)
    assert type(got) is codec.Encoded and got.wire == "int8"
    np.testing.assert_array_equal(got.segs[1], enc.segs[1])
    cp = codec.value_copy(enc)
    assert cp.wire == "int8" and cp.segs[0] is not enc.segs[0]
    np.testing.assert_array_equal(cp.segs[0], enc.segs[0])
    # streamed path: unpack_raw_meta reconstructs pooled destinations
    mlen = codec.META.unpack_from(head)[0]
    ctx2, tag2, dest = codec.unpack_raw_meta(head[codec.META.size:
                                                  codec.META.size + mlen])
    assert type(dest) is codec.Encoded and dest.wire == "int8"
    assert [d.dtype for d in codec.raw_destinations(dest)] == \
        [np.dtype(np.float32), np.dtype(np.int8)]


def test_decode_mismatch_is_typed_error():
    with pytest.raises(TypeError, match="wire"):
        compress.BF16.decode(np.ones(4, np.float32))
    with pytest.raises(TypeError, match="wire"):
        compress.BF16.decode(codec.Encoded("int8", [np.ones(4, np.int8)]))


def test_int8_non_finite_segments_propagate():
    """Review finding: a max-abs scale cannot represent a non-finite
    segment — an inf entry would poison every finite value (scale=inf)
    and a NaN would silently zero.  Such segments ship as raw f32
    passthrough (the frame is self-describing per segment), so the
    divergence signal propagates EXACTLY like the classic ring's, and
    finite ranks' contributions survive."""
    # encode/decode unit: exact passthrough
    x = np.array([1.0, 2.0, np.inf, 3.0], np.float32)
    segs = compress.INT8.encode_segs(x)
    assert segs[1].dtype == np.float32  # passthrough form
    np.testing.assert_array_equal(compress.INT8.decode_segs(segs), x)
    xn = np.array([1.0, np.nan], np.float32)
    out = compress.INT8.decode_segs(compress.INT8.encode_segs(xn))
    assert out[0] == 1.0 and np.isnan(out[1])
    # end to end: one rank overflows, the sum carries inf at that
    # position and stays finite-and-bounded elsewhere (mixed frames on
    # the wire: passthrough from rank 0, quantized from rank 1)
    p = 2
    data = _payloads(p, 64, seed=12)
    data[0][7] = np.inf

    def prog(c):
        return c.allreduce(data[c.rank], algorithm="compressed:int8")

    for world in (run_local, run_socket_world):
        res = world(prog, p)
        for r in res:
            got = np.asarray(r, np.float64)
            assert np.isinf(got[7])
            mask = np.arange(64) != 7
            want = sum(d.astype(np.float64) for d in data)
            assert np.max(np.abs(got[mask] - want[mask])) \
                <= 3 * (np.nanmax(np.abs(np.where(mask, want, 0))) + 4) / 127
