"""Raw-array framing (transport/codec.py): numpy payloads cross the
byte-stream transports without being pickled — same matching semantics,
same values, every dtype/shape/backend combination, including frames
larger than the shm ring capacity and the documented pickle fallbacks."""

import numpy as np
import pytest

from mpi_tpu.transport import codec
from tests.test_shm_backend import run_shm_world
from tests.test_socket_backend import run_socket_world

WORLDS = [("socket", run_socket_world), ("shm", run_shm_world)]


# -- codec unit behavior ----------------------------------------------------


def test_raw_eligibility():
    assert codec.as_raw_array(np.arange(3)) is not None
    assert codec.as_raw_array([1, 2, 3]) is None          # not an array
    assert codec.as_raw_array(np.array([{}], object)) is None  # object dtype
    rec = np.zeros(2, dtype=[("a", "i4"), ("b", "f8")])
    assert codec.as_raw_array(rec) is None                 # structured/void
    # non-contiguous input is compacted, values preserved
    base = np.arange(12.0).reshape(3, 4)
    sliced = base[:, ::2]
    raw = codec.as_raw_array(sliced)
    assert raw.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(raw, sliced)


def test_meta_roundtrip():
    arr = np.arange(6, dtype=np.int16).reshape(2, 3)
    packed = codec.pack_raw_meta(("c",), 7, arr)
    (mlen,) = codec.META.unpack(packed[:codec.META.size])
    ctx, tag, out = codec.unpack_raw_meta(packed[codec.META.size:
                                                 codec.META.size + mlen])
    assert ctx == ("c",) and tag == 7
    assert out.shape == arr.shape and out.dtype == arr.dtype


# -- over the real transports ----------------------------------------------

ARRAYS = [
    np.array(3.5, np.float32),                      # 0-dim
    np.empty((0, 4), np.float64),                   # empty
    np.arange(1024, dtype=np.float32),              # small (one-write path)
    np.random.RandomState(0).randn(1 << 16),        # 512KB f64 (big path)
    np.arange(33, dtype=np.int8),                   # odd length
    np.array([[True, False], [False, True]]),       # bool
    np.arange(8, dtype=np.complex64),               # complex
    (np.arange(40.0).reshape(5, 8))[::2, 1::3],     # non-contiguous view
]


@pytest.mark.parametrize("name,world", WORLDS, ids=[w[0] for w in WORLDS])
def test_array_roundtrip_all_dtypes(name, world):
    def prog(comm):
        if comm.rank == 0:
            for i, a in enumerate(ARRAYS):
                comm.send(a, dest=1, tag=i)
            return True
        got = [comm.recv(source=0, tag=i) for i in range(len(ARRAYS))]
        for a, g in zip(ARRAYS, got):
            assert isinstance(g, np.ndarray)
            assert g.dtype == a.dtype and g.shape == a.shape
            np.testing.assert_array_equal(g, np.asarray(a))
        return True

    assert all(world(prog, 2))


@pytest.mark.slow
def test_shm_array_larger_than_ring_streams():
    """A raw frame bigger than the 4MB ring must stream through — the
    header/bell/body protocol against a live reader."""
    big = np.random.RandomState(1).randn(3 << 19)  # 12 MB f64

    def prog(comm):
        if comm.rank == 0:
            comm.send(big, dest=1)
            return True
        got = comm.recv(source=0)
        np.testing.assert_array_equal(got, big)
        return True

    assert all(run_shm_world(prog, 2, timeout=120.0))


@pytest.mark.parametrize("name,world", WORLDS, ids=[w[0] for w in WORLDS])
def test_pickle_fallbacks_still_work(name, world):
    """Object/structured arrays and plain objects ride the pickle frame."""
    rec = np.zeros(3, dtype=[("a", "i4"), ("b", "f4")])
    rec["a"] = [1, 2, 3]
    payloads = [rec, {"k": np.arange(3)}, [1, "two", 3.0], None]

    def prog(comm):
        if comm.rank == 0:
            for i, p in enumerate(payloads):
                comm.send(p, dest=1, tag=i)
            return True
        got = [comm.recv(source=0, tag=i) for i in range(len(payloads))]
        np.testing.assert_array_equal(got[0], rec)
        np.testing.assert_array_equal(got[1]["k"], np.arange(3))
        assert got[2] == [1, "two", 3.0] and got[3] is None
        return True

    assert all(world(prog, 2))


# -- multi-segment raw frames (ISSUE 1: list-of-arrays zero-copy) ----------


def test_multi_segment_eligibility():
    """Only plain non-empty lists whose EVERY element is a plain
    raw-eligible ndarray ride the multi-segment frame; everything else
    keeps pickle's full type fidelity."""
    ok = [np.arange(4.0), np.zeros((2, 3), np.int16)]
    segs = codec.as_raw_segments(ok)
    assert segs is not None and len(segs) == 2
    assert all(s.flags["C_CONTIGUOUS"] for s in segs)
    assert codec.as_raw_segments([]) is None                    # empty
    assert codec.as_raw_segments(tuple(ok)) is None             # tuple
    assert codec.as_raw_segments([np.arange(3), "x"]) is None   # mixed
    assert codec.as_raw_segments(
        [np.array([{}], object)]) is None                       # object dtype
    rec = np.zeros(2, dtype=[("a", "i4")])
    assert codec.as_raw_segments([rec]) is None                 # structured


def test_aliased_list_keeps_pickle_identity():
    """A list holding the SAME array twice stays on pickle, whose memo
    preserves the aliasing on the receiver (got[0] is got[1]) —
    independent raw segments (and per-element value_copy) cannot, and a
    program mutating got[0] expecting got[1] to follow would silently
    diverge."""
    a = np.arange(4.0)
    assert codec.as_raw_segments([a, a]) is None
    copied = codec.value_copy([a, a])
    assert copied[0] is copied[1]
    assert copied[0] is not a and np.array_equal(copied[0], a)
    # equal-but-distinct arrays still ride the raw frame
    assert codec.as_raw_segments([a, a.copy()]) is not None


def test_multi_segment_meta_roundtrip():
    segs = [np.arange(5, dtype=np.float32),
            np.arange(6, dtype=np.int64).reshape(2, 3)]
    packed = codec.pack_raw_segs_meta(("c",), 9, segs)
    (mlen,) = codec.META.unpack(packed[:codec.META.size])
    ctx, tag, out = codec.unpack_raw_meta(packed[codec.META.size:
                                                 codec.META.size + mlen])
    assert ctx == ("c",) and tag == 9
    assert isinstance(out, list) and len(out) == 2
    for dst, src in zip(out, segs):
        assert dst.shape == src.shape and dst.dtype == src.dtype


SEG_LISTS = [
    [np.arange(7.0)],                                    # single segment
    [np.arange(5, dtype=np.float32),                     # mixed dtypes/shapes
     np.arange(12, dtype=np.int64).reshape(3, 4),
     np.array(2.5, np.float64)],                         # incl. 0-dim
    [np.empty(0, np.float32), np.arange(3, dtype=np.int8)],  # empty segment
    [np.random.RandomState(3).randn(1 << 16),            # 512KB each: the
     np.random.RandomState(4).randn(1 << 16)],           # big streaming path
    [(np.arange(40.0).reshape(5, 8))[::2, 1::3],         # non-contiguous
     np.arange(4.0)],
]


@pytest.mark.parametrize("name,world", WORLDS, ids=[w[0] for w in WORLDS])
def test_multi_segment_roundtrip(name, world):
    """A list of arrays crosses both byte-stream transports as ONE raw
    frame — values exact, no pickled array bytes."""
    from mpi_tpu import mpit

    def prog(comm):
        if comm.rank == 0:
            for i, lst in enumerate(SEG_LISTS):
                comm.send(lst, dest=1, tag=i)
            return True
        for i, lst in enumerate(SEG_LISTS):
            got = comm.recv(source=0, tag=i)
            assert isinstance(got, list) and len(got) == len(lst)
            for g, want in zip(got, lst):
                assert g.dtype == want.dtype and g.shape == want.shape
                np.testing.assert_array_equal(g, want)
        return True

    pickled_before = mpit.counters.bytes_pickled
    assert all(world(prog, 2))
    assert mpit.counters.bytes_pickled == pickled_before


@pytest.mark.parametrize("name,world", WORLDS, ids=[w[0] for w in WORLDS])
def test_multi_segment_pickle_fallback_object_dtype(name, world):
    """A list containing an object-dtype array falls back to pickle —
    and round-trips the objects faithfully (the fidelity the fallback
    exists to preserve)."""
    from mpi_tpu import mpit

    lst = [np.arange(3.0), np.array([{"k": 1}, None], dtype=object)]
    assert codec.as_raw_segments(lst) is None

    def prog(comm):
        if comm.rank == 0:
            comm.send(lst, dest=1, tag=0)
            return True
        got = comm.recv(source=0, tag=0)
        np.testing.assert_array_equal(got[0], lst[0])
        assert got[1].dtype == object and got[1][0] == {"k": 1}
        assert got[1][1] is None
        return True

    pickled_before = mpit.counters.bytes_pickled
    assert all(world(prog, 2))
    assert mpit.counters.bytes_pickled > pickled_before


def test_multi_segment_self_send_value_semantics():
    """Self-sent lists of arrays keep message (value) semantics per
    element."""
    from mpi_tpu.transport import codec as c

    lst = [np.arange(4.0), np.ones(2)]
    cp = c.value_copy(lst)
    lst[0][:] = -1
    np.testing.assert_array_equal(cp[0], np.arange(4.0))
    np.testing.assert_array_equal(cp[1], np.ones(2))


@pytest.mark.parametrize("name,world", WORLDS, ids=[w[0] for w in WORLDS])
def test_raw_self_send_copies(name, world):
    """Self-sends keep value semantics: mutating after send must not
    affect the delivered message."""
    def prog(comm):
        buf = np.arange(4.0)
        comm.send(buf, dest=comm.rank, tag=5)
        buf[:] = -1.0
        got = comm.recv(source=comm.rank, tag=5)
        np.testing.assert_array_equal(got, np.arange(4.0))
        return True

    assert all(world(prog, 1))


@pytest.mark.parametrize("name,world", WORLDS, ids=[w[0] for w in WORLDS])
def test_ndarray_subclasses_survive(name, world):
    """MaskedArray must ride the pickle frame (raw frames would drop the
    mask); behavior must match self-sends."""
    ma = np.ma.masked_array([1.0, 2.0, 3.0], mask=[False, True, False])

    def prog(comm):
        peer = (comm.rank + 1) % comm.size
        comm.send(ma, dest=peer, tag=1)
        comm.send(ma, dest=comm.rank, tag=2)   # self-send
        for tag in (1, 2):
            got = comm.recv(tag=tag)
            assert isinstance(got, np.ma.MaskedArray)
            assert list(got.mask) == [False, True, False]
            np.testing.assert_array_equal(got.compressed(), [1.0, 3.0])
        return True

    assert all(world(prog, 2))


def test_recv_pool_recycles_and_vetoes_aliases():
    """The large-recv buffer pool (the 16MB-bandwidth fix: one page fault
    per destination page per message otherwise dominates the receiver's
    time) must reuse clean buffers and NEVER recycle aliased memory."""
    import numpy as np
    from mpi_tpu.transport.codec import _BufferPool

    pool = _BufferPool(min_bytes=1 << 20)
    a = pool.empty((1 << 20,), np.dtype(np.uint8))
    backing = a.base.ctypes.data
    a[:] = 7
    del a
    b = pool.empty((1 << 20,), np.dtype(np.uint8))
    assert b.base.ctypes.data == backing  # recycled

    alias = b[:16]
    del b
    c = pool.empty((1 << 20,), np.dtype(np.uint8))
    assert c.base.ctypes.data != backing  # alias vetoed the recycle
    c[:] = 9
    assert alias.tobytes() != b"\x09" * 16  # user data never clobbered

    # small allocations bypass the pool entirely
    s = pool.empty((16,), np.dtype(np.float32))
    assert s.base is None


def test_recv_pool_different_dtypes_share_storage():
    import numpy as np
    from mpi_tpu.transport.codec import _BufferPool

    pool = _BufferPool(min_bytes=1 << 20)
    a = pool.empty((1 << 18,), np.dtype(np.float32))  # 1MB
    backing = a.base.ctypes.data
    del a
    b = pool.empty((1 << 16, 2), np.dtype(np.complex64))  # also 1MB
    assert b.base.ctypes.data == backing
    assert b.shape == (1 << 16, 2) and b.dtype == np.complex64


# -- MPI-4 large counts (>2^31) — VERDICT r4 missing #5 ----------------------


def test_large_count_framing_arithmetic():
    """Every byte-stream framing layer carries 63-bit lengths: the
    codec/socket/shm length words round-trip counts far beyond 2^31
    (the MPI-3 int limit that large-count bindings exist to escape).
    Pure arithmetic — no multi-GB buffer is allocated."""
    import struct

    from mpi_tpu.transport import codec

    big = 5 * 2 ** 31 + 12345  # ~10.7 GB, > any 32-bit count
    assert big <= codec.LEN_MASK  # 63 usable bits
    # socket header word (transport/socket.py _HEADER "!QQ")
    word = codec.RAW_FLAG | big
    packed = struct.Struct("!QQ").pack(word, 7)
    w2, seq = struct.Struct("!QQ").unpack(packed)
    assert seq == 7 and (w2 & codec.LEN_MASK) == big
    assert w2 & codec.RAW_FLAG
    # shm header word (transport/shm.py _LEN "<Q")
    (w3,) = struct.Struct("<Q").unpack(struct.Struct("<Q").pack(word))
    assert (w3 & codec.LEN_MASK) == big
    # raw-array meta describes >2^31-element shapes losslessly (pickle
    # ints are unbounded); frame math stays exact at that scale
    class FakeArr:
        dtype = np.dtype(np.float32)
        shape = (big,)
    meta = codec.pack_raw_meta(("ctx",), 3, FakeArr())
    import pickle as pkl

    (mlen,) = codec.META.unpack_from(meta)
    ctx, tag, dtype_str, shape = pkl.loads(
        meta[codec.META.size:codec.META.size + mlen])
    assert ctx == ("ctx",) and tag == 3
    assert shape == (big,) and np.dtype(dtype_str) == np.float32


def test_large_count_io_syscall_loops(tmp_path, monkeypatch):
    """The pread/pwrite full-transfer loops (mpi_tpu/io.py) survive the
    kernel's ~2 GiB single-syscall cap: with the syscalls monkeypatched
    to cap at 1000 bytes, multi-"GB" (scaled-down) transfers complete
    exactly — the loop structure, not the buffer size, is what the
    large-count path needs."""
    import os as os_

    from mpi_tpu import io as mio

    calls = {"w": 0, "r": 0}
    real_pwrite, real_pread = os_.pwrite, os_.pread

    def capped_pwrite(fd, buf, off):
        calls["w"] += 1
        return real_pwrite(fd, bytes(buf[:1000]), off)

    def capped_pread(fd, n, off):
        calls["r"] += 1
        return real_pread(fd, min(n, 1000), off)

    monkeypatch.setattr(mio.os, "pwrite", capped_pwrite)
    monkeypatch.setattr(mio.os, "pread", capped_pread)
    path = str(tmp_path / "big.bin")
    data = np.arange(2500, dtype=np.uint8)  # forces 3 capped syscalls
    fd = os_.open(path, os_.O_CREAT | os_.O_RDWR, 0o644)
    try:
        mio._pwrite_full(fd, memoryview(data), 0)
        assert calls["w"] >= 3
        back = mio._pread_full(fd, 2500, 0)
        assert calls["r"] >= 3
        assert np.array_equal(np.frombuffer(back, np.uint8), data)
    finally:
        os_.close(fd)


def test_large_count_python_ints_unbounded():
    """The count plumbing (Status.count_bytes, payload_nbytes,
    MPI_Get_count division) is plain Python integers — no 32-bit
    truncation anywhere on the count path."""
    from mpi_tpu.communicator import Status
    from mpi_tpu.transport.base import payload_nbytes

    class Huge:
        nbytes = 3 * 2 ** 32

    assert payload_nbytes(Huge()) == 3 * 2 ** 32
    st = Status()
    st._set_count(Huge())
    assert st.count_bytes == 3 * 2 ** 32  # exact, not wrapped
