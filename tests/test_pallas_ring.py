"""Pallas RDMA ring allreduce vs numpy oracle (interpreter on the virtual
CPU mesh; the same kernel compiles for real ICI on a slice)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mpi_tpu.tpu import TpuCommunicator, default_mesh
from mpi_tpu.tpu.pallas_ring import pallas_ring_allreduce


def _run(nranks, n, tile_rows=8, seed=0):
    mesh = default_mesh(nranks)
    data = np.asarray(np.random.RandomState(seed).randn(nranks, n), np.float32)

    def f(x):
        return pallas_ring_allreduce(x.reshape(-1), "world", nranks,
                                     tile_rows=tile_rows, interpret=True)[None]

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(jnp.asarray(data.reshape(-1)))
    return np.asarray(out).reshape(nranks, n), data


@pytest.mark.parametrize("nranks,n", [(2, 128), (4, 1000), (8, 4096), (3, 77)])
def test_pallas_ring_allreduce(nranks, n):
    out, data = _run(nranks, n)
    for r in range(nranks):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_ring_via_communicator():
    from mpi_tpu.tpu import run_spmd

    data = np.asarray(np.random.RandomState(1).randn(8, 300), np.float32)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, check_vma=False))
    for r in range(8):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_ring_vma_diagnostic():
    """With vma typing on, the pallas path must fail with guidance, not a
    cryptic pallas internal error."""
    from mpi_tpu.tpu import run_spmd

    data = np.zeros((8, 16), np.float32)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], algorithm="pallas_ring")

    with pytest.raises(Exception, match="check_vma"):
        run_spmd(prog, data)  # default check_vma=True


def test_pallas_ring_diagnostics():
    mesh = default_mesh()
    comm = TpuCommunicator("world", mesh)
    sub = comm.split_by(lambda i: i % 2)
    from mpi_tpu import ops

    with pytest.raises(NotImplementedError, match="ungrouped"):
        sub.allreduce(jnp.zeros(8), algorithm="pallas_ring")
    with pytest.raises(NotImplementedError, match="SUM"):
        comm.allreduce(jnp.zeros(8), op=ops.MAX, algorithm="pallas_ring")
    with pytest.raises(NotImplementedError, match="float32"):
        pallas_ring_allreduce(jnp.zeros(8, jnp.int32), "world", 8)


@pytest.mark.parametrize("nranks,n", [(2, 4096), (4, 20000)])
def test_pallas_ring_multi_segment(nranks, n):
    """Sizes large enough that each chunk splits into >1 pipeline segment
    (tile_rows=8 → 4 segments at these sizes)."""
    out, data = _run(nranks, n)
    for r in range(nranks):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_ring_bf16():
    nranks, n = 4, 512
    mesh = default_mesh(nranks)
    data = np.asarray(np.random.RandomState(3).randn(nranks, n), np.float32)
    bf = jnp.asarray(data, jnp.bfloat16)

    def f(x):
        return pallas_ring_allreduce(x.reshape(-1), "world", nranks,
                                     tile_rows=16, interpret=True)[None]

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(bf.reshape(-1))
    assert out.dtype == jnp.bfloat16
    # bf16 ring-order sums: loose tolerance vs the f32 oracle
    np.testing.assert_allclose(
        np.asarray(out, np.float32).reshape(nranks, n)[0], data.sum(0),
        rtol=0.05, atol=0.05)


@pytest.mark.parametrize("nranks,block", [(2, 256), (4, 1000), (8, 128)])
def test_pallas_ring_reduce_scatter(nranks, block):
    from mpi_tpu.tpu.pallas_ring import pallas_ring_reduce_scatter

    mesh = default_mesh(nranks)
    # every rank holds a DIFFERENT full [P, block] stack
    data = np.asarray(
        np.random.RandomState(7).randn(nranks, nranks * block), np.float32)

    def f(x):
        return pallas_ring_reduce_scatter(
            x.reshape(nranks, block), "world", nranks, tile_rows=8,
            interpret=True).reshape(1, block)

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(jnp.asarray(data.reshape(-1)))
    out = np.asarray(out).reshape(nranks, block)
    oracle = data.reshape(nranks, nranks, block).sum(0)  # [P, block]
    for r in range(nranks):
        np.testing.assert_allclose(out[r], oracle[r], rtol=1e-4, atol=1e-5)


def test_pallas_ring_rejects_bad_dtype_and_shape():
    from mpi_tpu.tpu.pallas_ring import pallas_ring_reduce_scatter

    with pytest.raises(NotImplementedError, match="float32/bfloat16"):
        pallas_ring_allreduce(jnp.zeros(8, jnp.int32), "world", 2)
    with pytest.raises(ValueError, match="leading dimension"):
        pallas_ring_reduce_scatter(jnp.zeros(7, jnp.float32), "world", 2)


def test_pallas_ring_reduce_scatter_via_communicator():
    from mpi_tpu.tpu import run_spmd

    P_ = 4
    block = 100
    data = np.asarray(
        np.random.RandomState(9).randn(P_, P_, block), np.float32)

    def prog(comm, x):
        return comm.reduce_scatter(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, nranks=P_, check_vma=False))
    oracle = data.sum(0)  # [P, block]
    np.testing.assert_allclose(out, oracle, rtol=1e-4, atol=1e-5)
