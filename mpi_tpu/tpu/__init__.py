"""backend=tpu — MPI semantics over a jax.sharding.Mesh (SURVEY.md §7 M1-M2).

Public surface:
* :func:`run_spmd` / :func:`default_mesh` — run a portable MPI program as one
  SPMD trace over the device mesh.
* :class:`TpuCommunicator` — the Communicator bound to a mesh axis; fused XLA
  collectives plus hand-scheduled ppermute algorithms (ring /
  recursive-halving / tree / doubling / pairwise).
* :func:`pallas_ring_attention` — fused long-context ring attention (K/V
  circulate as in-kernel RDMAs; pallas_attention.py).
"""

from .communicator import SpmdSemanticsError, TpuCommunicator
from .runner import default_mesh, run_spmd
from . import collectives


def __getattr__(name: str):
    # PEP 562 lazy re-export: ``import mpi_tpu.tpu`` stays light (pallas
    # loads only when used) and callers get the GENUINE function object
    # — real signature, real docstring (review round 4)
    if name == "pallas_ring_attention":
        from .pallas_attention import pallas_ring_attention

        return pallas_ring_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "TpuCommunicator",
    "SpmdSemanticsError",
    "run_spmd",
    "default_mesh",
    "collectives",
    "pallas_ring_attention",
]
