"""Derived datatypes — MPI-1 chapter-3 layout descriptors [S].

The reference is MPI-1-level (BASELINE.json:5; SURVEY.md §0.1), and MPI-1's
type-constructor family (contiguous / vector / indexed / struct, plus
MPI_Pack/Unpack) is how real MPI programs describe non-contiguous payloads:
matrix columns, sub-blocks, halo faces.  A C MPI implements them as strided
memcpy loops executed at send time.  The TPU-native translation is different
and better suited to XLA: a committed datatype compiles ONCE into a flat
*gather index vector* over the base-typed buffer, and then

* ``pack``   = ``buf.flat[idx]``            (numpy take / one fusable
* ``unpack`` = ``out.flat[idx] = data``      lax.gather-scatter on device)

so the same index map drives the process backends (numpy) and jit-traced
SPMD code (``pack_jax`` / ``unpack_jax`` — the indices are static trace-time
constants, exactly what XLA wants: no dynamic shapes, no per-element loops).

Units and composition follow MPI semantics: displacements/strides in the
element constructors are in units of the *base type's extent*; heterogeneous
``type_create_struct`` drops to a byte-based map (base dtype uint8, byte
displacements), which is also what lets numpy structured dtypes interoperate.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import numpy as np

__all__ = [
    "Datatype", "type_contiguous", "type_vector", "type_indexed",
    "type_create_subarray", "type_create_struct", "type_create_resized",
    "type_create_hvector", "type_create_hindexed",
    "from_structured", "pack", "unpack", "pack_size",
    "pack_external", "unpack_external",
]

BaseLike = Union[str, type, np.dtype, "Datatype"]


def _as_base(base: BaseLike) -> "Datatype":
    if isinstance(base, Datatype):
        return base
    dt = np.dtype(base)
    if dt.names:  # structured dtype: byte-based map over its fields
        return from_structured(dt)
    if dt == np.uint8:  # MPI_BYTE: endian-neutral, external32 identity
        return Datatype(dt, np.arange(1, dtype=np.int64), 1,
                        elem_sizes=np.ones(1, np.int64))
    return Datatype(dt, np.arange(1, dtype=np.int64), 1)


class Datatype:
    """A committed layout: ``indices`` are element offsets (units of
    ``base_dtype``) selected by one instance; ``extent`` is the span one
    instance occupies when instances are replicated (``count > 1`` or an
    outer constructor), mirroring MPI extent semantics [S]."""

    __slots__ = ("base_dtype", "indices", "extent", "lb", "elem_sizes",
                 "_committed")

    def __init__(self, base_dtype: np.dtype, indices: np.ndarray, extent: int,
                 lb: int = 0, elem_sizes: Optional[np.ndarray] = None):
        self.base_dtype = np.dtype(base_dtype)
        self.indices = np.asarray(indices, dtype=np.int64).reshape(-1)
        self.extent = int(extent)
        self.lb = int(lb)  # bookkeeping only (get_extent); never shifts the map
        # byte-based (struct) maps only: per-ELEMENT byte lengths of one
        # packed instance, in packed order — what external32 needs to
        # byteswap field-wise (a whole-stream swap would be a no-op on
        # uint8).  None ⇔ not a struct map / unknown (external32 refuses).
        self.elem_sizes = (None if elem_sizes is None
                           else np.asarray(elem_sizes, dtype=np.int64))
        self._committed = False

    # -- introspection (MPI_Type_size / MPI_Type_get_extent) ---------------

    @property
    def size(self) -> int:
        """Bytes of actual data one instance transfers (MPI_Type_size)."""
        return int(self.indices.size * self.base_dtype.itemsize)

    @property
    def count(self) -> int:
        """Base elements one instance transfers."""
        return int(self.indices.size)

    @property
    def extent_bytes(self) -> int:
        return self.extent * self.base_dtype.itemsize

    def commit(self) -> "Datatype":
        """MPI_Type_commit: validate the map (duplicate offsets would make
        unpack order-dependent; negatives would alias from the end)."""
        if self.indices.size and int(self.indices.min()) < 0:
            raise ValueError("datatype has negative element displacements")
        if np.unique(self.indices).size != self.indices.size:
            raise ValueError("datatype maps the same element twice "
                             "(overlapping blocks) — unpack would be "
                             "order-dependent")
        self._committed = True
        return self

    def free(self) -> None:
        """MPI_Type_free (bookkeeping only — no resources to release)."""
        self._committed = False

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return (f"Datatype(base={self.base_dtype}, count={self.count}, "
                f"extent={self.extent})")

    # -- replication helper ------------------------------------------------

    def _tiled(self, count: int) -> np.ndarray:
        if count == 1:
            return self.indices
        offs = np.arange(count, dtype=np.int64) * self.extent
        return (self.indices[None, :] + offs[:, None]).reshape(-1)

    def _flat_view(self, buf: Any, writeback: bool = False) -> np.ndarray:
        if writeback and not isinstance(buf, np.ndarray):
            raise TypeError(f"unpack target must be an ndarray, got "
                            f"{type(buf).__name__}")
        a = np.asarray(buf)
        if writeback and not a.flags["C_CONTIGUOUS"]:
            # ascontiguousarray would copy and the scatter would land in
            # the copy — a silent no-op on the caller's buffer
            raise TypeError("unpack target must be C-contiguous (got a "
                            "strided view; unpack into the owning array "
                            "and describe the view with the datatype)")
        a = np.ascontiguousarray(a)
        if self.base_dtype == np.uint8 and a.dtype != np.uint8:
            a = a.view(np.uint8)
        elif a.dtype != self.base_dtype:
            raise TypeError(f"buffer dtype {a.dtype} != datatype base "
                            f"{self.base_dtype}")
        return a.reshape(-1)

    def _checked_indices(self, count: int, limit: int,
                         writeback: bool = False) -> np.ndarray:
        idx = self._tiled(count)
        if idx.size and int(idx.min()) < 0:
            raise ValueError("datatype has negative element displacements")
        if idx.size and int(idx.max()) >= limit:
            raise ValueError(f"datatype touches element {int(idx.max())} but "
                             f"buffer has {limit}")
        if writeback and count > 1 and self.indices.size and \
                self.extent <= int(self.indices.max()):
            # RECEIVE side only: MPI permits overlapping send typemaps
            # (reading an element twice is well-defined); an overlapping
            # unpack would be order-dependent.  Instances can interleave
            # only when the extent is inside the map's span — only then
            # pay for the uniqueness check.
            if np.unique(idx).size != idx.size:
                raise ValueError(
                    f"replicating {count} instances at extent {self.extent} "
                    "maps the same element twice (instances overlap) — "
                    "unpack would be order-dependent")
        return idx

    # -- host (numpy) path -------------------------------------------------

    def pack(self, buf: Any, count: int = 1) -> np.ndarray:
        """Gather ``count`` instances from ``buf`` into a contiguous array."""
        flat = self._flat_view(buf)
        idx = self._checked_indices(count, flat.size)
        return flat[idx].copy()

    def unpack(self, packed: Any, out: np.ndarray, count: int = 1) -> np.ndarray:
        """Scatter a contiguous ``packed`` array into ``out`` in-place."""
        flat = self._flat_view(out, writeback=True)
        idx = self._checked_indices(count, flat.size, writeback=True)
        data = np.asarray(packed).reshape(-1)
        if data.dtype != self.base_dtype:
            raise TypeError(f"packed payload dtype {data.dtype} != datatype "
                            f"base {self.base_dtype}")
        if data.size != idx.size:
            raise ValueError(f"packed payload has {data.size} elements, "
                             f"datatype expects {idx.size}")
        flat[idx] = data
        return out

    # -- device (jit-traceable) path ---------------------------------------

    def _jax_byte_view(self, x):
        """Byte-based maps index BYTES: bitcast the buffer to a uint8
        stream (the jit spelling of _flat_view's ``a.view(np.uint8)``)."""
        from jax import lax as jlax

        import jax.numpy as jnp

        if x.dtype == jnp.uint8:
            return x.reshape(-1)
        return jlax.bitcast_convert_type(x.reshape(-1), jnp.uint8).reshape(-1)

    def pack_jax(self, x: Any, count: int = 1):
        """Same gather under jit: indices are trace-time constants, so this
        lowers to one static lax.gather XLA can fuse."""
        import jax.numpy as jnp

        x = jnp.asarray(x)
        self._check_jax_dtype(x)
        if self.base_dtype == np.uint8:
            x = self._jax_byte_view(x)
        idx = self._checked_indices(count, x.size)  # static: checked at trace
        return jnp.take(x.reshape(-1), idx, axis=0)

    def unpack_jax(self, packed: Any, out: Any, count: int = 1):
        """Functional scatter: returns ``out`` with the instances placed."""
        import jax.numpy as jnp

        from jax import lax as jlax

        o = jnp.asarray(out)
        self._check_jax_dtype(o)
        data = jnp.asarray(packed).reshape(-1)
        if self.base_dtype == np.uint8:
            flat = self._jax_byte_view(o)
        else:
            flat = o.reshape(-1)
        # same strictness as the host path: exact payload dtype and size
        if data.dtype != flat.dtype:
            raise TypeError(f"packed payload dtype {data.dtype} != datatype "
                            f"base {flat.dtype}")
        idx = self._checked_indices(count, flat.size, writeback=True)  # static
        if data.size != idx.size:
            raise ValueError(f"packed payload has {data.size} elements, "
                             f"datatype expects {idx.size}")
        flat = flat.at[idx].set(data)
        if self.base_dtype == np.uint8 and o.dtype != jnp.uint8:
            flat = jlax.bitcast_convert_type(
                flat.reshape(-1, np.dtype(o.dtype).itemsize), o.dtype)
        return flat.reshape(o.shape)

    def _check_jax_dtype(self, x) -> None:
        """Same strictness as the numpy path — indices are ELEMENT offsets,
        so a buffer of a different dtype would be a silent reinterpretation.
        Compared against jax's CANONICALIZED base dtype (float64 maps to
        float32 under the default x64-off config — that narrowing is jax's
        documented behavior, not a layout error); byte-based maps are
        exempt, as on the host path."""
        if self.base_dtype == np.uint8:
            return
        from jax import dtypes as _jd

        if x.dtype != _jd.canonicalize_dtype(self.base_dtype):
            raise TypeError(f"buffer dtype {x.dtype} != datatype base "
                            f"{self.base_dtype}")


# -- constructors (MPI_Type_*) ---------------------------------------------


def _tile_es(b: "Datatype", n: int) -> Optional[np.ndarray]:
    """Replicate a byte-based base's per-element sizes through a derived
    constructor (element order is preserved by every constructor)."""
    if b.base_dtype != np.uint8 or b.elem_sizes is None:
        return None
    return np.tile(b.elem_sizes, n)


def type_contiguous(count: int, base: BaseLike) -> Datatype:
    """MPI_Type_contiguous: ``count`` back-to-back instances of ``base``."""
    b = _as_base(base)
    return Datatype(b.base_dtype, b._tiled(int(count)), int(count) * b.extent,
                    elem_sizes=_tile_es(b, int(count)))


def type_vector(count: int, blocklength: int, stride: int,
                base: BaseLike) -> Datatype:
    """MPI_Type_vector: ``count`` blocks of ``blocklength`` instances,
    block starts ``stride`` base-extents apart (a strided matrix column:
    ``type_vector(nrows, 1, ncols, float64)``)."""
    b = _as_base(base)
    count, blocklength, stride = int(count), int(blocklength), int(stride)
    starts = np.arange(count, dtype=np.int64) * stride * b.extent
    block = b._tiled(blocklength)
    idx = (starts[:, None] + block[None, :]).reshape(-1)
    extent = ((count - 1) * stride + blocklength) * b.extent if count else 0
    return Datatype(b.base_dtype, idx, extent,
                    elem_sizes=_tile_es(b, count * blocklength))


def type_indexed(blocklengths: Sequence[int], displacements: Sequence[int],
                 base: BaseLike) -> Datatype:
    """MPI_Type_indexed: irregular blocks at arbitrary displacements
    (units of the base extent)."""
    b = _as_base(base)
    if len(blocklengths) != len(displacements):
        raise ValueError("blocklengths and displacements differ in length")
    parts = []
    span = 0
    for n, d in zip(blocklengths, displacements):
        n, d = int(n), int(d)
        parts.append(d * b.extent + b._tiled(n))
        span = max(span, (d + n) * b.extent)
    idx = np.concatenate(parts) if parts else np.empty(0, np.int64)
    total = sum(int(n) for n in blocklengths)
    return Datatype(b.base_dtype, idx, span, elem_sizes=_tile_es(b, total))


def type_create_subarray(sizes: Sequence[int], subsizes: Sequence[int],
                         starts: Sequence[int], base: BaseLike) -> Datatype:
    """MPI_Type_create_subarray (C order): the n-D sub-block
    ``[start : start+subsize]`` per dim of an n-D array — THE datatype for
    halo faces and tiled I/O.  Extent spans the whole array, so ``count``
    instances mean consecutive whole arrays (matching MPI)."""
    b = _as_base(base)
    sizes = [int(s) for s in sizes]
    subsizes = [int(s) for s in subsizes]
    starts = [int(s) for s in starts]
    if not (len(sizes) == len(subsizes) == len(starts)):
        raise ValueError("sizes/subsizes/starts rank mismatch")
    for s, sub, st in zip(sizes, subsizes, starts):
        if st < 0 or sub < 0 or st + sub > s:
            raise ValueError(f"subarray [{st}:{st + sub}] out of bounds "
                             f"for size {s}")
    # element offsets of the sub-block in the row-major full array
    grid = np.ix_(*[np.arange(st, st + sub) for st, sub in zip(starts, subsizes)])
    flat_idx = np.ravel_multi_index(np.broadcast_arrays(*grid), sizes)
    idx = np.asarray(flat_idx, dtype=np.int64).reshape(-1)
    n_elems = int(np.prod(sizes)) if sizes else 1
    # compose with a non-trivial base by expanding each element slot
    n_sel = idx.size
    if b.count != 1 or b.extent != 1:
        idx = (idx[:, None] * b.extent + b.indices[None, :]).reshape(-1)
        n_elems *= b.extent
    return Datatype(b.base_dtype, idx, n_elems,
                    elem_sizes=_tile_es(b, n_sel))


def type_create_struct(blocklengths: Sequence[int],
                       displacements: Sequence[int],
                       types: Sequence[BaseLike]) -> Datatype:
    """MPI_Type_create_struct: heterogeneous blocks at *byte* displacements.
    Compiles to a byte-based map (base uint8) — the contiguous packed form
    is raw bytes, interoperable with numpy structured dtypes."""
    if not (len(blocklengths) == len(displacements) == len(types)):
        raise ValueError("struct constructor argument lengths differ")
    parts = []
    sizes = []  # per-element byte lengths, packed order (for external32)
    span = 0
    for n, d, t in zip(blocklengths, displacements, types):
        b = _as_base(t)
        n, d = int(n), int(d)
        item = b._tiled(n) * b.base_dtype.itemsize  # element→byte offsets
        byte_idx = (item[:, None]
                    + np.arange(b.base_dtype.itemsize, dtype=np.int64)[None, :]
                    ).reshape(-1) + d
        parts.append(byte_idx)
        if b.base_dtype == np.uint8:
            sizes.append(None if b.elem_sizes is None
                         else np.tile(b.elem_sizes, n))
        elif b.base_dtype.kind == "c":
            # complex = two independently-endian components: swapping the
            # whole element would also swap real/imag order on the wire
            sizes.append(np.full(n * b.count * 2,
                                 b.base_dtype.itemsize // 2, np.int64))
        else:
            sizes.append(np.full(n * b.count, b.base_dtype.itemsize,
                                 np.int64))
        span = max(span, d + n * b.extent_bytes)
    idx = np.concatenate(parts) if parts else np.empty(0, np.int64)
    es = (np.concatenate(sizes) if sizes and all(s is not None for s in sizes)
          else None)
    return Datatype(np.dtype(np.uint8), idx, span, elem_sizes=es)


def type_create_hvector(count: int, blocklength: int, stride_bytes: int,
                        base: BaseLike) -> Datatype:
    """MPI_Type_create_hvector: like type_vector but the stride is in
    BYTES.  The index-map model addresses typed elements, so the byte
    stride must be a whole multiple of the base extent (arbitrary byte
    strides would mis-align every element); misuse is diagnosed, not
    approximated."""
    b = _as_base(base)
    unit = b.extent_bytes  # type_vector strides are in units of the base
    # EXTENT (a derived base spans extent elements, not one itemsize)
    if unit == 0 or stride_bytes % unit:
        raise ValueError(
            f"hvector byte stride {stride_bytes} is not a multiple of the "
            f"base extent {unit} bytes — such a layout cannot address "
            f"whole base instances (use a uint8-based struct map for raw "
            f"bytes)")
    return type_vector(count, blocklength, stride_bytes // unit, base)


def type_create_hindexed(blocklengths: Sequence[int],
                         byte_displacements: Sequence[int],
                         base: BaseLike) -> Datatype:
    """MPI_Type_create_hindexed: indexed with BYTE displacements (same
    whole-element restriction as hvector)."""
    b = _as_base(base)
    unit = b.extent_bytes  # displacements are in base-EXTENT units too
    disps = []
    for d in byte_displacements:
        if unit == 0 or int(d) % unit:
            raise ValueError(
                f"hindexed byte displacement {d} is not a multiple of the "
                f"base extent {unit} bytes")
        disps.append(int(d) // unit)
    return type_indexed(blocklengths, disps, base)


def type_create_resized(base: BaseLike, lb: int, extent: int) -> Datatype:
    """MPI_Type_create_resized: same typemap (displacements UNCHANGED —
    lb/extent are bookkeeping markers in MPI, not shifts [S]); ``extent``
    (units of the base dtype) controls where replicated instances land;
    ``lb`` is recorded for MPI_Type_get_extent."""
    b = _as_base(base)
    return Datatype(b.base_dtype, b.indices, int(extent), lb=int(lb),
                    elem_sizes=b.elem_sizes)


def from_structured(dtype: Any) -> Datatype:
    """A numpy structured dtype as a (byte-based) MPI struct — including
    its padding holes, which are skipped exactly like MPI_UB gaps."""
    dt = np.dtype(dtype)
    if not dt.names:
        raise ValueError(f"{dt} is not a structured dtype")
    lens, disps, types = [], [], []
    for name in dt.names:
        fdt, off = dt.fields[name][0], dt.fields[name][1]
        if fdt.subdtype is not None:
            sub, shape = fdt.subdtype
            lens.append(int(np.prod(shape)))
            types.append(sub)
        else:
            lens.append(1)
            types.append(fdt)
        disps.append(off)
    out = type_create_struct(lens, disps, types)
    return Datatype(out.base_dtype, out.indices, dt.itemsize,
                    elem_sizes=out.elem_sizes)


# -- MPI_Pack / MPI_Unpack --------------------------------------------------


def pack(buf: Any, datatype: Datatype, count: int = 1,
         position: Optional[bytearray] = None) -> bytes:
    """MPI_Pack: append ``count`` instances to ``position`` (a growing
    bytearray standing in for the MPI position cursor) and return the
    packed bytes added."""
    data = datatype.pack(buf, count).tobytes()
    if position is not None:
        position.extend(data)
    return data


def unpack(packed: Union[bytes, bytearray, memoryview], datatype: Datatype,
           out: np.ndarray, count: int = 1, offset: int = 0) -> int:
    """MPI_Unpack: consume ``count`` instances from ``packed`` starting at
    byte ``offset`` into ``out``; returns the new offset."""
    nbytes = datatype.size * count
    chunk = np.frombuffer(bytes(packed[offset:offset + nbytes]),
                          dtype=datatype.base_dtype)
    datatype.unpack(chunk, out, count)
    return offset + nbytes


def pack_size(count: int, datatype: Datatype) -> int:
    """MPI_Pack_size: bytes needed for ``count`` instances."""
    return datatype.size * int(count)


# -- external32 (MPI_Pack_external [S]) -------------------------------------


def _swap_struct_bytes(raw: np.ndarray, datatype: Datatype,
                       count: int) -> np.ndarray:
    """Reverse each element's byte run in a packed struct stream (the
    field-wise endianness flip; a whole-stream swap is a no-op on uint8)."""
    if datatype.elem_sizes is None:
        raise NotImplementedError(
            "external32 needs per-element sizes, which this byte-based "
            "datatype does not carry (composed byte maps); pack the "
            "fields with elementary/struct datatypes instead")
    import sys

    if sys.byteorder == "big":  # memory order already IS external32
        return raw
    sizes = np.tile(datatype.elem_sizes, count)
    uniq = np.unique(sizes)
    if uniq.size == 1:
        s = int(uniq[0])
        if s <= 1:
            return raw
        return np.ascontiguousarray(raw.reshape(-1, s)[:, ::-1]).reshape(-1)
    # mixed field sizes: reverse runs of equal size in vectorized groups
    out = raw.copy()
    bounds = np.concatenate(([0], np.cumsum(sizes)))
    pos = 0
    while pos < sizes.size:
        s = int(sizes[pos])
        end = pos
        while end < sizes.size and sizes[end] == s:
            end += 1
        if s > 1:
            b0, b1 = int(bounds[pos]), int(bounds[end])
            out[b0:b1] = np.ascontiguousarray(
                out[b0:b1].reshape(-1, s)[:, ::-1]).reshape(-1)
        pos = end
    return out


def pack_external(buf: Any, datatype: Datatype, count: int = 1) -> bytes:
    """MPI_Pack_external("external32"): the portable big-endian wire
    format — same gather as :func:`pack`, bytes emitted big-endian so
    heterogeneous receivers agree.  Struct (byte-based) maps byteswap
    FIELD-WISE via the per-element sizes recorded at construction."""
    data = datatype.pack(buf, count)
    if datatype.base_dtype == np.uint8:
        return _swap_struct_bytes(data, datatype, count).tobytes()
    return data.astype(data.dtype.newbyteorder(">"), copy=False).tobytes()


def unpack_external(packed: Any, datatype: Datatype, out: np.ndarray,
                    count: int = 1, offset: int = 0) -> int:
    """MPI_Unpack_external: consume big-endian instances; returns the new
    byte offset."""
    nbytes = datatype.size * count
    chunk = bytes(packed[offset:offset + nbytes])
    if datatype.base_dtype == np.uint8:
        host = _swap_struct_bytes(np.frombuffer(chunk, np.uint8),
                                  datatype, count)
        datatype.unpack(host, out, count)
        return offset + nbytes
    be = np.frombuffer(chunk, dtype=datatype.base_dtype.newbyteorder(">"))
    datatype.unpack(be.astype(datatype.base_dtype), out, count)
    return offset + nbytes
