"""Federated serve fabric: leader election, pool takeover, failover.

ISSUE 15 tentpole — the last single point of failure in the stack.
``launcher serve`` (mpi_tpu/serve.py) survives any WORKER death, but the
server process itself was one process fronting one warm pool: kill it
and every client, lease, and worker orphans.  This module federates N
servers over a shared **namespace directory** (the Ray-GCS /
ZooKeeper-lease shape, rebuilt on the FileBoard lock idiom this repo
already trusts — O_EXCL claim + mtime-renewed lease + stale takeover):

* **Endpoint records** — every server renews ``server.<id>.json``
  (pid, control addr, metrics addr, a light stats summary) each tick;
  a record whose pid is dead or whose renewal is stale past the lease
  bound IS a dead server.
* **Leader election** (:class:`LeaderLease`) — one ``leader.lease``
  file, acquired with an atomic ``O_EXCL`` create and renewed by
  ``os.utime`` ONLY (the content — holder id, pid, term — is immutable
  per acquisition, so ownership is never ambiguous); a lease whose
  mtime is stale past ``lease_timeout_s`` is taken over (read term →
  unlink → O_EXCL create with term+1; two racing takeovers both unlink
  — idempotent — and the create arbitrates).  The safety half: a
  holder's AUTHORITY expires ``validity_s = lease_timeout_s/2`` after
  its last successful renew, strictly before any takeover can fire, so
  a leader frozen past the bound (SIGSTOP, the PR-10 rank-freeze story
  at the server tier) has provably lapsed before its usurper begins —
  and on thaw its next renew sees foreign content and DEMOTES.  Every
  acquire/renew appends a ``[from, until]`` authority interval to an
  append-only per-server log; :func:`assert_no_leader_overlap` is the
  split-brain assertion the tests run.
* **Pool takeover** — the leader watches the endpoint records; a dead
  server's pools (``pool.<id>.json`` ownership records) are assigned
  to the least-loaded survivor via a ``takeover.<dead>.json``
  assignment.  The survivor adopts the pool (serve.py grows multi-pool
  bookkeeping), rewrites the ownership record, and the dead server's
  ORPHANED WORKERS — whose transports, arenas, and FT detectors are
  all still warm — re-register with it over the control channel
  (:func:`wait_pool_owner` is the worker-side resolve).  Worker-level
  healing on an adopted pool rides the existing announce/claim/admit
  rejoin protocol against the adopted rendezvous dir unchanged.
  Double-serving is structurally excluded: a worker serves exactly one
  master at a time (its control connection is the token), and a thawed
  ex-owner that finds a newer ownership record relinquishes — closing
  those connections is precisely what releases the workers to the
  usurper.
* **Client failover** (:class:`FederatedClient`) — ``mpi_tpu.connect``
  grows a server-list / namespace-dir mode: acquire and stats re-resolve
  live endpoints and retry with backoff on a dead-server
  ``ServerLostError`` (re-acquire is idempotent — a lease whose server
  died, died with it); an in-flight ``lease.run`` surfaces the named
  error instead of transparently re-running a possibly-side-effecting
  job.
* **Roll-up** (:func:`federation_stats`) — the per-server summaries in
  the endpoint records aggregate into one namespace-level document, so
  the PR-13 Prometheus endpoint stays truthful when pools move between
  servers.

Chaos: ``python bench.py --chaos --federation [--quick]`` SIGKILLs
servers under an open-loop fleet of concurrent clients and asserts
aggregate worlds/s never reaches zero with every failure named
(committed ``benchmarks/results/federation_{pre,post}.json``; pre =
the single-server run dying to zero).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from . import resilience as _resilience
from . import telemetry as _telemetry
from .membership import _pid_alive, _read_json, _write_json
from .transport.base import TransportError

# One leadership/liveness knob: a leader lease (and a server endpoint
# record) untouched for this long belongs to a dead or frozen process
# and is taken over.  Authority self-expires at HALF this bound
# (_VALIDITY_FRACTION), so an ex-holder's authority provably lapses
# before any takeover can begin — the no-overlap invariant the
# split-brain test asserts.  Per-server override: WorldServer
# fed_lease_timeout_s / ``launcher serve --fed-lease-timeout``.
_LEASE_TIMEOUT_S = 3.0
_VALIDITY_FRACTION = 0.5

# Endpoint records are judged dead a bit later than the leader lease
# (renewals ride the same tick; the margin absorbs one missed tick
# under load before a takeover storm starts).
_SERVER_STALE_FACTOR = 1.5

_TICK_S = 0.25          # federation member duty cadence
_LEASE_FILE = "leader.lease"
_OWNER_POLL_S = 0.1     # orphaned-worker resolve cadence

# Client-side liveness filter for endpoint records: liberal (a dial
# failure skips a dead candidate anyway); the pid check does the fast
# discrimination on this single-host fabric.
_CLIENT_RECORD_STALE_S = 10.0


# -- namespace file helpers ---------------------------------------------------


def _server_path(ns: str, sid: str) -> str:
    return os.path.join(ns, f"server.{sid}.json")


def _pool_path(ns: str, pool_id: str) -> str:
    return os.path.join(ns, f"pool.{pool_id}.json")


def _takeover_path(ns: str, sid: str) -> str:
    return os.path.join(ns, f"takeover.{sid}.json")


def _log_path(ns: str, sid: str) -> str:
    return os.path.join(ns, f"leader.log.{sid}")


def read_server_records(ns: str) -> Dict[str, dict]:
    """All ``server.<id>.json`` endpoint records in the namespace."""
    out: Dict[str, dict] = {}
    try:
        names = os.listdir(ns)
    except OSError:
        return out
    for name in names:
        if name.startswith("server.") and name.endswith(".json"):
            rec = _read_json(os.path.join(ns, name))
            if rec and rec.get("id"):
                out[rec["id"]] = rec
    return out


def read_server_record(ns: str, sid: str) -> Optional[dict]:
    return _read_json(_server_path(ns, sid))


def read_leader(ns: str) -> Optional[dict]:
    """The current ``leader.lease`` content (holder id/pid/term), or
    None with no leader elected — a RELEASED lease (clean shutdown
    left the file as a term tombstone) reads as no leader.  File
    ownership only — whether the holder's AUTHORITY is still valid is
    its own clock's business (LeaderLease.is_leader)."""
    rec = _read_json(os.path.join(ns, _LEASE_FILE))
    return None if rec is None or rec.get("released") else rec


def record_live(rec: dict, now: Optional[float] = None,
                stale_s: float = _CLIENT_RECORD_STALE_S) -> bool:
    """Is this endpoint record's server alive?  Dead pid → dead NOW
    (kill -9 detection is one stat); otherwise renewal staleness (the
    frozen-server case: SIGSTOP keeps the pid but stops the renewals)."""
    pid = rec.get("pid")
    if pid is not None and not _pid_alive(int(pid)):
        return False
    now = time.time() if now is None else now
    return now - float(rec.get("renewed_at", 0)) <= stale_s


def write_pool_owner(ns: str, pool_id: str, owner: str, ctrl: str,
                     rdv: str, backend: str, size: int, epoch: int,
                     term: int, since: Optional[float] = None) -> None:
    """Publish/replace the ownership record of one pool.  ``since`` is
    the wall time ownership began — an ex-owner relinquishes on seeing
    a record with a different owner and a ``since`` at or past its own
    (the thawed-usurped-server demotion path)."""
    _write_json(_pool_path(ns, pool_id), {
        "pool": pool_id, "owner": owner, "ctrl": ctrl, "rdv": rdv,
        "backend": backend, "size": int(size), "epoch": int(epoch),
        "term": int(term),
        "since": time.time() if since is None else float(since)})


def read_pool_owner(ns: str, pool_id: str) -> Optional[dict]:
    return _read_json(_pool_path(ns, pool_id))


def read_pool_owners(ns: str) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    try:
        names = os.listdir(ns)
    except OSError:
        return out
    for name in names:
        if name.startswith("pool.") and name.endswith(".json"):
            rec = _read_json(os.path.join(ns, name))
            if rec and rec.get("pool"):
                out[rec["pool"]] = rec
    return out


def read_takeovers(ns: str) -> List[dict]:
    out: List[dict] = []
    try:
        names = os.listdir(ns)
    except OSError:
        return out
    for name in names:
        if name.startswith("takeover.") and name.endswith(".json"):
            rec = _read_json(os.path.join(ns, name))
            if rec:
                out.append(rec)
    return out


def wait_pool_owner(ns: str, pool_id: str, not_ctrl: Optional[str],
                    timeout: float,
                    stale_s: float = _CLIENT_RECORD_STALE_S
                    ) -> Optional[str]:
    """Orphaned-worker resolve: block until the pool's ownership record
    names a control address other than ``not_ctrl`` (the address whose
    ESTABLISHED registration just died; None excludes nothing — a
    merely-unreachable owner may resolve again) and its owner's
    endpoint record, when present, reads live — or the orphan budget
    runs out (→ None: the worker exits rather than leak).  Each
    death round passes its own just-dead address, so a chain of server
    deaths keeps resolving forward."""
    deadline = time.monotonic() + timeout
    while True:
        rec = read_pool_owner(ns, pool_id)
        if rec is not None and rec.get("ctrl") and rec["ctrl"] != not_ctrl:
            srv = read_server_record(ns, str(rec.get("owner")))
            if srv is None or record_live(srv, stale_s=stale_s):
                return rec["ctrl"]
        if time.monotonic() > deadline:
            return None
        time.sleep(_OWNER_POLL_S)


# -- the leader lease ---------------------------------------------------------


class LeaderLease:
    """File-lease leader election on the namespace dir (the FileBoard
    ``pending.summary.lock`` idiom, grown the two properties an
    AUTHORITY needs that a compaction lock does not):

    * **bounded authority** — holding the file is necessary but not
      sufficient; :meth:`is_leader` is true only within ``validity_s``
      of the last *successful* renew, and ``validity_s`` is strictly
      below the takeover bound, so a frozen holder's authority lapses
      before a usurper's can begin;
    * **immutable content per term** — the lease file is written only
      by ``O_EXCL`` create; renewal is ``os.utime`` + an ownership
      re-read on BOTH sides of it.  A thawed ex-holder's pending utime
      can at worst extend a usurper's staleness clock (delaying the
      next takeover — the conservative direction), never re-take the
      file.  The residual race — a takeover's re-stat → unlink gap
      straddled by a thawed holder's utime — is the same accepted
      one-syscall window FileBoard._unlock documents.

    Every acquire and renew appends the authority interval
    ``[from, until]`` to ``leader.log.<id>`` (append-only, one writer
    per file — no contention); :func:`assert_no_leader_overlap` checks
    the whole namespace's history for the split-brain condition."""

    def __init__(self, ns: str, owner_id: str,
                 lease_timeout_s: float = _LEASE_TIMEOUT_S) -> None:
        self.ns = ns
        self.owner_id = owner_id
        self.lease_timeout_s = float(lease_timeout_s)
        self.validity_s = _VALIDITY_FRACTION * self.lease_timeout_s
        self.term = 0
        self.takeovers = 0        # stale leases reclaimed by US
        self.demotions = 0        # times we discovered usurpation
        self._held = False
        self._valid_until_mono = 0.0

    def _path(self) -> str:
        return os.path.join(self.ns, _LEASE_FILE)

    def _content(self) -> dict:
        return {"id": self.owner_id, "pid": os.getpid(),
                "term": self.term, "acquired_at": time.time()}

    def is_leader(self) -> bool:
        """Authority check — NOT just file ownership: false the moment
        ``validity_s`` elapses since the last successful renew, which
        is how a frozen leader knows, on thaw, that it must re-verify
        before acting (and finds itself usurped)."""
        return self._held and time.monotonic() < self._valid_until_mono

    def _mine(self, cur: Optional[dict]) -> bool:
        return (cur is not None and not cur.get("released")
                and cur.get("id") == self.owner_id
                and cur.get("pid") == os.getpid()
                and int(cur.get("term", -1)) == self.term)

    def _log_interval(self, now_wall: float) -> None:
        try:
            with open(_log_path(self.ns, self.owner_id), "a") as f:
                f.write(json.dumps({
                    "id": self.owner_id, "term": self.term,
                    "from": now_wall,
                    "until": now_wall + self.validity_s}) + "\n")
        except OSError:
            pass  # namespace tearing down

    def tick(self) -> bool:
        """Acquire-or-renew; returns whether we hold valid authority
        after the tick.  Called on the federation member cadence."""
        return self._renew() if self._held else self._try_acquire()

    def _try_acquire(self) -> bool:
        path = self._path()
        next_term = self.term + 1
        for attempt in (0, 1):
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                             0o600)
            except FileExistsError:
                if attempt:
                    return False  # lost the post-takeover create race
                cur = _read_json(path)
                if cur is not None:
                    next_term = max(next_term, int(cur.get("term", 0)) + 1)
                released = cur is not None and cur.get("released")
                try:
                    if not released:
                        # a released lease is a term TOMBSTONE (clean
                        # shutdown): immediately claimable, no stale
                        # wait — and the term history survives it
                        st = os.stat(path)
                        if time.time() - st.st_mtime \
                                < self.lease_timeout_s:
                            return False  # live holder
                        # re-stat right before the unlink: a holder
                        # whose renew landed in our stat→unlink gap
                        # keeps its lease (shrinks the accepted race
                        # to one syscall)
                        if os.stat(path).st_mtime != st.st_mtime:
                            return False
                    os.unlink(path)
                except OSError:
                    return False  # vanished/renewed: holder is live
                if not released:
                    self.takeovers += 1
                continue
            except OSError:
                return False  # namespace tearing down
            now_mono, now_wall = time.monotonic(), time.time()
            self.term = next_term
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(self._content(), f)
            except OSError:
                return False
            self._held = True
            # authority anchored BEFORE the write: conservative
            self._valid_until_mono = now_mono + self.validity_s
            self._log_interval(now_wall)
            rec = _telemetry.REC
            if rec is not None:
                rec.emit("serve", "leader_elected",
                         attrs={"id": self.owner_id, "term": self.term,
                                "takeover": self.takeovers > 0})
            return True
        return False  # pragma: no cover - loop always returns

    def _renew(self) -> bool:
        path = self._path()
        now_mono, now_wall = time.monotonic(), time.time()
        if not self._mine(_read_json(path)):
            return self._demote("usurped")
        try:
            os.utime(path)
        except OSError:
            return self._demote("lease file gone")
        # re-read AFTER the utime: if we just touched a usurper's file
        # we extended THEIR staleness clock (conservative — delays the
        # next takeover, never creates a second holder) and demote
        if not self._mine(_read_json(path)):
            return self._demote("usurped")
        self._valid_until_mono = now_mono + self.validity_s
        self._log_interval(now_wall)
        return True

    def _demote(self, why: str) -> bool:
        self._held = False
        self._valid_until_mono = 0.0
        self.demotions += 1
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("serve", "leader_demoted",
                     attrs={"id": self.owner_id, "term": self.term,
                            "why": why})
        return False

    def release(self) -> None:
        """Clean handoff at shutdown: mark the lease RELEASED (a term
        tombstone the next acquirer claims immediately and bumps past —
        unlinking would lose the term history) and log the reign's end,
        capping our authority interval at NOW rather than letting the
        last renew's ``until`` imply authority we gave up."""
        held, self._held = self._held, False
        self._valid_until_mono = 0.0
        if not held:
            return
        path = self._path()
        now_wall = time.time()
        try:
            if self._mine(_read_json(path)):
                _write_json(path, {**self._content(), "released": True})
                with open(_log_path(self.ns, self.owner_id), "a") as f:
                    f.write(json.dumps({
                        "id": self.owner_id, "term": self.term,
                        "release": True, "until": now_wall}) + "\n")
        except OSError:
            pass


def assert_no_leader_overlap(ns: str) -> List[dict]:
    """THE split-brain assertion: parse every server's authority-
    interval log and verify no two DIFFERENT servers' intervals
    overlap.  Returns the parsed intervals (sorted) for diagnostics;
    raises AssertionError naming the clash.  The intervals are what
    each server believed its authority to be (from its own renews),
    logged conservatively — an overlap here means two servers could
    both have acted as leader at one instant."""
    raw: List[dict] = []
    try:
        names = os.listdir(ns)
    except OSError:
        names = []
    for name in names:
        if not name.startswith("leader.log."):
            continue
        try:
            with open(os.path.join(ns, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        raw.append(json.loads(line))
        except (OSError, ValueError):
            continue
    # a release record caps its (id, term) reign at the release instant
    # — authority voluntarily given up must not read as held through
    # the last renew's validity window
    releases: Dict[tuple, float] = {}
    for e in raw:
        if e.get("release"):
            key = (e["id"], e.get("term"))
            releases[key] = min(releases.get(key, float("inf")),
                                float(e["until"]))
    intervals = []
    for e in raw:
        if e.get("release"):
            continue
        cap = releases.get((e["id"], e.get("term")))
        e = dict(e)
        if cap is not None:
            e["until"] = min(float(e["until"]), cap)
        if e["until"] > e["from"]:
            intervals.append(e)
    intervals.sort(key=lambda e: e["from"])
    # merge per-id runs first (renews of one reign overlap by design)
    merged: List[dict] = []
    for e in intervals:
        if merged and merged[-1]["id"] == e["id"] \
                and e["from"] <= merged[-1]["until"]:
            merged[-1]["until"] = max(merged[-1]["until"], e["until"])
        else:
            merged.append(dict(e))
    for a, b in zip(merged, merged[1:]):
        if a["id"] != b["id"] and b["from"] < a["until"]:
            raise AssertionError(
                f"leader authority overlap: {a['id']} (term {a['term']}) "
                f"held until {a['until']:.3f} but {b['id']} (term "
                f"{b['term']}) began at {b['from']:.3f} "
                f"({a['until'] - b['from']:.3f}s overlap)")
    return merged


# -- the per-server federation member ----------------------------------------


class FederationMember:
    """The federation duties of ONE server, run on a daemon thread at
    ``_TICK_S``: renew the endpoint record, tick the leader lease,
    publish/verify pool ownership (relinquishing pools a usurper took
    while we were frozen), consume takeover assignments addressed to
    us, and — while holding valid leader authority — assign dead
    servers' pools to survivors and garbage-collect their records.
    A tick that raises logs a structured line and keeps ticking (the
    serve monitor-loop rule: the fabric's lifeline must not die of one
    exception)."""

    def __init__(self, server, ns: str, server_id: Optional[str] = None,
                 lease_timeout_s: float = _LEASE_TIMEOUT_S,
                 tick_s: float = _TICK_S) -> None:
        os.makedirs(ns, exist_ok=True)
        self.server = server
        self.ns = ns
        self.server_id = server_id or ("srv-" + uuid.uuid4().hex[:8])
        self.lease = LeaderLease(ns, self.server_id, lease_timeout_s)
        self.tick_s = float(tick_s)
        self.server_stale_s = _SERVER_STALE_FACTOR * float(lease_timeout_s)
        self.started_at = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def is_leader(self) -> bool:
        return self.lease.is_leader()

    def start(self) -> "FederationMember":
        self._tick_safe()  # register synchronously: visible on return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"fed-{self.server_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # clean departure: release the lease, retract our records (the
        # pools die with an orderly stop() — serve shuts the workers
        # down — so their ownership records retract too)
        self.lease.release()
        for pool_id, rec in read_pool_owners(self.ns).items():
            if rec.get("owner") == self.server_id:
                try:
                    os.unlink(_pool_path(self.ns, pool_id))
                except OSError:
                    pass
        try:
            os.unlink(_server_path(self.ns, self.server_id))
        except OSError:
            pass

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            self._tick_safe()

    def _tick_safe(self) -> None:
        try:
            self._tick()
        except Exception as e:  # noqa: BLE001 - the fabric's lifeline
            if self._stop.is_set():
                return
            import sys
            import traceback

            sys.stderr.write(
                f"mpi_tpu.federation: member tick failed "
                f"({type(e).__name__}: {str(e)[:200]}) — ticking on:\n"
                f"{traceback.format_exc()}")

    # -- duties ------------------------------------------------------------

    def _tick(self) -> None:
        now = time.time()
        self._write_server_record(now)
        leading = self.lease.tick()
        # ONE pool-record snapshot per tick, shared by every duty
        # (each used to rescan the namespace itself — 3-4 directory
        # walks per 250ms tick per server, multiplied across the
        # fabric); staleness within a tick is harmless, every consumer
        # re-checks live server state before acting
        owners = read_pool_owners(self.ns)
        self._verify_pool_ownership(owners)
        self._reclaim_ghost_pools(owners)
        self._consume_assignments()
        if leading and self.lease.is_leader():
            self._leader_duties(now, owners)

    def _write_server_record(self, now: float) -> None:
        _write_json(_server_path(self.ns, self.server_id), {
            "id": self.server_id, "pid": os.getpid(),
            "ctrl": self.server.addr,
            "metrics": getattr(self.server, "metrics_addr", None),
            "started_at": self.started_at, "renewed_at": now,
            "is_leader": self.lease.is_leader(),
            "term": self.lease.term,
            "summary": self.server.fed_summary()})

    def _verify_pool_ownership(self, owners: Dict[str, dict]) -> None:
        """Publish ownership for pools we hold; RELINQUISH any pool the
        namespace says a usurper took over while we were frozen (the
        split-brain-avoidance half of pool handover: our closing of the
        worker control connections is what releases the workers)."""
        for pool_id, meta in self.server.owned_pool_records().items():
            rec = owners.get(pool_id)
            if rec is None:
                write_pool_owner(
                    self.ns, pool_id, owner=self.server_id,
                    ctrl=self.server.addr, rdv=meta["rdv"],
                    backend=meta["backend"], size=meta["size"],
                    epoch=meta["epoch"], term=self.lease.term,
                    since=meta["since"])
            elif (rec.get("owner") != self.server_id
                  and float(rec.get("since", 0)) >= float(meta["since"])):
                self.server.relinquish_pool(pool_id, rec.get("owner"))

    def _reclaim_ghost_pools(self, owners: Dict[str, dict]) -> None:
        """A pool record naming US that we do not actually serve is a
        ghost of our PREVIOUS incarnation (a restart under a stable
        ``--server-id``): the record reads live to the leader (our new
        pid renews ``server.<id>.json``), so no takeover will ever
        fire for it — reclaim it ourselves.  The old incarnation's
        warm orphans are excluding its DEAD control address in their
        re-resolve; rewriting the record with our new address is what
        brings them home."""
        owned = self.server.owned_pool_records()
        for pool_id, rec in owners.items():
            if rec.get("owner") != self.server_id or pool_id in owned:
                continue
            if self.server.adopt_pool(pool_id, rec,
                                      term=self.lease.term):
                write_pool_owner(
                    self.ns, pool_id, owner=self.server_id,
                    ctrl=self.server.addr, rdv=rec["rdv"],
                    backend=rec.get("backend", "socket"),
                    size=int(rec["size"]),
                    epoch=int(rec.get("epoch", 0)),
                    term=self.lease.term)

    def _consume_assignments(self) -> None:
        for t in read_takeovers(self.ns):
            if t.get("to") != self.server_id:
                continue
            for pool_id, prec in (t.get("pools") or {}).items():
                cur = read_pool_owner(self.ns, pool_id)
                if cur is not None and cur.get("owner") not in (
                        t.get("dead"), self.server_id):
                    continue  # moved again since: stale assignment
                if cur is not None and cur.get("owner") == self.server_id:
                    continue  # already adopted
                if self.server.adopt_pool(pool_id, prec,
                                          term=int(t.get("term", 0))):
                    write_pool_owner(
                        self.ns, pool_id, owner=self.server_id,
                        ctrl=self.server.addr, rdv=prec["rdv"],
                        backend=prec.get("backend", "socket"),
                        size=int(prec["size"]),
                        epoch=int(prec.get("epoch", 0)),
                        term=int(t.get("term", 0)))

    def _leader_duties(self, now: float,
                       owners: Dict[str, dict]) -> None:
        records = read_server_records(self.ns)
        live = {sid for sid, r in records.items()
                if sid == self.server_id
                or record_live(r, now, self.server_stale_s)}
        for sid, r in records.items():
            if sid in live:
                continue
            dead_pools = {pid: rec for pid, rec in owners.items()
                          if rec.get("owner") == sid}
            if dead_pools:
                existing = _read_json(_takeover_path(self.ns, sid))
                if existing is None or existing.get("to") not in live:
                    target = self._choose_survivor(live, owners)
                    if target is not None and self.lease.is_leader():
                        # assignments carry the term they were decided
                        # under — written ONLY with valid authority
                        _write_json(_takeover_path(self.ns, sid), {
                            "dead": sid, "to": target,
                            "term": self.lease.term, "at": now,
                            "pools": dead_pools})
                        rec_t = _telemetry.REC
                        if rec_t is not None:
                            rec_t.emit("serve", "takeover_assigned",
                                       attrs={"dead": sid, "to": target,
                                              "pools":
                                              sorted(dead_pools)})
            else:
                # fully relieved (or never owned a pool): GC the corpse
                for path in (_server_path(self.ns, sid),
                             _takeover_path(self.ns, sid)):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass

    def _choose_survivor(self, live: set,
                         owners: Dict[str, dict]) -> Optional[str]:
        """Least-loaded live server (fewest owned pools, id tiebreak) —
        the leader may assign to itself."""
        if not live:
            return None
        load = {sid: 0 for sid in live}
        for rec in owners.values():
            if rec.get("owner") in load:
                load[rec["owner"]] += 1
        return min(sorted(load), key=lambda sid: load[sid])


# -- namespace roll-up --------------------------------------------------------


def federation_stats(ns: str) -> dict:
    """Aggregate the namespace: one document summing the live servers'
    summaries (worlds/s, workers, idle, pools, waiting) plus the
    current leader — what keeps the PR-13 Prometheus endpoint truthful
    when pools move between servers.  Pure file reads: scrape-safe,
    callable with zero servers reachable."""
    now = time.time()
    records = read_server_records(ns)
    lease = read_leader(ns)
    servers = {}
    totals = {"worlds_per_s": 0.0, "workers": 0, "idle": 0, "pools": 0,
              "leases_active": 0, "waiting": 0}
    live = 0
    for sid, r in sorted(records.items()):
        alive = record_live(r, now)
        summary = r.get("summary") or {}
        servers[sid] = {"live": alive, "ctrl": r.get("ctrl"),
                        "is_leader": bool(r.get("is_leader")),
                        **summary}
        if alive:
            live += 1
            for k in totals:
                totals[k] = totals[k] + summary.get(k, 0)
    totals["worlds_per_s"] = round(totals["worlds_per_s"], 3)
    return {"namespace": ns, "servers_total": len(records),
            "servers_live": live,
            "leader": lease.get("id") if lease else None,
            "leader_term": int(lease.get("term", 0)) if lease else 0,
            "servers": servers, **totals}


# -- the failover client ------------------------------------------------------


class FederatedClient:
    """Client handle to a FEDERATION of world servers: resolve live
    endpoints from a namespace dir (and/or a static address list), and
    fail acquire/stats over to a survivor on a dead-server
    ``ServerLostError`` with backoff, bounded by the
    ``connect_retry_timeout_s`` budget.  Lease semantics are the
    single-server ones: re-acquire after a failover is idempotent (the
    lost lease died with its server), and an in-flight ``lease.run``
    surfaces the named error — jobs are not transparently re-run."""

    def __init__(self, namespace: Optional[str] = None,
                 addrs: Optional[List[Any]] = None,
                 timeout: float = 30.0, priority: int = 0,
                 failover_timeout_s: Optional[float] = None) -> None:
        if not namespace and not addrs:
            raise ValueError("FederatedClient needs a namespace dir "
                             "and/or a server address list")
        self._ns = namespace
        self._static = ["%s:%s" % tuple(a) if isinstance(a, (tuple, list))
                        else str(a) for a in (addrs or [])]
        self._timeout = float(timeout)
        self._priority = int(priority)
        self._id = uuid.uuid4().hex  # one fair-share identity across servers
        self._failover_s = failover_timeout_s
        self._client = None
        self._addr: Optional[str] = None
        self._rr = 0
        self.failovers = 0

    # -- endpoint resolution ----------------------------------------------

    def _budget(self) -> float:
        if self._failover_s is not None:
            return float(self._failover_s)
        from . import mpit as _mpit

        return float(_mpit.cvar_read("connect_retry_timeout_s"))

    def _candidates(self) -> List[str]:
        out = list(self._static)
        if self._ns:
            now = time.time()
            # freshest renewal first: a SIGSTOP-frozen server's record
            # passes record_live until it ages past the stale bound,
            # but its renewals have already stopped — ordering by
            # recency steers a fresh client at the actively-renewing
            # survivor instead of the silent not-yet-stale ex-leader
            # (id order was the tiebreak that dialed the frozen one
            # first every time).  Ties (all healthy) stay deterministic
            # via the id in the sort key.
            recs = sorted(read_server_records(self._ns).items(),
                          key=lambda kv: (-float(
                              kv[1].get("renewed_at", 0)), kv[0]))
            for sid, rec in recs:
                if rec.get("ctrl") and record_live(rec, now) \
                        and rec["ctrl"] not in out:
                    out.append(rec["ctrl"])
        return out

    def _ensure(self):
        if self._client is not None:
            return self._client
        from . import serve as _serve

        deadline = time.monotonic() + max(self._budget(), 0.0)
        delays = _resilience.backoff_delays()
        last_err: Optional[BaseException] = None
        while True:
            cands = self._candidates()
            for i in range(len(cands)):
                addr = cands[(self._rr + i) % len(cands)]
                host, _, port = addr.rpartition(":")
                try:
                    # a short per-candidate dial budget: OUR loop is
                    # the patience; a dead candidate must not eat the
                    # whole failover budget before the next is tried.
                    # The cap applies to the SINGLE connect attempt
                    # too (timeout=), not just the retry loop — a
                    # SYN-blackholed candidate would otherwise block
                    # the full client timeout before the live survivor
                    # is ever dialed
                    c = _serve.ServerClient(
                        host, int(port),
                        timeout=min(self._timeout, 2.0),
                        priority=self._priority, client_id=self._id,
                        dial_retry_s=0.5)
                except OSError as e:
                    last_err = e
                    continue
                self._client, self._addr = c, addr
                self._rr = (self._rr + i + 1) % max(1, len(cands))
                return c
            if time.monotonic() > deadline:
                raise _serve.ServerLostError(
                    f"no live federation server reachable "
                    f"(candidates {cands or 'none'}; last: "
                    f"{type(last_err).__name__ if last_err else 'none'}: "
                    f"{last_err})")
            time.sleep(min(next(delays), 0.5))

    def _drop(self) -> None:
        c, self._client, self._addr = self._client, None, None
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def _with_failover(self, op):
        from .serve import ServerLostError

        deadline = time.monotonic() + max(self._budget(), 0.0)
        delays = _resilience.backoff_delays()
        while True:
            client = self._ensure()
            try:
                return op(client)
            except (ServerLostError, OSError) as e:
                if isinstance(e, TimeoutError) \
                        and not isinstance(e, ServerLostError):
                    # a LEASE timeout (TimeoutError is an OSError
                    # subclass!) is the live server's named verdict,
                    # not a dead server — never a failover signal
                    raise
                self._drop()
                self.failovers += 1
                if time.monotonic() > deadline:
                    raise
                time.sleep(min(next(delays), 0.25))

    # -- the ServerClient surface ------------------------------------------

    @property
    def addr(self) -> Optional[str]:
        """Control address currently connected (None when dropped)."""
        return self._addr

    def acquire(self, nranks: int, timeout: Optional[float] = None,
                priority: Optional[int] = None):
        """Lease ``nranks`` warm workers from any live server —
        failover-transparent (re-acquire is idempotent).  Named
        non-failover errors propagate: ``ServerBusyError`` (admission
        rejection), ``TimeoutError`` (pool busy past the bound)."""
        return self._with_failover(
            lambda c: c.acquire(nranks, timeout=timeout,
                                priority=priority))

    def run(self, fn, *args: Any, nranks: int = 2,
            timeout: Optional[float] = None) -> Any:
        """acquire (with failover) + run + release.  A server death
        MID-JOB raises the named ``ServerLostError`` — the job may have
        side effects, so re-running it is the caller's decision."""
        lease = self.acquire(nranks, timeout=timeout)
        try:
            return lease.run(fn, *args, timeout=timeout)
        finally:
            try:
                lease.release()
            except (TransportError, OSError):
                pass  # server gone: the lease died with it

    def stats(self) -> dict:
        """One live server's stats document (failover-transparent);
        federated servers embed the namespace roll-up under
        ``"federation"``."""
        return self._with_failover(lambda c: c.stats())

    def federation_stats(self) -> dict:
        """The namespace roll-up directly (no server round-trip)."""
        if not self._ns:
            return self.stats().get("federation") or {}
        return federation_stats(self._ns)

    def close(self) -> None:
        self._drop()

    def __enter__(self) -> "FederatedClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
