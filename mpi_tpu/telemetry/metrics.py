"""Prometheus text rendering for the serve metrics endpoint.

Pure functions from a ``WorldServer.stats()`` document (plus the mpit
histogram pvars) to Prometheus exposition format, so the HTTP endpoint
in serve.py is a ten-line thread and the rendering is unit-testable
without a server.  The shape follows the Prometheus conventions:
counters get ``_total``, histograms emit ``_bucket{le=...}`` +
``_sum`` + ``_count``, labels for the per-worker rows.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import mpit as _mpit

# stats() keys rendered as monotone counters (name -> _total metric)
_COUNTER_KEYS = ("leases_granted", "leases_denied", "jobs_ok",
                 "jobs_failed", "heals_completed", "workers_lost",
                 "busy_rejected", "orphans_reregistered",
                 "pools_adopted", "pools_relinquished")

# stats() keys rendered as gauges
_GAUGE_KEYS = ("epoch", "pool_size", "idle", "leases_active",
               "worlds_per_s", "uptime_s", "waiting", "max_pending")

# federation roll-up keys (stats()["federation"]) rendered as gauges
_FED_GAUGE_KEYS = ("servers_total", "servers_live", "worlds_per_s",
                   "workers", "idle", "pools", "leases_active",
                   "waiting", "leader_term")

_PREFIX = "mpi_tpu_serve"


def _fmt(v) -> str:
    return repr(float(v)) if isinstance(v, float) else str(int(v))


def render_histogram(name: str, metric: str,
                     lines: List[str]) -> None:
    """One mpit histogram pvar as a Prometheus histogram series."""
    snap = _mpit.pvar_hist_read(name)
    lines.append(f"# TYPE {metric} histogram")
    for le, cum in _mpit.hist_cumulative(name):
        lines.append(f'{metric}_bucket{{le="{le:.9g}"}} {cum}')
    lines.append(f'{metric}_bucket{{le="+Inf"}} {snap["count"]}')
    lines.append(f"{metric}_sum {snap['sum_s']:.9g}")
    lines.append(f"{metric}_count {snap['count']}")


def prometheus_text(stats: Dict,
                    hists: Optional[Dict[str, str]] = None) -> str:
    """Render a serve stats document (see ``WorldServer.stats()``) as
    Prometheus exposition text.  ``hists`` maps mpit histogram pvar
    names to metric names; the default exports the lease-acquire
    distribution (the p50/p99 the acceptance names)."""
    lines: List[str] = []
    for key in _GAUGE_KEYS:
        if key in stats:
            metric = f"{_PREFIX}_{key}"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {_fmt(stats[key])}")
    for key in _COUNTER_KEYS:
        if key in stats:
            metric = f"{_PREFIX}_{key}_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {_fmt(stats[key])}")
    workers = stats.get("workers") or {}
    if workers:
        metric = f"{_PREFIX}_worker_state"
        lines.append(f"# TYPE {metric} gauge")
        for slot, state in sorted(workers.items()):
            lines.append(
                f'{metric}{{slot="{slot}",state="{state}"}} 1')
    healing = stats.get("healing")
    if healing is not None:
        metric = f"{_PREFIX}_healing_slots"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {len(healing)}")
    # multi-pool detail (ISSUE 15): per-pool epoch + worker states,
    # labeled by pool id, so a scrape distinguishes the home pool from
    # adopted ones after a takeover
    pools = stats.get("pools") or {}
    if len(pools) > 1 or any(not p.get("home") for p in pools.values()):
        metric = f"{_PREFIX}_pool_epoch"
        lines.append(f"# TYPE {metric} gauge")
        for pid, p in sorted(pools.items()):
            home = "true" if p.get("home") else "false"
            lines.append(f'{metric}{{pool="{pid}",home="{home}"}} '
                         f'{_fmt(p.get("epoch", 0))}')
        metric = f"{_PREFIX}_pool_worker_state"
        lines.append(f"# TYPE {metric} gauge")
        for pid, p in sorted(pools.items()):
            for slot, state in sorted((p.get("workers") or {}).items()):
                lines.append(f'{metric}{{pool="{pid}",slot="{slot}",'
                             f'state="{state}"}} 1')
    if stats.get("is_leader") is not None:
        metric = f"{_PREFIX}_is_leader"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {1 if stats['is_leader'] else 0}")
    # federation namespace roll-up (ISSUE 15): the aggregate the
    # acceptance scrapes — the endpoint stays truthful when pools move
    fed = stats.get("federation")
    if fed:
        for key in _FED_GAUGE_KEYS:
            if key in fed:
                metric = f"mpi_tpu_fed_{key}"
                lines.append(f"# TYPE {metric} gauge")
                lines.append(f"{metric} {_fmt(fed[key])}")
        metric = "mpi_tpu_fed_server_live"
        lines.append(f"# TYPE {metric} gauge")
        for sid, rec in sorted((fed.get("servers") or {}).items()):
            leader = "true" if rec.get("is_leader") else "false"
            lines.append(
                f'{metric}{{server="{sid}",leader="{leader}"}} '
                f'{1 if rec.get("live") else 0}')
    # aggregated worker pvars (piggybacked on job_done replies): the
    # pool's data-plane story — link reconnects, arena hits, detected
    # failures — summed over the latest snapshot of each slot
    agg = stats.get("worker_pvars") or {}
    if agg:
        metric = "mpi_tpu_worker_pvar"
        lines.append(f"# TYPE {metric} gauge")
        for name in sorted(agg):
            lines.append(f'{metric}{{name="{name}"}} {_fmt(agg[name])}')
    for name, metric in (hists if hists is not None
                         else {"lease_acquire_s":
                               "mpi_tpu_lease_acquire_seconds"}).items():
        render_histogram(name, metric, lines)
    # the quantile gauges the acceptance scrapes directly (estimated
    # from the log buckets — see mpit.hist_quantile's error bound)
    for q, label in ((0.5, "p50"), (0.99, "p99")):
        est = _mpit.hist_quantile("lease_acquire_s", q)
        if est is not None:
            metric = f"{_PREFIX}_lease_acquire_{label}_seconds"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {est:.9g}")
    return "\n".join(lines) + "\n"
